"""Generate, lint, and summarize torchmpi_tpu fault plans (docs/FAULTS.md).

The chaos-engineering operator surface over ``torchmpi_tpu/faults/``:

    python scripts/chaos_tool.py gen --out plan.json --seed 7 \\
        --rule ps.request:drop:0.5:3:0.01 --rule host_staged.*:corrupt
    python scripts/chaos_tool.py gen --out shrink.json \\
        --shrink 2:5:4      # kill rank 2 at step 5 of a 4-rank gang
    python scripts/chaos_tool.py lint plan.json
    python scripts/chaos_tool.py summarize metrics_host*.jsonl

``gen`` writes a versioned fault-plan JSON from ``--rule`` specs
(``site:kind[:prob[:max_hits[:delay_s]]]``; ``site`` may glob the
instrumented sites, ``max_hits=-1`` means unbounded).  Kinds include
``corrupt_silent`` (docs/GUARD.md): bits flip and NOTHING raises —
payload-carrying sites only (``host_staged.*``, ``ps.request``,
``ckpt.write``, ``ckpt.read``); ``lint`` rejects it anywhere else,
where it would be a total no-op.  ``--shrink
RANK:STEP:NRANKS`` is the elastic-gang recipe (docs/ELASTIC.md): the
driver fires the ``elastic.member`` site once per member per step
boundary in rank order, so arrival ``STEP*NRANKS + RANK`` is exactly
rank RANK's liveness check at step STEP — the recipe emits a
``fail`` rule with that ``after`` and ``max_hits=1``, a deterministic
kill-one-peer-at-step-n plan (compute NRANKS against the ORIGINAL gang
size; arrivals per step shrink with the gang).  ``lint`` validates a
plan — schema/version errors exit 2, semantic problems (site patterns
matching no instrumented site, dead rules) print and exit 1.
The checkpoint sites (docs/CHECKPOINT.md) round out the storage
surface: ``ckpt.write``/``ckpt.read`` carry real payloads (the
serialized npz bytes), so ``corrupt``/``corrupt_silent`` flip bits
that land on (or come back from) disk; ``torn`` — ``ckpt.write``
only, lint rejects it elsewhere — leaves a truncated ``.tmp``
artifact and kills the save (the crash-mid-write double); ``fail``
is ENOSPC-flavored on write, EIO on read.  The ``stall`` kind
(docs/WATCHDOG.md) is the silent hang: the site stops making progress
and raises nothing — valid at EVERY site, payload-free ones included
(the failure is the absence of progress, there is nothing to flip);
``delay_s`` on a stall is linted (the hold is indefinite by
definition).  ``--stall RANK:STEP:NRANKS`` is the gang-wedge recipe:
a stall on rank RANK's ``elastic.member`` liveness check at step
STEP, the deterministic "one rank wedges the whole gang" scenario
the watchdog acceptance drives.  ``--partition
RANKS:STEP[:HEAL_STEP]`` is the split-brain recipe (docs/ELASTIC.md):
a ``partition`` rule at the ``board.read`` site masks the membership
board's visibility along RANKS (``"2,3"`` symmetric, ``"0,1|2,3"``
explicit groups, ``"~2,3"`` one-way/deaf — the asymmetric case) from
gang step STEP until HEAL_STEP; ``lint`` rejects ``partition`` off
the ``board.*`` sites and payload kinds ON them, and ``summarize``
reports the park/fence counters
(``tm_elastic_{quorum_lost,parked,fenced,healed}_total``) alongside
the rest.  ``--migrate RANK:STEP:NRANKS`` is the planned-migration
drill (docs/HOTSTATE.md): the driver drains rank RANK onto a spare at
step STEP (``hotstate.migrate``), and the plan kills the SOURCE one
step later — a ``fail`` rule at ``elastic.member`` arrival
``(STEP+1)*NRANKS + RANK`` — so the run proves the drain beat the
preemption: zero checkpoint rollback, ``tm_hotstate_migrated_total``
up, and the late kill lands on a rank that already left the gang.
The hot-state stream's own sites (``hotstate.send``/``hotstate.recv``)
are payload-carrying like the ckpt pair: ``corrupt_silent`` flips
real bits in the staged delta (the restore-side digest verify must
catch it and fall to the disk rung), ``drop`` loses the message (the
chain self-heals at the next snapshot).  ``summarize`` reads
per-host obs metric dumps (the files ``TORCHMPI_TPU_OBS=metrics``
leaves behind) and prints the ``tm_fault_*``, ``tm_elastic_*``,
``tm_guard_*``, ``tm_ckpt_*``, ``tm_watchdog_*``, ``tm_hotstate_*``,
``tm_serving_*`` (the serving fleet's shed/reroute/prefix-cache
outcomes under chaos), and ``tm_bench_*`` (the bench supervisor's
per-stage live/banked/wedged outcome counters) series — what
was injected, what survived a retry, what hit a deadline, what
shrink/rejoin the gang ran, what digests failed/healed, what updates
the numeric tripwire skipped, what checkpoint copies failed
verification, were repaired from buddies, or were walked past by
recovery, what collectives the watchdog flagged stalled / broke /
escalated, and which recovery rung (RAM / disk) actually served —
the after-action report of a chaos run; exits 1 when a
chaos run left NO fault counters (it injected nothing: wrong plan,
wrong sites, or faults never armed).

Standalone on purpose: no jax — writing a chaos plan for a pod (or
reading its post-mortem) must not need the pod's software stack.  The
plan schema is loaded straight from ``torchmpi_tpu/faults/inject.py``
(itself dependency-free) without importing the package.
"""

import argparse
import importlib.util
import json
import os
import sys
from typing import Dict, List, Tuple

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _load_inject():
    path = os.path.join(_REPO, "torchmpi_tpu", "faults", "inject.py")
    spec = importlib.util.spec_from_file_location("_faults_inject", path)
    mod = importlib.util.module_from_spec(spec)
    # Registered before exec: the dataclass machinery resolves the
    # module's (future-style string) annotations through sys.modules.
    sys.modules[spec.name] = mod
    spec.loader.exec_module(mod)
    return mod


def _load_findings():
    """Standalone copy of analysis/findings.py (the shared Finding
    record the static-analysis CLIs emit) — file-loaded like
    ``_load_inject`` so ``chaos_tool lint`` never imports jax."""
    path = os.path.join(_REPO, "torchmpi_tpu", "analysis",
                        "findings.py")
    name = "_chaos_findings"
    if name in sys.modules:
        return sys.modules[name]
    spec = importlib.util.spec_from_file_location(name, path)
    mod = importlib.util.module_from_spec(spec)
    # Registered before exec: dataclass machinery needs the module
    # resolvable through sys.modules.
    sys.modules[name] = mod
    spec.loader.exec_module(mod)
    return mod


def parse_rule(inject, spec: str):
    """``site:kind[:prob[:max_hits[:delay_s[:after]]]]`` -> FaultRule.
    ``after`` skips the first N arrivals — how a plain --rule lands a
    fault at a specific mid-run arrival (the boundary recipes compute
    it for the ``elastic.member`` site; everywhere else the arrival
    ordinal is the site's dispatch count)."""
    parts = spec.split(":")
    if len(parts) < 2 or len(parts) > 6:
        raise ValueError(
            f"--rule {spec!r}: want "
            f"site:kind[:prob[:max_hits[:delay_s[:after]]]]")
    kw = {"site": parts[0], "kind": parts[1]}
    if len(parts) > 2:
        kw["prob"] = float(parts[2])
    if len(parts) > 3:
        kw["max_hits"] = int(parts[3])
    if len(parts) > 4:
        kw["delay_s"] = float(parts[4])
    if len(parts) > 5:
        kw["after"] = int(parts[5])
    rule = inject.FaultRule(**kw)
    rule.validate()
    return rule


def _boundary_rule(inject, flag: str, spec: str, kind: str):
    """``RANK:STEP:NRANKS`` -> a deterministic rule at the
    ``elastic.member`` site (the gang fires it once per member per step
    boundary in rank order, so the arrival ordinal is
    ``STEP*NRANKS + RANK``)."""
    parts = spec.split(":")
    if len(parts) != 3:
        raise ValueError(f"{flag} {spec!r}: want RANK:STEP:NRANKS")
    rank, step, nranks = (int(p) for p in parts)
    if nranks < 1 or not (0 <= rank < nranks) or step < 0:
        raise ValueError(
            f"{flag} {spec!r}: need 0 <= RANK < NRANKS and STEP >= 0")
    rule = inject.FaultRule(site="elastic.member", kind=kind,
                            prob=1.0, after=step * nranks + rank,
                            max_hits=1)
    rule.validate()
    return rule, rank, step, nranks


def parse_shrink(inject, spec: str):
    """Kill-rank-at-step recipe (``fail`` at ``elastic.member``)."""
    return _boundary_rule(inject, "--shrink", spec, "fail")


def parse_partition(inject, spec: str):
    """``RANKS:STEP[:HEAL_STEP]`` -> a ``partition`` rule at the
    ``board.read`` site (docs/ELASTIC.md "Partitions and split-brain"):
    from gang step STEP, the membership board's visibility splits along
    RANKS — ``"2,3"`` (those ranks vs. the rest, symmetric),
    ``"0,1|2,3"`` (explicit groups), ``"~2,3"`` (one-way: the named
    ranks go DEAF — they see nobody else's board files while their own
    writes stay visible; the asymmetric case).  With HEAL_STEP the
    mask lifts once any member's posted progress reaches it; without,
    the partition never heals.  The step clock is the gang's own
    progress, so the recipe replays bit-exactly."""
    parts = spec.split(":")
    if len(parts) not in (2, 3):
        raise ValueError(
            f"--partition {spec!r}: want RANKS:STEP[:HEAL_STEP] "
            f"(RANKS e.g. '1' / '0,1|2,3' / '~1')")
    ranks = parts[0]
    step = int(parts[1])
    heal = int(parts[2]) if len(parts) == 3 else -1
    if step < 0:
        raise ValueError(f"--partition {spec!r}: STEP must be >= 0")
    rule = inject.FaultRule(site="board.read", kind="partition",
                            ranks=ranks, after=step, heal_after=heal)
    rule.validate()
    return rule, ranks, step, heal


def parse_migrate(inject, spec: str):
    """``RANK:STEP:NRANKS`` -> the planned-migration drill
    (docs/HOTSTATE.md): the DRIVER is expected to drain rank RANK onto
    a spare at step STEP (``hotstate.migrate`` — e.g.
    ``benchmarks/recovery_bench.py --scenario migration``); this rule
    kills the source at its NEXT boundary arrival,
    ``(STEP+1)*NRANKS + RANK``, so a green run is the proof the drain
    beat the preemption: zero checkpoint rollback and the kill landing
    on an already-retired rank."""
    parts = spec.split(":")
    if len(parts) != 3:
        raise ValueError(f"--migrate {spec!r}: want RANK:STEP:NRANKS")
    rank, step, nranks = (int(p) for p in parts)
    if nranks < 1 or not (0 <= rank < nranks) or step < 0:
        raise ValueError(
            f"--migrate {spec!r}: need 0 <= RANK < NRANKS and STEP >= 0")
    rule = inject.FaultRule(site="elastic.member", kind="fail",
                            prob=1.0, after=(step + 1) * nranks + rank,
                            max_hits=1)
    rule.validate()
    return rule, rank, step, nranks


def parse_stall(inject, spec: str):
    """Wedge-rank-at-step recipe (docs/WATCHDOG.md): a ``stall`` at
    member RANK's liveness check at step STEP — every process of the
    gang holds at that same boundary arrival, which is exactly the
    symmetric wedge a peer stalled mid-collective produces.  With the
    watchdog off the gang hangs until the harness timeout; with
    ``break`` every rank's hold converts into a
    ``CollectiveHangError`` implicating ``member:RANK`` and the gang
    shrinks to N-1."""
    return _boundary_rule(inject, "--stall", spec, "stall")


def cmd_gen(args) -> int:
    inject = _load_inject()
    try:
        if len(args.shrink) + len(args.migrate) > 1:
            # After the first kill the gang recovers (replaying step
            # boundaries) AND fires one fewer arrival per step, so a
            # second rule's step*NRANKS+RANK ordinal no longer lands on
            # the (rank, step) it names — the recipe is exact for ONE
            # kill per plan (--migrate kills its source too).
            raise ValueError(
                "--shrink/--migrate may be given once per plan: arrival "
                "ordinals are only exact for the first kill (recovery "
                "replays and the shrunken gang shift later arrivals) — "
                "generate separate plans for separate kills")
        rules = [parse_rule(inject, spec) for spec in args.rule]
        for spec in args.shrink:
            rule, rank, step, nranks = parse_shrink(inject, spec)
            rules.append(rule)
            print(f"shrink recipe: kill rank {rank} at step {step} of a "
                  f"{nranks}-rank gang (elastic.member arrival "
                  f"{rule.after})")
        for spec in args.migrate:
            rule, rank, step, nranks = parse_migrate(inject, spec)
            rules.append(rule)
            print(f"migrate recipe: drain rank {rank} onto a spare at "
                  f"step {step} of a {nranks}-rank gang, source killed "
                  f"at step {step + 1} (elastic.member arrival "
                  f"{rule.after}; a green run means the drain beat the "
                  f"preemption — zero rollback, docs/HOTSTATE.md)")
        for spec in args.stall:
            rule, rank, step, nranks = parse_stall(inject, spec)
            rules.append(rule)
            print(f"stall recipe: wedge the gang on rank {rank}'s "
                  f"liveness check at step {step} of a {nranks}-rank "
                  f"gang (elastic.member arrival {rule.after}; "
                  f"watchdog=break recovers at N-1, watchdog=off hangs "
                  f"— docs/WATCHDOG.md)")
        for spec in args.partition:
            rule, ranks, step, heal = parse_partition(inject, spec)
            rules.append(rule)
            heal_s = (f", heals at step {heal}" if heal >= 0
                      else ", never heals")
            print(f"partition recipe: split the membership board "
                  f"along ranks {ranks!r} from step {step}{heal_s} "
                  f"(elastic_quorum=majority parks the minority and "
                  f"rejoins at heal; quorum off forks the view — "
                  f"docs/ELASTIC.md)")
    except ValueError as e:
        print(f"error: {e}", file=sys.stderr)
        return 2
    if not rules:
        print("error: gen needs at least one --rule, --shrink, "
              "--stall, --partition or --migrate", file=sys.stderr)
        return 2
    plan = inject.FaultPlan(seed=args.seed, note=args.note, rules=rules)
    problems = inject.lint_plan(plan)
    for p in problems:
        print(f"warning: {p}")
    plan.save(args.out)
    print(f"wrote {args.out}: seed={plan.seed} rules={len(plan.rules)}"
          + (f" ({len(problems)} warning(s))" if problems else ""))
    return 0


def cmd_lint(args) -> int:
    """Plan problems surface as F1 error :class:`Finding`\\ s — the
    same structured record (and ``--json`` wire format) as
    ``scripts/lint_collectives.py``, so one consumer parses every
    static-analysis stream in the repo."""
    inject = _load_inject()
    fmod = _load_findings()
    as_json = getattr(args, "json", False)
    rc = 0
    findings = []
    for path in args.files:
        try:
            plan = inject.FaultPlan.load(path)
        except (OSError, ValueError) as e:
            print(f"error: {e}", file=sys.stderr)
            return 2
        found = [fmod.Finding(rule="F1", severity=fmod.ERROR,
                              message=p, source=path)
                 for p in inject.lint_plan(plan)]
        findings.extend(found)
        if found:
            rc = 1
        if not as_json:
            status = "OK" if not found else f"{len(found)} problem(s)"
            print(f"{path}: version={inject.FAULT_PLAN_VERSION} "
                  f"seed={plan.seed} rules={len(plan.rules)} — {status}")
            for f in found:
                print(f"  {f}")
    if as_json:
        print(json.dumps(
            [f.to_json() for f in fmod.sort_findings(findings)],
            indent=1))
    return rc


def _load_counters(path: str) -> List[dict]:
    out = []
    with open(path) as f:
        for i, line in enumerate(f, 1):
            line = line.strip()
            if not line:
                continue
            try:
                rec = json.loads(line)
            except json.JSONDecodeError as e:
                raise ValueError(f"{path}:{i}: not JSON ({e})") from None
            if isinstance(rec, dict) and rec.get("kind") == "counter":
                out.append(rec)
    return out


def cmd_summarize(args) -> int:
    totals: Dict[Tuple[str, Tuple[Tuple[str, str], ...]], float] = {}
    for path in args.files:
        for rec in _load_counters(path):
            name = rec.get("name", "")
            if not name.startswith(("tm_fault_", "tm_elastic_",
                                    "tm_guard_", "tm_ckpt_",
                                    "tm_watchdog_", "tm_hotstate_",
                                    "tm_bench_", "tm_serving_")):
                continue
            key = (name, tuple(sorted(rec.get("labels", {}).items())))
            totals[key] = totals.get(key, 0) + rec.get("value", 0)
    if not totals:
        print("no tm_fault_*/tm_elastic_*/tm_guard_* counters found — "
              "the chaos run injected nothing (plan never matched a "
              "site, or faults were not armed)", file=sys.stderr)
        return 1
    by_action: Dict[str, float] = {}
    print(f"fault summary over {len(args.files)} host dump(s):")
    for (name, labels), v in sorted(totals.items()):
        lab = ",".join(f"{k}={val}" for k, val in labels)
        print(f"  {name}{{{lab}}} = {int(v)}")
        if name.startswith("tm_fault_"):
            action = name[len("tm_fault_"):-len("_total")]
        else:  # tm_elastic_*/tm_guard_*/tm_ckpt_*: keep the subsystem
            #   prefix
            action = name[len("tm_"):-len("_total")]
        by_action[action] = by_action.get(action, 0) + v
    line = "  ".join(f"{a}={int(v)}" for a, v in sorted(by_action.items()))
    print(f"totals: {line}")
    return 0


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    sub = p.add_subparsers(dest="cmd", required=True)

    s = sub.add_parser("gen", help="write a fault plan from --rule specs")
    s.add_argument("--out", required=True)
    s.add_argument("--seed", type=int, default=0)
    s.add_argument("--note", default="")
    s.add_argument("--rule", action="append", default=[],
                   help="site:kind[:prob[:max_hits[:delay_s[:after]]]] "
                        "(repeatable)")
    s.add_argument("--shrink", action="append", default=[],
                   help="RANK:STEP:NRANKS — elastic-gang recipe: kill "
                        "rank RANK at step STEP of an NRANKS-rank gang "
                        "(once per plan — later kills' arrival "
                        "ordinals shift after the first shrink)")
    s.add_argument("--stall", action="append", default=[],
                   help="RANK:STEP:NRANKS — watchdog recipe "
                        "(docs/WATCHDOG.md): wedge the gang on rank "
                        "RANK's liveness check at step STEP (a silent "
                        "indefinite hold; watchdog=break converts it "
                        "into a typed hang + N-1 shrink)")
    s.add_argument("--partition", action="append", default=[],
                   help="RANKS:STEP[:HEAL_STEP] — split-brain recipe "
                        "(docs/ELASTIC.md): partition the membership "
                        "board along RANKS ('2,3' symmetric, "
                        "'0,1|2,3' groups, '~2,3' one-way/deaf) from "
                        "gang step STEP, optionally healing at "
                        "HEAL_STEP; elastic_quorum=majority parks the "
                        "minority, quorum off demonstrably forks")
    s.add_argument("--migrate", action="append", default=[],
                   help="RANK:STEP:NRANKS — planned-migration drill "
                        "(docs/HOTSTATE.md): the driver drains rank "
                        "RANK onto a spare at step STEP "
                        "(hotstate.migrate); this kills the source at "
                        "step STEP+1 — a green run proves the drain "
                        "beat the preemption with zero rollback")
    s.set_defaults(fn=cmd_gen)

    s = sub.add_parser("lint", help="validate plan files")
    s.add_argument("files", nargs="+")
    s.add_argument("--json", action="store_true",
                   help="emit problems as findings JSON (the "
                        "lint_collectives.py wire format)")
    s.set_defaults(fn=cmd_lint)

    s = sub.add_parser("summarize",
                       help="print tm_fault_* counters from obs dumps")
    s.add_argument("files", nargs="+")
    s.set_defaults(fn=cmd_summarize)

    args = p.parse_args(argv)
    try:
        return args.fn(args)
    except BrokenPipeError:
        return 0
    except (OSError, ValueError) as e:
        print(f"error: {e}", file=sys.stderr)
        return 2


if __name__ == "__main__":
    sys.exit(main())

"""Dump, diff, aggregate, and blame torchmpi_tpu telemetry files
(docs/OBSERVABILITY.md).

The obs layer (``torchmpi_tpu.obs``) writes one JSONL file per host:
``metrics_host*.jsonl`` (counter/histogram snapshot) and
``flight_host*.jsonl`` (the deadlock flight recorder's event ring).
This tool is the operator surface over those files:

    python scripts/obs_tool.py dump  FILE [FILE ...]
    python scripts/obs_tool.py agg   FILE [FILE ...] [--json]
    python scripts/obs_tool.py diff  BEFORE AFTER
    python scripts/obs_tool.py prom  FILE [FILE ...]
    python scripts/obs_tool.py blame FLIGHT [FLIGHT ...]
    python scripts/obs_tool.py slo   FILE [FILE ...]

``slo`` reads a serving session's metric dumps and prints per-replica
p50/p95/p99 time-to-first-token and inter-token latency from the
``tm_serving_ttft_us`` / ``tm_serving_itl_us`` histograms
(docs/SERVING.md) — percentiles are upper log2-bucket edges, i.e.
conservative to within 2x, which is what a latency SLO check wants.
``dump`` validates and pretty-prints any obs file.  ``agg`` sums
counters and merges histograms across per-host metric files (the
fleet view).  ``diff`` prints per-series counter deltas between two
snapshots of the same host (rate over an interval).  ``prom`` renders
the aggregated snapshot in Prometheus text format.  ``blame`` aligns
per-host flight-recorder seq streams and names the FIRST diverging
collective — the runtime complement of the static analyzer's D1/D3
deadlock rules: hosts of one SPMD gang must issue identical collective
sequences, so the first seq where op/bytes differ (or where one host
keeps launching past the others' last event) is where the hang began.
Exits nonzero on divergence (blame) or unparseable input.

Standalone on purpose: no jax — parsing a pod's post-mortem must not
need the pod's software stack.  The Prometheus renderer is loaded
straight from ``torchmpi_tpu/obs/registry.py`` (itself dependency-free)
without importing the package.
"""

import argparse
import importlib.util
import json
import os
import sys
from typing import Dict, List, Tuple

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _load_registry_module():
    """Load obs/registry.py by path — reuses prometheus_lines without
    triggering the torchmpi_tpu package import (which pulls in jax)."""
    path = os.path.join(_REPO, "torchmpi_tpu", "obs", "registry.py")
    spec = importlib.util.spec_from_file_location("_obs_registry", path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def load_jsonl(path: str) -> Tuple[dict, List[dict]]:
    """Parse one obs JSONL file -> (meta, records).  Raises ValueError
    with a line number on malformed input."""
    meta: dict = {}
    records: List[dict] = []
    with open(path) as f:
        for i, line in enumerate(f, 1):
            line = line.strip()
            if not line:
                continue
            try:
                rec = json.loads(line)
            except json.JSONDecodeError as e:
                raise ValueError(f"{path}:{i}: not JSON ({e})") from None
            if not isinstance(rec, dict) or "kind" not in rec:
                raise ValueError(f"{path}:{i}: record without 'kind'")
            if rec["kind"] == "meta":
                meta = rec
            else:
                records.append(rec)
    return meta, records


def _series_key(rec: dict) -> Tuple[str, Tuple[Tuple[str, str], ...]]:
    return (rec["name"], tuple(sorted(rec.get("labels", {}).items())))


def _fmt_series(name: str, labels) -> str:
    lab = ",".join(f"{k}={v}" for k, v in labels)
    return f"{name}{{{lab}}}" if lab else name


def aggregate(files: List[str]) -> List[dict]:
    """Sum counters / merge histograms across metric files."""
    counters: Dict = {}
    hists: Dict = {}
    for path in files:
        _, records = load_jsonl(path)
        for rec in records:
            if rec["kind"] == "counter":
                k = _series_key(rec)
                counters[k] = counters.get(k, 0) + rec["value"]
            elif rec["kind"] == "hist":
                k = _series_key(rec)
                h = hists.setdefault(k, {"buckets": {}, "count": 0,
                                         "sum": 0.0})
                for b, c in rec.get("buckets", {}).items():
                    h["buckets"][b] = h["buckets"].get(b, 0) + c
                h["count"] += rec.get("count", 0)
                h["sum"] += rec.get("sum", 0.0)
    out = [{"kind": "counter", "name": n, "labels": dict(lk), "value": v}
           for (n, lk), v in sorted(counters.items())]
    out += [{"kind": "hist", "name": n, "labels": dict(lk),
             "buckets": dict(sorted(h["buckets"].items(),
                                    key=lambda kv: int(kv[0]))),
             "count": h["count"], "sum": h["sum"]}
            for (n, lk), h in sorted(hists.items())]
    return out


def cmd_dump(args) -> int:
    rc = 0
    for path in args.files:
        try:
            meta, records = load_jsonl(path)
        except (OSError, ValueError) as e:
            print(f"error: {e}", file=sys.stderr)
            rc = 1
            continue
        stream = meta.get("stream", "?")
        print(f"{path}: stream={stream} host={meta.get('host')} "
              f"mode={meta.get('mode')} records={len(records)}")
        for rec in records:
            if rec["kind"] == "counter":
                print(f"  {_fmt_series(rec['name'], sorted(rec.get('labels', {}).items()))}"
                      f" = {rec['value']}")
            elif rec["kind"] == "hist":
                bk = " ".join(f"2^{b}:{c}" for b, c
                              in sorted(rec.get("buckets", {}).items(),
                                        key=lambda kv: int(kv[0])))
                print(f"  {_fmt_series(rec['name'], sorted(rec.get('labels', {}).items()))}"
                      f" count={rec['count']} sum={rec['sum']:.6g} [{bk}]")
            elif rec["kind"] == "event":
                print(f"  #{rec['seq']} {rec.get('ev')}:"
                      f"{rec.get('op') or rec.get('detail')}"
                      f" {rec.get('nbytes', 0)}B {rec.get('backend', '')}"
                      f" t={rec.get('ts', 0):.6f}")
    return rc


def cmd_agg(args) -> int:
    snap = aggregate(args.files)
    if args.json:
        print(json.dumps(snap, indent=1))
    else:
        print(f"aggregated {len(args.files)} file(s), {len(snap)} series")
        for rec in snap:
            labels = sorted(rec.get("labels", {}).items())
            if rec["kind"] == "counter":
                print(f"  {_fmt_series(rec['name'], labels)} = {rec['value']}")
            else:
                print(f"  {_fmt_series(rec['name'], labels)} "
                      f"count={rec['count']} sum={rec['sum']:.6g}")
    return 0


def cmd_diff(args) -> int:
    _, before = load_jsonl(args.before)
    _, after = load_jsonl(args.after)
    b = {_series_key(r): r["value"] for r in before
         if r["kind"] == "counter"}
    a = {_series_key(r): r["value"] for r in after
         if r["kind"] == "counter"}
    shown = 0
    for k in sorted(set(b) | set(a)):
        d = a.get(k, 0) - b.get(k, 0)
        if d:
            shown += 1
            sign = "+" if d > 0 else ""
            print(f"  {_fmt_series(k[0], k[1])} {b.get(k, 0)} -> "
                  f"{a.get(k, 0)}  ({sign}{d})")
    print(f"{shown} series changed")
    return 0


def cmd_prom(args) -> int:
    reg = _load_registry_module()
    snap = aggregate(args.files)
    sys.stdout.write("\n".join(reg.prometheus_lines(snap)) + "\n")
    return 0


def _hist_percentile(buckets: Dict[str, int], count: int,
                     q: float) -> float:
    """Approximate quantile from log2 buckets: the UPPER edge
    ``2**(b+1)`` of the first bucket whose cumulative count reaches
    ``q * count`` — conservative (never under-reports a latency)."""
    target = q * count
    acc = 0
    for b, c in sorted(buckets.items(), key=lambda kv: int(kv[0])):
        acc += c
        if acc >= target:
            return float(2 ** (int(b) + 1))
    return 0.0


def cmd_slo(args) -> int:
    snap = aggregate(args.files)
    series: Dict[Tuple[str, str], dict] = {}
    counters: Dict[Tuple[str, str], float] = {}
    for rec in snap:
        rep = rec.get("labels", {}).get("replica", "")
        if rec["kind"] == "hist" and rec["name"] in (
                "tm_serving_ttft_us", "tm_serving_itl_us"):
            kind = "ttft" if "ttft" in rec["name"] else "itl"
            series[(rep, kind)] = rec
        elif rec["kind"] == "counter" and \
                rec["name"].startswith("tm_serving_"):
            # aggregate() already merged each (name, labels) series to
            # one record — plain assignment states that invariant.
            counters[(rep, rec["name"])] = rec["value"]
    if not series:
        print("no tm_serving_* latency histograms in the given files "
              "(was the session a serving run with obs active?)",
              file=sys.stderr)
        return 2
    replicas = sorted({rep for rep, _ in series})
    print(f"serving SLO percentiles over {len(args.files)} file(s) "
          f"(upper log2-bucket edges):")
    for rep in replicas:
        parts = []
        for kind, label in (("ttft", "TTFT"), ("itl", "inter-token")):
            rec = series.get((rep, kind))
            if rec is None or not rec.get("count"):
                continue
            ps = {p: _hist_percentile(rec.get("buckets", {}),
                                      rec["count"], p / 100.0) / 1e3
                  for p in (50, 95, 99)}
            mean = rec["sum"] / rec["count"] / 1e3
            parts.append(
                f"{label} p50<={ps[50]:g}ms p95<={ps[95]:g}ms "
                f"p99<={ps[99]:g}ms mean={mean:.3g}ms n={rec['count']}")
        extras = []
        for cname in ("tm_serving_requests_total",
                      "tm_serving_completed_total",
                      "tm_serving_rerouted_total",
                      "tm_serving_rejected_total"):
            v = counters.get((rep, cname))
            if v:
                extras.append(f"{cname.split('_')[2]}={int(v)}")
        rep_name = rep or "<all>"
        tail = f"  [{' '.join(extras)}]" if extras else ""
        print(f"  {rep_name}: " + " | ".join(parts) + tail)
    return 0


def _event_sig(e: dict) -> Tuple:
    """What must agree across an SPMD gang at one seq: the event type,
    op, and payload (backend compared too — hosts replaying divergent
    tuning plans compile different programs, the PL1 hazard)."""
    return (e.get("ev"), e.get("op"), e.get("nbytes"),
            e.get("backend"))


def cmd_blame(args) -> int:
    streams: Dict[str, Dict[int, dict]] = {}
    for path in args.files:
        meta, records = load_jsonl(path)
        events = {r["seq"]: r for r in records if r["kind"] == "event"}
        host = str(meta.get("host", path))
        streams[f"{host} ({os.path.basename(path)})"] = events
    if len(streams) < 2:
        print("blame needs >= 2 per-host flight files", file=sys.stderr)
        return 2
    names = sorted(streams)
    if not all(streams.values()):
        print("a host recorded no flight events — nothing to align")
        return 2
    lo = max(min(s) for s in streams.values())
    hi = min(max(s) for s in streams.values())
    if hi < lo:
        print("no overlapping seq range across hosts (rings trimmed "
              "past each other) — raise obs_ring_size")
        return 2
    for seq in range(lo, hi + 1):
        sigs = {n: _event_sig(streams[n][seq]) for n in names
                if seq in streams[n]}
        if len(set(sigs.values())) > 1:
            print(f"DIVERGENCE at seq {seq} — first collective the "
                  f"hosts disagree on:")
            for n in names:
                e = streams[n].get(seq)
                desc = (f"{e.get('ev')}:{e.get('op') or e.get('detail')} "
                        f"{e.get('nbytes', 0)}B {e.get('backend', '')}"
                        if e else "<no event>")
                print(f"  {n}: {desc}")
            return 1
    # Aligned over the overlap: a host that kept launching past the
    # others' last event names the collective the laggards never
    # reached — the classic "rank 0 is stuck, rank 1 moved on" hang.
    ends = {n: max(s) for n, s in streams.items()}
    last = min(ends.values())
    ahead = {n: e for n, e in ends.items() if e > last}
    if ahead:
        print(f"aligned through seq {last}; "
              f"{len(ahead)}/{len(names)} host(s) continued past it:")
        for n, e in sorted(ahead.items()):
            nxt = streams[n].get(last + 1)
            desc = (f"{nxt.get('ev')}:{nxt.get('op') or nxt.get('detail')} "
                    f"{nxt.get('nbytes', 0)}B" if nxt else "?")
            print(f"  {n}: reached seq {e}; first extra event: {desc}")
        print("the lagging host(s) likely hang in (or before) that "
              "collective")
        return 1
    print(f"aligned: {len(names)} hosts agree on seqs {lo}..{hi}")
    return 0


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    sub = p.add_subparsers(dest="cmd", required=True)

    s = sub.add_parser("dump", help="validate + pretty-print obs files")
    s.add_argument("files", nargs="+")
    s.set_defaults(fn=cmd_dump)

    s = sub.add_parser("agg", help="aggregate per-host metric files")
    s.add_argument("files", nargs="+")
    s.add_argument("--json", action="store_true")
    s.set_defaults(fn=cmd_agg)

    s = sub.add_parser("diff", help="counter deltas between two snapshots")
    s.add_argument("before")
    s.add_argument("after")
    s.set_defaults(fn=cmd_diff)

    s = sub.add_parser("prom", help="render aggregate as Prometheus text")
    s.add_argument("files", nargs="+")
    s.set_defaults(fn=cmd_prom)

    s = sub.add_parser("blame", help="align per-host flight recorders, "
                                     "name the first diverging collective")
    s.add_argument("files", nargs="+")
    s.set_defaults(fn=cmd_blame)

    s = sub.add_parser("slo", help="per-replica p50/p95/p99 TTFT and "
                                   "inter-token latency from a serving "
                                   "session's metric dumps")
    s.add_argument("files", nargs="+")
    s.set_defaults(fn=cmd_slo)

    args = p.parse_args(argv)
    try:
        return args.fn(args)
    except BrokenPipeError:
        return 0  # `obs_tool ... | head` is fine
    except (OSError, ValueError) as e:
        print(f"error: {e}", file=sys.stderr)
        return 2


if __name__ == "__main__":
    sys.exit(main())

"""Dump, diff, aggregate, and blame torchmpi_tpu telemetry files
(docs/OBSERVABILITY.md).

The obs layer (``torchmpi_tpu.obs``) writes one JSONL file per host:
``metrics_host*.jsonl`` (counter/histogram snapshot) and
``flight_host*.jsonl`` (the deadlock flight recorder's event ring).
This tool is the operator surface over those files:

    python scripts/obs_tool.py dump  FILE [FILE ...]
    python scripts/obs_tool.py agg   FILE [FILE ...] [--json]
    python scripts/obs_tool.py diff  BEFORE AFTER
    python scripts/obs_tool.py prom  FILE [FILE ...]
    python scripts/obs_tool.py blame FLIGHT [FLIGHT ...]
    python scripts/obs_tool.py blame --live LEASE_DIR
    python scripts/obs_tool.py slo   FILE [FILE ...]
    python scripts/obs_tool.py attribute DIR_OR_FLIGHT [...] [--json]
    python scripts/obs_tool.py attribute --diff BEFORE_DIR AFTER_DIR

``slo`` reads a serving session's metric dumps and prints per-replica
p50/p95/p99 time-to-first-token and inter-token latency from the
``tm_serving_ttft_us`` / ``tm_serving_itl_us`` histograms
(docs/SERVING.md) — percentiles are upper log2-bucket edges, i.e.
conservative to within 2x, which is what a latency SLO check wants.
``dump`` validates and pretty-prints any obs file.  ``agg`` sums
counters and merges histograms across per-host metric files (the
fleet view).  ``diff`` prints per-series counter deltas between two
snapshots of the same host (rate over an interval).  ``prom`` renders
the aggregated snapshot in Prometheus text format.  ``blame`` aligns
per-host flight-recorder seq streams and names the FIRST diverging
collective — the runtime complement of the static analyzer's D1/D3
deadlock rules: hosts of one SPMD gang must issue identical collective
sequences, so the first seq where op/bytes differ (or where one host
keeps launching past the others' last event) is where the hang began.
``attribute`` turns a host's flight ring + histograms into a per-step
time budget — dispatch_gap / collective_wait / host_staging / compile /
guard_verify shares summing to the step wall time (the phase model
lives in ``torchmpi_tpu/obs/attribution.py``; docs/OBSERVABILITY.md
"Attribution workflow") — and ``attribute --diff`` names the phase
whose share regressed between two dumps.
Since the ring records BOTH edges of a collective (dispatch + the
``*_done`` completion events), the laggard's last event distinguishes
"launched and stuck inside it" from "completed, never launched the
next".  ``blame --live <dir>`` skips the dump entirely: it reads the
collective watchdog's liveness leases (``wd_lease_*.json``,
docs/WATCHDOG.md) while the job runs and names the stalled/expired
rank live.  Exits nonzero on divergence (blame) or unparseable input.

Standalone on purpose: no jax — parsing a pod's post-mortem must not
need the pod's software stack.  The Prometheus renderer is loaded
straight from ``torchmpi_tpu/obs/registry.py`` (itself dependency-free)
without importing the package.
"""

import argparse
import importlib.util
import json
import os
import sys
from typing import Dict, List, Tuple

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _load_registry_module():
    """Load obs/registry.py by path — reuses prometheus_lines without
    triggering the torchmpi_tpu package import (which pulls in jax)."""
    path = os.path.join(_REPO, "torchmpi_tpu", "obs", "registry.py")
    spec = importlib.util.spec_from_file_location("_obs_registry", path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def load_jsonl(path: str) -> Tuple[dict, List[dict]]:
    """Parse one obs JSONL file -> (meta, records).  Raises ValueError
    with a line number on malformed input."""
    meta: dict = {}
    records: List[dict] = []
    with open(path) as f:
        for i, line in enumerate(f, 1):
            line = line.strip()
            if not line:
                continue
            try:
                rec = json.loads(line)
            except json.JSONDecodeError as e:
                raise ValueError(f"{path}:{i}: not JSON ({e})") from None
            if not isinstance(rec, dict) or "kind" not in rec:
                raise ValueError(f"{path}:{i}: record without 'kind'")
            if rec["kind"] == "meta":
                meta = rec
            else:
                records.append(rec)
    return meta, records


def _series_key(rec: dict) -> Tuple[str, Tuple[Tuple[str, str], ...]]:
    return (rec["name"], tuple(sorted(rec.get("labels", {}).items())))


def _fmt_series(name: str, labels) -> str:
    lab = ",".join(f"{k}={v}" for k, v in labels)
    return f"{name}{{{lab}}}" if lab else name


def aggregate(files: List[str]) -> List[dict]:
    """Sum counters / merge histograms across metric files."""
    counters: Dict = {}
    hists: Dict = {}
    for path in files:
        _, records = load_jsonl(path)
        for rec in records:
            if rec["kind"] == "counter":
                k = _series_key(rec)
                counters[k] = counters.get(k, 0) + rec["value"]
            elif rec["kind"] == "hist":
                k = _series_key(rec)
                h = hists.setdefault(k, {"buckets": {}, "count": 0,
                                         "sum": 0.0})
                for b, c in rec.get("buckets", {}).items():
                    h["buckets"][b] = h["buckets"].get(b, 0) + c
                h["count"] += rec.get("count", 0)
                h["sum"] += rec.get("sum", 0.0)
    out = [{"kind": "counter", "name": n, "labels": dict(lk), "value": v}
           for (n, lk), v in sorted(counters.items())]
    out += [{"kind": "hist", "name": n, "labels": dict(lk),
             "buckets": dict(sorted(h["buckets"].items(),
                                    key=lambda kv: int(kv[0]))),
             "count": h["count"], "sum": h["sum"]}
            for (n, lk), h in sorted(hists.items())]
    return out


def cmd_dump(args) -> int:
    rc = 0
    for path in args.files:
        try:
            meta, records = load_jsonl(path)
        except (OSError, ValueError) as e:
            print(f"error: {e}", file=sys.stderr)
            rc = 1
            continue
        stream = meta.get("stream", "?")
        print(f"{path}: stream={stream} host={meta.get('host')} "
              f"mode={meta.get('mode')} records={len(records)}")
        for rec in records:
            if rec["kind"] == "counter":
                print(f"  {_fmt_series(rec['name'], sorted(rec.get('labels', {}).items()))}"
                      f" = {rec['value']}")
            elif rec["kind"] == "hist":
                bk = " ".join(f"2^{b}:{c}" for b, c
                              in sorted(rec.get("buckets", {}).items(),
                                        key=lambda kv: int(kv[0])))
                print(f"  {_fmt_series(rec['name'], sorted(rec.get('labels', {}).items()))}"
                      f" count={rec['count']} sum={rec['sum']:.6g} [{bk}]")
            elif rec["kind"] == "event":
                print(f"  #{rec['seq']} {rec.get('ev')}:"
                      f"{rec.get('op') or rec.get('detail')}"
                      f" {rec.get('nbytes', 0)}B {rec.get('backend', '')}"
                      f" t={rec.get('ts', 0):.6f}")
    return rc


def cmd_agg(args) -> int:
    snap = aggregate(args.files)
    if args.json:
        print(json.dumps(snap, indent=1))
    else:
        print(f"aggregated {len(args.files)} file(s), {len(snap)} series")
        for rec in snap:
            labels = sorted(rec.get("labels", {}).items())
            if rec["kind"] == "counter":
                print(f"  {_fmt_series(rec['name'], labels)} = {rec['value']}")
            else:
                print(f"  {_fmt_series(rec['name'], labels)} "
                      f"count={rec['count']} sum={rec['sum']:.6g}")
    return 0


def cmd_diff(args) -> int:
    _, before = load_jsonl(args.before)
    _, after = load_jsonl(args.after)
    b = {_series_key(r): r["value"] for r in before
         if r["kind"] == "counter"}
    a = {_series_key(r): r["value"] for r in after
         if r["kind"] == "counter"}
    shown = 0
    for k in sorted(set(b) | set(a)):
        d = a.get(k, 0) - b.get(k, 0)
        if d:
            shown += 1
            sign = "+" if d > 0 else ""
            print(f"  {_fmt_series(k[0], k[1])} {b.get(k, 0)} -> "
                  f"{a.get(k, 0)}  ({sign}{d})")
    print(f"{shown} series changed")
    return 0


def cmd_prom(args) -> int:
    reg = _load_registry_module()
    snap = aggregate(args.files)
    sys.stdout.write("\n".join(reg.prometheus_lines(snap)) + "\n")
    return 0


def _hist_percentile(buckets: Dict[str, int], count: int,
                     q: float) -> float:
    """Approximate quantile from log2 buckets: the UPPER edge
    ``2**(b+1)`` of the first bucket whose cumulative count reaches
    ``q * count`` — conservative (never under-reports a latency)."""
    target = q * count
    acc = 0
    for b, c in sorted(buckets.items(), key=lambda kv: int(kv[0])):
        acc += c
        if acc >= target:
            return float(2 ** (int(b) + 1))
    return 0.0


def cmd_slo(args) -> int:
    snap = aggregate(args.files)
    series: Dict[Tuple[str, str], dict] = {}
    counters: Dict[Tuple[str, str], float] = {}
    for rec in snap:
        rep = rec.get("labels", {}).get("replica", "")
        if rec["kind"] == "hist" and rec["name"] in (
                "tm_serving_ttft_us", "tm_serving_itl_us"):
            kind = "ttft" if "ttft" in rec["name"] else "itl"
            series[(rep, kind)] = rec
        elif rec["kind"] == "counter" and \
                rec["name"].startswith("tm_serving_"):
            # aggregate() already merged each (name, labels) series to
            # one record — plain assignment states that invariant.
            counters[(rep, rec["name"])] = rec["value"]
    if not series:
        print("no tm_serving_* latency histograms in the given files "
              "(was the session a serving run with obs active?)",
              file=sys.stderr)
        return 2
    replicas = sorted({rep for rep, _ in series})
    print(f"serving SLO percentiles over {len(args.files)} file(s) "
          f"(upper log2-bucket edges):")
    for rep in replicas:
        parts = []
        for kind, label in (("ttft", "TTFT"), ("itl", "inter-token")):
            rec = series.get((rep, kind))
            if rec is None or not rec.get("count"):
                continue
            ps = {p: _hist_percentile(rec.get("buckets", {}),
                                      rec["count"], p / 100.0) / 1e3
                  for p in (50, 95, 99)}
            mean = rec["sum"] / rec["count"] / 1e3
            parts.append(
                f"{label} p50<={ps[50]:g}ms p95<={ps[95]:g}ms "
                f"p99<={ps[99]:g}ms mean={mean:.3g}ms n={rec['count']}")
        extras = []
        for cname in ("tm_serving_requests_total",
                      "tm_serving_completed_total",
                      "tm_serving_rerouted_total",
                      "tm_serving_rejected_total",
                      "tm_serving_prefill_compiles_total",
                      "tm_serving_spec_drafted_total",
                      "tm_serving_spec_accepted_total",
                      "tm_serving_prefix_hits_total",
                      "tm_serving_prefix_misses_total",
                      "tm_serving_prefix_tokens_saved_total",
                      "tm_serving_prefix_bytes_saved_total",
                      "tm_serving_prefix_inserted_total",
                      "tm_serving_prefix_evicted_total"):
            v = counters.get((rep, cname))
            if v:
                label = cname[len("tm_serving_"):-len("_total")]
                extras.append(f"{label}={int(v)}")
        rep_name = rep or "<all>"
        tail = f"  [{' '.join(extras)}]" if extras else ""
        print(f"  {rep_name}: " + " | ".join(parts) + tail)
    # Fleet/admission summary: the gate and the autoscaler are global
    # (replica-unlabeled), so they print once — shed/admitted counts,
    # scale events, and the queue-depth p95 the controller acts on.
    fleet = []
    for cname in ("tm_serving_admitted_total", "tm_serving_shed_total",
                  "tm_serving_scale_up_total",
                  "tm_serving_scale_down_total"):
        v = sum(val for (rep, name), val in counters.items()
                if name == cname)
        if v:
            fleet.append(f"{cname[len('tm_serving_'):-len('_total')]}="
                         f"{int(v)}")
    depth = next((rec for rec in snap
                  if rec["kind"] == "hist"
                  and rec["name"] == "tm_serving_queue_depth"), None)
    if depth is not None and depth.get("count"):
        p95 = _hist_percentile(depth.get("buckets", {}), depth["count"],
                               0.95)
        mean = depth["sum"] / depth["count"]
        fleet.append(f"queue_depth p95<={p95:g} mean={mean:.3g} "
                     f"ticks={depth['count']}")
    if fleet:
        print(f"  fleet: {' '.join(fleet)}")
    return 0


def _event_sig(e: dict) -> Tuple:
    """What must agree across an SPMD gang at one seq: the event type,
    op, and payload (backend compared too — hosts replaying divergent
    tuning plans compile different programs, the PL1 hazard)."""
    return (e.get("ev"), e.get("op"), e.get("nbytes"),
            e.get("backend"))


# Dispatch events that now have a matching completion edge in the ring
# (docs/WATCHDOG.md): a laggard whose LAST event is one of these died
# INSIDE that collective; a laggard whose last event is a *_done edge
# completed its last collective and hung before launching the next.
_DISPATCH_EVENTS = ("eager", "barrier")
_DONE_EVENTS = ("eager_done", "barrier_done", "ps_wait_done")


def _laggard_verdict(last_event: dict) -> str:
    ev = last_event.get("ev", "")
    what = f"{ev}:{last_event.get('op') or last_event.get('detail')}"
    if ev in _DISPATCH_EVENTS:
        return (f"last event is a DISPATCH ({what}) with no completion "
                f"edge — launched and stuck INSIDE that collective")
    if ev == "eager_done" and last_event.get("backend") != "host":
        # The direct (XLA) path's done edge marks the ASYNC ENQUEUE
        # returning, not device execution finishing — a wedge in the
        # fabric still happens after this edge, at the blocking
        # readiness wait.
        return (f"last event is the dispatch-returned edge ({what}, "
                f"direct backend) — the collective was enqueued; the "
                f"wedge is in its device execution or in whatever "
                f"comes after (check async waits / block_until_ready)")
    if ev in _DONE_EVENTS:
        return (f"last event is a COMPLETION edge ({what}) — its last "
                f"collective finished; the NEXT one was never launched "
                f"(stuck between collectives: data loader, host code, "
                f"or a non-collective wait)")
    return f"last event: {what}"


def cmd_blame(args) -> int:
    if getattr(args, "live", False):
        return cmd_blame_live(args)
    streams: Dict[str, Dict[int, dict]] = {}
    for path in args.files:
        meta, records = load_jsonl(path)
        events = {r["seq"]: r for r in records if r["kind"] == "event"}
        host = str(meta.get("host", path))
        streams[f"{host} ({os.path.basename(path)})"] = events
    if len(streams) < 2:
        print("blame needs >= 2 per-host flight files", file=sys.stderr)
        return 2
    names = sorted(streams)
    if not all(streams.values()):
        print("a host recorded no flight events — nothing to align")
        return 2
    lo = max(min(s) for s in streams.values())
    hi = min(max(s) for s in streams.values())
    if hi < lo:
        print("no overlapping seq range across hosts (rings trimmed "
              "past each other) — raise obs_ring_size")
        return 2
    for seq in range(lo, hi + 1):
        sigs = {n: _event_sig(streams[n][seq]) for n in names
                if seq in streams[n]}
        if len(set(sigs.values())) > 1:
            print(f"DIVERGENCE at seq {seq} — first collective the "
                  f"hosts disagree on:")
            for n in names:
                e = streams[n].get(seq)
                desc = (f"{e.get('ev')}:{e.get('op') or e.get('detail')} "
                        f"{e.get('nbytes', 0)}B {e.get('backend', '')}"
                        if e else "<no event>")
                print(f"  {n}: {desc}")
            return 1
    # Aligned over the overlap: a host that kept launching past the
    # others' last event names the collective the laggards never
    # reached — the classic "rank 0 is stuck, rank 1 moved on" hang.
    ends = {n: max(s) for n, s in streams.items()}
    last = min(ends.values())
    ahead = {n: e for n, e in ends.items() if e > last}
    if ahead:
        print(f"aligned through seq {last}; "
              f"{len(ahead)}/{len(names)} host(s) continued past it:")
        for n, e in sorted(ahead.items()):
            nxt = streams[n].get(last + 1)
            desc = (f"{nxt.get('ev')}:{nxt.get('op') or nxt.get('detail')} "
                    f"{nxt.get('nbytes', 0)}B" if nxt else "?")
            print(f"  {n}: reached seq {e}; first extra event: {desc}")
        print("the lagging host(s) likely hang in (or before) that "
              "collective")
        for n in sorted(set(names) - set(ahead)):
            # Both edges are recorded now (dispatch + completion), so
            # the laggard's last event says WHERE it died: inside its
            # last collective, or between collectives.
            print(f"  {n}: {_laggard_verdict(streams[n][ends[n]])}")
        return 1
    print(f"aligned: {len(names)} hosts agree on seqs {lo}..{hi}")
    return 0


def _load_leases(directory: str) -> Dict[int, dict]:
    """Parse every ``wd_lease_*.json`` under ``directory`` (the
    collective watchdog's liveness leases, docs/WATCHDOG.md) keyed by
    rank.  Parsing is inlined on purpose — this tool must not import
    the pod's software stack; the lease schema is self-describing
    (each lease carries its own ``ttl_s``)."""
    out: Dict[int, dict] = {}
    try:
        names = sorted(n for n in os.listdir(directory)
                       if n.startswith("wd_lease_") and n.endswith(".json"))
    except OSError as e:
        raise ValueError(f"{directory}: {e}") from None
    for name in names:
        try:
            with open(os.path.join(directory, name)) as f:
                d = json.load(f)
            out[int(d["rank"])] = d
        except (OSError, ValueError, KeyError):
            continue  # torn mid-renewal — same as unrenewed
    return out


def cmd_blame_live(args) -> int:
    """``blame --live <dir>``: read the watchdog leases while the job
    RUNS — no dumps, no SIGTERM — and name the implicated rank(s).
    The triage matrix (docs/WATCHDOG.md): a rank whose lease is FRESH
    but whose collective is STALLED is wedged on a *peer*; an EXPIRED
    or ``escalated`` lease is that rank's own death evidence; a fresh
    lease with ``state=parked`` is a quorum-lost minority waiting out
    a partition (docs/ELASTIC.md) — alive, deliberately idle, and NOT
    to be restarted; a fresh lease with ``state=migrating`` is a live
    hot-state drain in flight (docs/HOTSTATE.md — the detail carries
    ``source -> spare``): in transition BY DESIGN, neither parked nor
    dead, and killing either end mid-drain forfeits the zero-rollback
    hand-off.  Exits 1 when anything is stalled/expired/parked/
    migrating, 0 when all ranks look healthy, 2 on unusable input."""
    import time

    if len(args.files) != 1:
        print("blame --live takes exactly one lease DIRECTORY "
              "(Config.watchdog_dir / the membership board)",
              file=sys.stderr)
        return 2
    directory = args.files[0]
    leases = _load_leases(directory)
    if not leases:
        print(f"no wd_lease_*.json under {directory} — is the watchdog "
              f"armed (Config.watchdog != 'off') with watchdog_dir "
              f"pointing here?", file=sys.stderr)
        return 2
    now = time.time()
    implicated = []
    parked = []
    migrating = []
    stalled_peers = set()
    print(f"live watchdog leases in {directory} ({len(leases)} rank(s)):")
    for rank in sorted(leases):
        d = leases[rank]
        age = now - float(d.get("ts", 0))
        expired = age > float(d.get("ttl_s", 0))
        stalls = [e for e in d.get("inflight", []) if e.get("stalled")]
        if d.get("escalated"):
            state = (f"ESCALATED (watchdog exited the process on an "
                     f"unbreakable stall; lease renewed {age:.1f}s ago)")
            implicated.append(rank)
        elif expired:
            state = (f"EXPIRED (last renewed {age:.1f}s ago, ttl "
                     f"{d.get('ttl_s')}s) — dead, or wedged beyond its "
                     f"own watchdog")
            implicated.append(rank)
        elif d.get("state") == "parked":
            # A quorum-parked minority (docs/ELASTIC.md "Partitions
            # and split-brain"): deliberately idle, lease FRESH — not
            # a corpse, not a stall.  It rejoins the majority's
            # committed epoch on its own once the partition heals.
            detail = d.get("state_detail") or "a newer committed epoch"
            state = (f"PARKED (quorum lost — {detail}; lease renewed "
                     f"{age:.1f}s ago; will rejoin at heal, no "
                     f"restart needed)")
            parked.append(rank)
        elif d.get("state") == "migrating":
            # A live hot-state drain (docs/HOTSTATE.md): the rank is
            # mid-hand-off onto a spare — in transition BY DESIGN,
            # lease fresh.  Distinct from parked (it is not waiting on
            # anything external) and from dead (killing it mid-drain
            # forfeits the zero-rollback migration).
            detail = d.get("state_detail") or "onto a spare"
            state = (f"MIGRATING ({detail}; lease renewed {age:.1f}s "
                     f"ago — live drain in flight, do not kill either "
                     f"end)")
            migrating.append(rank)
        elif stalls:
            parts = ", ".join(
                f"{e.get('site')}"
                + (f" op={e.get('op')}" if e.get("op") else "")
                + (f" peer={e.get('peer')}" if e.get("peer") else "")
                + f" for {e.get('elapsed_s', 0):.3g}s"
                + (" [break requested]" if e.get("break_requested")
                   else "")
                for e in stalls)
            state = f"LIVE but STALLED in {parts}"
            stalled_peers.update(e.get("peer") for e in stalls
                                 if e.get("peer"))
        elif d.get("inflight"):
            state = (f"LIVE (renewed {age:.1f}s ago), "
                     f"{len(d['inflight'])} collective(s) in flight")
        else:
            state = f"LIVE idle (renewed {age:.1f}s ago)"
        print(f"  rank {rank}: {state}")
    verdicts = []
    if implicated:
        verdicts.append(
            f"rank(s) {implicated} implicated (expired/escalated lease "
            f"— the elastic layer treats this as death evidence)")
    if parked:
        verdicts.append(
            f"rank(s) {parked} PARKED (quorum-lost minority waiting "
            f"out a partition — alive and heartbeating, NOT a corpse; "
            f"they readmit themselves once the board heals)")
    if migrating:
        verdicts.append(
            f"rank(s) {migrating} MIGRATING (hot-state drain onto a "
            f"spare in flight — transitional, not parked, not dead; "
            f"leave both ends alone until the lease returns to "
            f"running)")
    stalled_ranks = [r for r in sorted(leases)
                     if any(e.get("stalled")
                            for e in leases[r].get("inflight", []))]
    if stalled_ranks and not implicated:
        peers = sorted(p for p in stalled_peers if p and p != "gang")
        blame_s = f"; stalls implicate {peers}" if peers else ""
        verdicts.append(
            f"rank(s) {stalled_ranks} stalled with fresh leases — the "
            f"hang is on a peer (or the fabric), not their own "
            f"liveness{blame_s}")
    if verdicts:
        print("verdict: " + "; ".join(verdicts))
        return 1
    print("verdict: all ranks healthy (fresh leases, no stalls)")
    return 0


def _load_attribution_module():
    """Load obs/attribution.py by path — the ``registry.py`` pattern:
    the phase model is stdlib-only, and a post-mortem must not need
    jax."""
    path = os.path.join(_REPO, "torchmpi_tpu", "obs", "attribution.py")
    spec = importlib.util.spec_from_file_location("_obs_attribution",
                                                  path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def _flight_files(paths: List[str]) -> List[str]:
    """Expand dump directories to their flight_host*.jsonl files."""
    out: List[str] = []
    for p in paths:
        if os.path.isdir(p):
            found = sorted(os.path.join(p, f) for f in os.listdir(p)
                           if f.startswith("flight_host")
                           and f.endswith(".jsonl"))
            if not found:
                raise ValueError(f"{p}: no flight_host*.jsonl files")
            out.extend(found)
        else:
            out.append(p)
    return out


def _attribute_paths(attr, paths: List[str]) -> List[dict]:
    """Per-host budgets for a dump: each flight file paired with its
    sibling metrics_host*.jsonl (same host suffix) when present."""
    budgets: List[dict] = []
    for fpath in _flight_files(paths):
        meta, flight = load_jsonl(fpath)
        mpath = os.path.join(
            os.path.dirname(fpath),
            os.path.basename(fpath).replace("flight_host",
                                            "metrics_host", 1))
        metrics: List[dict] = []
        if mpath != fpath and os.path.exists(mpath):
            _, metrics = load_jsonl(mpath)
        host = str(meta.get("host", "")) or os.path.basename(fpath)
        b = attr.attribute_host(flight, metrics, host=host)
        if b is not None:
            budgets.append(b)
    return budgets


def cmd_attribute(args) -> int:
    attr = _load_attribution_module()
    if args.diff:
        if args.files:
            raise ValueError("--diff takes its two dumps as the diff "
                             "arguments; drop the positional files")
        before = _attribute_paths(attr, [args.diff[0]])
        after = _attribute_paths(attr, [args.diff[1]])
        if not before or not after:
            raise ValueError("no events to attribute in one of the "
                             "dumps")
        d = attr.diff_budgets(before, after)
        if args.json:
            print(json.dumps(d, indent=2, sort_keys=True))
            return 0
        for p in attr.PHASES:
            print(f"{p:16s} {d['before']['shares'][p] * 100:6.1f}% -> "
                  f"{d['after']['shares'][p] * 100:6.1f}%  "
                  f"({d['deltas'][p] * +100:+.1f}pp)")
        ratio = d["step_ratio"]
        if ratio is not None:
            print(f"step wall: {d['before']['step_s'] * 1e3:.2f}ms -> "
                  f"{d['after']['step_s'] * 1e3:.2f}ms ({ratio:.2f}x)")
        if d["regressed"]:
            print(f"regressed phase: {d['regressed']} "
                  f"(+{d['deltas'][d['regressed']] * 100:.1f}pp of "
                  f"step time)")
        else:
            print("regressed phase: none (no share grew)")
        return 0
    if not args.files:
        raise ValueError("give a dump directory or flight_host*.jsonl "
                         "files (or --diff BEFORE AFTER)")
    budgets = _attribute_paths(attr, args.files)
    if not budgets:
        raise ValueError("no events to attribute (empty flight rings)")
    if args.json:
        print(json.dumps({"hosts": budgets,
                          "aggregate": attr.aggregate_shares(budgets)},
                         indent=2, sort_keys=True))
        return 0
    print(attr.format_table(budgets))
    agg = attr.aggregate_shares(budgets)
    print("aggregate: " + "  ".join(
        f"{p}={agg[p] * 100:.1f}%" for p in attr.PHASES))
    return 0


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    sub = p.add_subparsers(dest="cmd", required=True)

    s = sub.add_parser("dump", help="validate + pretty-print obs files")
    s.add_argument("files", nargs="+")
    s.set_defaults(fn=cmd_dump)

    s = sub.add_parser("agg", help="aggregate per-host metric files")
    s.add_argument("files", nargs="+")
    s.add_argument("--json", action="store_true")
    s.set_defaults(fn=cmd_agg)

    s = sub.add_parser("diff", help="counter deltas between two snapshots")
    s.add_argument("before")
    s.add_argument("after")
    s.set_defaults(fn=cmd_diff)

    s = sub.add_parser("prom", help="render aggregate as Prometheus text")
    s.add_argument("files", nargs="+")
    s.set_defaults(fn=cmd_prom)

    s = sub.add_parser("blame", help="align per-host flight recorders, "
                                     "name the first diverging collective; "
                                     "--live reads watchdog leases from a "
                                     "directory while the job runs")
    s.add_argument("files", nargs="+",
                   help="flight_host*.jsonl files, or with --live ONE "
                        "lease directory (Config.watchdog_dir)")
    s.add_argument("--live", action="store_true",
                   help="read wd_lease_*.json liveness leases "
                        "(docs/WATCHDOG.md) instead of post-mortem "
                        "flight dumps")
    s.set_defaults(fn=cmd_blame)

    s = sub.add_parser("slo", help="per-replica p50/p95/p99 TTFT and "
                                   "inter-token latency from a serving "
                                   "session's metric dumps")
    s.add_argument("files", nargs="+")
    s.set_defaults(fn=cmd_slo)

    s = sub.add_parser("attribute",
                       help="per-step time budget from a host's flight "
                            "ring + histograms (dispatch_gap / "
                            "collective_wait / host_staging / compile "
                            "/ guard_verify); --diff names the phase "
                            "whose share regressed between two dumps")
    s.add_argument("files", nargs="*",
                   help="dump directory or flight_host*.jsonl files "
                        "(sibling metrics_host*.jsonl auto-paired)")
    s.add_argument("--json", action="store_true")
    s.add_argument("--diff", nargs=2, metavar=("BEFORE", "AFTER"),
                   help="two dump directories (or flight files) to "
                        "compare")
    s.set_defaults(fn=cmd_attribute)

    args = p.parse_args(argv)
    try:
        return args.fn(args)
    except BrokenPipeError:
        return 0  # `obs_tool ... | head` is fine
    except (OSError, ValueError) as e:
        print(f"error: {e}", file=sys.stderr)
        return 2


if __name__ == "__main__":
    sys.exit(main())

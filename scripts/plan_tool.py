"""Inspect, merge, and prune tuning-plan files (docs/TUNING.md).

The plan DB (``torchmpi_tpu/tuning/plancache.py``) is one JSON file per
machine; fleets accumulate several (one per topology, or per job's
``--plan-out``).  This tool is the operator surface:

    python scripts/plan_tool.py show  plans.json [--match cpu]
    python scripts/plan_tool.py merge merged.json a.json b.json [...]
    python scripts/plan_tool.py prune plans.json --older-than-days 30
    python scripts/plan_tool.py prune plans.json --drop-match "ici:4"
    python scripts/plan_tool.py lint  a.json [b.json ...] [--json]
    python scripts/plan_tool.py dump-live [--devices 8] [--exec f.py]

``show`` prints one line per entry (key, backend, evidence medians).
``merge`` unions entries (newer timestamp wins a key conflict) into OUT.
``prune`` drops entries by age and/or key substring, atomically
rewriting the file.  ``dump-live`` prints the IN-PROCESS CollectivePlan
table (``torchmpi_tpu/planner.py`` — the dispatch-path decision cache,
distinct from the on-disk tuning-plan DB the other commands manage):
it initializes a runtime, runs either ``--exec SCRIPT`` in-process or a
small built-in warmup, and prints one line per live plan plus the
hit/miss stats — the debugging surface for "is my hot path replaying
or re-planning?".  Library code can call
``torchmpi_tpu.planner.describe()`` directly for the same rows.  ``lint`` validates plan files for cross-host
divergence hazards (the same fingerprint resolved to DIFFERENT backends
in different files — two hosts of one job would pick different
implementations for the same collective and deadlock; rule PL1, error)
and orphaned size buckets (a lone measurement more than 4 log2 buckets
from its nearest neighbor in an otherwise-measured group — a size
nobody actually runs, usually a stale experiment; rule PL2, warning),
reporting via the analyzer's structured Finding type and exiting
nonzero on errors.  All commands use PlanCache's never-crash load: a
corrupt input is reported, not a traceback.
"""

import argparse
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from torchmpi_tpu.tuning import PlanCache  # noqa: E402


def _load_or_die(path: str) -> PlanCache:
    cache = PlanCache.load(path)
    if cache.degraded_reason is not None:
        print(f"warning: {path}: {cache.degraded_reason} "
              f"(treating as empty)", file=sys.stderr)
    return cache


def cmd_show(args) -> int:
    cache = _load_or_die(args.file)
    shown = 0
    for key, e in sorted(cache.entries.items()):
        if args.match and args.match not in key:
            continue
        shown += 1
        meds = ""
        if e.median_ms:
            meds = " " + " ".join(
                f"{b}={ms:.3f}ms" for b, ms in sorted(e.median_ms.items()))
        age = ""
        if e.timestamp:
            age = f" age={(time.time() - e.timestamp) / 86400:.1f}d"
        print(f"{key} -> {e.backend} [{e.source} rounds={e.rounds}{age}]"
              f"{meds}")
    print(f"{shown}/{len(cache)} entries"
          + (f" matching {args.match!r}" if args.match else ""))
    return 0


def cmd_merge(args) -> int:
    out = PlanCache(args.out)
    for path in args.inputs:
        src = _load_or_die(path)
        adopted = out.merge_from(src)
        print(f"{path}: {len(src)} entries, {adopted} adopted")
    if not out.save(args.out):
        print(f"error: cannot write {args.out}", file=sys.stderr)
        return 1
    print(f"wrote {args.out}: {len(out)} entries")
    return 0


def cmd_prune(args) -> int:
    cache = _load_or_die(args.file)
    if cache.degraded_reason is not None:
        print("error: refusing to rewrite a degraded file", file=sys.stderr)
        return 1
    cutoff = (time.time() - args.older_than_days * 86400
              if args.older_than_days is not None else None)

    def keep(key, e) -> bool:
        if cutoff is not None and e.timestamp and e.timestamp < cutoff:
            return False
        if args.drop_match and args.drop_match in key:
            return False
        return True

    dropped = cache.prune(keep)
    if not cache.save(args.file, merge=False):
        print(f"error: cannot write {args.file}", file=sys.stderr)
        return 1
    print(f"dropped {dropped}, kept {len(cache)} -> {args.file}")
    return 0


def cmd_lint(args) -> int:
    import json

    from torchmpi_tpu import analysis

    findings = []
    # key -> {backend -> [files]} across every input.
    seen = {}
    caches = []
    for path in args.files:
        cache = _load_or_die(path)
        caches.append((path, cache))
        for key, e in cache.entries.items():
            seen.setdefault(key, {}).setdefault(e.backend, []).append(path)

    # PL1: cross-host divergence — one fingerprint, different backends.
    for key, by_backend in sorted(seen.items()):
        if len(by_backend) > 1:
            detail = "; ".join(
                f"{b} in {', '.join(sorted(set(fs)))}"
                for b, fs in sorted(by_backend.items()))
            findings.append(analysis.Finding(
                rule="PL1", severity=analysis.ERROR,
                message=(f"plan key {key} resolves to different backends "
                         f"across files ({detail}): hosts replaying "
                         f"different plans pick different collective "
                         f"implementations for the same step and "
                         f"deadlock — re-merge with plan_tool merge "
                         f"(newest wins) before deploying"),
                path=key))

    # PL2: orphaned size buckets — a measurement >4 log2 buckets from
    # its nearest neighbor in a group that has other entries.
    groups = {}
    for path, cache in caches:
        for key in cache.entries:
            prefix, _, bucket = key.rpartition("|b")
            try:
                groups.setdefault(prefix, set()).add((int(bucket), key))
            except ValueError:
                continue
    for prefix, buckets in sorted(groups.items()):
        if len(buckets) < 2:
            continue
        ordered = sorted(buckets)
        for i, (b, key) in enumerate(ordered):
            gaps = []
            if i > 0:
                gaps.append(b - ordered[i - 1][0])
            if i + 1 < len(ordered):
                gaps.append(ordered[i + 1][0] - b)
            if gaps and min(gaps) > 4:
                findings.append(analysis.Finding(
                    rule="PL2", severity=analysis.WARNING,
                    message=(f"size bucket b{b} is {min(gaps)} log2 "
                             f"buckets from its nearest measured "
                             f"neighbor in this group — an orphaned "
                             f"one-off measurement (stale experiment?); "
                             f"prune it or re-measure the sizes between"),
                    path=key))

    findings = analysis.sort_findings(findings)
    if args.json:
        print(json.dumps([f.to_json() for f in findings], indent=1))
    else:
        total = sum(len(c.entries) for _, c in caches)
        print(f"linted {len(args.files)} file(s), {total} entries: "
              f"{len(findings)} finding(s)")
        for f in findings:
            print(f"  {f}")
    return 1 if analysis.has_errors(findings) else 0


def cmd_dump_live(args) -> int:
    import json

    if args.devices:
        from torchmpi_tpu.utils.simulation import force_cpu_devices

        force_cpu_devices(args.devices)
    import numpy as np

    import torchmpi_tpu as mpi
    from torchmpi_tpu import planner

    if args.exec_path:
        import runpy

        # Run the user's entry point in-process so its plans populate
        # THIS interpreter's table (init() inside the script is fine —
        # init is idempotent and dump-live adds none of its own).
        runpy.run_path(args.exec_path, run_name="__main__")
    else:
        # Built-in warmup: a few representative eager dispatches (each
        # second call is a hit, so the stats line shows replay working).
        mpi.init()
        n = mpi.device_count()
        x = np.arange(n * 64, dtype=np.float32).reshape(n, 64)
        for _ in range(2):
            mpi.allreduce(x)
            mpi.broadcast(x, root=0)
            mpi.allreduce(x.astype(np.float16), op="sum")
    rows = planner.describe()
    st = planner.stats()
    if args.json:
        print(json.dumps({"stats": st, "plans": rows}, indent=1))
    else:
        for r in rows:
            print(f"{r['kind']:13s} {r['op']:14s} "
                  f"backend={r['backend'] or '-':13s} "
                  f"topo={r.get('topology') or '-':7s} "
                  f"{r['nbytes']:>10d} B  {r['launches']:3d} launches  "
                  f"epoch={r['epoch']}  hits={r['hits']}  "
                  f"build={r['build_ms']:.2f}ms"
                  + ("  staged" if r["staged"] else "")
                  + (f"  analysis={r['analysis']}"
                     if r["analysis"] != "off" else ""))
        print(f"{len(rows)} live plan(s); {st['hits']} hits / "
              f"{st['misses']} misses / {st['invalidations']} "
              f"invalidations this process")
    return 0


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    sub = p.add_subparsers(dest="cmd", required=True)

    s = sub.add_parser("show", help="list a plan file's entries")
    s.add_argument("file")
    s.add_argument("--match", default=None,
                   help="only keys containing this substring")
    s.set_defaults(fn=cmd_show)

    s = sub.add_parser("merge", help="union plan files into OUT")
    s.add_argument("out")
    s.add_argument("inputs", nargs="+")
    s.set_defaults(fn=cmd_merge)

    s = sub.add_parser("prune", help="drop entries by age / key match")
    s.add_argument("file")
    s.add_argument("--older-than-days", type=float, default=None)
    s.add_argument("--drop-match", default=None,
                   help="drop keys containing this substring")
    s.set_defaults(fn=cmd_prune)

    s = sub.add_parser("lint", help="validate plan files: cross-host "
                                    "divergence (PL1), orphaned size "
                                    "buckets (PL2)")
    s.add_argument("files", nargs="+")
    s.add_argument("--json", action="store_true",
                   help="emit findings as JSON")
    s.set_defaults(fn=cmd_lint)

    s = sub.add_parser("dump-live",
                       help="print the in-process CollectivePlan table "
                            "(runs --exec SCRIPT or a built-in warmup "
                            "to populate it)")
    s.add_argument("--devices", type=int, default=0,
                   help="force N simulated CPU devices before init")
    s.add_argument("--exec", dest="exec_path", default=None,
                   help="python entry point to run in-process before "
                        "dumping (its plans populate the table)")
    s.add_argument("--json", action="store_true",
                   help="emit the table as JSON")
    s.set_defaults(fn=cmd_dump_live)

    args = p.parse_args(argv)
    return args.fn(args)


if __name__ == "__main__":
    sys.exit(main())

#!/usr/bin/env python
"""Relay watcher: probe the TPU until it is alive, then bank benchmarks.

The relay's compile service is serial and can wedge indefinitely (rounds
1-2 postmortems, docs/ROUND2_NOTES.md): liveness windows are rare and
must not be wasted.  This watcher probes cheaply on an interval, and the
moment a probe succeeds runs the banking sequence — cheapest artifacts
first, one device client at a time, each stage streaming its JSON to
disk the moment it completes:

1. ``bench.py`` (self-supervised stage ladder A->D; the stage-D gate
   inside bench.py refuses to start the big ResNet compile without
   budget to finish it);
2. ``benchmarks/autotune.py --quick`` (single-chip-meaningful knobs);
3. ``benchmarks/overlap_trace.py`` (profiler-trace artifact).

Every probe child is killed with SIGTERM + grace, never a bare SIGKILL:
a KILL mid-device-claim is what wedged the relay in round 1.

Run: ``python scripts/tpu_watch.py [--interval 300] [--once]``
Artifacts land in ``docs/artifacts/`` (gitignored raw logs are written
next to them with a ``.log`` suffix; the JSON records are committed).
"""

import argparse
import json
import os
import signal
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
ART = os.path.join(REPO, "docs", "artifacts")

PROBE = r"""
import time, sys
t0 = time.time()
import jax, jax.numpy as jnp
ds = jax.devices()
x = jnp.ones((1024, 1024), jnp.bfloat16)
y = (x @ x * (1.0/1024)).block_until_ready()
print(f"ALIVE {ds[0].platform} {ds[0].device_kind} "
      f"probe_s={time.time()-t0:.1f}", flush=True)
"""


def log(*a):
    print(time.strftime("[%H:%M:%S]"), *a, file=sys.stderr, flush=True)


def _compile_inflight():
    """True while ANY process holds a fresh compile-inflight heartbeat
    (written by torchmpi_tpu.utils.compilegate during a blessed relay
    compile).  Matched by glob, not pid: bench.py's compiles run in a
    grandchild of the proc this watcher holds, so keying on the direct
    child's pid would miss the heartbeat that matters.  Escalating to
    SIGKILL while one is fresh would abandon the relay's serial compile
    queue — the exact wedge this watcher exists to avoid."""
    import glob as _glob

    for path in _glob.glob(os.path.join(REPO, ".jax_compile_cache",
                                        "compile_inflight_*")):
        try:
            if (time.time() - os.path.getmtime(path)) < 45.0:
                return True
        except OSError:
            continue
    return False


def _wait_compile_drain(why, cap_s=2700.0):
    """Sleep while a compile heartbeat is fresh, up to ``cap_s`` (3x the
    cold-compile budget): a heartbeat that outlives any plausible
    compile means the relay is already wedged and waiting longer buys
    nothing — the watcher must get back to probing (code review r4)."""
    t0 = time.time()
    while _compile_inflight():
        if time.time() - t0 > cap_s:
            log(f"{why}: compile heartbeat still fresh after {cap_s:.0f}s "
                "cap; relay presumed wedged — proceeding to signal")
            return
        log(f"{why}: compile in flight; waiting before signalling")
        time.sleep(30)


def run_bounded(cmd, timeout, log_path, env=None):
    """Run cmd with SIGTERM-then-KILL bounding; tee output to log_path.
    Returns (rc, last_lines).  The KILL escalation WAITS (bounded) while
    a relay compile heartbeat is fresh — a compile must never be
    abandoned mid-queue (docs/ROUND3_NOTES.md)."""
    with open(log_path, "a") as lf:
        lf.write(f"\n=== {time.strftime('%F %T')} {' '.join(cmd)} "
                 f"(timeout {timeout}s)\n")
        lf.flush()
        proc = subprocess.Popen(cmd, stdout=lf, stderr=subprocess.STDOUT,
                                env=env, cwd=REPO)
        try:
            proc.wait(timeout=timeout)
        except subprocess.TimeoutExpired:
            _wait_compile_drain("timeout")
            proc.terminate()  # SIGTERM + grace — never bare SIGKILL
            try:
                proc.wait(timeout=30)
            except subprocess.TimeoutExpired:
                _wait_compile_drain("SIGTERM grace expired")
                proc.kill()
                proc.wait()
    with open(log_path) as f:
        tail = f.readlines()[-40:]
    return proc.returncode, tail


def probe(timeout):
    rc, tail = run_bounded([sys.executable, "-c", PROBE], timeout,
                           os.path.join(ART, "probe.log"))
    alive = rc == 0 and any("ALIVE" in ln for ln in tail)
    if alive:
        log("PROBE:", next(ln.strip() for ln in tail if "ALIVE" in ln))
    return alive


def bank():
    """The liveness window is open: run the sequence, cheapest first.
    Each step is individually bounded; a hang in one still leaves the
    earlier artifacts on disk."""
    stamp = time.strftime("%Y%m%d_%H%M%S")  # year-qualified (ADVICE r3)
    results = {}

    bench_log = os.path.join(ART, f"bench_{stamp}.log")
    # bench.py's supervised() defaults its internal stage-ladder deadline
    # to 900 s — enough for warm-cache runs but not for stage B' + the
    # >900 s cold ResNet-50 compile in one cycle (the 03:43 r4 cycle shed
    # stage D with 434 s left).  The watcher owns the liveness window, so
    # grant the child a full cold-ladder budget and bound it outside.
    bench_env = dict(os.environ)
    bench_env.setdefault("TORCHMPI_TPU_BENCH_TIMEOUT", "2700")
    rc, _ = run_bounded(
        [sys.executable, "bench.py"],
        int(bench_env["TORCHMPI_TPU_BENCH_TIMEOUT"]) + 600, bench_log,
        env=bench_env)
    # Parse the WHOLE log for records, not run_bounded's 40-line tail:
    # the ladder's leading stages scroll out of a fixed tail as runs add
    # log lines (the 08:23 cycle-3 bank silently dropped its matmul
    # record at 49 log lines — code review r4).  This run's appended
    # segment starts at the last run_bounded banner, matched by its
    # exact format ("=== <timestamp> <cmd>") so a stray "=== " in bench
    # output can't re-truncate the records.
    recs = []
    with open(bench_log) as f:
        lines = f.readlines()
    starts = [i for i, ln in enumerate(lines)
              if ln.startswith("=== ") and "bench.py" in ln
              and "(timeout" in ln]
    for ln in lines[starts[-1]:] if starts else lines:
        try:
            rec = json.loads(ln.strip())
            if isinstance(rec, dict) and "metric" in rec:
                recs.append(rec)
        except ValueError:
            continue
    results["bench"] = {"rc": rc, "records": recs}
    with open(os.path.join(ART, f"bench_{stamp}.json"), "w") as f:
        json.dump(results["bench"], f, indent=1)
    log(f"bench rc={rc}, {len(recs)} records banked")
    # A banked-fallback re-emission (bench.py's wedge fallback) is not
    # evidence the hardware is alive — only LIVE tpu records count.
    got_hw = any(r.get("extra", {}).get("platform") == "tpu"
                 and not r.get("extra", {}).get("banked_fallback")
                 for r in recs)
    if not got_hw:
        log("no hardware-platform record in bench output; relay likely "
            "re-wedged — not queueing more device work")
        return False

    # The relay can wedge MID-cycle (2026-07-31 04:05: bench stages A-C2
    # live, then spontaneous wedge in stage B'): re-probe between stages
    # so a dead relay costs one 150 s probe instead of two 1200 s
    # timeouts queued against it.
    if not probe(150):
        log("relay died mid-cycle after bench; skipping autotune/trace")
        return True

    at_log = os.path.join(ART, f"autotune_{stamp}.log")
    rc, tail = run_bounded(
        # 2400 s, not 1200: under full-suite CPU contention the quick
        # sweep legitimately exceeds 20 min, and the 10:54 2026-07-31
        # SIGTERM of a contention-slowed autotune mid-device-work
        # immediately preceded a relay wedge — give it room to finish.
        [sys.executable, "benchmarks/autotune.py", "--quick"], 2400, at_log)
    rec_line = next((ln.strip() for ln in reversed(tail)
                     if '"recommend"' in ln), None)
    if rec_line:
        with open(os.path.join(ART, f"autotune_{stamp}.json"), "w") as f:
            f.write(rec_line + "\n")
    log(f"autotune rc={rc}, recommend={'yes' if rec_line else 'no'}")

    if not probe(150):
        log("relay died mid-cycle after autotune; skipping trace")
        return True

    tr_dir = os.path.join(ART, f"overlap_trace_{stamp}")
    rc, _ = run_bounded(
        [sys.executable, "benchmarks/overlap_trace.py", "--trace-dir",
         tr_dir], 1200, os.path.join(ART, f"overlap_{stamp}.log"))
    log(f"overlap_trace rc={rc}")

    if not probe(150):
        log("relay died mid-cycle after overlap trace; skipping profile")
        return True

    # ResNet-50 step profile (VERDICT r4 #3): the top-time-sink table
    # behind the headline's MFU — warm-cache compile, ~2 min live.
    rc, _ = run_bounded(
        [sys.executable, "scripts/resnet_profile.py"], 1800,
        os.path.join(ART, f"resnet_profile_{stamp}.log"))
    log(f"resnet_profile rc={rc}")

    if not probe(150):
        log("relay died mid-cycle after profile; skipping flash sweep")
        return True

    # Widened flash autotune sweep (VERDICT r4 #2): candidates beyond
    # the 512x512 plateau, floor-honest chained timing.
    rc, _ = run_bounded(
        [sys.executable, "scripts/flash_sweep.py", "--wide"], 2400,
        os.path.join(ART, f"flash_sweep_{stamp}.log"))
    log(f"flash_sweep rc={rc}")
    return True


def main():
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--interval", type=int, default=300)
    p.add_argument("--probe-timeout", type=int, default=150)
    p.add_argument("--once", action="store_true",
                   help="probe once; bank if alive; exit")
    p.add_argument("--max-hours", type=float, default=11.0)
    args = p.parse_args()
    os.makedirs(ART, exist_ok=True)
    deadline = time.time() + args.max_hours * 3600
    banked = False
    while time.time() < deadline:
        if probe(args.probe_timeout):
            banked = bank() or banked
            if args.once:
                return 0 if banked else 1
            if banked:
                # Success: drop to a slow re-probe so a later, healthier
                # window can still improve the numbers (e.g. stage D
                # after the compile cache warmed), without hammering.
                time.sleep(max(args.interval * 4, 1200))
                continue
        else:
            log("relay not alive")
        if args.once:
            return 0 if banked else 1
        time.sleep(args.interval)
    return 0 if banked else 1


if __name__ == "__main__":
    signal.signal(signal.SIGTERM, lambda *a: sys.exit(143))
    raise SystemExit(main())

"""Lint step functions, example entry points, and the host-side
protocol surfaces for SPMD hazards (docs/ANALYSIS.md).

Three passes:

- **Trace-time** (the targets below): collective-consistency rules
  plus the S1/S2 cache-slice rules, run over jaxprs.
- **Host-side** (``--host``): the H1-H5 AST/doc-drift rule pack
  (:mod:`torchmpi_tpu.analysis.hostcheck`) over the package tree —
  import discipline, telemetry/config/fault-site drift, lock-order
  cycles.  Pure AST: no jax import, so ``--host`` alone runs in
  milliseconds; ``--host`` combined with targets runs both passes.
- **Default sweep**: with no targets and no ``--host``, lints
  ``tests/fixtures_analysis_clean.py`` + ``tests/fixtures_lint_sweep.py``
  (the shipped decode/serving entry points) AND the host pass — the
  one-command whole-stack check CI runs.

Two target forms, auto-detected per file:

1. **Declared targets** — a Python file defining ``LINT_TARGETS``: a
   list of dicts ``{"fn": callable, "args": (arrays or
   jax.ShapeDtypeStructs, ...), "axis_env": [("axis", size), ...],
   "rules": None}``.  Each target is traced (never executed) and
   checked in-process.  This is how seeded-bad fixtures and library
   step functions are linted.

2. **Example entry points** — any other Python file (e.g.
   ``examples/mnist_allreduce.py``): run as a subprocess with the
   runtime analysis hook armed (``TORCHMPI_TPU_ANALYSIS=warn`` +
   ``TORCHMPI_TPU_ANALYSIS_OUT``); every program the example compiles
   through the library's step builders and eager collectives is checked
   once per jit-cache entry, and the findings JSON is collected when
   the process exits.  Pass example arguments after ``--args``.  The
   example's own exit code is reported but does not gate the lint
   verdict (tiny ``--steps`` smoke runs legitimately fail convergence
   asserts); use ``--strict-run`` to gate on it too.

Exit codes: 0 clean (or warnings only), 1 error-severity findings,
2 a target could not be loaded/analyzed at all.

Usage:
    python scripts/lint_collectives.py              # full default sweep
    python scripts/lint_collectives.py --host       # host pass only
    python scripts/lint_collectives.py tests/fixtures_analysis.py
    python scripts/lint_collectives.py examples/mnist_allreduce.py \\
        --args "--devices 8 --steps 2"
    python scripts/lint_collectives.py --json --bank ...
"""

import argparse
import ast
import importlib.util
import json
import os
import shlex
import subprocess
import sys
import tempfile

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _REPO)


#: Linted when the CLI is invoked with no targets: the clean near-miss
#: fixtures plus the shipped decode/serving entry points.
DEFAULT_SWEEP = (
    os.path.join(_REPO, "tests", "fixtures_analysis_clean.py"),
    os.path.join(_REPO, "tests", "fixtures_lint_sweep.py"),
)


def _load_module(path: str):
    name = os.path.splitext(os.path.basename(path))[0]
    spec = importlib.util.spec_from_file_location(f"_lint_{name}", path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def _load_hostcheck():
    """Load the host-side rule pack WITHOUT importing jax: hostcheck
    is pure AST and loads its own findings module standalone, so
    ``--host``-only invocations (CI's cheap gate) stay in the
    millisecond range instead of paying a full jax import."""
    path = os.path.join(_REPO, "torchmpi_tpu", "analysis",
                        "hostcheck.py")
    name = "_lint_hostcheck"
    if name in sys.modules:
        return sys.modules[name]
    spec = importlib.util.spec_from_file_location(name, path)
    mod = importlib.util.module_from_spec(spec)
    sys.modules[name] = mod  # registered before exec: dataclasses
    spec.loader.exec_module(mod)
    return mod


def _declares_lint_targets(path: str) -> bool:
    """True iff ``path`` has a top-level ``LINT_TARGETS = ...``
    assignment — checked via AST, not substring, so a file that merely
    *mentions* the convention in a docstring is never imported
    in-process (example imports force device counts / start training)."""
    try:
        with open(path) as f:
            tree = ast.parse(f.read())
    except (OSError, SyntaxError):
        return False
    for node in tree.body:
        targets = []
        if isinstance(node, ast.Assign):
            targets = node.targets
        elif isinstance(node, ast.AnnAssign):
            targets = [node.target]
        for t in targets:
            if isinstance(t, ast.Name) and t.id == "LINT_TARGETS":
                return True
    return False


def _declared_targets(path: str):
    """LINT_TARGETS from ``path`` if it declares them, else None."""
    if not _declares_lint_targets(path):
        return None
    mod = _load_module(path)
    return getattr(mod, "LINT_TARGETS", None)


def lint_declared(path: str, targets) -> list:
    from torchmpi_tpu import analysis

    findings = []
    for i, t in enumerate(targets):
        label = t.get("label") or f"{os.path.basename(path)}[{i}]"
        findings.extend(analysis.check(
            t["fn"], *t.get("args", ()), rules=t.get("rules"),
            axis_env=t.get("axis_env"), label=label))
    return findings


def lint_example(path: str, extra_args: str, timeout: float):
    """Run one example under the runtime analysis hook; returns
    ``(findings, run_rc)`` or raises RuntimeError when the example
    produced no report at all."""
    from torchmpi_tpu import analysis

    fd, out_path = tempfile.mkstemp(prefix="lint_findings_",
                                    suffix=".json")
    os.close(fd)
    os.unlink(out_path)
    env = dict(os.environ)
    env.pop("XLA_FLAGS", None)  # examples size their own device counts
    env["TORCHMPI_TPU_ANALYSIS"] = "warn"
    env[analysis.ANALYSIS_OUT_ENV] = out_path
    proc = subprocess.run(
        [sys.executable, os.path.abspath(path),
         *shlex.split(extra_args or "")],
        cwd=os.path.dirname(os.path.abspath(path)) or ".",
        capture_output=True, text=True, timeout=timeout, env=env)
    try:
        with open(out_path) as f:
            raw = json.load(f)
    except (OSError, ValueError):
        raise RuntimeError(
            f"{path}: no analysis report produced (rc={proc.returncode});"
            f"\nstderr tail: {proc.stderr[-800:]}")
    finally:
        try:
            os.unlink(out_path)
        except OSError:
            pass
    return [analysis.Finding.from_json(d) for d in raw], proc.returncode


def main(argv=None) -> int:
    p = argparse.ArgumentParser(
        description=__doc__.splitlines()[0],
        formatter_class=argparse.RawDescriptionHelpFormatter,
        epilog=__doc__)
    p.add_argument("targets", nargs="*",
                   help="python files: LINT_TARGETS declarations or "
                        "example entry points (none = the default "
                        "sweep + the host pass)")
    p.add_argument("--host", action="store_true",
                   help="run the host-side H1-H5 rule pack "
                        "(docs/ANALYSIS.md); alone = host pass only "
                        "(no jax import), with targets = both passes")
    p.add_argument("--bank", action="store_true",
                   help="append a LINT-SUMMARY record to "
                        "benchmarks/SUMMARY_BANK.json")
    p.add_argument("--args", default="",
                   help="arguments passed to example subprocesses "
                        "(e.g. \"--devices 8 --steps 2\")")
    p.add_argument("--json", action="store_true",
                   help="emit findings as JSON on stdout")
    p.add_argument("--rules", default=None,
                   help="comma-separated rule subset for declared "
                        "targets (e.g. D1,D2,C1)")
    p.add_argument("--timeout", type=float, default=600.0,
                   help="per-example subprocess timeout (seconds)")
    p.add_argument("--strict-run", action="store_true",
                   help="also fail when an example subprocess exits "
                        "nonzero")
    args = p.parse_args(argv)

    targets = list(args.targets)
    run_host = args.host
    if not targets and not args.host:
        targets = list(DEFAULT_SWEEP)
        run_host = True

    if targets:
        from torchmpi_tpu import analysis
    else:
        # --host alone: the pure-AST pack, no jax import.
        analysis = _load_hostcheck()

    rules = args.rules.split(",") if args.rules else None
    all_findings = []
    load_failures = 0
    run_failures = 0

    if run_host:
        hrules = ([r for r in rules if r.upper().startswith("H")]
                  if rules else None)
        if rules is None or hrules:
            try:
                found = analysis.run_hostcheck(rules=hrules)
            except Exception as e:  # noqa: BLE001 — report, keep going
                print(f"error: host pass failed: {e}", file=sys.stderr)
                load_failures += 1
                found = []
            all_findings.extend(found)
            if not args.json:
                tag = analysis.max_severity(found) or "clean"
                print(f"host pass (H1-H5): {len(found)} finding(s) "
                      f"[{tag}]")

    for path in targets:
        try:
            declared = _declared_targets(path)
        except Exception as e:  # noqa: BLE001 — report, keep linting
            print(f"error: cannot load {path}: {e}", file=sys.stderr)
            load_failures += 1
            continue
        try:
            if declared is not None:
                found = lint_declared(path, [
                    dict(t, rules=t.get("rules") or rules)
                    for t in declared])
                rc = 0
            else:
                found, rc = lint_example(path, args.args, args.timeout)
        except Exception as e:  # noqa: BLE001 — report, keep linting
            print(f"error: {path}: {e}", file=sys.stderr)
            load_failures += 1
            continue
        if rc != 0:
            run_failures += 1
            print(f"note: {path} subprocess exited {rc} "
                  f"(not gating; --strict-run gates)", file=sys.stderr)
        all_findings.extend(found)
        if not args.json:
            tag = analysis.max_severity(found) or "clean"
            print(f"{path}: {len(found)} finding(s) [{tag}]")

    all_findings = analysis.sort_findings(all_findings)
    if args.json:
        print(json.dumps([f.to_json() for f in all_findings], indent=1))
    else:
        for f in all_findings:
            print(f"  {f}")

    if args.bank:
        from benchmarks.banking import bank_summary

        by_rule = {}
        for f in all_findings:
            by_rule[f.rule] = by_rule.get(f.rule, 0) + 1
        bank_summary("LINT-SUMMARY", {
            "targets": [os.path.relpath(t, _REPO) for t in targets],
            "host_pass": bool(run_host),
            "findings": len(all_findings),
            "errors": sum(1 for f in all_findings
                          if f.severity == "error"),
            "warnings": sum(1 for f in all_findings
                            if f.severity == "warning"),
            "by_rule": dict(sorted(by_rule.items())),
            "load_failures": load_failures,
            "run_failures": run_failures,
        }, argv=sys.argv[1:])

    if load_failures:
        return 2
    if analysis.has_errors(all_findings):
        return 1
    if args.strict_run and run_failures:
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())

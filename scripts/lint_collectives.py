"""Lint step functions and example entry points for SPMD collective
hazards (docs/ANALYSIS.md).

Two target forms, auto-detected per file:

1. **Declared targets** — a Python file defining ``LINT_TARGETS``: a
   list of dicts ``{"fn": callable, "args": (arrays or
   jax.ShapeDtypeStructs, ...), "axis_env": [("axis", size), ...],
   "rules": None}``.  Each target is traced (never executed) and
   checked in-process.  This is how seeded-bad fixtures and library
   step functions are linted.

2. **Example entry points** — any other Python file (e.g.
   ``examples/mnist_allreduce.py``): run as a subprocess with the
   runtime analysis hook armed (``TORCHMPI_TPU_ANALYSIS=warn`` +
   ``TORCHMPI_TPU_ANALYSIS_OUT``); every program the example compiles
   through the library's step builders and eager collectives is checked
   once per jit-cache entry, and the findings JSON is collected when
   the process exits.  Pass example arguments after ``--args``.  The
   example's own exit code is reported but does not gate the lint
   verdict (tiny ``--steps`` smoke runs legitimately fail convergence
   asserts); use ``--strict-run`` to gate on it too.

Exit codes: 0 clean (or warnings only), 1 error-severity findings,
2 a target could not be loaded/analyzed at all.

Usage:
    python scripts/lint_collectives.py tests/fixtures_analysis.py
    python scripts/lint_collectives.py examples/mnist_allreduce.py \\
        --args "--devices 8 --steps 2"
    python scripts/lint_collectives.py --json ...
"""

import argparse
import ast
import importlib.util
import json
import os
import shlex
import subprocess
import sys
import tempfile

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _REPO)


def _load_module(path: str):
    name = os.path.splitext(os.path.basename(path))[0]
    spec = importlib.util.spec_from_file_location(f"_lint_{name}", path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def _declares_lint_targets(path: str) -> bool:
    """True iff ``path`` has a top-level ``LINT_TARGETS = ...``
    assignment — checked via AST, not substring, so a file that merely
    *mentions* the convention in a docstring is never imported
    in-process (example imports force device counts / start training)."""
    try:
        with open(path) as f:
            tree = ast.parse(f.read())
    except (OSError, SyntaxError):
        return False
    for node in tree.body:
        targets = []
        if isinstance(node, ast.Assign):
            targets = node.targets
        elif isinstance(node, ast.AnnAssign):
            targets = [node.target]
        for t in targets:
            if isinstance(t, ast.Name) and t.id == "LINT_TARGETS":
                return True
    return False


def _declared_targets(path: str):
    """LINT_TARGETS from ``path`` if it declares them, else None."""
    if not _declares_lint_targets(path):
        return None
    mod = _load_module(path)
    return getattr(mod, "LINT_TARGETS", None)


def lint_declared(path: str, targets) -> list:
    from torchmpi_tpu import analysis

    findings = []
    for i, t in enumerate(targets):
        label = t.get("label") or f"{os.path.basename(path)}[{i}]"
        findings.extend(analysis.check(
            t["fn"], *t.get("args", ()), rules=t.get("rules"),
            axis_env=t.get("axis_env"), label=label))
    return findings


def lint_example(path: str, extra_args: str, timeout: float):
    """Run one example under the runtime analysis hook; returns
    ``(findings, run_rc)`` or raises RuntimeError when the example
    produced no report at all."""
    from torchmpi_tpu import analysis

    fd, out_path = tempfile.mkstemp(prefix="lint_findings_",
                                    suffix=".json")
    os.close(fd)
    os.unlink(out_path)
    env = dict(os.environ)
    env.pop("XLA_FLAGS", None)  # examples size their own device counts
    env["TORCHMPI_TPU_ANALYSIS"] = "warn"
    env[analysis.ANALYSIS_OUT_ENV] = out_path
    proc = subprocess.run(
        [sys.executable, os.path.abspath(path),
         *shlex.split(extra_args or "")],
        cwd=os.path.dirname(os.path.abspath(path)) or ".",
        capture_output=True, text=True, timeout=timeout, env=env)
    try:
        with open(out_path) as f:
            raw = json.load(f)
    except (OSError, ValueError):
        raise RuntimeError(
            f"{path}: no analysis report produced (rc={proc.returncode});"
            f"\nstderr tail: {proc.stderr[-800:]}")
    finally:
        try:
            os.unlink(out_path)
        except OSError:
            pass
    return [analysis.Finding.from_json(d) for d in raw], proc.returncode


def main(argv=None) -> int:
    p = argparse.ArgumentParser(
        description=__doc__.splitlines()[0],
        formatter_class=argparse.RawDescriptionHelpFormatter,
        epilog=__doc__)
    p.add_argument("targets", nargs="+",
                   help="python files: LINT_TARGETS declarations or "
                        "example entry points")
    p.add_argument("--args", default="",
                   help="arguments passed to example subprocesses "
                        "(e.g. \"--devices 8 --steps 2\")")
    p.add_argument("--json", action="store_true",
                   help="emit findings as JSON on stdout")
    p.add_argument("--rules", default=None,
                   help="comma-separated rule subset for declared "
                        "targets (e.g. D1,D2,C1)")
    p.add_argument("--timeout", type=float, default=600.0,
                   help="per-example subprocess timeout (seconds)")
    p.add_argument("--strict-run", action="store_true",
                   help="also fail when an example subprocess exits "
                        "nonzero")
    args = p.parse_args(argv)

    from torchmpi_tpu import analysis

    rules = args.rules.split(",") if args.rules else None
    all_findings = []
    load_failures = 0
    run_failures = 0
    for path in args.targets:
        try:
            targets = _declared_targets(path)
        except Exception as e:  # noqa: BLE001 — report, keep linting
            print(f"error: cannot load {path}: {e}", file=sys.stderr)
            load_failures += 1
            continue
        try:
            if targets is not None:
                found = lint_declared(path, [
                    dict(t, rules=t.get("rules") or rules)
                    for t in targets])
                rc = 0
            else:
                found, rc = lint_example(path, args.args, args.timeout)
        except Exception as e:  # noqa: BLE001 — report, keep linting
            print(f"error: {path}: {e}", file=sys.stderr)
            load_failures += 1
            continue
        if rc != 0:
            run_failures += 1
            print(f"note: {path} subprocess exited {rc} "
                  f"(not gating; --strict-run gates)", file=sys.stderr)
        all_findings.extend(found)
        if not args.json:
            tag = analysis.max_severity(found) or "clean"
            print(f"{path}: {len(found)} finding(s) [{tag}]")

    all_findings = analysis.sort_findings(all_findings)
    if args.json:
        print(json.dumps([f.to_json() for f in all_findings], indent=1))
    else:
        for f in all_findings:
            print(f"  {f}")
    if load_failures:
        return 2
    if analysis.has_errors(all_findings):
        return 1
    if args.strict_run and run_failures:
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())

#!/usr/bin/env python
"""Single-chip headline-number tuning experiments (live relay required).

Three quick studies, each printing one line per config:
  1. ResNet-50 DP train step vs per-chip batch (is 64 leaving MXU idle?)
  2. bf16 matmul TFLOP/s vs N (is the 4096 probe under-reporting peak?)
  3. transformer-LM step local (dense) vs flash attention at stage-B shapes

Informs bench.py defaults; run standalone between watcher bank cycles.
"""

import argparse
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp
import numpy as np

from torchmpi_tpu.utils.metrics import fence, timed


def study_matmul():
    for n in (4096, 8192, 16384):
        a = jnp.ones((n, n), jnp.bfloat16)
        b = jnp.ones((n, n), jnp.bfloat16)

        @jax.jit
        def chain(a, b, n=n):
            mm = a
            for _ in range(4):
                mm = (mm @ b) * (1.0 / n)  # stay finite, keep dependency
            return mm

        dt = timed(lambda: chain(a, b), 10) / 4  # per-matmul
        print(f"matmul N={n}: {dt*1e6:.0f} us/matmul, "
              f"{2*n**3/dt/1e12:.1f} TFLOP/s", flush=True)


def study_resnet(batches):
    import optax

    import torchmpi_tpu as mpi
    from torchmpi_tpu.models import ResNet50

    mesh = mpi.init()
    model = ResNet50(dtype=jnp.bfloat16)
    init_dev = jax.local_devices(backend="cpu")[0]
    with jax.default_device(init_dev):
        variables = model.init(jax.random.PRNGKey(0),
                               jnp.zeros((1, 224, 224, 3)), train=False)
    params, batch_stats = variables["params"], variables["batch_stats"]
    tx = optax.sgd(0.1, momentum=0.9)
    opt_state = tx.init(params)
    dp_step = mpi.recipes.make_bn_dp_train_step(model, tx, mesh=mesh)
    p, o, bs = mpi.recipes.replicate_bn_state(params, opt_state,
                                              batch_stats, mesh=mesh)
    for batch in batches:
        images = jnp.asarray(
            np.random.RandomState(0).rand(batch, 224, 224, 3), jnp.float32)
        labels = jnp.asarray(
            np.random.RandomState(1).randint(0, 1000, size=(batch,)))
        t0 = time.time()
        state = [p, o, bs]

        def step(state=state, images=images, labels=labels):
            state[0], state[1], state[2], loss = dp_step(
                state[0], state[1], state[2], images, labels)
            return loss

        loss = step()
        fence(loss)
        compile_s = time.time() - t0
        dt = timed(step, 10)
        print(f"resnet50 b={batch}: {dt*1e3:.1f} ms/step, "
              f"{batch/dt:.0f} img/s, mfu "
              f"{3*8.2e9*batch/dt/1e12/197:.3f} "
              f"(compile {compile_s:.0f}s)", flush=True)


def study_transformer():
    import optax

    import torchmpi_tpu as mpi
    from torchmpi_tpu.models import TransformerLM

    mesh = mpi.init()
    for impl, T, B in (("local", 512, 8), ("flash", 512, 8),
                       ("local", 2048, 2), ("flash", 2048, 2)):
        lm = TransformerLM(vocab=8192, embed=512, depth=4, num_heads=8,
                           head_dim=64, max_len=T, dtype=jnp.bfloat16,
                           attn_impl=impl)
        tok = jnp.asarray(np.random.RandomState(2).randint(
            0, 8192, size=(B, T)), jnp.int32)
        # init on-device: the flash variant's pallas_call cannot trace on
        # the CPU backend outside interpret mode, and this model is small.
        v = lm.init(jax.random.PRNGKey(1), tok[:1])
        tx = optax.sgd(0.1)
        o = tx.init(v)

        def lm_step(v, o, tok, lm=lm, tx=tx):
            def loss_fn(v):
                logits = lm.apply(v, tok).astype(jnp.float32)
                return optax.softmax_cross_entropy_with_integer_labels(
                    logits[:, :-1], tok[:, 1:]).mean()

            loss, g = jax.value_and_grad(loss_fn)(v)
            u, o2 = tx.update(g, o, v)
            return optax.apply_updates(v, u), o2, loss

        jit_step = jax.jit(lm_step)
        state = {"v": v, "o": o}

        def step(state=state, jit_step=jit_step, tok=tok):
            state["v"], state["o"], loss = jit_step(state["v"], state["o"],
                                                    tok)
            return loss

        dt = timed(step, 10)
        print(f"lm {impl} T={T} B={B}: {dt*1e3:.2f} ms/step, "
              f"{B*T/dt:.0f} tokens/s", flush=True)


if __name__ == "__main__":
    # Operator-run device client: declare an unbounded, non-abandonable
    # compile budget up front (its study steps exceed the compile gate's
    # large-graph threshold on the relay).  The round-3 rule this
    # encodes: run hw_tune WITHOUT an external timeout that could
    # SIGKILL mid-compile — the gate defers SIGTERM and heartbeats so
    # cooperating supervisors extend their grace.
    import torchmpi_tpu as mpi

    _budget = mpi.compile_budget()
    _budget.__enter__()
    ap = argparse.ArgumentParser()
    ap.add_argument("--study", choices=["matmul", "resnet", "lm", "all"],
                    default="all")
    ap.add_argument("--batches", type=int, nargs="*",
                    default=[64, 128, 256])
    args = ap.parse_args()
    if args.study in ("matmul", "all"):
        study_matmul()
    if args.study in ("lm", "all"):
        study_transformer()
    if args.study in ("resnet", "all"):
        study_resnet(args.batches)

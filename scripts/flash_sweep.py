#!/usr/bin/env python
"""On-chip flash-attention block sweep vs XLA dense attention.

Run on a live relay window (single chip).  Prints per-config ms,
causal-credited TFLOP/s, and max|err| vs the library's dense oracle
(parallel.sequence.reference_attention — the same oracle the test suite
validates the kernel against).
"""

import argparse
import functools
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp
import numpy as np

from torchmpi_tpu.ops.flash import flash_attention
from torchmpi_tpu.parallel.sequence import reference_attention
from torchmpi_tpu.utils.metrics import timed

B, T, H, D = 4, 4096, 8, 128
CONFIGS = [(256, 256), (512, 256), (256, 512), (512, 512),
           (512, 1024), (1024, 512)]
# --wide (VERDICT r4 #2): candidates beyond the 512x512 plateau — the
# full-block mask-skip specialization shifts the VPU:MXU balance, so the
# old optimum must be re-derived, and larger blocks amortize per-block
# bookkeeping further (VMEM at 1024x1024: q+acc+2x(k,v) ~ 1.6 MiB, well
# inside scope).
WIDE_EXTRA = [(1024, 1024), (2048, 512), (512, 2048), (1024, 256),
              (768, 512), (512, 768), (2048, 1024)]
# Dependent-chain depth per dispatch: amortizes the relay's ~7 ms
# per-dispatch floor out of the per-kernel number (VERDICT r3 #4 — the
# floor otherwise sits in BOTH sides of every flash-vs-dense ratio).
CHAIN = 4


def bench(f, *a, iters=10):
    return timed(lambda: f(*a), iters)


def chained(attn_fn):
    """Dependent-chain jit (q <- out) via the shared harness helper:
    the dispatch floor is paid once and CSE cannot collapse the links."""
    from torchmpi_tpu.utils.metrics import chained as _chained

    return _chained(attn_fn, depth=CHAIN)


def sweep_shape(label, q, k, v, configs, *, window=None):
    """One (shape, window) sweep: dense oracle once, then each block
    config with chained floor-honest timing + on-device oracle check."""
    Bs, Ts, Hs, Ds = q.shape
    dj = jax.jit(functools.partial(reference_attention, causal=True,
                                   window=window))
    od = dj(q, k, v)
    t = bench(chained(functools.partial(reference_attention, causal=True,
                                        window=window)), q, k, v) / CHAIN
    print(f"[{label}] dense: {t*1e3:.2f} ms/invocation (chained x{CHAIN})",
          flush=True)

    if window is None:
        flops = 2 * Bs * Hs * Ts * Ts * Ds * 2 * 0.5  # causal-credited
    else:
        avg_ctx = ((window / 2) * window + (Ts - window) * window) / Ts \
            if Ts > window else Ts / 2
        flops = 2 * Bs * Hs * Ts * avg_ctx * Ds * 2
    best = None
    for bq, bk in configs:
        f1 = functools.partial(flash_attention, causal=True, window=window,
                               block_q=bq, block_k=bk, interpret=False)
        fj = jax.jit(f1)
        try:
            of = fj(q, k, v)
            err = float(jnp.max(jnp.abs(of.astype(jnp.float32)
                                        - od.astype(jnp.float32))))
            t = bench(chained(f1), q, k, v) / CHAIN
            tfl = flops / t / 1e12
            print(f"[{label}] flash {bq}x{bk}: {t*1e3:.2f} ms/invocation "
                  f"(chained x{CHAIN})  {tfl:.1f} TFLOP/s  "
                  f"err {err:.4f}", flush=True)
            if best is None or tfl > best[2]:
                best = (bq, bk, tfl)
        except Exception as e:  # noqa: BLE001 — sweep continues
            print(f"[{label}] flash {bq}x{bk}: FAIL {type(e).__name__}: "
                  f"{str(e)[:120]}", flush=True)
    if best:
        print(f"[{label}] BEST {best[0]}x{best[1]} {best[2]:.1f} TFLOP/s",
              flush=True)
    return best, flops


def main():
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--wide", action="store_true",
                   help="extended candidate blocks + the stage-B' "
                        "GQA/window shape")
    args = p.parse_args()

    # Operator-run device client (see hw_tune.py): unbounded budget so
    # the gate blesses the chained kernel jits on the relay.
    import torchmpi_tpu as mpi

    _budget = mpi.compile_budget()
    _budget.__enter__()
    # Explicit prescale=False baseline: an exported
    # TORCHMPI_TPU_FLASH_PRESCALE=1 must not make the "direct" side of
    # the A/B run prescaled too (code review r5).
    mpi.init(mpi.Config.from_env(flash_prescale=False))

    def prescale_ab(label, q, k, v, best, flops, window=None):
        """Re-time the winning block config with Config.flash_prescale
        on (the scale folded into q; kernel runs scale=1) — the A/B
        that decides whether to adopt the knob as default."""
        if not best:
            return
        bq, bk, base_tfl = best
        mpi.set_config(flash_prescale=True)
        try:
            f1 = functools.partial(flash_attention, causal=True,
                                   window=window, block_q=bq, block_k=bk,
                                   interpret=False)
            t = bench(chained(f1), q, k, v) / CHAIN
            tfl = flops / t / 1e12
            print(f"[{label}] prescale@{bq}x{bk}: {t*1e3:.2f} ms "
                  f"{tfl:.1f} TFLOP/s (vs {base_tfl:.1f} direct)",
                  flush=True)
        finally:
            mpi.set_config(flash_prescale=False)

    configs = CONFIGS + (WIDE_EXTRA if args.wide else [])
    rs = np.random.RandomState(0)
    q = jnp.asarray(rs.randn(B, T, H, D), jnp.bfloat16)
    k = jnp.asarray(rs.randn(B, T, H, D), jnp.bfloat16)
    v = jnp.asarray(rs.randn(B, T, H, D), jnp.bfloat16)
    best, flops = sweep_shape(f"mha B{B} T{T} H{H}", q, k, v, configs)
    if args.wide:
        # --wide adds the prescale A/B runs and the flagship shape on
        # top of the extended block candidates.
        prescale_ab(f"mha B{B} T{T} H{H}", q, k, v, best, flops)

        # The flagship stage-B' attention shape: GQA 16q/4kv, T=2048,
        # sliding window 1024 — the config whose cost sits inside the
        # headline MFU (VERDICT r4 #2 done-criterion: B' MFU >= 0.62).
        B2, T2, H2, HKV2, W2 = 4, 2048, 16, 4, 1024
        q2 = jnp.asarray(rs.randn(B2, T2, H2, D), jnp.bfloat16)
        k2 = jnp.asarray(rs.randn(B2, T2, HKV2, D), jnp.bfloat16)
        v2 = jnp.asarray(rs.randn(B2, T2, HKV2, D), jnp.bfloat16)
        best2, flops2 = sweep_shape(f"gqa B{B2} T{T2} H{H2}/{HKV2} w{W2}",
                                    q2, k2, v2, configs, window=W2)
        prescale_ab(f"gqa B{B2} T{T2} H{H2}/{HKV2} w{W2}", q2, k2, v2,
                    best2, flops2, window=W2)


if __name__ == "__main__":
    main()

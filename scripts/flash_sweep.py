#!/usr/bin/env python
"""On-chip flash-attention block sweep vs XLA dense attention.

Run on a live relay window (single chip).  Prints per-config ms,
causal-credited TFLOP/s, and max|err| vs the library's dense oracle
(parallel.sequence.reference_attention — the same oracle the test suite
validates the kernel against).
"""

import functools
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp
import numpy as np

from torchmpi_tpu.ops.flash import flash_attention
from torchmpi_tpu.parallel.sequence import reference_attention
from torchmpi_tpu.utils.metrics import timed

B, T, H, D = 4, 4096, 8, 128
CONFIGS = [(256, 256), (512, 256), (256, 512), (512, 512),
           (512, 1024), (1024, 512)]
# Dependent-chain depth per dispatch: amortizes the relay's ~7 ms
# per-dispatch floor out of the per-kernel number (VERDICT r3 #4 — the
# floor otherwise sits in BOTH sides of every flash-vs-dense ratio).
CHAIN = 4


def bench(f, *a, iters=10):
    return timed(lambda: f(*a), iters)


def chained(attn_fn):
    """Dependent-chain jit (q <- out) via the shared harness helper:
    the dispatch floor is paid once and CSE cannot collapse the links."""
    from torchmpi_tpu.utils.metrics import chained as _chained

    return _chained(attn_fn, depth=CHAIN)


def main():
    # Operator-run device client (see hw_tune.py): unbounded budget so
    # the gate blesses the chained kernel jits on the relay.
    import torchmpi_tpu as mpi

    _budget = mpi.compile_budget()
    _budget.__enter__()
    rs = np.random.RandomState(0)
    q = jnp.asarray(rs.randn(B, T, H, D), jnp.bfloat16)
    k = jnp.asarray(rs.randn(B, T, H, D), jnp.bfloat16)
    v = jnp.asarray(rs.randn(B, T, H, D), jnp.bfloat16)

    dj = jax.jit(functools.partial(reference_attention, causal=True))
    od = dj(q, k, v)
    t = bench(chained(functools.partial(reference_attention,
                                        causal=True)), q, k, v) / CHAIN
    print(f"dense (reference_attention): {t*1e3:.2f} ms/invocation "
          f"(chained x{CHAIN})")

    flops = 2 * B * H * T * T * D * 2 * 0.5  # causal-credited
    for bq, bk in CONFIGS:
        f1 = functools.partial(flash_attention, causal=True,
                               block_q=bq, block_k=bk, interpret=False)
        fj = jax.jit(f1)
        try:
            of = fj(q, k, v)
            err = float(jnp.max(jnp.abs(of.astype(jnp.float32)
                                        - od.astype(jnp.float32))))
            t = bench(chained(f1), q, k, v) / CHAIN
            print(f"flash {bq}x{bk}: {t*1e3:.2f} ms/invocation "
                  f"(chained x{CHAIN})  {flops/t/1e12:.1f} TFLOP/s  "
                  f"err {err:.4f}")
        except Exception as e:  # noqa: BLE001 — sweep continues
            print(f"flash {bq}x{bk}: FAIL {type(e).__name__}: "
                  f"{str(e)[:120]}")


if __name__ == "__main__":
    main()

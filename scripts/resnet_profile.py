#!/usr/bin/env python
"""ResNet-50 train-step profile: capture + top-time-sink table.

VERDICT r4 #3: the headline trains at MFU 0.317 with no committed
breakdown of where the other 68% goes.  This script runs the exact
stage-D train step (same recipes/batch/image as bench.py), captures a
``jax.profiler`` trace of warm steps, and reduces the busiest device
lane to a category/op time table — the evidence a layout/fusion/input
fix must be justified against, or the ceiling statement if the
remainder is conv-inherent.

Run on a LIVE window (the watcher invokes it after the cheaper bank
steps): ``python scripts/resnet_profile.py``.  On a non-TPU platform it
shrinks to smoke shapes so the capture+parse pipeline stays testable.
Artifacts: ``docs/artifacts/resnet_profile_<stamp>.{json,md}``.
"""

import argparse
import collections
import glob
import gzip
import json
import os
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)
ART = os.path.join(REPO, "docs", "artifacts")


def log(*a):
    print(time.strftime("[%H:%M:%S]"), *a, file=sys.stderr, flush=True)


def categorize(name: str) -> str:
    n = name.lower()
    if n.startswith(("convolution", "conv")) or ".conv" in n:
        return "convolution"
    if "all-reduce" in n or "allreduce" in n:
        return "all-reduce"
    if n.startswith("fusion"):
        return "fusion (elementwise/BN/loss)"
    if n.startswith(("copy", "transpose", "convert", "bitcast", "reshape")):
        return "data movement"
    if n.startswith(("dot", "cublas", "gemm")):
        return "matmul"
    if n.startswith(("reduce", "scatter", "gather", "select", "dynamic")):
        return "reduce/scatter/gather"
    return "other"


def analyze(trace_glob: str) -> dict:
    """Reduce the busiest device lane of the newest trace to category +
    per-op totals (same perfetto-JSON surface benchmarks/
    overlap_analyze.py parses)."""
    paths = sorted(glob.glob(trace_glob, recursive=True),
                   key=os.path.getmtime)
    if not paths:
        return {"error": f"no trace under {trace_glob}"}
    path = paths[-1]
    with gzip.open(path, "rt") as f:
        data = json.load(f)
    ev = [e for e in data.get("traceEvents", [])
          if e.get("ph") == "X" and e.get("dur") is not None
          and not e.get("name", "").startswith("end:")]
    lanes = collections.defaultdict(list)
    for e in ev:
        lanes[(e.get("pid"), e.get("tid"))].append(e)
    if not lanes:
        return {"error": "no complete events in trace", "trace": path}

    # Prefer the lane that looks like the XLA device-op stream (most
    # time in recognizable op categories); the merely-busiest lane can
    # be the Python host thread (PjitFunction/fence frames), which says
    # nothing about where device time goes.
    def xla_score(l):
        return sum(e["dur"] for e in l
                   if categorize(e["name"]) != "other")

    lane = max(lanes.values(), key=xla_score)
    if xla_score(lane) == 0:
        lane = max(lanes.values(),
                   key=lambda l: sum(e["dur"] for e in l))
    total_us = sum(e["dur"] for e in lane)
    by_op = collections.Counter()
    by_cat = collections.Counter()
    for e in lane:
        by_op[e["name"]] += e["dur"]
        by_cat[categorize(e["name"])] += e["dur"]
    top_ops = [{"op": n[:120], "ms": round(us / 1e3, 3),
                "pct": round(100.0 * us / total_us, 2)}
               for n, us in by_op.most_common(10)]
    cats = [{"category": c, "ms": round(us / 1e3, 3),
             "pct": round(100.0 * us / total_us, 2)}
            for c, us in by_cat.most_common()]
    return {"trace": path, "lane_busy_ms": round(total_us / 1e3, 3),
            "lane_events": len(lane), "categories": cats,
            "top_ops": top_ops}


def main():
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--steps", type=int, default=8)
    p.add_argument("--force-full", action="store_true",
                   help="full stage-D shapes even off-TPU")
    args = p.parse_args()

    # The container's sitecustomize imports jax at startup and pins the
    # axon platform; JAX_PLATFORMS set later is ignored (ROUND4_NOTES).
    # The same smoke knob bench.py honors forces a simulated CPU mesh.
    cpu_n = int(os.environ.get("TORCHMPI_TPU_BENCH_CPU", "0"))
    if cpu_n:
        from torchmpi_tpu.utils.simulation import force_cpu_devices

        force_cpu_devices(cpu_n)

    import jax
    import jax.numpy as jnp
    import numpy as np
    import optax

    import torchmpi_tpu as mpi
    from torchmpi_tpu.models import ResNet50
    from torchmpi_tpu.utils import compilecache, tracing
    from torchmpi_tpu.utils.metrics import fence

    compilecache.enable_persistent_cache()
    mesh = mpi.init()
    n_dev = mpi.device_count()
    platform = jax.devices()[0].platform
    full = platform == "tpu" or args.force_full
    BATCH, IMAGE = (128, 224) if full else (4, 64)
    batch = BATCH * n_dev
    log(f"platform={platform} devices={n_dev} batch/chip={BATCH} "
        f"image={IMAGE}")

    from jax.sharding import NamedSharding, PartitionSpec as P

    shard = NamedSharding(mesh, P(mesh.axis_names))
    init_dev = None
    if platform != "cpu":
        try:
            init_dev = jax.local_devices(backend="cpu")[0]
        except RuntimeError:
            pass

    model = ResNet50(dtype=jnp.bfloat16)
    with jax.default_device(init_dev):
        variables = model.init(jax.random.PRNGKey(0),
                               jnp.zeros((1, IMAGE, IMAGE, 3)),
                               train=False)
    params, batch_stats = variables["params"], variables["batch_stats"]
    tx = optax.sgd(0.1, momentum=0.9)
    opt_state = tx.init(params)
    dp_step = mpi.recipes.make_bn_dp_train_step(model, tx, mesh=mesh)
    params, opt_state, batch_stats = mpi.recipes.replicate_bn_state(
        params, opt_state, batch_stats, mesh=mesh)
    images = jax.device_put(
        np.random.RandomState(0).rand(batch, IMAGE, IMAGE, 3)
        .astype(np.float32), shard)
    labels = jax.device_put(
        np.random.RandomState(1).randint(0, 1000, size=batch)
        .astype(np.int32), shard)

    log("warmup/compile...")
    with mpi.compile_budget():
        for _ in range(2):
            params, opt_state, batch_stats, loss = dp_step(
                params, opt_state, batch_stats, images, labels)
        fence(loss)

    stamp = time.strftime("%Y%m%d_%H%M%S")
    trace_dir = os.path.join("/tmp", f"resnet_trace_{stamp}")
    log(f"tracing {args.steps} warm steps -> {trace_dir}")
    t0 = time.time()
    with tracing.trace(trace_dir):
        for _ in range(args.steps):
            params, opt_state, batch_stats, loss = dp_step(
                params, opt_state, batch_stats, images, labels)
        fence(loss)
    wall = time.time() - t0

    rec = analyze(os.path.join(trace_dir, "**", "*.trace.json.gz"))
    rec.update({"platform": platform, "devices": n_dev,
                "batch_per_chip": BATCH, "image": IMAGE,
                "steps": args.steps,
                "wall_s": round(wall, 3),
                "img_s_chip": round(batch * args.steps / wall / n_dev, 1),
                "stamp": stamp})
    # Committed artifacts are hardware evidence; CPU smoke output stays
    # in /tmp so a pipeline test can't masquerade as a profile.
    out_dir = ART if full else "/tmp"
    os.makedirs(out_dir, exist_ok=True)
    out_json = os.path.join(out_dir, f"resnet_profile_{stamp}.json")
    with open(out_json, "w") as f:
        json.dump(rec, f, indent=1)
    # Markdown table for the committed evidence.
    out_md = os.path.join(out_dir, f"resnet_profile_{stamp}.md")
    with open(out_md, "w") as f:
        f.write(f"# ResNet-50 train-step profile ({stamp})\n\n"
                f"platform={platform} devices={n_dev} "
                f"batch/chip={BATCH} image={IMAGE} steps={args.steps} "
                f"throughput={rec['img_s_chip']} img/s/chip\n\n")
        if "categories" in rec:
            f.write("| category | ms | % of lane |\n|---|---|---|\n")
            for c in rec["categories"]:
                f.write(f"| {c['category']} | {c['ms']} | {c['pct']} |\n")
            f.write("\n| top op | ms | % |\n|---|---|---|\n")
            for o in rec["top_ops"][:5]:
                f.write(f"| `{o['op'][:80]}` | {o['ms']} | {o['pct']} |\n")
    print(json.dumps(rec))
    log(f"wrote {out_json} and {out_md}")


if __name__ == "__main__":
    main()

// Native async host-IO executor: thread pool + futures + atomic file writes.
//
// Reference analog: the async engine's C++ thread pool (SURVEY.md §3 C7,
// `lib/collectives*` pool [MED] — reconstructed, reference mount empty).
// The reference ran collectives and PS traffic on host threads because the
// device runtime gave it nothing; on TPU the device side is already async
// under XLA dispatch, so the native pool's remaining job is host IO that
// must not stall the train loop — checkpoint writes first of all
// (SURVEY.md §6.4: the rebuild owns the checkpoint-restart story).
//
// Durability contract per write: data goes to `<path>.tmp.<id>`, is
// optionally fsync'd, then rename(2)'d over the final path, and the parent
// directory is fsync'd — so the final path either holds the complete
// payload or does not exist; a crash can never expose a torn checkpoint.
//
// Trust model: in-process library, no network surface.  Callers pass raw
// pointers; a submitted buffer must stay alive until its future completes
// (the Python wrapper pins it on the handle).
//
// C ABI (for ctypes, matching csrc/ps.cpp conventions):
//   tm_io_executor_create(nthreads)          -> eid  (<0 on failure)
//   tm_io_submit_write(eid, path, data, n, durable) -> fid (<0 on failure)
//   tm_io_wait_for(fid, timeout_ms)          -> 1 done, 0 timeout, -1 no such
//   tm_io_status(fid)   (done futures only)  -> 0 ok, else errno of the op
//   tm_io_free(fid)
//   tm_io_bytes_written(eid)                 -> completed payload bytes
//   tm_io_executor_destroy(eid)              // drains queue, joins threads

#include <fcntl.h>
#include <libgen.h>
#include <unistd.h>

#include <cerrno>

#include <atomic>
#include <chrono>
#include <condition_variable>
#include <cstdint>
#include <cstring>
#include <deque>
#include <functional>
#include <map>
#include <memory>
#include <mutex>
#include <string>
#include <thread>
#include <vector>

namespace {

struct IoFuture {
  std::mutex mu;
  std::condition_variable cv;
  bool done = false;
  int err = 0;  // errno of the failed step; 0 = success
};

struct Executor {
  // Static destruction of the registry (process exit without an explicit
  // destroy — CPython does not guarantee __del__ runs) must not run
  // ~std::thread on a joinable worker: that is std::terminate.  The
  // destructor drains and joins, same as an explicit destroy.
  ~Executor() { stop(); }

  std::vector<std::thread> threads;
  std::deque<std::function<void()>> queue;
  std::mutex mu;
  std::condition_variable cv;
  bool stopping = false;
  std::atomic<uint64_t> bytes_written{0};
  std::atomic<uint64_t> tmp_seq{0};

  void start(int nthreads) {
    for (int i = 0; i < nthreads; ++i)
      threads.emplace_back([this] { run(); });
  }

  void run() {
    for (;;) {
      std::function<void()> job;
      {
        std::unique_lock<std::mutex> lk(mu);
        cv.wait(lk, [this] { return stopping || !queue.empty(); });
        // Drain before exit: a stop request must not drop queued writes —
        // a checkpoint the caller was told is in flight has to land.
        if (queue.empty()) return;
        job = std::move(queue.front());
        queue.pop_front();
      }
      job();
    }
  }

  void enqueue(std::function<void()> job) {
    {
      std::lock_guard<std::mutex> g(mu);
      queue.push_back(std::move(job));
    }
    cv.notify_one();
  }

  void stop() {
    {
      std::lock_guard<std::mutex> g(mu);
      stopping = true;
    }
    cv.notify_all();
    for (auto& t : threads)
      if (t.joinable()) t.join();
    threads.clear();
  }
};

// Returns 0 on success, else the errno of the first failing step.
int write_atomic(Executor* ex, const std::string& path, const uint8_t* data,
                 uint64_t nbytes, bool durable) {
  std::string tmp = path + ".tmp." + std::to_string(::getpid()) + "." +
                    std::to_string(ex->tmp_seq.fetch_add(1));
  int fd = ::open(tmp.c_str(), O_WRONLY | O_CREAT | O_TRUNC | O_CLOEXEC,
                  0644);
  if (fd < 0) return errno ? errno : EIO;
  uint64_t off = 0;
  while (off < nbytes) {
    ssize_t n = ::write(fd, data + off, nbytes - off);
    if (n < 0) {
      if (errno == EINTR) continue;
      int e = errno;
      ::close(fd);
      ::unlink(tmp.c_str());
      return e;
    }
    off += static_cast<uint64_t>(n);
  }
  if (durable && ::fsync(fd) != 0) {
    int e = errno;
    ::close(fd);
    ::unlink(tmp.c_str());
    return e;
  }
  if (::close(fd) != 0) {
    int e = errno;
    ::unlink(tmp.c_str());
    return e;
  }
  if (::rename(tmp.c_str(), path.c_str()) != 0) {
    int e = errno;
    ::unlink(tmp.c_str());
    return e;
  }
  if (durable) {
    // fsync the parent directory so the rename itself survives a crash.
    std::vector<char> buf(path.begin(), path.end());
    buf.push_back('\0');
    int dfd = ::open(::dirname(buf.data()), O_RDONLY | O_DIRECTORY);
    if (dfd >= 0) {
      ::fsync(dfd);  // best-effort: some filesystems reject dir fsync
      ::close(dfd);
    }
  }
  ex->bytes_written.fetch_add(nbytes);
  return 0;
}

std::mutex g_mu;
std::map<int64_t, std::shared_ptr<Executor>> g_executors;
std::map<int64_t, std::shared_ptr<IoFuture>> g_futures;
int64_t g_next_id = 1;

}  // namespace

extern "C" {

int64_t tm_io_executor_create(int nthreads) {
  if (nthreads < 1 || nthreads > 64) return -1;
  auto ex = std::make_shared<Executor>();
  ex->start(nthreads);
  std::lock_guard<std::mutex> g(g_mu);
  int64_t id = g_next_id++;
  g_executors[id] = std::move(ex);
  return id;
}

// Does NOT copy `data`: the buffer must stay alive until the future
// completes (one memcpy of a multi-GB checkpoint is exactly what this
// module exists to avoid).
int64_t tm_io_submit_write(int64_t eid, const char* path,
                           const uint8_t* data, uint64_t nbytes,
                           int durable) {
  std::shared_ptr<Executor> ex;
  {
    std::lock_guard<std::mutex> g(g_mu);
    auto it = g_executors.find(eid);
    if (it == g_executors.end()) return -1;
    ex = it->second;
  }
  auto fut = std::make_shared<IoFuture>();
  int64_t fid;
  {
    std::lock_guard<std::mutex> g(g_mu);
    fid = g_next_id++;
    g_futures[fid] = fut;
  }
  std::string p(path);
  ex->enqueue([ex, fut, p, data, nbytes, durable] {
    int err = write_atomic(ex.get(), p, data, nbytes, durable != 0);
    std::lock_guard<std::mutex> g(fut->mu);
    fut->err = err;
    fut->done = true;
    fut->cv.notify_all();
  });
  return fid;
}

int tm_io_wait_for(int64_t fid, int timeout_ms) {
  std::shared_ptr<IoFuture> f;
  {
    std::lock_guard<std::mutex> g(g_mu);
    auto it = g_futures.find(fid);
    if (it == g_futures.end()) return -1;
    f = it->second;
  }
  std::unique_lock<std::mutex> lk(f->mu);
  if (timeout_ms < 0) {
    f->cv.wait(lk, [&] { return f->done; });
    return 1;
  }
  return f->cv.wait_for(lk, std::chrono::milliseconds(timeout_ms),
                        [&] { return f->done; })
             ? 1
             : 0;
}

int tm_io_status(int64_t fid) {
  std::lock_guard<std::mutex> g(g_mu);
  auto it = g_futures.find(fid);
  if (it == g_futures.end()) return -1;
  std::lock_guard<std::mutex> fg(it->second->mu);
  return it->second->done ? it->second->err : -2;
}

void tm_io_free(int64_t fid) {
  std::lock_guard<std::mutex> g(g_mu);
  g_futures.erase(fid);
}

uint64_t tm_io_bytes_written(int64_t eid) {
  std::lock_guard<std::mutex> g(g_mu);
  auto it = g_executors.find(eid);
  return it == g_executors.end() ? 0 : it->second->bytes_written.load();
}

void tm_io_executor_destroy(int64_t eid) {
  std::shared_ptr<Executor> ex;
  {
    std::lock_guard<std::mutex> g(g_mu);
    auto it = g_executors.find(eid);
    if (it == g_executors.end()) return;
    ex = std::move(it->second);
    g_executors.erase(it);
  }
  ex->stop();
}

}  // extern "C"

// torchmpi_tpu parameter-server host transport.
//
// TPU-native rebuild of the reference's C7 async engine + C8 parameter-server
// shards (lib/parameterserver.cpp/.h [MED], SURVEY.md §3 — reconstructed,
// reference mount empty).  The reference ran server threads over
// MPI_THREAD_MULTIPLE point-to-point; on a TPU pod the asynchronous traffic
// is host-side over DCN, so the transport is TCP sockets driven by native
// threads, entirely outside the SPMD/XLA world (async PS is fundamentally
// incompatible with gang-scheduled collectives — SURVEY.md §8.2.5).
//
// Exposed as a C ABI for ctypes (no pybind11 in this environment).
//
// Server: owns a float32 shard; a listener thread accepts connections and
// spawns one handler thread per client (clients = ranks, i.e. few).  Ops
// apply under a shard mutex.
//
// Client: one socket per connection; async send/receive run on a small
// thread pool with per-connection serialization; futures are integer ids
// (the reference's opaque handles + torchmpi_sync_handle).
//
// Trust model: the listener binds loopback only and is UNAUTHENTICATED —
// any local process can connect and read/overwrite shard contents.  This
// matches the reference's posture (MPI ranks inside one scheduler-placed
// job trust each other); do not bind non-loopback interfaces without adding
// authentication.

#include <arpa/inet.h>
#include <errno.h>
#include <fcntl.h>
#include <netinet/in.h>
#include <netinet/tcp.h>
#include <poll.h>
#include <sys/socket.h>
#include <unistd.h>

#include <atomic>
#include <chrono>
#include <condition_variable>
#include <cstdint>
#include <cstring>
#include <deque>
#include <functional>
#include <map>
#include <memory>
#include <mutex>
#include <string>
#include <thread>
#include <vector>

namespace {

// ----------------------------------------------------------------- protocol
enum Op : uint8_t {
  OP_SEND = 1,      // payload in; rule applied to shard
  OP_RECEIVE = 2,   // payload out
  OP_SHUTDOWN = 3,  // close this connection
  OP_PING = 4,
};

enum Rule : uint32_t {
  RULE_COPY = 0,     // shard[i]  = p[i]
  RULE_ADD = 1,      // shard[i] += p[i]
  RULE_ZERO = 2,     // shard[i]  = 0        (payload ignored but present)
  RULE_AXPY = 3,     // shard[i] += alpha * p[i]
  RULE_ELASTIC = 4,  // delta = alpha*(p[i]-shard[i]); shard += delta;
                     // response payload = delta (EASGD symmetric update)
};

struct __attribute__((packed)) Header {
  uint8_t op;
  uint32_t rule;
  float alpha;
  uint64_t offset;  // float index into the shard
  uint64_t count;   // number of floats
};

uint64_t now_ns() {
  return static_cast<uint64_t>(
      std::chrono::duration_cast<std::chrono::nanoseconds>(
          std::chrono::steady_clock::now().time_since_epoch())
          .count());
}

bool read_exact(int fd, void* buf, size_t n) {
  auto* p = static_cast<char*>(buf);
  while (n > 0) {
    ssize_t r = ::read(fd, p, n);
    if (r <= 0) return false;
    p += r;
    n -= static_cast<size_t>(r);
  }
  return true;
}

bool write_exact(int fd, const void* buf, size_t n) {
  auto* p = static_cast<const char*>(buf);
  while (n > 0) {
    ssize_t r = ::write(fd, p, n);
    if (r <= 0) return false;
    p += r;
    n -= static_cast<size_t>(r);
  }
  return true;
}

// ------------------------------------------------------------------- server
struct Server {
  std::vector<float> shard;
  std::mutex shard_mu;
  int listen_fd = -1;
  int port = 0;
  std::thread accept_thread;
  std::vector<std::thread> handlers;
  std::vector<int> handler_fds;  // guarded by handlers_mu
  std::mutex handlers_mu;
  std::atomic<bool> stopping{false};
  std::atomic<uint64_t> ops_served{0};
  // Cycle-cost decomposition (VERDICT r4 #8): where a served op's time
  // goes, accumulated in nanoseconds across all handler threads.  The
  // blocking wait for the NEXT request header is deliberately excluded —
  // that is idle time between ops, not op cost.  recv = payload read
  // (syscall share), lock_wait = shard-mutex acquisition (contention
  // share), apply = rule loop / memcpy under the mutex, send = response
  // write.  elastic_bytes_out tracks RULE_ELASTIC response payloads
  // separately so consumers (ps_bench's apply ns/B denominator) can
  // subtract bytes the apply loop never touched as extra work.  Backs
  // benchmarks/ps_bench.py's loopback breakdown and the ROUND3_NOTES
  // scaling model with measured constants.
  //
  // Snapshot consistency (ADVICE round 5): counters update in GROUPS
  // under the existing shard mutex — the request-side group
  // (recv/lock_wait/apply/bytes_in/ops) lands inside the same critical
  // section as the rule apply, i.e. BEFORE the ok byte unblocks the
  // client, so a stats() read taken after a completed wait() sees
  // every finished op exactly; the response-side group
  // (send/bytes_out) lands after the write under a second acquire.
  // tm_ps_server_stats reads under the same mutex, so a snapshot can
  // never tear mid-group (ops ticked but its bytes_in invisible).
  std::atomic<uint64_t> recv_ns{0}, lock_wait_ns{0}, apply_ns{0},
      send_ns{0}, bytes_in{0}, bytes_out{0}, elastic_bytes_out{0};

  ~Server() { stop(); }

  bool start(uint64_t size, int want_port) {
    shard.assign(size, 0.0f);
    listen_fd = ::socket(AF_INET, SOCK_STREAM, 0);
    if (listen_fd < 0) return false;
    int one = 1;
    ::setsockopt(listen_fd, SOL_SOCKET, SO_REUSEADDR, &one, sizeof(one));
    sockaddr_in addr{};
    addr.sin_family = AF_INET;
    addr.sin_addr.s_addr = htonl(INADDR_LOOPBACK);
    addr.sin_port = htons(static_cast<uint16_t>(want_port));
    if (::bind(listen_fd, reinterpret_cast<sockaddr*>(&addr),
               sizeof(addr)) != 0)
      return false;
    socklen_t len = sizeof(addr);
    ::getsockname(listen_fd, reinterpret_cast<sockaddr*>(&addr), &len);
    port = ntohs(addr.sin_port);
    if (::listen(listen_fd, 64) != 0) return false;
    accept_thread = std::thread([this] { accept_loop(); });
    return true;
  }

  void accept_loop() {
    while (!stopping.load()) {
      int fd = ::accept(listen_fd, nullptr, nullptr);
      if (fd < 0) break;
      int one = 1;
      ::setsockopt(fd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof(one));
      std::lock_guard<std::mutex> g(handlers_mu);
      handler_fds.push_back(fd);
      handlers.emplace_back([this, fd] { handle(fd); });
    }
  }

  void handle(int fd) {
    std::vector<float> buf;
    Header h{};
    while (!stopping.load() && read_exact(fd, &h, sizeof(h))) {
      if (h.op == OP_SHUTDOWN) break;
      if (h.op == OP_PING) {
        uint8_t ok = 1;
        if (!write_exact(fd, &ok, 1)) break;
        continue;
      }
      // Overflow-safe bounds check: `offset + count` can wrap uint64, so
      // test count against the remaining space instead (ADVICE round 1).
      if (h.count > shard.size() || h.offset > shard.size() - h.count)
        break;  // malformed; drop client
      if (h.op == OP_SEND) {
        buf.resize(h.count);  // allocation kept out of every bucket
        uint64_t t0 = now_ns();
        if (!read_exact(fd, buf.data(), h.count * sizeof(float))) break;
        uint64_t t1 = now_ns();
        uint64_t t2, t3;
        {
          std::lock_guard<std::mutex> g(shard_mu);
          t2 = now_ns();
          float* s = shard.data() + h.offset;
          switch (h.rule) {
            case RULE_COPY:
              std::memcpy(s, buf.data(), h.count * sizeof(float));
              break;
            case RULE_ADD:
              for (uint64_t i = 0; i < h.count; ++i) s[i] += buf[i];
              break;
            case RULE_ZERO:
              std::memset(s, 0, h.count * sizeof(float));
              break;
            case RULE_AXPY:
              for (uint64_t i = 0; i < h.count; ++i) s[i] += h.alpha * buf[i];
              break;
            case RULE_ELASTIC:
              for (uint64_t i = 0; i < h.count; ++i) {
                float delta = h.alpha * (buf[i] - s[i]);
                s[i] += delta;
                buf[i] = delta;  // reply with deltas
              }
              break;
            default:
              break;
          }
          t3 = now_ns();
          // Request-side counter group, inside the SAME critical
          // section as the apply: consistent under the stats mutex and
          // visible BEFORE the ok byte unblocks the client.
          recv_ns.fetch_add(t1 - t0);
          lock_wait_ns.fetch_add(t2 - t1);
          apply_ns.fetch_add(t3 - t2);
          bytes_in.fetch_add(h.count * sizeof(float));
          ops_served.fetch_add(1);
        }
        uint8_t ok = 1;
        if (!write_exact(fd, &ok, 1)) break;
        if (h.rule == RULE_ELASTIC &&
            !write_exact(fd, buf.data(), h.count * sizeof(float)))
          break;
        uint64_t t4 = now_ns();
        {
          std::lock_guard<std::mutex> g(shard_mu);
          send_ns.fetch_add(t4 - t3);
          bytes_out.fetch_add(
              1 + (h.rule == RULE_ELASTIC ? h.count * sizeof(float) : 0));
          if (h.rule == RULE_ELASTIC)
            elastic_bytes_out.fetch_add(h.count * sizeof(float));
        }
      } else if (h.op == OP_RECEIVE) {
        buf.resize(h.count);  // allocation kept out of every bucket
        uint64_t t0 = now_ns();
        uint64_t t1, t2;
        {
          std::lock_guard<std::mutex> g(shard_mu);
          t1 = now_ns();
          std::memcpy(buf.data(), shard.data() + h.offset,
                      h.count * sizeof(float));
          t2 = now_ns();
          // Request-side counter group (see OP_SEND).
          lock_wait_ns.fetch_add(t1 - t0);
          apply_ns.fetch_add(t2 - t1);
          ops_served.fetch_add(1);
        }
        uint8_t ok = 1;
        if (!write_exact(fd, &ok, 1)) break;
        if (!write_exact(fd, buf.data(), h.count * sizeof(float))) break;
        uint64_t t3 = now_ns();
        {
          std::lock_guard<std::mutex> g(shard_mu);
          send_ns.fetch_add(t3 - t2);
          bytes_out.fetch_add(1 + h.count * sizeof(float));
        }
      } else {
        break;
      }
    }
    ::close(fd);
  }

  void stop() {
    if (stopping.exchange(true)) return;
    if (listen_fd >= 0) {
      ::shutdown(listen_fd, SHUT_RDWR);
      ::close(listen_fd);
    }
    if (accept_thread.joinable()) accept_thread.join();
    std::lock_guard<std::mutex> g(handlers_mu);
    // Wake handler threads blocked in read() on idle client connections —
    // without this, join() below deadlocks on any connected-but-quiet
    // client (close() alone does not interrupt a blocked read).
    for (int fd : handler_fds) ::shutdown(fd, SHUT_RDWR);
    for (auto& t : handlers)
      if (t.joinable()) t.join();
    handlers.clear();
    handler_fds.clear();
  }
};

// ------------------------------------------------------------------- client
struct Future {
  std::mutex mu;
  std::condition_variable cv;
  bool done = false;
  int status = 0;  // 1 ok, <0 error
};

struct Client {
  int fd = -1;
  // Set on the first failed op.  A failure no longer implies a dead TCP
  // connection (SO_RCVTIMEO can fire while the server is merely slow), and
  // a late response would desynchronize the request/response stream — the
  // next op would read the previous op's bytes as its own.  So the first
  // failure poisons the connection: the socket is shut down and every
  // subsequent op fails fast.
  std::atomic<bool> dead{false};
  // Per-connection op serialization: ops on one connection execute in
  // submission order (the reference's async-ordering guarantee, SURVEY §4.4).
  std::mutex io_mu;
  std::thread worker;
  std::deque<std::function<void()>> queue;
  std::mutex q_mu;
  std::condition_variable q_cv;
  std::atomic<bool> stopping{false};

  ~Client() { stop(); }

  bool connect_to(const char* host, int port, int timeout_ms) {
    fd = ::socket(AF_INET, SOCK_STREAM, 0);
    if (fd < 0) return false;
    sockaddr_in addr{};
    addr.sin_family = AF_INET;
    addr.sin_port = htons(static_cast<uint16_t>(port));
    if (::inet_pton(AF_INET, host, &addr.sin_addr) != 1) return false;
    if (timeout_ms > 0) {
      // The bounded-failure contract covers the connection phase too: a
      // listener with a full accept backlog drops SYNs and a blocking
      // connect() would ride the kernel retry schedule (~2 min) past any
      // socket timeout.  Non-blocking connect + poll bounds it.
      int flags = ::fcntl(fd, F_GETFL, 0);
      ::fcntl(fd, F_SETFL, flags | O_NONBLOCK);
      int rc = ::connect(fd, reinterpret_cast<sockaddr*>(&addr),
                         sizeof(addr));
      if (rc != 0) {
        if (errno != EINPROGRESS) return false;
        pollfd pfd{fd, POLLOUT, 0};
        if (::poll(&pfd, 1, timeout_ms) != 1) return false;
        int err = 0;
        socklen_t len = sizeof(err);
        if (::getsockopt(fd, SOL_SOCKET, SO_ERROR, &err, &len) != 0 ||
            err != 0)
          return false;
      }
      ::fcntl(fd, F_SETFL, flags);
    } else if (::connect(fd, reinterpret_cast<sockaddr*>(&addr),
                         sizeof(addr)) != 0) {
      return false;
    }
    int one = 1;
    ::setsockopt(fd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof(one));
    if (timeout_ms > 0) {
      // A wedged (alive but unresponsive) server must surface as a failed
      // future, not a hang: response reads time out, the job completes with
      // an error, and every tm_ps_wait unblocks (ADVICE round 1).
      timeval tv{};
      tv.tv_sec = timeout_ms / 1000;
      tv.tv_usec = (timeout_ms % 1000) * 1000;
      ::setsockopt(fd, SOL_SOCKET, SO_RCVTIMEO, &tv, sizeof(tv));
      ::setsockopt(fd, SOL_SOCKET, SO_SNDTIMEO, &tv, sizeof(tv));
    }
    worker = std::thread([this] { run(); });
    return true;
  }

  void run() {
    for (;;) {
      std::function<void()> job;
      {
        std::unique_lock<std::mutex> lk(q_mu);
        q_cv.wait(lk, [this] { return stopping.load() || !queue.empty(); });
        if (stopping.load() && queue.empty()) return;
        job = std::move(queue.front());
        queue.pop_front();
      }
      job();
    }
  }

  void enqueue(std::function<void()> job) {
    {
      std::lock_guard<std::mutex> g(q_mu);
      queue.push_back(std::move(job));
    }
    q_cv.notify_one();
  }

  void stop() {
    if (stopping.exchange(true)) return;
    q_cv.notify_all();
    if (worker.joinable()) worker.join();
    if (fd >= 0) {
      Header h{};
      h.op = OP_SHUTDOWN;
      write_exact(fd, &h, sizeof(h));
      ::close(fd);
      fd = -1;
    }
  }
};

// ------------------------------------------------------------------ registry
std::mutex g_mu;
std::map<int64_t, std::unique_ptr<Server>> g_servers;
std::map<int64_t, std::shared_ptr<Client>> g_clients;
std::map<int64_t, std::shared_ptr<Future>> g_futures;
int64_t g_next_id = 1;

std::shared_ptr<Future> new_future(int64_t* id_out) {
  auto f = std::make_shared<Future>();
  std::lock_guard<std::mutex> g(g_mu);
  *id_out = g_next_id++;
  g_futures[*id_out] = f;
  return f;
}

void complete(const std::shared_ptr<Future>& f, int status) {
  std::lock_guard<std::mutex> g(f->mu);
  f->status = status;
  f->done = true;
  f->cv.notify_all();
}

}  // namespace

extern "C" {

// ---- server ----
int64_t tm_ps_server_create(uint64_t shard_floats, int port) {
  auto s = std::make_unique<Server>();
  if (!s->start(shard_floats, port)) return -1;
  std::lock_guard<std::mutex> g(g_mu);
  int64_t id = g_next_id++;
  g_servers[id] = std::move(s);
  return id;
}

int tm_ps_server_port(int64_t sid) {
  std::lock_guard<std::mutex> g(g_mu);
  auto it = g_servers.find(sid);
  return it == g_servers.end() ? -1 : it->second->port;
}

uint64_t tm_ps_server_ops(int64_t sid) {
  std::lock_guard<std::mutex> g(g_mu);
  auto it = g_servers.find(sid);
  return it == g_servers.end() ? 0 : it->second->ops_served.load();
}

// Cycle-cost decomposition (VERDICT r4 #8): fills out[0..n-1] (n >= 7)
// with {ops_served, bytes_in, bytes_out, recv_ns, lock_wait_ns,
// apply_ns, send_ns} and, with n >= 8, {elastic_bytes_out} — cumulative
// since server start, summed over all handler threads.  Returns the
// number of fields written, or -1 for an unknown server / too-small
// buffer.  The idle wait for each next request header is NOT in any
// bucket (see the Server field comment).  The read takes the shard
// mutex the counter groups update under (ADVICE round 5), so a
// snapshot can no longer tear mid-group: every op whose ok byte the
// client has seen is fully counted in {ops, bytes_in, recv, lock_wait,
// apply}; {send_ns, bytes_out, elastic_bytes_out} land after the
// response write and may lag by the in-flight ops only.
int tm_ps_server_stats(int64_t sid, uint64_t* out, int n) {
  if (n < 7) return -1;
  std::lock_guard<std::mutex> g(g_mu);
  auto it = g_servers.find(sid);
  if (it == g_servers.end()) return -1;
  Server& s = *it->second;
  std::lock_guard<std::mutex> g2(s.shard_mu);
  out[0] = s.ops_served.load();
  out[1] = s.bytes_in.load();
  out[2] = s.bytes_out.load();
  out[3] = s.recv_ns.load();
  out[4] = s.lock_wait_ns.load();
  out[5] = s.apply_ns.load();
  out[6] = s.send_ns.load();
  if (n >= 8) {
    out[7] = s.elastic_bytes_out.load();
    return 8;
  }
  return 7;
}

void tm_ps_server_destroy(int64_t sid) {
  std::unique_ptr<Server> s;
  {
    std::lock_guard<std::mutex> g(g_mu);
    auto it = g_servers.find(sid);
    if (it == g_servers.end()) return;
    s = std::move(it->second);
    g_servers.erase(it);
  }
  s->stop();
}

// ---- client ----
// timeout_ms > 0 arms SO_RCVTIMEO/SO_SNDTIMEO on the connection; 0 = never
// time out (the round-1 behavior).
int64_t tm_ps_client_connect(const char* host, int port, int timeout_ms) {
  auto c = std::make_shared<Client>();
  if (!c->connect_to(host, port, timeout_ms)) return -1;
  std::lock_guard<std::mutex> g(g_mu);
  int64_t id = g_next_id++;
  g_clients[id] = std::move(c);
  return id;
}

void tm_ps_client_destroy(int64_t cid) {
  std::shared_ptr<Client> c;
  {
    std::lock_guard<std::mutex> g(g_mu);
    auto it = g_clients.find(cid);
    if (it == g_clients.end()) return;
    c = std::move(it->second);
    g_clients.erase(it);
  }
  c->stop();
}

// Async SEND.  data is copied internally before returning, so the caller's
// buffer may be reused immediately.  For RULE_ELASTIC, `inout` receives the
// server's delta response and must stay alive until the future completes.
int64_t tm_ps_send(int64_t cid, uint32_t rule, float alpha, uint64_t offset,
                   const float* data, float* inout, uint64_t count) {
  // Hold shared ownership across enqueue: a concurrent
  // tm_ps_client_destroy must not free the Client under us (ping runs from
  // monitoring threads by design).
  std::shared_ptr<Client> c;
  {
    std::lock_guard<std::mutex> g(g_mu);
    auto it = g_clients.find(cid);
    if (it == g_clients.end()) return -1;
    c = it->second;
  }
  int64_t fid;
  auto fut = new_future(&fid);
  auto payload = std::make_shared<std::vector<float>>(data, data + count);
  // The job captures the shared_ptr: the Client outlives its queue entries.
  c->enqueue([c, fut, rule, alpha, offset, payload, inout, count] {
    Header h{};
    h.op = OP_SEND;
    h.rule = rule;
    h.alpha = alpha;
    h.offset = offset;
    h.count = count;
    std::lock_guard<std::mutex> g(c->io_mu);
    bool ok = !c->dead.load() &&
              write_exact(c->fd, &h, sizeof(h)) &&
              write_exact(c->fd, payload->data(), count * sizeof(float));
    uint8_t st = 0;
    ok = ok && read_exact(c->fd, &st, 1) && st == 1;
    if (ok && rule == RULE_ELASTIC)
      ok = read_exact(c->fd, inout, count * sizeof(float));
    if (!ok && !c->dead.exchange(true)) ::shutdown(c->fd, SHUT_RDWR);
    complete(fut, ok ? 1 : -1);
  });
  return fid;
}

// Async RECEIVE into `out` (must stay alive until the future completes).
int64_t tm_ps_receive(int64_t cid, uint64_t offset, float* out,
                      uint64_t count) {
  // Hold shared ownership across enqueue: a concurrent
  // tm_ps_client_destroy must not free the Client under us (ping runs from
  // monitoring threads by design).
  std::shared_ptr<Client> c;
  {
    std::lock_guard<std::mutex> g(g_mu);
    auto it = g_clients.find(cid);
    if (it == g_clients.end()) return -1;
    c = it->second;
  }
  int64_t fid;
  auto fut = new_future(&fid);
  c->enqueue([c, fut, offset, out, count] {
    Header h{};
    h.op = OP_RECEIVE;
    h.offset = offset;
    h.count = count;
    std::lock_guard<std::mutex> g(c->io_mu);
    bool ok = !c->dead.load() && write_exact(c->fd, &h, sizeof(h));
    uint8_t st = 0;
    ok = ok && read_exact(c->fd, &st, 1) && st == 1;
    ok = ok && read_exact(c->fd, out, count * sizeof(float));
    if (!ok && !c->dead.exchange(true)) ::shutdown(c->fd, SHUT_RDWR);
    complete(fut, ok ? 1 : -1);
  });
  return fid;
}

// Async liveness probe (OP_PING round-trip on the connection's queue) —
// the failure-detection hook the SPMD side cannot have (a dead peer there
// kills the gang); here a dead shard is detected and reported.
int64_t tm_ps_ping(int64_t cid) {
  // Hold shared ownership across enqueue: a concurrent
  // tm_ps_client_destroy must not free the Client under us (ping runs from
  // monitoring threads by design).
  std::shared_ptr<Client> c;
  {
    std::lock_guard<std::mutex> g(g_mu);
    auto it = g_clients.find(cid);
    if (it == g_clients.end()) return -1;
    c = it->second;
  }
  int64_t fid;
  auto fut = new_future(&fid);
  c->enqueue([c, fut] {
    Header h{};
    h.op = OP_PING;
    std::lock_guard<std::mutex> g(c->io_mu);
    uint8_t st = 0;
    bool ok = !c->dead.load() &&
              write_exact(c->fd, &h, sizeof(h)) &&
              read_exact(c->fd, &st, 1) && st == 1;
    if (!ok && !c->dead.exchange(true)) ::shutdown(c->fd, SHUT_RDWR);
    complete(fut, ok ? 1 : -1);
  });
  return fid;
}

// Blocking wait; returns status (1 ok, <0 error) and frees the future.
int tm_ps_wait(int64_t fid) {
  std::shared_ptr<Future> f;
  {
    std::lock_guard<std::mutex> g(g_mu);
    auto it = g_futures.find(fid);
    if (it == g_futures.end()) return -2;
    f = it->second;
    g_futures.erase(it);
  }
  std::unique_lock<std::mutex> lk(f->mu);
  f->cv.wait(lk, [&] { return f->done; });
  return f->status;
}

// Timed wait: like tm_ps_wait but returns -3 on timeout WITHOUT freeing the
// future (the op may still complete; caller decides to retry, wait again, or
// forget).  Lets destructors and monitors bound their blocking (ADVICE
// round 1: wait() during GC must not hang the interpreter).
int tm_ps_wait_for(int64_t fid, int timeout_ms) {
  std::shared_ptr<Future> f;
  {
    std::lock_guard<std::mutex> g(g_mu);
    auto it = g_futures.find(fid);
    if (it == g_futures.end()) return -2;
    f = it->second;
  }
  {
    std::unique_lock<std::mutex> lk(f->mu);
    if (!f->cv.wait_for(lk, std::chrono::milliseconds(timeout_ms),
                        [&] { return f->done; }))
      return -3;
  }
  int status;
  {
    std::lock_guard<std::mutex> lk(f->mu);
    status = f->status;
  }
  std::lock_guard<std::mutex> g(g_mu);
  g_futures.erase(fid);
  return status;
}

// Drop interest in a future without waiting (fire-and-forget sends).  The
// in-flight job holds its own shared_ptr, so completion stays safe; this
// just prevents unbounded growth of the registry for never-waited handles.
void tm_ps_forget(int64_t fid) {
  std::lock_guard<std::mutex> g(g_mu);
  g_futures.erase(fid);
}

// Non-blocking poll: 1 done, 0 pending, -2 unknown.  Does not free.
int tm_ps_test(int64_t fid) {
  std::shared_ptr<Future> f;
  {
    std::lock_guard<std::mutex> g(g_mu);
    auto it = g_futures.find(fid);
    if (it == g_futures.end()) return -2;
    f = it->second;
  }
  std::lock_guard<std::mutex> lk(f->mu);
  return f->done ? 1 : 0;
}

}  // extern "C"

"""Collective micro-benchmark: allreduce + broadcast sweeps across backends.

Reference analog: ``benchmarks/*.lua`` (SURVEY.md §3 C14, reconstructed —
reference mount empty): sweep message sizes, report effective bus bandwidth
(``algbw * 2(n-1)/n`` for allreduce; ``bytes/time`` for broadcast), compare
implementations — the reference compared stock MPI vs NCCL vs its custom
chunked algorithms; here we compare ``xla`` vs ``hierarchical`` vs
``pallas``.  Broadcast is benchmarked next to allreduce because its
pipelined-chain schedule should reach ~2x the allreduce wire efficiency
(~size vs ~2*size bytes moved per device; VERDICT round 1 item 6).

The BASELINE target is this sweep measured from 8 to 256 chips on a real
pod; on the simulated CPU mesh the numbers exercise the same code paths and
validate relative behavior, and on any real multi-chip slice this script
measures the real thing unchanged.

``--pytree`` switches to the fused-pytree mode: a mixed fp32/bf16
parameter-tree allreduce (the gradsync hot path), measured per-leaf
(``fuse_max_bytes=0``) vs fused (dtype-grouped coalescing,
torchmpi_tpu/fusion.py), reporting collective launches/step from the
lowered HLO alongside wall time — the launch-count half is the
statically verifiable win, on CPU or TPU alike.

``--overlap-compare`` measures the gradsync *schedule*: the same
mixed-dtype MLP step with the post-backward sync vs the
backprop-overlapped schedule (``gradsync.make_overlapped_grad_fn`` —
docs/OVERLAP.md), reporting launches/step from the lowered HLO, wall
time, and a gradients-bitwise-equal check — the capturable evidence the
ROADMAP bench watch-item requires for a perf claim (the wall-clock win
itself is hardware-only on the CPU sim).

Run: ``python benchmarks/collectives_bench.py --devices 8 [--dcn 2]``
Or:  ``python benchmarks/collectives_bench.py --devices 8 --pytree``
Or:  ``python benchmarks/collectives_bench.py --devices 8 --overlap-compare``
"""

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def _maybe_bank(args, kind, summary):
    """Persist a ``KIND-SUMMARY`` line under ``--bank`` (stamped,
    git-pinned, platform-tagged — benchmarks/banking.py) so the
    verdict outlives the CI log it was grepped from."""
    if not getattr(args, "bank", False):
        return
    from benchmarks import banking

    rec = banking.bank_summary(kind, summary,
                               round=getattr(args, "round", None))
    print(f"# banked {kind} stamp={rec['stamp']} "
          f"commit={rec['commit']} platform={rec['platform']} -> "
          f"{banking.DEFAULT_PATH}", file=sys.stderr)


def _pytree_mode(args, mpi, mesh, sizes):
    """Fused vs per-leaf pytree allreduce: launches/step (from the
    lowered HLO — the statically verifiable win) and wall time."""
    import jax
    import jax.numpy as jnp
    import numpy as np
    from jax import shard_map
    from jax.sharding import PartitionSpec as P

    import time

    axes = tuple(mesh.axis_names)
    fuse_default = (args.fuse_bytes if args.fuse_bytes is not None
                    else mpi.Config().fuse_max_bytes)
    rng = np.random.RandomState(0)
    for nbytes in sizes:
        # ~equal-bytes leaves alternating fp32/bf16 (a mixed-precision
        # transformer tree's shape: many small tensors, two dtypes).
        per_leaf = max(8, nbytes // max(1, args.leaves) // 4)
        tree = {
            f"p{i:03d}": jnp.asarray(
                rng.randn(per_leaf),
                np.float32 if i % 2 == 0 else jnp.bfloat16)
            for i in range(args.leaves)
        }
        # Report the tree's REAL payload (bf16 leaves are 2 B/elem, so
        # it is ~3/4 of the requested --sizes figure).
        tree_bytes = sum(v.size * v.dtype.itemsize for v in tree.values())
        rows = []
        for mode, fuse_bytes in (("per-leaf", 0), ("fused", fuse_default)):
            mpi.set_config(fuse_max_bytes=fuse_bytes)

            def body(t):
                return mpi.collectives.allreduce_in_axis(t, axes, op="sum")

            fn = jax.jit(shard_map(body, mesh=mesh, in_specs=P(),
                                   out_specs=P(), check_vma=False))
            launches = fn.lower(tree).as_text().count(
                "stablehlo.all_reduce")
            out = fn(tree)  # compile
            jax.block_until_ready(out)
            t0 = time.time()
            for _ in range(args.iters):
                out = fn(tree)
            jax.block_until_ready(out)
            dt = (time.time() - t0) / args.iters
            rows.append((mode, launches, dt))
            line = {"op": "allreduce_pytree", "mode": mode,
                    "leaves": args.leaves, "bytes": tree_bytes,
                    "fuse_max_bytes": fuse_bytes, "launches": launches,
                    "ms": round(dt * 1e3, 3)}
            if args.json:
                print(json.dumps(line))
            else:
                print(f"allreduce_pytree {mode:9s} {args.leaves:4d} leaves "
                      f"{tree_bytes:>12d} B  {launches:4d} launches/step  "
                      f"{dt*1e3:8.2f} ms")
        (m0, l0, t0_), (m1, l1, t1_) = rows
        if not args.json:
            print(f"# {l0} -> {l1} launches ({l0 / max(1, l1):.0f}x fewer), "
                  f"{t0_ / max(t1_, 1e-12):.2f}x wall-time ratio "
                  f"(per-leaf/fused)")


def _obs_compare_mode(args, mpi, n):
    """Eager-dispatch overhead of the telemetry layer: the same small
    allreduce timed under obs=off / metrics / trace (docs/OBSERVABILITY
    acceptance: off->metrics must sit within the timing noise floor).
    Small payload on purpose — the Python dispatch path is what the obs
    branch sits on; large tensors would bury it under transfer time."""
    import numpy as np

    from torchmpi_tpu.utils import metrics as umetrics

    x = np.random.RandomState(0).rand(n, 1024).astype(np.float32)
    results = {}
    for mode in ("off", "metrics", "trace"):
        mpi.set_config(obs=mode)  # clears the eager jit cache
        mpi.allreduce(x)  # re-warm the executable under this mode
        results[mode] = umetrics.timed(lambda: mpi.allreduce(x),
                                       iters=args.iters, rounds=5)
        r = results[mode]
        line = {"mode": mode, "us_per_dispatch": round(r.median * 1e6, 2),
                "jitter_us": round(r.jitter * 1e6, 2)}
        print(json.dumps(line) if args.json else
              f"obs={mode:8s} {r.median * 1e6:9.2f} us/dispatch "
              f"(jitter {r.jitter * 1e6:.2f} us)")
    mpi.set_config(obs="off")
    base, m = results["off"], results["metrics"]
    delta = m.median - base.median
    floor = base.jitter + m.jitter
    verdict = "WITHIN NOISE" if abs(delta) <= floor else "MEASURABLE"
    print(f"# metrics-vs-off delta {delta * 1e6:+.2f} us "
          f"(noise floor {floor * 1e6:.2f} us): {verdict}",
          file=sys.stderr)
    summary = {
        "off_us": round(base.median * 1e6, 2),
        "metrics_us": round(m.median * 1e6, 2),
        "trace_us": round(results["trace"].median * 1e6, 2),
        "delta_us": round(delta * 1e6, 2),
        "noise_floor_us": round(floor * 1e6, 2),
        "within_noise": bool(abs(delta) <= floor),
    }
    print("OBS-SUMMARY " + json.dumps(summary))
    _maybe_bank(args, "OBS-SUMMARY", summary)


def _faults_compare_mode(args, mpi, n):
    """Dispatch overhead of the fault layer on its instrumented hot
    path: the same small STAGED allreduce (the eager surface that
    carries the ``Config.faults`` branch + policy wrapper) timed under
    faults=off / policy (docs/FAULTS.md acceptance: off->policy must
    sit within the same noise floor --obs-compare establishes for the
    telemetry branch).  Policy-only on purpose — injection would
    measure the injected faults, not the dispatch."""
    import numpy as np

    from torchmpi_tpu.utils import metrics as umetrics

    x = np.random.RandomState(0).rand(n, 1024).astype(np.float32)
    results = {}
    for mode in ("off", "policy"):
        mpi.set_config(faults=mode)
        mpi.allreduce(x, backend="host")  # warm the placement path
        results[mode] = umetrics.timed(
            lambda: mpi.allreduce(x, backend="host"),
            iters=args.iters, rounds=5)
        r = results[mode]
        line = {"mode": mode, "us_per_dispatch": round(r.median * 1e6, 2),
                "jitter_us": round(r.jitter * 1e6, 2)}
        print(json.dumps(line) if args.json else
              f"faults={mode:7s} {r.median * 1e6:9.2f} us/dispatch "
              f"(jitter {r.jitter * 1e6:.2f} us)")
    mpi.set_config(faults="off")
    base, pol = results["off"], results["policy"]
    delta = pol.median - base.median
    floor = base.jitter + pol.jitter
    verdict = "WITHIN NOISE" if abs(delta) <= floor else "MEASURABLE"
    print(f"# policy-vs-off delta {delta * 1e6:+.2f} us "
          f"(noise floor {floor * 1e6:.2f} us): {verdict}",
          file=sys.stderr)
    summary = {
        "off_us": round(base.median * 1e6, 2),
        "policy_us": round(pol.median * 1e6, 2),
        "delta_us": round(delta * 1e6, 2),
        "noise_floor_us": round(floor * 1e6, 2),
        "within_noise": bool(abs(delta) <= floor),
    }
    print("FAULTS-SUMMARY " + json.dumps(summary))
    _maybe_bank(args, "FAULTS-SUMMARY", summary)


def _watchdog_compare_mode(args, mpi, n):
    """Dispatch overhead of the collective watchdog on its instrumented
    hot path: the same small STAGED allreduce (the eager surface whose
    planned replay carries the begin/end in-flight window when armed)
    timed under watchdog=off / warn / break (docs/WATCHDOG.md
    acceptance: off->break must sit within the same noise floor the
    obs/faults branches establish).  No stalls injected — a stall
    would measure the stall, not the monitor."""
    import numpy as np

    from torchmpi_tpu.utils import metrics as umetrics

    x = np.random.RandomState(0).rand(n, 1024).astype(np.float32)
    modes = ("off", "warn", "break")
    # INTERLEAVED passes: measuring each mode in one sequential block
    # lets container load drift between blocks dominate the ~tens-of-us
    # signal (observed: the off/break delta flips sign run to run).
    # Alternating the modes per pass puts every mode under the same
    # drift; the per-mode median-of-passes is then comparable.
    samples = {m: [] for m in modes}
    for _ in range(4):
        for mode in modes:
            mpi.set_config(watchdog=mode)  # clears the plan table
            mpi.allreduce(x, backend="host")  # re-plan under this mode
            samples[mode].append(umetrics.timed(
                lambda: mpi.allreduce(x, backend="host"),
                iters=args.iters, rounds=3))
    mpi.set_config(watchdog="off")

    def med(vals):
        s = sorted(vals)
        return s[len(s) // 2]

    results = {}
    for mode in modes:
        m_us = med([r.median for r in samples[mode]]) * 1e6
        j_us = med([r.jitter for r in samples[mode]]) * 1e6
        results[mode] = (m_us, j_us)
        line = {"mode": mode, "us_per_dispatch": round(m_us, 2),
                "jitter_us": round(j_us, 2)}
        print(json.dumps(line) if args.json else
              f"watchdog={mode:6s} {m_us:9.2f} us/dispatch "
              f"(jitter {j_us:.2f} us)")
    delta = results["break"][0] - results["off"][0]
    floor = results["off"][1] + results["break"][1]
    # One-sided on purpose: this is an OVERHEAD check — a negative
    # delta is measurement noise, not a speedup to report.
    verdict = "WITHIN NOISE" if delta <= floor else "MEASURABLE"
    print(f"# break-vs-off delta {delta:+.2f} us "
          f"(noise floor {floor:.2f} us): {verdict}",
          file=sys.stderr)
    summary = {
        "off_us": round(results["off"][0], 2),
        "warn_us": round(results["warn"][0], 2),
        "break_us": round(results["break"][0], 2),
        "delta_us": round(delta, 2),
        "noise_floor_us": round(floor, 2),
        "within_noise": bool(delta <= floor),
    }
    print("WATCHDOG-SUMMARY " + json.dumps(summary))
    _maybe_bank(args, "WATCHDOG-SUMMARY", summary)


def _guard_compare_mode(args, mpi, n):
    """Dispatch overhead of the guard layer (docs/GUARD.md), in two
    halves.  **wire**: the same small STAGED allreduce (the surface
    that carries the digest compute + verify) timed under
    guard=off/wire.  **numeric**: a jitted in-axis gradient sync timed
    under guard=off/numeric — the fused sum-of-squares tripwire is
    in-graph, so this measures the compiled-step cost, not Python
    dispatch.  Acceptance: overhead recorded on the CPU sim, expected
    small; documented either way (the GUARD-SUMMARY line is what the
    guard-smoke CI job archives)."""
    import jax
    import jax.numpy as jnp
    import numpy as np
    from jax import shard_map
    from jax.sharding import PartitionSpec as P

    from torchmpi_tpu.parallel import gradsync
    from torchmpi_tpu.utils import metrics as umetrics

    x = np.random.RandomState(0).rand(n, 1024).astype(np.float32)
    summary = {}
    for mode in ("off", "wire"):
        mpi.set_config(guard=mode)
        mpi.allreduce(x, backend="host")  # warm the placement path
        r = umetrics.timed(lambda: mpi.allreduce(x, backend="host"),
                           iters=args.iters, rounds=5)
        summary[f"wire_{mode}_us"] = round(r.median * 1e6, 2)
        summary[f"wire_{mode}_jitter_us"] = round(r.jitter * 1e6, 2)
        line = {"half": "wire", "mode": mode,
                "us_per_dispatch": summary[f"wire_{mode}_us"],
                "jitter_us": summary[f"wire_{mode}_jitter_us"]}
        print(json.dumps(line) if args.json else
              f"guard={mode:8s} staged {r.median * 1e6:9.2f} us/dispatch "
              f"(jitter {r.jitter * 1e6:.2f} us)")
    mesh = mpi.current_mesh()
    axes = mesh.axis_names
    grads = {"a": jnp.ones((256, 64), jnp.float32),
             "b": jnp.ones((1024,), jnp.float32)}
    for mode in ("off", "numeric"):
        mpi.set_config(guard=mode)
        sync = jax.jit(shard_map(
            lambda g: gradsync.synchronize_gradients(g, axes),
            mesh=mesh, in_specs=(P(),), out_specs=P(), check_vma=False))
        jax.block_until_ready(sync(grads))  # compile
        r = umetrics.timed(
            lambda: jax.block_until_ready(sync(grads)),
            iters=args.iters, rounds=5)
        summary[f"numeric_{mode}_us"] = round(r.median * 1e6, 2)
        summary[f"numeric_{mode}_jitter_us"] = round(r.jitter * 1e6, 2)
        line = {"half": "numeric", "mode": mode,
                "us_per_step": summary[f"numeric_{mode}_us"],
                "jitter_us": summary[f"numeric_{mode}_jitter_us"]}
        print(json.dumps(line) if args.json else
              f"guard={mode:8s} gradsync {r.median * 1e6:9.2f} us/step "
              f"(jitter {r.jitter * 1e6:.2f} us)")
    mpi.set_config(guard="off")
    for half in ("wire", "numeric"):
        on = "wire" if half == "wire" else "numeric"
        delta = summary[f"{half}_{on}_us"] - summary[f"{half}_off_us"]
        floor = (summary[f"{half}_off_jitter_us"]
                 + summary[f"{half}_{on}_jitter_us"])
        summary[f"{half}_delta_us"] = round(delta, 2)
        summary[f"{half}_verdict"] = ("WITHIN NOISE"
                                      if abs(delta) <= floor
                                      else "MEASURABLE")
        print(f"# {half} {on}-vs-off delta {delta:+.2f} us "
              f"(noise floor {floor:.2f} us): "
              f"{summary[f'{half}_verdict']}", file=sys.stderr)
    print("GUARD-SUMMARY " + json.dumps(summary))
    _maybe_bank(args, "GUARD-SUMMARY", summary)


def _plan_compare_mode(args, mpi, n):
    """Dispatch overhead of the CollectivePlan replay path
    (docs/PLANNER.md acceptance): the same small eager allreduce timed
    planned vs pre-planner (``planner.set_enabled(False)``), each under
    every-layer-off and every-layer-ON (tuning ``backend="auto"`` +
    ``analysis=warn`` + ``obs=metrics`` + ``faults=policy``).  Small
    payload on purpose — the Python dispatch path is what the planner
    compresses; large tensors would bury it under transfer time.

    Also asserts (and emits as a ``PLAN-SUMMARY`` JSON line for CI) the
    steady-state contract: after one warm dispatch, ``--steady`` more
    dispatches produce exactly that many plan hits and ZERO re-plans,
    and every path's result is bit-identical to the pre-planner path.
    """
    import tempfile

    import numpy as np

    from torchmpi_tpu import planner
    from torchmpi_tpu.utils import metrics as umetrics

    x = np.random.RandomState(0).rand(n, 1024).astype(np.float32)
    plan_db = os.path.join(tempfile.mkdtemp(prefix="tm_plan_bench_"),
                           "plans.json")
    layer_cfgs = {
        "off": dict(backend="xla", analysis="off", obs="off", faults="off"),
        "all-on": dict(backend="auto", tuning_plan_path=plan_db,
                       analysis="warn", obs="metrics", faults="policy"),
    }
    results = {}
    bitwise_ok = True
    for lname, cfg in layer_cfgs.items():
        ref = None
        for pname, enabled in (("pre-planner", False), ("planned", True)):
            planner.set_enabled(enabled)
            mpi.set_config(**cfg)  # bumps the epoch + clears every plan
            out = np.asarray(mpi.allreduce(x))  # warm (auto measures here)
            if ref is None:
                ref = out
            elif not np.array_equal(ref, out):
                bitwise_ok = False
            r = umetrics.timed(lambda: mpi.allreduce(x),
                               iters=args.iters, rounds=5)
            results[(lname, pname)] = r
            line = {"layers": lname, "path": pname,
                    "us_per_dispatch": round(r.median * 1e6, 2),
                    "jitter_us": round(r.jitter * 1e6, 2)}
            print(json.dumps(line) if args.json else
                  f"layers={lname:7s} {pname:11s} "
                  f"{r.median * 1e6:9.2f} us/dispatch "
                  f"(jitter {r.jitter * 1e6:.2f} us)")
        planner.set_enabled(True)

    # Steady-state: one warm dispatch, then N replays — all hits.
    mpi.set_config(**layer_cfgs["all-on"])
    mpi.allreduce(x)
    planner.reset_stats()
    steady = args.steady
    for _ in range(steady):
        mpi.allreduce(x)
    st = planner.stats()

    # Verdict A/B: the grid rows above are drift-sensitive (each cell
    # is measured seconds apart, and on a small container the scheduler
    # moves more than the planner overhead between cells).  The
    # acceptance comparison interleaves the two PLANNED configs
    # round-by-round so load/thermal drift hits both equally.
    meds = {name: [] for name in layer_cfgs}
    for _ in range(5):
        for lname, cfg in layer_cfgs.items():
            mpi.set_config(**cfg)
            mpi.allreduce(x)  # re-plan + warm under this config
            meds[lname].append(
                umetrics.timed(lambda: mpi.allreduce(x),
                               iters=args.iters, rounds=1).median)
    base = umetrics.TimedResult(meds["off"])
    allon = umetrics.TimedResult(meds["all-on"])
    # Min-of-rounds (TimedResult's float value) is the stable dispatch
    # estimator on a loaded container — medians here still carry XLA
    # execution tail noise several times the planner overhead.  The
    # acceptance is ONE-sided: overhead at or below the floor (all-on
    # measuring faster than off is noise, not a failure).
    delta = float(allon) - float(base)
    floor = base.jitter + allon.jitter
    within = delta <= floor
    summary = {"steady_steps": steady, "hits": st["hits"],
               "misses": st["misses"], "entries": st["entries"],
               "bitwise_identical": bitwise_ok,
               "all_on_vs_off_us": round(delta * 1e6, 2),
               "noise_floor_us": round(floor * 1e6, 2),
               "within_noise": bool(within)}
    print("PLAN-SUMMARY " + json.dumps(summary))
    _maybe_bank(args, "PLAN-SUMMARY", summary)
    print(f"# all-layers-on planned vs off planned delta "
          f"{delta * 1e6:+.2f} us (noise floor {floor * 1e6:.2f} us): "
          f"{'WITHIN NOISE' if within else 'MEASURABLE'}; "
          f"steady-state {st['hits']} hits / {st['misses']} re-plans "
          f"over {steady} dispatches; bitwise identical to "
          f"pre-planner: {bitwise_ok}", file=sys.stderr)
    mpi.set_config(backend="xla", analysis="off", obs="off", faults="off")
    if not bitwise_ok:
        raise SystemExit("plan-compare: planned results diverged from "
                         "the pre-planner path")
    if st["misses"]:
        raise SystemExit(
            f"plan-compare: {st['misses']} steady-state re-plans "
            f"(expected zero)")


def _overlap_compare_mode(args, mpi, mesh):
    """Sync vs backprop-overlapped gradient dispatch (docs/OVERLAP.md)
    on the same mixed fp32/bf16 MLP: per-step wall time, all-reduce
    launches from the lowered HLO, and a bitwise-equality check of the
    gradients — the statically verifiable halves of the overlap claim
    on CPU or TPU alike (the wall-clock win itself is hardware-only,
    like overlap_trace.py's timing)."""
    import jax
    import jax.numpy as jnp
    import numpy as np
    from jax import shard_map
    from jax.sharding import PartitionSpec as P

    from torchmpi_tpu.parallel import gradsync

    axes = tuple(mesh.axis_names)
    key = jax.random.PRNGKey(0)
    dim = args.overlap_dim
    params = {}
    for i in range(args.overlap_layers):
        dt = jnp.float32 if i % 2 == 0 else jnp.bfloat16
        params[f"l{i:02d}"] = {
            "w": jax.random.normal(key, (dim, dim)).astype(dt)}

    def loss_fn(p, x, y):
        h = x
        for i in range(args.overlap_layers):
            w = p[f"l{i:02d}"]["w"]
            h = jnp.tanh(h.astype(w.dtype) @ w)
        return jnp.mean((h.astype(jnp.float32) - y) ** 2)

    X = np.random.RandomState(0).rand(64, dim).astype(np.float32)
    Y = np.random.RandomState(1).rand(64, dim).astype(np.float32)
    per_bucket = dim * dim * 4  # one fp32 layer per bucket

    def step_sync(p, x, y):
        loss, grads = jax.value_and_grad(loss_fn)(p, x, y)
        return loss, gradsync.synchronize_gradients(grads, axes)

    def step_overlap(p, x, y):
        return gradsync.make_overlapped_grad_fn(
            loss_fn, p, axes, max_bytes=per_bucket)(p, x, y)

    rows = []
    for mode, step in (("sync", step_sync), ("overlapped", step_overlap)):
        fn = jax.jit(shard_map(step, mesh=mesh,
                               in_specs=(P(), P(axes), P(axes)),
                               out_specs=(P(), P()), check_vma=False))
        launches = fn.lower(params, X, Y).as_text().count(
            "stablehlo.all_reduce")
        out = fn(params, X, Y)  # compile
        jax.block_until_ready(out)
        t0 = time.time()
        for _ in range(args.iters):
            out = fn(params, X, Y)
        jax.block_until_ready(out)
        dt = (time.time() - t0) / args.iters
        rows.append((mode, launches, dt, out[1]))
        line = {"op": "gradsync", "mode": mode,
                "layers": args.overlap_layers, "launches": launches,
                "ms": round(dt * 1e3, 3)}
        print(json.dumps(line) if args.json else
              f"gradsync {mode:10s} {args.overlap_layers:3d} layers  "
              f"{launches:3d} launches/step  {dt * 1e3:8.2f} ms")
    (_, l0, t0_, g0), (_, l1, t1_, g1) = rows
    bitwise = all(
        np.array_equal(np.asarray(a), np.asarray(b))
        for a, b in zip(jax.tree.leaves(g0), jax.tree.leaves(g1)))
    print(f"# overlapped-vs-sync: grads bitwise equal: {bitwise}; "
          f"{l0} -> {l1} launches; {t0_ / max(t1_, 1e-12):.2f}x wall-time "
          f"ratio (sync/overlapped — dispatch-structure evidence on "
          f"cpu-sim, wall-clock win is hardware-only)", file=sys.stderr)
    summary = {
        "layers": args.overlap_layers,
        "sync_launches": l0,
        "overlapped_launches": l1,
        "sync_ms": round(t0_ * 1e3, 3),
        "overlapped_ms": round(t1_ * 1e3, 3),
        "grads_bitwise_equal": bool(bitwise),
    }
    print("OVERLAP-SUMMARY " + json.dumps(summary))
    _maybe_bank(args, "OVERLAP-SUMMARY", summary)
    if not bitwise:
        raise SystemExit("overlap-compare: gradients diverged")


def _dcn_compare_mode(args, mpi, mesh):
    """Flat vs two-level vs two-level+codec allreduce on a simulated
    ``(dcn, ici)`` mesh (docs/HIERARCHICAL.md; ROADMAP item 4).

    The wall-clock win is hardware-only (cpu-sim has no bandwidth cliff
    between the emulated slices), so the CPU-assertable evidence is the
    DCN-leg **wire bytes** from the obs counters
    (``tm_dcn_wire_bytes_total`` — what one device actually puts on the
    inter-slice links): two-level moves ``1/ici_n`` of the flat payload,
    the int8 codec another ~1/4 of that.  Also asserted, and emitted as
    a ``DCN-SUMMARY`` JSON line for CI: chunked == unchunked bitwise,
    every mode allclose vs flat, the error-feedback running mean
    converging where single-shot quantization stays biased, and zero
    steady-state re-plans with topology-keyed plan entries.
    """
    import jax
    import jax.numpy as jnp
    import numpy as np
    from jax import shard_map
    from jax.sharding import PartitionSpec as P

    from torchmpi_tpu import obs, planner
    from torchmpi_tpu.parallel import gradsync
    from torchmpi_tpu.utils.metrics import fence

    axes = tuple(mesh.axis_names)
    n_dcn = int(mesh.shape[axes[0]])
    n_ici = int(mesh.shape[axes[1]])
    if n_dcn <= 1:
        raise SystemExit("--dcn-compare needs a two-level mesh "
                         "(run with --dcn 2)")
    n = n_dcn * n_ici
    nbytes = args.dcn_bytes
    n_elems = nbytes // 4
    x = np.random.RandomState(0).rand(n, n_elems).astype(np.float32)
    mpi.set_config(obs="metrics", custom_min_bytes=0)

    def _wire(codec):
        snap = obs.registry().snapshot()
        return sum(c["value"] for c in snap
                   if c["name"] == "tm_dcn_wire_bytes_total"
                   and (codec is None or c["labels"].get("codec") == codec))

    rows = {}
    flat = None
    modes = [("flat", "xla", "off"), ("two-level", "hierarchical", "off"),
             ("two-level+bf16", "hierarchical", "bf16"),
             ("two-level+int8", "hierarchical", "int8")]
    for tag, backend, codec in modes:
        mpi.set_config(dcn_compress=codec, dcn_compress_min_bytes=0)
        label = codec if codec != "off" else (
            "none" if backend == "hierarchical" else None)
        before = _wire(label) if backend == "hierarchical" else 0
        out = np.asarray(mpi.allreduce(x, backend=backend))  # compile
        t0 = time.time()
        for _ in range(args.iters):
            # Per-iteration fence: overlapping in-flight hierarchical
            # programs can interleave their sibling collectives'
            # blocking rendezvous on the CPU sim (same hazard the
            # steady-state loop below fences; we report per-iteration
            # averages, so the fence costs nothing we measure).
            fence(mpi.allreduce(x, backend=backend))
        dt = (time.time() - t0) / max(1, args.iters)
        # Trace-time counters: the delta across the compile is the
        # per-step DCN wire bytes one device sends (flat has no DCN
        # staging — its whole payload crosses the cliff; analytic).
        wire = (_wire(label) - before if backend == "hierarchical"
                else nbytes)
        if flat is None:
            flat = out
        rel = float(np.max(np.abs(out - flat))
                    / max(1e-12, float(np.max(np.abs(flat)))))
        rows[tag] = dict(wire_bytes=int(wire), ms=round(dt * 1e3, 3),
                         rel_err=rel)
        line = {"mode": tag, "bytes": nbytes, "dcn_wire_bytes": int(wire),
                "ms": round(dt * 1e3, 3), "rel_err_vs_flat": rel}
        print(json.dumps(line) if args.json else
              f"{tag:15s} {nbytes:>10d} B payload  "
              f"{int(wire):>10d} B across dcn  {dt * 1e3:8.2f} ms  "
              f"rel-err vs flat {rel:.2e}")

    # Chunk pipelining: bitwise vs the unchunked schedule.
    mpi.set_config(dcn_compress="off", dcn_chunk_bytes=0)
    base = np.asarray(mpi.allreduce(x, backend="hierarchical"))
    mpi.set_config(dcn_chunk_bytes=max(1, nbytes // n_ici // 4))
    chunked = np.asarray(mpi.allreduce(x, backend="hierarchical"))
    chunk_bitwise = bool(np.array_equal(base, chunked))
    mpi.set_config(dcn_chunk_bytes=4 * 1024 * 1024)

    # Error-feedback residual convergence: running mean of EF-quantized
    # syncs approaches the exact mean; single-shot quantization stays
    # biased (the deep-gradient-compression trade, checkable on cpu-sim).
    mpi.set_config(dcn_compress="int8", dcn_compress_min_bytes=0)
    r = np.random.RandomState(1)
    gvals = r.randn(4096).astype(np.float32)
    gvals[:8] *= 100.0  # outliers -> coarse scale -> visible bias
    grads = {"g": jnp.asarray(gvals)}
    exact = np.asarray(jax.jit(shard_map(
        lambda g: gradsync.synchronize_gradients(g, axes, op="mean"),
        mesh=mesh, in_specs=P(), out_specs=P(), check_vma=False))(
        grads)["g"])
    ef = jax.jit(shard_map(
        lambda g, rs: gradsync.synchronize_gradients(
            g, axes, op="mean", residuals=rs),
        mesh=mesh, in_specs=(P(), P(axes)), out_specs=(P(), P(axes)),
        check_vma=False))
    res = gradsync.init_dcn_residuals(grads, axes)
    res0 = gradsync.init_dcn_residuals(grads, axes)
    ef_acc = ss_acc = None
    steps = 6
    for _ in range(steps):
        out_ef, res = ef(grads, res)
        out_ss, _ = ef(grads, res0)
        ef_acc = out_ef["g"] if ef_acc is None else ef_acc + out_ef["g"]
        ss_acc = out_ss["g"] if ss_acc is None else ss_acc + out_ss["g"]
    ef_err = float(jnp.mean(jnp.abs(ef_acc / steps - exact)))
    ss_err = float(jnp.mean(jnp.abs(ss_acc / steps - exact)))
    residual_ok = ef_err < ss_err

    # Steady state: two-level+int8 eager dispatches must all be plan
    # hits (0 re-plans) with topology-keyed entries.  Every iteration is
    # fenced: the hierarchical program runs several subset collectives
    # per execution, and letting async dispatch skew the simulated
    # devices across many in-flight executions deadlocks XLA:CPU's
    # collective rendezvous on small hosts (the loop counts plan hits,
    # not wall time, so the fence costs nothing we report).
    fence(mpi.allreduce(x, backend="hierarchical"))  # warm under int8
    planner.reset_stats()
    for _ in range(args.steady):
        fence(mpi.allreduce(x, backend="hierarchical"))
    st = planner.stats()
    topologies = {row["topology"] for row in planner.describe()}

    wire_none = rows["two-level"]["wire_bytes"]
    wire_int8 = rows["two-level+int8"]["wire_bytes"]
    # The acceptance ratio: int8 moves <= 1/ici_n * ~1/4 of the flat
    # bytes (scale overhead gets a little slack).
    bound = nbytes / n_ici / 4 * 1.05
    summary = {
        "payload_bytes": nbytes, "n_dcn": n_dcn, "n_ici": n_ici,
        "flat_dcn_bytes": nbytes, "two_level_dcn_bytes": wire_none,
        "int8_dcn_bytes": wire_int8,
        "compressed_lt_uncompressed": bool(wire_int8 < wire_none
                                           and wire_none < nbytes),
        "int8_within_bound": bool(wire_int8 <= bound),
        "chunked_bitwise": chunk_bitwise,
        "allclose_vs_flat": bool(
            rows["two-level"]["rel_err"] < 1e-5
            and rows["two-level+bf16"]["rel_err"] < 2e-2
            and rows["two-level+int8"]["rel_err"] < 2e-2),
        "residual_convergence_ok": residual_ok,
        "ef_mean_err": round(ef_err, 6), "ss_mean_err": round(ss_err, 6),
        "steady_steps": args.steady, "hits": st["hits"],
        "misses": st["misses"], "topologies": sorted(topologies),
    }
    print("DCN-SUMMARY " + json.dumps(summary))
    _maybe_bank(args, "DCN-SUMMARY", summary)
    print(f"# dcn-compare: flat {nbytes} B vs two-level {wire_none} B "
          f"(1/{n_ici}) vs int8 {wire_int8} B across dcn; chunked "
          f"bitwise={chunk_bitwise}; EF mean-err {ef_err:.4g} vs "
          f"single-shot {ss_err:.4g}; steady {st['hits']} hits / "
          f"{st['misses']} re-plans", file=sys.stderr)
    mpi.set_config(obs="off", dcn_compress="off")
    failures = [k for k in ("compressed_lt_uncompressed",
                            "int8_within_bound", "chunked_bitwise",
                            "allclose_vs_flat", "residual_convergence_ok")
                if not summary[k]]
    if failures:
        raise SystemExit(f"dcn-compare failed: {failures}")
    if st["misses"]:
        raise SystemExit(f"dcn-compare: {st['misses']} steady-state "
                         f"re-plans (expected zero)")


def main():
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--devices", type=int, default=0,
                   help="force N simulated CPU devices")
    p.add_argument("--dcn", type=int, default=None)
    p.add_argument("--sizes", type=str,
                   default="65536,1048576,16777216,67108864",
                   help="comma-separated tensor bytes")
    p.add_argument("--iters", type=int, default=10)
    p.add_argument("--backends", type=str, default="xla,hierarchical,pallas")
    p.add_argument("--json", action="store_true",
                   help="emit one JSON line per measurement")
    p.add_argument("--pytree", action="store_true",
                   help="fused-pytree mode: per-leaf vs dtype-grouped "
                        "fused allreduce over a mixed-dtype tree, with "
                        "launches/step from the lowered HLO")
    p.add_argument("--leaves", type=int, default=64,
                   help="pytree mode: number of leaves (alternating "
                        "fp32/bf16)")
    p.add_argument("--fuse-bytes", type=int, default=None,
                   help="pytree mode: fuse_max_bytes for the fused rows "
                        "(default: the Config default)")
    p.add_argument("--obs-compare", action="store_true",
                   help="telemetry overhead mode: the same small eager "
                        "allreduce under obs=off/metrics/trace "
                        "(docs/OBSERVABILITY.md)")
    p.add_argument("--faults-compare", action="store_true",
                   help="fault-layer overhead mode: the same small "
                        "staged allreduce under faults=off/policy "
                        "(docs/FAULTS.md)")
    p.add_argument("--watchdog-compare", action="store_true",
                   help="watchdog overhead mode: the same small staged "
                        "allreduce under watchdog=off/warn/break (the "
                        "armed in-flight window + monitor thread, no "
                        "stalls injected) — docs/WATCHDOG.md")
    p.add_argument("--guard-compare", action="store_true",
                   help="guard overhead mode: the same small staged "
                        "allreduce under guard=off/wire (digest cost) "
                        "and a jitted gradient sync under "
                        "guard=off/numeric (fused tripwire cost) — "
                        "docs/GUARD.md")
    p.add_argument("--plan-compare", action="store_true",
                   help="planner overhead mode: the same small eager "
                        "allreduce, planned vs pre-planner dispatch, "
                        "under all-layers-off and all-layers-on "
                        "(tuning+analysis+obs+faults), plus a "
                        "steady-state zero-re-plan assertion "
                        "(docs/PLANNER.md)")
    p.add_argument("--steady", type=int, default=100,
                   help="plan-compare mode: steady-state dispatches to "
                        "assert zero re-plans over")
    p.add_argument("--overlap-compare", action="store_true",
                   help="gradsync schedule mode: sync vs "
                        "backprop-overlapped dispatch on a mixed-dtype "
                        "MLP, with launches/step from the lowered HLO "
                        "and a grads bitwise check (docs/OVERLAP.md)")
    p.add_argument("--dcn-compare", action="store_true",
                   help="two-level mode: flat vs hierarchical vs "
                        "hierarchical+codec on a (dcn, ici) mesh — "
                        "DCN-leg wire bytes from obs counters, "
                        "bitwise/allclose verdicts, error-feedback "
                        "residual convergence, steady-state plan hits "
                        "(docs/HIERARCHICAL.md; needs --dcn >= 2)")
    p.add_argument("--dcn-bytes", type=int, default=1 << 20,
                   help="dcn-compare mode: per-device payload bytes")
    p.add_argument("--overlap-layers", type=int, default=8,
                   help="overlap mode: MLP depth (alternating "
                        "fp32/bf16 layers)")
    p.add_argument("--overlap-dim", type=int, default=128,
                   help="overlap mode: layer width")
    p.add_argument("--bank", action="store_true",
                   help="persist each *-SUMMARY line to "
                        "SUMMARY_BANK.json at the repo root (stamped + "
                        "git-pinned + platform-tagged; "
                        "benchmarks/banking.py) next to the "
                        "BENCH_r*.json round records")
    p.add_argument("--round", type=int, default=None,
                   help="bench round number stamped on banked records "
                        "(the BENCH_r<N> numbering; bench.py's "
                        "micro-ladder pass sets it — defaults to "
                        "TORCHMPI_TPU_BENCH_ROUND when unset)")
    args = p.parse_args()
    if args.devices:
        from torchmpi_tpu.utils.simulation import force_cpu_devices

        force_cpu_devices(args.devices)
    import jax
    import numpy as np

    import torchmpi_tpu as mpi
    from torchmpi_tpu.ops import ring
    from torchmpi_tpu.utils.metrics import allreduce_bus_bandwidth, fence

    mesh = mpi.init(mpi.Config(dcn_size=args.dcn, custom_min_bytes=0))
    n = mpi.device_count()
    is_cpu = list(mesh.devices.flat)[0].platform == "cpu"
    if is_cpu:
        from jax.experimental.pallas import tpu as pltpu

        if hasattr(pltpu, "InterpretParams"):
            ring.set_interpret(pltpu.InterpretParams())
    print(f"# mesh {dict(zip(mesh.axis_names, mesh.devices.shape))} "
          f"({'cpu-sim' if is_cpu else 'tpu'})", file=sys.stderr)

    backends = args.backends.split(",")
    sizes = [int(s) for s in args.sizes.split(",")]

    if args.plan_compare:
        _plan_compare_mode(args, mpi, n)
        mpi.stop()
        return

    if args.obs_compare:
        _obs_compare_mode(args, mpi, n)
        mpi.stop()
        return

    if args.faults_compare:
        _faults_compare_mode(args, mpi, n)
        mpi.stop()
        return

    if args.watchdog_compare:
        _watchdog_compare_mode(args, mpi, n)
        mpi.stop()
        return

    if args.guard_compare:
        _guard_compare_mode(args, mpi, n)
        mpi.stop()
        return

    if args.overlap_compare:
        _overlap_compare_mode(args, mpi, mesh)
        mpi.stop()
        return

    if args.dcn_compare:
        _dcn_compare_mode(args, mpi, mesh)
        mpi.stop()
        return

    if args.pytree:
        _pytree_mode(args, mpi, mesh, sizes)
        mpi.stop()
        return

    for nbytes in sizes:
        floats_per_rank = nbytes // 4
        x = np.random.RandomState(0).rand(n, floats_per_rank).astype(
            np.float32)
        for backend in backends:
            if backend == "hierarchical" and mesh.shape[mpi.DCN_AXIS] <= 1:
                continue
            if backend == "pallas" and is_cpu and nbytes > 1 << 20:
                continue  # interpreter too slow for big tensors
            try:
                out = mpi.allreduce(x, backend=backend)  # compile
                fence(out)
                t0 = time.time()
                for _ in range(args.iters):
                    out = mpi.allreduce(x, backend=backend)
                fence(out)
                dt = (time.time() - t0) / args.iters
            except Exception as e:  # noqa: BLE001 — report and continue
                print(f"{backend:13s} {nbytes:>12d} B  FAILED: {e}",
                      file=sys.stderr)
                continue
            busbw = allreduce_bus_bandwidth(nbytes, n, dt)
            line = {"op": "allreduce", "backend": backend, "bytes": nbytes,
                    "devices": n, "ms": round(dt * 1e3, 3),
                    "busbw_GBs": round(busbw, 3)}
            if args.json:
                print(json.dumps(line))
            else:
                print(f"{'allreduce':10s} {backend:13s} {nbytes:>12d} B  "
                      f"{dt*1e3:8.2f} ms  busbw {busbw:8.3f} GB/s")

        # Root-ops next to allreduce.  Broadcast: algo bytes = tensor
        # size, so the chain schedule should approach 2x the allreduce
        # busbw line.  Gather/scatter: above the chunk_bytes cutover the
        # chain schedules move O(size) like the reference's
        # MPI_Gather/Scatter, so their time should track broadcast of the
        # same total payload — NOT the allgather row (which moves the
        # gathered payload to EVERY device).  algo bytes = the total
        # payload that must cross the root's link.
        root_ops = [
            ("broadcast", lambda b: mpi.broadcast(x, root=0, backend=b),
             nbytes),
            ("gather", lambda b: mpi.gather(x, root=0, backend=b),
             n * nbytes),
            ("scatter", lambda b: mpi.scatter(x, root=0, backend=b),
             nbytes),
            ("allgather", lambda b: mpi.allgather(x, backend=b),
             n * nbytes),
        ]
        for opname, op_fn, algo_bytes in root_ops:
            for backend in backends:
                # gather/scatter have no pallas registration; allgather
                # DOES (ring_all_gather) and must appear in the
                # comparison.  Same interpreter size guard as allreduce.
                if backend == "pallas" and (
                        opname != "allgather"
                        or (is_cpu and nbytes > 1 << 20)):
                    continue
                if (backend == "hierarchical"
                        and mesh.shape[mpi.DCN_AXIS] <= 1):
                    continue
                if backend == "hierarchical" and opname == "scatter":
                    continue  # delegates to the stock chain; same row
                try:
                    out = op_fn(backend)
                    fence(out)
                    t0 = time.time()
                    for _ in range(args.iters):
                        out = op_fn(backend)
                    fence(out)
                    dt = (time.time() - t0) / args.iters
                except Exception as e:  # noqa: BLE001 — report, continue
                    print(f"{opname:10s} {backend:13s} {nbytes:>12d} B  "
                          f"FAILED: {e}", file=sys.stderr)
                    continue
                bw = algo_bytes / dt / 1e9
                line = {"op": opname, "backend": backend, "bytes": nbytes,
                        "devices": n, "ms": round(dt * 1e3, 3),
                        "busbw_GBs": round(bw, 3)}
                if args.json:
                    print(json.dumps(line))
                else:
                    print(f"{opname:10s} {backend:13s} {nbytes:>12d} B  "
                          f"{dt*1e3:8.2f} ms  busbw {bw:8.3f} GB/s")
    mpi.stop()


if __name__ == "__main__":
    main()

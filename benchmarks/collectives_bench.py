"""Collective micro-benchmark: allreduce + broadcast sweeps across backends.

Reference analog: ``benchmarks/*.lua`` (SURVEY.md §3 C14, reconstructed —
reference mount empty): sweep message sizes, report effective bus bandwidth
(``algbw * 2(n-1)/n`` for allreduce; ``bytes/time`` for broadcast), compare
implementations — the reference compared stock MPI vs NCCL vs its custom
chunked algorithms; here we compare ``xla`` vs ``hierarchical`` vs
``pallas``.  Broadcast is benchmarked next to allreduce because its
pipelined-chain schedule should reach ~2x the allreduce wire efficiency
(~size vs ~2*size bytes moved per device; VERDICT round 1 item 6).

The BASELINE target is this sweep measured from 8 to 256 chips on a real
pod; on the simulated CPU mesh the numbers exercise the same code paths and
validate relative behavior, and on any real multi-chip slice this script
measures the real thing unchanged.

Run: ``python benchmarks/collectives_bench.py --devices 8 [--dcn 2]``
"""

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main():
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--devices", type=int, default=0,
                   help="force N simulated CPU devices")
    p.add_argument("--dcn", type=int, default=None)
    p.add_argument("--sizes", type=str,
                   default="65536,1048576,16777216,67108864",
                   help="comma-separated tensor bytes")
    p.add_argument("--iters", type=int, default=10)
    p.add_argument("--backends", type=str, default="xla,hierarchical,pallas")
    p.add_argument("--json", action="store_true",
                   help="emit one JSON line per measurement")
    args = p.parse_args()
    if args.devices:
        from torchmpi_tpu.utils.simulation import force_cpu_devices

        force_cpu_devices(args.devices)
    import jax
    import numpy as np

    import torchmpi_tpu as mpi
    from torchmpi_tpu.ops import ring
    from torchmpi_tpu.utils.metrics import allreduce_bus_bandwidth, fence

    mesh = mpi.init(mpi.Config(dcn_size=args.dcn, custom_min_bytes=0))
    n = mpi.device_count()
    is_cpu = list(mesh.devices.flat)[0].platform == "cpu"
    if is_cpu:
        from jax.experimental.pallas import tpu as pltpu

        if hasattr(pltpu, "InterpretParams"):
            ring.set_interpret(pltpu.InterpretParams())
    print(f"# mesh {dict(zip(mesh.axis_names, mesh.devices.shape))} "
          f"({'cpu-sim' if is_cpu else 'tpu'})", file=sys.stderr)

    backends = args.backends.split(",")
    sizes = [int(s) for s in args.sizes.split(",")]
    for nbytes in sizes:
        floats_per_rank = nbytes // 4
        x = np.random.RandomState(0).rand(n, floats_per_rank).astype(
            np.float32)
        for backend in backends:
            if backend == "hierarchical" and mesh.shape[mpi.DCN_AXIS] <= 1:
                continue
            if backend == "pallas" and is_cpu and nbytes > 1 << 20:
                continue  # interpreter too slow for big tensors
            try:
                out = mpi.allreduce(x, backend=backend)  # compile
                fence(out)
                t0 = time.time()
                for _ in range(args.iters):
                    out = mpi.allreduce(x, backend=backend)
                fence(out)
                dt = (time.time() - t0) / args.iters
            except Exception as e:  # noqa: BLE001 — report and continue
                print(f"{backend:13s} {nbytes:>12d} B  FAILED: {e}",
                      file=sys.stderr)
                continue
            busbw = allreduce_bus_bandwidth(nbytes, n, dt)
            line = {"op": "allreduce", "backend": backend, "bytes": nbytes,
                    "devices": n, "ms": round(dt * 1e3, 3),
                    "busbw_GBs": round(busbw, 3)}
            if args.json:
                print(json.dumps(line))
            else:
                print(f"{'allreduce':10s} {backend:13s} {nbytes:>12d} B  "
                      f"{dt*1e3:8.2f} ms  busbw {busbw:8.3f} GB/s")

        # Root-ops next to allreduce.  Broadcast: algo bytes = tensor
        # size, so the chain schedule should approach 2x the allreduce
        # busbw line.  Gather/scatter: above the chunk_bytes cutover the
        # chain schedules move O(size) like the reference's
        # MPI_Gather/Scatter, so their time should track broadcast of the
        # same total payload — NOT the allgather row (which moves the
        # gathered payload to EVERY device).  algo bytes = the total
        # payload that must cross the root's link.
        root_ops = [
            ("broadcast", lambda b: mpi.broadcast(x, root=0, backend=b),
             nbytes),
            ("gather", lambda b: mpi.gather(x, root=0, backend=b),
             n * nbytes),
            ("scatter", lambda b: mpi.scatter(x, root=0, backend=b),
             nbytes),
            ("allgather", lambda b: mpi.allgather(x, backend=b),
             n * nbytes),
        ]
        for opname, op_fn, algo_bytes in root_ops:
            for backend in backends:
                # gather/scatter have no pallas registration; allgather
                # DOES (ring_all_gather) and must appear in the
                # comparison.  Same interpreter size guard as allreduce.
                if backend == "pallas" and (
                        opname != "allgather"
                        or (is_cpu and nbytes > 1 << 20)):
                    continue
                if (backend == "hierarchical"
                        and mesh.shape[mpi.DCN_AXIS] <= 1):
                    continue
                if backend == "hierarchical" and opname == "scatter":
                    continue  # delegates to the stock chain; same row
                try:
                    out = op_fn(backend)
                    fence(out)
                    t0 = time.time()
                    for _ in range(args.iters):
                        out = op_fn(backend)
                    fence(out)
                    dt = (time.time() - t0) / args.iters
                except Exception as e:  # noqa: BLE001 — report, continue
                    print(f"{opname:10s} {backend:13s} {nbytes:>12d} B  "
                          f"FAILED: {e}", file=sys.stderr)
                    continue
                bw = algo_bytes / dt / 1e9
                line = {"op": opname, "backend": backend, "bytes": nbytes,
                        "devices": n, "ms": round(dt * 1e3, 3),
                        "busbw_GBs": round(bw, 3)}
                if args.json:
                    print(json.dumps(line))
                else:
                    print(f"{opname:10s} {backend:13s} {nbytes:>12d} B  "
                          f"{dt*1e3:8.2f} ms  busbw {bw:8.3f} GB/s")
    mpi.stop()


if __name__ == "__main__":
    main()

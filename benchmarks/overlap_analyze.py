#!/usr/bin/env python
"""Analyze gradient-sync overlap: trace events + HLO evidence.

Companion to ``overlap_trace.py`` (SURVEY.md §8.4.3 / ROADMAP item 1):
given a captured profiler trace, summarize how the per-bucket gradient
all-reduces interleave with backward compute; independently, lower the
bucketed DP step at several ``n_buckets`` settings — plus the
**backprop-overlapped schedule** (``Config.gradsync_overlap="auto"``,
docs/OVERLAP.md), whose per-bucket all-reduces are anchored inside the
backward by ``custom_vjp`` hooks and barrier-chained — and count
collective ops pre-optimization vs in the compiled executable: the
direct evidence of whether XLA's all-reduce combiner preserved or
merged the configured buckets on this platform (it merges below its
combine threshold, which is the scheduling fact any bucket-count
default must be justified against; the overlapped schedule's barrier
chain is specifically built to survive it).

Run: ``python benchmarks/overlap_analyze.py [--devices 8]
[--trace path/to/*.trace.json.gz] [--buckets 1,4,8]``
Emits one JSON line per measurement (``schedule`` names bucketed vs
overlapped rows) and a final ``summary`` line whose
``overlap_buckets_survive`` field is the assertable verdict for the
overlapped rows.
"""

import argparse
import collections
import glob
import gzip
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def analyze_trace(path):
    """Summarize a perfetto/xplane JSON trace: collective events and
    their position among compute ops on the busiest device lane."""
    with gzip.open(path, "rt") as f:
        data = json.load(f)
    ev = [e for e in data.get("traceEvents", []) if e.get("ph") == "X"]
    coll = collections.Counter(
        e["name"] for e in ev
        if "all-reduce" in e.get("name", "").lower()
        and not e["name"].startswith("end:"))
    lanes = collections.defaultdict(list)
    for e in ev:
        nm = e.get("name", "")
        if nm.startswith(("fusion", "convolution", "all-reduce", "loop_",
                          "transpose", "convert", "dot")) \
                and not nm.startswith("end:"):
            lanes[(e.get("pid"), e.get("tid"))].append((e.get("ts"), nm))
    if not lanes:
        return {"trace": path, "collective_ops": dict(coll), "lanes": 0}
    lane = max(lanes.values(), key=len)
    lane.sort()
    ar_pos = [i for i, (_, nm) in enumerate(lane) if "all-reduce" in nm]
    # Overlap evidence: a collective strictly between compute ops (not at
    # the lane edges) means the scheduler placed compute after it that
    # does not depend on it.
    interleaved = [p for p in ar_pos if 0 < p < len(lane) - 1]
    return {"trace": path,
            "collective_ops": dict(coll),
            "lane_ops": len(lane),
            "allreduce_positions": ar_pos,
            "interleaved": len(interleaved)}


def bucket_hlo_counts(n_buckets, mesh, model_ctor, tx, barrier=False,
                      overlap=False):
    """Count all_reduce ops pre-optimization vs compiled for one bucket
    setting of the standard BN DP train step.  ``barrier=True`` chains
    buckets through optimization barriers (``Config.gradsync_barrier``)
    — the compiled count then shows whether THIS platform's combiner
    respects them (TPU does; the CPU pipeline expands them first).
    ``overlap=True`` lowers the backprop-overlapped schedule instead
    (``gradsync_overlap="auto"``, ~``n_buckets`` buckets via the
    overlap byte bound), whose barrier token chain should keep every
    bucket distinct."""
    import jax
    import jax.numpy as jnp
    import numpy as np
    from jax.sharding import NamedSharding, PartitionSpec as P

    import torchmpi_tpu as mpi

    prev_barrier = mpi.config().gradsync_barrier
    prev_ob = mpi.config().gradsync_overlap_bytes
    mpi.set_config(gradsync_barrier=barrier)
    model = model_ctor()
    v = model.init(jax.random.PRNGKey(0), jnp.zeros((1, 32, 32, 3)),
                   train=False)
    params, bs = v["params"], v["batch_stats"]
    if overlap:
        total = sum(int(np.prod(l.shape)) * l.dtype.itemsize
                    for l in jax.tree.leaves(params))
        mpi.set_config(gradsync_overlap_bytes=max(
            1, -(-total // max(1, n_buckets))))
    step = mpi.recipes.make_bn_dp_train_step(
        model, tx, mesh=mesh, n_buckets=n_buckets,
        overlap="auto" if overlap else "off")
    p2, o2, b2 = mpi.recipes.replicate_bn_state(params, tx.init(params),
                                                bs, mesh=mesh)
    sh = NamedSharding(mesh, P(mesh.axis_names))
    X = jax.device_put(np.random.RandomState(0).rand(
        16, 32, 32, 3).astype(np.float32), sh)
    Y = jax.device_put(np.random.RandomState(1).randint(
        0, 10, size=16).astype(np.int32), sh)
    low = step.jitted.lower(p2, o2, b2, X, Y)
    pre = low.as_text().count("stablehlo.all_reduce")
    txt = low.compile().as_text()
    # no config leakage
    mpi.set_config(gradsync_barrier=prev_barrier,
                   gradsync_overlap_bytes=prev_ob)
    # TPU's latency-hiding scheduler emits overlapped collectives as
    # paired all-reduce-start/done ops; count starts OR the sync form,
    # never both (a start is never also spelled "all-reduce(").
    post = txt.count("all-reduce-start(") or txt.count("all-reduce(")
    return {"schedule": "overlapped" if overlap else "bucketed",
            "n_buckets": n_buckets, "barrier": barrier,
            "all_reduce_pre_opt": pre,
            "all_reduce_compiled": post,
            "async_form": bool(txt.count("all-reduce-start("))}


def main():
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--devices", type=int, default=0)
    p.add_argument("--trace", default=None,
                   help="trace .json.gz (default: newest under "
                        "docs/artifacts/overlap_trace*)")
    p.add_argument("--buckets", default="1,4,8")
    args = p.parse_args()
    if args.devices:
        from torchmpi_tpu.utils.simulation import force_cpu_devices

        force_cpu_devices(args.devices)

    import optax

    import torchmpi_tpu as mpi
    from torchmpi_tpu.models import ResNet20

    trace = args.trace
    if trace is None:
        root = os.path.join(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))), "docs", "artifacts")
        cands = sorted(glob.glob(os.path.join(
            root, "overlap_trace*", "**", "*.json.gz"), recursive=True))
        trace = cands[-1] if cands else None
    if trace:
        print(json.dumps(analyze_trace(trace)))

    mesh = mpi.init()
    platform = list(mesh.devices.flat)[0].platform
    rows = []
    bucket_list = [int(b) for b in args.buckets.split(",")]
    for nb in bucket_list:
        for barrier in ((False, True) if nb > 1 else (False,)):
            row = bucket_hlo_counts(nb, mesh,
                                    lambda: ResNet20(num_classes=10),
                                    optax.sgd(0.1), barrier=barrier)
            row["platform"] = platform
            rows.append(row)
            print(json.dumps(row))
    # The backprop-overlapped schedule at the largest bucket count: its
    # custom_vjp anchoring + barrier token chain should keep every
    # bucket's all-reduce distinct through compilation.
    over = bucket_hlo_counts(max(bucket_list), mesh,
                             lambda: ResNet20(num_classes=10),
                             optax.sgd(0.1), overlap=True)
    over["platform"] = platform
    print(json.dumps(over))
    # Verdict over the DEFAULT (barrier=False) rows only: barrier rows
    # are the control lever, not the default behavior being judged.
    plain_rows = [r for r in rows if not r["barrier"]]
    merged = all(r["all_reduce_compiled"] <= plain_rows[0]
                 ["all_reduce_compiled"] for r in plain_rows)
    print(json.dumps({
        "summary": "combiner_merged_buckets" if merged
        else "buckets_survive_compilation",
        "platform": platform,
        "overlap_buckets_survive":
            over["all_reduce_compiled"] >= over["all_reduce_pre_opt"]
            and over["all_reduce_pre_opt"] > 1,
        "overlap_all_reduce": {
            "pre_opt": over["all_reduce_pre_opt"],
            "compiled": over["all_reduce_compiled"]},
        "note": ("XLA's all-reduce combiner merged the configured buckets "
                 "into one compiled collective at this model scale — "
                 "bucket-count tuning only matters above the combine "
                 "threshold" if merged else
                 "compiled collective count tracks n_buckets — bucket "
                 "overlap is schedulable on this platform"),
    }))
    mpi.stop()


if __name__ == "__main__":
    main()

"""Parameter-server throughput micro-benchmark.

Reference analog: the PS half of ``benchmarks/`` (SURVEY.md §3 C14):
send/receive round-trip latency and sustained one-way throughput against the
native shard servers, vs payload size and shard count.

Run: ``python benchmarks/ps_bench.py``
"""

import argparse
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main():
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--sizes", type=str, default="65536,1048576,16777216")
    p.add_argument("--shards", type=int, default=4)
    p.add_argument("--iters", type=int, default=20)
    p.add_argument("--elastic", action="store_true",
                   help="also run an EASGD elastic-rule workload (the "
                        "response carries a full delta payload; its "
                        "bytes are tracked separately so the apply "
                        "ns/B denominator stays honest)")
    args = p.parse_args()

    from torchmpi_tpu.parallel.ps import ParameterServer

    for nbytes in (int(s) for s in args.sizes.split(",")):
        tree = {"p": np.zeros(nbytes // 4, np.float32)}
        ps = ParameterServer(tree, num_shards=args.shards)
        try:
            payload = {"p": np.ones(nbytes // 4, np.float32)}
            ps.send(payload, rule="add").wait()  # warm
            t0 = time.time()
            for _ in range(args.iters):
                ps.send(payload, rule="add").wait()
            send_dt = (time.time() - t0) / args.iters
            ps.receive().wait()
            t0 = time.time()
            for _ in range(args.iters):
                ps.receive().wait()
            recv_dt = (time.time() - t0) / args.iters
            # pipelined (async, wait at end) — the prefetch pattern's win
            t0 = time.time()
            hs = [ps.send(payload, rule="add") for _ in range(args.iters)]
            for h in hs:
                h.wait()
            pipe_dt = (time.time() - t0) / args.iters
            line = (f"{nbytes:>12d} B x{args.shards} shards  "
                    f"send {nbytes/send_dt/1e9:6.2f} GB/s  "
                    f"recv {nbytes/recv_dt/1e9:6.2f} GB/s  "
                    f"pipelined-send {nbytes/pipe_dt/1e9:6.2f} GB/s")
            if args.elastic:
                ps.send(payload, rule="elastic", alpha=0.5).wait()  # warm
                t0 = time.time()
                for _ in range(args.iters):
                    ps.send(payload, rule="elastic", alpha=0.5).wait()
                el_dt = (time.time() - t0) / args.iters
                # The elastic exchange moves the payload BOTH ways
                # (gradient in, delta out) — report the two-way rate.
                line += f"  elastic {2*nbytes/el_dt/1e9:6.2f} GB/s"
            print(line)
            # Server-loop cycle-cost decomposition (VERDICT r4 #8): the
            # measured split behind the loopback numbers — syscall
            # (recv+send) vs memcpy/rule-apply vs mutex contention.
            # The scaling model (docs/ROUND3_NOTES.md) rests on these
            # constants: apply_ns/byte is the per-core shard-work floor,
            # recv/send the TCP stack share that a real NIC replaces.
            st = ps.stats()
            busy = st["recv_s"] + st["lock_wait_s"] + st["apply_s"] \
                + st["send_s"]
            if busy > 0 and st["ops"] > 0:
                def pct(x):
                    return f"{100.0 * x / busy:5.1f}%"

                # Bytes the apply bucket actually touched: send payloads
                # in + receive payloads out (bytes_out minus the 1-byte
                # status per op) — receives run their memcpy in `apply`
                # too (code review r5).  RULE_ELASTIC response payloads
                # are EXCLUDED (ADVICE round 5): the delta reply is
                # written into the same buffer the apply loop already
                # touched once as input, so counting it again would
                # inflate the ns/B denominator for elastic workloads —
                # the server tracks them separately (elastic_bytes_out).
                ebytes = st.get("elastic_bytes_out", 0)
                apply_bytes = (st["bytes_in"] + st["bytes_out"]
                               - st["ops"] - ebytes)
                line = (f"{'':>12s}   server-loop decomposition over "
                        f"{st['ops']} ops ({busy*1e3:.1f} ms busy): "
                        f"recv {pct(st['recv_s'])}  "
                        f"lock-wait {pct(st['lock_wait_s'])}  "
                        f"apply {pct(st['apply_s'])}  "
                        f"send {pct(st['send_s'])}  | "
                        f"apply {st['apply_s']*1e9/max(1,apply_bytes):.2f}"
                        f" ns/B")
                if ebytes:
                    line += f"  (elastic resp {ebytes} B excluded)"
                print(line)
        finally:
            ps.shutdown()


if __name__ == "__main__":
    main()

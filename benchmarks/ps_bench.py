"""Parameter-server throughput micro-benchmark.

Reference analog: the PS half of ``benchmarks/`` (SURVEY.md §3 C14):
send/receive round-trip latency and sustained one-way throughput against the
native shard servers, vs payload size and shard count.

Run: ``python benchmarks/ps_bench.py``
"""

import argparse
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main():
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--sizes", type=str, default="65536,1048576,16777216")
    p.add_argument("--shards", type=int, default=4)
    p.add_argument("--iters", type=int, default=20)
    args = p.parse_args()

    from torchmpi_tpu.parallel.ps import ParameterServer

    for nbytes in (int(s) for s in args.sizes.split(",")):
        tree = {"p": np.zeros(nbytes // 4, np.float32)}
        ps = ParameterServer(tree, num_shards=args.shards)
        try:
            payload = {"p": np.ones(nbytes // 4, np.float32)}
            ps.send(payload, rule="add").wait()  # warm
            t0 = time.time()
            for _ in range(args.iters):
                ps.send(payload, rule="add").wait()
            send_dt = (time.time() - t0) / args.iters
            ps.receive().wait()
            t0 = time.time()
            for _ in range(args.iters):
                ps.receive().wait()
            recv_dt = (time.time() - t0) / args.iters
            # pipelined (async, wait at end) — the prefetch pattern's win
            t0 = time.time()
            hs = [ps.send(payload, rule="add") for _ in range(args.iters)]
            for h in hs:
                h.wait()
            pipe_dt = (time.time() - t0) / args.iters
            print(f"{nbytes:>12d} B x{args.shards} shards  "
                  f"send {nbytes/send_dt/1e9:6.2f} GB/s  "
                  f"recv {nbytes/recv_dt/1e9:6.2f} GB/s  "
                  f"pipelined-send {nbytes/pipe_dt/1e9:6.2f} GB/s")
        finally:
            ps.shutdown()


if __name__ == "__main__":
    main()

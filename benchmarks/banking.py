"""Bank assertable ``*-SUMMARY`` benchmark lines with staleness stamps.

The compare modes of ``collectives_bench.py`` (``--guard-compare``,
``--plan-compare``, ``--dcn-compare``, ``--obs-compare``,
``--faults-compare``, ``--watchdog-compare``, ``--overlap-compare``)
and the recovery bench end in one machine-readable
``KIND-SUMMARY {json}`` line that CI greps and asserts — and then the
evidence evaporates with the log.  This module
is the persistence half: ``--bank`` appends each summary to
``SUMMARY_BANK.json`` at the repo root, NEXT TO the ``BENCH_r*.json``
round records it contextualizes, so a later session (or a reviewer)
can diff today's verdicts against the banked history without re-running
anything.

Staleness discipline (the ``bench.py`` banked-fallback rules): every
record carries its wall-clock stamp, the git commit it measured (when
resolvable), the jax platform (``cpu`` sim vs real ``tpu`` — a sim
number must never be relabeled silicon), and the argv that produced
it.  Consumers compare stamps/commits and treat a mismatch as stale;
nothing here ever overwrites an older record — history is the point.
The bank keeps the newest :data:`KEEP_PER_KIND` records per summary
kind so the file stays reviewable.

Standalone on purpose (stdlib only; jax/git probed best-effort): a
summary must be bankable from any bench entry point without dragging
the bench's stack along.
"""

import json
import os
import subprocess
import sys
import time

KEEP_PER_KIND = 20

round_ = round  # bank_summary's ``round=`` kwarg shadows the builtin

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
DEFAULT_PATH = os.path.join(_REPO, "SUMMARY_BANK.json")


def _git_commit():
    try:
        out = subprocess.run(
            ["git", "rev-parse", "--short", "HEAD"], cwd=_REPO,
            capture_output=True, text=True, timeout=10)
        return out.stdout.strip() or None
    except (OSError, subprocess.SubprocessError):
        return None


def _platform():
    """``cpu`` / ``tpu`` / ... when jax is already up, else None —
    probed, never imported fresh (banking must not initialize a
    backend as a side effect)."""
    jax = sys.modules.get("jax")
    if jax is None:
        return None
    try:
        return jax.devices()[0].platform
    except Exception:  # noqa: BLE001 — absence of evidence, recorded as such
        return None


def load_bank(path=None):
    path = path or DEFAULT_PATH
    if not os.path.exists(path):
        return {}
    with open(path) as f:
        bank = json.load(f)
    if not isinstance(bank, dict):
        raise ValueError(f"{path}: bank must be a JSON object "
                         f"(kind -> records)")
    return bank


def bank_summary(kind, summary, *, path=None, argv=None, round=None):
    """Append one ``kind`` (e.g. ``"GUARD-SUMMARY"``) record to the
    bank, newest first, atomically.  Returns the stamped record.

    ``round`` stamps the bench round the record belongs to (the
    ``BENCH_r<N>`` numbering — ``collectives_bench --round N`` /
    ``bench.py``'s per-round micro-ladder pass both set it); when
    omitted it falls back to ``TORCHMPI_TPU_BENCH_ROUND`` so every
    banking call inside one round agrees without threading the number
    through each CLI.  Consumers (``latest`` callers, CI) read it to
    tell this round's verdict from a stale one."""
    if not isinstance(summary, dict):
        raise TypeError(f"summary must be a dict, got {type(summary)}")
    path = path or DEFAULT_PATH
    if round is None:
        env_round = os.environ.get("TORCHMPI_TPU_BENCH_ROUND")
        round = int(env_round) if env_round else None
    rec = {"stamp": time.strftime("%Y%m%d_%H%M%S"),
           "time": round_(time.time(), 3),
           "commit": _git_commit(),
           "platform": _platform(),
           "argv": list(sys.argv[1:] if argv is None else argv),
           "summary": summary}
    if round is not None:
        rec["round"] = int(round)
    bank = load_bank(path)
    rows = bank.setdefault(kind, [])
    rows.insert(0, rec)
    del rows[KEEP_PER_KIND:]
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        json.dump(bank, f, indent=1, sort_keys=True)
        f.write("\n")
    os.replace(tmp, path)
    return rec


def latest(kind, *, path=None, platform=None):
    """Newest banked record for ``kind`` (optionally filtered to one
    platform — pass ``"tpu"`` to refuse sim numbers), or None."""
    for rec in load_bank(path).get(kind, []):
        if platform is None or rec.get("platform") == platform:
            return rec
    return None

"""Recovery MTTR benchmark: disk-only vs RAM-buddy vs live migration.

The number the hot-state tier (docs/HOTSTATE.md) exists to move: how
much work a seeded kill costs under each recovery story, on the same
deterministic trainer.

- ``baseline``  — the uninterrupted run; its bit-exact loss digest is
  the reference every recovered trajectory must reproduce.
- ``disk``      — the PR 13 posture: checkpoints every ``--save-every``
  steps, kill at ``--kill-at``, ``restart.recover`` walks the disk
  rung.  Steps lost = the save interval's tail, all replayed.
- ``ram``       — ``Config.hotstate="on"``: every completed step
  streams an int8 delta (+ exact sparse correction — reconstruction is
  bit-identical, torchmpi_tpu/hotstate) to the buddy's RAM;
  ``restart.recover`` takes the RAM rung and resumes at the very step
  the kill landed on.  Steps lost = 0, digest unchanged.
- ``migration`` — the planned-preemption drill (``chaos_tool gen
  --migrate``): ``hotstate.migrate`` drains the doomed rank onto a
  spare at a step boundary, the source dies one step later into a gang
  that already let it go.  Zero checkpoint rollback — recovery never
  runs at all.

Each scenario prints a ``scenario`` JSON line (``steps_lost``,
``mttr_s``, ``rollback_steps``, ``digest``, ``digest_match``,
``restored_step``) and the run ends with one assertable line::

    RECOVERY-SUMMARY {"baseline": {...}, "disk": {...}, ...}

MTTR here is the recovery-path wall time on the CPU sim (detect ->
restore -> resume-able); the structural numbers — steps lost, rollback
depth, digest equality, which rung served — are exact and are what CI
asserts (tier1.yml ``recovery-smoke``).  Arm a fault plan via
``TORCHMPI_TPU_FAULTS`` to corrupt the stream (e.g.
``hotstate.recv:corrupt_silent``) and watch the ladder: verify fails,
``tm_hotstate_fallback_disk_total`` counts, and the run degrades to
exactly the disk numbers instead of restoring poisoned state.

Run: ``python benchmarks/recovery_bench.py --steps 40 --save-every 10
--kill-at 27`` (add ``--scenario ram`` etc. to run one; JSONL obs
dumps land wherever ``TORCHMPI_TPU_OBS_DIR`` points).
"""

import argparse
import hashlib
import json
import os
import sys
import tempfile
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np  # noqa: E402

DIM = 96


def _make_state(steps):
    rng = np.random.RandomState(0)
    return {"w": (rng.randn(DIM) * 0.3).astype(np.float32),
            "losses": np.full((steps,), np.nan, np.float32)}


def _step(state, i):
    """One deterministic 'training' step: pure f(state, i), so a replay
    from ANY restored step reproduces the trajectory bit-exactly — the
    property every digest assertion below leans on."""
    w = state["w"]
    drive = np.float32(0.1) * np.tanh(
        w * np.float32(1.0 + (i % 7) * 0.03), dtype=np.float32)
    w2 = (w - drive).astype(np.float32)
    loss = np.float32(np.mean(w2 * w2, dtype=np.float32))
    losses = state["losses"].copy()
    losses[i] = loss
    return {"w": w2, "losses": losses}


def _digest(state):
    h = hashlib.blake2b(digest_size=16)
    h.update(np.ascontiguousarray(state["losses"]).tobytes())
    h.update(np.ascontiguousarray(state["w"]).tobytes())
    return h.hexdigest()


def _fresh_runtime(mpi, **cfg_kw):
    mpi.stop()
    return mpi.init(mpi.Config(**cfg_kw))


def _run_to(state, start, stop, publish=None):
    for i in range(start, stop):
        state = _step(state, i)
        if publish is not None:
            publish(state, i + 1)
    return state


def scenario_baseline(args, mpi):
    _fresh_runtime(mpi)
    state = _run_to(_make_state(args.steps), 0, args.steps)
    return {"steps_lost": 0, "rollback_steps": 0, "mttr_s": 0.0,
            "restored_step": 0, "digest": _digest(state)}


def scenario_disk(args, mpi):
    from torchmpi_tpu.utils import checkpoint, restart

    _fresh_runtime(mpi)
    d = tempfile.mkdtemp(prefix="rec_disk_", dir=args.workdir)
    init_fn = lambda: _make_state(args.steps)  # noqa: E731

    def save(state, step):
        if step % args.save_every == 0:
            checkpoint.save(d, state, step=step)

    state = _run_to(init_fn(), 0, args.kill_at, publish=save)
    # -- the kill: live state is gone; all that survives is the disk --
    del state
    t0 = time.perf_counter()
    state, step = restart.recover(init_fn, d, init_fn())
    mttr = time.perf_counter() - t0
    lost = args.kill_at - step
    state = _run_to(state, step, args.steps, publish=save)
    return {"steps_lost": lost, "rollback_steps": lost, "mttr_s": mttr,
            "restored_step": step, "digest": _digest(state)}


def scenario_ram(args, mpi):
    from torchmpi_tpu import hotstate
    from torchmpi_tpu.utils import checkpoint, restart

    _fresh_runtime(mpi, hotstate="on",
                   hotstate_interval=args.hotstate_interval)
    d = tempfile.mkdtemp(prefix="rec_ram_", dir=args.workdir)
    rep = hotstate.enable(args.world, rank=0, buddies=1)
    init_fn = lambda: _make_state(args.steps)  # noqa: E731

    def publish(state, step):
        rep.publish(state, step)
        if step % args.save_every == 0:
            checkpoint.save(d, state, step=step)

    state = _run_to(init_fn(), 0, args.kill_at, publish=publish)
    del state  # the kill: this process's live state is gone —
    #            the buddy's RAM replicas and the disk tier survive
    t0 = time.perf_counter()
    state, step = restart.recover(init_fn, d, init_fn())
    mttr = time.perf_counter() - t0
    lost = args.kill_at - step
    state = _run_to(state, step, args.steps, publish=publish)
    out = {"steps_lost": lost, "rollback_steps": lost, "mttr_s": mttr,
           "restored_step": step, "digest": _digest(state)}
    hotstate.disable()
    return out


def scenario_migration(args, mpi):
    from torchmpi_tpu import hotstate
    from torchmpi_tpu.utils import checkpoint

    _fresh_runtime(mpi, hotstate="on",
                   hotstate_interval=args.hotstate_interval)
    d = tempfile.mkdtemp(prefix="rec_mig_", dir=args.workdir)
    rep = hotstate.enable(args.world, rank=0, buddies=1)
    init_fn = lambda: _make_state(args.steps)  # noqa: E731
    source, spare = 0, args.world  # the spare joins outside the gang

    def publish(state, step):
        rep.publish(state, step, rank=publish.rank)
        if step % args.save_every == 0:
            checkpoint.save(d, state, step=step)

    publish.rank = source
    state = _run_to(init_fn(), 0, args.kill_at, publish=publish)
    # -- the drill: drain the doomed rank onto the spare at this step
    #    boundary; the seeded kill lands at kill_at + 1, one step after
    #    the source already left (chaos_tool gen --migrate) --
    slot = {}
    t0 = time.perf_counter()
    moved, step = hotstate.migrate(
        source, spare, init_fn(),
        admit=lambda st, s: slot.update(state=st, step=s),
        retire=lambda r: slot.update(retired=r))
    drain = time.perf_counter() - t0
    assert step == args.kill_at and slot["retired"] == source
    publish.rank = spare
    state = _run_to(slot["state"], step, args.steps, publish=publish)
    out = {"steps_lost": 0, "rollback_steps": args.kill_at - step,
           "mttr_s": drain, "restored_step": step,
           "digest": _digest(state)}
    hotstate.disable()
    return out


SCENARIOS = {"baseline": scenario_baseline, "disk": scenario_disk,
             "ram": scenario_ram, "migration": scenario_migration}


def main(argv=None):
    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    p.add_argument("--steps", type=int, default=40)
    p.add_argument("--save-every", type=int, default=10)
    p.add_argument("--kill-at", type=int, default=27,
                   help="last completed step before the kill (pick one "
                        "NOT on a save boundary so disk has work to "
                        "lose)")
    p.add_argument("--world", type=int, default=4)
    p.add_argument("--hotstate-interval", type=int, default=8)
    p.add_argument("--scenario", choices=[*SCENARIOS, "all"],
                   default="all")
    p.add_argument("--workdir", default=None,
                   help="parent for scenario checkpoint dirs "
                        "(default: system tmp)")
    p.add_argument("--bank", action="store_true",
                   help="persist the RECOVERY-SUMMARY line to "
                        "SUMMARY_BANK.json at the repo root "
                        "(benchmarks/banking.py)")
    args = p.parse_args(argv)
    if not (0 < args.kill_at < args.steps):
        p.error("--kill-at must be inside (0, --steps)")

    import torchmpi_tpu as mpi

    names = list(SCENARIOS) if args.scenario == "all" else [args.scenario]
    if "baseline" not in names:
        names.insert(0, "baseline")  # every digest needs the reference
    summary = {}
    for name in names:
        res = SCENARIOS[name](args, mpi)
        res["digest_match"] = (res["digest"]
                               == summary.get("baseline",
                                              res)["digest"])
        summary[name] = res
        print(json.dumps({"scenario": name, **res}))
    mpi.stop()
    print("RECOVERY-SUMMARY " + json.dumps(summary, sort_keys=True))
    if args.bank:
        from benchmarks import banking

        rec = banking.bank_summary("RECOVERY-SUMMARY", summary)
        print(f"# banked RECOVERY-SUMMARY stamp={rec['stamp']} "
              f"commit={rec['commit']} platform={rec['platform']} -> "
              f"{banking.DEFAULT_PATH}", file=sys.stderr)
    # Structural self-checks (CI re-asserts these from the SUMMARY
    # line; failing fast here makes local runs honest too).
    ok = all(r["digest_match"] for r in summary.values())
    if not ok:
        print("error: a recovered trajectory diverged from baseline",
              file=sys.stderr)
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())

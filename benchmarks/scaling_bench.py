"""Data-parallel scaling-efficiency sweep — the shape of BASELINE.md's
headline metric (img/s/chip vs single chip, target >=90% at v5e-64).

Runs the same DP train step on growing sub-meshes (1, 2, 4, ... devices)
with a FIXED per-chip batch (weak scaling, the reference's regime) and
reports throughput per chip and efficiency vs the single-device run.  On a
real pod this measures the real thing; on the simulated CPU mesh it
validates the harness and the collective paths.

Run: ``python benchmarks/scaling_bench.py --devices 8 [--model resnet20]``
"""

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main():
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--devices", type=int, default=0)
    p.add_argument("--model", default="resnet20",
                   choices=["resnet20", "resnet50"])
    p.add_argument("--batch-per-chip", type=int, default=16)
    p.add_argument("--image-size", type=int, default=32)
    p.add_argument("--steps", type=int, default=5)
    p.add_argument("--warmup", type=int, default=2)
    p.add_argument("--backend", default=None)
    p.add_argument("--bucket-sweep", default=None,
                   help="comma-separated gradsync bucket counts to sweep on "
                        "the full mesh (comm/compute-overlap tuning; the "
                        "reference tuned its chunk pipeline the same way)")
    p.add_argument("--json", action="store_true")
    args = p.parse_args()
    if args.devices:
        from torchmpi_tpu.utils.simulation import force_cpu_devices

        force_cpu_devices(args.devices)

    import jax
    import jax.numpy as jnp
    import numpy as np
    import optax
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    import torchmpi_tpu as mpi
    from torchmpi_tpu import recipes
    from torchmpi_tpu.models import ResNet20, ResNet50
    from torchmpi_tpu.utils.metrics import fence

    mpi.init()
    all_devices = list(mpi.world_mesh().devices.flat)
    total = len(all_devices)

    if args.model == "resnet20":
        model, chans, img = ResNet20(), 3, args.image_size
    else:
        model, chans, img = ResNet50(num_classes=100,
                                     dtype=jnp.bfloat16), 3, args.image_size

    variables = model.init(jax.random.PRNGKey(0),
                           jnp.zeros((1, img, img, chans)), train=False)
    # Host copies: the replicating device_put may alias on-device arrays,
    # and the train step donates its inputs — donating an alias would
    # delete this template needed for the next mesh size.
    variables = jax.tree.map(np.asarray, variables)
    tx = optax.sgd(0.1, momentum=0.9)

    sizes = [1 << i for i in range(total.bit_length()) if (1 << i) <= total]
    if sizes[-1] != total:
        sizes.append(total)  # always measure the full slice
    base_per_chip = None
    for n in sizes:
        mesh = Mesh(np.asarray(all_devices[:n]).reshape(1, n),
                    (mpi.DCN_AXIS, mpi.ICI_AXIS))
        dp = recipes.make_bn_dp_train_step(model, tx, mesh=mesh,
                                           backend=args.backend)
        params, opt_state, batch_stats = recipes.replicate_bn_state(
            variables["params"], tx.init(variables["params"]),
            variables["batch_stats"], mesh=mesh)
        batch = args.batch_per_chip * n
        shard = NamedSharding(mesh, P((mpi.DCN_AXIS, mpi.ICI_AXIS)))
        X = jax.device_put(np.random.RandomState(0).rand(
            batch, img, img, chans).astype(np.float32), shard)
        Y = jax.device_put(np.random.RandomState(1).randint(
            0, 10, size=batch).astype(np.int32), shard)
        for i in range(args.warmup + args.steps):
            if i == args.warmup:
                fence(params)
                t0 = time.time()
            params, opt_state, batch_stats, loss = dp(params, opt_state,
                                                      batch_stats, X, Y)
        fence(loss)
        dt = time.time() - t0
        per_chip = args.steps * batch / dt / n
        if base_per_chip is None:
            base_per_chip = per_chip
        eff = per_chip / base_per_chip
        rec = {"devices": n, "img_s_per_chip": round(per_chip, 2),
               "efficiency": round(eff, 4),
               "step_ms": round(dt / args.steps * 1e3, 1)}
        print(json.dumps(rec) if args.json else
              f"n={n:4d}  {per_chip:9.2f} img/s/chip  "
              f"eff {eff*100:6.1f}%  step {rec['step_ms']:8.1f} ms")

    # Bucket sweep on the full mesh: more buckets = earlier allreduce
    # launches during backward (more overlap) but more collective launches;
    # the optimum is hardware-dependent, measured here, defaulted in config.
    if args.bucket_sweep:
        mesh = Mesh(np.asarray(all_devices).reshape(1, total),
                    (mpi.DCN_AXIS, mpi.ICI_AXIS))
        batch = args.batch_per_chip * total
        shard = NamedSharding(mesh, P((mpi.DCN_AXIS, mpi.ICI_AXIS)))
        X = jax.device_put(np.random.RandomState(0).rand(
            batch, img, img, chans).astype(np.float32), shard)
        Y = jax.device_put(np.random.RandomState(1).randint(
            0, 10, size=batch).astype(np.int32), shard)
        for nb in [int(b) for b in args.bucket_sweep.split(",")]:
            dp = recipes.make_bn_dp_train_step(model, tx, mesh=mesh,
                                               backend=args.backend,
                                               n_buckets=nb)
            params, opt_state, batch_stats = recipes.replicate_bn_state(
                variables["params"], tx.init(variables["params"]),
                variables["batch_stats"], mesh=mesh)
            for i in range(args.warmup + args.steps):
                if i == args.warmup:
                    fence(params)
                    t0 = time.time()
                params, opt_state, batch_stats, loss = dp(
                    params, opt_state, batch_stats, X, Y)
            fence(loss)
            dt = time.time() - t0
            rec = {"buckets": nb, "devices": total,
                   "img_s_per_chip": round(args.steps * batch / dt / total, 2),
                   "step_ms": round(dt / args.steps * 1e3, 1)}
            print(json.dumps(rec) if args.json else
                  f"buckets={nb:3d}  {rec['img_s_per_chip']:9.2f} "
                  f"img/s/chip  step {rec['step_ms']:8.1f} ms")
    mpi.stop()


if __name__ == "__main__":
    main()

"""Persistent-memory footprint per device across the DP state-sharding
ladder: replicated DP -> ZeRO-1 -> ZeRO-3 -> annotation-driven FSDP.

The reference's DP replicated everything on every rank (SURVEY.md §3.3);
the TPU rebuild's ladder trades collective traffic for per-device
persistent memory.  This bench MEASURES the footprint rather than claiming
it: it places the model + Adam state each way on a real (or simulated)
mesh and sums the bytes each strategy physically pins on device 0 —
addressable shard bytes, not theory.

Run: ``python benchmarks/memory_bench.py --devices 8 [--model resnet20]``
"""

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def _bytes_on(dev, tree) -> int:
    import jax

    total = 0
    for leaf in jax.tree.leaves(tree):
        for sh in getattr(leaf, "addressable_shards", []):
            if sh.device == dev:
                total += sh.data.nbytes
        if not hasattr(leaf, "addressable_shards"):
            total += getattr(leaf, "nbytes", 0)
    return total


def main():
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--devices", type=int, default=0)
    p.add_argument("--model", default="resnet20",
                   choices=["lenet", "resnet20"])
    p.add_argument("--json", action="store_true")
    args = p.parse_args()
    if args.devices:
        from torchmpi_tpu.utils.simulation import force_cpu_devices

        force_cpu_devices(args.devices)

    import jax
    import jax.numpy as jnp
    import optax

    import torchmpi_tpu as mpi
    from torchmpi_tpu.models import LeNet, ResNet20
    from torchmpi_tpu.parallel import zero

    mesh = mpi.init()
    n = mesh.devices.size
    dev0 = list(mesh.devices.flat)[0]
    tx = optax.adam(1e-3)  # 2x params of state: makes the ladder vivid

    if args.model == "lenet":
        model = LeNet(num_classes=10)
        variables = model.init(jax.random.PRNGKey(0),
                               jnp.zeros((1, 28, 28, 1)))
        params, bn = variables["params"], None
    else:
        model = ResNet20(num_classes=10)
        variables = model.init(jax.random.PRNGKey(0),
                               jnp.zeros((1, 32, 32, 3)), train=False)
        params, bn = variables["params"], variables["batch_stats"]

    rows = []

    def row(strategy, p_tree, o_tree):
        pb, ob = _bytes_on(dev0, p_tree), _bytes_on(dev0, o_tree)
        rows.append({
            "strategy": strategy, "devices": n,
            "params_kib_per_device": round(pb / 1024, 1),
            "opt_state_kib_per_device": round(ob / 1024, 1),
            "total_kib_per_device": round((pb + ob) / 1024, 1),
        })

    # 1. Replicated DP (the reference's regime): full copy everywhere.
    p_r = mpi.nn.synchronize_parameters(params, mesh=mesh)
    o_r = mpi.nn.synchronize_parameters(tx.init(params), mesh=mesh)
    row("replicated_dp", p_r, o_r)

    # 2. ZeRO-1: optimizer state sharded, params replicated.
    o_1 = zero.init(params, tx, mesh=mesh)
    row("zero1", p_r, o_1)

    # 3. ZeRO-3: params AND state live as flat 1/n shards between steps.
    p_3 = zero.shard_params(params, mesh=mesh)
    row("zero3", p_3, o_1)

    # 4. Annotation-driven FSDP: per-parameter GSPMD shardings (leaves
    #    with no n-divisible dim stay replicated — measured, not assumed).
    #    make_fsdp_train_step takes plain (BatchNorm-free) models, so this
    #    rung runs for lenet and is explicitly skipped otherwise.
    if bn is None:
        _, p_f, o_f = mpi.recipes.make_fsdp_train_step(model, tx, params,
                                                       mesh=mesh)
        row("fsdp", p_f, o_f)
    else:
        print(f"fsdp rung SKIPPED: make_fsdp_train_step takes plain "
              f"models and {args.model} carries batch_stats — run with "
              f"--model lenet for the full ladder", file=sys.stderr)

    base = rows[0]["total_kib_per_device"]
    for r in rows:
        r["vs_replicated"] = round(r["total_kib_per_device"] / base, 3)

    if args.json:  # sibling-bench convention: JSON only when asked
        for r in rows:
            print(json.dumps(r), flush=True)
    else:
        for r in rows:
            print(f"{r['strategy']:>14}: params "
                  f"{r['params_kib_per_device']:>9.1f} KiB  opt "
                  f"{r['opt_state_kib_per_device']:>9.1f} KiB  total "
                  f"{r['total_kib_per_device']:>9.1f} KiB/device  "
                  f"({r['vs_replicated']:.3f}x)")
        print(f"\nreplicated {base:.0f} KiB/device -> "
              f"best {min(r['total_kib_per_device'] for r in rows):.0f} "
              f"KiB/device on {n} devices")
    mpi.stop()


if __name__ == "__main__":
    main()

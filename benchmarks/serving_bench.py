"""Continuous-batching vs static-batching serving benchmark
(ISSUE 9 acceptance; docs/SERVING.md).

One synthetic Poisson arrival trace (fixed prompt length, MIXED decode
lengths — the traffic shape continuous batching exists for) served two
ways over the same checkpoint:

- **continuous** — ``torchmpi_tpu.serving.Server``: iteration-level
  admission into slot blocks, immediate retirement, virtual clock
  advanced by each tick's measured wall time;
- **static** — the classic offline semantics over
  ``models.generate.generate``: wait until a full batch has ARRIVED,
  run every member to the batch's longest decode length, deliver
  results at batch completion (which is when the offline API returns
  them — its TTFT is honestly its completion time).

Reported (the ``SERVING-SUMMARY`` line CI asserts on):

- token throughput = useful tokens / summed compute seconds for each
  system (idle queue gaps excluded from both) — continuous wins by not
  burning steps on retired rows and not idling short rows to the batch
  straggler;
- mean TTFT on the shared virtual clock (arrival -> first token);
- ``bitwise`` — every request's continuous tokens equal the offline
  ``generate`` oracle token for token (greedy);
- with ``--chaos``: a deterministic fault plan hard-kills one of two
  replicas mid-trace; the run must still complete, re-route > 0
  sessions, and stay token-exact (``CHAOS-SUMMARY`` line).

Further phases (each with its own asserted ``*-SUMMARY`` line):

- ``--tp N`` — a replica as an N-device TP mesh slice
  (``Server.sharded``): continuous batching over the sharded model vs
  the PR 9 fallback (static batching through the same TP engine), both
  bitwise vs the offline ``tp_generate`` oracle
  (``TP-SERVING-SUMMARY``);
- ``--sample`` — temperature/top-k/top-p with per-request seeds:
  streams must be bitwise-identical across replica layouts and re-runs,
  and distinct from greedy (``SAMPLE-SUMMARY``);
- ``--spec`` — draft-K/verify-once speculative decoding
  (``--spec-draft`` ngram | model): token streams bitwise vs non-spec
  at the same seeds (greedy AND sampled), TTFT/ITL must win on the
  work-unit clock (``SPEC-SUMMARY``);
- ``--buckets B`` — pow-2 bucketed prefill on a mixed-prompt-length
  trace: compile count == bucket count (< distinct lengths), streams
  bitwise unchanged (``BUCKET-SUMMARY``);
- ``--prefix`` — radix prefix-sharing KV cache over the slot pool: a
  shared system prompt is prefilled ONCE, later arrivals assemble the
  cached blocks and extend from the fork point.  Streams must be
  bitwise-identical cache on vs off (greedy additionally vs the
  offline oracle), hits > 0, and the prefilled-token count must drop
  (``PREFIX-SUMMARY``);
- ``--surge`` — deterministic Poisson rate-step trace (inter-arrival
  divided by ``--surge-x`` mid-trace): the SLO admission gate must
  SHED typed rejections instead of letting p99 TTFT collapse, the
  autoscaler must add a replica under the sustained queue, and a
  replica hard-kill DURING the surge must drain + re-route and stay
  token-exact (``SURGE-SUMMARY``);
- ``--bank`` — persist every emitted summary to ``SUMMARY_BANK.json``
  (stamped, git-pinned, keep-last-20 — ``benchmarks/banking.py``).

Exits nonzero unless continuous >= --min-speedup x static throughput
AND continuous mean TTFT < static AND bitwise holds (and every phase
run passed its own verdict).  Run under obs
(``TORCHMPI_TPU_OBS=metrics``) to get the ``tm_serving_*`` SLO
histograms; ``scripts/obs_tool.py slo`` renders them.

Usage::

    JAX_PLATFORMS=cpu TORCHMPI_TPU_OBS=metrics \
        python benchmarks/serving_bench.py --requests 48 --chaos \
            --sample --spec --buckets 8 --tp 2
"""

import argparse
import json
import os
import sys
import tempfile
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


def build_trace(rng, n, tp, lens, inter_arrival_s, vocab, *,
                sampling=None, prompt_lens=None, id_prefix="q"):
    import numpy as np

    from torchmpi_tpu import serving

    if prompt_lens is None:
        prompts = list(rng.randint(0, vocab, size=(n, tp))
                       .astype(np.int32))
    else:
        prompts = [rng.randint(
            0, vocab, size=(int(prompt_lens[i % len(prompt_lens)]),)
        ).astype(np.int32) for i in range(n)]
    max_news = [int(lens[i % len(lens)]) for i in
                rng.permutation(n)]
    gaps = rng.exponential(inter_arrival_s, size=n)
    arrivals = np.cumsum(gaps)
    kw = dict(sampling or {})
    seed0 = int(kw.pop("seed0", 0))
    return [serving.Request(f"{id_prefix}{i}", prompts[i],
                            max_new=max_news[i],
                            arrival_s=float(arrivals[i]),
                            seed=seed0 + i if sampling is not None else 0,
                            **kw)
            for i in range(n)]


def clone_reqs(reqs):
    """Fresh Request copies (runs mutate the result fields) — sampling
    knobs carried over so re-runs are seed-identical."""
    from torchmpi_tpu import serving

    return [serving.Request(r.rid, r.prompt, r.max_new, eos_id=r.eos_id,
                            arrival_s=r.arrival_s,
                            temperature=r.temperature, top_k=r.top_k,
                            top_p=r.top_p, seed=r.seed)
            for r in reqs]


def _maybe_bank(args, kind, line):
    """Persist one ``*-SUMMARY key=value ...`` line to
    SUMMARY_BANK.json under ``--bank`` (parsed to a record dict —
    numbers as numbers — so banked history diffs field-wise)."""
    if not getattr(args, "bank", False) or line is None:
        return
    from benchmarks import banking

    head, _, rest = line.partition(" ")
    summary = {"line": head}
    for kv in rest.split():
        k, _, v = kv.partition("=")
        try:
            summary[k] = int(v)
        except ValueError:
            try:
                summary[k] = float(v)
            except ValueError:
                summary[k] = v
    rec = banking.bank_summary(kind, summary)
    print(f"# banked {kind} stamp={rec['stamp']} "
          f"commit={rec['commit']} platform={rec['platform']} -> "
          f"{banking.DEFAULT_PATH}", file=sys.stderr)


def offline_oracle(model, params, reqs):
    """Per-request offline greedy decode — THE token reference."""
    import numpy as np

    from torchmpi_tpu.models import generate

    out = {}
    for r in reqs:
        toks = np.asarray(generate(
            model, params, np.asarray(r.prompt).reshape(1, -1),
            steps=r.max_new))
        out[r.rid] = toks[0, len(r.prompt):].tolist()
    return out


def run_static(model, params, reqs, batch_size, slot_tokens,
               engine=None):
    """Static-batch SEMANTICS through the same engine mechanics: wait
    until a full batch has arrived, admit it whole, run every member to
    the batch's longest decode (each tick steps all ``batch_size`` slot
    rows whether or not a short row already finished — exactly the
    run-to-longest cost), deliver at batch completion, admit nothing
    mid-batch.

    Same compiled ``[S, 1]`` step and prefill executables as the
    continuous server (same slots, same model clone), so the comparison
    isolates the SCHEDULING property — iteration-level admission +
    early retirement — instead of dispatch mechanics.  (The
    fully-offline ``models.generate`` scan amortizes its whole decode
    inside one XLA dispatch and is the TOKEN oracle, not the latency
    baseline: no server can batch requests that have not arrived.)

    The clock is the same work-unit clock the continuous run uses (one
    unit = one prefill or one step invocation), so both schedules are
    deterministic and the throughput ratio is a pure invocation-count
    ratio of IDENTICAL executables — immune to container noise; wall
    time is measured alongside as the per-unit cost evidence.

    ``engine`` overrides the dense engine — the TP phase passes a
    pre-built :class:`~torchmpi_tpu.serving.TPReplicaEngine` so the
    static baseline runs the SAME sharded executables.

    Returns (per-rid tokens, work_units, wall_s, mean_ttft_units)."""
    import numpy as np

    from torchmpi_tpu import serving

    ordered = clone_reqs(sorted(reqs, key=lambda r: r.arrival_s))
    eng = engine if engine is not None else serving.ReplicaEngine(
        model, params, name="static", slots=batch_size,
        slot_tokens=slot_tokens)
    tokens, clock, ttfts = {}, 0.0, []
    wall0 = time.monotonic()
    for i in range(0, len(ordered), batch_size):
        batch = ordered[i:i + batch_size]
        start = max(clock, max(r.arrival_s for r in batch))
        units0 = eng.stats["prefills"] + eng.stats["steps"]
        finished = []
        for r in batch:
            sess, done = eng.admit(r)
            if done:
                finished.append(sess)
        while eng.active:
            _, fin = eng.step()
            finished.extend(fin)
        clock = start + (eng.stats["prefills"] + eng.stats["steps"]
                         - units0)
        for sess in finished:
            tokens[sess.request.rid] = list(sess.emitted)
            ttfts.append(clock - sess.request.arrival_s)
    wall = time.monotonic() - wall0
    work = eng.stats["prefills"] + eng.stats["steps"]
    return tokens, work, wall, float(np.mean(ttfts))


def run_sample(model, params, args, rng, vocab):
    """Sampled decode (temperature/top-k/top-p, per-request seeds):
    streams must be bitwise-identical across replica layouts and
    re-runs — sampling keys each token on fold_in(PRNGKey(seed), i),
    never on slot/replica/neighbors — and distinct from greedy."""
    import numpy as np

    from torchmpi_tpu import serving

    n = max(16, args.requests // 2)
    inter = float(np.mean(args.lens)) / (args.load * args.slots)
    reqs = build_trace(rng, n, args.prompt_len, args.lens, inter, vocab,
                       sampling=dict(temperature=0.8, top_k=20,
                                     top_p=0.9, seed0=args.seed + 100),
                       id_prefix="s")
    oracle = offline_oracle(model, params, reqs)  # greedy reference
    streams = []
    for replicas in (1, 2, 1):
        run = clone_reqs(reqs)
        srv = serving.Server(model, params, replicas=replicas,
                             slots=args.slots,
                             slot_tokens=args.slot_tokens)
        done = srv.run_trace(run, unit_seconds=1.0)
        assert len(done) == len(run)
        streams.append({r.rid: list(r.tokens) for r in run})
    repro = streams[0] == streams[1] == streams[2]
    distinct = any(streams[0][r.rid] != oracle[r.rid] for r in reqs)
    ok = repro and distinct
    line = (f"SAMPLE-SUMMARY requests={n} layouts=1,2,1 "
            f"bitwise_repro={'ok' if repro else 'FAIL'} "
            f"distinct_from_greedy={'ok' if distinct else 'FAIL'} "
            f"verdict={'sampled-reproducible' if ok else 'FAIL'}")
    print(line)
    return ok, line


def run_spec(model, params, args, rng, vocab):
    """Speculative decoding: the spec stream must be bitwise the
    non-spec stream at the same seeds (greedy AND sampled traces), and
    must WIN mean TTFT + ITL on the work-unit clock — an accepted draft
    lands extra tokens for the same 1-unit verify forward."""
    import numpy as np

    from torchmpi_tpu import serving

    if args.spec_draft == "model":
        import jax
        import jax.numpy as jnp

        from torchmpi_tpu.models import TransformerLM

        dm = TransformerLM(vocab=vocab, embed=16, depth=1, num_heads=2,
                           head_dim=8, max_len=args.slot_tokens,
                           pos_emb="rope")
        dp = dm.init(jax.random.PRNGKey(args.seed + 3),
                     jnp.zeros((1, args.prompt_len),
                               jnp.int32))["params"]
        draft = serving.ModelDraft(dm, dp)
    else:
        draft = serving.NgramDraft()

    inter = float(np.mean(args.lens)) / (args.load * args.slots)
    greedy = build_trace(rng, args.requests, args.prompt_len, args.lens,
                         inter, vocab, id_prefix="g")

    def run(reqs, **kw):
        srv = serving.Server(model, params, replicas=1,
                             slots=args.slots,
                             slot_tokens=args.slot_tokens, **kw)
        out = clone_reqs(reqs)
        done = srv.run_trace(out, unit_seconds=1.0)
        assert len(done) == len(out)
        return out, srv.router.replicas[0]

    base, _ = run(greedy)
    spec, eng = run(greedy, spec_k=args.spec_k, draft=draft)
    bitwise = {r.rid: r.tokens for r in base} == \
        {r.rid: r.tokens for r in spec}

    def lat(reqs):
        ttft = float(np.mean([r.ttft_s for r in reqs]))
        itl = float(np.mean([(r.finish_s - r.arrival_s - r.ttft_s)
                             / max(1, len(r.tokens) - 1)
                             for r in reqs]))
        return ttft, itl

    b_ttft, b_itl = lat(base)
    s_ttft, s_itl = lat(spec)
    acc = eng.stats["spec_accepted"] / max(1, eng.stats["spec_drafted"])

    sampled = build_trace(rng, max(16, args.requests // 2),
                          args.prompt_len, args.lens, inter, vocab,
                          sampling=dict(temperature=0.8, top_k=20,
                                        top_p=0.9,
                                        seed0=args.seed + 200),
                          id_prefix="gs")
    sb, _ = run(sampled)
    ss, _ = run(sampled, spec_k=args.spec_k, draft=draft)
    bitwise_sampled = {r.rid: r.tokens for r in sb} == \
        {r.rid: r.tokens for r in ss}

    ok = (bitwise and bitwise_sampled and s_ttft < b_ttft
          and s_itl < b_itl)
    line = (f"SPEC-SUMMARY draft={args.spec_draft} k={args.spec_k} "
            f"requests={len(greedy)} acceptance={acc:.2f} "
            f"ttft_u={s_ttft:.1f}/{b_ttft:.1f} "
            f"itl_u={s_itl:.2f}/{b_itl:.2f} "
            f"bitwise={'ok' if bitwise else 'FAIL'} "
            f"bitwise_sampled={'ok' if bitwise_sampled else 'FAIL'} "
            f"verdict={'spec-wins' if ok else 'FAIL'}")
    print(line)
    return ok, line


def run_buckets(model, params, args, rng, vocab):
    """Mixed prompt lengths: bucketed prefill compiles O(buckets)
    executables instead of one per distinct length, with every stream
    bitwise unchanged (causality + true-length logit slice)."""
    import numpy as np

    from torchmpi_tpu import serving

    plens = [3, 5, 6, 9, 11, 17]
    reqs = build_trace(rng, max(24, args.requests // 2), 0, [4, 8],
                       0.02, vocab, prompt_lens=plens, id_prefix="b")
    oracle = offline_oracle(model, params, reqs)

    def run(bucket):
        srv = serving.Server(model, params, replicas=1,
                             slots=args.slots,
                             slot_tokens=args.slot_tokens,
                             prefill_bucket=bucket)
        out = clone_reqs(reqs)
        done = srv.run_trace(out, unit_seconds=1.0)
        assert len(done) == len(out)
        eng = srv.router.replicas[0]
        return ({r.rid: r.tokens for r in out},
                eng.stats["prefill_compiles"])

    plain_toks, plain_compiles = run(0)
    buck_toks, buck_compiles = run(args.buckets)
    expect = {min(max(args.buckets, 1 << max(0, L - 1).bit_length()),
                  args.slot_tokens) for L in plens}
    distinct = len(set(plens))
    bitwise = (plain_toks == buck_toks
               and all(plain_toks[r.rid] == oracle[r.rid]
                       for r in reqs))
    ok = (bitwise and buck_compiles == len(expect)
          and plain_compiles == distinct
          and buck_compiles < plain_compiles)
    line = (f"BUCKET-SUMMARY bucket={args.buckets} "
            f"distinct_lens={distinct} compiles_plain={plain_compiles} "
            f"compiles_bucketed={buck_compiles} "
            f"expected_buckets={len(expect)} "
            f"bitwise={'ok' if bitwise else 'FAIL'} "
            f"verdict={'bucketed-compiles-ok' if ok else 'FAIL'}")
    print(line)
    return ok, line


def run_tp(args, rng, vocab):
    """One replica as an ``--tp``-device TP mesh slice
    (``Server.sharded``): continuous batching vs static batching
    through the SAME sharded engine class, both bitwise vs the offline
    ``tp_generate`` oracle."""
    import importlib

    import numpy as np

    import jax
    from jax.sharding import Mesh

    from torchmpi_tpu import serving
    from torchmpi_tpu.serving.tp_engine import TPReplicaEngine

    if len(jax.devices()) < args.tp:
        print(f"TP-SERVING-SUMMARY tp={args.tp} verdict=SKIP "
              f"(only {len(jax.devices())} devices)")
        return True, None
    tpg = importlib.import_module("torchmpi_tpu.models.tp_generate")
    tparams = tpg.init_tp_lm(jax.random.PRNGKey(args.seed + 2),
                             vocab=vocab, embed=args.embed, depth=2,
                             num_heads=4, head_dim=8)
    mesh = Mesh(np.asarray(jax.devices()[:args.tp]), ("model",))

    n = min(args.requests, 32)
    inter = float(np.mean(args.lens)) / (args.load * args.slots)
    reqs = build_trace(rng, n, args.prompt_len, args.lens, inter,
                       vocab, id_prefix="t")
    oracle = {}
    for r in reqs:
        toks = np.asarray(tpg.tp_generate(
            tparams, np.asarray(r.prompt).reshape(1, -1),
            steps=r.max_new, mesh=mesh, axis="model", num_heads=4))
        oracle[r.rid] = toks[0, len(r.prompt):].tolist()

    def sharded():
        return serving.Server.sharded(
            tparams, tp=args.tp, num_heads=4,
            slot_tokens=args.slot_tokens, replicas=1, slots=args.slots)

    # Warmup: pay the shard_map prefill/step compiles off the clock.
    sharded().run_trace(clone_reqs(reqs[:args.slots]))

    run = clone_reqs(reqs)
    srv = sharded()
    wall0 = time.monotonic()
    done = srv.run_trace(run, unit_seconds=1.0)
    cont_wall = time.monotonic() - wall0
    eng = srv.router.replicas[0]
    cont_work = eng.stats["prefills"] + eng.stats["steps"]
    bitwise = (len(done) == len(run)
               and all(r.tokens == oracle[r.rid] for r in run))

    static_eng = TPReplicaEngine(
        tparams, mesh=mesh, axis="model", num_heads=4, name="tpstatic",
        slots=args.slots, slot_tokens=args.slot_tokens)
    static_toks, static_work, static_wall, _ = run_static(
        None, None, reqs, args.slots, args.slot_tokens,
        engine=static_eng)
    bitwise = bitwise and all(static_toks[r.rid] == oracle[r.rid]
                              for r in reqs)
    speedup = static_work / cont_work
    n_tok = sum(len(oracle[r.rid]) for r in reqs)
    ok = bitwise and speedup >= args.min_speedup
    line = (f"TP-SERVING-SUMMARY tp={args.tp} requests={n} "
            f"tokens={n_tok} cont_work={cont_work} "
            f"static_work={static_work} speedup={speedup:.2f} "
            f"cont_tok_s={n_tok / cont_wall:.1f} "
            f"static_tok_s={n_tok / static_wall:.1f} "
            f"bitwise={'ok' if bitwise else 'FAIL'} verdict="
            f"{'tp-continuous-beats-static' if ok else 'FAIL'}")
    print(line)
    return ok, line


def run_chaos(model, params, args, rng, vocab):
    """Two replicas, deterministic mid-trace hard kill: the server must
    drain + re-route and stay token-exact."""
    import torchmpi_tpu as mpi
    from torchmpi_tpu import serving

    plan = {"version": 1, "seed": args.seed, "note": "serving kill",
            "rules": [{"site": "serving.replica", "kind": "fail",
                       "prob": 1.0, "after": args.chaos_after,
                       "max_hits": 1}]}
    path = os.path.join(tempfile.mkdtemp(prefix="serving_chaos_"),
                        "plan.json")
    with open(path, "w") as f:
        json.dump(plan, f)
    reqs = build_trace(rng, args.requests, args.prompt_len,
                       args.lens, 0.01, vocab)
    oracle = offline_oracle(model, params, reqs)
    mpi.set_config(faults=path)
    try:
        srv = serving.Server(model, params, replicas=2,
                             slots=args.slots,
                             slot_tokens=args.slot_tokens)
        done = srv.run_trace(reqs, tick_seconds=0.005)
    finally:
        mpi.set_config(faults="off")
    dead = [e.name for e in srv.router.replicas if e.dead]
    rerouted = sum(r.reroutes for r in reqs)
    ok = (len(done) == len(reqs) and len(dead) == 1 and rerouted > 0
          and all(r.tokens == oracle[r.rid] for r in reqs))
    line = (f"CHAOS-SUMMARY requests={len(reqs)} dead={','.join(dead)} "
            f"rerouted={rerouted} "
            f"bitwise={'ok' if ok else 'FAIL'} "
            f"verdict={'drain-reroute-ok' if ok else 'FAIL'}")
    print(line)
    return ok, rerouted, line


def run_prefix(model, params, args, rng, vocab):
    """Radix prefix-sharing KV cache: every request opens with the same
    24-token system prompt, so the cache-on server prefills the shared
    blocks ONCE and later arrivals assemble them + extend from the fork
    point.  Streams must be bitwise cache on == cache off (greedy
    additionally == the offline ``generate`` oracle), hits > 0, the
    prefilled-token count must drop, and the block ledger must come
    back clean (every cached block at refcount 1, zero leaks)."""
    import numpy as np

    from torchmpi_tpu import serving

    # Dedicated stream: the phase trace (and verdict) must not depend
    # on which earlier phases consumed draws from the shared rng.
    rng = np.random.RandomState(args.seed + 5)
    n = max(16, args.requests // 4)
    shared = rng.randint(0, vocab, size=(24,)).astype(np.int32)
    arrivals = np.cumsum(rng.exponential(0.02, size=n))
    reqs = []
    for i in range(n):
        tail = rng.randint(0, vocab, size=(3 + i % 6,)).astype(np.int32)
        kw = (dict(temperature=0.8, top_k=20, seed=args.seed + 300 + i)
              if i % 2 else {})
        reqs.append(serving.Request(
            f"p{i}", np.concatenate([shared, tail]),
            max_new=int([4, 8][i % 2]), arrival_s=float(arrivals[i]),
            **kw))
    oracle = offline_oracle(model, params,
                            [r for r in reqs if r.temperature is None])

    def timed(cache):
        def mk():
            return serving.Server(model, params, replicas=1,
                                  slots=args.slots,
                                  slot_tokens=args.slot_tokens,
                                  prefix_cache=cache, prefix_block=8)

        mk().run_trace(clone_reqs(reqs), unit_seconds=1.0)  # warm
        srv, out = mk(), clone_reqs(reqs)
        wall0 = time.monotonic()
        done = srv.run_trace(out, unit_seconds=1.0)
        wall = time.monotonic() - wall0
        assert len(done) == len(out)
        return {r.rid: r.tokens for r in out}, \
            srv.router.replicas[0], wall

    off_toks, off_eng, off_wall = timed(0)
    on_toks, on_eng, on_wall = timed(16)
    bitwise = (on_toks == off_toks
               and all(on_toks[rid] == oracle[rid] for rid in oracle))
    hits = on_eng.stats["prefix_hits"]
    pt_on = on_eng.stats["prefill_tokens"]
    pt_off = off_eng.stats["prefill_tokens"]
    leaks = sum(1 for node in on_eng._prefix._nodes
                if on_eng.pool.block_refcount(node.bid) != 1)
    leaks += on_eng.pool.blocks_in_use - on_eng._prefix.n_nodes
    n_tok = sum(len(t) for t in off_toks.values())
    ok = (bitwise and hits > 0 and pt_on < pt_off and leaks == 0)
    line = (f"PREFIX-SUMMARY requests={n} shared_tokens=24 "
            f"hits={hits} misses={on_eng.stats['prefix_misses']} "
            f"prefill_tok_on={pt_on} prefill_tok_off={pt_off} "
            f"saved_pct={100 * (1 - pt_on / pt_off):.0f} "
            f"tok_s_on={n_tok / on_wall:.1f} "
            f"tok_s_off={n_tok / off_wall:.1f} "
            f"leaks={leaks} bitwise={'ok' if bitwise else 'FAIL'} "
            f"verdict={'prefix-cache-wins' if ok else 'FAIL'}")
    print(line)
    return ok, line


def run_surge(model, params, args, rng, vocab):
    """10x admission-rate step: without the gate the queue (and p99
    TTFT) grows without bound for the surge cohort; with the SLO gate
    armed the server SHEDS typed rejections at the door and p99 of the
    SERVED requests stays bounded.  The autoscaler must add a replica
    under the sustained queue, and a replica hard-kill DURING the surge
    must drain + re-route with every served greedy stream still equal
    to the offline oracle."""
    import numpy as np

    import torchmpi_tpu as mpi
    from torchmpi_tpu import serving

    # Dedicated stream (see run_prefix): calibration p95 — and so the
    # SLO target — must not move when other phases are toggled.
    rng = np.random.RandomState(args.seed + 7)
    mean_len = float(np.mean(args.lens))
    base = mean_len / (args.load * args.slots)
    # The surge cohort must OUTLAST the admission gate's observation
    # lag: p95 TTFT only climbs as first tokens land (at the service
    # rate), so arrivals have to still be flowing when the measured
    # p95 crosses the target — otherwise there is nothing left to
    # shed.  Sheds are free (no compute), so a long surge is cheap.
    n_base = max(16, args.requests // 2)
    n_surge = max(128, 3 * args.requests)
    n = n_base + n_surge
    gaps = np.concatenate([
        rng.exponential(base, size=n_base),
        rng.exponential(base / args.surge_x, size=n_surge)])
    arrivals = np.cumsum(gaps)
    max_news = [int(args.lens[i % len(args.lens)])
                for i in rng.permutation(n)]
    reqs = [serving.Request(
        f"u{i}", rng.randint(0, vocab, size=(args.prompt_len,))
        .astype(np.int32), max_new=max_news[i],
        arrival_s=float(arrivals[i])) for i in range(n)]

    def run(reqs_in, replicas=1, **kw):
        srv = serving.Server(model, params, replicas=replicas,
                             slots=args.slots,
                             slot_tokens=args.slot_tokens, **kw)
        out = clone_reqs(reqs_in)
        done = srv.run_trace(out, unit_seconds=1.0)
        return out, done, srv

    # Calibrate the SLO from the base-rate cohort alone: the target is
    # 2x its p95 TTFT — deterministic (work-unit clock), so the verdict
    # never depends on container wall noise.
    cal, _, _ = run(reqs[:n_base])
    p95_base = float(np.percentile([r.ttft_s for r in cal], 95))
    target_us = 2.0 * max(p95_base, 1.0) * 1e6

    off, off_done, _ = run(reqs)
    p99_off = float(np.percentile([r.ttft_s for r in off], 99))

    on, on_done, srv_on = run(reqs, slo_ttft_us=target_us, autoscale=2)
    served = [r for r in on if not r.shed]
    shed = [r for r in on if r.shed]
    p99_on = float(np.percentile([r.ttft_s for r in served], 99))
    events = list(srv_on._fleet.events)
    typed = all(isinstance(r.error, str) and "slo" in r.error
                for r in shed)

    # Replica hard-kill DURING the surge, gate still armed: the fleet
    # must shed + re-route + finish, with every SERVED greedy stream
    # still bitwise the offline oracle.
    oracle = offline_oracle(model, params, reqs)
    plan = {"version": 1, "seed": args.seed, "note": "surge kill",
            "rules": [{"site": "serving.replica", "kind": "fail",
                       "prob": 1.0, "after": args.chaos_after,
                       "max_hits": 1}]}
    path = os.path.join(tempfile.mkdtemp(prefix="serving_surge_"),
                        "plan.json")
    with open(path, "w") as f:
        json.dump(plan, f)
    mpi.set_config(faults=path)
    try:
        kill, kill_done, srv_k = run(reqs, replicas=2,
                                     slo_ttft_us=target_us)
    finally:
        mpi.set_config(faults="off")
    dead = [e.name for e in srv_k.router.replicas if e.dead]
    rerouted = sum(r.reroutes for r in kill)
    kill_ok = (len(kill_done) == n and len(dead) == 1 and rerouted > 0
               and all(r.tokens == oracle[r.rid]
                       for r in kill if not r.shed))

    ok = (len(on_done) == n and len(off_done) == n
          and len(served) + len(shed) == n and len(shed) > 0 and typed
          and p99_on < p99_off and "scale_up" in events and kill_ok)
    line = (f"SURGE-SUMMARY requests={n} surge_x={args.surge_x} "
            f"slo_us={target_us:.0f} p99_off_u={p99_off:.1f} "
            f"p99_on_u={p99_on:.1f} served={len(served)} "
            f"shed={len(shed)} scale_events={len(events)} "
            f"kill_dead={','.join(dead)} kill_rerouted={rerouted} "
            f"kill_ok={'ok' if kill_ok else 'FAIL'} "
            f"typed_shed={'ok' if typed else 'FAIL'} "
            f"verdict={'shed-not-collapse' if ok else 'FAIL'}")
    print(line)
    return ok, line


def main():
    p = argparse.ArgumentParser(
        description=__doc__.splitlines()[0])
    p.add_argument("--requests", type=int, default=64)
    p.add_argument("--prompt-len", type=int, default=6)
    p.add_argument("--lens", type=int, nargs="+",
                   default=[4, 8, 16, 56],
                   help="decode-length mix (static pays the longest "
                        "per batch, so the tail sets its waste)")
    p.add_argument("--slots", type=int, default=8,
                   help="slot blocks per replica; also the static "
                        "batch size")
    p.add_argument("--slot-tokens", type=int, default=64)
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("--load", type=float, default=1.05,
                   help="offered load vs measured continuous capacity "
                        "(>1 = saturating: throughput is the verdict "
                        "metric; TTFT then includes queueing, which is "
                        "exactly where static batching loses hardest)")
    p.add_argument("--embed", type=int, default=64)
    p.add_argument("--min-speedup", type=float, default=1.5)
    p.add_argument("--chaos", action="store_true",
                   help="also run the replica-kill phase")
    p.add_argument("--chaos-after", type=int, default=20,
                   help="site arrivals before the planned kill")
    p.add_argument("--sample", action="store_true",
                   help="also run the sampled-decode phase "
                        "(SAMPLE-SUMMARY)")
    p.add_argument("--spec", action="store_true",
                   help="also run the speculative-decoding phase "
                        "(SPEC-SUMMARY)")
    p.add_argument("--spec-k", type=int, default=4,
                   help="draft tokens per speculative tick")
    p.add_argument("--spec-draft", choices=["ngram", "model"],
                   default="ngram",
                   help="proposer for --spec (ngram = prompt lookup, "
                        "free; model = small draft LM, priced by "
                        "param ratio)")
    p.add_argument("--buckets", type=int, default=0,
                   help="> 0: run the bucketed-prefill phase with this "
                        "min bucket (BUCKET-SUMMARY)")
    p.add_argument("--tp", type=int, default=0,
                   help="> 0: run the TP-sharded replica phase on this "
                        "many devices (TP-SERVING-SUMMARY)")
    p.add_argument("--prefix", action="store_true",
                   help="also run the radix prefix-cache phase "
                        "(PREFIX-SUMMARY)")
    p.add_argument("--surge", action="store_true",
                   help="also run the rate-step admission/autoscale "
                        "phase (SURGE-SUMMARY)")
    p.add_argument("--surge-x", type=int, default=10,
                   help="admission-rate multiplier for the surge "
                        "cohort")
    p.add_argument("--bank", action="store_true",
                   help="persist every summary line to "
                        "SUMMARY_BANK.json")
    args = p.parse_args()

    import numpy as np

    import jax
    import jax.numpy as jnp

    import torchmpi_tpu as mpi
    from torchmpi_tpu import serving
    from torchmpi_tpu.models import TransformerLM

    mpi.init()
    vocab = 64
    model = TransformerLM(vocab=vocab, embed=args.embed, depth=2,
                          num_heads=4, head_dim=8,
                          max_len=max(args.slot_tokens, 64),
                          pos_emb="rope")
    params = model.init(jax.random.PRNGKey(args.seed + 1),
                        jnp.zeros((1, args.prompt_len),
                                  jnp.int32))["params"]
    rng = np.random.RandomState(args.seed)

    # Warmup: one saturating trace pays the prefill/step compiles so
    # the timed phases run warm executables only.
    srv = serving.Server(model, params, replicas=1, slots=args.slots,
                         slot_tokens=args.slot_tokens)
    srv.run_trace(build_trace(rng, args.slots, args.prompt_len,
                              [max(args.lens)], 0.0, vocab))

    # Poisson arrivals on the WORK-UNIT clock (one unit = one compiled
    # prefill or step invocation): offered token rate = load x the
    # slots-per-step capacity.  Deterministic in the seed — scheduling
    # never depends on wall noise.
    mean_len = float(np.mean(args.lens))
    inter_arrival = mean_len / (args.load * args.slots)
    reqs = build_trace(rng, args.requests, args.prompt_len, args.lens,
                       inter_arrival, vocab)
    oracle = offline_oracle(model, params, reqs)

    srv = serving.Server(model, params, replicas=1, slots=args.slots,
                         slot_tokens=args.slot_tokens)
    wall0 = time.monotonic()
    done = srv.run_trace(reqs, unit_seconds=1.0)
    cont_wall = time.monotonic() - wall0
    eng = srv.router.replicas[0]
    cont_work = eng.stats["prefills"] + eng.stats["steps"]
    n_tok = sum(len(r.tokens) for r in reqs)
    cont_ttft_u = float(np.mean([r.ttft_s for r in reqs]))
    bitwise = all(r.tokens == oracle[r.rid] for r in reqs) \
        and len(done) == len(reqs)

    static_toks, static_work, static_wall, static_ttft_u = run_static(
        model, params, reqs, args.slots, args.slot_tokens)
    bitwise = bitwise and all(static_toks[r.rid] == oracle[r.rid]
                              for r in reqs)

    # Throughput ratio = invocation-count ratio of the SAME two
    # executables; wall tok/s uses each phase's own measured unit cost.
    speedup = static_work / cont_work
    cont_tps = n_tok / cont_wall
    static_tps = n_tok / static_wall
    unit_ms = (cont_wall + static_wall) / (cont_work + static_work) * 1e3

    chaos_ok, rerouted, chaos_line = (True, 0, None)
    if args.chaos:
        chaos_ok, rerouted, chaos_line = run_chaos(model, params, args,
                                                   rng, vocab)

    phases = []  # (bank kind, ok, summary line)
    if args.sample:
        ok, line = run_sample(model, params, args, rng, vocab)
        phases.append(("serving_sample", ok, line))
    if args.spec:
        ok, line = run_spec(model, params, args, rng, vocab)
        phases.append(("serving_spec", ok, line))
    if args.buckets > 0:
        ok, line = run_buckets(model, params, args, rng, vocab)
        phases.append(("serving_bucket", ok, line))
    if args.tp > 0:
        ok, line = run_tp(args, rng, vocab)
        phases.append(("serving_tp", ok, line))
    if args.prefix:
        ok, line = run_prefix(model, params, args, rng, vocab)
        phases.append(("serving_prefix", ok, line))
    if args.surge:
        ok, line = run_surge(model, params, args, rng, vocab)
        phases.append(("serving_surge", ok, line))

    good = (bitwise and speedup >= args.min_speedup
            and cont_ttft_u < static_ttft_u and chaos_ok
            and all(ok for _, ok, _ in phases))
    line = (f"SERVING-SUMMARY requests={len(reqs)} tokens={n_tok} "
            f"cont_work={cont_work} static_work={static_work} "
            f"speedup={speedup:.2f} "
            f"cont_tok_s={cont_tps:.1f} static_tok_s={static_tps:.1f} "
            f"unit_ms={unit_ms:.2f} "
            f"cont_ttft_ms={cont_ttft_u * unit_ms:.1f} "
            f"static_ttft_ms={static_ttft_u * unit_ms:.1f} "
            f"bitwise={'ok' if bitwise else 'FAIL'} "
            f"rerouted={rerouted} "
            f"verdict="
            f"{'continuous-beats-static' if good else 'FAIL'}")
    print(line)
    _maybe_bank(args, "serving", line)
    _maybe_bank(args, "serving_chaos", chaos_line)
    for kind, _, pline in phases:
        _maybe_bank(args, kind, pline)
    if not good:
        print(f"FAIL: need speedup >= {args.min_speedup}, lower TTFT, "
              f"bitwise tokens"
              + (", and a drained+re-routed chaos phase"
                 if args.chaos else "")
              + (", and every phase verdict"
                 if phases else ""), file=sys.stderr)
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())

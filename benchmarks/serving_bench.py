"""Continuous-batching vs static-batching serving benchmark
(ISSUE 9 acceptance; docs/SERVING.md).

One synthetic Poisson arrival trace (fixed prompt length, MIXED decode
lengths — the traffic shape continuous batching exists for) served two
ways over the same checkpoint:

- **continuous** — ``torchmpi_tpu.serving.Server``: iteration-level
  admission into slot blocks, immediate retirement, virtual clock
  advanced by each tick's measured wall time;
- **static** — the classic offline semantics over
  ``models.generate.generate``: wait until a full batch has ARRIVED,
  run every member to the batch's longest decode length, deliver
  results at batch completion (which is when the offline API returns
  them — its TTFT is honestly its completion time).

Reported (the ``SERVING-SUMMARY`` line CI asserts on):

- token throughput = useful tokens / summed compute seconds for each
  system (idle queue gaps excluded from both) — continuous wins by not
  burning steps on retired rows and not idling short rows to the batch
  straggler;
- mean TTFT on the shared virtual clock (arrival -> first token);
- ``bitwise`` — every request's continuous tokens equal the offline
  ``generate`` oracle token for token (greedy);
- with ``--chaos``: a deterministic fault plan hard-kills one of two
  replicas mid-trace; the run must still complete, re-route > 0
  sessions, and stay token-exact (``CHAOS-SUMMARY`` line).

Exits nonzero unless continuous >= --min-speedup x static throughput
AND continuous mean TTFT < static AND bitwise holds (and the chaos
phase, when run, drained + re-routed).  Run under obs
(``TORCHMPI_TPU_OBS=metrics``) to get the ``tm_serving_*`` SLO
histograms; ``scripts/obs_tool.py slo`` renders them.

Usage::

    JAX_PLATFORMS=cpu TORCHMPI_TPU_OBS=metrics \
        python benchmarks/serving_bench.py --requests 48 --chaos
"""

import argparse
import json
import os
import sys
import tempfile
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


def build_trace(rng, n, tp, lens, inter_arrival_s, vocab):
    import numpy as np

    from torchmpi_tpu import serving

    prompts = rng.randint(0, vocab, size=(n, tp)).astype(np.int32)
    max_news = [int(lens[i % len(lens)]) for i in
                rng.permutation(n)]
    gaps = rng.exponential(inter_arrival_s, size=n)
    arrivals = np.cumsum(gaps)
    return [serving.Request(f"q{i}", prompts[i], max_new=max_news[i],
                            arrival_s=float(arrivals[i]))
            for i in range(n)]


def offline_oracle(model, params, reqs):
    """Per-request offline greedy decode — THE token reference."""
    import numpy as np

    from torchmpi_tpu.models import generate

    out = {}
    for r in reqs:
        toks = np.asarray(generate(
            model, params, np.asarray(r.prompt).reshape(1, -1),
            steps=r.max_new))
        out[r.rid] = toks[0, len(r.prompt):].tolist()
    return out


def run_static(model, params, reqs, batch_size, slot_tokens):
    """Static-batch SEMANTICS through the same engine mechanics: wait
    until a full batch has arrived, admit it whole, run every member to
    the batch's longest decode (each tick steps all ``batch_size`` slot
    rows whether or not a short row already finished — exactly the
    run-to-longest cost), deliver at batch completion, admit nothing
    mid-batch.

    Same compiled ``[S, 1]`` step and prefill executables as the
    continuous server (same slots, same model clone), so the comparison
    isolates the SCHEDULING property — iteration-level admission +
    early retirement — instead of dispatch mechanics.  (The
    fully-offline ``models.generate`` scan amortizes its whole decode
    inside one XLA dispatch and is the TOKEN oracle, not the latency
    baseline: no server can batch requests that have not arrived.)

    The clock is the same work-unit clock the continuous run uses (one
    unit = one prefill or one step invocation), so both schedules are
    deterministic and the throughput ratio is a pure invocation-count
    ratio of IDENTICAL executables — immune to container noise; wall
    time is measured alongside as the per-unit cost evidence.

    Returns (per-rid tokens, work_units, wall_s, mean_ttft_units)."""
    import numpy as np

    from torchmpi_tpu import serving

    ordered = [serving.Request(r.rid, r.prompt, r.max_new,
                               eos_id=r.eos_id, arrival_s=r.arrival_s)
               for r in sorted(reqs, key=lambda r: r.arrival_s)]
    eng = serving.ReplicaEngine(model, params, name="static",
                                slots=batch_size,
                                slot_tokens=slot_tokens)
    tokens, clock, ttfts = {}, 0.0, []
    wall0 = time.monotonic()
    for i in range(0, len(ordered), batch_size):
        batch = ordered[i:i + batch_size]
        start = max(clock, max(r.arrival_s for r in batch))
        units0 = eng.stats["prefills"] + eng.stats["steps"]
        finished = []
        for r in batch:
            sess, done = eng.admit(r)
            if done:
                finished.append(sess)
        while eng.active:
            _, fin = eng.step()
            finished.extend(fin)
        clock = start + (eng.stats["prefills"] + eng.stats["steps"]
                         - units0)
        for sess in finished:
            tokens[sess.request.rid] = list(sess.emitted)
            ttfts.append(clock - sess.request.arrival_s)
    wall = time.monotonic() - wall0
    work = eng.stats["prefills"] + eng.stats["steps"]
    return tokens, work, wall, float(np.mean(ttfts))


def run_chaos(model, params, args, rng, vocab):
    """Two replicas, deterministic mid-trace hard kill: the server must
    drain + re-route and stay token-exact."""
    import torchmpi_tpu as mpi
    from torchmpi_tpu import serving

    plan = {"version": 1, "seed": args.seed, "note": "serving kill",
            "rules": [{"site": "serving.replica", "kind": "fail",
                       "prob": 1.0, "after": args.chaos_after,
                       "max_hits": 1}]}
    path = os.path.join(tempfile.mkdtemp(prefix="serving_chaos_"),
                        "plan.json")
    with open(path, "w") as f:
        json.dump(plan, f)
    reqs = build_trace(rng, args.requests, args.prompt_len,
                       args.lens, 0.01, vocab)
    oracle = offline_oracle(model, params, reqs)
    mpi.set_config(faults=path)
    try:
        srv = serving.Server(model, params, replicas=2,
                             slots=args.slots,
                             slot_tokens=args.slot_tokens)
        done = srv.run_trace(reqs, tick_seconds=0.005)
    finally:
        mpi.set_config(faults="off")
    dead = [e.name for e in srv.router.replicas if e.dead]
    rerouted = sum(r.reroutes for r in reqs)
    ok = (len(done) == len(reqs) and len(dead) == 1 and rerouted > 0
          and all(r.tokens == oracle[r.rid] for r in reqs))
    print(f"CHAOS-SUMMARY requests={len(reqs)} dead={','.join(dead)} "
          f"rerouted={rerouted} "
          f"bitwise={'ok' if ok else 'FAIL'} "
          f"verdict={'drain-reroute-ok' if ok else 'FAIL'}")
    return ok, rerouted


def main():
    p = argparse.ArgumentParser(
        description=__doc__.splitlines()[0])
    p.add_argument("--requests", type=int, default=64)
    p.add_argument("--prompt-len", type=int, default=6)
    p.add_argument("--lens", type=int, nargs="+",
                   default=[4, 8, 16, 56],
                   help="decode-length mix (static pays the longest "
                        "per batch, so the tail sets its waste)")
    p.add_argument("--slots", type=int, default=8,
                   help="slot blocks per replica; also the static "
                        "batch size")
    p.add_argument("--slot-tokens", type=int, default=64)
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("--load", type=float, default=1.05,
                   help="offered load vs measured continuous capacity "
                        "(>1 = saturating: throughput is the verdict "
                        "metric; TTFT then includes queueing, which is "
                        "exactly where static batching loses hardest)")
    p.add_argument("--embed", type=int, default=64)
    p.add_argument("--min-speedup", type=float, default=1.5)
    p.add_argument("--chaos", action="store_true",
                   help="also run the replica-kill phase")
    p.add_argument("--chaos-after", type=int, default=20,
                   help="site arrivals before the planned kill")
    args = p.parse_args()

    import numpy as np

    import jax
    import jax.numpy as jnp

    import torchmpi_tpu as mpi
    from torchmpi_tpu import serving
    from torchmpi_tpu.models import TransformerLM

    mpi.init()
    vocab = 64
    model = TransformerLM(vocab=vocab, embed=args.embed, depth=2,
                          num_heads=4, head_dim=8,
                          max_len=max(args.slot_tokens, 64),
                          pos_emb="rope")
    params = model.init(jax.random.PRNGKey(args.seed + 1),
                        jnp.zeros((1, args.prompt_len),
                                  jnp.int32))["params"]
    rng = np.random.RandomState(args.seed)

    # Warmup: one saturating trace pays the prefill/step compiles so
    # the timed phases run warm executables only.
    srv = serving.Server(model, params, replicas=1, slots=args.slots,
                         slot_tokens=args.slot_tokens)
    srv.run_trace(build_trace(rng, args.slots, args.prompt_len,
                              [max(args.lens)], 0.0, vocab))

    # Poisson arrivals on the WORK-UNIT clock (one unit = one compiled
    # prefill or step invocation): offered token rate = load x the
    # slots-per-step capacity.  Deterministic in the seed — scheduling
    # never depends on wall noise.
    mean_len = float(np.mean(args.lens))
    inter_arrival = mean_len / (args.load * args.slots)
    reqs = build_trace(rng, args.requests, args.prompt_len, args.lens,
                       inter_arrival, vocab)
    oracle = offline_oracle(model, params, reqs)

    srv = serving.Server(model, params, replicas=1, slots=args.slots,
                         slot_tokens=args.slot_tokens)
    wall0 = time.monotonic()
    done = srv.run_trace(reqs, unit_seconds=1.0)
    cont_wall = time.monotonic() - wall0
    eng = srv.router.replicas[0]
    cont_work = eng.stats["prefills"] + eng.stats["steps"]
    n_tok = sum(len(r.tokens) for r in reqs)
    cont_ttft_u = float(np.mean([r.ttft_s for r in reqs]))
    bitwise = all(r.tokens == oracle[r.rid] for r in reqs) \
        and len(done) == len(reqs)

    static_toks, static_work, static_wall, static_ttft_u = run_static(
        model, params, reqs, args.slots, args.slot_tokens)
    bitwise = bitwise and all(static_toks[r.rid] == oracle[r.rid]
                              for r in reqs)

    # Throughput ratio = invocation-count ratio of the SAME two
    # executables; wall tok/s uses each phase's own measured unit cost.
    speedup = static_work / cont_work
    cont_tps = n_tok / cont_wall
    static_tps = n_tok / static_wall
    unit_ms = (cont_wall + static_wall) / (cont_work + static_work) * 1e3

    chaos_ok, rerouted = (True, 0)
    if args.chaos:
        chaos_ok, rerouted = run_chaos(model, params, args, rng, vocab)

    good = (bitwise and speedup >= args.min_speedup
            and cont_ttft_u < static_ttft_u and chaos_ok)
    print(f"SERVING-SUMMARY requests={len(reqs)} tokens={n_tok} "
          f"cont_work={cont_work} static_work={static_work} "
          f"speedup={speedup:.2f} "
          f"cont_tok_s={cont_tps:.1f} static_tok_s={static_tps:.1f} "
          f"unit_ms={unit_ms:.2f} "
          f"cont_ttft_ms={cont_ttft_u * unit_ms:.1f} "
          f"static_ttft_ms={static_ttft_u * unit_ms:.1f} "
          f"bitwise={'ok' if bitwise else 'FAIL'} "
          f"rerouted={rerouted} "
          f"verdict="
          f"{'continuous-beats-static' if good else 'FAIL'}")
    if not good:
        print(f"FAIL: need speedup >= {args.min_speedup}, lower TTFT, "
              f"bitwise tokens"
              + (", and a drained+re-routed chaos phase"
                 if args.chaos else ""), file=sys.stderr)
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())

"""Capture a profiler trace of backprop-overlapped gradient sync.

The artifact for SURVEY.md §8.4.3 / VERDICT round-1 item 8 / ROADMAP
item 1: the trace shows per-bucket allreduce launches interleaved with
backward compute (communication/computation overlap — the property the
reference's async per-layer hooks bought).

Two schedules:

- default: the *bucketed* post-backward sync (``n_buckets`` independent
  collectives inside one jit; XLA is free to overlap them).
- ``--overlap``: the *backprop-overlapped* schedule
  (``Config.gradsync_overlap="auto"`` — docs/OVERLAP.md): each
  reverse-parameter-order bucket's allreduce fires INSIDE the backward
  pass via ``gradsync.make_overlapped_grad_fn``, and the script turns
  on the obs flight recorder, reads back the per-bucket grads/launch
  events, and emits an **assertable summary line**::

      OVERLAP-SUMMARY {"schedule": "overlapped", "interleaved": true, ...}

  ``interleaved`` is the CPU-sim-checkable invariant (bucket 0's launch
  recorded before the last bucket's grads exist); the wall-clock win
  itself is hardware-only, as ever.

Run on hardware::

    python benchmarks/overlap_trace.py [--overlap] [--buckets 4]
        [--trace-dir DIR]

then open the trace.json.gz under ``<dir>/plugins/profile/`` in
ui.perfetto.dev or tensorboard.  On the simulated CPU mesh
(``--devices 8``) the trace validates the capture path and the summary
validates the schedule; overlap *timing* is only meaningful on real
chips.  ``--model resnet20`` keeps the CPU-sim run light (the tier-1
``overlap-smoke`` CI job drives exactly that).
"""

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def overlap_summary(obs, schedule: str) -> dict:
    """Fold the flight ring's overlap events into the assertable
    verdict: per-bucket first grads/launch seqs and whether the
    first-fired bucket's launch preceded the last-fired bucket's
    cotangents (the overlap invariant)."""
    ov = [(e[0], e[3], e[4]) for e in obs.recorder().events()
          if e[2] == "overlap"]  # (seq, stage, bucket)
    first_launch, first_grads = {}, {}
    for seq, stage, bucket in ov:
        d = first_launch if stage == "launch" else first_grads
        d.setdefault(bucket, seq)
    if not first_launch or not first_grads:
        return {"schedule": schedule, "interleaved": False, "buckets": 0,
                "note": "no overlap events recorded"}
    last = max(first_grads)
    interleaved = (last >= 1
                   and first_launch.get(0, 1 << 62) < first_grads[last])
    return {"schedule": schedule, "interleaved": bool(interleaved),
            "buckets": last + 1,
            "first_launch_seq": first_launch.get(0),
            "last_bucket_grads_seq": first_grads[last]}


def main():
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--devices", type=int, default=0)
    p.add_argument("--buckets", type=int, default=4)
    p.add_argument("--steps", type=int, default=5)
    p.add_argument("--batch-per-chip", type=int, default=16)
    p.add_argument("--image-size", type=int, default=64)
    p.add_argument("--model", choices=("resnet50", "resnet20"),
                   default="resnet50",
                   help="resnet20 keeps CPU-sim smoke runs light")
    p.add_argument("--overlap", action="store_true",
                   help="backprop-overlapped schedule "
                        "(gradsync_overlap=auto) + flight-recorder "
                        "summary (docs/OVERLAP.md)")
    p.add_argument("--trace-dir", default="/tmp/torchmpi_tpu_overlap_trace")
    args = p.parse_args()
    if args.devices:
        from torchmpi_tpu.utils.simulation import force_cpu_devices

        force_cpu_devices(args.devices)

    import glob

    import jax
    import jax.numpy as jnp
    import numpy as np
    import optax
    from jax.sharding import NamedSharding, PartitionSpec as P

    import torchmpi_tpu as mpi
    from torchmpi_tpu.models import ResNet20, ResNet50
    from torchmpi_tpu.utils import tracing
    from torchmpi_tpu.utils.metrics import fence

    cfg = mpi.Config()
    if args.overlap:
        cfg.gradsync_overlap = "auto"
        # The flight recorder is the evidence channel for the summary.
        if cfg.obs == "off":
            cfg.obs = "metrics"
    mesh = mpi.init(cfg)
    budget_cm = mpi.compile_budget()  # watcher-supervised client
    budget_cm.__enter__()
    n_dev = mpi.device_count()
    n_classes = 1000 if args.model == "resnet50" else 10
    model = (ResNet50(dtype=jnp.bfloat16) if args.model == "resnet50"
             else ResNet20(num_classes=n_classes))
    variables = model.init(jax.random.PRNGKey(0),
                           jnp.zeros((1, args.image_size, args.image_size,
                                      3)), train=False)
    params, batch_stats = variables["params"], variables["batch_stats"]
    tx = optax.sgd(0.1, momentum=0.9)
    if args.overlap:
        # Let --buckets govern the overlapped schedule too: bound each
        # bucket to ~1/buckets of the gradient payload (otherwise a
        # small model fits one tuning-plan bucket and there is nothing
        # to interleave).
        total = sum(int(np.prod(l.shape)) * l.dtype.itemsize
                    for l in jax.tree.leaves(params))
        mpi.set_config(gradsync_overlap_bytes=max(
            1, -(-total // max(1, args.buckets))))
    dp_step = mpi.recipes.make_bn_dp_train_step(
        model, tx, mesh=mesh, n_buckets=args.buckets,
        overlap="auto" if args.overlap else "off")
    params, opt_state, batch_stats = mpi.recipes.replicate_bn_state(
        params, tx.init(params), batch_stats, mesh=mesh)
    batch = args.batch_per_chip * n_dev
    shard = NamedSharding(mesh, P(mesh.axis_names))
    X = jax.device_put(np.random.RandomState(0).rand(
        batch, args.image_size, args.image_size, 3).astype(np.float32),
        shard)
    Y = jax.device_put(np.random.RandomState(1).randint(
        0, n_classes, size=batch).astype(np.int32), shard)

    # compile outside the trace so the capture is steps only
    params, opt_state, batch_stats, loss = dp_step(params, opt_state,
                                                   batch_stats, X, Y)
    fence(loss)
    if args.overlap:
        from torchmpi_tpu import obs

        obs.reset()  # summarize the traced steps only
    with tracing.trace(args.trace_dir) as d:
        for _ in range(args.steps):
            params, opt_state, batch_stats, loss = dp_step(
                params, opt_state, batch_stats, X, Y)
        fence(loss)
    artifacts = glob.glob(os.path.join(d, "**", "*.json.gz"),
                          recursive=True)
    print(f"trace captured: {artifacts or d} "
          f"(model={args.model}, buckets={args.buckets}, "
          f"devices={n_dev}, "
          f"schedule={'overlapped' if args.overlap else 'bucketed'})")
    if args.overlap:
        from torchmpi_tpu import obs

        print("OVERLAP-SUMMARY " + json.dumps(
            overlap_summary(obs, "overlapped")))
    mpi.stop()


if __name__ == "__main__":
    main()

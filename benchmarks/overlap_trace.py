"""Capture a profiler trace of the bucketed, overlapped gradient sync.

The artifact for SURVEY.md §8.4.3 / VERDICT round-1 item 8: on real TPU,
the trace shows per-bucket allreduce launches interleaved with backward
compute (communication/computation overlap — the property the reference's
async per-layer hooks bought).  Run on hardware:

    python benchmarks/overlap_trace.py [--buckets 4] [--trace-dir DIR]

then open the trace.json.gz under ``<dir>/plugins/profile/`` in
ui.perfetto.dev or tensorboard.  On the simulated CPU mesh (``--devices 8``)
the trace validates the capture path; overlap timing is only meaningful on
real chips.
"""

import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main():
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--devices", type=int, default=0)
    p.add_argument("--buckets", type=int, default=4)
    p.add_argument("--steps", type=int, default=5)
    p.add_argument("--batch-per-chip", type=int, default=16)
    p.add_argument("--image-size", type=int, default=64)
    p.add_argument("--trace-dir", default="/tmp/torchmpi_tpu_overlap_trace")
    args = p.parse_args()
    if args.devices:
        from torchmpi_tpu.utils.simulation import force_cpu_devices

        force_cpu_devices(args.devices)

    import glob

    import jax
    import jax.numpy as jnp
    import numpy as np
    import optax
    from jax.sharding import NamedSharding, PartitionSpec as P

    import torchmpi_tpu as mpi
    from torchmpi_tpu.models import ResNet50
    from torchmpi_tpu.utils import tracing
    from torchmpi_tpu.utils.metrics import fence

    mesh = mpi.init()
    budget_cm = mpi.compile_budget()  # watcher-supervised client
    budget_cm.__enter__()
    n_dev = mpi.device_count()
    model = ResNet50(dtype=jnp.bfloat16)
    variables = model.init(jax.random.PRNGKey(0),
                           jnp.zeros((1, args.image_size, args.image_size,
                                      3)), train=False)
    params, batch_stats = variables["params"], variables["batch_stats"]
    tx = optax.sgd(0.1, momentum=0.9)
    dp_step = mpi.recipes.make_bn_dp_train_step(model, tx, mesh=mesh,
                                                n_buckets=args.buckets)
    params, opt_state, batch_stats = mpi.recipes.replicate_bn_state(
        params, tx.init(params), batch_stats, mesh=mesh)
    batch = args.batch_per_chip * n_dev
    shard = NamedSharding(mesh, P(mesh.axis_names))
    X = jax.device_put(np.random.RandomState(0).rand(
        batch, args.image_size, args.image_size, 3).astype(np.float32),
        shard)
    Y = jax.device_put(np.random.RandomState(1).randint(
        0, 1000, size=batch).astype(np.int32), shard)

    # compile outside the trace so the capture is steps only
    params, opt_state, batch_stats, loss = dp_step(params, opt_state,
                                                   batch_stats, X, Y)
    fence(loss)
    with tracing.trace(args.trace_dir) as d:
        for _ in range(args.steps):
            params, opt_state, batch_stats, loss = dp_step(
                params, opt_state, batch_stats, X, Y)
        fence(loss)
    artifacts = glob.glob(os.path.join(d, "**", "*.json.gz"),
                          recursive=True)
    print(f"trace captured: {artifacts or d} "
          f"(buckets={args.buckets}, devices={n_dev})")
    mpi.stop()


if __name__ == "__main__":
    main()

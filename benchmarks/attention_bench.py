"""Attention micro-benchmark: Pallas flash kernel vs the XLA dense path.

Beyond-reference (the reference predates attention — SURVEY.md §6.7); this
is the compute-kernel analog of the stock-vs-custom collective comparison
in collectives_bench.py: same numerics two ways, measured side by side.
Reports achieved TFLOP/s (4*B*H*Tq*Tkv*D flops per attention, halved for
causal) and peak HBM residency difference — the dense path materializes
the [T, T] score matrix, flash never does, so flash extends to sequence
lengths the dense path cannot hold.

Run: ``python benchmarks/attention_bench.py [--seqs 1024,4096] [--json]``
(real TPU when available; CPU interpret-mode smoke with --cpu).
"""

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main():
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--cpu", action="store_true",
                   help="force CPU (interpret-mode smoke; tiny shapes)")
    p.add_argument("--seqs", type=str, default="1024,4096,16384")
    p.add_argument("--batch", type=int, default=4)
    p.add_argument("--heads", type=int, default=8)
    p.add_argument("--head-dim", type=int, default=128)
    p.add_argument("--iters", type=int, default=10)
    p.add_argument("--causal", action=argparse.BooleanOptionalAction,
                   default=True)
    p.add_argument("--json", action="store_true")
    args = p.parse_args()
    if args.cpu:
        from torchmpi_tpu.utils.simulation import force_cpu_devices

        force_cpu_devices(1)
        args.seqs = "128"
        args.batch, args.heads, args.head_dim = 1, 2, 8

    import jax
    import jax.numpy as jnp
    import numpy as np

    from torchmpi_tpu.ops.flash import flash_attention
    from torchmpi_tpu.parallel.sequence import reference_attention
    from torchmpi_tpu.utils.metrics import fence

    platform = jax.devices()[0].platform
    dtype = jnp.bfloat16 if platform == "tpu" else jnp.float32
    B, H, D = args.batch, args.heads, args.head_dim

    impls = {
        "flash": jax.jit(lambda q, k, v: flash_attention(
            q, k, v, causal=args.causal)),
        "xla-dense": jax.jit(lambda q, k, v: reference_attention(
            q, k, v, causal=args.causal)),
    }

    for T in (int(s) for s in args.seqs.split(",")):
        rng = np.random.RandomState(0)
        q = jnp.asarray(rng.randn(B, T, H, D), dtype)
        k = jnp.asarray(rng.randn(B, T, H, D), dtype)
        v = jnp.asarray(rng.randn(B, T, H, D), dtype)
        flops = 4.0 * B * H * T * T * D * (0.5 if args.causal else 1.0)
        for name, fn in impls.items():
            try:
                out = fn(q, k, v)
                fence(out)
                t0 = time.perf_counter()
                for _ in range(args.iters):
                    out = fn(q, k, v)
                fence(out)
                dt = (time.perf_counter() - t0) / args.iters
            except Exception as e:  # dense path OOMs first at long T
                line = {"op": "attention", "impl": name, "seq": T,
                        "error": str(e)[:120]}
                print(json.dumps(line) if args.json
                      else f"attention {name:9s} T={T:>6d}  FAILED: "
                           f"{str(e)[:80]}")
                continue
            tflops = flops / dt / 1e12
            line = {"op": "attention", "impl": name, "seq": T,
                    "batch": B, "heads": H, "head_dim": D,
                    "dtype": str(dtype.__name__ if hasattr(dtype, "__name__")
                                 else dtype), "ms": round(dt * 1e3, 3),
                    "tflops": round(tflops, 2), "platform": platform}
            print(json.dumps(line) if args.json
                  else f"attention {name:9s} T={T:>6d}  {dt*1e3:8.2f} ms  "
                       f"{tflops:7.2f} TFLOP/s")


if __name__ == "__main__":
    main()

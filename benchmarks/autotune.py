"""Knob autotuner: measure, then recommend a ``Config`` for this platform.

The reference shipped hand-tuned constants (chunk sizes, size cutovers for
stock-vs-custom collectives — SURVEY.md §6.6's chunk/buffer-size setters);
this harness derives them empirically instead:

1. allreduce backend per size — sweep xla vs pallas (vs hierarchical on
   multi-slice meshes) and find the measured ``custom_min_bytes`` cutover;
2. ``chunk_bytes`` — sweep the streaming-ring subchunk size at a
   gradient-sized payload;
3. ``gradsync_buckets`` — sweep bucket counts on the ResNet-20 DP step
   (reuses scaling_bench's sweep at a single mesh size);
4./5. Pallas kernel tilings (flash attention, fused xent) on real TPU.

Measurement discipline (VERDICT r3 weak #3: single-trial timings on a
~7 ms-dispatch-floor relay cannot resolve knob deltas — ten contradictory
committed recommendations are worse than one with error bars): every
candidate is timed over ``--rounds`` (default 5) fenced rounds and scored
by the MEDIAN; the per-candidate jitter (half the inter-quartile range)
is printed with every measurement; and a NOISE GATE keeps the
config-default value unless a challenger beats it by more than the
combined jitter of the two.  A re-run therefore agrees with itself:
within-noise knobs stay at their defaults instead of flapping.  The
discipline itself lives in ``torchmpi_tpu.tuning.measure`` (structured
``TimedResult`` from ``utils/metrics.timed`` + ``noise_gate``) — the
same library the online ``backend="auto"`` selector uses; this harness
just drives it over the full knob grid.

Prints one JSON line per measurement plus a final ``recommend`` line that
can be applied directly::

    rec = json.loads(last_line)["config"]
    mpi.init(mpi.Config(**rec))

The recommend line carries ``evidence`` per knob: chosen vs default
medians, the delta, and the jitter the delta had to clear.

``--plan-out PATH`` additionally writes the backend sweep into a
versioned tuning-plan file — one entry per (op, size bucket) at this
platform/mesh — that
``mpi.init(Config(backend="auto", tuning_plan_path=PATH))`` replays
directly, so the offline sweep seeds the online plan DB.  (The plan
drives selection only where a backend resolves to ``"auto"``; a plan
path alone loads the file and logs that it is inactive.)

On the CPU-simulated mesh the absolute numbers are meaningless but the
harness (and its JSON contract) is identical to what runs on a real slice.

Run: ``python benchmarks/autotune.py [--devices 8] [--quick] [--rounds 5]
[--plan-out plans.json]``
"""

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

ROUNDS = 5  # set from --rounds in main()


def _measure(fn, iters, fence):
    """Structured TimedResult (median/jitter/rounds attached) over
    ROUNDS fenced timing rounds of ``iters`` dispatches after one
    warm/compile call — tuning.measure's discipline at this module's
    round count."""
    from torchmpi_tpu.tuning import measure as tmeasure

    return tmeasure.measure(fn, iters=iters, rounds=ROUNDS, fence=fence)


def _ms(res):
    from torchmpi_tpu.tuning import measure as tmeasure

    return tmeasure.result_ms(res)


def _gate(cands, default_key):
    """Noise-gated argmin (tuning.measure.noise_gate): the config
    default wins unless a challenger beats it beyond the pair's
    combined jitter — the anti-flap rule that makes re-runs agree."""
    from torchmpi_tpu.tuning import measure as tmeasure

    return tmeasure.noise_gate(cands, default_key)


def main():
    import functools
    global print, ROUNDS
    print = functools.partial(print, flush=True)
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--devices", type=int, default=0,
                   help="force N simulated CPU devices")
    p.add_argument("--dcn", type=int, default=None)
    p.add_argument("--iters", type=int, default=5)
    p.add_argument("--rounds", type=int, default=5,
                   help="timing rounds per candidate (median scored)")
    p.add_argument("--quick", action="store_true",
                   help="tiny sweep (CI smoke)")
    p.add_argument("--plan-out", default=None, metavar="PATH",
                   help="write the backend sweep as a tuning-plan file "
                        "(loadable via Config.tuning_plan_path / "
                        "backend='auto')")
    args = p.parse_args()
    ROUNDS = args.rounds
    if args.devices:
        from torchmpi_tpu.utils.simulation import force_cpu_devices

        force_cpu_devices(args.devices)

    import numpy as np

    import torchmpi_tpu as mpi
    from torchmpi_tpu.ops import ring
    from torchmpi_tpu.utils.metrics import fence

    mesh = mpi.init(mpi.Config(dcn_size=args.dcn, custom_min_bytes=0))
    # Declare an unbounded, non-abandonable compile budget for the
    # whole sweep: this client is run by supervisors that honor the
    # compile-gate heartbeat (tpu_watch.run_bounded), so no compile
    # it starts can be abandoned mid-queue, and its candidate jits
    # (ResNet-20 steps, flash-grad tilings) exceed the gate's
    # large-graph threshold on the relay.
    budget_cm = mpi.compile_budget()
    budget_cm.__enter__()
    n = mpi.device_count()
    is_cpu = list(mesh.devices.flat)[0].platform == "cpu"
    if is_cpu:
        from jax.experimental.pallas import tpu as pltpu

        if hasattr(pltpu, "InterpretParams"):
            ring.set_interpret(pltpu.InterpretParams())
        # else: jax too old for the TPU interpreter — pallas candidates
        # fail to compile on CPU and the sweep records them as errors.

    defaults = mpi.Config()  # the values the noise gate protects
    rec = {}
    evidence = {}

    # -- 1. backend cutover ------------------------------------------------
    sizes = ([1 << 14, 1 << 17] if args.quick
             else [1 << 14, 1 << 17, 1 << 20, 1 << 24])
    cutover = None
    last = {}
    plan_sweep = []  # (per_rank_bytes, cands) per size, for --plan-out
    for nbytes in sizes:
        x = np.random.RandomState(0).rand(n, nbytes // 4).astype(np.float32)
        cands = {}
        backends = ["xla", "pallas"]
        if mesh.shape.get("dcn", 1) > 1:
            backends.append("hierarchical")  # the multi-slice 2-level path
        for backend in backends:
            if backend == "pallas" and is_cpu and nbytes > 1 << 14:
                continue  # interpreter too slow at size
            try:
                mpi.collectives.clear_cache()
                cands[backend] = _measure(
                    lambda b=backend: mpi.allreduce(x, backend=b),
                    args.iters, fence)
            except Exception as e:  # noqa: BLE001 — record and continue
                print(json.dumps({"phase": "backend", "bytes": nbytes,
                                  "backend": backend,
                                  "error": str(e)[:120]}))
                continue
            print(json.dumps({"phase": "backend", "per_rank_bytes": nbytes,
                              "backend": backend, **_ms(cands[backend])}))
        # Noise-gated per size: pallas must beat xla beyond the pair's
        # jitter to set the cutover here.  Gated on the {xla, pallas}
        # PAIR: a hierarchical win at this size must not mask a
        # beyond-noise pallas-over-xla cutover (code review r4).
        pair = {k: v for k, v in cands.items() if k in ("xla", "pallas")}
        winner, ev = _gate(pair, "xla")
        if winner == "pallas" and cutover is None:
            cutover = nbytes
            evidence["custom_min_bytes"] = {"at_bytes": nbytes, **ev}
        last = cands
        plan_sweep.append((nbytes, cands))
    winner, ev = _gate(last, "xla")
    if winner == "hierarchical":
        # Two-level wins at gradient scale on this multi-slice mesh.
        # custom_min_bytes must be 0: the selector applies the cutover to
        # every non-xla config-default backend, so a huge cutover would
        # silently route everything back to xla.
        rec["backend"] = "hierarchical"
        rec["custom_min_bytes"] = 0
        evidence["backend"] = ev
    elif cutover is not None:
        # The selector compares custom_min_bytes against PER-RANK bytes:
        # the eager path picks on x[0] (collectives.py `_pick(op, x[0],..)`)
        # and the in-axis path picks on the local shard — so the measured
        # per-rank cutover is exactly the right knob value, unscaled.
        rec["backend"] = "pallas"
        rec["custom_min_bytes"] = cutover
    else:
        rec["backend"] = defaults.backend
        rec["custom_min_bytes"] = defaults.custom_min_bytes
        evidence.setdefault("backend", ev)

    # Seed the online plan DB from the sweep: one noise-gated entry per
    # (op, size bucket) at this platform/mesh, in the exact format
    # mpi.init(Config(tuning_plan_path=...)) / backend="auto" replays.
    if args.plan_out:
        from torchmpi_tpu import tuning as tlib

        cache = tlib.PlanCache(args.plan_out)
        for nbytes, cands in plan_sweep:
            if not cands:
                continue
            w, _ev = _gate(cands, "xla")
            cache.put(
                tlib.make_fingerprint("allreduce", nbytes, "float32", mesh),
                tlib.PlanEntry(
                    backend=str(w), source="autotune",
                    median_ms={b: round(r.median * 1e3, 4)
                               for b, r in cands.items()},
                    jitter_ms={b: round(r.jitter * 1e3, 4)
                               for b, r in cands.items()},
                    rounds=ROUNDS))
        saved = cache.save(args.plan_out)
        print(json.dumps({"phase": "plan_out", "path": args.plan_out,
                          "entries": len(cache), "saved": saved}))

    # -- 2. chunk_bytes ----------------------------------------------------
    if not is_cpu:  # streaming ring needs real lowering to mean anything
        payload = 1 << 26  # 64 MiB: gradient-scale
        x = np.random.RandomState(1).rand(n, payload // 4).astype(np.float32)
        cands = {}
        for cb in (1 << 20, 1 << 22, 1 << 24):
            mpi.set_config(chunk_bytes=cb, custom_min_bytes=0)
            try:
                cands[cb] = _measure(
                    lambda: mpi.allreduce(x, backend="pallas"),
                    args.iters, fence)
            except Exception as e:  # noqa: BLE001
                print(json.dumps({"phase": "chunk", "chunk_bytes": cb,
                                  "error": str(e)[:120]}))
                continue
            print(json.dumps({"phase": "chunk", "chunk_bytes": cb,
                              **_ms(cands[cb])}))
        if cands:
            chosen, ev = _gate(cands, defaults.chunk_bytes)
            rec["chunk_bytes"] = chosen
            evidence["chunk_bytes"] = ev

    # -- 3. gradsync buckets ----------------------------------------------
    # Sweep under the configuration phases 1-2 actually recommend, not the
    # leftovers of their last sweep iteration.
    mpi.set_config(backend=rec["backend"],
                   custom_min_bytes=rec["custom_min_bytes"],
                   **({"chunk_bytes": rec["chunk_bytes"]}
                      if "chunk_bytes" in rec else {}))
    import jax
    import jax.numpy as jnp
    import optax

    from torchmpi_tpu.models import ResNet20

    model = ResNet20(num_classes=10)
    variables = model.init(jax.random.PRNGKey(0),
                           jnp.zeros((1, 32, 32, 3)), train=False)
    params, batch_stats = variables["params"], variables["batch_stats"]
    tx = optax.sgd(0.1, momentum=0.9)
    bsz = (2 if args.quick else 8) * n
    img = np.random.RandomState(2).rand(bsz, 32, 32, 3).astype(np.float32)
    lab = np.random.RandomState(3).randint(0, 10, bsz).astype(np.int32)
    cands = {}
    for nb in ((1, 4) if args.quick else (1, 2, 4, 8, 16)):
        # barrier=True only matters with >1 bucket: it is the lever that
        # keeps buckets distinct through XLA's combiner (see
        # overlap_analyze.py), so measure both scheduling modes.
        for barrier in ((False, True) if nb > 1 else (False,)):
            mpi.set_config(gradsync_buckets=nb, gradsync_barrier=barrier)
            step = mpi.recipes.make_bn_dp_train_step(model, tx, mesh=mesh,
                                                     donate=False)
            p2, o2, b2 = mpi.recipes.replicate_bn_state(
                params, tx.init(params), batch_stats, mesh=mesh)

            def run(p2=p2, o2=o2, b2=b2, step=step):
                return step(p2, o2, b2, img, lab)[3]

            cands[(nb, barrier)] = _measure(run, max(2, args.iters // 2),
                                            fence)
            print(json.dumps({"phase": "buckets", "buckets": nb,
                              "barrier": barrier,
                              **_ms(cands[(nb, barrier)])}))
    chosen, ev = _gate(cands, (defaults.gradsync_buckets,
                               defaults.gradsync_barrier))
    rec["gradsync_buckets"], rec["gradsync_barrier"] = chosen
    evidence["gradsync_buckets"] = ev

    # -- 4. flash-attention block sizes (real TPU only: Mosaic tiling) ----
    # Timed through value_and_grad over flash_attention_grad — the
    # training path the knobs primarily serve — so a tiling that wins the
    # forward but loses the dq/dkv backward kernels cannot be recommended.
    if not is_cpu:
        from torchmpi_tpu.ops.flash import flash_attention_grad

        Bf, Tf, Hf, Df = 2, (1024 if args.quick else 4096), 8, 128
        rngf = np.random.RandomState(4)
        qkv = [jnp.asarray(rngf.randn(Bf, Tf, Hf, Df), jnp.bfloat16)
               for _ in range(3)]
        cands = {}
        # Quick grid includes the beyond-512 candidates (VERDICT r4 #2):
        # the full-block mask-skip specialization shifted the VPU:MXU
        # balance, so the 512x512 plateau must be re-derived.
        grid = ((256, 256), (512, 512), (1024, 512),
                (512, 1024)) if args.quick else \
            ((128, 128), (256, 256), (512, 256), (256, 512), (512, 512),
             (512, 1024), (1024, 512), (1024, 1024), (2048, 512),
             (768, 512))
        for bq, bk in grid:
            try:
                def fwd_bwd(q, k, v, bq=bq, bk=bk):
                    def loss(q, k, v):
                        o = flash_attention_grad(q, k, v, causal=True,
                                                 block_q=bq, block_k=bk)
                        return jnp.sum(o.astype(jnp.float32) ** 2)

                    return jax.grad(loss, argnums=(0, 1, 2))(q, k, v)

                f = jax.jit(fwd_bwd)
                cands[(bq, bk)] = _measure(lambda: f(*qkv), args.iters,
                                           fence)
            except Exception as e:  # noqa: BLE001 — invalid tiling, skip
                print(json.dumps({"phase": "flash_blocks",
                                  "block_q": bq, "block_k": bk,
                                  "error": str(e)[:120]}))
                continue
            print(json.dumps({"phase": "flash_blocks", "block_q": bq,
                              "block_k": bk, **_ms(cands[(bq, bk)])}))
        if cands:
            chosen, ev = _gate(cands, (defaults.flash_block_q,
                                       defaults.flash_block_k))
            rec["flash_block_q"], rec["flash_block_k"] = chosen
            evidence["flash_blocks"] = ev
        del qkv

    # -- 5. fused-xent block sizes (real TPU only) -------------------------
    if not is_cpu:
        from torchmpi_tpu.ops.xent import fused_linear_cross_entropy

        Nx, Ex, Vx = (2048 if args.quick else 8192), 1024, 32768
        rngx = np.random.RandomState(5)
        xx = jnp.asarray(rngx.randn(Nx, Ex) * 0.05, jnp.bfloat16)
        wx = jnp.asarray(rngx.randn(Ex, Vx) * 0.05, jnp.bfloat16)
        lx = jnp.asarray(rngx.randint(0, Vx, size=Nx), jnp.int32)
        cands = {}
        grid = ((128, 512), (256, 512)) if args.quick else \
            ((128, 512), (128, 1024), (256, 512), (256, 1024), (512, 512))
        for bn, bv in grid:
            try:
                f = jax.jit(lambda x, w, l, bn=bn, bv=bv:
                            fused_linear_cross_entropy(
                                x, w, l, block_n=bn, block_v=bv).mean())
                cands[(bn, bv)] = _measure(lambda: f(xx, wx, lx),
                                           args.iters, fence)
            except Exception as e:  # noqa: BLE001 — invalid tiling, skip
                print(json.dumps({"phase": "xent_blocks", "block_n": bn,
                                  "block_v": bv, "error": str(e)[:120]}))
                continue
            print(json.dumps({"phase": "xent_blocks", "block_n": bn,
                              "block_v": bv, **_ms(cands[(bn, bv)])}))
        if cands:
            chosen, ev = _gate(cands, (defaults.xent_block_n,
                                       defaults.xent_block_v))
            rec["xent_block_n"], rec["xent_block_v"] = chosen
            evidence["xent_blocks"] = ev
        del xx, wx, lx

    print(json.dumps({"recommend": True,
                      "platform": "cpu-sim" if is_cpu else "tpu",
                      "devices": n, "rounds": ROUNDS,
                      "noise_gated": True,
                      "config": rec, "evidence": evidence}))
    mpi.stop()


if __name__ == "__main__":
    main()

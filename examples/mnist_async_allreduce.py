"""MNIST LeNet, data-parallel SGD with overlapped gradient sync.

Reference analog: ``examples/mnist_allreduce_async.lua`` [MED] (reconstructed
— reference mount empty, SURVEY.md §0/§4.3): per-layer async allreduce hooks
fired during backward, synced before the optimizer step.  Two TPU-native
expressions of that overlap:

- default: K bucketed collectives inside one jit — XLA's scheduler
  overlaps bucket transfers with remaining computation (SURVEY §8.4.3).
- ``TORCHMPI_TPU_GRADSYNC_OVERLAP=1``: the first-class backprop-overlapped
  schedule (docs/OVERLAP.md) — ``gradsync.make_overlapped_grad_fn``
  fires each reverse-parameter-order bucket's allreduce INSIDE the
  backward pass as its cotangents materialize, the literal analog of
  the reference's per-layer hooks.  Bit-identical gradients either way.

Run: ``python examples/mnist_async_allreduce.py --devices 8 --buckets 4``
Or:  ``TORCHMPI_TPU_GRADSYNC_OVERLAP=1 python examples/mnist_async_allreduce.py --devices 8``
"""

import common


def main():
    args = common.parse_args(__doc__)
    if args.buckets is None:
        args.buckets = 4
    import jax
    import optax

    import torchmpi_tpu as mpi
    from torchmpi_tpu.models import LeNet
    from torchmpi_tpu.utils import data as dutil

    mpi.init(mpi.Config(dcn_size=args.dcn, gradsync_buckets=args.buckets))
    mesh = mpi.world_mesh()
    model = LeNet()
    params, tx, opt_state, local_loss = common.make_train_tools(
        model, (1, 28, 28, 1), args.lr, args.momentum, args.seed)

    overlap = mpi.config().gradsync_overlap == "auto"

    def step(params, opt_state, images, labels):
        if overlap:
            # Backprop-overlapped schedule: bucket allreduces fire in
            # the backward pass itself; grads return already reduced.
            loss, grads = mpi.nn.make_overlapped_grad_fn(
                local_loss, params, mesh.axis_names)(params, images,
                                                     labels)
        else:
            loss, grads = jax.value_and_grad(local_loss)(params, images,
                                                         labels)
            # n_buckets comes from config; each bucket is an independent
            # collective XLA may overlap (the async-hooks analog).
            grads = mpi.nn.synchronize_gradients(grads)
        loss = mpi.collectives.allreduce_in_axis(loss, mesh.axis_names,
                                                 op="mean")
        updates, opt_state = tx.update(grads, opt_state, params)
        return optax.apply_updates(params, updates), opt_state, loss

    dp_step = mpi.nn.data_parallel_step(step, batch_argnums=(2, 3))
    params = mpi.nn.synchronize_parameters(params)
    opt_state = mpi.nn.synchronize_parameters(opt_state)

    X, Y = dutil.synthetic_mnist(4096, seed=args.seed)
    for i, (xb, yb) in enumerate(
            dutil.batches(X, Y, args.batch_size, steps=args.steps,
                          seed=args.seed)):
        params, opt_state, loss = dp_step(params, opt_state, xb, yb)
        if i % 20 == 0 or i == args.steps - 1:
            print(f"step {i:4d}  loss {float(loss):.4f}")
    acc = common.evaluate(model, params, X[:1024], Y[:1024])
    print(f"final accuracy {acc:.3f}")
    mpi.stop()
    assert acc > 0.9, "bucketed data-parallel MNIST did not converge"


if __name__ == "__main__":
    main()

"""MNIST LeNet, annotation-driven FSDP (fully-sharded data parallelism).

Beyond the reference (TorchMPI was replicated-state DP only — SURVEY.md
§3.3); this is the GSPMD / scaling-book way to shard: parameters and
optimizer state LIVE sharded per-parameter (`recipes.fsdp_specs`), the
train step is plain single-program jit, and XLA inserts the per-use
parameter all-gathers and gradient reduce-scatters itself.  Batches are
placed with the mesh sharding by the same `prefetch_to_mesh` pipeline the
other examples use.  Numerics equal full-batch single-device SGD
(tests/test_zero.py proves it); this script proves convergence and that
the persistent state stays at 1/n per device through real training.

Run: ``python examples/mnist_fsdp.py --devices 8 --steps 150``
"""

import common


def main():
    args = common.parse_args(__doc__, defaults={"lr": 0.02, "steps": 150,
                                                "batch_size": 128})
    import jax
    import jax.numpy as jnp
    import numpy as np
    import optax
    from jax.sharding import PartitionSpec as P

    import torchmpi_tpu as mpi
    from torchmpi_tpu.models import LeNet
    from torchmpi_tpu.utils import data as dutil
    from torchmpi_tpu.utils.input_pipeline import prefetch_to_mesh

    mpi.init(mpi.Config(dcn_size=args.dcn))
    mesh = mpi.world_mesh()
    axes = tuple(mesh.axis_names)

    model = LeNet(num_classes=10)
    params = model.init(jax.random.PRNGKey(args.seed),
                        jnp.zeros((1, 28, 28, 1)))["params"]
    tx = optax.sgd(args.lr, momentum=args.momentum)
    step, params, opt_state = mpi.recipes.make_fsdp_train_step(
        model, tx, params, mesh=mesh)

    X, Y = dutil.synthetic_mnist(4096, seed=args.seed)
    timer = common.StepTimer()
    timer.start()
    it = prefetch_to_mesh(
        dutil.batches(X, Y, args.batch_size, steps=args.steps,
                      seed=args.seed), mesh, P(axes))
    for i, (xb, yb) in enumerate(it):
        params, opt_state, loss = step(params, opt_state, xb, yb)
        timer.tick()
        if i % 25 == 0 or i == args.steps - 1:
            print(f"step {i:4d}  loss {float(loss):.4f}")

    # Persistent state is still 1/n per device after real training — for
    # every leaf fsdp_specs actually sharded (a device count that divides
    # no dimension of a leaf legitimately replicates that leaf).
    from jax.sharding import PartitionSpec
    n = mesh.devices.size
    specs = mpi.recipes.fsdp_specs(params, mesh=mesh)
    sharded = 0
    for leaf, spec in zip(jax.tree.leaves(params), jax.tree.leaves(specs)):
        if spec != PartitionSpec():
            assert len(leaf.sharding.device_set) == n
            assert (max(s.data.size for s in leaf.addressable_shards)
                    == leaf.size // n)
            sharded += 1
    print(f"sharded param leaves: {sharded}/{len(jax.tree.leaves(params))}")

    # Evaluate with the sharded params directly — jit gathers them per use.
    logits = jax.jit(lambda p, x: model.apply({"params": p}, x))(
        params, jnp.asarray(X[:1024]))
    acc = float((np.argmax(np.asarray(logits), 1) == Y[:1024]).mean())
    print(f"final accuracy {acc:.3f}  "
          f"({timer.rate(args.batch_size):.0f} img/s)")
    mpi.stop()
    assert acc > 0.9, "FSDP LeNet did not converge"


if __name__ == "__main__":
    main()

"""Elastic Downpour: async-PS training that SURVIVES worker loss.

The reference had no elasticity anywhere — an MPI rank failure aborted the
whole job (SURVEY.md §6.3: "an MPI rank failure aborts the job; no
elasticity").  That is unavoidable for gang-scheduled SPMD (this rebuild
keeps that failure model for the collective path, recovering via
checkpoint-restart), but asynchronous parameter-server training is exactly
the place failure IS survivable: no worker ever waits on another, so a
dead worker just stops contributing gradients.

This example proves it end to end: mid-training, a "failing" worker dies
(simulated crash — it simply stops, pushing nothing more, holding no
lock); the survivors keep pushing to the shard servers and the model still
converges.  A monitor thread detects the loss by watching per-worker
progress counters go stale — the same heartbeat-style detection the PS
client's ``ping()`` provides for server liveness, applied to workers.

Run: ``python examples/downpour_elastic.py --devices 8 --workers 4``

Chaos walkthrough (docs/FAULTS.md): the same run under an injected
transient PS fault — the fault layer retries the dropped exchanges and
every worker still finishes::

    python scripts/chaos_tool.py gen --out /tmp/elastic_chaos.json \
        --seed 11 --rule ps.request:drop:1.0:2:0.02
    TORCHMPI_TPU_FAULTS=/tmp/elastic_chaos.json \
        python examples/downpour_elastic.py --devices 8 --workers 4

Running this walkthrough exposed two latent robustness gaps, both fixed
below: (1) a worker whose PS exchange stayed dead (``PeerTimeoutError``
after the retry budget) crashed the WHOLE job through ``run_workers`` —
in an elastic system a worker that loses its parameter server is just a
dead worker, so ``guarded`` now retires it and lets the monitor report
the loss; (2) a worker that exited (crash or fault) with its prefetch
``receive()`` still in flight left the handle to a garbage-collection-
time drain against a possibly-wedged server — the worker now settles
its own prefetch on the way out, bounded by the socket timeout
(``Config.ps_timeout_s``), which this example predated.
"""

import threading
import time

import common


def main():
    args = common.parse_args(
        __doc__,
        workers=dict(type=int, default=4),
        fetch_every=dict(type=int, default=5),
        shards=dict(type=int, default=2),
        die_at=dict(type=int, default=30,
                    help="step at which worker 0 crashes"),
        # 200 steps (not 120): convergence after losing a worker at step
        # 30 is timing-sensitive under async staleness on a loaded host —
        # the longer survivor run makes the >0.9 assert robust without
        # weakening it (observed: 120 steps flaked to 0.81 once under
        # full-sweep CPU contention, 1.0 rerun).
        defaults={"steps": 200, "batch_size": 64, "lr": 0.02},
    )
    import jax
    import jax.numpy as jnp
    import numpy as np
    import optax

    import torchmpi_tpu as mpi
    from torchmpi_tpu.models import LeNet
    from torchmpi_tpu.utils import data as dutil

    mpi.init()
    model = LeNet()
    params0 = model.init(jax.random.PRNGKey(args.seed),
                         jnp.zeros((1, 28, 28, 1)))
    ps = mpi.parameterserver.init(params0, num_shards=args.shards)

    def local_loss(p, images, labels):
        logits = model.apply(p, images)
        return optax.softmax_cross_entropy_with_integer_labels(
            logits, labels).mean()

    grad_fn = jax.jit(jax.value_and_grad(local_loss))
    # run_workers clamps to the device count; every per-worker structure
    # must use the clamped count or the final asserts cover ghosts.
    n_workers = min(args.workers, len(jax.devices()))
    devices = jax.devices()[:n_workers]
    X, Y = dutil.synthetic_mnist(4096, seed=args.seed)
    progress = [0] * n_workers  # per-worker step counters (heartbeats)

    class SimulatedCrash(Exception):
        pass

    def worker(widx):
        dev = devices[widx]
        with jax.default_device(dev):
            params = jax.tree.map(jnp.asarray, params0)
            fetch_handle = None
            try:
                for step, (xb, yb) in enumerate(dutil.batches(
                        X, Y, args.batch_size, steps=args.steps,
                        seed=args.seed + widx + 1)):
                    if widx == 0 and step == args.die_at:
                        raise SimulatedCrash(
                            f"worker 0 dies at step {step}")
                    _, grads = grad_fn(params, jnp.asarray(xb),
                                       jnp.asarray(yb))
                    update = jax.tree.map(
                        lambda g: -args.lr * np.asarray(g), grads)
                    ps.send(update, rule="add")
                    params = jax.tree.map(lambda p, u: p + u, params,
                                          jax.tree.map(jnp.asarray,
                                                       update))
                    progress[widx] = step + 1
                    if fetch_handle is not None and fetch_handle.done:
                        params = jax.tree.map(jnp.asarray,
                                              fetch_handle.wait())
                        fetch_handle = None
                    if step % args.fetch_every == 0 and \
                            fetch_handle is None:
                        fetch_handle = ps.receive()
            finally:
                # Latent-hang fix (chaos walkthrough above): never exit
                # with the prefetch in flight.  The wait is bounded by
                # the socket timeout; a failed/late prefetch on a dying
                # worker is simply discarded.
                if fetch_handle is not None:
                    try:
                        fetch_handle.wait()
                    except Exception:  # noqa: BLE001 — worker is done
                        pass

    # Failure detector: a worker whose counter stops advancing while the
    # job is still running is declared dead (no gang abort — just noted).
    dead = set()
    stop_monitor = threading.Event()

    def monitor():
        last = list(progress)
        stale = [0] * n_workers
        while not stop_monitor.is_set():
            time.sleep(0.25)
            for w in range(n_workers):
                advanced = progress[w] != last[w]
                if advanced and w in dead:
                    # A stall (e.g. first-step jit compile) is not a crash;
                    # progress resurrects the worker.
                    dead.discard(w)
                    print(f"monitor: worker {w} recovered at step "
                          f"{progress[w]}")
                if w in dead:
                    continue
                # Warm-up guard: before the first completed step a worker
                # is compiling, not dead.
                if (not advanced and 0 < progress[w] < args.steps):
                    stale[w] += 1
                    if stale[w] >= 8:  # ~2s without progress
                        dead.add(w)
                        print(f"monitor: worker {w} lost at step "
                              f"{progress[w]} — continuing without it")
                else:
                    stale[w] = 0
                last[w] = progress[w]

    mon = threading.Thread(target=monitor, daemon=True)
    mon.start()
    # run_workers propagates exceptions; the simulated crash must not kill
    # the job, so worker 0's death is caught and recorded instead.  The
    # same goes for a worker whose parameter-server exchanges stayed dead
    # past the fault layer's retry budget (PeerTimeoutError/
    # RetriesExhaustedError under TORCHMPI_TPU_FAULTS): elastically, that
    # is one lost worker, not a job failure — the monitor reports it and
    # the survivors keep training.
    crashed = []
    fault_lost = set()

    def _fault_errors():
        import sys as _sys

        mod = _sys.modules.get("torchmpi_tpu.faults")
        if mod is None:  # faults off: the classes don't exist
            return ()
        return (mod.PeerTimeoutError, mod.RetriesExhaustedError,
                mod.FaultError)

    def guarded(widx):
        try:
            worker(widx)
        except SimulatedCrash as e:
            crashed.append(str(e))
        except _fault_errors() as e:
            fault_lost.add(widx)
            crashed.append(f"worker {widx} lost its PS: {e!r}")

    common.run_workers(guarded, n_workers)
    stop_monitor.set()
    mon.join(timeout=5)

    center = jax.tree.map(jnp.asarray, ps.receive().wait())
    acc = common.evaluate(model, center, X[:1024], Y[:1024])
    survivors = [w for w in range(n_workers)
                 if w != 0 and w not in fault_lost]
    print(f"crashed: {crashed}")
    print(f"detected dead: {sorted(dead)}")
    print(f"fault-lost workers: {sorted(fault_lost)}")
    print(f"survivor steps: {[progress[w] for w in survivors]}")
    print(f"final accuracy (PS params) {acc:.3f}")
    ps.shutdown()
    mpi.stop()
    assert crashed, "worker 0 should have crashed"
    assert 0 in dead, "monitor failed to detect the lost worker"
    assert survivors, "every worker died — nothing elastic survived"
    assert all(progress[w] == args.steps for w in survivors), \
        "survivors did not finish"
    assert acc > 0.9, "elastic downpour did not converge"


if __name__ == "__main__":
    main()

"""Train a small LM, then decode with the KV cache — the serving path.

Beyond-reference demo (the reference predates LMs — SURVEY.md §6.7):
trains TransformerLM on the learnable next-token task
``t_{i+1} = (3 t_i + 1) mod V``, then uses :func:`models.generate`
(KV-cache autoregressive decoding, one jitted scan) to continue held-out
prompts and asserts the continuations follow the learned rule — the
decode analog of the examples' convergence assertions (SURVEY.md §5).

Run: ``python examples/lm_generate.py --devices 1 [--steps 250]``
"""

import common


def main():
    args = common.parse_args(
        __doc__,
        seq_len=dict(type=int, default=16),
        vocab=dict(type=int, default=32),
        gen_steps=dict(type=int, default=8),
        defaults={"steps": 250, "batch_size": 32, "lr": 3e-3},
    )
    import jax
    import jax.numpy as jnp
    import numpy as np
    import optax

    import torchmpi_tpu as mpi
    from torchmpi_tpu.models import TransformerLM, beam_search, generate

    mpi.init()
    V, T = args.vocab, args.seq_len
    model = TransformerLM(vocab=V, embed=64, depth=2, num_heads=4,
                          head_dim=8, max_len=T)

    def make_batch(rng, batch):
        t0 = rng.randint(0, V, size=(batch, 1))
        toks = [t0]
        for _ in range(T - 1):
            toks.append((toks[-1] * 3 + 1) % V)
        return np.concatenate(toks, axis=1).astype(np.int32)

    rng = np.random.RandomState(args.seed)
    params = model.init(jax.random.PRNGKey(args.seed),
                        jnp.asarray(make_batch(rng, 2)))["params"]
    tx = optax.adam(args.lr)
    opt_state = tx.init(params)

    @jax.jit
    def step(p, o, toks):
        def loss_fn(p):
            logits = model.apply({"params": p}, toks)
            return optax.softmax_cross_entropy_with_integer_labels(
                logits[:, :-1].astype(jnp.float32), toks[:, 1:]).mean()

        loss, g = jax.value_and_grad(loss_fn)(p)
        u, o = tx.update(g, o, p)
        return optax.apply_updates(p, u), o, loss

    for i in range(args.steps):
        params, opt_state, loss = step(
            params, opt_state, jnp.asarray(make_batch(rng, args.batch_size)))
        if i % 50 == 0:
            print(f"step {i:4d}  loss {float(loss):.4f}")
    print(f"final train loss {float(loss):.4f}")

    # Decode held-out prompts; the continuation must follow the rule —
    # through every decode mode the serving path offers.
    prompts = make_batch(np.random.RandomState(args.seed + 999), 8)[:, :4]

    def rule_acc(out):
        correct = total = 0
        for b in range(out.shape[0]):
            t = int(prompts[b, -1])
            for j in range(4, 4 + args.gen_steps):
                t = (t * 3 + 1) % V
                correct += int(out[b, j] == t)
                total += 1
        return correct / total

    out = np.asarray(generate(model, params, prompts,
                              steps=args.gen_steps))
    acc = rule_acc(out)
    print(f"greedy decode: {out.shape[0]} prompts x {args.gen_steps} "
          f"tokens, rule accuracy {acc:.3f}")
    print(f"sample: prompt {prompts[0].tolist()} -> "
          f"{out[0, 4:].tolist()}")

    # A trained model's rule tokens sit inside any reasonable nucleus, so
    # filtered sampling must follow the rule too; beam search likewise.
    acc_s = rule_acc(np.asarray(generate(
        model, params, prompts, steps=args.gen_steps, temperature=0.7,
        top_k=4, top_p=0.95, rng=jax.random.PRNGKey(7))))
    acc_b = rule_acc(np.asarray(beam_search(
        model, params, prompts, steps=args.gen_steps, beams=4,
        length_penalty=0.6)))
    print(f"top-k/top-p sampled accuracy {acc_s:.3f}, "
          f"beam-4 accuracy {acc_b:.3f}")

    # EOS stopping: pick the rule successor of the first prompt's last
    # token as a stop token — that row must emit it immediately and
    # eos-pad the rest, while rows whose rule path never hits it keep
    # decoding.
    eos = int((3 * prompts[0, -1] + 1) % V)
    out_e = np.asarray(generate(model, params, prompts,
                                steps=args.gen_steps, eos_id=eos))
    stopped = out_e[0, prompts.shape[1]:]
    print(f"eos={eos} stopping: row 0 -> {stopped.tolist()}")
    assert (stopped == eos).all(), "row hitting eos must flatline"

    mpi.stop()
    assert acc > 0.8, "greedy continuations do not follow the rule"
    assert acc_b > 0.8, "beam continuations do not follow the rule"
    assert acc_s > 0.5, "sampled continuations ignore the rule"


if __name__ == "__main__":
    main()

"""ImageNet ResNet-50 data-parallel — the headline workload (BASELINE
config 3; target: >=90% scaling efficiency img/s/chip on v5e-64).

Reference analog: fb.resnet.torch ResNet-50 under ``torchmpi.nn``
(SURVEY.md §8.1, reconstructed — reference mount empty).  Uses synthetic
ImageNet-shaped data (no-egress environment); the interesting part is the
step throughput and its scaling, which synthetic data measures faithfully.

Run (simulated): ``python examples/imagenet_resnet50.py --devices 8 --steps 5
                   --batch-size 32 --image-size 64``
Run (real chip): ``python examples/imagenet_resnet50.py --steps 30
                   --batch-size 256 --bf16``
"""

import common


def main():
    args = common.parse_args(
        __doc__,
        image_size=dict(type=int, default=224),
        num_classes=dict(type=int, default=1000),
        bf16=dict(action="store_true", help="bfloat16 compute"),
        warmup=dict(type=int, default=3),
        zero=dict(action="store_true",
                  help="ZeRO-1: shard optimizer state over the mesh"),
    )
    import jax
    import jax.numpy as jnp
    import numpy as np
    import optax

    import torchmpi_tpu as mpi
    from torchmpi_tpu.models import ResNet50
    from torchmpi_tpu.utils import data as dutil

    mpi.init(mpi.Config(dcn_size=args.dcn))
    if args.backend:
        mpi.set_config(backend=args.backend, custom_min_bytes=0)
    mesh = mpi.world_mesh()
    n_dev = mpi.device_count()
    print(f"mesh {dict(zip(mesh.axis_names, mesh.devices.shape))}")

    dtype = jnp.bfloat16 if args.bf16 else jnp.float32
    model = ResNet50(num_classes=args.num_classes, dtype=dtype)
    variables = model.init(
        jax.random.PRNGKey(args.seed),
        jnp.zeros((1, args.image_size, args.image_size, 3)), train=False)
    params, batch_stats = variables["params"], variables["batch_stats"]
    tx = optax.sgd(args.lr, momentum=args.momentum)
    n_params = sum(int(np.prod(p.shape)) for p in jax.tree.leaves(params))
    print(f"ResNet-50: {n_params/1e6:.1f}M params, dtype {dtype.__name__}"
          + (", ZeRO-1 sharded optimizer" if args.zero else ""))

    dp_step = mpi.recipes.make_bn_dp_train_step(model, tx, mesh=mesh,
                                                backend=args.backend,
                                                n_buckets=args.buckets,
                                                zero=args.zero)
    if args.zero:
        from torchmpi_tpu.parallel import zero as zero_lib

        params = mpi.nn.synchronize_parameters(params, mesh=mesh)
        batch_stats = mpi.nn.synchronize_parameters(batch_stats, mesh=mesh)
        opt_state = zero_lib.init(params, tx, mesh=mesh)  # sharded, 1/n mem
    else:
        params, opt_state, batch_stats = mpi.recipes.replicate_bn_state(
            params, tx.init(params), batch_stats, mesh=mesh)

    X, Y = dutil.synthetic_image_classification(
        max(512, args.batch_size * 2),
        image_shape=(args.image_size, args.image_size, 3),
        num_classes=args.num_classes, seed=args.seed)

    # Host batches stage onto the mesh from a background thread, so the
    # (slow on relay hosts) host->device copy of batch N+1 overlaps step N.
    from jax.sharding import PartitionSpec as P

    from torchmpi_tpu.utils.input_pipeline import prefetch_to_mesh

    it = prefetch_to_mesh(
        dutil.batches(X, Y, args.batch_size,
                      steps=args.steps + args.warmup, seed=args.seed),
        mesh, P(mesh.axis_names), depth=2)
    import time

    for i, (xb, yb) in enumerate(it):
        if i == args.warmup:
            jax.block_until_ready(jax.tree.leaves(params)[0])
            t0 = time.time()
        params, opt_state, batch_stats, loss = dp_step(
            params, opt_state, batch_stats, xb, yb)
        if i % 10 == 0:
            print(f"step {i:4d}  loss {float(loss):.4f}")
    jax.block_until_ready(jax.tree.leaves(params)[0])
    dt = time.time() - t0
    imgs = args.steps * args.batch_size
    print(f"throughput {imgs/dt:.1f} img/s total, "
          f"{imgs/dt/n_dev:.1f} img/s/chip ({n_dev} devices)")
    mpi.stop()


if __name__ == "__main__":
    main()

"""Train an expert-parallel MoE LM, then decode it expert-parallel.

Beyond-reference demo (the reference predates LMs — SURVEY.md §6.7;
its parallelism is DP-only, SURVEY.md §3.3): trains a top-k MoE
TransformerLM with experts sharded over ``ici`` and batch over ``dcn``
on the learnable rule ``t_{i+1} = (3 t_i + 1) mod V``, then samples
continuations with :func:`models.generate_parallel` — the SAME mesh and
expert sharding at decode time, each step routing its token batch
through the dispatch/combine all-to-all — and asserts the continuations
follow the learned rule (the decode analog of the examples' convergence
assertions, SURVEY.md §5).

Run: ``python examples/moe_generate.py --devices 8 [--dcn 2]``
"""

import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main():
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--devices", type=int, default=0)
    p.add_argument("--dcn", type=int, default=None)
    p.add_argument("--steps", type=int, default=150)
    p.add_argument("--batch-size", type=int, default=16)
    p.add_argument("--seq-len", type=int, default=16)
    p.add_argument("--vocab", type=int, default=32)
    p.add_argument("--lr", type=float, default=3e-3)
    p.add_argument("--gen-steps", type=int, default=8)
    p.add_argument("--seed", type=int, default=0)
    args = p.parse_args()
    if args.devices:
        from torchmpi_tpu.utils.simulation import force_cpu_devices

        force_cpu_devices(args.devices)

    import jax
    import jax.numpy as jnp
    import numpy as np
    import optax
    from jax import lax, shard_map
    from jax.sharding import NamedSharding, PartitionSpec as P

    import torchmpi_tpu as mpi
    from torchmpi_tpu.models import TransformerLM, generate_parallel

    mpi.init(mpi.Config(dcn_size=args.dcn))
    mesh = mpi.world_mesh()
    n_dp = mesh.shape[mpi.DCN_AXIS]
    V, T = args.vocab, args.seq_len
    assert args.batch_size % n_dp == 0
    print(f"mesh {dict(zip(mesh.axis_names, mesh.devices.shape))}: "
          f"batch over dcn({n_dp}), experts over ici")

    # capacity_factor is generous so training-time and decode-time routing
    # agree exactly (no capacity overflow in either token count).
    model = TransformerLM(vocab=V, embed=64, depth=2, num_heads=4,
                          head_dim=16, max_len=T, moe_axis=mpi.ICI_AXIS,
                          moe_experts_per_device=1, moe_k=2,
                          moe_capacity_factor=8.0)

    def make_batch(rng, batch):
        t0 = rng.randint(0, V, size=(batch, 1))
        toks = [t0]
        for _ in range(T - 1):
            toks.append((toks[-1] * 3 + 1) % V)
        return np.concatenate(toks, axis=1).astype(np.int32)

    spec = P(mpi.DCN_AXIS)
    rng = np.random.RandomState(args.seed)
    tok0 = jax.device_put(make_batch(rng, args.batch_size),
                          NamedSharding(mesh, spec))

    def init_fn(tok):
        return model.init(jax.random.PRNGKey(args.seed), tok)

    variables = jax.jit(shard_map(init_fn, mesh=mesh, in_specs=spec,
                                  out_specs=P(), check_vma=False))(tok0)
    tx = optax.adam(args.lr)
    opt_state = tx.init(variables)

    def step(vs, opt_state, tok):
        def loss_fn(v):
            logits, sown = model.apply(v, tok, mutable=["losses"])
            losses = optax.softmax_cross_entropy_with_integer_labels(
                logits[:, :-1], tok[:, 1:])
            aux = sum(jax.tree.leaves(sown["losses"]))
            return lax.pmean(losses.mean() + 1e-2 * aux, mesh.axis_names)

        loss, grads = jax.value_and_grad(loss_fn)(vs)
        grads = mpi.nn.synchronize_gradients(grads, mesh.axis_names,
                                             op="sum")
        updates, opt_state = tx.update(grads, opt_state, vs)
        return optax.apply_updates(vs, updates), opt_state, loss

    ep_step = jax.jit(shard_map(
        step, mesh=mesh, in_specs=(P(), P(), spec),
        out_specs=(P(), P(), P()), check_vma=False), donate_argnums=(0, 1))
    variables = mpi.nn.synchronize_parameters(variables)
    opt_state = mpi.nn.synchronize_parameters(opt_state)
    for i in range(args.steps):
        tok = jax.device_put(make_batch(rng, args.batch_size),
                             NamedSharding(mesh, spec))
        variables, opt_state, loss = ep_step(variables, opt_state, tok)
        if i % 50 == 0:
            print(f"step {i:4d}  loss {float(loss):.4f}")
    print(f"final train loss {float(loss):.4f}")

    # Expert-parallel greedy decode on the same mesh; continuations must
    # follow the learned rule.
    n_prompts = 2 * n_dp
    prompts = make_batch(np.random.RandomState(args.seed + 999),
                         n_prompts)[:, :4]
    out = np.asarray(generate_parallel(
        model, variables["params"], prompts, steps=args.gen_steps,
        mesh=mesh, batch_axis=mpi.DCN_AXIS))
    correct = total = 0
    for b in range(out.shape[0]):
        t = int(prompts[b, -1])
        for j in range(4, 4 + args.gen_steps):
            t = (t * 3 + 1) % V
            correct += int(out[b, j] == t)
            total += 1
    acc = correct / total
    print(f"EP decode: {n_prompts} prompts x {args.gen_steps} tokens, "
          f"rule accuracy {acc:.3f}")
    print(f"sample: prompt {prompts[0].tolist()} -> {out[0, 4:].tolist()}")

    # Expert-parallel BEAM decode (VERDICT r3 #7): the same mesh and
    # expert sharding, B*K beam rows through the dispatch/combine
    # all-to-all each step; a trained model's rule path dominates every
    # beam, so beam-3 must follow the rule too.
    from torchmpi_tpu.models import beam_search_parallel

    out_b = np.asarray(beam_search_parallel(
        model, variables["params"], prompts, steps=args.gen_steps,
        beams=3, mesh=mesh, batch_axis=mpi.DCN_AXIS))
    correct = total = 0
    for b in range(out_b.shape[0]):
        t = int(prompts[b, -1])
        for j in range(4, 4 + args.gen_steps):
            t = (t * 3 + 1) % V
            correct += int(out_b[b, j] == t)
            total += 1
    acc_b = correct / total
    print(f"EP beam-3 decode: rule accuracy {acc_b:.3f}")
    mpi.stop()
    assert acc > 0.8, "EP-decoded continuations do not follow the rule"
    assert acc_b > 0.8, "EP beam continuations do not follow the rule"


if __name__ == "__main__":
    main()

"""MNIST LeNet, asynchronous EASGD via the parameter server's elastic rule.

Reference analog: ``examples/mnist_easgd.lua`` [HIGH] (reconstructed —
reference mount empty, SURVEY.md §3 C15, §4.5): each worker runs *local* SGD
and every ``tau`` steps performs an elastic exchange with the center
variable: ``delta = alpha * (x_i - center)``; the server moves the center by
``+delta`` (RULE_ELASTIC) and the worker moves itself by ``-delta`` — the
symmetric elastic averaging of Zhang et al., exactly the update the
reference implemented server-side.

Run: ``python examples/mnist_easgd.py --devices 8 --workers 4``
"""

import common


def main():
    args = common.parse_args(
        __doc__,
        workers=dict(type=int, default=4),
        tau=dict(type=int, default=4),
        alpha=dict(type=float, default=0.3),
        shards=dict(type=int, default=2),
        defaults={"steps": 120, "batch_size": 64, "lr": 0.02},
    )
    import jax
    import jax.numpy as jnp
    import numpy as np
    import optax

    import torchmpi_tpu as mpi
    from torchmpi_tpu.models import LeNet
    from torchmpi_tpu.utils import data as dutil

    mpi.init()
    model = LeNet()
    params0 = model.init(jax.random.PRNGKey(args.seed),
                         jnp.zeros((1, 28, 28, 1)))
    ps = mpi.parameterserver.init(params0, num_shards=args.shards)

    def local_loss(p, images, labels):
        logits = model.apply(p, images)
        return optax.softmax_cross_entropy_with_integer_labels(
            logits, labels).mean()

    grad_fn = jax.jit(jax.value_and_grad(local_loss))
    devices = jax.devices()[: args.workers]
    X, Y = dutil.synthetic_mnist(4096, seed=args.seed)

    def worker(widx):
        dev = devices[widx]
        with jax.default_device(dev):
            params = jax.tree.map(jnp.asarray, params0)
            for step, (xb, yb) in enumerate(dutil.batches(
                    X, Y, args.batch_size, steps=args.steps,
                    seed=args.seed + widx + 1)):
                _, grads = grad_fn(params, jnp.asarray(xb), jnp.asarray(yb))
                params = jax.tree.map(lambda p, g: p - args.lr * g,
                                      params, grads)
                if step % args.tau == args.tau - 1:
                    delta = ps.send(params, rule="elastic",
                                    alpha=args.alpha).wait()
                    params = jax.tree.map(
                        lambda p, d: p - jnp.asarray(d), params, delta)

    common.run_workers(worker, args.workers)

    center = jax.tree.map(jnp.asarray, ps.receive().wait())
    acc = common.evaluate(model, center, X[:1024], Y[:1024])
    print(f"PS ops served: {ps.ops_served()}")
    print(f"final accuracy (center) {acc:.3f}")
    ps.shutdown()
    mpi.stop()
    assert acc > 0.9, "EASGD MNIST did not converge"


if __name__ == "__main__":
    main()

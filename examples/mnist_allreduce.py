"""MNIST LeNet, synchronous data-parallel SGD via gradient allreduce.

Reference analog: ``examples/mnist_allreduce.lua`` [HIGH] (reconstructed —
reference mount empty, SURVEY.md §0/§3 C15): the "add 4 lines to go
distributed" pitch.  The four lines here: ``mpi.init()``,
``synchronize_parameters``, ``synchronize_gradients`` in the step, and
``mpi.stop()``.

Run on 8 simulated devices:
  ``python examples/mnist_allreduce.py --devices 8 --steps 100``
Hierarchical 2-level allreduce over an emulated 2-slice topology:
  ``python examples/mnist_allreduce.py --devices 8 --dcn 2 --backend hierarchical``

``--backend pallas`` routes gradient sync through the custom ring kernels;
on simulated CPU meshes those run under the Pallas TPU *interpreter*
(correctness-speed only — use very few steps; on real ICI they compile).
"""

import common


def main():
    args = common.parse_args(
        __doc__,
        eager_loss=dict(
            action="store_true",
            help="reduce the per-step logging loss via the EAGER "
                 "host-staged rank-major allreduce (backend='host') — "
                 "the surface the guard-smoke CI wounds with "
                 "corrupt_silent (docs/GUARD.md) and the watchdog-smoke "
                 "CI wedges with a stall (docs/WATCHDOG.md); prints a "
                 "LOSS-DIGEST line for bit-identity checks"),
        restart_loop=dict(
            action="store_true",
            help="drive the steps through restart.run_with_restarts "
                 "(periodic checkpoints + restore-and-replay recovery) "
                 "— the watchdog-smoke CI recipe: a seeded stall on the "
                 "eager-loss staged path under watchdog=break is broken "
                 "into a typed CollectiveHangError and recovered; "
                 "prints RECOVERED-STEP / RESTARTS"),
        save_every={"type": int, "default": 10,
                    "help": "checkpoint cadence (--restart-loop only)"})
    import hashlib
    import shutil
    import tempfile

    import jax
    import numpy as np
    import optax

    import torchmpi_tpu as mpi
    from torchmpi_tpu.models import LeNet
    from torchmpi_tpu.utils import data as dutil

    mpi.init(mpi.Config(dcn_size=args.dcn))
    if args.backend:
        mpi.set_config(backend=args.backend, custom_min_bytes=0)
    if args.buckets:
        mpi.set_config(gradsync_buckets=args.buckets)
    mesh = mpi.world_mesh()
    print(f"mesh {dict(zip(mesh.axis_names, mesh.devices.shape))} "
          f"rank {mpi.rank()}/{mpi.size()}")

    model = LeNet()
    params, tx, opt_state, local_loss = common.make_train_tools(
        model, (1, 28, 28, 1), args.lr, args.momentum, args.seed)

    def step(params, opt_state, images, labels):
        loss, grads = jax.value_and_grad(local_loss)(params, images, labels)
        grads = mpi.nn.synchronize_gradients(grads, backend=args.backend)
        loss = mpi.collectives.allreduce_in_axis(loss, mesh.axis_names,
                                                 op="mean")
        updates, opt_state = tx.update(grads, opt_state, params)
        return optax.apply_updates(params, updates), opt_state, loss

    dp_step = mpi.nn.data_parallel_step(step, batch_argnums=(2, 3))
    params = mpi.nn.synchronize_parameters(params)
    opt_state = mpi.nn.synchronize_parameters(opt_state)

    n_dev = mpi.device_count()
    X, Y = dutil.synthetic_mnist(4096, seed=args.seed)
    timer = common.StepTimer()
    timer.start()
    # Keyed by step (not appended) so a restart's replayed steps
    # overwrite their own slots: the digest of a recovered run is
    # bit-identical to a clean one when the replay reproduces the same
    # losses — the watchdog-smoke CI verdict.
    losses = {}

    def train_step(params, opt_state, i, xb, yb):
        params, opt_state, loss = dp_step(params, opt_state, xb, yb)
        loss_v = float(loss)
        if args.eager_loss:
            # Route the (replicated) step loss through the eager
            # HOST-STAGED rank-major allreduce: the payload round-trips
            # through host memory — the end-to-end surface the wire
            # guard digests, the guard-smoke chaos plan corrupts, and
            # the watchdog-smoke chaos plan stalls.
            red = mpi.allreduce(
                np.full((n_dev, 1), loss_v, np.float32), op="mean",
                backend="host")
            loss_v = float(np.asarray(red)[0, 0])
        losses[i] = loss_v
        timer.tick()
        if i % 20 == 0 or i == args.steps - 1:
            print(f"step {i:4d}  loss {loss_v:.4f}")
        return params, opt_state

    if args.restart_loop:
        from torchmpi_tpu.utils import restart

        batches = list(dutil.batches(X, Y, args.batch_size,
                                     steps=args.steps, seed=args.seed))

        def init_fn():
            p, _, o, _ = common.make_train_tools(
                model, (1, 28, 28, 1), args.lr, args.momentum, args.seed)
            return {"params": mpi.nn.synchronize_parameters(p),
                    "opt": mpi.nn.synchronize_parameters(o)}

        def step_fn(state, i):
            xb, yb = batches[i]
            p, o = train_step(state["params"], state["opt"], i, xb, yb)
            return {"params": p, "opt": o}

        ckpt_dir = tempfile.mkdtemp(prefix="tm_wd_ckpt_")
        try:
            state, info = restart.run_with_restarts(
                init_fn, step_fn, steps=args.steps, directory=ckpt_dir,
                save_every=args.save_every)
        finally:
            shutil.rmtree(ckpt_dir, ignore_errors=True)
        params = state["params"]
        print(f"RESTARTS {info['restarts_used']}")
        print(f"RECOVERED-STEP {info['recovered_step']}")
    else:
        for i, (xb, yb) in enumerate(
                dutil.batches(X, Y, args.batch_size, steps=args.steps,
                              seed=args.seed)):
            params, opt_state = train_step(params, opt_state, i, xb, yb)
    acc = common.evaluate(model, params, X[:1024], Y[:1024])
    print(f"final accuracy {acc:.3f}  ({timer.rate(args.batch_size):.0f} img/s)")
    if args.eager_loss:
        # Bit-identity evidence for the guard-/watchdog-smoke CI: the
        # digest of every loss that crossed the (possibly wounded)
        # staged path, in step order.
        dig = hashlib.blake2b(
            np.asarray([losses[i] for i in sorted(losses)],
                       np.float32).tobytes(),
            digest_size=16).hexdigest()
        print(f"LOSS-DIGEST {dig}")
    mpi.stop()
    # Short recovery-recipe runs stop before convergence; the full
    # default run keeps its regression bar.
    assert args.steps < 60 or acc > 0.9, \
        "data-parallel MNIST did not converge"


if __name__ == "__main__":
    main()

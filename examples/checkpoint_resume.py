"""Checkpoint/restart recovery — the failure-handling story.

Reference behavior (SURVEY.md §6.3/§6.4, reconstructed — reference mount
empty): an MPI rank failure aborted the whole job; the library shipped no
checkpointing, so recovery meant restarting from whatever the user saved.
The rebuild keeps the same gang-scheduled failure model for the SPMD side
(a slice fails as a unit) and makes the checkpoint-restart loop a
first-class, tested path: periodic sharded checkpoints, then resume from
the latest one after a (simulated) crash, with the loss curve continuing
where it left off.

Run: ``python examples/checkpoint_resume.py --devices 8``

``--restart-loop`` switches to the ``restart.run_with_restarts``
driver — the durable-checkpoint chaos recipe (docs/CHECKPOINT.md, CI
``ckpt-chaos``): train with periodic saves, crash at ``--crash-at``,
and let recovery restore the newest verifiable step.  Under a seeded
``ckpt.read`` bit-rot plan (TORCHMPI_TPU_FAULTS=plan.json), the
contrast is the point: with ``TORCHMPI_TPU_CKPT_REDUNDANCY=off`` the
rotted newest checkpoint fails its parse and recovery silently walks
back (RECOVERED-STEP drops, work is lost); with ``buddy`` the digest
check names the rot, the primary is repaired bit-identically from the
buddy mirror (``tm_ckpt_verify_failed``/``tm_ckpt_repaired``), and
the resumed trajectory lands on a LOSS-DIGEST bit-identical to a
clean run.
"""

import hashlib
import os
import shutil
import tempfile

import common


def restart_loop(args):
    """The run_with_restarts + durable-checkpoint recipe (CI
    ckpt-chaos).  Prints RECOVERED-STEP / RESTARTS / LOSS-DIGEST."""
    import jax
    import numpy as np
    import optax

    import torchmpi_tpu as mpi
    from torchmpi_tpu.models import LeNet
    from torchmpi_tpu.utils import data as dutil
    from torchmpi_tpu.utils import restart

    ckpt_dir = args.ckpt_dir or tempfile.mkdtemp(prefix="tm_ckpt_")
    try:
        mpi.init(mpi.Config(dcn_size=args.dcn))
        mesh = mpi.world_mesh()
        model = LeNet()

        def make_tools():
            return common.make_train_tools(
                model, (1, 28, 28, 1), args.lr, args.momentum, args.seed)

        params0, tx, opt0, local_loss = make_tools()

        def step(params, opt_state, images, labels):
            loss, grads = jax.value_and_grad(local_loss)(params, images,
                                                         labels)
            grads = mpi.nn.synchronize_gradients(grads)
            loss = mpi.collectives.allreduce_in_axis(
                loss, mesh.axis_names, op="mean")
            updates, opt_state = tx.update(grads, opt_state, params)
            return optax.apply_updates(params, updates), opt_state, loss

        dp_step = mpi.nn.data_parallel_step(step, batch_argnums=(2, 3),
                                            donate_argnums=())
        X, Y = dutil.synthetic_mnist(2048, seed=args.seed)
        batches = list(dutil.batches(X, Y, args.batch_size,
                                     steps=args.steps, seed=args.seed))

        def init_fn():
            p, _, o, _ = make_tools()
            return {"params": mpi.nn.synchronize_parameters(p),
                    "opt": mpi.nn.synchronize_parameters(o)}

        losses = {}
        crashed = []

        def step_fn(state, i):
            if args.crash_at is not None and i == args.crash_at \
                    and not crashed:
                crashed.append(i)
                raise RuntimeError("injected crash (checkpoint_resume "
                                   "--crash-at)")
            xb, yb = batches[i]
            p, o, loss = dp_step(state["params"], state["opt"], xb, yb)
            losses[i] = float(loss)
            return {"params": p, "opt": o}

        state, info = restart.run_with_restarts(
            init_fn, step_fn, steps=args.steps, directory=ckpt_dir,
            save_every=args.save_every)

        # Bit-identity evidence over the FINAL state: deterministic
        # steps mean a recovery that restored its checkpoint
        # bit-exactly lands on exactly the clean run's bytes.
        h = hashlib.blake2b(digest_size=16)
        for key, leaf in sorted(
                jax.tree_util.tree_flatten_with_path(state)[0],
                key=lambda kv: str(kv[0])):
            h.update(np.ascontiguousarray(np.asarray(leaf)).tobytes())
        print(f"final loss {losses[max(losses)]:.4f}")
        print(f"RESTARTS {info['restarts_used']}")
        print(f"RECOVERED-STEP {info['recovered_step']}")
        print(f"LOSS-DIGEST {h.hexdigest()}")
        mpi.stop()
    finally:
        if not args.ckpt_dir:
            shutil.rmtree(ckpt_dir, ignore_errors=True)


def main():
    args = common.parse_args(
        __doc__, defaults={"steps": 40, "batch_size": 128},
        restart_loop={"action": "store_true",
                      "help": "run the run_with_restarts durable-"
                              "checkpoint recipe instead of the two-"
                              "phase demo"},
        crash_at={"type": int, "default": None,
                  "help": "inject one crash at this step "
                          "(--restart-loop only)"},
        save_every={"type": int, "default": 10},
        ckpt_dir={"type": str, "default": None,
                  "help": "checkpoint directory (default: a temp dir, "
                          "removed on exit)"})
    if args.restart_loop:
        return restart_loop(args)
    import jax
    import numpy as np

    import torchmpi_tpu as mpi
    from torchmpi_tpu.models import LeNet
    from torchmpi_tpu.utils import checkpoint, data as dutil

    ckpt_dir = tempfile.mkdtemp(prefix="tm_ckpt_")
    try:
        import jax.numpy as jnp
        import optax

        mpi.init(mpi.Config(dcn_size=args.dcn))
        mesh = mpi.world_mesh()
        model = LeNet()
        params, tx, opt_state, local_loss = common.make_train_tools(
            model, (1, 28, 28, 1), args.lr, args.momentum, args.seed)

        def step(params, opt_state, images, labels):
            loss, grads = jax.value_and_grad(local_loss)(params, images,
                                                         labels)
            grads = mpi.nn.synchronize_gradients(grads)
            loss = mpi.collectives.allreduce_in_axis(loss, mesh.axis_names,
                                                     op="mean")
            updates, opt_state = tx.update(grads, opt_state, params)
            return optax.apply_updates(params, updates), opt_state, loss

        dp_step = mpi.nn.data_parallel_step(step, batch_argnums=(2, 3),
                                            donate_argnums=())
        params = mpi.nn.synchronize_parameters(params)
        opt_state = mpi.nn.synchronize_parameters(opt_state)
        X, Y = dutil.synthetic_mnist(2048, seed=args.seed)

        # --- phase 1: train, checkpointing every 10 steps, "crash" midway.
        # Saves go through the native async executor (csrc/io.cpp): the
        # device->host snapshot is synchronous, the write+fsync+rename
        # overlap the following train steps.  The single-thread writer is
        # FIFO, so at most the handles need a final wait at the crash point.
        crash_at = args.steps // 2
        # Step-0 checkpoint up front so recovery works however early the
        # crash lands relative to the periodic save interval.
        pending = checkpoint.save_async(
            ckpt_dir, {"params": params, "opt": opt_state,
                       "step": np.int64(0)}, step=0)
        losses = []
        for i, (xb, yb) in enumerate(dutil.batches(
                X, Y, args.batch_size, steps=crash_at, seed=args.seed)):
            params, opt_state, loss = dp_step(params, opt_state, xb, yb)
            losses.append(float(loss))
            if i % 10 == 9:
                # Fence the previous save before starting the next: on the
                # FIFO writer it has almost always landed by now, and the
                # wait is where a failed write surfaces as an exception.
                pending.wait(timeout=120.0)
                pending = checkpoint.save_async(
                    ckpt_dir, {"params": params, "opt": opt_state,
                               "step": np.int64(i + 1)}, step=i + 1)
        pending.wait(timeout=120.0)  # fence in-flight writes before "crash"
        print(f"phase 1: step {crash_at} loss {losses[-1]:.4f}; "
              f"latest ckpt step {checkpoint.latest_step(ckpt_dir)}")
        pre_crash = losses[-1]
        del params, opt_state  # the crash

        # --- phase 2: fresh process state, resume from latest checkpoint
        params2, tx, opt_state2, _ = common.make_train_tools(
            model, (1, 28, 28, 1), args.lr, args.momentum, args.seed)
        template = {"params": params2, "opt": tx.init(params2),
                    "step": np.int64(0)}
        restored = checkpoint.restore(ckpt_dir, template)
        resume_step = int(restored["step"])
        params = mpi.nn.synchronize_parameters(restored["params"])
        opt_state = mpi.nn.synchronize_parameters(restored["opt"])
        print(f"phase 2: resumed from step {resume_step}")
        # continue on the same data stream position
        stream = dutil.batches(X, Y, args.batch_size, steps=args.steps,
                               seed=args.seed)
        for i, (xb, yb) in enumerate(stream):
            if i < resume_step:
                continue  # replay the stream to the resume point
            params, opt_state, loss = dp_step(params, opt_state, xb, yb)
            losses.append(float(loss))
        final = float(loss)
        print(f"final loss {final:.4f} (pre-crash {pre_crash:.4f})")
        mpi.stop()
        assert final < pre_crash, "resume did not continue improving"
    finally:
        shutil.rmtree(ckpt_dir, ignore_errors=True)


if __name__ == "__main__":
    main()

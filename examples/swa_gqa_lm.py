"""Modern-LM stack demo: RoPE + sliding-window + GQA + flash attention.

Beyond-reference (the reference predates transformers — SURVEY.md §6.7):
trains a Mistral-shaped small LM — rotary position embeddings
(``pos_emb="rope"``, no position table), sliding-window attention
(``window=``, the flash path runs O(T*window) banded Pallas grids), and
grouped-query attention (``num_kv_heads=``, the decode KV cache stores
only the kv heads) — on the learnable next-token task
``t_{i+1} = (3 t_i + 1) mod V``, then decodes held-out prompts through
the GQA cache and asserts the continuations follow the rule.

The task is window-friendly by construction (next token depends only on
the previous one), so a tight window must still converge.

Run (simulated): ``python examples/swa_gqa_lm.py --devices 1``
Run (real chip): ``python examples/swa_gqa_lm.py --attn flash``
"""

import common


def main():
    args = common.parse_args(
        __doc__,
        seq_len=dict(type=int, default=32),
        vocab=dict(type=int, default=32),
        window=dict(type=int, default=8),
        kv_heads=dict(type=int, default=2),
        gen_steps=dict(type=int, default=8),
        attn=dict(type=str, default="local",
                  choices=["local", "flash"]),
        defaults={"steps": 250, "batch_size": 32, "lr": 3e-3},
    )
    import jax
    import jax.numpy as jnp
    import numpy as np
    import optax

    import torchmpi_tpu as mpi
    from torchmpi_tpu.models import TransformerLM, generate

    mpi.init()
    V, T = args.vocab, args.seq_len
    model = TransformerLM(vocab=V, embed=64, depth=2, num_heads=4,
                          head_dim=16, max_len=T, pos_emb="rope",
                          window=args.window, num_kv_heads=args.kv_heads,
                          attn_impl=args.attn)

    def make_batch(rng, batch):
        t0 = rng.randint(0, V, size=(batch, 1))
        toks = [t0]
        for _ in range(T - 1):
            toks.append((toks[-1] * 3 + 1) % V)
        return np.concatenate(toks, axis=1).astype(np.int32)

    rng = np.random.RandomState(args.seed)
    params = model.init(jax.random.PRNGKey(args.seed),
                        jnp.asarray(make_batch(rng, 2)))["params"]
    assert "pos_embed" not in params, "rope model must have no pos table"
    tx = optax.adam(args.lr)
    opt_state = tx.init(params)

    @jax.jit
    def step(p, o, toks):
        def loss_fn(p):
            logits = model.apply({"params": p}, toks)
            return optax.softmax_cross_entropy_with_integer_labels(
                logits[:, :-1].astype(jnp.float32), toks[:, 1:]).mean()

        loss, g = jax.value_and_grad(loss_fn)(p)
        u, o = tx.update(g, o, p)
        return optax.apply_updates(p, u), o, loss

    for i in range(args.steps):
        params, opt_state, loss = step(
            params, opt_state, jnp.asarray(make_batch(rng, args.batch_size)))
        if i % 50 == 0:
            print(f"step {i:4d}  loss {float(loss):.4f}")
    print(f"final train loss {float(loss):.4f}")

    # Decode through the GQA (kv-heads-only) cache with the SAME sliding
    # window the model trained with (the cache mask applies the band) and
    # the rope rotate-then-cache protocol.
    prompts = make_batch(np.random.RandomState(args.seed + 999), 8)[:, :4]
    out = np.asarray(generate(model, params, prompts,
                              steps=args.gen_steps))
    correct = total = 0
    for b in range(out.shape[0]):
        t = int(prompts[b, -1])
        for j in range(4, 4 + args.gen_steps):
            t = (t * 3 + 1) % V
            correct += int(out[b, j] == t)
            total += 1
    acc = correct / total
    print(f"decode: {out.shape[0]} prompts x {args.gen_steps} tokens, "
          f"rule accuracy {acc:.3f} "
          f"(window {args.window}, kv heads {args.kv_heads}, rope)")
    mpi.stop()
    assert acc > 0.8, "decoded continuations do not follow the learned rule"


if __name__ == "__main__":
    main()

"""Long-context causal LM with ring-attention sequence parallelism.

No reference analog — TorchMPI predates transformers (SURVEY.md §6.7); this
example demonstrates the sequence/context-parallel extension: the sequence
dimension is sharded across the mesh, ring attention rotates key/value
blocks over the interconnect, and the data-parallel gradient sync runs on
the same communicator tree.

Task: needle retrieval — each sequence is zeros except one "needle" token at
a random position; every later position must output the needle's value.  A
shard can only solve positions after a needle that lives on *another* shard
by attending across the ring, so convergence directly certifies the
cross-shard attention path (and the causal mask: positions before the
needle are excluded).

Run: ``python examples/longcontext_lm.py --devices 8``

``--attn ring_flash`` runs each ring step through the Pallas flash kernel
(ops/flash.py) with its ring-structured backward — the production path for
long local shards on real TPU.  On a simulated CPU mesh that kernel runs
under the Pallas interpreter and is far too slow for this example's
convergence run; use the default ``ring`` (same math, XLA blocks) there.
"""

import common


def main():
    args = common.parse_args(
        __doc__,
        seq_len=dict(type=int, default=256),
        vocab=dict(type=int, default=64),
        attn=dict(type=str, default="ring",
                  choices=["ring", "ring_flash", "ulysses"]),
        defaults={"steps": 80, "batch_size": 16, "lr": 3e-3},
    )
    import jax
    import jax.numpy as jnp
    import numpy as np
    import optax
    from jax import lax, shard_map
    from jax.sharding import NamedSharding, PartitionSpec as P

    import torchmpi_tpu as mpi
    from torchmpi_tpu.models import TransformerLM

    mpi.init(mpi.Config(dcn_size=args.dcn))
    mesh = mpi.world_mesh()
    axes = mesh.axis_names
    # Context parallelism rides ICI only (ring attention communicates over
    # the fast intra-slice links); the dcn axis carries data parallelism —
    # batch over dcn, sequence over ici.  Sharding the sequence over dcn too
    # would silently skip cross-slice attention.
    n_seq = mesh.shape[mpi.ICI_AXIS]
    n_dp = mesh.shape[mpi.DCN_AXIS]
    T = args.seq_len
    assert T % n_seq == 0 and args.batch_size % n_dp == 0
    t_local = T // n_seq
    print(f"mesh {dict(zip(axes, mesh.devices.shape))}, global seq {T}, "
          f"{t_local}/device over ici, batch/{n_dp} over dcn, "
          f"attention={args.attn}")

    model = TransformerLM(vocab=args.vocab, embed=128, depth=2, num_heads=8,
                          head_dim=16, max_len=T, attn_impl=args.attn,
                          seq_axis="ici")
    # Init with a local-attention twin (same params, no mesh needed).
    init_model = TransformerLM(vocab=args.vocab, embed=128, depth=2,
                               num_heads=8, head_dim=16, max_len=T,
                               attn_impl="local")
    variables = init_model.init(
        jax.random.PRNGKey(args.seed),
        jnp.zeros((1, T), jnp.int32))
    tx = optax.adam(args.lr)
    opt_state = tx.init(variables)

    def make_batch(rng):
        tokens = np.zeros((args.batch_size, T), np.int32)
        key = rng.randint(1, args.vocab, size=args.batch_size).astype(
            np.int32)
        # needle anywhere in the first 7/8ths, so every shard regularly has
        # post-needle positions whose needle lives on an earlier shard
        p = rng.randint(0, (T * 7) // 8, size=args.batch_size)
        tokens[np.arange(args.batch_size), p] = key
        return tokens, key.astype(np.int32), p.astype(np.int32)

    def step(variables, opt_state, tokens, key, p):
        # tokens: [B/n_dp, t_local] this device's shard; key/p: [B/n_dp]
        offset = lax.axis_index(mpi.ICI_AXIS) * t_local

        def loss_fn(vs):
            logits = model.apply(vs, tokens, pos_offset=offset)
            gpos = offset + jnp.arange(t_local)
            mask = (gpos[None, :] > p[:, None]).astype(jnp.float32)
            losses = optax.softmax_cross_entropy_with_integer_labels(
                logits, jnp.broadcast_to(key[:, None], tokens.shape))
            local = (losses * mask).sum()
            cnt = mask.sum()
            # normalize by the GLOBAL number of supervised positions
            return (lax.psum(local, axes) / lax.psum(cnt, axes))

        loss, grads = jax.value_and_grad(loss_fn)(variables)
        grads = mpi.nn.synchronize_gradients(grads, axes, op="sum")
        updates, opt_state = tx.update(grads, opt_state, variables)
        return optax.apply_updates(variables, updates), opt_state, loss

    spec = P(mpi.DCN_AXIS, mpi.ICI_AXIS)      # batch x sequence
    vec_spec = P(mpi.DCN_AXIS)                # per-sequence key / needle pos
    sp_step = jax.jit(shard_map(
        step, mesh=mesh, in_specs=(P(), P(), spec, vec_spec, vec_spec),
        out_specs=(P(), P(), P()),
        check_vma=False), donate_argnums=(0, 1))

    variables = mpi.nn.synchronize_parameters(variables)
    opt_state = mpi.nn.synchronize_parameters(opt_state)
    rng = np.random.RandomState(args.seed)
    first = None
    tok_sharding = NamedSharding(mesh, spec)
    vec_sharding = NamedSharding(mesh, vec_spec)
    for i in range(args.steps):
        tokens, key, p = make_batch(rng)
        tokens = jax.device_put(tokens, tok_sharding)
        key = jax.device_put(key, vec_sharding)
        p = jax.device_put(p, vec_sharding)
        variables, opt_state, loss = sp_step(variables, opt_state, tokens,
                                             key, p)
        if i % 10 == 0 or i == args.steps - 1:
            print(f"step {i:4d}  loss {float(loss):.4f}")
        if first is None:
            first = float(loss)
    last = float(loss)
    print(f"loss {first:.3f} -> {last:.3f} (chance ~{np.log(args.vocab):.2f})")
    mpi.stop()
    assert last < 0.35 * first, "long-context LM did not learn"


if __name__ == "__main__":
    main()

"""Continuous-batching serving demo: a Poisson request trace through
the admission-queue + slot-pool + health-routed-replica stack
(torchmpi_tpu/serving/, docs/SERVING.md).

Two data-parallel replicas of one RoPE TransformerLM checkpoint, each
pinned to its own (simulated) device, serve a trace of mixed-length
requests with iteration-level batching; every request's tokens are then
checked BIT-IDENTICAL against the offline ``models.generate.generate``
path — the serving correctness property — and the per-request SLO stats
are printed.  Run with telemetry to get the ``tm_serving_*`` dumps::

    TORCHMPI_TPU_OBS=metrics python examples/continuous_serving.py \
        --devices 8

Exits nonzero on any token mismatch, so subprocess rc is the whole
check (SURVEY.md §5 style).
"""

import common


def main():
    args = common.parse_args(
        __doc__,
        requests=dict(type=int, default=24),
        replicas=dict(type=int, default=2),
        slots=dict(type=int, default=4),
        defaults={"steps": 0, "batch_size": 8},
    )
    import numpy as np

    import jax
    import jax.numpy as jnp

    import torchmpi_tpu as mpi
    from torchmpi_tpu import serving
    from torchmpi_tpu.models import TransformerLM, generate

    mpi.init()
    vocab, tp = 64, 6
    model = TransformerLM(vocab=vocab, embed=32, depth=2, num_heads=4,
                          head_dim=8, max_len=64, pos_emb="rope")
    params = model.init(jax.random.PRNGKey(args.seed),
                        jnp.zeros((1, tp), jnp.int32))["params"]

    rng = np.random.RandomState(args.seed + 1)
    prompts = rng.randint(0, vocab, size=(args.requests, tp)).astype(
        np.int32)
    lens = [int(rng.choice([4, 8, 16, 32])) for _ in
            range(args.requests)]
    arrivals = np.cumsum(rng.exponential(2.0, size=args.requests))
    reqs = [serving.Request(f"r{i}", prompts[i], max_new=lens[i],
                            arrival_s=float(arrivals[i]))
            for i in range(args.requests)]

    devices = jax.devices()[:args.replicas] \
        if len(jax.devices()) >= args.replicas else None
    server = serving.Server(model, params, replicas=args.replicas,
                            slots=args.slots, slot_tokens=64,
                            devices=devices)
    done = server.run_trace(reqs, unit_seconds=1.0)
    assert len(done) == args.requests

    for i, req in enumerate(reqs):
        off = np.asarray(generate(model, params, prompts[i:i + 1],
                                  steps=lens[i]))[0, tp:]
        assert req.tokens == off.tolist(), (
            f"request {req.rid} diverged from offline generate:\n"
            f"{req.tokens}\nvs\n{off.tolist()}")

    st = server.last_stats
    by_rep = {}
    for r in reqs:
        by_rep[r.replica] = by_rep.get(r.replica, 0) + 1
    ttft = sorted(r.ttft_s for r in reqs)
    print(f"continuous serving OK: {args.requests} requests "
          f"({sum(lens)} tokens) over {args.replicas} replicas x "
          f"{args.slots} slot blocks; sessions per replica {by_rep}; "
          f"{st['ticks']} ticks, work-unit TTFT p50/p95 = "
          f"{ttft[len(ttft) // 2]:.0f}/{ttft[int(len(ttft) * .95)]:.0f}"
          f"; every request token-exact vs offline generate")


if __name__ == "__main__":
    main()

"""Serve one checkpoint tree three ways: dense, tensor-parallel, and
pipeline-parallel — and assert they emit identical tokens.

Beyond-reference demo (the reference has no serving at all — SURVEY.md
§1): the same ``init_tp_lm`` parameter layout decodes

- dense on one device (the oracle, recomputing the full forward per
  token);
- tensor-parallel over the 8-way model axis (``models.tp_generate``:
  head-local KV cache, column-parallel LM head re-joined by one tiled
  all_gather per token);
- pipeline-parallel over 8 stages (``models.pp_generate``: round-robin
  micro-groups, one wraparound ppermute per tick).

Greedy decode must agree token-for-token across all three — THE serving
correctness property (parallelism must never change the sampled text) —
and EOS freezing must behave identically.  Exits nonzero on any
mismatch, so subprocess rc is the whole check (SURVEY.md §5 style).

Run: ``python examples/parallel_serving.py --devices 8``
"""

import common


def main():
    args = common.parse_args(
        __doc__,
        vocab=dict(type=int, default=64),
        gen_steps=dict(type=int, default=8),
        defaults={"steps": 0, "batch_size": 8},
    )
    import jax
    import numpy as np

    import torchmpi_tpu as mpi
    from torchmpi_tpu.models.pp_generate import pp_generate
    from torchmpi_tpu.models.tp_generate import (init_tp_lm,
                                                 tp_beam_search,
                                                 tp_generate)

    mesh = mpi.init()
    axis = tuple(mesh.axis_names)
    V, B, steps = args.vocab, args.batch_size, args.gen_steps

    # One parameter tree, depth divisible by the stage count.
    n_dev = mesh.devices.size
    depth = n_dev
    params = init_tp_lm(jax.random.PRNGKey(args.seed), vocab=V,
                        embed=32, depth=depth, num_heads=8)
    prompt = np.random.RandomState(args.seed + 1).randint(
        0, V, size=(B, 4)).astype(np.int32)

    # Dense oracle: the shared cache-free reference implementation
    # (torchmpi_tpu.models.oracle) — ONE copy of the oracle math.
    from torchmpi_tpu.models.oracle import dense_greedy

    toks = dense_greedy(params, prompt, steps, num_heads=8)

    tp_toks = np.asarray(tp_generate(
        params, prompt, steps, mesh=mesh, axis=axis, num_heads=8))
    pp_toks = np.asarray(pp_generate(
        params, prompt, steps, mesh=mesh, axis=axis, num_heads=8))

    assert (tp_toks == toks).all(), (
        f"TP decode diverged from dense:\n{tp_toks}\nvs\n{toks}")
    assert (pp_toks == toks).all(), (
        f"PP decode diverged from dense:\n{pp_toks}\nvs\n{toks}")

    # EOS: freeze on a token the dense decode actually emits.
    eos = int(toks[0, prompt.shape[1]])
    tp_eos = np.asarray(tp_generate(
        params, prompt, steps, mesh=mesh, axis=axis, num_heads=8,
        eos_id=eos))
    pp_eos = np.asarray(pp_generate(
        params, prompt, steps, mesh=mesh, axis=axis, num_heads=8,
        eos_id=eos))
    assert (tp_eos == pp_eos).all(), "TP vs PP EOS divergence"
    assert (tp_eos[0, prompt.shape[1]:] == eos).all(), (
        "row 0 should freeze at its first emitted token")

    # Beam decode on the TP stack: beams=1 must reduce to greedy.
    beam1 = np.asarray(tp_beam_search(
        params, prompt, steps, mesh=mesh, axis=axis, num_heads=8,
        beams=1))
    assert (beam1 == toks).all(), "TP beam(1) diverged from greedy"
    beam3 = np.asarray(tp_beam_search(
        params, prompt, steps, mesh=mesh, axis=axis, num_heads=8,
        beams=3, length_penalty=0.6))
    assert beam3.shape == toks.shape

    print(f"parallel serving OK: dense == TP == PP over {n_dev} devices "
          f"({B}x{prompt.shape[1]} prompt + {steps} tokens; EOS freeze "
          f"consistent; TP beam(1) == greedy)")


if __name__ == "__main__":
    main()

"""Expert-parallel MoE language model — beyond-reference demo.

The reference is DP-only (SURVEY.md §3.3); this example drives the
expert-parallel axis end to end: a TransformerLM whose MLP is a top-k MoE
(``--top-k``: 1 = Switch-style combine, 2+ = GShard renormalized) with one
expert per device, tokens dispatched to their experts' devices via
all-to-all over ``ici`` and combined back, trained data-parallel over
``dcn``.  Convergence is asserted (loss must drop on a learnable synthetic
next-token task), the examples-as-tests strategy of SURVEY.md §5.

Run: ``python examples/moe_lm.py --devices 8 [--dcn 2] [--top-k 2]``
"""

import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main():
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--devices", type=int, default=0)
    p.add_argument("--dcn", type=int, default=None)
    p.add_argument("--steps", type=int, default=60)
    p.add_argument("--batch-size", type=int, default=16)
    p.add_argument("--seq-len", type=int, default=32)
    p.add_argument("--vocab", type=int, default=64)
    p.add_argument("--lr", type=float, default=3e-3)
    p.add_argument("--top-k", type=int, default=1,
                   help="experts per token (1=Switch, 2=GShard combine)")
    p.add_argument("--seed", type=int, default=0)
    args = p.parse_args()
    if args.devices:
        from torchmpi_tpu.utils.simulation import force_cpu_devices

        force_cpu_devices(args.devices)

    import jax
    import jax.numpy as jnp
    import numpy as np
    import optax
    from jax import lax, shard_map
    from jax.sharding import NamedSharding, PartitionSpec as P

    import torchmpi_tpu as mpi
    from torchmpi_tpu.models import TransformerLM

    mpi.init(mpi.Config(dcn_size=args.dcn))
    mesh = mpi.world_mesh()
    n_dp = mesh.shape[mpi.DCN_AXIS]
    n_ep = mesh.shape[mpi.ICI_AXIS]
    assert args.batch_size % n_dp == 0
    T = args.seq_len
    print(f"mesh {dict(zip(mesh.axis_names, mesh.devices.shape))}: "
          f"dp={n_dp} over dcn, ep={n_ep} experts over ici")

    model = TransformerLM(vocab=args.vocab, embed=64, depth=2, num_heads=4,
                          head_dim=16, max_len=T, moe_axis=mpi.ICI_AXIS,
                          moe_experts_per_device=1, moe_k=args.top_k)

    # Learnable synthetic task: next token = (token * 3 + 1) mod vocab.
    def make_batch(rng):
        t0 = rng.randint(0, args.vocab, size=(args.batch_size, 1))
        toks = [t0]
        for _ in range(T - 1):
            toks.append((toks[-1] * 3 + 1) % args.vocab)
        return np.concatenate(toks, axis=1).astype(np.int32)

    spec = P(mpi.DCN_AXIS)  # batch over dcn; sequence unsharded (EP demo)
    rng = np.random.RandomState(args.seed)
    tok0 = jax.device_put(make_batch(rng), NamedSharding(mesh, spec))

    def init_fn(tok):
        return model.init(jax.random.PRNGKey(args.seed), tok)

    variables = jax.jit(shard_map(init_fn, mesh=mesh, in_specs=spec,
                                  out_specs=P(), check_vma=False))(tok0)
    tx = optax.adam(args.lr)
    opt_state = tx.init(variables)

    def step(vs, opt_state, tok):
        def loss_fn(v):
            # mutable=["losses"] collects the sown per-layer load-balance
            # losses; scaled into the task loss so the router is actually
            # pushed toward uniform expert utilization.
            logits, sown = model.apply(v, tok, mutable=["losses"])
            losses = optax.softmax_cross_entropy_with_integer_labels(
                logits[:, :-1], tok[:, 1:])
            aux = sum(jax.tree.leaves(sown["losses"]))  # one per MoE layer
            return lax.pmean(losses.mean() + 1e-2 * aux, mesh.axis_names)

        loss, grads = jax.value_and_grad(loss_fn)(vs)
        # op="sum": the pmean in loss_fn already scaled each shard's grad by
        # 1/N, so summing yields the cross-device mean (op="mean" here would
        # divide by N twice).  Same convention as longcontext_lm.py.
        grads = mpi.nn.synchronize_gradients(grads, mesh.axis_names,
                                             op="sum")
        updates, opt_state = tx.update(grads, opt_state, vs)
        return optax.apply_updates(vs, updates), opt_state, loss

    ep_step = jax.jit(shard_map(
        step, mesh=mesh, in_specs=(P(), P(), spec),
        out_specs=(P(), P(), P()), check_vma=False), donate_argnums=(0, 1))

    variables = mpi.nn.synchronize_parameters(variables)
    opt_state = mpi.nn.synchronize_parameters(opt_state)
    first = None
    for i in range(args.steps):
        tok = jax.device_put(make_batch(rng), NamedSharding(mesh, spec))
        variables, opt_state, loss = ep_step(variables, opt_state, tok)
        lv = float(loss)
        if first is None:
            first = lv
        if i % 10 == 0:
            print(f"step {i:4d}  loss {lv:.4f}")
    print(f"final loss {lv:.4f} (from {first:.4f})")
    assert lv < first * 0.7, (
        f"MoE LM failed to learn: {first:.4f} -> {lv:.4f}")
    print("converged OK")
    mpi.stop()


if __name__ == "__main__":
    main()

"""AlexNet asynchronous Downpour SGD — BASELINE config 4.

Reference analog: the AlexNet + ``torchmpi.parameterserver`` workload
(SURVEY.md §8.1 config 4, reconstructed — reference mount empty).  Same
Downpour structure as ``mnist_downpour.py`` with the reference's ImageNet-era
model.  Defaults are sized for the simulated CPU mesh; on real hardware raise
``--image-size 224 --num-classes 1000 --batch-size 128``.

Run: ``python examples/alexnet_downpour.py --devices 8 --workers 2``
"""

import common


def main():
    args = common.parse_args(
        __doc__,
        workers=dict(type=int, default=2),
        fetch_every=dict(type=int, default=5),
        shards=dict(type=int, default=4),
        image_size=dict(type=int, default=64),
        num_classes=dict(type=int, default=10),
        # lr: AlexNet has no normalization layers; Adam above ~1e-3 on this
        # cold start oscillates in place (loss pinned at ln C) while 3e-4
        # trains to 100% on the synthetic task — measured, see
        # docs/ROUND2_NOTES.md.
        defaults={"steps": 80, "batch_size": 32, "lr": 3e-4},
    )
    import jax
    import jax.numpy as jnp
    import numpy as np
    import optax

    import torchmpi_tpu as mpi
    from torchmpi_tpu.models import AlexNet
    from torchmpi_tpu.utils import data as dutil

    mpi.init()
    model = AlexNet(num_classes=args.num_classes, dropout=0.0)
    params0 = model.init(
        jax.random.PRNGKey(args.seed),
        jnp.zeros((1, args.image_size, args.image_size, 3)), train=False)
    ps = mpi.parameterserver.init(params0, num_shards=args.shards)

    def local_loss(p, images, labels):
        logits = model.apply(p, images, train=False)
        return optax.softmax_cross_entropy_with_integer_labels(
            logits, labels).mean()

    # Downpour with a *local* optimizer: each worker keeps its own Adam
    # state, pushes the resulting update to the PS with the 'add' rule (the
    # PS stays a dumb accumulator, exactly the reference's server-side
    # role), and periodically refetches the shared parameters.  AlexNet has
    # no normalization layers, so plain SGD barely moves from a cold start —
    # the original needed LR warmup schedules the example doesn't carry.
    tx = optax.adam(args.lr)

    @jax.jit
    def local_step(p, opt_state, images, labels):
        loss, grads = jax.value_and_grad(local_loss)(p, images, labels)
        updates, opt_state = tx.update(grads, opt_state, p)
        return updates, opt_state, loss

    devices = jax.devices()[: args.workers]
    n_workers = min(args.workers, len(devices))
    X, Y = dutil.synthetic_image_classification(
        1024, image_shape=(args.image_size, args.image_size, 3),
        num_classes=args.num_classes, seed=args.seed)
    def worker(widx):
        with jax.default_device(devices[widx]):
            params = jax.tree.map(jnp.asarray, params0)
            opt_state = tx.init(params)
            fetch_handle = None
            for step, (xb, yb) in enumerate(dutil.batches(
                    X, Y, args.batch_size, steps=args.steps,
                    seed=args.seed + widx + 1)):
                updates, opt_state, _ = local_step(
                    params, opt_state, jnp.asarray(xb), jnp.asarray(yb))
                # Push with the axpy rule scaled 1/K so the center moves by
                # the *average* of the workers' updates — K workers pushing
                # full Adam steps against near-identical params otherwise
                # move the center K-fold per round (persistent overshoot).
                ps.send(jax.tree.map(np.asarray, updates), rule="axpy",
                        alpha=1.0 / n_workers)
                params = optax.apply_updates(params, updates)
                # Prefetch at step s, adopt at s+1: the push is fully async
                # but parameter staleness stays bounded at one step — with
                # unbounded staleness the PS center (sum of all workers'
                # deltas) diverges from every worker on sharp loss surfaces
                # like AlexNet's.
                if fetch_handle is not None:
                    params = jax.tree.map(jnp.asarray, fetch_handle.wait())
                    fetch_handle = None
                elif step % args.fetch_every == 0:
                    fetch_handle = ps.receive()

    common.run_workers(worker, args.workers)

    center = jax.tree.map(jnp.asarray, ps.receive().wait())
    logits = model.apply(center, jnp.asarray(X[:256]), train=False)
    acc = float((np.argmax(np.asarray(logits), 1) == Y[:256]).mean())
    print(f"PS ops served: {ps.ops_served()}")
    print(f"final accuracy (PS params) {acc:.3f}  "
          f"(chance {1/args.num_classes:.3f})")
    ps.shutdown()
    mpi.stop()
    assert acc > 2.0 / args.num_classes, "AlexNet downpour made no progress"


if __name__ == "__main__":
    main()

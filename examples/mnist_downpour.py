"""MNIST LeNet, asynchronous Downpour SGD via the parameter server.

Reference analog: ``examples/mnist_downpour.lua`` [HIGH] (reconstructed —
reference mount empty, SURVEY.md §3 C15, §4.5): each worker computes
gradients on its own minibatch, pushes ``-lr * grad`` to the sharded PS with
the ``add`` rule (the PS *is* the optimizer), and periodically refreshes its
local replica with an async prefetch.  Workers here are host threads, each
pinned to its own device of the CPU/TPU mesh — genuinely asynchronous, no
gang scheduling, exactly the property the reference's thread-pool PS had.

Run: ``python examples/mnist_downpour.py --devices 8 --workers 4``
"""

import common


def main():
    args = common.parse_args(
        __doc__,
        workers=dict(type=int, default=4),
        fetch_every=dict(type=int, default=5),
        shards=dict(type=int, default=2),
        defaults={"steps": 120, "batch_size": 64, "lr": 0.02},
    )
    import jax
    import jax.numpy as jnp
    import numpy as np
    import optax

    import torchmpi_tpu as mpi
    from torchmpi_tpu.models import LeNet
    from torchmpi_tpu.utils import data as dutil
    from torchmpi_tpu.utils import tree as tree_util

    mpi.init()
    model = LeNet()
    params0 = model.init(jax.random.PRNGKey(args.seed),
                         jnp.zeros((1, 28, 28, 1)))

    # PS seeded with the initial params — the analog of
    # synchronizeParameters before async training starts.
    ps = mpi.parameterserver.init(params0, num_shards=args.shards)

    def local_loss(p, images, labels):
        logits = model.apply(p, images)
        return optax.softmax_cross_entropy_with_integer_labels(
            logits, labels).mean()

    grad_fn = jax.jit(jax.value_and_grad(local_loss))
    devices = jax.devices()[: args.workers]
    X, Y = dutil.synthetic_mnist(4096, seed=args.seed)
    losses = [[] for _ in range(args.workers)]

    def worker(widx):
        dev = devices[widx]
        with jax.default_device(dev):
            params = jax.tree.map(jnp.asarray, params0)
            fetch_handle = None
            for step, (xb, yb) in enumerate(dutil.batches(
                    X, Y, args.batch_size, steps=args.steps,
                    seed=args.seed + widx + 1)):
                loss, grads = grad_fn(params, jnp.asarray(xb),
                                      jnp.asarray(yb))
                update = jax.tree.map(lambda g: -args.lr * np.asarray(g),
                                      grads)
                ps.send(update, rule="add")  # async push, no wait
                # stale local step so progress continues between fetches
                params = jax.tree.map(lambda p, u: p + u, params,
                                      jax.tree.map(jnp.asarray, update))
                losses[widx].append(float(loss))
                if fetch_handle is not None and fetch_handle.done:
                    params = jax.tree.map(jnp.asarray, fetch_handle.wait())
                    fetch_handle = None
                if step % args.fetch_every == 0 and fetch_handle is None:
                    fetch_handle = ps.receive()  # prefetch (SURVEY §4.5)

    common.run_workers(worker, args.workers)

    center = ps.receive().wait()
    center = jax.tree.map(jnp.asarray, center)
    acc = common.evaluate(model, center, X[:1024], Y[:1024])
    print(f"PS ops served: {ps.ops_served()}")
    print(f"worker-0 loss first/last: {losses[0][0]:.4f} / "
          f"{losses[0][-1]:.4f}")
    print(f"final accuracy (PS params) {acc:.3f}")
    ps.shutdown()
    mpi.stop()
    assert acc > 0.9, "downpour MNIST did not converge"


if __name__ == "__main__":
    main()

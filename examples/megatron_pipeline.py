"""3D model parallelism on the first-class N-D world mesh: tensor-parallel
transformer blocks inside pipeline stages, replicated over a data axis —
``Config(mesh_shape={"pp": S, "dp": G, "tp": W})``, no communicator pushes.

Beyond the reference (TorchMPI is DP-only — SURVEY.md §3.3); this is the
composition its communicator-tree design must not preclude (§6.7), run
for real on ONE init-level mesh (VERDICT r3 #6): every pipeline stage is
a Megatron block (`tensor.tp_transformer_block`: heads and MLP sharded
over `tp`, one allreduce per sublayer), the stages ride a `pipeline`
schedule over `pp` (`gpipe_apply`, or `interleaved_apply` with two
virtual chunks per stage via `--schedule interleaved`), and each `dp`
group trains its own microbatch stream with gradients pmean'd across
`dp`.  Gradients flow through all three axes' collectives at once —
ppermute stage handoffs, f/g allreduce pairs, and the dp gradient
reduction.  Trains a fixed-batch regression and asserts the loss drops
5x.

Run: ``python examples/megatron_pipeline.py --devices 8``
     (mesh pp2 x dp1 x tp4: two pipeline stages of tensor-parallel width 4)
     ``python examples/megatron_pipeline.py --devices 8 --dp 2``
     (mesh pp2 x dp2 x tp2: true 3D)
"""

import common


def main():
    args = common.parse_args(
        __doc__, defaults={"lr": 0.05, "steps": 120},
        schedule=dict(type=str, default="gpipe",
                      choices=["gpipe", "interleaved"]),
        pp=dict(type=int, default=2, help="pipeline stages"),
        dp=dict(type=int, default=1, help="data-parallel groups"),
        tp=dict(type=int, default=-1,
                help="tensor-parallel width (-1 = rest of the devices)"))
    import jax
    import jax.numpy as jnp
    import numpy as np
    from jax import shard_map
    from jax.sharding import NamedSharding, PartitionSpec as P

    import torchmpi_tpu as mpi
    from torchmpi_tpu.parallel import pipeline as pp
    from torchmpi_tpu.parallel import tensor as tp

    # ONE world mesh with named axes, major -> minor = pp, dp, tp (tp
    # innermost: its f/g allreduce pairs are the chattiest, so they ride
    # the most interconnect-local axis).
    mesh = mpi.init(mpi.Config(mesh_shape={
        "pp": args.pp, "dp": args.dp, "tp": args.tp}))
    S = mesh.shape["pp"]            # pipeline stages
    n_dp = mesh.shape["dp"]         # data-parallel groups
    n_tp = mesh.shape["tp"]         # tensor-parallel width
    V = 2 if args.schedule == "interleaved" else 1
    L = S * V                       # logical transformer blocks
    H, D, F, B, T, M = n_tp, 8 * n_tp, 16 * n_tp, 2, 8, 2 * S

    rng = np.random.RandomState(args.seed)

    def dense_block(seed):
        r = np.random.RandomState(seed)
        s = 1.0 / np.sqrt(D)
        return {
            "wq": r.randn(D, D).astype(np.float32) * s,
            "wk": r.randn(D, D).astype(np.float32) * s,
            "wv": r.randn(D, D).astype(np.float32) * s,
            "wo": r.randn(D, D).astype(np.float32) * s,
            "w1": r.randn(D, F).astype(np.float32) * s,
            "w2": r.randn(F, D).astype(np.float32) * (1.0 / np.sqrt(F)),
        }

    # [L, ...] per-block weights -> TP shards on a new axis 1 -> pipeline
    # layout on axis 0 ([S, V, n_tp, ...], P("pp", None, "tp")) —
    # replicated over dp.
    blocks = [dense_block(args.seed + 1 + l) for l in range(L)]

    def tp_shard(key, w):
        shard = (tp.shard_rows if key in ("wo", "w2") else tp.shard_columns)
        return np.stack([shard(w, None, n_tp, i) for i in range(n_tp)])

    stacked = {k: np.stack([tp_shard(k, blk[k]) for blk in blocks])
               for k in blocks[0]}          # [L, n_tp, ...]
    staged = {k: pp.interleave_stages(v, S)  # [S, V, n_tp, ...]
              for k, v in stacked.items()}
    wspec = P("pp", None, "tp")
    staged = {k: jax.device_put(v, NamedSharding(mesh, wspec))
              for k, v in staged.items()}
    lnp = (jnp.ones(D), jnp.zeros(D))

    # Each dp group gets its own microbatch stream (leading dp axis).
    xs = rng.randn(n_dp, M, B, T, D).astype(np.float32)
    ys = (rng.randn(n_dp, M, B, T, D) * 0.3).astype(np.float32)
    dspec = P("dp")

    def stage_fn(params, x):
        # One pipeline tick = one TP transformer block (the schedule
        # hands this device's chunk tree for the tick).
        p = {"ln1": lnp, "ln2": lnp}
        p.update(params)
        return tp.tp_transformer_block(x, p, "tp", num_heads=H)

    def gpipe_stage(pv, x):
        # gpipe's stage params keep the V=1 chunk dim; strip it.
        return stage_fn({k: v[0] for k, v in pv.items()}, x)

    def body(staged_local, xg, yg):
        # staged_local leaves: [1, V, 1, ...] -> [V, ...] chunk tree;
        # xg/yg: [1, M, B, T, D] -> this dp group's stream.
        chunks = {k: v[0, :, 0] for k, v in staged_local.items()}
        xl, yl = xg[0], yg[0]

        def loss(chunks):
            if args.schedule == "interleaved":
                out = pp.interleaved_apply(stage_fn, chunks, xl, "pp",
                                           broadcast_out=False)
            else:
                out = pp.gpipe_apply(gpipe_stage, chunks, xl, "pp",
                                     broadcast_out=False)
            # Real outputs exist only on the last stage (zeros elsewhere,
            # where (out-ys)^2 would contribute a spurious ys^2): mask to
            # the last stage, then psum counts the true loss once with
            # backward identity via the g pair.
            my = jax.lax.axis_index("pp")
            err = jnp.where(my == S - 1, jnp.sum((out - yl) ** 2), 0.0)
            return tp.g_allreduce(err, "pp") / yl.size

        l, g = jax.value_and_grad(loss)(chunks)
        # The dp reduction of the reference's synchronizeGradients, on
        # the named dp axis of the same mesh.
        g = jax.tree.map(lambda t: jax.lax.pmean(t, "dp"), g)
        l = jax.lax.pmean(l, "dp")
        new = {k: chunks[k] - args.lr * g[k] for k in chunks}
        return l, {k: v[None, :, None] for k, v in new.items()}

    sspec = {k: wspec for k in staged}
    step = jax.jit(shard_map(
        body, mesh=mesh, in_specs=(sspec, dspec, dspec),
        out_specs=(P(), sspec), check_vma=False))

    xs_d = jax.device_put(xs, NamedSharding(mesh, dspec))
    ys_d = jax.device_put(ys, NamedSharding(mesh, dspec))
    losses = []
    for i in range(args.steps):
        l, staged = step(staged, xs_d, ys_d)
        losses.append(float(l))
        if i % 20 == 0 or i == args.steps - 1:
            print(f"step {i:4d}  loss {losses[-1]:.4f}")

    drop = losses[-1] / losses[0]
    print(f"loss {losses[0]:.4f} -> {losses[-1]:.4f}  "
          f"({args.schedule}, pp{S} x dp{n_dp} x tp{n_tp}, {L} blocks)")
    mpi.stop()
    assert drop < 0.2, f"3D-parallel training did not converge: {drop:.3f}"


if __name__ == "__main__":
    main()

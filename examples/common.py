"""Shared plumbing for the example scripts.

The reference kept training loops in the examples, not the library
(TorchMPI was "a communication library, not a trainer" — SURVEY.md §1); this
module is the examples' shared boilerplate, not part of torchmpi_tpu.
"""

from __future__ import annotations

import argparse
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def parse_args(description: str, defaults: dict = None, **extra):
    p = argparse.ArgumentParser(description=description)
    p.add_argument("--devices", type=int, default=0,
                   help="force N simulated CPU devices (0 = use real devices)")
    p.add_argument("--dcn", type=int, default=None,
                   help="outer (inter-slice) mesh axis size")
    p.add_argument("--steps", type=int, default=100)
    p.add_argument("--batch-size", type=int, default=256)
    p.add_argument("--lr", type=float, default=0.01)
    p.add_argument("--momentum", type=float, default=0.9)
    p.add_argument("--backend", type=str, default=None,
                   choices=[None, "xla", "hierarchical", "pallas"])
    p.add_argument("--buckets", type=int, default=None,
                   help="gradient allreduce buckets (overlap)")
    p.add_argument("--seed", type=int, default=0)
    for name, kw in extra.items():
        p.add_argument(f"--{name.replace('_', '-')}", **kw)
    if defaults:
        p.set_defaults(**defaults)
    args = p.parse_args()
    if args.devices:
        from torchmpi_tpu.utils.simulation import force_cpu_devices

        force_cpu_devices(args.devices)
    return args


def make_train_tools(model, sample_input, lr, momentum, seed=0):
    import jax
    import jax.numpy as jnp
    import optax

    params = model.init(jax.random.PRNGKey(seed), jnp.zeros(sample_input))
    tx = optax.sgd(lr, momentum=momentum)
    opt_state = tx.init(params)

    def local_loss(p, images, labels):
        logits = model.apply(p, images)
        return optax.softmax_cross_entropy_with_integer_labels(
            logits, labels).mean()

    return params, tx, opt_state, local_loss


def evaluate(model, params, images, labels, batch=512):
    import jax.numpy as jnp
    import numpy as np

    correct = 0
    for i in range(0, len(images), batch):
        logits = model.apply(params, jnp.asarray(images[i:i + batch]))
        correct += int((np.argmax(np.asarray(logits), axis=1)
                        == labels[i:i + batch]).sum())
    return correct / len(images)


def run_workers(worker_fn, n_workers: int) -> int:
    """Run PS worker threads, clamped to the device count, propagating any
    worker exception to the caller (a silently-dead worker otherwise makes
    convergence failures undiagnosable)."""
    import threading
    import traceback

    import jax

    n = min(n_workers, len(jax.devices()))
    if n < n_workers:
        print(f"[common] clamping workers {n_workers} -> {n} "
              f"(device count)")
    errors = []

    def wrap(i):
        try:
            worker_fn(i)
        except Exception as e:  # noqa: BLE001 — reported to main thread
            traceback.print_exc()
            errors.append((i, e))

    threads = [threading.Thread(target=wrap, args=(i,)) for i in range(n)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    if errors:
        raise RuntimeError(
            f"{len(errors)} worker(s) failed; first: {errors[0][1]!r}")
    return n


class StepTimer:
    def __init__(self):
        self.t0 = None
        self.steps = 0

    def start(self):
        self.t0 = time.time()

    def tick(self):
        self.steps += 1

    def rate(self, batch_size):
        dt = time.time() - self.t0
        return self.steps * batch_size / dt if dt > 0 else float("inf")

"""CIFAR-10 ResNet-20, synchronous data-parallel SGD (BASELINE config 2).

Reference analog: the fb.resnet.torch CIFAR recipe driven through
``torchmpi.nn`` gradient allreduce (SURVEY.md §8.1, reconstructed — reference
mount empty).  Demonstrates the full stateful-model path: BatchNorm running
statistics live in a separate collection and are cross-replica averaged with
the same selector-routed collectives as the gradients.

Run: ``python examples/cifar_resnet20.py --devices 8 --steps 60``
(add ``--zero 1`` for a sharded optimizer, ``--zero 3`` to also keep the
parameters as flat 1/n shards between steps — same numerics either way).
"""

import common


def main():
    args = common.parse_args(
        __doc__, defaults={"lr": 0.2, "steps": 60, "batch_size": 128},
        zero=dict(type=int, default=0, choices=[0, 1, 3],
                  help="ZeRO level: 1 shards optimizer state, 3 also "
                       "shards the parameters between steps"))
    import jax
    import jax.numpy as jnp
    import optax

    import torchmpi_tpu as mpi
    from torchmpi_tpu.models import ResNet20
    from torchmpi_tpu.parallel import zero as pzero
    from torchmpi_tpu.utils import data as dutil

    mpi.init(mpi.Config(dcn_size=args.dcn))
    if args.backend:
        mpi.set_config(backend=args.backend, custom_min_bytes=0)
    mesh = mpi.world_mesh()
    model = ResNet20()

    variables = model.init(jax.random.PRNGKey(args.seed),
                           jnp.zeros((1, 32, 32, 3)), train=False)
    params, batch_stats = variables["params"], variables["batch_stats"]
    tx = optax.sgd(args.lr, momentum=args.momentum)

    # Canonical DP recipe: grad allreduce + BatchNorm running-stats average
    # on the same selector-routed collective path + metric reduction.
    # ZeRO levels reuse the same recipe with sharded persistent state.
    # Templates carry shapes only — holding real replicated arrays through
    # the run would defeat the 1/n persistent-params story of zero=3.
    shape_template = jax.tree.map(
        lambda l: jax.ShapeDtypeStruct(l.shape, l.dtype), params)
    dp_step = mpi.recipes.make_bn_dp_train_step(
        model, tx, mesh=mesh, backend=args.backend, n_buckets=args.buckets,
        zero=args.zero,
        params_template=shape_template if args.zero == 3 else None)
    if args.zero:
        batch_stats = mpi.nn.synchronize_parameters(batch_stats, mesh=mesh)
        opt_state = pzero.init(params, tx, mesh=mesh)
        if args.zero == 3:
            params = pzero.shard_params(params, mesh=mesh)
        else:
            params = mpi.nn.synchronize_parameters(params, mesh=mesh)
    else:
        params, opt_state, batch_stats = mpi.recipes.replicate_bn_state(
            params, tx.init(params), batch_stats, mesh=mesh)

    X, Y = dutil.synthetic_cifar(4096, seed=args.seed)
    timer = common.StepTimer()
    timer.start()
    for i, (xb, yb) in enumerate(
            dutil.batches(X, Y, args.batch_size, steps=args.steps,
                          seed=args.seed)):
        params, opt_state, batch_stats, loss = dp_step(
            params, opt_state, batch_stats, xb, yb)
        timer.tick()
        if i % 10 == 0 or i == args.steps - 1:
            print(f"step {i:4d}  loss {float(loss):.4f}")

    if args.zero == 3:
        # Export the full parameter pytree from the flat shards for eval.
        params = pzero.unshard_params(params, shape_template, mesh=mesh)

    def eval_logits(xb):
        return model.apply({"params": params, "batch_stats": batch_stats},
                           jnp.asarray(xb), train=False)

    import numpy as np

    logits = eval_logits(X[:1024])
    acc = float((np.argmax(np.asarray(logits), 1) == Y[:1024]).mean())
    print(f"final accuracy {acc:.3f}  ({timer.rate(args.batch_size):.0f} img/s)")
    mpi.stop()
    assert acc > 0.85, "CIFAR ResNet-20 did not converge"


if __name__ == "__main__":
    main()

"""MNIST LeNet, single-device sequential baseline.

Reference analog: ``examples/mnist_sequential.lua`` [HIGH] (reconstructed —
reference mount empty, SURVEY.md §0/§3 C15): the non-distributed control run
the distributed variants are compared against.

Run: ``python examples/mnist_sequential.py --steps 100``
"""

import common


def main():
    args = common.parse_args(__doc__)
    import jax
    import jax.numpy as jnp
    import optax

    from torchmpi_tpu.models import LeNet
    from torchmpi_tpu.utils import data as dutil

    model = LeNet()
    params, tx, opt_state, local_loss = common.make_train_tools(
        model, (1, 28, 28, 1), args.lr, args.momentum, args.seed)

    @jax.jit
    def step(params, opt_state, images, labels):
        loss, grads = jax.value_and_grad(local_loss)(params, images, labels)
        updates, opt_state = tx.update(grads, opt_state, params)
        return optax.apply_updates(params, updates), opt_state, loss

    X, Y = dutil.synthetic_mnist(4096, seed=args.seed)
    timer = common.StepTimer()
    timer.start()
    for i, (xb, yb) in enumerate(
            dutil.batches(X, Y, args.batch_size, steps=args.steps,
                          seed=args.seed)):
        params, opt_state, loss = step(params, opt_state,
                                       jnp.asarray(xb), jnp.asarray(yb))
        timer.tick()
        if i % 20 == 0 or i == args.steps - 1:
            print(f"step {i:4d}  loss {float(loss):.4f}")
    acc = common.evaluate(model, params, X[:1024], Y[:1024])
    print(f"final accuracy {acc:.3f}  ({timer.rate(args.batch_size):.0f} img/s)")
    assert acc > 0.9, "sequential MNIST did not converge"


if __name__ == "__main__":
    main()

"""Wire codecs for the inter-slice (DCN) leg of two-level collectives.

Multi-slice reality is a bandwidth cliff: ICI moves hundreds of GB/s per
chip, DCN a fraction of that (SNIPPETS.md [1]'s GSPMD pattern scales to
6000-chip superclusters by treating the two tiers differently).  The
two-level allreduce already sends only ``1/ici_n`` of the tensor over
DCN (reduce_scatter over ICI first — ``parallel/hierarchical.py``);
this module narrows that residual DCN payload further with scaled
integer/fp8 wire codecs, the deep-gradient-compression trade:

- **Only the post-reduce_scatter shard crossing DCN is quantized.**
  The ICI legs always run in the tensor's native dtype — the fusion
  discipline (never promote, never narrow where bandwidth is free).
- **Per-bucket scale**: ``int8``/``fp8`` payloads carry one f32 scale
  per bucket (``scale = amax / qmax``); the inter-slice sum runs as an
  all-gather of the quantized shards + scales with a local decoded
  reduction, so every rank computes the identical result from the
  identical wire bytes (no re-quantization between slices).
- **Error feedback** (the gradient-sync paths): a persistent
  per-(site, bucket) residual accumulator is added back before
  quantization and refilled with the new quantization error, so the
  bias of repeated rounding cancels over steps instead of accumulating
  — threaded as explicit state through
  ``gradsync.synchronize_gradients(residuals=...)``,
  ``gradsync.make_overlapped_grad_fn(residuals=...)``, and the ZeRO
  update legs (``dcn_residuals=...``).  Residuals are f32 regardless of
  the wire dtype (the error is below the wire's own precision).

Opt-in via ``Config.dcn_compress`` ("off"/"bf16"/"int8"/"fp8") +
``Config.dcn_compress_min_bytes``; **never imported when off** — the
same discipline as analysis/obs/faults: every call site resolves the
codec at trace/plan-build time behind one string compare, so a build
that never opts in pays zero import cost and dispatches bit-identically
(subprocess-asserted in tests/test_compress.py).

This module is also THE home of wire-compression validation
(:func:`validate_wire`): ``gradsync.py`` and ``zero.py`` used to each
hand-roll ``compress not in (None, "none", "bf16")``.

See docs/HIERARCHICAL.md for the codec semantics, the error-feedback
caveats (at-least-once delivery, restart), and the evidence workflow.
"""

from __future__ import annotations

from typing import Optional, Sequence, Tuple

import jax.numpy as jnp
import numpy as np
from jax import lax

from . import fusion, runtime

# Codec name -> wire dtype.  fp8 is e4m3 (the gradient-friendly wide-
# mantissa variant); jaxcompat guarantees nothing here — an older jax
# without float8 support fails validate_wire loudly instead of
# miscompiling.
CODECS = ("bf16", "int8", "fp8")
_WIRE_DTYPES = {
    "bf16": jnp.bfloat16,
    "int8": jnp.int8,
    "fp8": getattr(jnp, "float8_e4m3fn", None),
}
# Largest representable magnitude per quantized codec (the scale
# denominator): int8 symmetric [-127, 127]; e4m3fn tops out at 448.
_QMAX = {"int8": 127.0, "fp8": 448.0}


def validate_wire(value, *, allowed: Sequence[str] = CODECS,
                  site: str = "compress") -> Optional[str]:
    """Canonicalize a wire-compression knob: ``None``/"none"/"off"/""
    mean uncompressed (returns None); anything else must name a codec
    in ``allowed`` (case-insensitive) or this raises.  The ONE
    validation point for ``gradsync_compress`` (``allowed=("bf16",)``
    — the legacy whole-wire cast) and ``dcn_compress`` (all codecs)."""
    if value is None:
        return None
    v = str(value).strip().lower()
    if v in ("none", "off", ""):
        return None
    if v not in allowed:
        raise ValueError(
            f"{site}: unknown compression {value!r} "
            f"(allowed: {', '.join(allowed)} or none)")
    if _WIRE_DTYPES.get(v) is None:
        raise ValueError(
            f"{site}: codec {v!r} needs jnp.float8_e4m3fn, which this "
            f"jax build lacks")
    return v


def resolve_dcn(cfg) -> Optional[str]:
    """The active DCN codec from a Config (None when off)."""
    return validate_wire(getattr(cfg, "dcn_compress", "off"),
                         site="config.dcn_compress")


def resolve_ef(dcn_compress, cfg, *, site: str, backend=None,
               explicit_compress: bool = False, compress=None,
               allow_backend: bool = False) -> str:
    """Resolve + police one error-feedback entry point's knobs — THE
    shared activation gate for ``synchronize_gradients(residuals=)``,
    ``make_overlapped_grad_fn(residuals=True)``, and the ZeRO
    ``dcn_residuals=`` legs.  Returns the codec, never None: residual
    state without an active codec is an error.  The EF collective is a
    fixed two-level schedule, so an explicit ``backend=`` raises unless
    the caller routes *other* legs with it (``allow_backend`` — ZeRO's
    parameter all_gather), and an explicit resolved ``compress=`` (the
    legacy ICI wire cast) always raises rather than being silently
    dropped."""
    if dcn_compress is None and cfg is not None:
        dcn_compress = getattr(cfg, "dcn_compress", "off")
    codec = validate_wire(dcn_compress, site=f"{site}(dcn_compress)")
    if codec is None:
        raise ValueError(
            f"{site}: residual state given but no DCN codec active — "
            f"set Config.dcn_compress (or pass dcn_compress=) to "
            f"bf16|int8|fp8")
    if backend is not None and not allow_backend:
        raise ValueError(
            f"{site}: backend= does not combine with error-feedback "
            f"residuals — the EF collective is the fixed two-level "
            f"hierarchical schedule")
    if explicit_compress and compress is not None:
        raise ValueError(
            f"{site}: compress= does not combine with error-feedback "
            f"residuals — on this path the wire compression is the "
            f"DCN codec (dcn_compress)")
    return codec


def ef_axes(axis_names) -> Tuple[str, str]:
    """Validate/split the ``(outer, inner)`` axis pair every
    error-feedback entry point requires — the ONE home of the check
    (``gradsync``/``zero`` used to each hand-roll it)."""
    axes = ((axis_names,) if isinstance(axis_names, str)
            else tuple(axis_names))
    if len(axes) != 2:
        raise ValueError(
            f"DCN error feedback needs (outer, inner) axes, got {axes}")
    return axes[0], axes[1]


def init_residuals(shard_sizes: Sequence[int], n_dev: int) -> list:
    """Zero-initialized error-feedback accumulators: one f32
    ``[n_dev, shard]`` buffer per bucket.  The ONE place the residual
    buffer layout is defined — the ``init_*_residuals`` helpers in
    ``gradsync``/``zero`` all build through here, so a layout change
    lands everywhere at once."""
    return [jnp.zeros((int(n_dev), int(s)), jnp.float32)
            for s in shard_sizes]


def expected_shards(extents: Sequence[int], n_inner: int) -> list:
    """Per-bucket ICI-scattered residual extents — ``ceil(extent /
    n_inner)``, the point where quantization happens.  The ONE formula
    shared by the ``init_*_residuals`` helpers and every EF entry
    point's structural validation (a drifted copy would reject state
    its own init helper built)."""
    n = max(1, int(n_inner))
    return [-(-int(e) // n) for e in extents]


def wire_itemsize(codec: str) -> int:
    return np.dtype(_WIRE_DTYPES[codec]).itemsize


def wire_nbytes_of(n_elems: int, codec: str) -> int:
    """Bytes one device puts on the DCN wire for an ``n_elems`` bucket:
    the quantized payload plus the f32 scale (bf16 carries none)."""
    return int(n_elems) * wire_itemsize(codec) + (
        0 if codec == "bf16" else 4)


def encode(x, codec: str) -> Tuple[jnp.ndarray, Optional[jnp.ndarray]]:
    """Quantize one bucket onto the wire.  Returns ``(payload, scale)``
    — ``scale`` is a scalar f32 for int8/fp8, None for bf16 (a plain
    cast).  ``x`` is promoted to f32 before scaling so bf16 inputs
    quantize from their exact values."""
    xf = x.astype(jnp.float32)
    if codec == "bf16":
        return xf.astype(jnp.bfloat16), None
    qmax = _QMAX[codec]
    amax = jnp.max(jnp.abs(xf)) if xf.size else jnp.float32(0)
    # The tiny floor keeps an all-zero bucket from dividing by zero; it
    # decodes back to exactly zero either way.
    scale = jnp.maximum(amax / qmax, jnp.float32(1e-30))
    if codec == "int8":
        q = jnp.clip(jnp.round(xf / scale), -qmax, qmax).astype(jnp.int8)
    else:
        q = (xf / scale).astype(_WIRE_DTYPES["fp8"])
    return q, scale


def decode(payload, scale, dtype=jnp.float32):
    """Inverse of :func:`encode` (up to the codec's rounding)."""
    if scale is None:
        return payload.astype(dtype)
    return (payload.astype(jnp.float32) * scale).astype(dtype)


def host_encode(x, codec: str):
    """Numpy twin of :func:`encode` for host-side payloads (the
    hot-state replication tier — docs/HOTSTATE.md — quantizes state
    deltas that already live in host RAM; a device round trip per
    streamed leaf would cost more than the quantization saves).  Same
    math, same tiny-floor scale, so a host encode decodes identically
    to a device encode of the same values."""
    xf = np.asarray(x, dtype=np.float32)
    if codec == "bf16":
        # No numpy bf16: keep the wire dtype discipline by truncating
        # the mantissa in uint32 space (round-to-nearest-even is what
        # jnp does; truncation here is fine — host bf16 is unused by
        # the exact-delta path, which is int8 + correction).
        u = xf.view(np.uint32)
        return ((u + 0x7FFF + ((u >> 16) & 1)) >> 16).astype(np.uint16), \
            None
    qmax = _QMAX[codec]
    amax = float(np.max(np.abs(xf))) if xf.size else 0.0
    scale = np.float32(max(amax / qmax, 1e-30))
    q = np.clip(np.round(xf / scale), -qmax, qmax).astype(np.int8)
    return q, scale


def host_decode(payload, scale, dtype=np.float32):
    """Inverse of :func:`host_encode` (up to the codec's rounding)."""
    if scale is None:
        u = payload.astype(np.uint32) << 16
        return u.view(np.float32).astype(dtype)
    return (payload.astype(np.float32) * np.float32(scale)).astype(dtype)


def _leg_record(op: str, codec: str, nbytes: int, wire_nbytes: int,
                min_bytes: int, axes, **extra) -> dict:
    """The one ``kind="dcn_compress"`` record schema (analysis rule C2
    reads these — a field rename lands here and in ``rules._rule_c2``
    only)."""
    return dict(kind="dcn_compress", op=op, codec=codec,
                nbytes=int(nbytes), wire_nbytes=int(wire_nbytes),
                min_bytes=int(min_bytes), axes=tuple(axes),
                source=fusion._record_source(), **extra)


def note_leg(op: str, codec: Optional[str], payload_nbytes: int,
             wire_nbytes: int, axes, *, min_bytes: int = 0) -> None:
    """Trace-time accounting for one DCN leg: the obs wire-byte
    counters (the CPU-sim-assertable win ``collectives_bench.py
    --dcn-compare`` reads) and the analysis C2 record.  Gated here so
    call sites stay one-liners; runs at trace only, never per step."""
    name = codec or "none"
    if runtime.effective_config().obs != "off":
        from . import obs

        obs.record_dcn(op, name, wire_nbytes, payload_nbytes)
    if fusion._trace_listener is not None:
        fusion._emit_trace_record(_leg_record(
            op, name, payload_nbytes, wire_nbytes, min_bytes, axes))


def note_skipped(op: str, codec: str, nbytes: int, axes, *,
                 min_bytes: int = 0, incompatible: bool = False) -> None:
    """Trace-time C2 evidence for a DCN leg that ran UNCOMPRESSED
    despite an active codec (incompatible op/payload, or below the
    ``dcn_compress_min_bytes`` floor): wire == payload, and no obs
    record — the caller's uncompressed dispatch accounts for itself."""
    if fusion._trace_listener is not None:
        extra = {"incompatible": True} if incompatible else {}
        fusion._emit_trace_record(_leg_record(
            op, codec, nbytes, nbytes, min_bytes, axes, **extra))


def dcn_allreduce(shard, outer: str, codec: str, *, residual=None,
                  op: str = "sum"):
    """Allreduce the ICI-scattered shard across slices (the DCN leg) on
    a quantized wire.  Returns ``(sum, new_residual)``.

    ``bf16`` rides a plain cast + psum (half the wire, one launch).
    ``int8``/``fp8`` all-gather the quantized shards + per-bucket
    scales over ``outer`` and reduce the decoded values locally — every
    slice computes the identical f32 sum from the identical wire bytes,
    so no slice ever re-quantizes another's contribution.

    ``residual`` (f32, shard-shaped) arms error feedback: it is added
    to the shard before quantization and ``new_residual`` is the new
    quantization error (``None`` in, ``None`` out).  ``op`` must be
    ``sum`` — mean scaling is the caller's (it owns the global count).
    """
    if op != "sum":
        raise ValueError(
            f"compressed DCN leg supports op='sum', got {op!r}")
    out_dtype = shard.dtype
    xf = shard.astype(jnp.float32)
    if residual is not None:
        xf = xf + residual.reshape(xf.shape).astype(jnp.float32)
    payload, scale = encode(xf, codec)
    if codec == "bf16":
        tot = lax.psum(payload, outer).astype(jnp.float32)
    else:
        from .parallel import hierarchical

        qs = lax.all_gather(payload, outer, axis=0, tiled=False)
        sin = scale
        if hierarchical._serialize_collectives():
            # Unordered sibling collectives deadlock the CPU sim's
            # blocking rendezvous (see hierarchical._serialize_collectives)
            # — chain the scale gather after the payload gather there.
            sin, _ = lax.optimization_barrier((sin, qs))
        ss = lax.all_gather(sin, outer, axis=0, tiled=False)
        tot = jnp.sum(qs.astype(jnp.float32) * ss[:, None], axis=0)
    new_residual = None
    if residual is not None:
        new_residual = xf - decode(payload, scale, jnp.float32)
    return tot.astype(out_dtype), new_residual


def ef_bucket_allreduce(flat, outer: str, inner: str, codec: str,
                        residual, *, op: str = "sum",
                        min_bytes: int = 0):
    """One bucket's two-level allreduce with error feedback:
    reduce_scatter(ici) -> EF-quantized allreduce(dcn) ->
    all_gather(ici).  ``flat`` is the bucket's 1-D concat (native
    dtype), ``residual`` this device's f32 accumulator (reshapeable to
    the shard: ``ceil(len/ici_n)`` elements).  A DCN shard below
    ``min_bytes`` (``config.dcn_compress_min_bytes``) crosses
    uncompressed with the residual passed through unchanged — the same
    floor the plain hierarchical path applies, with the C2 INFO
    evidence.  Returns ``(reduced_flat, new_residual)`` with the
    residual in the input residual's shape/dtype.  The gradient-sync
    EF entry point (``gradsync``/``zero``/the overlap schedule build
    on this)."""
    if op not in ("sum", "mean"):
        raise ValueError(
            f"error-feedback allreduce supports sum|mean, got {op!r}")
    n_i = lax.axis_size(inner)
    n_o = lax.axis_size(outer)
    length = flat.shape[0]
    pad = (-length) % n_i
    if pad:
        flat = jnp.concatenate([flat, jnp.zeros((pad,), flat.dtype)])
    shard = lax.psum_scatter(flat, inner, scatter_dimension=0, tiled=True)
    shard_nbytes = shard.size * shard.dtype.itemsize
    if min_bytes and shard_nbytes < int(min_bytes):
        note_skipped("allreduce", codec, shard_nbytes, (outer, inner),
                     min_bytes=min_bytes)
        if runtime.effective_config().obs != "off":
            from . import obs

            obs.record_dcn("allreduce", "none", shard_nbytes,
                           shard_nbytes)
        tot = lax.psum(shard, outer)
        new_res = residual
    else:
        note_leg("allreduce", codec, shard_nbytes,
                 wire_nbytes_of(shard.size, codec), (outer, inner),
                 min_bytes=min_bytes)
        tot, new_res = dcn_allreduce(shard, outer, codec,
                                     residual=residual.reshape(-1))
        new_res = new_res.reshape(residual.shape).astype(residual.dtype)
    full = lax.all_gather(tot, inner, axis=0, tiled=True)
    if pad:
        full = full[:length]
    if op == "mean":
        full = full / (n_i * n_o)
    return full, new_res


def ef_group_reduce_scatter(g_flat, outer: str, inner: str, codec: str,
                            residual, *, min_bytes: int = 0):
    """One dtype group's two-level ZeRO gradient leg with error
    feedback: deliver this device its ``_axis_index``-linearized flat
    shard of the summed group, quantizing only the DCN crossing.

    ``g_flat`` is the group's padded flat buffer (length divisible by
    ``n_outer * n_inner``).  The naive ici-then-dcn reduce_scatter
    would hand each device an ICI-MAJOR extent, but the persistent ZeRO
    state layout (``fusion.local_shard``) is dcn-major — so the buffer
    is pre-permuted (a pure relabeling; the reduction is elementwise)
    such that the cheap-first staging still lands every device on its
    dcn-major extent.  Returns ``(flat_shard [len/n], new_residual)``;
    the residual covers the ICI-scattered intermediate
    (``len/n_inner`` f32 elements), where the quantization happens.
    """
    n_i = lax.axis_size(inner)
    n_o = lax.axis_size(outer)
    sub = g_flat.shape[0] // (n_i * n_o)
    perm = g_flat.reshape(n_o, n_i, sub).swapaxes(0, 1).reshape(-1)
    s = lax.psum_scatter(perm, inner, scatter_dimension=0, tiled=True)
    s_nbytes = s.size * s.dtype.itemsize
    if min_bytes and s_nbytes < int(min_bytes):
        # Below the config floor: the DCN crossing runs uncompressed
        # with the residual passed through unchanged (C2 INFO).
        note_skipped("reduce_scatter", codec, s_nbytes, (outer, inner),
                     min_bytes=min_bytes)
        if runtime.effective_config().obs != "off":
            from . import obs

            obs.record_dcn("reduce_scatter", "none", s_nbytes, s_nbytes)
        tot = lax.psum(s, outer)
        new_res = residual
    else:
        note_leg("reduce_scatter", codec, s_nbytes,
                 wire_nbytes_of(s.size, codec), (outer, inner),
                 min_bytes=min_bytes)
        tot, new_res = dcn_allreduce(s, outer, codec,
                                     residual=residual.reshape(-1))
        new_res = new_res.reshape(residual.shape).astype(residual.dtype)
    shard = lax.dynamic_slice(tot, (lax.axis_index(outer) * sub,), (sub,))
    return shard, new_res


class ResidualMismatchError(ValueError):
    """Raised by the EF entry points when threaded residual state does
    not match the bucket layout.  A distinct type (still a ValueError
    for callers) so ``analysis.check`` can convert exactly this raise
    into its C2 finding without swallowing unrelated trace errors."""


def residual_note(expected: int, got: int, ok: bool, axes) -> None:
    """Trace-time record of an error-feedback residual binding for the
    analysis C2 rule: how many residual buffers the bucket layout
    expects vs what the caller threaded, and whether shapes lined up."""
    if fusion._trace_listener is not None:
        fusion._emit_trace_record(dict(
            kind="dcn_residual", expected=int(expected), got=int(got),
            ok=bool(ok), axes=tuple(axes),
            source=fusion._record_source()))


def check_residuals(residuals, want: Sequence[int], axes, *, site: str,
                    layout: str, init_hint: str) -> list:
    """Coerce + structurally validate one EF entry point's residual
    state against the expected per-bucket shard extents (the
    :func:`expected_shards` values) — the ONE home of the check for
    ``gradsync``/the overlap schedule/``zero``.  Emits the C2 evidence
    record BEFORE raising, so the analyzer reports the mismatch with
    provenance even though the runtime raise is what the user first
    hits.  Returns the coerced per-bucket list on success."""
    import jax

    res_list = (list(residuals) if isinstance(residuals, (list, tuple))
                else jax.tree.leaves(residuals))
    ok = (len(res_list) == len(want)
          and all(int(np.prod(r.shape)) == int(w)
                  for r, w in zip(res_list, want)))
    residual_note(len(want), len(res_list), ok, axes)
    if not ok:
        raise ResidualMismatchError(
            f"{site}: DCN residual state does not match {layout} "
            f"({len(res_list)} buffers of sizes "
            f"{[int(np.prod(r.shape)) for r in res_list]} vs "
            f"{len(want)} bucket(s) needing shard sizes {list(want)}) "
            f"— build the state with {init_hint}")
    return res_list

"""Hot-state replication tier: RAM-buddy recovery + live rank migration.

The durable checkpoint stack (utils/checkpoint.py + utils/durable.py,
docs/CHECKPOINT.md) bottoms every recovery out in filesystem restore,
so MTTR is gated on save-interval + disk.  This package adds the tier
above it (docs/HOTSTATE.md): after each completed step a rank ships its
state *delta* to its buddy's RAM — int8-quantized through the
``compress.py`` host codecs plus an exact sparse residual correction,
so the reconstruction is BIT-IDENTICAL to the sender's state — tagged
``(step, epoch, incarnation, blake2b digest)`` and epoch-fenced like
board writes.  ``restart.recover`` (and through it the elastic driver)
consults the RAM tier FIRST, falling back to the disk buddies and then
the primaries only when the RAM copy is missing/stale/corrupt: the
three-rung recovery ladder, each rung counted
(``tm_hotstate_{streamed,restored,fallback_disk,verify_failed,...}_total``).

The same stream generalizes to planned live migration:
:func:`migrate` drains a rank onto a spare at a step boundary (reverse
of ``elastic.admit`` — pre-seed the spare's RAM from the stream, admit
it, retire the source) with zero checkpoint rollback; the drain is
lease-visible (watchdog state ``migrating``) so ``obs_tool blame
--live`` renders it distinct from parked/dead.

Off-mode discipline (the analysis/obs/faults/guard posture):
``Config.hotstate="off"`` never imports this module — the knob is a
consent gate for a driver layer the user enables explicitly
(:func:`enable`), and the dispatch path has no branch on it at all.
``restart.recover``/``elastic.run_elastic`` reach an armed replicator
only through ``sys.modules`` lookups, exactly like the fault and
telemetry seams (subprocess-asserted in tests/test_hotstate.py).

Fault surface (docs/FAULTS.md): every stream message crosses the
``hotstate.send`` (sender side) and ``hotstate.recv`` (buddy side)
payload sites — ``drop`` loses the message (the chain self-heals: the
next publish for that rank is forced to a full snapshot),
``corrupt_silent`` flips real bits in the packed payload (the digest
verify catches it at restore time and the ladder falls to the disk
rung instead of restoring poisoned state), ``stall`` wedges the stream
where the watchdog can see it.  Deliberately NOT retried: replication
is best-effort by design — a lost replica costs a rung, never a step.
"""

from __future__ import annotations

import hashlib
import sys
import threading
from typing import Any, Callable, Dict, List, Optional, Tuple

import numpy as np

from .. import runtime
from ..utils import telemetry

PyTree = Any

_HEADER_VERSION = 1


class HotStateMiss(RuntimeError):
    """No usable RAM replica (missing, stale, or every candidate failed
    its digest verify) — the caller falls to the disk rung."""


def _require_on():
    """Every public entry point's consent gate (the user must opt in
    via ``Config.hotstate`` — same posture as ``elastic``: a driver
    layer's knob, not a dispatch-path switch)."""
    cfg = runtime.effective_config()
    if cfg.hotstate == "off":
        raise RuntimeError(
            "torchmpi_tpu.hotstate requires Config.hotstate='on' (or "
            "TORCHMPI_TPU_HOTSTATE=1) — the hot-state tier is opt-in; "
            "see docs/HOTSTATE.md")
    return cfg


def _record(event: str, *, step: int = 0, peer: str = "",
            reason: str = "") -> None:
    """tm_hotstate_* through obs when active (the telemetry shim does
    the sys.modules gating — this module never imports obs)."""
    telemetry.emit("record_hotstate", event, step=step, peer=peer,
                   reason=reason)


def _faults_mod():
    """The armed fault layer, or None (sys.modules — never imported)."""
    mod = sys.modules.get("torchmpi_tpu.faults")
    return mod if (mod is not None and mod.active()) else None


def _fence_check(epoch: int) -> None:
    """Epoch-fence a stream write like a board write: a publisher whose
    view epoch is behind the board's committed epoch must not land
    replicas the survivors could mistake for fresh state (the zombie-
    minority hazard, RAM edition).  One sys.modules lookup — quorum-off
    sessions never import the fencing module."""
    fz = sys.modules.get("torchmpi_tpu.faults.fencing")
    if fz is None:
        return
    fence = fz.current()
    if fence is not None:
        fence.check(epoch=epoch, what="hotstate stream")


def _buddy_holders(rank: int, world: int, k: int) -> List[int]:
    """Ranks ``(rank+1..k) mod world`` — the SAME ring as
    ``utils.durable.buddy_holders`` (kept formula-identical so the RAM
    replica of a shard lives where its disk mirror does; duplicated
    rather than imported because ``utils/durable.py`` must stay
    never-imported under ``ckpt_redundancy="off"``)."""
    k = max(0, min(int(k), max(0, int(world) - 1)))
    return [(int(rank) + j) % int(world) for j in range(1, k + 1)]


def _tree_leaves(tree) -> Tuple[list, Any]:
    import jax

    leaves, treedef = jax.tree_util.tree_flatten(tree)
    return [np.asarray(x) for x in leaves], treedef


def _digest_state(leaves: List[np.ndarray]) -> str:
    """Canonical blake2b over the state's leaf bytes + shape/dtype
    headers — what the sender tags and a restore must reproduce."""
    h = hashlib.blake2b(digest_size=16)
    for x in leaves:
        x = np.ascontiguousarray(x)
        h.update(f"{x.dtype.str}:{x.shape};".encode())
        h.update(x.tobytes())
    return h.hexdigest()


def _is_delta_leaf(x: np.ndarray) -> bool:
    return x.dtype.kind == "f" and x.size > 0


def _pack_delta(new: List[np.ndarray], base: List[np.ndarray]
                ) -> np.ndarray:
    """Pack one delta message: per float leaf an int8-quantized delta
    (``compress.host_encode``) plus the exact sparse correction that
    makes ``base + decode(q)`` land bit-identically on ``new``; non-
    float (and empty) leaves ship raw.  Returns one contiguous uint8
    blob — the writable payload the fault sites flip bits in."""
    from .. import compress

    chunks: List[np.ndarray] = []
    for x, b in zip(new, base):
        x = np.ascontiguousarray(x)
        if not _is_delta_leaf(x):
            chunks.append(x.reshape(-1).view(np.uint8))
            continue
        delta = x.astype(np.float32) - b.astype(np.float32)
        # Non-finite deltas (NaN-padded buffers, inf overflow) would
        # poison the quantizer's scale; zero them — the sparse exact
        # correction below carries those elements verbatim anyway.
        delta = np.nan_to_num(delta, nan=0.0, posinf=0.0, neginf=0.0)
        q, scale = compress.host_encode(delta, "int8")
        approx = (b.astype(np.float32)
                  + compress.host_decode(q, scale)).astype(x.dtype)
        idx = np.flatnonzero((approx != x).reshape(-1)).astype(np.int64)
        vals = x.reshape(-1)[idx]
        chunks.append(q.reshape(-1).view(np.uint8))
        chunks.append(np.atleast_1d(np.float32(scale)).view(np.uint8))
        chunks.append(np.array([idx.size], np.int64).view(np.uint8))
        chunks.append(idx.view(np.uint8))
        chunks.append(np.ascontiguousarray(vals).view(np.uint8))
    return (np.concatenate(chunks) if chunks
            else np.zeros(0, np.uint8)).copy()


def _unpack_delta(blob: np.ndarray, base: List[np.ndarray]
                  ) -> List[np.ndarray]:
    """Inverse of :func:`_pack_delta` against the same ``base``.
    Raises (ValueError/IndexError) on a blob whose structure no longer
    parses — corrupted lengths surface as a verify failure upstream."""
    from .. import compress

    buf = blob.view(np.uint8)
    off = 0

    def take(n: int) -> np.ndarray:
        nonlocal off
        if n < 0 or off + n > buf.size:
            raise ValueError("hotstate delta blob truncated")
        out = buf[off:off + n]
        off += n
        return out

    out: List[np.ndarray] = []
    for b in base:
        b = np.ascontiguousarray(b)
        if not _is_delta_leaf(b):
            raw = take(b.nbytes)
            out.append(raw.view(b.dtype).reshape(b.shape).copy())
            continue
        q = take(b.size).view(np.int8)
        scale = take(4).view(np.float32)[0]
        n_corr = int(take(8).view(np.int64)[0])
        if n_corr < 0 or n_corr > b.size:
            raise ValueError("hotstate delta correction count corrupt")
        idx = take(n_corr * 8).view(np.int64)
        vals = take(n_corr * b.dtype.itemsize).view(b.dtype)
        approx = (b.astype(np.float32)
                  + compress.host_decode(q.reshape(b.shape), scale)
                  ).astype(b.dtype)
        flat = approx.reshape(-1)
        if n_corr and (idx.min() < 0 or idx.max() >= flat.size):
            raise ValueError("hotstate delta correction index corrupt")
        flat[idx] = vals
        out.append(flat.reshape(b.shape))
    if off != buf.size:
        raise ValueError("hotstate delta blob has trailing bytes")
    return out


def _pack_snap(leaves: List[np.ndarray]) -> np.ndarray:
    chunks = [np.ascontiguousarray(x).reshape(-1).view(np.uint8)
              for x in leaves]
    return (np.concatenate(chunks) if chunks
            else np.zeros(0, np.uint8)).copy()


def _unpack_snap(blob: np.ndarray, like: List[np.ndarray]
                 ) -> List[np.ndarray]:
    buf = blob.view(np.uint8)
    out, off = [], 0
    for b in like:
        b = np.ascontiguousarray(b)
        if off + b.nbytes > buf.size:
            raise ValueError("hotstate snapshot blob truncated")
        out.append(buf[off:off + b.nbytes].view(b.dtype)
                   .reshape(b.shape).copy())
        off += b.nbytes
    if off != buf.size:
        raise ValueError("hotstate snapshot blob has trailing bytes")
    return out


class _Entry:
    """One received replica message in a buddy's RAM."""

    __slots__ = ("kind", "step", "epoch", "incarnation", "digest",
                 "blob")

    def __init__(self, kind: str, step: int, epoch: int,
                 incarnation: int, digest: str, blob: np.ndarray):
        self.kind = kind            # "snap" | "delta"
        self.step = int(step)
        self.epoch = int(epoch)
        self.incarnation = int(incarnation)
        self.digest = digest        # of the FULL state at self.step
        self.blob = blob


class Replicator:
    """The per-process hot-state store: sender mirrors (what each local
    rank last streamed, leaf-exact) plus the inbox of replicas received
    FOR peers (generations: a full snapshot and the delta chain on top
    of it), bounded by ``budget_mb``.

    The default transport is process-local delivery — on the
    single-process CPU sim (the tested configuration, like the elastic
    protocol harness) every simulated rank's "buddy RAM" lives in this
    one store; a multi-process gang passes ``transport`` to carry the
    packed blob+tag across hosts (the entry layout is transport-
    agnostic: one contiguous uint8 payload per message)."""

    def __init__(self, world: int, *, rank: int = 0, buddies: int = 1,
                 interval: Optional[int] = None,
                 budget_mb: Optional[int] = None,
                 transport: Optional[Callable[[int, int, dict], None]]
                 = None):
        cfg = runtime.effective_config()
        self.world = int(world)
        self.rank = int(rank)
        self.buddies = max(1, int(buddies))
        self.interval = int(cfg.hotstate_interval if interval is None
                            else interval)
        self.budget_bytes = int(cfg.hotstate_budget_mb if budget_mb
                                is None else budget_mb) * (1 << 20)
        if self.interval < 1 or self.budget_bytes < 1:
            raise ValueError(
                f"hotstate interval/budget must be >= 1, got "
                f"{self.interval}/{self.budget_bytes}")
        self._transport = transport
        self._lock = threading.RLock()
        # sender side: rank -> (mirror leaves, treedef, streams since
        # last snapshot, force-snapshot flag)
        self._mirror: Dict[int, dict] = {}
        # receiver side: rank -> list of generations, each a list of
        # _Entry (generation[0] is the snapshot); oldest first.
        self._inbox: Dict[int, List[List[_Entry]]] = {}
        self.stats = {"streamed": 0, "dropped": 0, "evicted": 0}

    # -- stream (sender side) -------------------------------------------

    def publish(self, state: PyTree, step: int, *, rank: Optional[int]
                = None, epoch: int = 0, incarnation: int = 0) -> None:
        """Ship ``rank``'s state at completed step ``step`` to its
        buddies' RAM.  Epoch-fenced first (a fenced publisher raises
        ``FencedWriterError`` and lands nothing); the packed payload
        then crosses the ``hotstate.send`` fault site.  A dropped
        message forces the next publish for that rank to a full
        snapshot, so one lost delta never poisons the chain."""
        rank = self.rank if rank is None else int(rank)
        _fence_check(epoch)
        with self._lock:
            leaves, treedef = _tree_leaves(state)
            mir = self._mirror.get(rank)
            fresh = (mir is None or mir["force_snap"]
                     or mir["since_snap"] + 1 >= self.interval
                     or len(mir["leaves"]) != len(leaves))
            digest = _digest_state(leaves)
            if fresh:
                blob = _pack_snap(leaves)
                kind = "snap"
            else:
                blob = _pack_delta(leaves, mir["leaves"])
                kind = "delta"
            entry = _Entry(kind, step, epoch, incarnation, digest, blob)
            mod = _faults_mod()
            try:
                if mod is not None:
                    mod.fire("hotstate.send", payload=entry.blob,
                             peer=f"member:{rank}")
            except Exception as e:  # noqa: BLE001 — any injected fault
                # on the send leg = the message never left; best-effort
                # by design (a lost replica costs a rung, not a step).
                self.stats["dropped"] += 1
                self._mirror[rank] = {"leaves": leaves,
                                      "treedef": treedef,
                                      "since_snap": 0,
                                      "force_snap": True}
                _record("dropped", step=step, peer=f"member:{rank}",
                        reason=type(e).__name__)
                return
            self._mirror[rank] = {
                "leaves": [x.copy() for x in leaves],
                "treedef": treedef,
                "since_snap": 0 if kind == "snap"
                else mir["since_snap"] + 1,
                "force_snap": False}
            self.stats["streamed"] += 1
            _record("streamed", step=step, peer=f"member:{rank}",
                    reason=kind)
            for holder in _buddy_holders(rank, self.world,
                                         self.buddies):
                self._deliver(rank, holder, entry)

    def _deliver(self, sender: int, holder: int, entry: _Entry) -> None:
        if self._transport is not None and holder != self.rank:
            self._transport(sender, holder, {
                "kind": entry.kind, "step": entry.step,
                "epoch": entry.epoch,
                "incarnation": entry.incarnation,
                "digest": entry.digest, "blob": entry.blob})
            return
        self.receive(sender, entry.kind, entry.step, entry.blob,
                     digest=entry.digest, epoch=entry.epoch,
                     incarnation=entry.incarnation)

    # -- inbox (buddy side) ---------------------------------------------

    def receive(self, sender: int, kind: str, step: int,
                blob: np.ndarray, *, digest: str, epoch: int = 0,
                incarnation: int = 0) -> None:
        """Land one replica message in this process's RAM (the buddy
        half — also the entry point a cross-host transport calls).  The
        payload crosses the ``hotstate.recv`` fault site: a silent
        corruption here is exactly the bit-flipped RAM buffer the
        digest verify must catch at restore time."""
        blob = np.asarray(blob, np.uint8).copy()
        entry = _Entry(kind, step, epoch, incarnation, digest, blob)
        mod = _faults_mod()
        try:
            if mod is not None:
                mod.fire("hotstate.recv", payload=entry.blob,
                         peer=f"member:{sender}")
        except Exception as e:  # noqa: BLE001 — a dropped/failed recv
            # = the buddy never saw the message; the next snapshot
            # starts a fresh generation.
            self.stats["dropped"] += 1
            _record("dropped", step=step, peer=f"member:{sender}",
                    reason=type(e).__name__)
            return
        with self._lock:
            gens = self._inbox.setdefault(int(sender), [])
            if kind == "snap" or not gens:
                if kind != "snap":
                    return  # a delta with no base is unusable
                gens.append([entry])
            else:
                gens[-1].append(entry)
            _record("received", step=step, peer=f"member:{sender}",
                    reason=kind)
            self._enforce_budget()

    def _enforce_budget(self) -> None:
        total = sum(e.blob.nbytes for gs in self._inbox.values()
                    for g in gs for e in g)
        while total > self.budget_bytes:
            # Oldest evictable generation across all peers — never a
            # peer's only (newest) generation: the budget trims history
            # depth, not restorability.
            victim = None
            for sender, gens in self._inbox.items():
                if len(gens) > 1 and (victim is None
                                      or gens[0][0].step
                                      < victim[1][0][0].step):
                    victim = (sender, gens)
            if victim is None:
                break
            gen = victim[1].pop(0)
            total -= sum(e.blob.nbytes for e in gen)
            self.stats["evicted"] += 1
            _record("evicted", step=gen[0].step,
                    peer=f"member:{victim[0]}")

    # -- restore (the RAM rung) -----------------------------------------

    def latest_step(self, rank: Optional[int] = None) -> int:
        """Newest replicated step for ``rank`` (unverified), 0 if none."""
        rank = self.rank if rank is None else int(rank)
        with self._lock:
            gens = self._inbox.get(rank, [])
            return max((e.step for g in gens for e in g), default=0)

    def restore(self, template: PyTree, *, rank: Optional[int] = None,
                step: Optional[int] = None
                ) -> Optional[Tuple[PyTree, int]]:
        """Reconstruct ``rank``'s newest digest-verified state from the
        RAM replicas: walk candidate target entries newest-first (each
        reconstructed from its generation's snapshot through its delta
        chain), digest-verify against the sender's tag, and return the
        first survivor as ``(state, step)``.  ``step`` pins the target
        to one exact step (the multi-host agreement path: every
        survivor must resume from the SAME agreed step, so a newer RAM
        copy is unusable there).  A failed candidate counts
        ``tm_hotstate_verify_failed_total`` and the walk continues;
        None when nothing survives (the caller falls to the disk
        rung)."""
        import jax

        rank = self.rank if rank is None else int(rank)
        t_leaves, treedef = _tree_leaves(template)
        with self._lock:
            gens = [list(g) for g in self._inbox.get(rank, [])]
        for gen in reversed(gens):
            for cut in range(len(gen), 0, -1):
                target = gen[cut - 1]
                if step is not None and target.step != int(step):
                    continue
                try:
                    leaves = _unpack_snap(gen[0].blob, t_leaves)
                    for e in gen[1:cut]:
                        leaves = _unpack_delta(e.blob, leaves)
                except Exception as e:  # noqa: BLE001 — corrupt blob
                    _record("verify_failed", step=target.step,
                            peer=f"member:{rank}",
                            reason=type(e).__name__)
                    continue
                if _digest_state(leaves) != target.digest:
                    _record("verify_failed", step=target.step,
                            peer=f"member:{rank}", reason="digest")
                    continue
                state = jax.tree_util.tree_unflatten(
                    treedef, [l.astype(t.dtype).reshape(t.shape)
                              for l, t in zip(leaves, t_leaves)])
                return state, target.step
        return None

    # -- membership bookkeeping -----------------------------------------

    def adopt(self, rank: int, state: PyTree, step: int, *,
              epoch: int = 0, incarnation: int = 0) -> None:
        """Pre-seed ``rank``'s slot with a verified full state (the
        migration hand-off: the spare's RAM is primed before it is
        admitted, so it starts streaming deltas immediately)."""
        leaves, _ = _tree_leaves(state)
        entry = _Entry("snap", step, epoch, incarnation,
                       _digest_state(leaves), _pack_snap(leaves))
        with self._lock:
            self._inbox.setdefault(int(rank), []).append([entry])
            self._enforce_budget()

    def drop(self, ranks) -> None:
        """Forget a retired/dead rank's sender mirror AND replicas —
        called once its state has been consumed (migration retire, or
        an elastic shrink whose recovery settled)."""
        if isinstance(ranks, int):
            ranks = [ranks]
        with self._lock:
            for r in ranks:
                self._mirror.pop(int(r), None)
                self._inbox.pop(int(r), None)

    def note_shrink(self, ranks, step: int) -> None:
        """Membership evidence from the elastic driver: the dead ranks
        stop streaming (their mirrors go), but their REPLICAS stay —
        they are exactly what the RAM rung restores from."""
        if isinstance(ranks, int):
            ranks = [ranks]
        with self._lock:
            for r in ranks:
                self._mirror.pop(int(r), None)
        for r in ranks:
            _record("peer_lost", step=step, peer=f"member:{int(r)}")


# ---------------------------------------------------------------------------
# Module-level driver surface (what restart/elastic reach via sys.modules).
# ---------------------------------------------------------------------------

_active_rep: Optional[Replicator] = None


def enable(world: int, *, rank: int = 0, buddies: int = 1,
           interval: Optional[int] = None,
           budget_mb: Optional[int] = None,
           transport: Optional[Callable] = None) -> Replicator:
    """Arm the hot-state tier for this process (consent-gated on
    ``Config.hotstate``).  Returns the active :class:`Replicator`."""
    global _active_rep
    _require_on()
    _active_rep = Replicator(world, rank=rank, buddies=buddies,
                             interval=interval, budget_mb=budget_mb,
                             transport=transport)
    return _active_rep


def disable() -> None:
    global _active_rep
    _active_rep = None


def active() -> bool:
    return _active_rep is not None


def replicator() -> Replicator:
    if _active_rep is None:
        raise RuntimeError("hotstate is not enabled (hotstate.enable)")
    return _active_rep


def offer_restore(template: PyTree, *, rank: Optional[int] = None,
                  min_step: int = 0, step: Optional[int] = None
                  ) -> Optional[Tuple[PyTree, int]]:
    """The RAM rung as ``restart.recover`` consults it (via
    sys.modules): the newest digest-verified replica for ``rank``
    (default: this process's own rank — the state it lost) at or above
    ``min_step`` (pass the newest disk step: a RAM copy older than the
    disk tier is stale, the disk rung wins), or None with
    ``tm_hotstate_fallback_disk_total`` counted — the ladder's
    explicit step down to the PR 13 disk buddies.  A hit counts
    ``tm_hotstate_restored_total``."""
    rep = _active_rep
    if rep is None:
        return None
    who = f"member:{rep.rank if rank is None else rank}"
    got = rep.restore(template, rank=rank, step=step)
    if got is None or got[1] < int(min_step):
        _record("fallback_disk",
                step=0 if got is None else got[1], peer=who,
                reason="missing" if got is None else "stale")
        return None
    _record("restored", step=got[1], peer=who)
    return got


def migrate(source: int, spare: int, template: PyTree, *,
            admit: Optional[Callable[[PyTree, int], Any]] = None,
            retire: Optional[Callable[[int], Any]] = None,
            epoch: int = 0) -> Tuple[PyTree, int]:
    """Drain ``source`` onto ``spare`` at a step boundary with zero
    checkpoint rollback — the reverse of ``elastic.admit``: reconstruct
    the source's newest verified state from the stream, pre-seed the
    spare's RAM with it (:meth:`Replicator.adopt`), hand it to
    ``admit(state, step)`` (e.g. the elastic grow path, or the sim's
    slot swap), then retire the source (``retire(source)`` +
    :meth:`Replicator.drop`).  The whole drain is lease-visible:
    watchdog state ``migrating`` with ``source -> spare`` detail, so
    ``obs_tool blame --live`` renders a mid-migration rank distinct
    from parked/dead.  Returns ``(state, step)`` — the step the spare
    resumes at (the source's last completed step)."""
    _require_on()
    rep = replicator()
    wd = sys.modules.get("torchmpi_tpu.watchdog")
    if wd is not None and wd.active():
        wd.set_state("migrating",
                     detail=f"rank {int(source)} -> rank {int(spare)}")
    try:
        got = rep.restore(template, rank=int(source))
        if got is None:
            _record("fallback_disk", peer=f"member:{int(source)}")
            raise HotStateMiss(
                f"no verified RAM replica for rank {source} — migrate "
                f"needs a live stream (fall back to checkpoint "
                f"admission)")
        state, step = got
        rep.adopt(int(spare), state, step, epoch=epoch)
        if admit is not None:
            admit(state, step)
        if retire is not None:
            retire(int(source))
        rep.drop(int(source))
        _record("migrated", step=step,
                peer=f"member:{int(source)}->member:{int(spare)}")
        return state, step
    finally:
        if wd is not None and wd.active():
            wd.set_state("running")

"""Sharded checkpoint/restore (SURVEY.md §6.3/§6.4).

The reference had NO checkpointing in the library (examples used plain
torch.save) and no elasticity: a rank failure aborted the job, recovery =
restart.  The rebuild keeps the same gang-scheduled failure model and makes
the checkpoint-restart story real: save the pytree per host (each process
writes its own file — the multi-host analog of per-rank torch.save), restore
on any topology since params are replicated.

Orbax is available in the environment for heavier use; this hand-rolled npz
path has zero dependencies and a stable on-disk layout.

``save_async`` overlaps the disk write with training: the device->host
snapshot happens in the caller (it must — the arrays keep training), the
serialized bytes are handed to the native IO executor (csrc/io.cpp), and
the train loop continues while the write + fsync + atomic rename land on a
background thread.  The reference's C7 async engine did exactly this shape
of work (host threads + opaque futures) for its collectives; here XLA owns
device asynchrony, so the native pool serves the checkpoint path.
"""

from __future__ import annotations

import io as _io
import json
import os
import threading
import time
from typing import Any, Optional

import jax
import numpy as np

PyTree = Any


def _paths(tree: PyTree):
    paths_leaves = jax.tree_util.tree_flatten_with_path(tree)[0]
    out = []
    for path, leaf in paths_leaves:
        key = "/".join(str(getattr(p, "key", getattr(p, "idx", p)))
                       for p in path)
        out.append((key, leaf))
    return out


def save(directory: str, tree: PyTree, *, step: int = 0) -> str:
    """Write a checkpoint; returns the file path.  Multi-host: every process
    writes ``ckpt_<step>_p<proc>.npz`` (replicated trees: identical files,
    restore reads the local one).

    Writes are tmp+atomic-rename (matching the async writer), so a crash
    mid-save can never surface a truncated npz as the latest step — the
    invariant the checkpoint-restart driver (utils/restart.py) leans on."""
    os.makedirs(directory, exist_ok=True)
    proc = jax.process_index()
    path = os.path.join(directory, f"ckpt_{step}_p{proc}.npz")
    arrays = {key: np.asarray(leaf) for key, leaf in _paths(tree)}
    tmp = path + ".tmp"
    with open(tmp, "wb") as f:
        np.savez(f, **arrays)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)
    # dtypes recorded because npz erases extension dtypes (bf16 -> '|V2');
    # restore() needs the true stored dtype to reinterpret and to make the
    # template-mismatch check meaningful.
    meta = {"step": step, "keys": sorted(arrays.keys()),
            "dtypes": {k: str(a.dtype) for k, a in arrays.items()}}
    meta_path = os.path.join(directory, f"ckpt_{step}_p{proc}.json")
    with open(meta_path + ".tmp", "w") as f:
        json.dump(meta, f)
    os.replace(meta_path + ".tmp", meta_path)
    return path


class CheckpointHandle:
    """Future for one async checkpoint (data + metadata writes)."""

    def __init__(self, handles, path: str):
        self._handles = handles
        self.path = path

    def done(self) -> bool:
        return all(h.done() for h in self._handles)

    def wait(self, timeout: Optional[float] = None) -> str:
        """Block until the checkpoint is durably on disk; returns the npz
        path.  ``timeout`` bounds the WHOLE call (it is a deadline shared
        across the data and metadata writes, not per-write).  Raises
        ``OSError`` if any write failed."""
        deadline = None if timeout is None else time.monotonic() + timeout
        for h in self._handles:
            h.wait(None if deadline is None
                   else max(0.0, deadline - time.monotonic()))
        return self.path


_WRITER = None
_WRITER_LOCK = threading.Lock()


def _writer():
    global _WRITER
    with _WRITER_LOCK:
        if _WRITER is None:
            import atexit

            from . import aio

            # One thread: FIFO order commits the npz before its metadata.
            _WRITER = aio.AsyncWriter(threads=1)
            # Drain + join at interpreter exit: __del__ is not guaranteed
            # for module globals, and exiting with the native pool's
            # threads joinable would std::terminate in the .so's static
            # destructors.  close() is idempotent.
            atexit.register(_WRITER.close)
        return _WRITER


def save_async(directory: str, tree: PyTree, *, step: int = 0,
               durable: bool = True) -> CheckpointHandle:
    """Like :func:`save` but the disk IO runs on the native executor.

    Synchronous cost: one device->host transfer per leaf plus one in-memory
    npz serialization (memcpy-bound, uncompressed).  The write, fsync, and
    atomic rename overlap training; ``handle.wait()`` (or the next
    ``save_async`` on the same writer, which is FIFO) fences it.  The final
    filename only ever appears complete — a crash mid-write leaves a
    ``.tmp.*`` file, which ``latest_step`` ignores.
    """
    os.makedirs(directory, exist_ok=True)
    proc = jax.process_index()
    path = os.path.join(directory, f"ckpt_{step}_p{proc}.npz")
    arrays = {key: np.asarray(leaf) for key, leaf in _paths(tree)}
    buf = _io.BytesIO()
    np.savez(buf, **arrays)
    meta = json.dumps({"step": step, "keys": sorted(arrays.keys()),
                       "dtypes": {k: str(a.dtype)
                                  for k, a in arrays.items()}})
    w = _writer()
    h_data = w.submit(path, buf.getbuffer(), durable=durable)
    h_meta = w.submit(
        os.path.join(directory, f"ckpt_{step}_p{proc}.json"),
        meta.encode(), durable=durable)
    return CheckpointHandle((h_data, h_meta), path)


def _steps(directory: str, prefix: str, *, require_meta: bool) -> list:
    if not os.path.isdir(directory):
        return []
    suffix = f"_p{jax.process_index()}.npz"
    steps = []
    for name in os.listdir(directory):
        if name.startswith(prefix) and name.endswith(suffix):
            try:
                step = int(name[len(prefix):-len(suffix)])
            except ValueError:
                continue
            # A crash between the npz and json renames must not surface a
            # step that cannot be restored; only count complete pairs when
            # the restore path needs the metadata.
            if require_meta and not os.path.exists(
                    os.path.join(directory, name[:-4] + ".json")):
                continue
            steps.append(step)
    return sorted(steps)


def _latest(directory: str, prefix: str, *, require_meta: bool) -> \
        Optional[int]:
    steps = _steps(directory, prefix, require_meta=require_meta)
    return steps[-1] if steps else None


def latest_step(directory: str) -> Optional[int]:
    return _latest(directory, "ckpt_", require_meta=False)


def available_steps(directory: str) -> list:
    """All restorable steps for this process, ascending."""
    return _steps(directory, "ckpt_", require_meta=False)


def _undo_void(arr: np.ndarray, dtype) -> np.ndarray:
    """npz stores extension dtypes (bfloat16 & friends from ml_dtypes) as
    raw void ('|V2'); reinterpret back.  A cast would raise ('no cast
    function') — the bits are already right, only the view is lost."""
    dtype = np.dtype(dtype)
    if arr.dtype.kind == "V" and arr.dtype.itemsize == dtype.itemsize:
        return arr.view(dtype)
    return arr


def _check_template(key: str, stored_shape, stored_dtype, leaf) -> None:
    """A template whose shape/dtype contradicts the checkpoint must raise,
    not silently return stale-shaped params (resized vocab, dtype
    migration) that only explode later at trace time."""
    t_shape = tuple(np.shape(leaf)) if not hasattr(leaf, "shape") \
        else tuple(leaf.shape)
    t_dtype = np.dtype(getattr(leaf, "dtype", np.asarray(leaf).dtype))
    if tuple(stored_shape) != t_shape or np.dtype(stored_dtype) != t_dtype:
        raise ValueError(
            f"{key!r}: checkpoint has {tuple(stored_shape)} "
            f"{np.dtype(stored_dtype)} but template expects {t_shape} "
            f"{t_dtype} — the model changed since this checkpoint was "
            f"saved")


def _index_meta(index, shape):
    """Normalize a shard index (tuple of slices) to [[start, stop], ...]."""
    out = []
    for sl, dim in zip(index, shape):
        start = 0 if sl.start is None else int(sl.start)
        stop = dim if sl.stop is None else int(sl.stop)
        out.append([start, stop])
    return out


def save_sharded(directory: str, tree: PyTree, *, step: int = 0,
                 durable: bool = True, wait: bool = True):
    """Checkpoint SHARDED arrays: each process writes only its addressable
    shards (deduplicated — replicated copies save once), with per-leaf
    global shape/dtype and shard extents in the metadata.

    The replicated-tree :func:`save` gathers every leaf to one host copy;
    once parameters are genuinely sharded (tensor/expert parallelism, or
    optimizer state sharded over data), that is wrong twice — it
    materializes the global array and it duplicates bytes across hosts.
    Here disk bytes ≈ one copy of the global tree split across processes.
    Files: ``shckpt_<step>_p<proc>.npz`` + ``.json`` via the native async
    writer; ``wait=False`` returns the in-flight :class:`CheckpointHandle`.
    """
    os.makedirs(directory, exist_ok=True)
    proc = jax.process_index()
    arrays = {}
    meta_leaves = {}
    for key, leaf in _paths(tree):
        if isinstance(leaf, jax.Array) and hasattr(leaf,
                                                   "addressable_shards"):
            shape, dtype = leaf.shape, str(leaf.dtype)
            shards_meta = []
            seen = set()
            for sh in leaf.addressable_shards:
                extents = tuple(tuple(e) for e in _index_meta(sh.index,
                                                              shape))
                if extents in seen:
                    continue  # replicated copy of the same shard
                seen.add(extents)
                name = f"{key}//{len(shards_meta)}"
                arrays[name] = np.asarray(sh.data)
                shards_meta.append({"extents": [list(e) for e in extents],
                                    "name": name})
            meta_leaves[key] = {"shape": list(shape), "dtype": dtype,
                                "shards": shards_meta}
        else:
            a = np.asarray(leaf)
            name = f"{key}//0"
            arrays[name] = a
            meta_leaves[key] = {
                "shape": list(a.shape), "dtype": str(a.dtype),
                "shards": [{"extents": _index_meta(
                    tuple(slice(None) for _ in a.shape), a.shape),
                    "name": name}]}
    buf = _io.BytesIO()
    np.savez(buf, **arrays)
    meta = json.dumps({"step": step, "leaves": meta_leaves})
    w = _writer()
    path = os.path.join(directory, f"shckpt_{step}_p{proc}.npz")
    h_data = w.submit(path, buf.getbuffer(), durable=durable)
    h_meta = w.submit(
        os.path.join(directory, f"shckpt_{step}_p{proc}.json"),
        meta.encode(), durable=durable)
    handle = CheckpointHandle((h_data, h_meta), path)
    if wait:
        handle.wait()
    return handle


def latest_sharded_step(directory: str) -> Optional[int]:
    return _latest(directory, "shckpt_", require_meta=True)


def agree_min_step(local: int) -> int:
    """Cross-process MIN of an int — the one collective the checkpoint
    agreement protocols are built from (``restore_sharded`` here and
    ``restart.recover``).  Runs unconditionally on every process; callers
    encode "nothing available" as a sentinel (<= 0) and must make every
    subsequent branch decision from the returned GLOBAL value, never a
    local one, so no process can raise or fall back alone."""
    from jax.experimental import multihost_utils

    return int(multihost_utils.process_allgather(
        np.asarray(int(local))).min())


def _latest_exists(directory: str, step: int) -> bool:
    proc = jax.process_index()
    return (os.path.exists(os.path.join(
        directory, f"shckpt_{step}_p{proc}.npz")) and os.path.exists(
        os.path.join(directory, f"shckpt_{step}_p{proc}.json")))


def restore_sharded(directory: str, template: PyTree,
                    *, step: Optional[int] = None) -> PyTree:
    """Restore into ``template``'s shardings: every leaf of ``template``
    must carry a sharding (a sharded ``jax.Array`` or a
    ``jax.ShapeDtypeStruct`` with ``sharding=``); each addressable device
    gets its shard matched BY EXTENTS from the local process file, so the
    restore never builds a global host copy.  Restoring onto a different
    sharding layout than was saved raises (re-shard via the replicated
    path, or save with the new layout)."""
    if step is None:
        local = latest_sharded_step(directory)
        if jax.process_count() > 1:
            # Cross-process agreement: a crash can land step N on some
            # hosts only; restoring mixed steps would silently stitch a
            # corrupt global array.  Everyone restores the minimum latest.
            # The collective runs UNCONDITIONALLY on every process (with a
            # no-checkpoint sentinel) — raising before it would leave the
            # other hosts hanging in the allgather.
            agreed = agree_min_step(-1 if local is None else local)
            if agreed < 0:
                raise FileNotFoundError(
                    f"no sharded checkpoints in {directory} on at least "
                    f"one process (local latest: {local})")
            if agreed != local and not _latest_exists(directory, agreed):
                raise FileNotFoundError(
                    f"processes disagree on the latest complete sharded "
                    f"step (local {local}, global min {agreed}) and step "
                    f"{agreed} is missing locally")
            step = agreed
        else:
            if local is None:
                raise FileNotFoundError(
                    f"no sharded checkpoints in {directory}")
            step = local
    proc = jax.process_index()
    data = np.load(os.path.join(directory,
                                f"shckpt_{step}_p{proc}.npz"))
    with open(os.path.join(directory,
                           f"shckpt_{step}_p{proc}.json")) as f:
        meta = json.load(f)["leaves"]

    keys = [key for key, _ in _paths(template)]
    missing = [k for k in keys if k not in meta]
    if missing:
        raise KeyError(f"checkpoint missing keys: {missing[:5]}...")
    leaves_out = []
    for key, leaf in _paths(template):
        info = meta[key]
        shape = tuple(info["shape"])
        dtype = np.dtype(info["dtype"])
        _check_template(key, shape, dtype, leaf)
        by_extents = {
            tuple(tuple(e) for e in s["extents"]): s["name"]
            for s in info["shards"]}
        sharding = getattr(leaf, "sharding", None)
        if sharding is None:
            raise ValueError(f"template leaf {key!r} has no sharding")
        idx_map = sharding.addressable_devices_indices_map(shape)
        per_device = []
        loaded = {}  # NpzFile re-extracts per access; read each shard once
        for dev, index in idx_map.items():
            extents = tuple(tuple(e) for e in _index_meta(index, shape))
            name = by_extents.get(extents)
            if name is None:
                raise ValueError(
                    f"{key!r}: no saved shard with extents {extents} — "
                    f"the checkpoint was saved under a different sharding "
                    f"layout (have {sorted(by_extents)[:3]}...)")
            if name not in loaded:
                # np.asarray, not ascontiguousarray: the latter promotes
                # 0-d scalars to 1-d, which make_array_... rejects.
                loaded[name] = np.asarray(_undo_void(data[name], dtype))
            per_device.append(jax.device_put(loaded[name], dev))
        leaves_out.append(jax.make_array_from_single_device_arrays(
            shape, sharding, per_device))
    return jax.tree.unflatten(jax.tree.structure(template), leaves_out)


def restore(directory: str, template: PyTree,
            *, step: Optional[int] = None) -> PyTree:
    """Restore into the structure of ``template`` (values replaced)."""
    if step is None:
        step = latest_step(directory)
        if step is None:
            raise FileNotFoundError(f"no checkpoints in {directory}")
    proc = jax.process_index()
    path = os.path.join(directory, f"ckpt_{step}_p{proc}.npz")
    data = np.load(path)
    # Recorded dtypes (see save): the authority for reinterpreting npz's
    # void-encoded extension dtypes.  Old checkpoints without the record
    # fall back to the template dtype for the view.
    dtypes = {}
    meta_path = path[:-4] + ".json"
    if os.path.exists(meta_path):
        with open(meta_path) as f:
            dtypes = json.load(f).get("dtypes", {})
    pairs = _paths(template)
    missing = [k for k, _ in pairs if k not in data]
    if missing:
        raise KeyError(f"checkpoint missing keys: {missing[:5]}...")
    leaves = []
    for key, leaf in pairs:
        t_dtype = np.dtype(getattr(leaf, "dtype", np.asarray(leaf).dtype))
        stored = _undo_void(data[key], np.dtype(dtypes[key])
                            if key in dtypes else t_dtype)
        _check_template(key, stored.shape, stored.dtype, leaf)
        leaves.append(stored)
    treedef = jax.tree.structure(template)
    return jax.tree.unflatten(treedef, leaves)

"""Sharded checkpoint/restore (SURVEY.md §6.3/§6.4).

The reference had NO checkpointing in the library (examples used plain
torch.save) and no elasticity: a rank failure aborted the job, recovery =
restart.  The rebuild keeps the same gang-scheduled failure model and makes
the checkpoint-restart story real: save the pytree per host (each process
writes its own file — the multi-host analog of per-rank torch.save), restore
on any topology since params are replicated.

Orbax is available in the environment for heavier use; this hand-rolled npz
path has zero dependencies and a stable on-disk layout.
"""

from __future__ import annotations

import json
import os
from typing import Any, Optional

import jax
import numpy as np

PyTree = Any


def _paths(tree: PyTree):
    paths_leaves = jax.tree_util.tree_flatten_with_path(tree)[0]
    out = []
    for path, leaf in paths_leaves:
        key = "/".join(str(getattr(p, "key", getattr(p, "idx", p)))
                       for p in path)
        out.append((key, leaf))
    return out


def save(directory: str, tree: PyTree, *, step: int = 0) -> str:
    """Write a checkpoint; returns the file path.  Multi-host: every process
    writes ``ckpt_<step>_p<proc>.npz`` (replicated trees: identical files,
    restore reads the local one)."""
    os.makedirs(directory, exist_ok=True)
    proc = jax.process_index()
    path = os.path.join(directory, f"ckpt_{step}_p{proc}.npz")
    arrays = {key: np.asarray(leaf) for key, leaf in _paths(tree)}
    np.savez(path, **arrays)
    meta = {"step": step, "keys": sorted(arrays.keys())}
    with open(os.path.join(directory, f"ckpt_{step}_p{proc}.json"),
              "w") as f:
        json.dump(meta, f)
    return path


def latest_step(directory: str) -> Optional[int]:
    if not os.path.isdir(directory):
        return None
    steps = []
    proc = jax.process_index()
    suffix = f"_p{proc}.npz"
    for name in os.listdir(directory):
        if name.startswith("ckpt_") and name.endswith(suffix):
            try:
                steps.append(int(name[len("ckpt_"):-len(suffix)]))
            except ValueError:
                continue
    return max(steps) if steps else None


def restore(directory: str, template: PyTree,
            *, step: Optional[int] = None) -> PyTree:
    """Restore into the structure of ``template`` (values replaced)."""
    if step is None:
        step = latest_step(directory)
        if step is None:
            raise FileNotFoundError(f"no checkpoints in {directory}")
    proc = jax.process_index()
    path = os.path.join(directory, f"ckpt_{step}_p{proc}.npz")
    data = np.load(path)
    keys = [key for key, _ in _paths(template)]
    missing = [k for k in keys if k not in data]
    if missing:
        raise KeyError(f"checkpoint missing keys: {missing[:5]}...")
    leaves = [data[k] for k in keys]
    treedef = jax.tree.structure(template)
    return jax.tree.unflatten(treedef, leaves)

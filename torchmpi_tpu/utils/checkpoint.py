"""Sharded checkpoint/restore (SURVEY.md §6.3/§6.4).

The reference had NO checkpointing in the library (examples used plain
torch.save) and no elasticity: a rank failure aborted the job, recovery =
restart.  The rebuild keeps the same gang-scheduled failure model and makes
the checkpoint-restart story real: save the pytree per host (each process
writes its own file — the multi-host analog of per-rank torch.save), restore
on any topology since params are replicated.

Orbax is available in the environment for heavier use; this hand-rolled npz
path has zero dependencies and a stable on-disk layout.

``save_async`` overlaps the disk write with training: the device->host
snapshot happens in the caller (it must — the arrays keep training), the
serialized bytes are handed to the native IO executor (csrc/io.cpp), and
the train loop continues while the write + fsync + atomic rename land on a
background thread.  The reference's C7 async engine did exactly this shape
of work (host threads + opaque futures) for its collectives; here XLA owns
device asynchrony, so the native pool serves the checkpoint path.
"""

from __future__ import annotations

import io as _io
import json
import os
import sys
import threading
import time
from typing import Any, Optional, Sequence

import jax
import numpy as np

PyTree = Any


class CheckpointCorruptError(RuntimeError):
    """A checkpoint file failed its integrity check — the blake2b
    digest recorded at save time (``Config.ckpt_redundancy`` in
    ``verify``/``buddy``, docs/CHECKPOINT.md) does not match the bytes
    read back, and (in buddy mode) no buddy copy verified either.
    Typed so ``restart.recover``'s walk-back can treat it as
    try-the-next-older-step EVIDENCE (recorded as ``corrupt``) instead
    of a blanket exception, and so callers can tell bit-rot apart from
    a model-shape mismatch."""

    def __init__(self, path: str, *, step: Optional[int] = None,
                 expect: str = "", got: str = "",
                 reason: str = "digest mismatch"):
        self.path = path
        self.step = step
        self.expect = expect
        self.got = got
        self.reason = reason
        detail = (f" (digest {got[:12]} != recorded {expect[:12]})"
                  if expect and got else "")
        super().__init__(
            f"{path}: checkpoint corrupt — {reason}{detail}")


class TemplateMismatchError(ValueError):
    """The restore template's shape/dtype contradicts the checkpoint —
    the model changed since the save.  A ``ValueError`` subclass (the
    historical type), split out so the recovery walk-back can report
    ``template_mismatch`` distinctly from corruption."""


def _faults_mod():
    """The INJECTING fault layer, via sys.modules — this module NEVER
    imports ``torchmpi_tpu.faults`` (the off-mode import discipline;
    the layer is guaranteed imported whenever ``runtime.init`` armed
    it).  Gated on ``injecting()`` (a plan is loaded), not merely
    ``active()``: the ``ckpt.*`` sites are injection-only (no retry
    policy — checkpoint durability is the recovery protocol's job),
    so the common ``faults="policy"`` production mode must not pay the
    per-save/per-read staging copies for a fire() that can never land
    anything.  None otherwise: the sites then cost one dict lookup per
    file operation."""
    mod = sys.modules.get("torchmpi_tpu.faults")
    if mod is not None and mod.injecting():
        return mod
    return None


def _redundancy():
    """The ONE string compare of the durable-checkpoint opt-in
    (docs/CHECKPOINT.md): ``Config.ckpt_redundancy == "off"`` returns
    None and ``utils/durable.py`` is never imported; otherwise the
    armed module handles digests, buddy mirrors, and retention."""
    from .. import runtime

    if runtime.effective_config().ckpt_redundancy == "off":
        return None
    from . import durable

    return durable


def _fence_check(path: str) -> None:
    """Epoch fencing (``faults/fencing.py`` — docs/ELASTIC.md
    "Partitions and split-brain"): ONE sys.modules lookup per save —
    this module never imports the fencing layer; it only exists when
    the elastic driver armed ``elastic_quorum="majority"``.  A writer
    whose view epoch is behind the board's committed epoch (a zombie
    minority that has not yet noticed the partition healed) raises the
    typed ``FencedWriterError`` BEFORE any byte lands, so it can never
    clobber the majority's checkpoint lineage."""
    mod = sys.modules.get("torchmpi_tpu.faults.fencing")
    if mod is not None:
        mod.check_save(path)


def _writable_u8(data):
    """A writable uint8 numpy view over ``data`` for the fault sites
    (``corrupt_silent`` must flip REAL bits in the staged buffer).
    Copies only when the buffer is read-only."""
    mv = memoryview(data)
    if mv.readonly:
        mv = memoryview(bytearray(mv))
    return np.frombuffer(mv, dtype=np.uint8)


def _write_atomic(path: str, data, *, fsync: bool = True) -> None:
    """Commit ``data`` (bytes-like) to ``path`` via tmp + write +
    flush + fsync + atomic rename — the one synchronous write home for
    checkpoint npz AND metadata json files (the json used to skip the
    fsync: a crash after its rename could surface a step whose dtype
    record was torn while ``latest_step(require_meta=False)`` still
    picked it).  With the fault layer armed the write runs under the
    ``ckpt.write`` site (torn/ENOSPC/bit-rot injection)."""
    mod = _faults_mod()
    if mod is not None:
        u8 = _writable_u8(data)
        mod.ckpt_write(path, u8, lambda: _commit_file(path, u8, fsync))
        return
    _commit_file(path, data, fsync)


_TMP_REAP_AGE_S = 600.0


def _reap_stale_tmp(directory: str) -> None:
    """Remove orphaned writer-unique staging files (``*.tmp.<pid>``)
    left by writers that died between staging and rename.  Unlike the
    old shared ``.tmp`` name, pid-unique staging never self-overwrites,
    so restart-heavy runs would otherwise accumulate checkpoint-sized
    orphans forever (review).  Age-gated: a LIVE concurrent writer's
    staging file is seconds old; only stale ones are reaped.  Exact
    ``.tmp`` suffixes (the injected torn-write artifact) are left for
    the tests/post-mortems that read them."""
    try:
        names = os.listdir(directory)
    except OSError:
        return
    now = time.time()
    for n in names:
        stem, _, pid = n.rpartition(".tmp.")
        if not stem or not pid.isdigit():
            continue
        p = os.path.join(directory, n)
        try:
            if now - os.path.getmtime(p) > _TMP_REAP_AGE_S:
                os.remove(p)
        except OSError:
            pass


def _commit_file(path: str, data, fsync: bool) -> None:
    # Writer-unique staging name: two processes saving the SAME path
    # (the split-brain two-lineages scenario — docs/ELASTIC.md — or two
    # drivers pointed at one directory) must each stage privately and
    # race only at the atomic rename, exactly like real shared storage;
    # a shared ".tmp" made one writer rename the other's staging away.
    tmp = f"{path}.tmp.{os.getpid()}"
    with open(tmp, "wb") as f:
        f.write(data)
        if fsync:
            f.flush()
            os.fsync(f.fileno())
    os.replace(tmp, path)
    _reap_stale_tmp(os.path.dirname(path))


def _read_npz_bytes(path: str) -> bytes:
    """Read a checkpoint npz back as bytes, through the ``ckpt.read``
    fault site when armed (injected bit-rot lands in the returned
    buffer — exactly what on-disk rot looks like to the parser and the
    digest check above it)."""
    with open(path, "rb") as f:
        raw = f.read()
    mod = _faults_mod()
    if mod is None:
        return raw
    buf = bytearray(raw)
    mod.ckpt_read(path, np.frombuffer(buf, dtype=np.uint8))
    return bytes(buf)


def _paths(tree: PyTree):
    paths_leaves = jax.tree_util.tree_flatten_with_path(tree)[0]
    out = []
    for path, leaf in paths_leaves:
        key = "/".join(str(getattr(p, "key", getattr(p, "idx", p)))
                       for p in path)
        out.append((key, leaf))
    return out


def save(directory: str, tree: PyTree, *, step: int = 0) -> str:
    """Write a checkpoint; returns the file path.  Multi-host: every process
    writes ``ckpt_<step>_p<proc>.npz`` (replicated trees: identical files,
    restore reads the local one).

    Writes are tmp+atomic-rename with BOTH files fsynced before their
    renames (the metadata json included — a torn dtype record would
    poison the step ``latest_step(require_meta=False)`` still picks),
    so a crash mid-save can never surface a truncated artifact as the
    latest step — the invariant the checkpoint-restart driver
    (utils/restart.py) leans on.  With ``Config.ckpt_redundancy`` on
    (ONE string compare here, docs/CHECKPOINT.md) the serialized bytes
    are digest-stamped in the metadata, mirrored to buddy locations,
    and old steps pruned per ``ckpt_keep``."""
    os.makedirs(directory, exist_ok=True)
    proc = jax.process_index()
    path = os.path.join(directory, f"ckpt_{step}_p{proc}.npz")
    _fence_check(path)
    arrays = {key: np.asarray(leaf) for key, leaf in _paths(tree)}
    # dtypes recorded because npz erases extension dtypes (bf16 -> '|V2');
    # restore() needs the true stored dtype to reinterpret and to make the
    # template-mismatch check meaningful.
    meta = {"step": step, "keys": sorted(arrays.keys()),
            "dtypes": {k: str(a.dtype) for k, a in arrays.items()}}
    dur = _redundancy()
    if dur is None and _faults_mod() is None:
        # Default path: STREAM the npz straight to the tmp file — no
        # second in-memory copy of the checkpoint (buffering is only
        # needed when a digest is recorded or a fault site wants the
        # staged payload).  Writer-unique name: see _commit_file.
        tmp = f"{path}.tmp.{os.getpid()}"
        with open(tmp, "wb") as f:
            np.savez(f, **arrays)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, path)
        _reap_stale_tmp(directory)
    else:
        buf = _io.BytesIO()
        np.savez(buf, **arrays)
        if dur is not None:
            dur.save_pair(directory, f"ckpt_{step}_p{proc}",
                          buf.getbuffer(), meta, step=step, proc=proc)
            return path
        _write_atomic(path, buf.getbuffer())
    _write_atomic(path[:-4] + ".json",
                  json.dumps(meta).encode())
    return path


class CheckpointHandle:
    """Future for one async checkpoint (data + metadata writes).

    ``on_durable`` (durable-checkpoint retention) runs once, after
    every write has landed: pruning older steps any earlier would race
    their still-queued writes on the FIFO writer — the removed file
    would be resurrected by its own pending rename.  A handle that is
    never waited skips its prune; the next save's prune recomputes the
    full doomed list, so retention self-heals one save later."""

    def __init__(self, handles, path: str, on_durable=None):
        self._handles = handles
        self.path = path
        self._on_durable = on_durable

    def done(self) -> bool:
        return all(h.done() for h in self._handles)

    def wait(self, timeout: Optional[float] = None) -> str:
        """Block until the checkpoint is durably on disk; returns the npz
        path.  ``timeout`` bounds the WHOLE call (it is a deadline shared
        across the data and metadata writes, not per-write).  Raises
        ``OSError`` if any write failed."""
        deadline = None if timeout is None else time.monotonic() + timeout
        for h in self._handles:
            h.wait(None if deadline is None
                   else max(0.0, deadline - time.monotonic()))
        if self._on_durable is not None:
            cb, self._on_durable = self._on_durable, None
            cb()
        return self.path


_WRITER = None
_WRITER_LOCK = threading.Lock()


def _writer():
    global _WRITER
    with _WRITER_LOCK:
        if _WRITER is None:
            import atexit

            from . import aio

            # One thread: FIFO order commits the npz before its metadata.
            _WRITER = aio.AsyncWriter(threads=1)
            # Drain + join at interpreter exit: __del__ is not guaranteed
            # for module globals, and exiting with the native pool's
            # threads joinable would std::terminate in the .so's static
            # destructors.  close() is idempotent.
            atexit.register(_WRITER.close)
        return _WRITER


def save_async(directory: str, tree: PyTree, *, step: int = 0,
               durable: bool = True) -> CheckpointHandle:
    """Like :func:`save` but the disk IO runs on the native executor.

    Synchronous cost: one device->host transfer per leaf plus one in-memory
    npz serialization (memcpy-bound, uncompressed).  The write, fsync, and
    atomic rename overlap training; ``handle.wait()`` (or the next
    ``save_async`` on the same writer, which is FIFO) fences it.  The final
    filename only ever appears complete — a crash mid-write leaves a
    ``.tmp.*`` file, which ``latest_step`` ignores.
    """
    os.makedirs(directory, exist_ok=True)
    proc = jax.process_index()
    path = os.path.join(directory, f"ckpt_{step}_p{proc}.npz")
    _fence_check(path)
    arrays = {key: np.asarray(leaf) for key, leaf in _paths(tree)}
    buf = _io.BytesIO()
    np.savez(buf, **arrays)
    meta = {"step": step, "keys": sorted(arrays.keys()),
            "dtypes": {k: str(a.dtype) for k, a in arrays.items()}}
    dur = _redundancy()
    if dur is not None:
        return dur.submit_pair(
            _writer(), directory, f"ckpt_{step}_p{proc}",
            buf.getbuffer(), meta, step=step, proc=proc,
            durable=durable)
    w = _writer()
    h_data = _submit(w, path, buf.getbuffer(), durable)
    h_meta = _submit(
        w, os.path.join(directory, f"ckpt_{step}_p{proc}.json"),
        json.dumps(meta).encode(), durable)
    return CheckpointHandle((h_data, h_meta), path)


def _submit(w, path: str, data, durable: bool):
    """One async-writer submission, through the ``ckpt.write`` fault
    site when armed (the async twin of :func:`_write_atomic` — the
    native writer already does tmp+rename+fsync itself)."""
    mod = _faults_mod()
    if mod is None:
        return w.submit(path, data, durable=durable)
    u8 = _writable_u8(data)
    return mod.ckpt_write(
        path, u8, lambda: w.submit(path, u8, durable=durable))


def _scan_steps(directory: str, prefix: str, suffix: str,
                require_meta: bool) -> set:
    found = set()
    if not os.path.isdir(directory):
        return found
    for name in os.listdir(directory):
        if name.startswith(prefix) and name.endswith(suffix):
            try:
                step = int(name[len(prefix):-len(suffix)])
            except ValueError:
                continue
            # A crash between the npz and json renames must not surface a
            # step that cannot be restored; only count complete pairs when
            # the restore path needs the metadata.
            if require_meta and not os.path.exists(
                    os.path.join(directory, name[:-4] + ".json")):
                continue
            found.add(step)
    return found


def _steps(directory: str, prefix: str, *, require_meta: bool) -> list:
    suffix = f"_p{jax.process_index()}.npz"
    steps = _scan_steps(directory, prefix, suffix, require_meta)
    # Buddy mode: a step whose primary died with its storage is STILL
    # restorable (restore repairs it from the buddy copy), so the
    # listing recovery walks must see it — otherwise a total primary
    # loss silently degrades to fresh-start with healthy buddies on
    # disk (docs/CHECKPOINT.md).
    dur = _redundancy()
    if dur is not None:
        for d in dur.scan_dirs(directory, jax.process_index()):
            steps |= _scan_steps(d, prefix, suffix, require_meta)
    return sorted(steps)


def _latest(directory: str, prefix: str, *, require_meta: bool) -> \
        Optional[int]:
    steps = _steps(directory, prefix, require_meta=require_meta)
    return steps[-1] if steps else None


def latest_step(directory: str) -> Optional[int]:
    return _latest(directory, "ckpt_", require_meta=False)


def available_steps(directory: str) -> list:
    """All restorable steps for this process, ascending."""
    return _steps(directory, "ckpt_", require_meta=False)


def _undo_void(arr: np.ndarray, dtype) -> np.ndarray:
    """npz stores extension dtypes (bfloat16 & friends from ml_dtypes) as
    raw void ('|V2'); reinterpret back.  A cast would raise ('no cast
    function') — the bits are already right, only the view is lost."""
    dtype = np.dtype(dtype)
    if arr.dtype.kind == "V" and arr.dtype.itemsize == dtype.itemsize:
        return arr.view(dtype)
    return arr


def _check_template(key: str, stored_shape, stored_dtype, leaf) -> None:
    """A template whose shape/dtype contradicts the checkpoint must raise,
    not silently return stale-shaped params (resized vocab, dtype
    migration) that only explode later at trace time."""
    t_shape = tuple(np.shape(leaf)) if not hasattr(leaf, "shape") \
        else tuple(leaf.shape)
    t_dtype = np.dtype(getattr(leaf, "dtype", np.asarray(leaf).dtype))
    if tuple(stored_shape) != t_shape or np.dtype(stored_dtype) != t_dtype:
        raise TemplateMismatchError(
            f"{key!r}: checkpoint has {tuple(stored_shape)} "
            f"{np.dtype(stored_dtype)} but template expects {t_shape} "
            f"{t_dtype} — the model changed since this checkpoint was "
            f"saved")


def _index_meta(index, shape):
    """Normalize a shard index (tuple of slices) to [[start, stop], ...]."""
    out = []
    for sl, dim in zip(index, shape):
        start = 0 if sl.start is None else int(sl.start)
        stop = dim if sl.stop is None else int(sl.stop)
        out.append([start, stop])
    return out


def save_sharded(directory: str, tree: PyTree, *, step: int = 0,
                 durable: bool = True, wait: bool = True):
    """Checkpoint SHARDED arrays: each process writes only its addressable
    shards (deduplicated — replicated copies save once), with per-leaf
    global shape/dtype and shard extents in the metadata.

    The replicated-tree :func:`save` gathers every leaf to one host copy;
    once parameters are genuinely sharded (tensor/expert parallelism, or
    optimizer state sharded over data), that is wrong twice — it
    materializes the global array and it duplicates bytes across hosts.
    Here disk bytes ≈ one copy of the global tree split across processes.
    Files: ``shckpt_<step>_p<proc>.npz`` + ``.json`` via the native async
    writer; ``wait=False`` returns the in-flight :class:`CheckpointHandle`.
    """
    os.makedirs(directory, exist_ok=True)
    proc = jax.process_index()
    _fence_check(os.path.join(directory, f"shckpt_{step}_p{proc}.npz"))
    arrays = {}
    meta_leaves = {}
    for key, leaf in _paths(tree):
        if isinstance(leaf, jax.Array) and hasattr(leaf,
                                                   "addressable_shards"):
            shape, dtype = leaf.shape, str(leaf.dtype)
            shards_meta = []
            seen = set()
            for sh in leaf.addressable_shards:
                extents = tuple(tuple(e) for e in _index_meta(sh.index,
                                                              shape))
                if extents in seen:
                    continue  # replicated copy of the same shard
                seen.add(extents)
                name = f"{key}//{len(shards_meta)}"
                arrays[name] = np.asarray(sh.data)
                shards_meta.append({"extents": [list(e) for e in extents],
                                    "name": name})
            meta_leaves[key] = {"shape": list(shape), "dtype": dtype,
                                "shards": shards_meta}
        else:
            a = np.asarray(leaf)
            name = f"{key}//0"
            arrays[name] = a
            meta_leaves[key] = {
                "shape": list(a.shape), "dtype": str(a.dtype),
                "shards": [{"extents": _index_meta(
                    tuple(slice(None) for _ in a.shape), a.shape),
                    "name": name}]}
    buf = _io.BytesIO()
    np.savez(buf, **arrays)
    meta = {"step": step, "leaves": meta_leaves}
    path = os.path.join(directory, f"shckpt_{step}_p{proc}.npz")
    dur = _redundancy()
    if dur is not None:
        handle = dur.submit_pair(
            _writer(), directory, f"shckpt_{step}_p{proc}",
            buf.getbuffer(), meta, step=step, proc=proc,
            durable=durable)
    else:
        w = _writer()
        h_data = _submit(w, path, buf.getbuffer(), durable)
        h_meta = _submit(
            w, os.path.join(directory, f"shckpt_{step}_p{proc}.json"),
            json.dumps(meta).encode(), durable)
        handle = CheckpointHandle((h_data, h_meta), path)
    if wait:
        handle.wait()
    return handle


def latest_sharded_step(directory: str) -> Optional[int]:
    return _latest(directory, "shckpt_", require_meta=True)


def agree_min_step(local: int) -> int:
    """Cross-process MIN of an int — the one collective the checkpoint
    agreement protocols are built from (``restore_sharded`` here and
    ``restart.recover``).  Runs unconditionally on every process; callers
    encode "nothing available" as a sentinel (<= 0) and must make every
    subsequent branch decision from the returned GLOBAL value, never a
    local one, so no process can raise or fall back alone."""
    from jax.experimental import multihost_utils

    return int(multihost_utils.process_allgather(
        np.asarray(int(local))).min())


def _latest_exists(directory: str, step: int) -> bool:
    proc = jax.process_index()
    return (os.path.exists(os.path.join(
        directory, f"shckpt_{step}_p{proc}.npz")) and os.path.exists(
        os.path.join(directory, f"shckpt_{step}_p{proc}.json")))


def restore_sharded(directory: str, template: PyTree,
                    *, step: Optional[int] = None) -> PyTree:
    """Restore into ``template``'s shardings: every leaf of ``template``
    must carry a sharding (a sharded ``jax.Array`` or a
    ``jax.ShapeDtypeStruct`` with ``sharding=``); each addressable device
    gets its shard matched BY EXTENTS from the local process file, so the
    restore never builds a global host copy.  Restoring onto a different
    sharding layout than was saved raises (re-shard via the replicated
    path, or save with the new layout)."""
    if step is None:
        local = latest_sharded_step(directory)
        if jax.process_count() > 1:
            # Cross-process agreement: a crash can land step N on some
            # hosts only; restoring mixed steps would silently stitch a
            # corrupt global array.  Everyone restores the minimum latest.
            # The collective runs UNCONDITIONALLY on every process (with a
            # no-checkpoint sentinel) — raising before it would leave the
            # other hosts hanging in the allgather.  A corrupt agreed
            # step raises the typed CheckpointCorruptError for the
            # caller's gang-level walk-back (restart.recover's ceiling
            # loop); walking back unilaterally here would desync the
            # gang.
            agreed = agree_min_step(-1 if local is None else local)
            if agreed < 0:
                raise FileNotFoundError(
                    f"no sharded checkpoints in {directory} on at least "
                    f"one process (local latest: {local})")
            if agreed != local and not _latest_exists(directory, agreed):
                raise FileNotFoundError(
                    f"processes disagree on the latest complete sharded "
                    f"step (local {local}, global min {agreed}) and step "
                    f"{agreed} is missing locally")
            step = agreed
        else:
            if local is None:
                raise FileNotFoundError(
                    f"no sharded checkpoints in {directory}")
            # Single participant: a corrupt (or vanished) newest step is
            # walk-back-one-step EVIDENCE, not a hard stop — the same
            # contract as restart.recover over the replicated files,
            # with each rejection recorded through the obs shim.
            steps = _steps(directory, "shckpt_", require_meta=True)
            last_err: Optional[BaseException] = None
            for cand in reversed(steps):
                try:
                    return _restore_sharded_at(directory, template, cand)
                except Exception as e:  # noqa: BLE001 — classified +
                    # recorded, then fall back to the next older step
                    _record_walkback(cand, e)
                    last_err = e
                    continue
            raise last_err if last_err is not None else FileNotFoundError(
                f"no sharded checkpoints in {directory}")
    return _restore_sharded_at(directory, template, step)


def _restore_sharded_at(directory: str, template: PyTree,
                        step: int) -> PyTree:
    proc = jax.process_index()
    path = os.path.join(directory, f"shckpt_{step}_p{proc}.npz")
    dur = _redundancy()
    if dur is not None:
        raw, _meta_full = dur.read_pair(
            directory, f"shckpt_{step}_p{proc}", step=step, proc=proc)
        meta = (_meta_full or {}).get("leaves")
        if meta is None:
            # A sharded pair is unrestorable without its shard-extent
            # metadata — a torn json here is corruption, typed so the
            # walk-back classifies it instead of crashing on None.
            raise CheckpointCorruptError(
                os.path.join(directory, f"shckpt_{step}_p{proc}.json"),
                step=step, reason="shard metadata missing/unparseable")
        data = np.load(_io.BytesIO(raw))
    else:
        mod = _faults_mod()
        data = np.load(_io.BytesIO(_read_npz_bytes(path))) \
            if mod is not None else np.load(path)
        with open(os.path.join(
                directory, f"shckpt_{step}_p{proc}.json")) as f:
            meta = json.load(f)["leaves"]

    keys = [key for key, _ in _paths(template)]
    missing = [k for k in keys if k not in meta]
    if missing:
        raise KeyError(f"checkpoint missing keys: {missing[:5]}...")
    leaves_out = []
    for key, leaf in _paths(template):
        info = meta[key]
        shape = tuple(info["shape"])
        dtype = np.dtype(info["dtype"])
        _check_template(key, shape, dtype, leaf)
        by_extents = {
            tuple(tuple(e) for e in s["extents"]): s["name"]
            for s in info["shards"]}
        sharding = getattr(leaf, "sharding", None)
        if sharding is None:
            raise ValueError(f"template leaf {key!r} has no sharding")
        idx_map = sharding.addressable_devices_indices_map(shape)
        per_device = []
        loaded = {}  # NpzFile re-extracts per access; read each shard once
        for dev, index in idx_map.items():
            extents = tuple(tuple(e) for e in _index_meta(index, shape))
            name = by_extents.get(extents)
            if name is None:
                raise ValueError(
                    f"{key!r}: no saved shard with extents {extents} — "
                    f"the checkpoint was saved under a different sharding "
                    f"layout (have {sorted(by_extents)[:3]}...)")
            if name not in loaded:
                # np.asarray, not ascontiguousarray: the latter promotes
                # 0-d scalars to 1-d, which make_array_... rejects.
                loaded[name] = np.asarray(_undo_void(data[name], dtype))
            per_device.append(jax.device_put(loaded[name], dev))
        leaves_out.append(jax.make_array_from_single_device_arrays(
            shape, sharding, per_device))
    return jax.tree.unflatten(jax.tree.structure(template), leaves_out)


def restore(directory: str, template: PyTree,
            *, step: Optional[int] = None) -> PyTree:
    """Restore into the structure of ``template`` (values replaced).

    With ``Config.ckpt_redundancy`` on (one string compare) the file's
    recorded digest is verified before the bytes are parsed; a
    mismatch repairs bit-identically from a buddy copy when one
    verifies (``"buddy"`` mode) and otherwise raises the typed
    :class:`CheckpointCorruptError` the recovery walk-back feeds on —
    never a silent garbage restore."""
    if step is None:
        step = latest_step(directory)
        if step is None:
            raise FileNotFoundError(f"no checkpoints in {directory}")
    proc = jax.process_index()
    path = os.path.join(directory, f"ckpt_{step}_p{proc}.npz")
    dur = _redundancy()
    if dur is not None:
        raw, meta_full = dur.read_pair(
            directory, f"ckpt_{step}_p{proc}", step=step, proc=proc)
        data = np.load(_io.BytesIO(raw))
        dtypes = (meta_full or {}).get("dtypes", {})
    else:
        mod = _faults_mod()
        data = np.load(_io.BytesIO(_read_npz_bytes(path))) \
            if mod is not None else np.load(path)
        # Recorded dtypes (see save): the authority for reinterpreting
        # npz's void-encoded extension dtypes.  Old checkpoints without
        # the record fall back to the template dtype for the view.
        dtypes = {}
        meta_path = path[:-4] + ".json"
        if os.path.exists(meta_path):
            with open(meta_path) as f:
                dtypes = json.load(f).get("dtypes", {})
    pairs = _paths(template)
    missing = [k for k, _ in pairs if k not in data]
    if missing:
        raise KeyError(f"checkpoint missing keys: {missing[:5]}...")
    leaves = []
    for key, leaf in pairs:
        t_dtype = np.dtype(getattr(leaf, "dtype", np.asarray(leaf).dtype))
        stored = _undo_void(data[key], np.dtype(dtypes[key])
                            if key in dtypes else t_dtype)
        _check_template(key, stored.shape, stored.dtype, leaf)
        leaves.append(stored)
    treedef = jax.tree.structure(template)
    return jax.tree.unflatten(treedef, leaves)


# ---------------------------------------------------------------------------
# Recovery evidence + retention protection (docs/CHECKPOINT.md)
# ---------------------------------------------------------------------------


def walkback_reason(e: BaseException) -> str:
    """Classify WHY a restore attempt rejected a step — the recovery
    walk-back's evidence label (``restart.recover`` satellite: a
    skipped step must say corrupt vs missing vs template mismatch, not
    vanish into a silent ``except``).  ``corrupt`` covers the typed
    digest failure AND an unparseable npz (torn zip, CRC mismatch);
    ``missing`` a file the directory no longer has."""
    import zipfile

    if isinstance(e, CheckpointCorruptError):
        return "corrupt"
    if isinstance(e, TemplateMismatchError):
        return "template_mismatch"
    if isinstance(e, (FileNotFoundError, KeyError)):
        return "missing"
    if isinstance(e, (ValueError, OSError, zipfile.BadZipFile)):
        # np.load on rotten bytes raises ValueError or BadZipFile (a
        # direct Exception subclass — the CRC-mismatch signature, and
        # with no digest recorded the only rot detector there is); an
        # injected ENOSPC/EIO is an OSError — all storage-side.
        return "corrupt"
    return type(e).__name__


def _record_walkback(step: int, e: BaseException) -> None:
    """One rejected step in a recovery walk-back, through the obs shim
    (``tm_ckpt_walkback_total{reason=...}`` + a ``ckpt`` flight event;
    no-op when obs is off)."""
    from . import telemetry

    telemetry.emit("record_ckpt", "walkback", step=int(step),
                   reason=walkback_reason(e))


_PROTECT_LOCK = threading.Lock()
_PROTECTED: dict = {}  # directory -> last step recovery settled on


def protect_step(directory: str, step: int) -> None:
    """Pin ``step`` against retention pruning in ``directory`` — called
    by ``restart.recover`` for the step a recovery (or a guard rewind)
    settled on, so a keep-last-K chaos soak can never prune the very
    checkpoint the gang agreed to stand on."""
    with _PROTECT_LOCK:
        _PROTECTED[os.path.abspath(directory)] = int(step)


def protected_step(directory: str) -> Optional[int]:
    with _PROTECT_LOCK:
        return _PROTECTED.get(os.path.abspath(directory))


def replicate_for(directory: str, step: int, dst_procs: Sequence[int],
                  *, src_proc: Optional[int] = None) -> None:
    """Seed per-process checkpoint files for ``dst_procs`` at ``step``
    from ``src_proc``'s file (default: this process) — the elastic
    rejoin boundary's seeding primitive (docs/ELASTIC.md): recovery
    reads only a process's own files, so a joiner needs a file under
    its own rank.  The state is replicated by the elastic ``build``
    contract, so the survivor's bytes ARE the joiner's bytes.

    Off mode copies the npz via tmp + atomic rename (the historical
    behavior).  With ``Config.ckpt_redundancy`` on, the source bytes
    are digest-VERIFIED first (repairing from a buddy copy if the
    survivor's own primary rotted — the dead-rank's-storage-died
    scenario) and each seeded rank gets the full pair (npz + stamped
    metadata) plus its own buddy mirrors."""
    src = src_proc if src_proc is not None else jax.process_index()
    dur = _redundancy()
    if dur is not None:
        raw, meta = dur.read_pair(directory, f"ckpt_{step}_p{src}",
                                  step=step, proc=src)
        for r in dst_procs:
            dur.save_pair(directory, f"ckpt_{step}_p{int(r)}",
                          raw, meta, step=step, proc=int(r),
                          prune_old=False)
        return
    src_path = os.path.join(directory, f"ckpt_{step}_p{src}.npz")
    mod = _faults_mod()
    if mod is None:
        # Off + no injection: STREAM the copy (tmp + atomic rename) —
        # no checkpoint-sized read into host RAM at the one moment the
        # gang is mid-recovery.
        import shutil

        for r in dst_procs:
            dst = os.path.join(directory, f"ckpt_{step}_p{int(r)}.npz")
            tmp = f"{dst}.tmp.{os.getpid()}"
            shutil.copyfile(src_path, tmp)
            os.replace(tmp, dst)
        _reap_stale_tmp(directory)
        return
    raw = _read_npz_bytes(src_path)
    for r in dst_procs:
        _write_atomic(
            os.path.join(directory, f"ckpt_{step}_p{int(r)}.npz"), raw)

"""Sharded checkpoint/restore (SURVEY.md §6.3/§6.4).

The reference had NO checkpointing in the library (examples used plain
torch.save) and no elasticity: a rank failure aborted the job, recovery =
restart.  The rebuild keeps the same gang-scheduled failure model and makes
the checkpoint-restart story real: save the pytree per host (each process
writes its own file — the multi-host analog of per-rank torch.save), restore
on any topology since params are replicated.

Orbax is available in the environment for heavier use; this hand-rolled npz
path has zero dependencies and a stable on-disk layout.

``save_async`` overlaps the disk write with training: the device->host
snapshot happens in the caller (it must — the arrays keep training), the
serialized bytes are handed to the native IO executor (csrc/io.cpp), and
the train loop continues while the write + fsync + atomic rename land on a
background thread.  The reference's C7 async engine did exactly this shape
of work (host threads + opaque futures) for its collectives; here XLA owns
device asynchrony, so the native pool serves the checkpoint path.
"""

from __future__ import annotations

import io as _io
import json
import os
import threading
import time
from typing import Any, Optional

import jax
import numpy as np

PyTree = Any


def _paths(tree: PyTree):
    paths_leaves = jax.tree_util.tree_flatten_with_path(tree)[0]
    out = []
    for path, leaf in paths_leaves:
        key = "/".join(str(getattr(p, "key", getattr(p, "idx", p)))
                       for p in path)
        out.append((key, leaf))
    return out


def save(directory: str, tree: PyTree, *, step: int = 0) -> str:
    """Write a checkpoint; returns the file path.  Multi-host: every process
    writes ``ckpt_<step>_p<proc>.npz`` (replicated trees: identical files,
    restore reads the local one)."""
    os.makedirs(directory, exist_ok=True)
    proc = jax.process_index()
    path = os.path.join(directory, f"ckpt_{step}_p{proc}.npz")
    arrays = {key: np.asarray(leaf) for key, leaf in _paths(tree)}
    np.savez(path, **arrays)
    meta = {"step": step, "keys": sorted(arrays.keys())}
    with open(os.path.join(directory, f"ckpt_{step}_p{proc}.json"),
              "w") as f:
        json.dump(meta, f)
    return path


class CheckpointHandle:
    """Future for one async checkpoint (data + metadata writes)."""

    def __init__(self, handles, path: str):
        self._handles = handles
        self.path = path

    def done(self) -> bool:
        return all(h.done() for h in self._handles)

    def wait(self, timeout: Optional[float] = None) -> str:
        """Block until the checkpoint is durably on disk; returns the npz
        path.  ``timeout`` bounds the WHOLE call (it is a deadline shared
        across the data and metadata writes, not per-write).  Raises
        ``OSError`` if any write failed."""
        deadline = None if timeout is None else time.monotonic() + timeout
        for h in self._handles:
            h.wait(None if deadline is None
                   else max(0.0, deadline - time.monotonic()))
        return self.path


_WRITER = None
_WRITER_LOCK = threading.Lock()


def _writer():
    global _WRITER
    with _WRITER_LOCK:
        if _WRITER is None:
            import atexit

            from . import aio

            # One thread: FIFO order commits the npz before its metadata.
            _WRITER = aio.AsyncWriter(threads=1)
            # Drain + join at interpreter exit: __del__ is not guaranteed
            # for module globals, and exiting with the native pool's
            # threads joinable would std::terminate in the .so's static
            # destructors.  close() is idempotent.
            atexit.register(_WRITER.close)
        return _WRITER


def save_async(directory: str, tree: PyTree, *, step: int = 0,
               durable: bool = True) -> CheckpointHandle:
    """Like :func:`save` but the disk IO runs on the native executor.

    Synchronous cost: one device->host transfer per leaf plus one in-memory
    npz serialization (memcpy-bound, uncompressed).  The write, fsync, and
    atomic rename overlap training; ``handle.wait()`` (or the next
    ``save_async`` on the same writer, which is FIFO) fences it.  The final
    filename only ever appears complete — a crash mid-write leaves a
    ``.tmp.*`` file, which ``latest_step`` ignores.
    """
    os.makedirs(directory, exist_ok=True)
    proc = jax.process_index()
    path = os.path.join(directory, f"ckpt_{step}_p{proc}.npz")
    arrays = {key: np.asarray(leaf) for key, leaf in _paths(tree)}
    buf = _io.BytesIO()
    np.savez(buf, **arrays)
    meta = json.dumps({"step": step, "keys": sorted(arrays.keys())})
    w = _writer()
    h_data = w.submit(path, buf.getbuffer(), durable=durable)
    h_meta = w.submit(
        os.path.join(directory, f"ckpt_{step}_p{proc}.json"),
        meta.encode(), durable=durable)
    return CheckpointHandle((h_data, h_meta), path)


def latest_step(directory: str) -> Optional[int]:
    if not os.path.isdir(directory):
        return None
    steps = []
    proc = jax.process_index()
    suffix = f"_p{proc}.npz"
    for name in os.listdir(directory):
        if name.startswith("ckpt_") and name.endswith(suffix):
            try:
                steps.append(int(name[len("ckpt_"):-len(suffix)]))
            except ValueError:
                continue
    return max(steps) if steps else None


def restore(directory: str, template: PyTree,
            *, step: Optional[int] = None) -> PyTree:
    """Restore into the structure of ``template`` (values replaced)."""
    if step is None:
        step = latest_step(directory)
        if step is None:
            raise FileNotFoundError(f"no checkpoints in {directory}")
    proc = jax.process_index()
    path = os.path.join(directory, f"ckpt_{step}_p{proc}.npz")
    data = np.load(path)
    keys = [key for key, _ in _paths(template)]
    missing = [k for k in keys if k not in data]
    if missing:
        raise KeyError(f"checkpoint missing keys: {missing[:5]}...")
    leaves = [data[k] for k in keys]
    treedef = jax.tree.structure(template)
    return jax.tree.unflatten(treedef, leaves)

"""Torch-dataset bridge: feed torch ``Dataset``/``DataLoader`` pipelines
into the mesh prefetcher.

The reference's examples consumed Torch datasets on the host and fed
tensors to the training loop (SURVEY.md §3 C15 — the Lua examples drove
``nn`` modules from Torch-side batches); a user migrating from it almost
certainly owns working torch data code.  This module keeps that code: any
``torch.utils.data.DataLoader`` (or iterable of tensors / dicts / tuples
of tensors) becomes an iterator of numpy pytrees, optionally staged
device-resident with the training sharding via
:func:`~torchmpi_tpu.utils.input_pipeline.prefetch_to_mesh`.

torch is an optional dependency of exactly this module — the rest of the
package never imports it.
"""

from __future__ import annotations

from typing import Any, Iterable, Iterator, Optional

PyTree = Any


def _to_numpy(batch):
    """Recursively convert torch tensors to numpy (zero-copy for CPU
    tensors); passes numpy arrays and scalars through."""
    import torch

    if isinstance(batch, torch.Tensor):
        t = batch.detach()
        if t.device.type != "cpu":
            t = t.cpu()
        return t.numpy()
    if isinstance(batch, dict):
        return {k: _to_numpy(v) for k, v in batch.items()}
    if isinstance(batch, tuple):
        out = [_to_numpy(v) for v in batch]
        # namedtuples (torch's default_collate preserves them) construct
        # from positional fields, not from one iterable.
        return (type(batch)(*out) if hasattr(batch, "_fields")
                else tuple(out))
    if isinstance(batch, list):
        return [_to_numpy(v) for v in batch]
    return batch


def as_numpy_batches(loader: Iterable) -> Iterator[PyTree]:
    """Iterate a torch ``DataLoader`` (or any iterable of tensor pytrees)
    as numpy pytrees."""
    for batch in loader:
        yield _to_numpy(batch)


def torch_loader_to_mesh(loader: Iterable, mesh, spec, *, depth: int = 2,
                         specs: Optional[PyTree] = None,
                         drop_remainder: bool = True) -> Iterator[PyTree]:
    """Stage a torch ``DataLoader``'s batches onto ``mesh`` with sharding
    ``spec`` (per-leaf ``specs`` wins), prefetching ``depth`` batches in
    the background.

    ``drop_remainder`` skips trailing batches whose leading dimension does
    not divide the mesh size (a ragged final batch cannot shard; the
    torch-side fix is ``DataLoader(..., drop_last=True)``).

    Usage::

        loader = torch.utils.data.DataLoader(ds, batch_size=64,
                                             drop_last=True)
        for xb, yb in torch_loader_to_mesh(loader, mesh,
                                           P(("dcn", "ici"))):
            state = step(state, xb, yb)   # device-resident, sharded
    """
    import jax
    import numpy as np

    from .input_pipeline import prefetch_to_mesh

    def dim0_shards(s):
        """How many ways the leading dim is split under spec ``s`` — the
        real divisibility requirement (NOT the total device count: a batch
        sharded over only the 'ici' axis of a 2x4 mesh needs
        divisibility by 4, not 8)."""
        if s is None or len(s) == 0 or s[0] is None:
            return 1
        names = (s[0],) if isinstance(s[0], str) else tuple(s[0])
        n = 1
        for a in names:
            n *= mesh.shape[a]
        return n

    def shardable(batch) -> bool:
        leaves = jax.tree.leaves(batch)
        if specs is not None:
            reqs = jax.tree.leaves(jax.tree.map(
                lambda _, s: dim0_shards(s), batch, specs,
                is_leaf=lambda x: x is None))
        else:
            reqs = [dim0_shards(spec)] * len(leaves)
        return all(np.ndim(leaf) == 0 or np.shape(leaf)[0] % req == 0
                   for leaf, req in zip(leaves, reqs))

    def batches():
        for batch in as_numpy_batches(loader):
            if drop_remainder and not shardable(batch):
                continue
            yield batch

    return prefetch_to_mesh(batches(), mesh, spec, depth=depth,
                            specs=specs)

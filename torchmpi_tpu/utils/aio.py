"""Async host-IO executor: Python wrapper over csrc/io.cpp.

Rebuild of the reference's C7 async-engine thread pool for the host side
(SURVEY.md §3 C7 — the reference ran async work on C++ threads with opaque
futures; device-side asynchrony is XLA dispatch here, so the native pool
serves host IO: checkpoint writes that must overlap the train loop).

Buffer-lifetime contract: the native layer does NOT copy submitted data
(avoiding a second memcpy of multi-GB checkpoints is the point), so every
``WriteHandle`` pins its buffer until the future completes; an unwaited
handle that gets garbage-collected never blocks GC — if the write is still
in flight, the buffer is parked in a module-level keep-alive list instead
(a leak beats a native write into freed memory; same policy as
parallel/ps.py, minus the bounded wait so a slow disk can't stall the
train loop from a finalizer).
"""

from __future__ import annotations

import ctypes
import os
import threading
from typing import Any, List, Optional

from . import native

_LIB_LOCK = threading.Lock()
_LIB: Optional[ctypes.CDLL] = None

_ORPHANED_BUFFERS: List[Any] = []


def _bind(lib: ctypes.CDLL) -> None:
    lib.tm_io_executor_create.restype = ctypes.c_int64
    lib.tm_io_executor_create.argtypes = [ctypes.c_int]
    lib.tm_io_submit_write.restype = ctypes.c_int64
    lib.tm_io_submit_write.argtypes = [
        ctypes.c_int64, ctypes.c_char_p, ctypes.c_char_p,
        ctypes.c_uint64, ctypes.c_int]
    lib.tm_io_wait_for.restype = ctypes.c_int
    lib.tm_io_wait_for.argtypes = [ctypes.c_int64, ctypes.c_int]
    lib.tm_io_status.restype = ctypes.c_int
    lib.tm_io_status.argtypes = [ctypes.c_int64]
    lib.tm_io_free.restype = None
    lib.tm_io_free.argtypes = [ctypes.c_int64]
    lib.tm_io_bytes_written.restype = ctypes.c_uint64
    lib.tm_io_bytes_written.argtypes = [ctypes.c_int64]
    lib.tm_io_executor_destroy.restype = None
    lib.tm_io_executor_destroy.argtypes = [ctypes.c_int64]


def _load_lib() -> ctypes.CDLL:
    global _LIB
    with _LIB_LOCK:
        if _LIB is None:
            _LIB = native.load_native("libtorchmpi_io.so", "io.cpp", _bind)
        return _LIB


class WriteHandle:
    """Future for one atomic file write (pins the source buffer)."""

    def __init__(self, lib: ctypes.CDLL, fid: int, path: str, buffer: Any):
        self._lib = lib
        self._fid = fid
        self.path = path
        self._buffer = buffer  # keep-alive until the native op completes
        self._err: Optional[int] = None  # sticky once the future resolves

    def done(self) -> bool:
        if self._fid is None:
            return True
        return self._lib.tm_io_status(self._fid) != -2

    def _raise_if_failed(self) -> None:
        if self._err:
            raise OSError(
                self._err,
                f"{os.strerror(self._err) if self._err > 0 else 'lost'}"
                f": {self.path}")

    def wait(self, timeout: Optional[float] = None) -> str:
        """Block until the write lands; returns the final path.  Raises
        ``TimeoutError`` (future stays live) or ``OSError`` with the native
        errno on failure.  Failure is sticky: every later ``wait`` re-raises
        — a retried wait must never report a write that did not happen."""
        if self._fid is None:
            self._raise_if_failed()
            return self.path
        ms = -1 if timeout is None else max(0, int(timeout * 1000))
        rc = self._lib.tm_io_wait_for(self._fid, ms)
        if rc == 0:
            raise TimeoutError(f"write of {self.path} still in flight "
                               f"after {timeout}s")
        self._err = self._lib.tm_io_status(self._fid) if rc == 1 else -1
        self._lib.tm_io_free(self._fid)
        self._fid = None
        self._buffer = None
        self._raise_if_failed()
        return self.path

    def __del__(self):
        # Never block GC on the disk: if the future is still in flight,
        # park the buffer (leak beats a native write into freed memory) and
        # release immediately.  A non-blocking poll settles the common case
        # where the write already finished.
        if getattr(self, "_fid", None) is None:
            return
        try:
            self.wait(timeout=0.0)
        except TimeoutError:
            _ORPHANED_BUFFERS.append((self._fid, self._buffer))
        except Exception:
            pass  # failed write has nowhere to raise from a finalizer


class AsyncWriter:
    """Thread-pool file writer with atomic tmp+rename semantics.

    ``threads=1`` (the default) gives FIFO completion order — submitting
    the data file before its metadata file guarantees on-disk ordering,
    which is how checkpoint.save_async commits.
    """

    def __init__(self, threads: int = 1):
        self._lib = _load_lib()
        self._eid = self._lib.tm_io_executor_create(threads)
        if self._eid < 0:
            raise RuntimeError(f"bad executor thread count {threads}")
        self._lock = threading.Lock()

    def submit(self, path: str, data, *, durable: bool = True) -> WriteHandle:
        """Queue an atomic write of ``data`` (bytes-like) to ``path``.
        Zero-copy: the buffer is pinned on the returned handle, not copied
        (embedded NULs are fine — the native side writes ``len`` bytes).

        With ``Config.faults`` armed, the submission runs under the
        fault layer (site ``aio.submit``: injected delays/drops, retried
        enqueue — docs/FAULTS.md); off is one string compare and the
        module is never imported."""
        from .. import runtime

        if runtime.effective_config().faults != "off":
            from .. import faults

            return faults.aio_submit(
                lambda: self._submit_once(path, data, durable))
        return self._submit_once(path, data, durable)

    def _submit_once(self, path: str, data, durable: bool) -> WriteHandle:
        if isinstance(data, bytes):
            n, ptr, pin = len(data), data, (data,)
        else:
            mv = memoryview(data).cast("B")
            n = len(mv)
            if mv.readonly:  # rare: copy once rather than reject
                b = bytes(mv)
                ptr, pin = b, (b,)
            else:
                ptr = (ctypes.c_char * n).from_buffer(mv) if n else None
                pin = (mv, ptr, data)
        with self._lock:
            if self._eid is None:
                raise RuntimeError("writer is closed")
            fid = self._lib.tm_io_submit_write(
                self._eid, path.encode(), ptr, n, 1 if durable else 0)
        if fid < 0:
            raise RuntimeError(f"submit failed for {path}")
        return WriteHandle(self._lib, fid, path, pin)

    def bytes_written(self) -> int:
        with self._lock:
            if self._eid is None:
                return 0
            return self._lib.tm_io_bytes_written(self._eid)

    def close(self) -> None:
        """Drain queued writes and join the pool."""
        with self._lock:
            eid, self._eid = self._eid, None
        if eid is not None:
            self._lib.tm_io_executor_destroy(eid)

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass

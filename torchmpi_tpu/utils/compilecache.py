"""Persistent XLA compilation cache plumbing.

On the relay-tunneled TPU platform this repo benchmarks on, compilation
is the scarce resource: the compile service is serial, a large graph can
take >15 minutes, and an abandoned compile wedges the queue for every
later client (round-2 postmortem, docs/ROUND2_NOTES.md).  JAX's
persistent compilation cache converts one successful compile into a disk
artifact every later process reuses, so the expensive compile is paid at
most once per (graph, jaxlib) — including across the builder's session
and the driver's end-of-round bench run.

The reference had no analog (compilation is not a phase in its
MPI/CUDA world); this is TPU-native operational machinery in the same
spirit as its tuned chunk-size constants: amortize the platform's fixed
costs.  Enabling is best-effort by design: platforms whose PJRT plugin
cannot serialize executables just miss the cache (JAX logs and moves
on); they never fail.
"""

from __future__ import annotations

import os

DEFAULT_DIR = os.path.join(os.path.dirname(os.path.dirname(
    os.path.dirname(os.path.abspath(__file__)))), ".jax_compile_cache")

_enabled: str | None = None


def enable_persistent_cache(directory: str | None = None) -> str:
    """Point JAX's persistent compilation cache at ``directory`` (default:
    ``<repo>/.jax_compile_cache``, override via
    ``TORCHMPI_TPU_COMPILE_CACHE``).  Idempotent; returns the directory.

    Thresholds are set to cache aggressively (min compile time 1 s, no
    minimum entry size): on the serial remote-compile platform even
    medium compiles are worth banking.
    """
    global _enabled
    directory = (directory
                 or os.environ.get("TORCHMPI_TPU_COMPILE_CACHE")
                 or DEFAULT_DIR)
    if _enabled == directory:
        return directory
    import jax

    os.makedirs(directory, exist_ok=True)
    jax.config.update("jax_compilation_cache_dir", directory)
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)
    jax.config.update("jax_persistent_cache_min_entry_size_bytes", -1)
    _enabled = directory
    return directory


def marker_path(name: str, directory: str | None = None) -> str:
    """Path of a success-marker file: records that the compile named
    ``name`` once completed against this cache, so later runs can treat
    re-compiles as probable cache hits when budgeting time (bench.py's
    stage-D gate).  ``name`` must encode everything that changes the
    compiled graph (platform, shapes, device count) — a marker from a
    different configuration would shrink the budget for what is actually
    a cold compile.  Resolution order: explicit arg > env > the directory
    passed to enable_persistent_cache > default.  Env outranks the
    enabled directory so a process that armed the cache at import (the
    compile gate does) still honors a later TORCHMPI_TPU_COMPILE_CACHE
    override for marker bookkeeping."""
    directory = (directory
                 or os.environ.get("TORCHMPI_TPU_COMPILE_CACHE")
                 or _enabled
                 or DEFAULT_DIR)
    return os.path.join(directory, f"compiled_ok_{name}")


def mark_compiled(name: str, directory: str | None = None) -> None:
    path = marker_path(name, directory)
    os.makedirs(os.path.dirname(path), exist_ok=True)
    with open(path, "w") as f:
        f.write("1\n")


def was_compiled(name: str, directory: str | None = None) -> bool:
    return os.path.exists(marker_path(name, directory))

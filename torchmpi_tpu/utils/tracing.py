"""Tracing/profiling hooks (SURVEY.md §6.1).

The reference had nothing built-in (external MPI profilers only); here each
collective / train step can be annotated so ``jax.profiler`` traces show
named spans, and a whole-program trace dumps perfetto-compatible files.
"""

from __future__ import annotations

import contextlib
import os
from typing import Iterator

import jax


@contextlib.contextmanager
def annotate(name: str) -> Iterator[None]:
    """Named scope visible in XLA/profiler traces (works inside jit)."""
    with jax.named_scope(name):
        yield


@contextlib.contextmanager
def trace(log_dir: str = "/tmp/torchmpi_tpu_trace",
          create_perfetto_link: bool = False) -> Iterator[str]:
    """Capture a profiler trace around a code region.

    View with tensorboard or ui.perfetto.dev (the trace.json.gz under
    ``<log_dir>/plugins/profile/...``).

    Robust to nested/failed ``start_trace``: jax allows one trace per
    process, so a ``trace()`` inside another (or after a crashed one
    left the profiler running) degrades to a no-op span instead of
    raising — and ``stop_trace`` only runs when OUR start succeeded, so
    a failed start can never raise a masking error out of the
    ``finally`` over the body's real exception.
    """
    os.makedirs(log_dir, exist_ok=True)
    started = False
    try:
        jax.profiler.start_trace(log_dir,
                                 create_perfetto_link=create_perfetto_link)
        started = True
    except RuntimeError:
        pass  # already tracing (nested start): body still runs, unprofiled
    try:
        yield log_dir
    finally:
        if started:
            try:
                jax.profiler.stop_trace()
            except RuntimeError:
                pass  # torn down elsewhere; never mask the body's error

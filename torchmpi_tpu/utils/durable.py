"""Durable checkpoints: digests, buddy replication, retention
(docs/CHECKPOINT.md — the ``Config.ckpt_redundancy`` layer).

Every recovery path shipped so far — restart replay, elastic
shrink/rejoin, guard rewind — bottoms out in ``utils/checkpoint.py``,
yet the storage under it was the weakest link it protects: single-copy
per-process files whose only post-restore check read one byte.  This
module is the resilience layer ``checkpoint.save``/``restore`` route
through when ``Config.ckpt_redundancy`` is on (ONE string compare at
their entry; ``"off"`` never imports this module — the
``analysis``/``obs``/``faults``/``guard`` discipline):

- **integrity** — a blake2b digest over the serialized npz bytes
  (:func:`~torchmpi_tpu.faults.integrity.digest_bytes`, the PR 11
  digest home) is recorded in the per-file metadata json and
  re-checked on every restore.  A mismatch is a typed
  :class:`~torchmpi_tpu.utils.checkpoint.CheckpointCorruptError` the
  recovery walk-back treats as try-the-next-older-step evidence —
  bit-rot can cost a step, never a silent garbage restore.
- **redundancy** (``"buddy"``) — each process mirrors its checkpoint
  pair to ``Config.ckpt_buddies`` buddy locations, holders
  ``(proc+1..K) mod world`` (a single-process sim mirrors to one
  separate on-disk location under ``<dir>/buddies/``).  A restore
  whose primary is missing or corrupt repairs from the first buddy
  copy that verifies — rewritten over the primary via the same atomic
  tmp+rename discipline, so the repair is bit-identical and durable.
  This is what makes an elastic shrink survivable when the dead
  rank's storage died with its files, and what the rejoin seeding
  (``checkpoint.replicate_for``) leans on.
- **retention** — ``Config.ckpt_keep`` keeps only the newest K steps
  per process (primaries and mirrors), never pruning the step
  recovery last settled on (``checkpoint.protect_step`` — the
  agreed/rewind step), so a chaos soak cannot fill the disk or eat
  its own rewind target.

Telemetry (``tm_ckpt_{saved,verified,verify_failed,repaired,pruned,
walkback}_total`` + ``ckpt`` flight events) rides
:mod:`torchmpi_tpu.obs` through the sys.modules-gated shim — a
checkpoint-only session never imports the telemetry it reports to.
The ``ckpt.write``/``ckpt.read`` fault sites live one layer down in
``checkpoint._write_atomic``/``_read_npz_bytes``, so injected
torn-write/ENOSPC/bit-rot hits primaries, mirrors, and repairs alike.
"""

from __future__ import annotations

import json
import os
from typing import List, Optional, Tuple

import jax

from . import checkpoint, telemetry
from ..faults import integrity


def _emit(action: str, *, step: int = 0, reason: str = "") -> None:
    telemetry.emit("record_ckpt", action, step=step, reason=reason)


def buddy_holders(proc: int, world: Optional[int] = None,
                  k: Optional[int] = None) -> List[int]:
    """The ranks holding ``proc``'s buddy copies: ``(proc+1..K) mod
    world``, never ``proc`` itself — except on a one-process world,
    where the single "holder" is a separate on-disk location under the
    same rank (protects against file loss/rot, not host loss; the
    multi-process deployment is where holders are real other
    storages)."""
    if world is None:
        world = jax.process_count()
    if k is None:
        from .. import runtime

        k = runtime.effective_config().ckpt_buddies
    holders = []
    for i in range(1, int(k) + 1):
        h = (int(proc) + i) % int(world)
        if h != int(proc) and h not in holders:
            holders.append(h)
    return holders or [int(proc)]


def buddy_dir(directory: str, holder: int) -> str:
    """The on-disk stand-in for rank ``holder``'s checkpoint storage."""
    return os.path.join(directory, "buddies", f"r{int(holder)}")


def _per_file_payloads(data, n: int):
    """``n`` byte buffers for ``n`` file writes of the same content.
    With the fault layer armed each file gets an INDEPENDENT copy —
    injected bit-rot must rot one storage location, not the shared
    staging buffer feeding every mirror (a shared buffer would make
    buddy repair structurally impossible under chaos).  Unarmed, the
    buffer is shared (zero copies)."""
    if checkpoint._faults_mod() is None:
        return [data] * n
    return [bytearray(memoryview(data)) for _ in range(n)]


def _pair_targets(directory: str, proc: int,
                  mode: str) -> List[str]:
    """Primary directory first, then each buddy location (created on
    demand)."""
    targets = [directory]
    if mode == "buddy":
        for h in buddy_holders(proc):
            d = buddy_dir(directory, h)
            os.makedirs(d, exist_ok=True)
            targets.append(d)
    return targets


def save_pair(directory: str, name: str, data, meta: dict, *,
              step: int, proc: int, prune_old: bool = True) -> str:
    """Synchronously commit one digest-stamped checkpoint pair
    (``<name>.npz`` + ``<name>.json``) to the primary directory and
    every buddy location, then apply retention.  ``data`` is the
    serialized npz; the digest is taken over it HERE, before any write
    (and before any injected fault can touch a staging buffer), so the
    metadata records what the saver meant to persist."""
    from .. import runtime

    cfg = runtime.effective_config()
    meta = dict(meta or {})
    meta["digest"] = integrity.digest_bytes(data)
    meta_bytes = json.dumps(meta).encode()
    targets = _pair_targets(directory, proc, cfg.ckpt_redundancy)
    payloads = _per_file_payloads(data, len(targets))
    for d, payload in zip(targets, payloads):
        checkpoint._write_atomic(os.path.join(d, name + ".npz"), payload)
        checkpoint._write_atomic(os.path.join(d, name + ".json"),
                                 meta_bytes)
    _emit("saved", step=step)
    if prune_old:
        prune(directory, name.split("_", 1)[0] + "_", proc,
              cfg.ckpt_keep)
    return os.path.join(directory, name + ".npz")


def submit_pair(writer, directory: str, name: str, data, meta: dict, *,
                step: int, proc: int, durable: bool = True):
    """The async-writer twin of :func:`save_pair`: primary pair and
    buddy mirrors all ride the native IO executor (FIFO — each npz
    commits before its json), returning one
    :class:`~torchmpi_tpu.utils.checkpoint.CheckpointHandle` over
    every in-flight write.  Retention is DEFERRED to the handle's
    ``wait()`` (the ``on_durable`` callback): pruning from the caller
    thread would race older steps' still-queued writes — FIFO orders
    completions, it does not mean they have happened — and a pruned
    file would be resurrected by its own pending rename.  A handle
    that is never waited prunes at the next save instead (the doomed
    list is recomputed in full each time)."""
    from .. import runtime

    cfg = runtime.effective_config()
    meta = dict(meta or {})
    meta["digest"] = integrity.digest_bytes(data)
    meta_bytes = json.dumps(meta).encode()
    targets = _pair_targets(directory, proc, cfg.ckpt_redundancy)
    payloads = _per_file_payloads(data, len(targets))
    handles = []
    for d, payload in zip(targets, payloads):
        handles.append(checkpoint._submit(
            writer, os.path.join(d, name + ".npz"), payload, durable))
        handles.append(checkpoint._submit(
            writer, os.path.join(d, name + ".json"), meta_bytes,
            durable))
    _emit("saved", step=step)
    on_durable = None
    if cfg.ckpt_keep > 0:
        prefix = name.split("_", 1)[0] + "_"
        keep = cfg.ckpt_keep

        def on_durable():
            prune(directory, prefix, proc, keep)
    return checkpoint.CheckpointHandle(
        handles, os.path.join(directory, name + ".npz"),
        on_durable=on_durable)


def _load_meta(path: str) -> Optional[dict]:
    """The metadata json, or None when missing/unparseable (a torn
    json is ABSENT evidence, not a crash — the npz digest in a buddy's
    json can still vouch for the bytes)."""
    try:
        with open(path) as f:
            return json.load(f)
    except (OSError, ValueError):
        return None


def _read_verified(d: str, name: str) -> Tuple[bytes, Optional[dict],
                                               Optional[str]]:
    """Read one location's pair and check its digest.  Returns
    ``(bytes, meta, error)`` where ``error`` is None on success, else
    why this copy was rejected."""
    meta = _load_meta(os.path.join(d, name + ".json"))
    try:
        raw = checkpoint._read_npz_bytes(os.path.join(d, name + ".npz"))
    except OSError as e:
        return b"", meta, f"unreadable ({e})"
    expect = (meta or {}).get("digest", "")
    if expect:
        got = integrity.digest_bytes(raw)
        if got != expect:
            return raw, meta, f"digest {got[:12]} != {expect[:12]}"
    elif meta is None:
        # No digest anywhere for this copy: only acceptable for the
        # PRIMARY of a legacy (pre-redundancy) checkpoint — the caller
        # decides; buddies are always written with stamped metadata.
        return raw, None, None
    return raw, meta, None


def read_pair(directory: str, name: str, *, step: int,
              proc: int) -> Tuple[bytes, Optional[dict]]:
    """Verified read of one checkpoint pair, repairing from a buddy
    copy when the primary is missing or fails its digest check
    (``"buddy"`` mode).  A primary whose OWN metadata is lost or torn
    (no digest to check against) is not trusted blind in buddy mode:
    the first buddy whose stamped pair verifies either VOUCHES for the
    primary bytes (digests match — the primary's json is re-seeded) or
    vetoes them (repair from the buddy); only with no verifiable buddy
    anywhere does the digestless primary pass as a legacy checkpoint.
    Returns ``(npz bytes, metadata dict)``; raises
    :class:`~torchmpi_tpu.utils.checkpoint.CheckpointCorruptError`
    when no copy verifies (or ``FileNotFoundError`` when no copy
    exists at all) — the walk-back evidence ``restart.recover``
    consumes."""
    from .. import runtime

    cfg = runtime.effective_config()
    path = os.path.join(directory, name + ".npz")
    primary_exists = os.path.exists(path)
    first_err = ""
    unvouched = None  # a readable primary with no digest of its own
    if primary_exists:
        raw, meta, err = _read_verified(directory, name)
        if err is None:
            if (meta or {}).get("digest"):
                _emit("verified", step=step)
                return raw, meta
            if cfg.ckpt_redundancy != "buddy":
                return raw, meta  # legacy pair; nothing to check against
            unvouched = (raw, meta)
        else:
            first_err = err
            _emit("verify_failed", step=step, reason="primary")
    if cfg.ckpt_redundancy == "buddy":
        for h in buddy_holders(proc):
            d = buddy_dir(directory, h)
            if not os.path.exists(os.path.join(d, name + ".npz")):
                continue
            raw, meta, err = _read_verified(d, name)
            if err is not None or not (meta or {}).get("digest"):
                _emit("verify_failed", step=step, reason=f"buddy_r{h}")
                continue
            meta_bytes = json.dumps(meta).encode()
            if unvouched is not None and \
                    integrity.digest_bytes(unvouched[0]) == meta["digest"]:
                # The buddy vouches for the digestless primary: same
                # bytes, so only the primary's json needs re-seeding.
                try:
                    checkpoint._write_atomic(
                        os.path.join(directory, name + ".json"),
                        meta_bytes)
                except OSError:
                    pass
                _emit("verified", step=step)
                return unvouched[0], meta
            if unvouched is not None:
                # The buddy VETOES the primary bytes — the digestless
                # primary was rot after all.
                first_err = "no local digest; buddy digest differs"
                _emit("verify_failed", step=step, reason="primary")
                unvouched = None
            # Repair: rewrite the primary pair bit-identically via the
            # same atomic+fsync discipline, so the NEXT restore (and
            # any peer seeding from this rank) finds a healthy copy.
            try:
                checkpoint._write_atomic(path, raw)
                checkpoint._write_atomic(
                    os.path.join(directory, name + ".json"), meta_bytes)
            except OSError:
                pass  # the bytes are good even if the disk still isn't
            _emit("repaired", step=step, reason=f"buddy_r{h}")
            return raw, meta
        if unvouched is not None:
            # Readable digestless primary, no verifiable buddy to
            # vouch or veto: the legacy acceptance.
            return unvouched
    if not primary_exists:
        raise FileNotFoundError(
            f"{path}: no checkpoint copy exists (primary missing, "
            f"no verifiable buddy)")
    raise checkpoint.CheckpointCorruptError(
        path, step=step, reason=f"primary {first_err}; no buddy copy "
                                f"verified")


def prune(directory: str, prefix: str, proc: int, keep: int) -> None:
    """Keep-last-``keep`` retention over one process's ``prefix`` steps
    (primaries AND buddy mirrors).  The protected step — the one
    recovery last settled on (``checkpoint.protect_step``) — is never
    pruned, whatever its age: a soak that rewinds to it must find it.
    ``keep <= 0`` disables."""
    if keep <= 0:
        return
    suffix = f"_p{int(proc)}.npz"
    steps = []
    try:
        names = os.listdir(directory)
    except OSError:
        return
    for fname in names:
        if fname.startswith(prefix) and fname.endswith(suffix):
            try:
                steps.append(int(fname[len(prefix):-len(suffix)]))
            except ValueError:
                continue
    steps.sort()
    protected = checkpoint.protected_step(directory)
    doomed = [s for s in steps[:-keep] if s != protected]
    for s in doomed:
        name = f"{prefix}{s}_p{int(proc)}"
        dirs = [directory] + [buddy_dir(directory, h)
                              for h in buddy_holders(proc)]
        for d in dirs:
            for ext in (".npz", ".json"):
                try:
                    os.remove(os.path.join(d, name + ext))
                except OSError:
                    pass
        _emit("pruned", step=s)


def scan_dirs(directory: str, proc: int) -> List[str]:
    """The buddy locations whose copies count as restorable steps for
    ``proc`` (``checkpoint._steps`` unions them into the listing in
    ``"buddy"`` mode — a step that only survives on a buddy is still a
    step)."""
    from .. import runtime

    if runtime.effective_config().ckpt_redundancy != "buddy":
        return []
    return [d for d in (buddy_dir(directory, h)
                        for h in buddy_holders(proc))
            if os.path.isdir(d)]

"""Synthetic dataset generators.

The reference's examples loaded MNIST/CIFAR/ImageNet from disk; this
environment has no network egress, so examples and convergence tests use
synthetic-but-learnable class-conditional data: each class is a fixed random
template plus noise.  A model that learns reaches high accuracy; a broken
gradient path does not — which is all the reference's "examples as
convergence smoke tests" strategy needed (SURVEY.md §5).
"""

from __future__ import annotations

from typing import Tuple

import numpy as np


def synthetic_image_classification(
    n: int,
    *,
    image_shape: Tuple[int, int, int] = (28, 28, 1),
    num_classes: int = 10,
    noise: float = 0.3,
    seed: int = 0,
) -> Tuple[np.ndarray, np.ndarray]:
    """Return (images [n, *image_shape] float32 in ~[0,1], labels [n] int32)."""
    rng = np.random.RandomState(seed)
    templates = rng.rand(num_classes, *image_shape).astype(np.float32)
    labels = rng.randint(0, num_classes, size=n).astype(np.int32)
    images = templates[labels] + noise * rng.randn(n, *image_shape).astype(
        np.float32)
    return images.astype(np.float32), labels


def synthetic_mnist(n: int, seed: int = 0):
    return synthetic_image_classification(
        n, image_shape=(28, 28, 1), num_classes=10, seed=seed)


def synthetic_cifar(n: int, seed: int = 0):
    return synthetic_image_classification(
        n, image_shape=(32, 32, 3), num_classes=10, seed=seed)


def synthetic_imagenet(n: int, image_size: int = 224, num_classes: int = 1000,
                       seed: int = 0):
    return synthetic_image_classification(
        n, image_shape=(image_size, image_size, 3), num_classes=num_classes,
        seed=seed)


def batches(images: np.ndarray, labels: np.ndarray, batch_size: int,
            *, steps: int, seed: int = 0):
    """Infinite-ish shuffled batch iterator yielding ``steps`` batches."""
    rng = np.random.RandomState(seed)
    n = images.shape[0]
    for _ in range(steps):
        idx = rng.randint(0, n, size=batch_size)
        yield images[idx], labels[idx]

"""Shared loader for the native extensions in csrc/ (C ABI via ctypes).

One staleness policy for every extension: the built .so is keyed on a
content hash of its source stored next to the binary — mtimes are
meaningless after git clone (ADVICE round 1), and build/ is not committed.
Builds go through ``make -C csrc``, whose atomic tmp+rename rule keeps
concurrent lazy builders from ever dlopen'ing a half-written library.
"""

from __future__ import annotations

import ctypes
import hashlib
import os
import subprocess
from typing import Callable, Optional


def repo_root() -> str:
    return os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))


def _src_digest(path: str) -> str:
    with open(path, "rb") as f:
        return hashlib.sha256(f.read()).hexdigest()


def load_native(so_name: str, src_name: str,
                bind: Optional[Callable[[ctypes.CDLL], None]] = None,
                ) -> ctypes.CDLL:
    """Load ``build/<so_name>``, rebuilding from ``csrc/<src_name>`` when
    its content hash changed; ``bind(lib)`` declares ctypes signatures.
    Callers hold their own cache + lock — this function is stateless.

    ``TORCHMPI_TPU_NATIVE_VARIANT=tsan`` loads the ``_tsan``-suffixed
    sanitizer build instead (``make -C csrc tsan``), so the whole PS/IO
    test suite can execute under ThreadSanitizer — pair with
    ``TSAN_OPTIONS=halt_on_error=1`` to turn any detected race into a
    loud test failure."""
    root = repo_root()
    variant = os.environ.get("TORCHMPI_TPU_NATIVE_VARIANT", "")
    if variant:
        base, ext = os.path.splitext(so_name)
        so_name = f"{base}_{variant}{ext}"
    so = os.path.join(root, "build", so_name)
    src = os.path.join(root, "csrc", src_name)
    if os.path.exists(src):
        digest_file = so + ".srchash"
        digest = _src_digest(src)
        built = None
        if os.path.exists(so) and os.path.exists(digest_file):
            with open(digest_file) as f:
                built = f.read().strip()
        if built != digest:
            try:
                subprocess.run(["make", "-C", os.path.join(root, "csrc")]
                               + ([variant] if variant else []),
                               check=True, capture_output=True, text=True)
            except subprocess.CalledProcessError as e:
                raise RuntimeError(
                    f"native build failed for {src_name}:\n{e.stderr}"
                ) from e
            with open(digest_file, "w") as f:
                f.write(digest)
    elif not os.path.exists(so):
        raise RuntimeError(
            f"native extension unavailable: neither {so} nor {src} exists")
    # src absent but .so present: prebuilt deployment; load as-is.
    lib = ctypes.CDLL(so)
    if bind is not None:
        bind(lib)
    return lib

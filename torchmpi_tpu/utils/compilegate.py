"""Library-wide compile-budget gate for the relay-tunneled TPU platform.

Why this exists (rounds 2-3 postmortems, docs/ROUND2_NOTES.md and
docs/ROUND3_NOTES.md "SELF-INFLICTED RE-WEDGE"): the relay's compile
service is SERIAL and a client that abandons an in-flight large compile
(external timeout -> SIGTERM mid-queue) wedges the service indefinitely
for every later client.  Round 3 proved that prose discipline is not
enough — the rule must live in the library so that *no* device client
can start a large cold compile it cannot finish.

The rule enforced here (VERDICT r3, next-round item #1): on the relay
platform, a device client about to dispatch a NEW-shape large graph
compile must either

  (a) hold a success marker for that exact graph key (the compile
      completed once against this persistent cache, so this dispatch is
      a probable cache hit / fast path), or
  (b) run under an explicitly declared budget that can absorb a cold
      compile — unbounded, or a deadline with enough time remaining.

Otherwise the gate raises :class:`CompileBudgetError` BEFORE anything is
sent to the relay: failing fast on the client side is always safe; the
wedge only happens when the relay's queue is abandoned mid-compile.

While a blessed large compile is in flight, SIGTERM/SIGINT are DEFERRED
(recorded, re-delivered after the compile returns) so a bounded outer
runner's termination cannot abandon the queue slot — this is the
"non-abandonable" half of rule (b).  An inflight heartbeat file is also
maintained so cooperating supervisors (scripts/tpu_watch.py run_bounded)
can extend their kill grace while a compile is genuinely in flight.

Mechanism: ``install()`` wraps ``jax._src.compiler.backend_compile`` and
``backend_compile_and_load`` — the exact points reached only when the
persistent compilation cache MISSES (cache hits return earlier inside
``compile_or_get_cached``), i.e. only for real compiles.  No other jax
module imports these symbols by value (verified against jax 0.9.0), so
the monkeypatch is a true chokepoint.  The wrapper is passive (zero
cost beyond an attribute check) unless the compiling backend is the
relay platform AND the module is large.

The reference had no analog — compilation is not a phase in its
MPI/CUDA world (SURVEY.md §0/§3); this is TPU-native operational
machinery forced by the platform's serial remote compiler.

Install points: ``torchmpi_tpu/__init__`` (import-time, so EVERY client
of the library is covered), ``mpi.init()`` (re-asserts), and bench.py.
Opt out with ``TORCHMPI_TPU_COMPILE_GATE=0``.
"""

from __future__ import annotations

import contextlib
import hashlib
import os
import signal
import threading
import time
from typing import Optional

from . import compilecache

# A graph below this serialized-bytecode size is never gated: probes
# and collective/kernel microbenches compile in seconds even cold.
# Calibration (this repo, jax 0.9.0, measured via jax.export serialized
# module bytes — tests/test_flagship_lowering.py pins the boundary):
# 1024^2 matmul probe ~3 KB, toy stage-B LM step ~101 KB (a ~minute
# relay compile), flagship stage-B' LM step ~207 KB, ResNet-50 b128
# train step ~272 KB (the known >900 s class).  Model train steps lower
# COMPACTLY — long relay compiles arrive as mere hundreds of KB — so
# the threshold sits below the ENTIRE train-step band, minute-class
# included: an abandoned in-flight compile wedges the serial queue
# whatever its duration (round-1 postmortem was a kill mid-claim), so
# a minute-class client needs the same declared budget as a 900 s one;
# only the seconds-class probe/kernel tier passes ungated.
DEFAULT_MIN_BYTES = 64 * 1024

# Budget (seconds) a cold large compile is assumed to need on the relay,
# and the shrunken figure when a success marker exists for the exact key.
DEFAULT_NEED_COLD = 900.0
DEFAULT_NEED_MARKED = 240.0


class CompileBudgetError(RuntimeError):
    """A large cold compile was requested without the budget to finish it.

    Raised BEFORE the compile is dispatched to the relay.  See module
    docstring for the rule; declare a budget with
    ``torchmpi_tpu.compile_budget(...)`` or
    ``TORCHMPI_TPU_COMPILE_BUDGET=unbounded``.
    """


class _GateState:
    def __init__(self) -> None:
        self.lock = threading.Lock()
        self.installed = False
        self.orig_backend_compile = None
        self.orig_backend_compile_and_load = None
        # Declared-budget stack (process-wide, not thread-local: the
        # relay queue is a process-external resource and jit compiles
        # can hop threads in jax; last declaration wins).
        self.budget_stack: list[Optional[float]] = []  # None => unbounded


_gate = _GateState()


# A numeric TORCHMPI_TPU_COMPILE_BUDGET means "this many seconds from
# when the budget was first consulted", so the derived epoch deadline is
# cached per raw value — re-deriving at every check would slide the
# deadline forward forever and bless compiles the real wall clock cannot
# absorb (code review r4).
_env_deadline_cache: dict[str, float] = {}


def _env_budget() -> Optional[object]:
    """The env-declared budget: 'unbounded' -> None, seconds -> epoch
    deadline (derived ONCE per value), unset/empty -> _MISSING."""
    raw = os.environ.get("TORCHMPI_TPU_COMPILE_BUDGET", "").strip()
    if not raw:
        return _MISSING
    if raw.lower() in ("unbounded", "inf", "none"):
        return None
    try:
        seconds = float(raw)
    except ValueError:
        return _MISSING
    if raw not in _env_deadline_cache:
        _env_deadline_cache[raw] = time.time() + seconds
    return _env_deadline_cache[raw]


_MISSING = object()


def current_budget() -> object:
    """Resolve the active budget: innermost compile_budget() context,
    else env, else bench's TORCHMPI_TPU_BENCH_DEADLINE (epoch seconds),
    else _MISSING.  Returns None for unbounded, an epoch-seconds float
    for a deadline, or _MISSING."""
    if _gate.budget_stack:
        return _gate.budget_stack[-1]
    env = _env_budget()
    if env is not _MISSING:
        return env
    bench_deadline = os.environ.get("TORCHMPI_TPU_BENCH_DEADLINE", "")
    if bench_deadline:
        try:
            return float(bench_deadline)
        except ValueError:
            pass
    return _MISSING


@contextlib.contextmanager
def compile_budget(seconds: Optional[float] = None):
    """Declare a compile budget for the dynamic extent of the block.

    ``seconds=None`` declares an UNBOUNDED, non-abandonable budget (the
    caller commits to letting any compile finish); a number declares a
    deadline ``now + seconds``.  Nesting: innermost wins.
    """
    deadline = None if seconds is None else time.time() + float(seconds)
    _gate.budget_stack.append(deadline)
    try:
        yield
    finally:
        _gate.budget_stack.pop()


def _relay_factory_registered() -> bool:
    """True when the axon relay PJRT plugin is registered (the wedgable
    platform).  Checked without initializing any backend."""
    try:
        from jax._src import xla_bridge as xb

        return any("axon" in str(name).lower()
                   for name in xb._backend_factories)
    except Exception:  # noqa: BLE001 — failing open keeps jax usable
        return False


def _gated_platform(backend) -> bool:
    if os.environ.get("TORCHMPI_TPU_COMPILE_GATE", "1") == "0":
        return False
    try:
        platform = backend.platform
    except Exception:  # noqa: BLE001
        return False
    if platform != "tpu":
        return False
    # Gate only when the relay plugin is what provides the tpu platform.
    # On real (non-relay) TPU hosts compiles are local and abandonment
    # is harmless, so the gate must not surprise normal users.
    if not _relay_factory_registered():
        return os.environ.get("TORCHMPI_TPU_COMPILE_GATE") == "1"
    return True


def _module_bytes(module) -> bytes:
    try:
        from jax._src.interpreters import mlir

        return mlir.module_to_bytecode(module)
    except Exception:  # noqa: BLE001
        return str(module).encode()


def _graph_key(module, n_devices: int) -> str:
    data = _module_bytes(module)
    digest = hashlib.sha256(data).hexdigest()[:16]
    return f"hlo_{digest}_n{n_devices}", len(data)


def inflight_path() -> str:
    """Heartbeat file maintained while a blessed compile is in flight.
    Supervisors that bound this process (tpu_watch.run_bounded) check
    its mtime before escalating SIGTERM to SIGKILL."""
    return os.path.join(compilecache.DEFAULT_DIR,
                        f"compile_inflight_{os.getpid()}")


class _DeferSignals:
    """Defer SIGTERM/SIGINT for the duration of a blessed compile.

    Only effective on the main thread (signal.signal restriction);
    compiles dispatched from worker threads simply skip deferral.
    """

    SIGS = (signal.SIGTERM, signal.SIGINT)

    def __init__(self) -> None:
        self.pending: list[int] = []
        self.prev = {}
        self.active = False

    def __enter__(self):
        if threading.current_thread() is not threading.main_thread():
            return self
        try:
            for s in self.SIGS:
                self.prev[s] = signal.signal(
                    s, lambda num, frame: self.pending.append(num))
            self.active = True
        except ValueError:
            self.active = False
        return self

    def __exit__(self, *exc):
        if not self.active:
            return False
        for s, h in self.prev.items():
            signal.signal(s, h)
        for num in self.pending:
            os.kill(os.getpid(), num)  # re-deliver, now under prev handler
        return False


def _check_budget(key: str, size: int, module_name: str) -> None:
    """Raise CompileBudgetError unless this large cold compile is
    blessed.  Called only on a persistent-cache MISS on the relay."""
    marked = compilecache.was_compiled(key)
    need = float(os.environ.get(
        "TORCHMPI_TPU_COMPILE_NEED",
        str(DEFAULT_NEED_MARKED if marked else DEFAULT_NEED_COLD)))
    budget = current_budget()
    if budget is None:
        return  # unbounded — blessed
    if budget is _MISSING:
        if marked:
            # Success marker but cache miss: the exact graph compiled
            # before, so this is the fast-recompile class; allow it.
            # (The marker is written only AFTER a completed compile.)
            return
        raise CompileBudgetError(
            f"refusing to dispatch a large cold compile to the relay: "
            f"module '{module_name}' ({size/1e6:.1f} MB bytecode, key "
            f"{key}) has no prior-success marker and no declared compile "
            f"budget. The relay's serial compile queue wedges for every "
            f"later client if this compile is abandoned "
            f"(docs/ROUND3_NOTES.md). Declare intent with "
            f"`with torchmpi_tpu.compile_budget(): ...` (unbounded) or "
            f"TORCHMPI_TPU_COMPILE_BUDGET=unbounded, and do NOT run "
            f"under an external timeout that could SIGKILL mid-compile.")
    remaining = budget - time.time()
    if remaining < need:
        raise CompileBudgetError(
            f"refusing to dispatch large compile of '{module_name}' "
            f"({size/1e6:.1f} MB, key {key}): declared budget has "
            f"{remaining:.0f}s left < {need:.0f}s estimated "
            f"{'re-compile' if marked else 'cold compile'} need. "
            f"Abandoning it would wedge the relay for all later clients.")


def _wrap(orig):
    def gated(backend, module, executable_devices, options, *args, **kw):
        if not _gated_platform(backend):
            return orig(backend, module, executable_devices, options,
                        *args, **kw)
        # First gated compile: make sure the persistent cache is live so
        # (a) this compile is banked for every later process and (b)
        # reaching this wrapper really means a cache miss.  Lazy and
        # relay-only on purpose — enabling globally at import would make
        # unrelated CPU runs load cache entries AOT-compiled for another
        # host's machine features (observed: cpu_aot_loader SIGILL-risk
        # errors after a container migration).
        try:
            compilecache.enable_persistent_cache()
        except OSError:
            pass
        min_bytes = int(os.environ.get("TORCHMPI_TPU_COMPILE_GATE_MIN_BYTES",
                                       str(DEFAULT_MIN_BYTES)))
        try:
            n_dev = len(executable_devices)
        except TypeError:
            n_dev = 1
        key, size = _graph_key(module, n_dev)
        try:
            sym = module.operation.attributes["sym_name"]
            module_name = str(sym).strip('"')
        except Exception:  # noqa: BLE001
            module_name = "<module>"
        if size < min_bytes:
            return orig(backend, module, executable_devices, options,
                        *args, **kw)
        _check_budget(key, size, module_name)
        # Blessed: non-abandonable from here. Defer signals, heartbeat.
        hb = inflight_path()
        stop_hb = threading.Event()

        def heartbeat():
            while not stop_hb.wait(10.0):
                try:
                    with open(hb, "w") as f:
                        f.write(f"{module_name} {time.time()}\n")
                except OSError:
                    return

        try:
            os.makedirs(os.path.dirname(hb), exist_ok=True)
            with open(hb, "w") as f:
                f.write(f"{module_name} {time.time()}\n")
        except OSError:
            pass
        hb_thread = threading.Thread(target=heartbeat, daemon=True)
        hb_thread.start()
        try:
            with _DeferSignals():
                out = orig(backend, module, executable_devices, options,
                           *args, **kw)
            compilecache.mark_compiled(key)
            return out
        finally:
            stop_hb.set()
            hb_thread.join(timeout=1.0)
            try:
                os.unlink(hb)
            except OSError:
                pass

    gated.__wrapped__ = orig
    return gated


def install() -> bool:
    """Arm the gate (idempotent).  Returns True when armed.  The
    persistent compile cache is enabled lazily by the wrapper on the
    first relay-gated compile (see note below)."""
    with _gate.lock:
        if _gate.installed:
            return True
        if os.environ.get("TORCHMPI_TPU_COMPILE_GATE", "1") == "0":
            return False
        try:
            from jax._src import compiler as _compiler
        except Exception:  # noqa: BLE001
            return False
        # NOTE: the persistent cache is deliberately NOT enabled here —
        # the wrapper enables it lazily on the first RELAY-gated compile.
        # Enabling at import time would (a) crash `import torchmpi_tpu`
        # outright on a read-only install tree (code review r4) and (b)
        # make every unrelated CPU run load cache entries AOT-compiled
        # for a previous host's machine features (SIGILL risk after a
        # container migration).
        _gate.orig_backend_compile = _compiler.backend_compile
        _compiler.backend_compile = _wrap(_compiler.backend_compile)
        # Older jax (< 0.6) has no backend_compile_and_load; wrap it only
        # where it exists so the gate arms on either version.
        if hasattr(_compiler, "backend_compile_and_load"):
            _gate.orig_backend_compile_and_load = (
                _compiler.backend_compile_and_load)
            _compiler.backend_compile_and_load = _wrap(
                _compiler.backend_compile_and_load)
        else:
            _gate.orig_backend_compile_and_load = None
        _gate.installed = True
        return True


def uninstall() -> None:
    with _gate.lock:
        if not _gate.installed:
            return
        from jax._src import compiler as _compiler

        _compiler.backend_compile = _gate.orig_backend_compile
        if _gate.orig_backend_compile_and_load is not None:
            _compiler.backend_compile_and_load = (
                _gate.orig_backend_compile_and_load)
        _gate.installed = False

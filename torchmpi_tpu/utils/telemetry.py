"""The sys.modules-gated obs dispatch shim.

Every off-by-default layer (faults, guard, integrity) reports telemetry
through :mod:`torchmpi_tpu.obs` *without importing it* — a
faults-only or guard-only session must never pull the telemetry layer
into the process (the never-imported-when-off discipline).  This is
the ONE implementation of that contract: look the module up in
``sys.modules``, check ``active()``, dispatch, and swallow everything
— telemetry never fails a step.  Dependency-free on purpose.
"""

from __future__ import annotations

import sys


def emit(method: str, *args, **kwargs) -> None:
    """Call ``torchmpi_tpu.obs.<method>(*args, **kwargs)`` iff obs is
    imported AND active; no-op (and exception-proof) otherwise."""
    mod = sys.modules.get("torchmpi_tpu.obs")
    try:
        if mod is not None and mod.active():
            getattr(mod, method)(*args, **kwargs)
    except Exception:  # noqa: BLE001 — telemetry never fails a step
        pass


def flight_tail(n: int = 8) -> list:
    """The last ``n`` flight-recorder events, when obs is active — the
    evidence a typed hang/timeout error ships with so the exception
    that kills a step arrives with what ``obs_tool blame`` would
    otherwise dig out of a post-mortem dump.  The ONE implementation
    (``faults.policy`` and ``watchdog`` both route here); same
    sys.modules gate as :func:`emit`."""
    mod = sys.modules.get("torchmpi_tpu.obs")
    try:
        if mod is not None and mod.active():
            return mod.recorder().to_records(best_effort=True)[-n:]
    except Exception:  # noqa: BLE001 — evidence must not mask the error
        pass
    return []

"""The sys.modules-gated obs dispatch shim.

Every off-by-default layer (faults, guard, integrity) reports telemetry
through :mod:`torchmpi_tpu.obs` *without importing it* — a
faults-only or guard-only session must never pull the telemetry layer
into the process (the never-imported-when-off discipline).  This is
the ONE implementation of that contract: look the module up in
``sys.modules``, check ``active()``, dispatch, and swallow everything
— telemetry never fails a step.  Dependency-free on purpose.
"""

from __future__ import annotations

import sys


def emit(method: str, *args, **kwargs) -> None:
    """Call ``torchmpi_tpu.obs.<method>(*args, **kwargs)`` iff obs is
    imported AND active; no-op (and exception-proof) otherwise."""
    mod = sys.modules.get("torchmpi_tpu.obs")
    try:
        if mod is not None and mod.active():
            getattr(mod, method)(*args, **kwargs)
    except Exception:  # noqa: BLE001 — telemetry never fails a step
        pass

"""Pytree <-> flat float32 vector utilities (the PS operates on flat shards,
as the reference's parameterserver did on flattened parameter tensors).

Dtype contract (VERDICT round 1, weak item 7): the wire/shard format is
float32 (the C++ server's update rules do f32 math, the analog of the
reference's per-dtype TH kernels instantiated for float).  Leaves may be
float32, or bfloat16/float16 — both embed in float32 exactly, so a
send->receive round trip is bit-exact after the cast back.  Any dtype whose
values do NOT embed exactly (float64, integers — f32 mantissa clips above
2^24) raises TypeError instead of silently laundering precision through the
optimizer-state store.
"""

from __future__ import annotations

from typing import Any, List, Tuple

import jax
import jax.numpy as jnp
import numpy as np

PyTree = Any

# Dtypes that embed in float32 exactly (value-preserving upcast, bit-exact
# round trip on the cast back).
_EXACT_IN_F32 = (np.dtype(np.float32), np.dtype(jnp.bfloat16),
                 np.dtype(np.float16))


class TreeSpec:
    def __init__(self, treedef, shapes: List[Tuple[int, ...]],
                 dtypes: List[np.dtype]):
        self.treedef = treedef
        self.shapes = shapes
        self.dtypes = dtypes
        self.sizes = [int(np.prod(s)) for s in shapes]
        self.total = int(sum(self.sizes))


def flatten_f32(tree: PyTree) -> Tuple[np.ndarray, TreeSpec]:
    """Flatten a pytree of arrays into one float32 numpy vector.

    Raises TypeError for leaves whose dtype does not embed exactly in
    float32 (see module docstring)."""
    leaves, treedef = jax.tree.flatten(tree)
    arrs = [np.asarray(l) for l in leaves]
    for a in arrs:
        if a.dtype not in _EXACT_IN_F32:
            raise TypeError(
                f"parameter-server trees must be f32/bf16/f16 (exact in the "
                f"f32 wire format); got {a.dtype} — cast explicitly if the "
                f"precision loss is intended")
    spec = TreeSpec(treedef, [a.shape for a in arrs],
                    [a.dtype for a in arrs])
    if not arrs:
        return np.zeros((0,), np.float32), spec
    flat = np.concatenate([a.astype(np.float32).reshape(-1) for a in arrs])
    return np.ascontiguousarray(flat, np.float32), spec


def unflatten_f32(spec: TreeSpec, flat: np.ndarray) -> PyTree:
    out = []
    off = 0
    for shape, size, dtype in zip(spec.shapes, spec.sizes, spec.dtypes):
        out.append(flat[off:off + size].reshape(shape).astype(dtype))
        off += size
    return jax.tree.unflatten(spec.treedef, out)

"""Pytree <-> flat float32 vector utilities (the PS operates on flat shards,
as the reference's parameterserver did on flattened parameter tensors)."""

from __future__ import annotations

from typing import Any, List, Tuple

import jax
import numpy as np

PyTree = Any


class TreeSpec:
    def __init__(self, treedef, shapes: List[Tuple[int, ...]],
                 dtypes: List[np.dtype]):
        self.treedef = treedef
        self.shapes = shapes
        self.dtypes = dtypes
        self.sizes = [int(np.prod(s)) for s in shapes]
        self.total = int(sum(self.sizes))


def flatten_f32(tree: PyTree) -> Tuple[np.ndarray, TreeSpec]:
    """Flatten a pytree of arrays into one float32 numpy vector."""
    leaves, treedef = jax.tree.flatten(tree)
    arrs = [np.asarray(l) for l in leaves]
    spec = TreeSpec(treedef, [a.shape for a in arrs],
                    [a.dtype for a in arrs])
    if not arrs:
        return np.zeros((0,), np.float32), spec
    flat = np.concatenate([a.astype(np.float32).reshape(-1) for a in arrs])
    return np.ascontiguousarray(flat, np.float32), spec


def unflatten_f32(spec: TreeSpec, flat: np.ndarray) -> PyTree:
    out = []
    off = 0
    for shape, size, dtype in zip(spec.shapes, spec.sizes, spec.dtypes):
        out.append(flat[off:off + size].reshape(shape).astype(dtype))
        off += size
    return jax.tree.unflatten(spec.treedef, out)

"""Simulated-mesh bootstrap: force N CPU devices in one process.

The rebuild's analog of "mpirun -np N on localhost is the fixture"
(SURVEY.md §5).  Shared by the test conftest, examples, and benchmarks so
the platform-forcing quirks live in exactly one place:

- ``XLA_FLAGS`` is read at backend-init time, so appending the forced host
  device count here works even if jax was already imported;
- ``JAX_PLATFORMS`` may have been consumed at import (e.g. a sitecustomize
  pinning a real TPU platform), so the platform is forced via ``jax.config``
  instead of the environment.
"""

from __future__ import annotations

import os
import re


def force_cpu_devices(n: int) -> None:
    """Make this process see at least ``n`` simulated CPU devices.  Must run
    before the first JAX backend use (not merely before import).

    A pre-set count smaller than ``n`` is raised to ``n`` — EXCEPT under the
    multi-process launcher (``TORCHMPI_TPU_COORDINATOR`` set), where the
    per-process device count is deliberate topology (nproc x devices_per_proc
    = global) and must not be clobbered."""
    flags = os.environ.get("XLA_FLAGS", "")
    m = re.search(r"--xla_force_host_platform_device_count=(\d+)", flags)
    if m is None:
        os.environ["XLA_FLAGS"] = (
            flags + f" --xla_force_host_platform_device_count={n}"
        ).strip()
    elif (int(m.group(1)) < n
          and "TORCHMPI_TPU_COORDINATOR" not in os.environ):
        os.environ["XLA_FLAGS"] = flags.replace(
            m.group(0), f"--xla_force_host_platform_device_count={n}")
    import jax

    jax.config.update("jax_platforms", "cpu")

"""Structured metrics & timing (SURVEY.md §6.5: the reference had print-only
observability; the BASELINE metrics demand per-step structure).

Platform note: on relay-tunneled TPU platforms ``block_until_ready`` can
return before real device execution completes, so :func:`fence` synchronizes
with a one-element device->host readback — the only reliable fence observed
on this environment (and harmless elsewhere).
"""

from __future__ import annotations

import json
import sys
import time
from typing import Any, Dict, List, Optional

import jax
import numpy as np


def fence(x) -> None:
    """Hard synchronization: force a readback of one element of ``x``."""
    leaf = jax.tree.leaves(x)[0]
    np.asarray(jax.device_get(leaf.ravel()[0] if leaf.ndim else leaf))


# Per-round seconds/iter of the most recent timed() call, fastest first
# is NOT applied — this is the raw chronological spread, so a consumer
# can audit how far min-of-rounds sits from the mean (ADVICE r3: the
# min-selection headline must leave the spread on the record).  Kept for
# backward compatibility; new code should read TimedResult.round_times.
last_round_times: List[float] = []


class TimedResult(float):
    """Structured result of :func:`timed`.

    IS a float (min-of-rounds seconds/iter) so every existing consumer
    keeps working, and carries the full per-round spread:

    - ``round_times``  chronological seconds/iter of each round
    - ``median``       median of the rounds (the autotune scoring rule)
    - ``jitter``       half the inter-quartile range — the scale a knob
                       delta must clear to be more than noise
    """

    __slots__ = ("round_times", "median", "jitter")

    def __new__(cls, round_times: List[float]) -> "TimedResult":
        ts = list(round_times)
        self = super().__new__(cls, min(ts))
        s = sorted(ts)
        n = len(s)
        self.round_times = ts
        self.median = (s[n // 2] if n % 2
                       else 0.5 * (s[n // 2 - 1] + s[n // 2]))
        self.jitter = (0.5 * (s[(3 * n) // 4] - s[n // 4]) if n >= 4
                       else 0.5 * (s[-1] - s[0]))
        return self


def timed(step, iters: int, fence=fence, rounds: int = 3) -> TimedResult:
    """Seconds per iteration of ``step``: one warm/compile call, then
    ``rounds`` fenced timing rounds of ``iters`` dispatches, returned as
    a :class:`TimedResult` — a float equal to the FASTEST round, with
    the median/jitter/per-round spread attached.

    Min-of-rounds is load-bearing on the relay platform: the first
    post-compile round can run ~100x slower than steady state (measured
    2026-07-30: ~600-1100 ms/step settling to ~7 ms) even after a fenced
    warmup call, so a single timing pass understates throughput 2-3x.
    The per-round times of the last call are also published in
    ``last_round_times`` (chronological, backward compat).  The shared
    harness behind bench.py, the scripts/ sweeps, and the online
    collective autoselector (``torchmpi_tpu.tuning``)."""
    out = step()
    fence(out)
    del last_round_times[:]
    for _ in range(max(1, rounds)):
        t0 = time.perf_counter()
        for _ in range(iters):
            out = step()
        fence(out)
        last_round_times.append((time.perf_counter() - t0) / iters)
    return TimedResult(last_round_times)


def chained(fn, depth: int = 4):
    """One jit program running ``depth`` dependent invocations of
    ``fn(x, *rest) -> y`` with ``y`` fed back as ``x`` — divide the
    measured time by ``depth`` for the per-invocation figure.

    The relay platform imposes a ~7 ms PER-DISPATCH floor (real TPU
    dispatch is ~10 us), larger than many kernels: single-call timings
    put the floor in both sides of every ratio.  Inside one program the
    floor is paid once, and the data dependence stops CSE from
    collapsing the identical calls (ops whose output cannot feed their
    input must rotate an operand instead — see bench.py stage C2).
    Shared by bench.py stage C and scripts/flash_sweep.py."""
    import jax

    @jax.jit
    def run(x, *rest):
        for _ in range(depth):
            x = fn(x, *rest).astype(x.dtype)
        return x

    return run


class Timer:
    """Wall-clock step timer with warmup and fenced boundaries."""

    def __init__(self):
        self._t0: Optional[float] = None
        self.steps = 0

    def start(self, fence_on=None):
        if fence_on is not None:
            fence(fence_on)
        self._t0 = time.time()
        self.steps = 0

    def tick(self):
        self.steps += 1

    def stop(self, fence_on=None) -> float:
        if fence_on is not None:
            fence(fence_on)
        assert self._t0 is not None
        return time.time() - self._t0


class MetricsLogger:
    """Per-step metrics as JSONL (img/s/chip, step time, achieved GB/s).

    A thin wrapper over the observability registry: when
    ``torchmpi_tpu.obs`` is active (``Config.obs != "off"``) every
    record is also counted there (``tm_log_records_total{logger=...}``)
    so a telemetry dump shows how much step-log traffic each stream
    produced.  The lookup goes through ``sys.modules`` — a process that
    never enabled obs never imports it (the off-path discipline)."""

    def __init__(self, path: Optional[str] = None, name: str = "metrics"):
        self.path = path
        self.name = name
        self.records: List[Dict[str, Any]] = []

    def log(self, **kw) -> None:
        rec = {"t": time.time(), **kw}
        self.records.append(rec)
        if self.path:
            with open(self.path, "a") as f:
                f.write(json.dumps(rec) + "\n")
        obs = sys.modules.get("torchmpi_tpu.obs")
        if obs is not None and obs.active():
            obs.record_log(self.name)


def allreduce_bus_bandwidth(nbytes: int, n_devices: int,
                            seconds: float) -> float:
    """Effective bus bandwidth GB/s, the reference's benchmark metric:
    algbw = size/time; busbw = algbw * 2(n-1)/n (ring lower bound)."""
    if seconds <= 0 or n_devices <= 1:
        return 0.0
    algbw = nbytes / seconds
    return algbw * 2 * (n_devices - 1) / n_devices / 1e9

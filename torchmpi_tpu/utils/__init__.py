"""Utilities: synthetic datasets, metrics, checkpointing, tracing."""

"""Forward-compatibility shims for older jax releases.

The codebase targets the modern jax surface (``jax.shard_map`` taking
``check_vma=``).  On jax 0.4.x that API lives at
``jax.experimental.shard_map.shard_map`` and the kwarg is spelled
``check_rep=``.  :func:`install` backfills the modern name onto the
``jax`` module itself so every call site — library, tests, examples,
including plain ``from jax import shard_map`` — works unchanged on
either version.  On a jax that already has ``jax.shard_map`` this is a
no-op.
"""

from __future__ import annotations

import functools

import jax


def install() -> None:
    """Backfill missing modern names onto ``jax`` (idempotent)."""
    if not hasattr(jax, "shard_map"):
        from jax.experimental import shard_map as _sm

        _orig = _sm.shard_map

        @functools.wraps(_orig)
        def shard_map(f, mesh=None, in_specs=None, out_specs=None, *,
                      check_vma=None, check_rep=None, **kw):
            if check_rep is None:
                check_rep = True if check_vma is None else check_vma
            return _orig(f, mesh=mesh, in_specs=in_specs,
                         out_specs=out_specs, check_rep=check_rep, **kw)

        jax.shard_map = shard_map

    from jax import lax

    if not hasattr(lax, "axis_size"):
        # psum of a Python literal constant-folds to the static axis size
        # (the long-standing idiom lax.axis_size formalized).
        def axis_size(axis_name):
            return lax.psum(1, axis_name)

        lax.axis_size = axis_size

    # ``jax.export`` is a real submodule on 0.4.x but is lazily gated:
    # plain ``import jax`` leaves the attribute unset and
    # ``jax.export.export(...)`` dies with a cryptic AttributeError.
    # Importing the submodule once makes the modern spelling work.
    try:
        # importlib, not ``import jax.export``: a plain import statement
        # would make ``jax`` a local name for this whole function body.
        import importlib

        importlib.import_module("jax.export")
    except ImportError:
        pass

    # Pallas-TPU renames: the kernels here use the modern spellings
    # (``CompilerParams``, ``MemorySpace``); 0.4.x only has the
    # TPU-prefixed ones.  ``InterpretParams`` (the modern interpreter
    # with race detection / RDMA simulation) has NO 0.4.x analog and is
    # deliberately NOT backfilled — call sites feature-detect it and
    # fall back to the boolean ``interpret=True`` interpreter, and
    # tests that need the modern interpreter's semantics skip.
    try:
        from jax.experimental.pallas import tpu as _pltpu

        if not hasattr(_pltpu, "CompilerParams") and \
                hasattr(_pltpu, "TPUCompilerParams"):
            _pltpu.CompilerParams = _pltpu.TPUCompilerParams
        if not hasattr(_pltpu, "MemorySpace") and \
                hasattr(_pltpu, "TPUMemorySpace"):
            class _CompatMemorySpace:
                """Modern ``pltpu.MemorySpace`` names on 0.4.x.  HBM
                maps to ANY — the 0.4.x spelling of off-VMEM scratch."""

                ANY = _pltpu.TPUMemorySpace.ANY
                VMEM = _pltpu.TPUMemorySpace.VMEM
                SMEM = _pltpu.TPUMemorySpace.SMEM
                SEMAPHORE = _pltpu.TPUMemorySpace.SEMAPHORE
                HBM = _pltpu.TPUMemorySpace.ANY

            _pltpu.MemorySpace = _CompatMemorySpace
    except ImportError:
        pass

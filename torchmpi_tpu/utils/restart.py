"""Checkpoint-restart training driver.

The reference had NO elasticity: an MPI rank failure aborted the job
(SURVEY.md §6.3).  The rebuild keeps that gang-scheduled model for the SPMD
side by design — a slice fails as a unit — so recovery is
checkpoint-restart, and this module makes the restart loop a library
primitive instead of an ops runbook: run a step function with periodic
checkpoints, and on a crash restore the latest checkpoint and keep going
(replaying the few steps since the last save — exact for deterministic
steps, the SPMD common case).

Complements the PS side's live elasticity (heartbeats + worker loss,
``examples/downpour_elastic.py``), which is where surviving failure
WITHOUT a restart is actually possible.

Fault-layer integration (docs/FAULTS.md): a ``PeerTimeoutError`` from
``torchmpi_tpu.faults`` — a peer the resilient-dispatch layer detected
dead within its site deadline — routes through the ``on_peer_timeout``
callback and the same restore path, so a wedged gang checkpoint-restores
instead of waiting for a watchdog kill.  The check is by type identity
through ``sys.modules``: this module never imports ``faults`` (the
off-mode import discipline).
"""

from __future__ import annotations

import json
import os
import sys
from typing import Any, Callable, Dict, Optional, Tuple

import jax

from . import checkpoint

PyTree = Any


def _health_path(directory: str) -> str:
    return os.path.join(directory,
                        f"health_p{jax.process_index()}.json")


def _save_health(directory: str) -> None:
    """Snapshot the armed fault layer's per-peer health ledger next to
    the checkpoints (sys.modules lookup — recovery never imports the
    fault layer), so peer health survives a process-level restart
    instead of resetting every peer to ``healthy`` and re-burning the
    suspect->dead escalation on a peer that was already dead.
    Best-effort: telemetry-grade state must never fail a save."""
    mod = sys.modules.get("torchmpi_tpu.faults")
    if mod is None or not mod.active():
        return
    try:
        path = _health_path(directory)
        tmp = path + ".tmp"
        with open(tmp, "w") as f:
            json.dump(mod.ledger().to_dict(), f, sort_keys=True)
        os.replace(tmp, path)
    except Exception:  # noqa: BLE001 — evidence, not correctness
        pass


def _load_health(directory: str) -> None:
    """Rehydrate the armed ledger from the last :func:`_save_health`
    snapshot, if one exists (the restore half of the same seam)."""
    mod = sys.modules.get("torchmpi_tpu.faults")
    if mod is None or not mod.active():
        return
    try:
        path = _health_path(directory)
        if os.path.exists(path):
            with open(path) as f:
                mod.ledger().restore(json.load(f))
    except Exception:  # noqa: BLE001 — a torn snapshot is just absent
        pass


def _is_peer_timeout(e: BaseException) -> bool:
    """Is ``e`` a ``faults.PeerTimeoutError`` — or the watchdog's
    ``CollectiveHangError`` (a stalled collective the watchdog broke;
    docs/WATCHDOG.md), which takes the same detected-dead-peer restore
    path?  Checked via sys.modules: if neither layer was ever armed,
    the classes do not exist and no exception can be one."""
    mod = sys.modules.get("torchmpi_tpu.faults.policy")
    if mod is not None and isinstance(e, mod.PeerTimeoutError):
        return True
    wd = sys.modules.get("torchmpi_tpu.watchdog")
    return wd is not None and isinstance(e, wd.CollectiveHangError)


def _obs_record(event: str, step: int) -> None:
    """Log a recovery decision through obs when it is active (sys.modules
    lookup — recovery must not import the telemetry it reports to)."""
    mod = sys.modules.get("torchmpi_tpu.obs")
    try:
        if mod is not None and mod.active():
            mod.record_restart(event, step)
    except Exception:  # noqa: BLE001 — telemetry never blocks recovery
        pass


def _ram_rung(template: PyTree, *, min_step: int = 0,
              step: Optional[int] = None
              ) -> Optional[Tuple[PyTree, int]]:
    """The hot-state RAM rung (docs/HOTSTATE.md), consulted FIRST by
    :func:`recover` when the user armed it — via sys.modules, the same
    off-mode import discipline as the fault/telemetry seams: a session
    that never enabled ``torchmpi_tpu.hotstate`` never imports it and
    this is one dict lookup.  Returns a digest-verified ``(state,
    step)`` or None (stale/missing/corrupt — the tier counts its own
    ``tm_hotstate_fallback_disk_total`` and the ladder steps down to
    the disk buddies).  Best-effort by construction: a broken RAM tier
    must never block a disk recovery."""
    mod = sys.modules.get("torchmpi_tpu.hotstate")
    if mod is None or not mod.active():
        return None
    try:
        return mod.offer_restore(template, min_step=min_step,
                                 step=step)
    except Exception:  # noqa: BLE001 — a rung, not a requirement
        return None


def _fsync_verify(directory: str, step: int) -> None:
    """Durability check on the step recovery settled on: re-open the
    local npz read-only (it must still be readable AFTER the restore
    that just parsed it — a disappearing file means the directory is
    lying to us) and fsync the directory so the atomic rename that
    produced the file is itself durable before training resumes on top
    of it.  Best-effort on filesystems without directory fsync."""
    path = os.path.join(directory,
                        f"ckpt_{step}_p{jax.process_index()}.npz")
    with open(path, "rb") as f:
        if not f.read(1):
            raise OSError(f"checkpoint {path} is empty after restore")
    try:
        fd = os.open(directory, os.O_RDONLY)
        try:
            os.fsync(fd)
        finally:
            os.close(fd)
    except OSError:
        pass


def attach_ef_residuals(state: Dict[str, Any], *,
                        params_key: str = "params",
                        axis_names=None, mesh=None,
                        n_buckets: Optional[int] = None,
                        key: str = "ef_residuals") -> Dict[str, Any]:
    """Bundle zero-initialized DCN error-feedback residual state into a
    checkpointable train-state dict (docs/HIERARCHICAL.md: "checkpoint
    residuals with the optimizer state").

    The quantized-DCN gradient paths thread a persistent per-bucket
    residual accumulator (``gradsync.synchronize_gradients(residuals=
    ...)``); dropping it on restore silently re-applies one step's
    accumulated error on every restart replay (the at-least-once
    hazard).  This helper is the restart-driver seam that closes the
    loop: call it inside ``init_fn`` so the residuals ride every
    :func:`run_with_restarts` checkpoint exactly like optimizer state —
    a fresh start zero-initializes them, a recovery restores the saved
    accumulators bitwise (round-trip asserted in tests/test_restart.py).

    ``state[params_key]`` is the gradient-shaped template the bucket
    layout derives from; ``axis_names``/``mesh``/``n_buckets`` pass
    through to :func:`~torchmpi_tpu.parallel.gradsync.
    init_dcn_residuals`.  Returns a NEW dict with the residual list
    under ``key``; the step function threads ``state[key]`` through the
    EF sync and stores the returned state back.
    """
    from ..parallel import gradsync

    if params_key not in state:
        raise KeyError(
            f"state has no {params_key!r} entry to derive the residual "
            f"bucket layout from (keys: {sorted(state)})")
    if key in state:
        raise ValueError(f"state already has a {key!r} entry")
    out = dict(state)
    out[key] = gradsync.init_dcn_residuals(
        state[params_key], axis_names, mesh=mesh, n_buckets=n_buckets)
    return out


def recover(init_fn: Callable[[], PyTree], directory: str,
            template: PyTree, *, participants: Optional[int] = None,
            agree: Optional[Callable[[int], int]] = None
            ) -> Tuple[PyTree, int]:
    """Restore the newest checkpoint all participants can agree on.

    Single-participant: the newest locally-restorable step, walking
    backwards past unreadable ones (atomic saves make those rare, but
    an older good step must win over a bad newer file — never a hard
    stop).  The settled-on step is fsync-verified and logged (via obs
    when active) so post-mortems can see WHICH step a recovery
    resumed from, not just that one happened.

    Multi-host (the gang-scheduled restart path): a crash between
    per-process ``save()`` calls can land step N on some hosts only,
    and replicas silently resuming from different steps diverge and
    desync collectives.  So the hosts run an agreement loop in which
    EVERY branch decision is a function of globally-agreed values — no
    host can raise, restore, or fall back alone: propose the newest
    local step under the ceiling, agree on the minimum, all try to
    restore exactly that step, agree on a success flag; any failure
    anywhere lowers the ceiling for everyone and the loop retries,
    degrading to a collective fresh start when no common restorable
    step exists.  Requires all participants to call :func:`recover`
    together; a failure on only a subset is not survivable by any
    in-band protocol.

    ``participants`` defaults to ``jax.process_count()`` and ``agree``
    to the full-gang :func:`checkpoint.agree_min_step`; the elastic
    driver (``torchmpi_tpu/elastic.py``) passes the surviving process
    count and a survivors-only board agreement instead — the full-gang
    collective would hang forever on the member whose death is exactly
    what recovery is recovering from.  Returns ``(state, next_step)``.

    When the hot-state tier is armed (docs/HOTSTATE.md) the ladder
    grows a rung ABOVE the disk walk: a digest-verified RAM replica at
    or past the newest disk step wins (no file I/O, no replay of the
    save interval); in the multi-host protocol the RAM step simply
    joins the candidate proposal, so the agreement loop stays the
    single source of truth about which step the gang stands on.
    """
    if participants is None:
        participants = jax.process_count()
    if agree is None:
        agree = checkpoint.agree_min_step

    def settled(state, step, source="disk"):
        if step > 0 and source == "disk":
            # A RAM restore has no checkpoint file at its step to
            # re-open — its durability story is the digest verify the
            # hot tier already ran (and the disk tier underneath it).
            _fsync_verify(directory, step)
        # Pin the settled step against retention pruning: the step a
        # recovery (or a guard rewind) agreed to stand on must survive
        # a keep-last-K chaos soak (docs/CHECKPOINT.md).
        checkpoint.protect_step(directory, step)
        _obs_record("recovered" if step > 0 else "fresh_start", step)
        return state, step

    steps_avail = [s for s in checkpoint.available_steps(directory)
                   if s > 0]
    if participants <= 1:
        ram = _ram_rung(template,
                        min_step=steps_avail[-1] if steps_avail else 1)
        if ram is not None:
            return settled(ram[0], ram[1], source="ram")
        for step in reversed(steps_avail):
            try:
                return settled(checkpoint.restore(directory, template,
                                                  step=step), step)
            except Exception as e:  # noqa: BLE001 — fall back to older,
                # recording WHY this step was rejected (corrupt vs
                # missing vs template mismatch) so a post-mortem can
                # see what the walk-back walked past, not just where
                # it landed.
                checkpoint._record_walkback(step, e)
                continue
        if steps_avail:
            # Disk fully failed: a stale-but-verified RAM replica
            # still beats a fresh start (last rung before step 0).
            ram = _ram_rung(template)
            if ram is not None:
                return settled(ram[0], ram[1], source="ram")
        return settled(init_fn(), 0)
    hs = sys.modules.get("torchmpi_tpu.hotstate")
    ram_step = 0
    if hs is not None and hs.active():
        try:
            ram_step = hs.replicator().latest_step()
        except Exception:  # noqa: BLE001 — a rung, not a requirement
            ram_step = 0
    ceiling = None
    while True:
        cand = next((s for s in reversed(steps_avail)
                     if ceiling is None or s <= ceiling), 0)
        if ram_step and (ceiling is None or ram_step <= ceiling):
            cand = max(cand, ram_step)
        agreed = agree(cand)
        if agreed <= 0:
            return settled(init_fn(), 0)  # collectively: nothing common
        state, ok, source = None, 1, "disk"
        ram = (_ram_rung(template, step=agreed)
               if ram_step and agreed <= ram_step else None)
        if ram is not None:
            state, source = ram[0], "ram"
        else:
            try:
                state = checkpoint.restore(directory, template,
                                           step=agreed)
            except Exception as e:  # noqa: BLE001 — resolved collectively
                checkpoint._record_walkback(agreed, e)
                ok = 0
        if agree(ok):
            return settled(state, agreed, source=source)
        ceiling = agreed - 1  # someone failed: walk back TOGETHER
        ram_step = 0  # a failed round demotes the RAM rung: disk only


def run_with_restarts(
    init_fn: Callable[[], PyTree],
    step_fn: Callable[[PyTree, int], PyTree],
    *,
    steps: int,
    directory: str,
    save_every: int = 10,
    max_restarts: int = 3,
    on_restart: Optional[Callable[[int, BaseException], None]] = None,
    on_peer_timeout: Optional[Callable[[int, BaseException], None]] = None,
) -> Tuple[PyTree, Dict[str, int]]:
    """Run ``steps`` calls of ``step_fn(state, i) -> state`` with
    checkpoint-restart recovery.

    ``init_fn()`` builds the initial state (and the restore template).  A
    checkpoint is written every ``save_every`` completed steps and at the
    end.  If ``step_fn`` raises, the latest checkpoint is restored and
    training resumes from the step after it — up to ``max_restarts`` times,
    after which the last exception propagates.  An existing checkpoint in
    ``directory`` is picked up on entry, so re-running the whole PROCESS
    after a fatal crash also resumes (process-level restart, the
    gang-scheduled recovery path).

    A ``faults.PeerTimeoutError`` (detected-dead peer) takes the same
    restore path but notifies ``on_peer_timeout`` instead of
    ``on_restart`` — the hook where an orchestrator re-admits or
    replaces the peer before the replay resumes.

    Returns ``(final_state, info)`` with ``info = {"restarts": r,
    "restarts_used": r, "steps_run": n, "recovered_step": s}``:
    ``steps_run`` counts executed step calls including replays,
    ``restarts_used`` is the restart budget consumed (assertable by
    chaos tests; ``"restarts"`` is the same number under its legacy
    name, kept for existing callers), ``recovered_step`` the step the
    LAST recovery settled on (0 when none, or when recovery fell back
    to a fresh start).
    """
    if steps < 0:
        raise ValueError(f"steps must be >= 0, got {steps}")
    template = init_fn()
    _load_health(directory)

    state, i = recover(init_fn, directory, template)
    recovered_step = i
    restarts = 0
    steps_run = 0
    while i < steps:
        try:
            state = step_fn(state, i)
            steps_run += 1
            i += 1
            if i % save_every == 0 or i == steps:
                checkpoint.save(directory, state, step=i)
                _save_health(directory)
        except KeyboardInterrupt:
            raise
        except BaseException as e:  # noqa: BLE001 — the restart loop IS
            # the handler: restore-and-replay or re-raise after budget.
            restarts += 1
            # The failure itself is health evidence (the ledger just
            # counted it) — snapshot BEFORE recovery so a process-level
            # restart sees the peer's streak, not a clean slate.
            _save_health(directory)
            if _is_peer_timeout(e):
                # Detected-dead peer: checkpoint-restore instead of a
                # watchdog kill.  Consumes restart budget like any other
                # failure (a peer that stays dead must not loop forever).
                _obs_record("peer_timeout", i)
                if on_peer_timeout is not None:
                    on_peer_timeout(restarts, e)
            elif on_restart is not None:
                on_restart(restarts, e)
            if restarts > max_restarts:
                raise
            state, i = recover(init_fn, directory, template)
            recovered_step = i
    return state, {"restarts": restarts, "restarts_used": restarts,
                   "steps_run": steps_run,
                   "recovered_step": recovered_step}

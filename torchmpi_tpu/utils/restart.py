"""Checkpoint-restart training driver.

The reference had NO elasticity: an MPI rank failure aborted the job
(SURVEY.md §6.3).  The rebuild keeps that gang-scheduled model for the SPMD
side by design — a slice fails as a unit — so recovery is
checkpoint-restart, and this module makes the restart loop a library
primitive instead of an ops runbook: run a step function with periodic
checkpoints, and on a crash restore the latest checkpoint and keep going
(replaying the few steps since the last save — exact for deterministic
steps, the SPMD common case).

Complements the PS side's live elasticity (heartbeats + worker loss,
``examples/downpour_elastic.py``), which is where surviving failure
WITHOUT a restart is actually possible.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, Optional, Tuple

import jax

from . import checkpoint

PyTree = Any


def run_with_restarts(
    init_fn: Callable[[], PyTree],
    step_fn: Callable[[PyTree, int], PyTree],
    *,
    steps: int,
    directory: str,
    save_every: int = 10,
    max_restarts: int = 3,
    on_restart: Optional[Callable[[int, BaseException], None]] = None,
) -> Tuple[PyTree, Dict[str, int]]:
    """Run ``steps`` calls of ``step_fn(state, i) -> state`` with
    checkpoint-restart recovery.

    ``init_fn()`` builds the initial state (and the restore template).  A
    checkpoint is written every ``save_every`` completed steps and at the
    end.  If ``step_fn`` raises, the latest checkpoint is restored and
    training resumes from the step after it — up to ``max_restarts`` times,
    after which the last exception propagates.  An existing checkpoint in
    ``directory`` is picked up on entry, so re-running the whole PROCESS
    after a fatal crash also resumes (process-level restart, the
    gang-scheduled recovery path).

    Returns ``(final_state, info)`` with ``info = {"restarts": r,
    "steps_run": n}`` (``steps_run`` counts executed step calls including
    replays).
    """
    if steps < 0:
        raise ValueError(f"steps must be >= 0, got {steps}")
    template = init_fn()

    def recover():
        """Restore the newest checkpoint all processes can agree on.

        Single-process: the newest locally-restorable step, walking
        backwards past unreadable ones (atomic saves make those rare, but
        an older good step must win over a bad newer file — never a hard
        stop).

        Multi-host (the gang-scheduled restart path): a crash between
        per-process ``save()`` calls can land step N on some hosts only,
        and replicas silently resuming from different steps diverge and
        desync collectives.  So the hosts run an agreement loop in which
        EVERY branch decision is a function of globally-allgathered
        values — no host can raise, restore, or fall back alone:
        propose the newest local step under the ceiling, agree on the
        minimum, all try to restore exactly that step, allgather a
        success flag; any failure anywhere lowers the ceiling for
        everyone and the loop retries, degrading to a collective fresh
        start when no common restorable step exists.  Requires all
        processes to call ``recover()`` together — the gang-failure model
        this module documents (an SPMD failure fails the slice as a
        unit); a failure on only a subset of hosts is not survivable by
        any in-band protocol.  Returns (state, next_step)."""
        steps_avail = [s for s in checkpoint.available_steps(directory)
                       if s > 0]
        if jax.process_count() <= 1:
            for step in reversed(steps_avail):
                try:
                    return checkpoint.restore(directory, template,
                                              step=step), step
                except Exception:  # noqa: BLE001 — fall back to older
                    continue
            return init_fn(), 0
        ceiling = None
        while True:
            cand = next((s for s in reversed(steps_avail)
                         if ceiling is None or s <= ceiling), 0)
            agreed = checkpoint.agree_min_step(cand)
            if agreed <= 0:
                return init_fn(), 0  # collectively: nothing in common
            state, ok = None, 1
            try:
                state = checkpoint.restore(directory, template,
                                           step=agreed)
            except Exception:  # noqa: BLE001 — resolved collectively
                ok = 0
            if checkpoint.agree_min_step(ok):
                return state, agreed
            ceiling = agreed - 1  # someone failed: walk back TOGETHER

    state, i = recover()
    restarts = 0
    steps_run = 0
    while i < steps:
        try:
            state = step_fn(state, i)
            steps_run += 1
            i += 1
            if i % save_every == 0 or i == steps:
                checkpoint.save(directory, state, step=i)
        except KeyboardInterrupt:
            raise
        except BaseException as e:  # noqa: BLE001 — the restart loop IS
            # the handler: restore-and-replay or re-raise after budget.
            restarts += 1
            if on_restart is not None:
                on_restart(restarts, e)
            if restarts > max_restarts:
                raise
            state, i = recover()
    return state, {"restarts": restarts, "steps_run": steps_run}

"""Host->device input pipeline with background prefetch.

The reference delegated input loading to Torch's host-side dataset loop
(SURVEY.md §3 C15 — examples drove `nn` modules from Lua-side batches); the
TPU-native equivalent is an async staging pipeline: while the device runs
step N, a background thread stages batch N+1's host arrays onto the mesh
with the training sharding, so the (slow — ~470 MB/s on relay-tunneled
hosts, per docs/ROUND1_NOTES.md) host->device copy overlaps compute instead
of serializing with it.

Usage::

    it = prefetch_to_mesh(batch_iter, mesh, P(("dcn", "ici")), depth=2)
    for xb, yb in it:          # already device-resident, sharded
        state = step(state, xb, yb)

Works on any pytree of numpy arrays per batch.  ``depth`` bounds staged
batches (device memory = depth x batch bytes).  The thread dies with the
iterator (daemon + sentinel), and exceptions in the source iterator re-raise
at the consumer.
"""

from __future__ import annotations

import queue
import threading
import weakref
from typing import Any, Iterable, Iterator, Optional

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec

PyTree = Any


def prefetch_to_mesh(batches: Iterable[PyTree], mesh: Mesh,
                     spec: PartitionSpec, *, depth: int = 2,
                     specs: Optional[PyTree] = None) -> Iterator[PyTree]:
    """Iterate device-resident, mesh-sharded copies of ``batches``.

    ``spec`` shards every leaf; pass ``specs`` (a pytree of PartitionSpec
    matching the batch structure) for per-leaf shardings instead.
    """
    if depth < 1:
        raise ValueError(f"prefetch depth must be >= 1, got {depth}")

    def put(batch: PyTree) -> PyTree:
        if specs is not None:
            return jax.tree.map(
                lambda leaf, s: jax.device_put(
                    leaf, NamedSharding(mesh, s)),
                batch, specs,
                is_leaf=lambda x: x is None)
        sharding = NamedSharding(mesh, spec)
        return jax.tree.map(lambda leaf: jax.device_put(leaf, sharding),
                            batch)

    q: "queue.Queue" = queue.Queue(maxsize=depth)
    stop = threading.Event()

    class _End:
        pass

    class _Error:
        def __init__(self, exc: BaseException):
            self.exc = exc

    def _enqueue(item) -> bool:
        # Bounded put that honors abandonment: an early-closed consumer
        # sets `stop` and the producer exits instead of blocking forever
        # holding device buffers.
        while not stop.is_set():
            try:
                q.put(item, timeout=0.1)
                return True
            except queue.Full:
                continue
        return False

    def producer():
        try:
            for batch in batches:
                if not _enqueue(put(batch)):
                    return
        except BaseException as e:  # noqa: BLE001 — re-raised at consumer
            _enqueue(_Error(e))
            return
        _enqueue(_End())

    # Plain function, not a generator: depth validation fails at the call
    # site and prefetching starts immediately, not at the first next().
    th = threading.Thread(target=producer, daemon=True,
                          name="torchmpi-prefetch")
    th.start()

    def _abandon():
        # Release the producer and drop staged device buffers.  Idempotent:
        # runs from the generator's finally AND from its GC finalizer.
        stop.set()
        try:
            while True:
                q.get_nowait()
        except queue.Empty:
            pass

    def consume() -> Iterator[PyTree]:
        try:
            while True:
                item = q.get()
                if isinstance(item, _End):
                    return
                if isinstance(item, _Error):
                    raise item.exc
                yield item
        finally:
            # Early close (break / exception / GC of the iterator).
            _abandon()

    gen = consume()
    # A never-started generator skips its finally on GC (close() is a no-op
    # before the first next()), which would leave the producer spinning and
    # `depth` batches pinned on device forever.  The finalizer covers that
    # path; it must not reference `gen` itself.
    weakref.finalize(gen, _abandon)
    return gen

"""Persistent, versioned collective-plan database.

One JSON file holds every measured decision for a machine (or a fleet,
when plans are merged with ``scripts/plan_tool.py``), keyed by the
topology fingerprint of :mod:`torchmpi_tpu.tuning.fingerprint`.  The
cache makes the same amortize-the-fixed-cost move as
``utils/compilecache.py`` makes for XLA compiles: the measurement is
paid once per (op, size bucket, mesh, platform) and every later process
reads the answer from disk.

Durability rules (a tuning cache must never take down a training job):

- ``load`` NEVER raises: a missing, corrupt, or version-mismatched file
  yields an empty cache with ``degraded_reason`` set, and the caller
  falls back to static selection.
- ``save`` is atomic (tmp file + ``os.replace``) and merges with
  whatever is on disk first, so concurrent writers union their entries
  instead of clobbering each other; on conflict the newer entry wins.
- ``save`` returns False instead of raising on unwritable paths.
"""

from __future__ import annotations

import dataclasses
import json
import os
import tempfile
import time
from typing import Callable, Dict, Optional

PLAN_VERSION = 1

# Default location, repo-relative like compilecache.DEFAULT_DIR: plans
# are per-machine artifacts banked next to the code that replays them.
DEFAULT_PLAN_DIR = os.path.join(os.path.dirname(os.path.dirname(
    os.path.dirname(os.path.abspath(__file__)))), ".tuning_plans")
DEFAULT_PLAN_PATH = os.path.join(DEFAULT_PLAN_DIR, "plans.json")


def resolve_plan_path(path: Optional[str] = None) -> str:
    """Explicit arg > ``TORCHMPI_TPU_TUNING_PLAN`` env > default."""
    return (path
            or os.environ.get("TORCHMPI_TPU_TUNING_PLAN")
            or DEFAULT_PLAN_PATH)


@dataclasses.dataclass
class PlanEntry:
    """One measured decision: the winning backend plus its evidence."""

    backend: str
    # Where the decision came from: "measured" (online autoselect),
    # "autotune" (offline benchmarks/autotune.py), "merged", "manual".
    source: str = "measured"
    # candidate -> median ms / jitter ms of the measurement that decided.
    median_ms: Optional[Dict[str, float]] = None
    jitter_ms: Optional[Dict[str, float]] = None
    rounds: int = 0
    timestamp: float = 0.0

    def to_json(self) -> dict:
        d = dataclasses.asdict(self)
        return {k: v for k, v in d.items() if v not in (None,)}

    @staticmethod
    def from_json(d: dict) -> "PlanEntry":
        if not isinstance(d, dict):
            raise ValueError(f"plan entry is not an object: {d!r}")
        fields = {f.name for f in dataclasses.fields(PlanEntry)}
        kept = {k: v for k, v in d.items() if k in fields}
        if "backend" not in kept or not isinstance(kept["backend"], str):
            raise ValueError(f"plan entry missing backend: {d!r}")
        # Hand-edited / foreign files may carry non-numeric timestamps or
        # rounds; coerce instead of letting a later merge comparison raise
        # (the never-crash contract covers every field, not just backend).
        if not isinstance(kept.get("timestamp", 0.0), (int, float)):
            kept["timestamp"] = 0.0
        if not isinstance(kept.get("rounds", 0), int):
            kept["rounds"] = 0
        if not isinstance(kept.get("source", ""), str):
            kept["source"] = "manual"
        for field in ("median_ms", "jitter_ms"):
            v = kept.get(field)
            if v is None:
                continue
            if not isinstance(v, dict):
                kept[field] = None
                continue
            kept[field] = {str(b): float(ms) for b, ms in v.items()
                           if isinstance(ms, (int, float))}
        return PlanEntry(**kept)


class PlanCache:
    """In-memory view of one plan file; see module docstring for rules."""

    def __init__(self, path: Optional[str] = None) -> None:
        self.path = path
        self.entries: Dict[str, PlanEntry] = {}
        # Non-None when the backing file existed but could not be used
        # (corrupt JSON, wrong version, ...) — the silent-degrade marker.
        self.degraded_reason: Optional[str] = None

    # -- queries ---------------------------------------------------------

    def get(self, key: str) -> Optional[PlanEntry]:
        return self.entries.get(key)

    def put(self, key: str, entry: PlanEntry) -> None:
        if not entry.timestamp:
            entry.timestamp = time.time()
        self.entries[key] = entry

    def __len__(self) -> int:
        return len(self.entries)

    # -- persistence -----------------------------------------------------

    @classmethod
    def load(cls, path: Optional[str] = None) -> "PlanCache":
        """Read ``path`` (resolved via :func:`resolve_plan_path`).

        Never raises: any failure returns an empty cache whose
        ``degraded_reason`` says why, so ``"auto"`` degrades to static
        selection instead of crashing a training job.
        """
        path = resolve_plan_path(path)
        cache = cls(path)
        if not os.path.exists(path):
            return cache
        try:
            with open(path) as f:
                data = json.load(f)
        except (OSError, ValueError) as e:
            cache.degraded_reason = f"unreadable plan file: {e}"
            return cache
        if not isinstance(data, dict):
            cache.degraded_reason = "plan file is not a JSON object"
            return cache
        if data.get("version") != PLAN_VERSION:
            cache.degraded_reason = (
                f"plan version {data.get('version')!r} != {PLAN_VERSION}")
            return cache
        entries = data.get("entries")
        if not isinstance(entries, dict):
            cache.degraded_reason = "plan file has no entries object"
            return cache
        for key, raw in entries.items():
            try:
                cache.entries[key] = PlanEntry.from_json(raw)
            except (TypeError, ValueError):
                # One bad entry must not poison the rest.
                continue
        return cache

    def to_json(self) -> dict:
        return {
            "version": PLAN_VERSION,
            "saved_at": time.time(),
            "entries": {k: e.to_json()
                        for k, e in sorted(self.entries.items())},
        }

    def save(self, path: Optional[str] = None, *,
             merge: bool = True) -> bool:
        """Atomically write the cache; by default merged with the file's
        current contents so concurrent writers keep each other's entries
        (newer timestamp wins a key conflict).  ``merge=False`` replaces
        the file outright — what prune/rewrite tools need, since a merge
        would resurrect the entries just dropped.  Returns False on
        failure (unwritable dir, ...) — persistence is best-effort by
        design.
        """
        path = resolve_plan_path(path or self.path)
        lock_file = None
        try:
            os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
            # Serialize the load-merge-replace against other writers:
            # without the lock, two concurrent savers can each load a
            # snapshot missing the other's new key and the second
            # os.replace clobbers the first.  Best-effort — a platform
            # without flock just degrades to last-writer-wins.
            try:
                import fcntl

                lock_file = open(path + ".lock", "w")
                fcntl.flock(lock_file, fcntl.LOCK_EX)
            except (ImportError, OSError):
                lock_file = None
            if merge:
                merged = PlanCache.load(path)
                if merged.degraded_reason is None:
                    self.merge_from(merged)
            fd, tmp = tempfile.mkstemp(dir=os.path.dirname(path) or ".",
                                       prefix=".plan_tmp_")
            try:
                with os.fdopen(fd, "w") as f:
                    json.dump(self.to_json(), f, indent=1, sort_keys=True)
                os.replace(tmp, path)
            except BaseException:
                try:
                    os.unlink(tmp)
                except OSError:
                    pass
                raise
        except OSError:
            return False
        finally:
            if lock_file is not None:
                try:
                    lock_file.close()  # releases the flock
                except OSError:
                    pass
        self.path = path
        return True

    # -- maintenance (plan_tool.py) --------------------------------------

    def merge_from(self, other: "PlanCache") -> int:
        """Union ``other``'s entries into this cache; on a key conflict
        the newer ``timestamp`` wins.  Returns the number adopted."""
        adopted = 0
        for key, entry in other.entries.items():
            mine = self.entries.get(key)
            if mine is None or entry.timestamp > mine.timestamp:
                self.entries[key] = entry
                adopted += 1
        return adopted

    def prune(self, keep: Callable[[str, PlanEntry], bool]) -> int:
        """Drop entries for which ``keep(key, entry)`` is false; returns
        the number dropped."""
        doomed = [k for k, e in self.entries.items() if not keep(k, e)]
        for k in doomed:
            del self.entries[k]
        return len(doomed)

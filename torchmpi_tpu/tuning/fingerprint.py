"""Topology fingerprints: the key space of the collective plan database.

A plan entry answers "which backend won for THIS situation"; the
fingerprint is what "situation" means: platform, mesh axis shape, op,
dtype, and a log2 size bucket.  Two processes on the same platform and
mesh shape produce identical keys, which is what lets a plan measured
once be reused by every later process (the compilecache move, applied
to backend selection).

Sizes are bucketed to floor(log2(nbytes)) — the granularity at which
backend crossover points actually move (the reference's cutover
constants were powers of two for the same reason), and coarse enough
that a handful of entries covers a training run's gradient sizes.
"""

from __future__ import annotations

from typing import Optional

import numpy as np


def size_bucket(nbytes: int) -> int:
    """floor(log2(nbytes)); sizes of 0/1 byte share bucket 0."""
    return max(0, int(nbytes).bit_length() - 1)


def bucket_bytes(bucket: int) -> int:
    """Lower edge (in bytes) of ``bucket`` — inverse of size_bucket."""
    return 1 << bucket


def mesh_key(mesh, axes=None) -> str:
    """Ordered axis-name:size signature, e.g. ``dcn:2,ici:4``.

    ``axes`` restricts the signature to the axes the collective actually
    spans (in-axis calls over a mesh subset): a decision measured over
    the whole mesh must not be replayed for an axis subset that was
    never measured — different axes, different key, safe plan miss.
    """
    if axes is None:
        return ",".join(f"{a}:{int(s)}" for a, s in mesh.shape.items())
    # Normalize to mesh order so equivalent spans share a key:
    # ("ici", "dcn") and ("dcn", "ici") name the same device group.
    sel = set(axes)
    return ",".join(f"{a}:{int(s)}" for a, s in mesh.shape.items()
                    if a in sel)


def topology(mesh=None, sizes=None, axes=None) -> str:
    """The ``n_dcn x n_ici``-style topology fingerprint of a dispatch:
    the spanned axis extents joined major-to-minor ("2x4" on the classic
    two-level world; "8" flat; "2x2x2" N-D).  This is the compact
    rendering of the same information :func:`mesh_key` puts in every
    plan-database key (axis sizes in mesh order) — the plan DB has been
    topology-keyed since PR 1, and this helper is what makes the
    flat-vs-hierarchical cutover READ as a per-topology decision: it is
    stored on every ``CollectivePlan`` and shown by ``plan_tool.py
    dump-live`` (docs/HIERARCHICAL.md).

    ``sizes`` (explicit extents) wins over ``mesh``; ``axes`` restricts
    a mesh to the spanned subset like :func:`mesh_key`."""
    if sizes:
        return "x".join(str(int(s)) for s in sizes)
    if mesh is not None:
        try:
            if axes is not None:
                sel = set(axes)
                return "x".join(str(int(s)) for a, s in mesh.shape.items()
                                if a in sel)
            return "x".join(str(int(s)) for s in mesh.devices.shape)
        except Exception:  # noqa: BLE001 — a label must never fail a plan
            return ""
    return ""


def platform_of(mesh) -> str:
    try:
        # flatiter indexing: O(1), no device-list materialization on the
        # per-call plan-hit path.
        return mesh.devices.flat[0].platform
    except Exception:  # noqa: BLE001 — degrade to a generic key
        return "unknown"


def fingerprint(op: str, nbytes: int, dtype, mesh,
                platform: Optional[str] = None, axes=None) -> str:
    """The plan-database key for one (op, size, mesh, platform) decision.

    ``nbytes`` is the PER-RANK payload (what the selector's size cutover
    compares against), ``dtype`` anything ``np.dtype`` accepts, ``axes``
    the mesh axes the collective spans (None = the whole mesh — what the
    eager rank-major mode always uses).
    """
    plat = platform if platform is not None else platform_of(mesh)
    return (f"{plat}|{mesh_key(mesh, axes)}|{op}|{np.dtype(dtype).name}"
            f"|b{size_bucket(nbytes)}")

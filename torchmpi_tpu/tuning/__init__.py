"""Tuning subsystem: persistent, topology-keyed collective plans.

TorchMPI's ``collectiveSelector`` picked an implementation from
hand-tuned constants; this package replaces the constants with a
measured, persisted, per-topology plan database:

- :mod:`fingerprint` — the key space: (platform, mesh shape, op, dtype,
  log2 size bucket).
- :mod:`plancache` — the versioned JSON plan DB with atomic writes,
  concurrent-writer merge, and never-crash load semantics.
- :mod:`measure` — the shared noise-gated median measurement discipline
  (also driving ``benchmarks/autotune.py``).
- :mod:`autoselect` — the online ``backend="auto"`` mode: first eager
  call of an uncached key measures, caches, persists; every later call
  (this process or any future one) replays the plan.

See ``docs/TUNING.md`` for the file format and lifecycle.
"""

from . import fingerprint, measure, plancache, autoselect  # noqa: F401
from .fingerprint import fingerprint as make_fingerprint  # noqa: F401
from .fingerprint import size_bucket, bucket_bytes, mesh_key  # noqa: F401
from .plancache import (  # noqa: F401
    PLAN_VERSION,
    DEFAULT_PLAN_PATH,
    PlanCache,
    PlanEntry,
    resolve_plan_path,
)
from .measure import measure as measure_step, noise_gate  # noqa: F401
from .autoselect import (  # noqa: F401
    configure,
    reset,
    is_active,
    plan,
    plan_lookup,
    resolve_eager,
    plan_bucket_bytes,
    decisions,
    set_decision_logger,
    measurement_count,
    reset_measurement_count,
    DEFAULT_BACKEND,
)

__all__ = [
    "fingerprint", "measure", "plancache", "autoselect",
    "make_fingerprint", "size_bucket", "bucket_bytes", "mesh_key",
    "PLAN_VERSION", "DEFAULT_PLAN_PATH", "PlanCache", "PlanEntry",
    "resolve_plan_path", "measure_step", "noise_gate",
    "configure", "reset", "is_active", "plan", "plan_lookup",
    "resolve_eager", "plan_bucket_bytes", "decisions",
    "set_decision_logger",
    "measurement_count", "reset_measurement_count", "DEFAULT_BACKEND",
]

"""Shared measurement discipline for knob/backend selection.

One home for the rules ``benchmarks/autotune.py`` proved out (VERDICT
r3 weak #3: single-trial timings on a ~7 ms-dispatch-floor relay cannot
resolve knob deltas), now also used by the online ``"auto"`` backend
selector:

- every candidate is timed over N fenced rounds via
  ``utils/metrics.timed`` and scored by the MEDIAN round;
- the per-candidate jitter (half the inter-quartile range) is kept with
  every measurement;
- a NOISE GATE keeps the default candidate unless a challenger beats it
  by more than the combined jitter of the two — the anti-flap rule that
  makes re-runs agree with themselves.
"""

from __future__ import annotations

from typing import Dict, Optional, Tuple

from ..utils import metrics


def measure(step, iters: int = 1, rounds: int = 3,
            fence=metrics.fence) -> metrics.TimedResult:
    """Time ``step`` (one warm/compile call + ``rounds`` fenced rounds
    of ``iters`` dispatches); returns the structured TimedResult."""
    return metrics.timed(step, max(1, iters), fence=fence,
                         rounds=max(1, rounds))


def noise_gate(cands: Dict, default_key,
               ) -> Tuple[Optional[object], dict]:
    """Noise-gated argmin over ``cands`` ({key: TimedResult}).

    Returns ``(chosen_key, evidence)``.  The default wins unless some
    candidate's median beats the default's by MORE than the pair's
    combined jitter.  With no successful measurements returns
    ``(default_key, ...)``; with the default candidate missing, a plain
    argmin over what did measure.
    """
    if not cands:
        return default_key, {"note": "no successful measurements"}
    if default_key not in cands:
        k = min(cands, key=lambda k: cands[k].median)
        return k, {"note": "default candidate failed; plain argmin",
                   "chosen_ms": round(cands[k].median * 1e3, 3)}
    d = cands[default_key]
    k_min = min(cands, key=lambda k: cands[k].median)
    m = cands[k_min]
    delta = d.median - m.median
    needed = max(d.jitter + m.jitter, 0.0)
    chosen = k_min if (k_min != default_key and delta > needed) \
        else default_key
    return chosen, {
        "default": str(default_key),
        "default_ms": round(d.median * 1e3, 3),
        "fastest": str(k_min),
        "fastest_ms": round(m.median * 1e3, 3),
        "delta_ms": round(delta * 1e3, 3),
        "noise_floor_ms": round(needed * 1e3, 3),
        "gated_to_default": chosen == default_key and k_min != default_key,
    }


def result_ms(res: metrics.TimedResult) -> dict:
    """JSON-friendly ms view of one measurement (autotune's log shape)."""
    return {"ms": round(res.median * 1e3, 3),
            "jitter_ms": round(res.jitter * 1e3, 3),
            "rounds_ms": [round(t * 1e3, 3) for t in res.round_times]}

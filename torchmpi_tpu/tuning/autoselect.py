"""Online ``backend="auto"`` selection against the persistent plan DB.

Lifecycle of one (op, size bucket, mesh, platform) key:

1. ``mpi.init`` with ``backend="auto"`` loads the plan file (missing /
   corrupt / version-mismatched files silently yield an empty plan).
2. The FIRST eager call of an uncached key measures every registered,
   topology-eligible candidate backend with the noise-gated median
   discipline of :mod:`torchmpi_tpu.tuning.measure` (the rules
   ``benchmarks/autotune.py`` proved out), caches the winner, and
   best-effort persists the plan to disk.
3. Every later call — in this process or any future one — hits the
   plan with zero re-measurement (assertable via
   :func:`measurement_count`).
4. In-axis collectives (inside a user's jit) cannot measure at trace
   time; they consult the plan read-only via the selector's plan
   provider and degrade to the static path on a miss.

Every decision is surfaced through ``utils/metrics``: an in-memory
record (:func:`decisions`) plus an optional JSONL ``MetricsLogger``
(``set_decision_logger`` / ``TORCHMPI_TPU_TUNING_LOG``), so a step log
records which backend ran and why.
"""

from __future__ import annotations

import os
import threading
from typing import Callable, Dict, List, Optional

from . import fingerprint, measure, plancache
from ..utils import metrics

# The gate's protected default: the stock path every platform has.
DEFAULT_BACKEND = "xla"


class _State:
    def __init__(self) -> None:
        self.lock = threading.RLock()
        self.cache: Optional[plancache.PlanCache] = None
        self.rounds = 3
        self.iters = 1
        self.measure_count = 0
        self.measuring = False
        self.decisions: List[dict] = []
        self.logger: Optional[metrics.MetricsLogger] = None
        self.logged_keys: set = set()


_state = _State()


def _obs():
    """The telemetry module when ``Config.obs`` is on, else None — one
    branch per call site and never an import on the off path (the
    ``torchmpi_tpu.obs`` discipline)."""
    from .. import runtime

    if runtime.effective_config().obs == "off":
        return None
    from .. import obs

    return obs


def _log(record: dict) -> None:
    _state.decisions.append(record)
    del _state.decisions[:-1000]  # bounded in-memory history
    if _state.logger is not None:
        _state.logger.log(**record)


def decisions() -> List[dict]:
    """The decision log so far (most recent last, bounded)."""
    return list(_state.decisions)


def set_decision_logger(logger: Optional[metrics.MetricsLogger]) -> None:
    _state.logger = logger


def measurement_count() -> int:
    """How many plan keys this process measured online (the hook the
    zero-re-measurement acceptance test asserts on)."""
    return _state.measure_count


def reset_measurement_count() -> None:
    _state.measure_count = 0


def is_active() -> bool:
    return _state.cache is not None


def plan() -> Optional[plancache.PlanCache]:
    return _state.cache


def configure(plan_path: Optional[str] = None, rounds: int = 3,
              log_path: Optional[str] = None,
              auto_active: bool = True) -> plancache.PlanCache:
    """Activate online tuning: load the plan file (silently degrading
    to an empty plan) and register the selector's plan provider.
    Called by ``runtime.init`` when the config opts into ``"auto"``.
    ``auto_active=False`` records that a plan was loaded while no
    backend resolves to ``"auto"`` — the plan is then dead weight, and
    the decision log says so instead of leaving the user to wonder why
    their seeded plan never applies."""
    from .. import selector

    with _state.lock:
        path = plancache.resolve_plan_path(plan_path)
        if (_state.cache is not None and _state.cache.path == path
                and _state.cache.degraded_reason is None):
            # Same DB, already live: keep the in-memory entries — they
            # may include measurements that could not be persisted
            # (unwritable path) and a reload would throw them away,
            # forcing a full re-measurement sweep after any set_config.
            # Still pick up entries that appeared on disk meanwhile
            # (another process, or a plan_tool merge into the live file).
            disk = plancache.PlanCache.load(path)
            if disk.degraded_reason is None:
                _state.cache.merge_from(disk)
        else:
            _state.cache = plancache.PlanCache.load(path)
        _state.rounds = max(1, int(rounds))
        _state.logged_keys = set()
        log_path = log_path or os.environ.get("TORCHMPI_TPU_TUNING_LOG")
        # Rebind (or drop) the JSONL logger every configure: a stale
        # logger from a previous init must not keep receiving this
        # run's records.  set_decision_logger() can still override.
        _state.logger = (metrics.MetricsLogger(log_path) if log_path
                         else None)
        if _state.cache.degraded_reason:
            _log({"event": "tuning_plan_degraded", "path": path,
                  "reason": _state.cache.degraded_reason})
        if not auto_active:
            _log({"event": "tuning_plan_inactive", "path": path,
                  "entries": len(_state.cache),
                  "reason": "plan loaded but no backend resolves to "
                            "'auto'; set backend='auto' (or a per-op "
                            "'auto') for the plan to drive selection"})
        selector.set_plan_provider(plan_lookup)
        return _state.cache


def reset() -> None:
    """Deactivate (``runtime.stop``): drop the in-memory plan and
    unregister the provider.  Counters survive — they are process-level
    bookkeeping the tests read across init/stop cycles."""
    from .. import selector

    with _state.lock:
        _state.cache = None
        selector.clear_plan_provider()


def plan_lookup(op: str, nbytes: int, dtype,
                axes=None) -> Optional[str]:
    """Read-only plan consult (the selector's plan provider): returns
    the planned backend for this key, or None on a miss / inactive
    tuning.  ``axes`` is the axis subset the collective spans (None =
    whole mesh) — part of the key, so whole-mesh decisions are never
    replayed for unmeasured axis subsets.  Never raises, never
    measures — safe at trace time."""
    from .. import runtime

    st = _state
    cache = st.cache  # snapshot: a concurrent stop() may null st.cache
    if (cache is None or cache.degraded_reason is not None
            or dtype is None or not runtime.is_initialized()):
        return None
    try:
        mesh = runtime.current_mesh()
        key = fingerprint.fingerprint(op, int(nbytes or 0), dtype, mesh,
                                      axes=axes)
    except Exception:  # noqa: BLE001 — lookup must never take down a step
        return None
    entry = cache.get(key)
    o = _obs()
    if o is not None:
        o.record_tuning_plan("hit" if entry is not None else "miss", op)
    if entry is None:
        return None
    if key not in st.logged_keys:
        st.logged_keys.add(key)
        _log({"event": "tuning_decision", "op": op, "key": key,
              "backend": entry.backend, "source": "plan",
              "entry_source": entry.source})
    return entry.backend


def _multiprocess() -> bool:
    try:
        import jax

        return jax.process_count() > 1
    except Exception:  # noqa: BLE001
        return False


def _eligible_candidates(op: str, n_dcn: int) -> List[str]:
    from .. import selector

    cands = []
    for b in sorted(selector.available(op)):
        if b == "hierarchical" and n_dcn <= 1:
            continue  # two-level staging needs a real outer axis
        cands.append(b)
    if DEFAULT_BACKEND not in cands:
        cands.insert(0, DEFAULT_BACKEND)
    return cands


def resolve_eager(op: str, nbytes: int, dtype, mesh,
                  runner: Callable[[str], object]) -> Optional[str]:
    """Resolve ``"auto"`` for one eager collective call.

    ``runner(backend)`` executes the collective with that explicit
    backend (supplied by ``collectives._eager_collective``).  Returns
    the backend to use, or None to degrade to static selection.
    """
    st = _state
    cache = st.cache  # snapshot: a concurrent stop() may null st.cache
    if cache is None or cache.degraded_reason is not None:
        # Degraded plan (corrupt / version mismatch): static-cutover
        # behavior, no measuring, no overwriting the evidence on disk.
        return None
    key = fingerprint.fingerprint(op, nbytes, dtype, mesh)
    entry = cache.get(key)
    if entry is None and _multiprocess():
        # Multi-host SPMD: per-process online measurement cannot agree
        # across hosts (local timings, local files, locally-skipped
        # candidates) and divergent backend choices compile mismatched
        # programs -> distributed hang.  Plans are read-only here:
        # distribute ONE plan file to every host (shared FS, or
        # plan_tool merge + copy) — docs/TUNING.md.
        if key not in st.logged_keys:
            st.logged_keys.add(key)
            _log({"event": "tuning_decision", "op": op, "key": key,
                  "backend": DEFAULT_BACKEND, "source": "fallback",
                  "reason": "multiprocess: online measurement disabled"})
        return None
    if entry is not None:
        o = _obs()
        if o is not None:
            o.record_tuning_plan("hit", op)
        if key not in st.logged_keys:
            st.logged_keys.add(key)
            _log({"event": "tuning_decision", "op": op, "key": key,
                  "backend": entry.backend, "source": "plan",
                  "entry_source": entry.source})
        return entry.backend
    with st.lock:
        if st.measuring:
            return None  # re-entrant call during a measurement: static
        # Key may have been measured while we waited on the lock.
        entry = cache.get(key)
        if entry is not None:
            return entry.backend
        st.measuring = True
    try:
        axes = mesh.axis_names
        n_dcn = int(mesh.shape[axes[0]]) if len(axes) > 1 else 1
        cands: Dict[str, metrics.TimedResult] = {}
        errors: Dict[str, str] = {}
        for b in _eligible_candidates(op, n_dcn):
            try:
                cands[b] = measure.measure(lambda b=b: runner(b),
                                           iters=st.iters,
                                           rounds=st.rounds)
            except Exception as e:  # noqa: BLE001 — skip broken candidate
                errors[b] = str(e)[:120]
        if not cands:
            _log({"event": "tuning_decision", "op": op, "key": key,
                  "backend": DEFAULT_BACKEND, "source": "fallback",
                  "errors": errors})
            return None
        winner, evidence = measure.noise_gate(cands, DEFAULT_BACKEND)
        st.measure_count += 1
        o = _obs()
        if o is not None:
            o.record_tuning_plan("measured", op)
            for b, r in cands.items():
                o.record_tuning_measure(op, b, r.median)
        new = plancache.PlanEntry(
            backend=str(winner), source="measured",
            median_ms={b: round(r.median * 1e3, 4)
                       for b, r in cands.items()},
            jitter_ms={b: round(r.jitter * 1e3, 4)
                       for b, r in cands.items()},
            rounds=st.rounds)
        cache.put(key, new)
        try:
            cache.save()  # best-effort; unwritable paths stay in-memory
        except Exception:  # noqa: BLE001 — persistence never fails a step
            pass
        st.logged_keys.add(key)
        _log({"event": "tuning_decision", "op": op, "key": key,
              "backend": new.backend, "source": "measured",
              "evidence": evidence, **({"errors": errors} if errors
                                       else {})})
        return new.backend
    finally:
        st.measuring = False


def plan_bucket_bytes(op: str, mesh, fallback_bytes: int) -> int:
    """Bucket byte bound for the gradsync overlap schedule, aligned to
    the plan database's log2 size buckets (docs/OVERLAP.md).

    The overlap schedule sizes its gradient buckets from the tuning
    plan instead of a fixed ``n_buckets``: when the active plan holds
    measured ``op`` entries for this platform+mesh, the bound is the
    byte size of the LARGEST measured bucket not above
    ``fallback_bytes`` — every fired bucket then keys to a plan entry
    somebody actually measured.  With no plan (or no matching entries)
    the bound is ``fallback_bytes`` rounded down to a bucket edge, so
    the buckets still land on plan keys a future ``backend="auto"`` run
    can fill in.
    """
    fallback_bytes = max(1, int(fallback_bytes))
    edge = fingerprint.bucket_bytes(fingerprint.size_bucket(fallback_bytes))
    cache = _state.cache
    if cache is None:
        return edge
    prefix = (f"{fingerprint.platform_of(mesh)}|"
              f"{fingerprint.mesh_key(mesh)}|{op}|")
    best = None
    for key in cache.entries:
        if not key.startswith(prefix):
            continue
        _, _, tail = key.rpartition("|b")
        try:
            b = int(tail)
        except ValueError:
            continue
        nbytes = fingerprint.bucket_bytes(b)
        if nbytes <= edge and (best is None or nbytes > best):
            best = nbytes
    return best if best is not None else edge

"""CollectivePlan: one cached planner for the whole dispatch path.

TorchMPI's core performance trick was a *resource cache* (SURVEY.md
§8.4.5): plan a collective once — buffers, communicator, algorithm —
and replay the plan on every later call.  Five subsystems grew around
this library's dispatch path (tuning, fusion, analysis, obs, faults,
overlap) and each call used to re-derive its decisions from all of them
in sequence: fusion grouping, ``selector.nbytes_of`` tree walks,
tuning-plan lookups, the static cutover, then per-site obs/faults
string compares — with only the compiled executable memoized ad hoc.

This module lifts the full decision record into an explicit, immutable
:class:`CollectivePlan`, computed once per key and replayed thereafter:

- **key** — ``(kind, op, pytree structure + leaf avals, mesh, backend,
  static params, config epoch)``.  Two calls with the same tree
  *structure* but different values share a plan; a different mesh, a
  pushed communicator, or any :func:`runtime.set_config` (which bumps
  the epoch) misses and re-plans.
- **record** — the dtype-grouped fusion buckets with precomputed nbytes
  and layouts (:class:`~torchmpi_tpu.fusion.FusedSpec`), the selector/
  tuning backend choice *per bucket*, the cached rank-major sharding,
  the compiled executable (eager mode), the static-analysis verdict,
  and pre-resolved obs/faults enablement — so "off" costs zero
  branches at replay (one ``is None`` check), not one string compare
  per layer per site.
- **replay** — the minimal residual work: one table lookup, then the
  pre-bound closure.

Consumers: ``collectives._eager_collective`` and the nine ``*_in_axis``
verbs (hence ``async_`` / ``async_in_axis`` on top of them),
``gradsync.synchronize_gradients`` / ``make_overlapped_grad_fn``, and
the ZeRO flatten/reduce-scatter leg.  Invalidation has ONE point:
:func:`invalidate` (``collectives.clear_cache`` and ``runtime.stop``
route here; ``set_config`` bumps the epoch *and* routes here) — the
seam serving, elasticity, and cross-slice topology (ROADMAP items 2-4)
hang their lifecycle off.  See docs/PLANNER.md.
"""

from __future__ import annotations

import threading
import time
from typing import Callable, Dict, List, Optional, Tuple

import jax
import numpy as np
from jax import lax, shard_map
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from . import fusion, runtime, selector

# ---------------------------------------------------------------------------
# The plan table: the ONE cache behind the dispatch path (it subsumes
# the old ad-hoc collectives._jit_cache / _sharding_cache pair).  Reads
# are lock-free dict gets (GIL-atomic); builds run under an RLock —
# re-entrant because building an eager backend="auto" plan measures
# candidates by dispatching them, which plans recursively.
# ---------------------------------------------------------------------------

_lock = threading.RLock()
_table: Dict[tuple, "CollectivePlan"] = {}
_shardings: Dict[Mesh, NamedSharding] = {}
_enabled = True
_stats = {"hits": 0, "misses": 0, "invalidations": 0}


class CollectivePlan:
    """Immutable decision record for one collective dispatch site.

    Built once by the ``plan_*`` functions below, then replayed: the
    fields are assigned at construction and never mutated afterwards
    (``hits`` is the one bookkeeping exception).  ``replay`` runs the
    pre-bound execution closure; decision-only plans (kinds
    ``overlap`` / ``flatspec``) carry no closure and are consumed via
    ``spec`` / ``impls`` / ``extra`` instead.
    """

    __slots__ = ("key", "kind", "op", "backend", "nbytes", "spec", "impls",
                 "extra", "staged", "obs", "faults", "guard", "watchdog",
                 "analysis", "epoch", "topology", "build_seconds", "hits",
                 "_replay", "_obs_hit")

    def __init__(self, key: tuple, kind: str, op: str, *,
                 backend: str = "", nbytes: int = 0,
                 spec: Optional[fusion.FusedSpec] = None,
                 impls: Optional[List[Callable]] = None,
                 extra: Optional[dict] = None,
                 staged: bool = False, obs: bool = False,
                 faults: bool = False, guard: bool = False,
                 watchdog: bool = False,
                 analysis: str = "off",
                 topology: str = "",
                 replay: Optional[Callable] = None) -> None:
        self.key = key
        self.kind = kind
        self.op = op
        self.backend = backend
        self.nbytes = int(nbytes)
        # Topology fingerprint ("n_dcn x n_ici ..." as "2x4"): the mesh
        # extents the plan's dispatch spans — what makes a flat-vs-
        # hierarchical decision visible per topology in dump-live
        # (ROADMAP item 4; docs/HIERARCHICAL.md).
        self.topology = topology
        self.spec = spec
        self.impls = impls
        self.extra = extra or {}
        self.staged = bool(staged)
        self.obs = bool(obs)
        self.faults = bool(faults)
        # Wire-integrity guard enablement, resolved at build like
        # obs/faults (docs/GUARD.md): guard="off" is one string compare
        # HERE — the replay closure carries no guard branch at all.
        self.guard = bool(guard)
        # Watchdog enablement, same build-time resolution
        # (docs/WATCHDOG.md): "off" is one string compare at build and
        # the replay closure carries ZERO watchdog branches; "on" binds
        # the in-flight window (staged) / deferred-raise boundary
        # (direct) into the closure itself.
        self.watchdog = bool(watchdog)
        self.analysis = analysis
        self.epoch = runtime.config_epoch()
        self.build_seconds = 0.0
        self.hits = 0
        self._replay = replay
        # Pre-bound hit counter (one dict op per replay when obs is on,
        # nothing at all when off — resolved at build, like every other
        # decision in the record).
        self._obs_hit: Optional[Callable] = None
        if self.obs:
            from . import obs as _obs

            self._obs_hit = _obs.registry().counter_handle(
                "tm_plan_hit_total", op=op, kind=kind)

    def replay(self, x):
        """Execute the planned dispatch for one same-structure input."""
        return self._replay(x)

    def describe(self) -> dict:
        """JSON-ready row for ``plan_tool.py dump-live`` / debugging."""
        return {
            "kind": self.kind, "op": self.op, "backend": self.backend,
            "nbytes": self.nbytes,
            "launches": (len(self.impls) if self.impls
                         else (self.spec.n_launches
                               if self.spec is not None else 1)),
            "staged": self.staged, "obs": self.obs, "faults": self.faults,
            "guard": self.guard, "watchdog": self.watchdog,
            "analysis": self.analysis, "epoch": self.epoch,
            "topology": self.topology,
            "build_ms": round(self.build_seconds * 1e3, 3),
            "hits": self.hits,
        }


def enabled() -> bool:
    return _enabled


def set_enabled(flag: bool) -> bool:
    """Switch the planner off (the pre-planner dispatch path runs
    instead) or back on.  Exists for the ``--plan-compare`` bench mode
    and the bit-identity tests; production code leaves it on.  Returns
    the previous value."""
    global _enabled
    prev, _enabled = _enabled, bool(flag)
    return prev


def invalidate() -> None:
    """THE invalidation point: drop every plan and cached sharding.

    ``collectives.clear_cache()`` and ``runtime.stop()`` route here, as
    does ``runtime.set_config`` (via clear_cache, after bumping the
    config epoch).  Mesh identity changes need no explicit call — the
    mesh object is part of every key — but a caller tearing down a mesh
    can invalidate() to release the plans pinned to it.  Clears IN
    PLACE so module-level aliases of the table stay live."""
    with _lock:
        _table.clear()
        _shardings.clear()
        _stats["invalidations"] += 1
    # The preserved pre-planner executables (collectives._legacy_jit_cache)
    # pin compiled programs + mesh references too; a lifecycle caller
    # invoking invalidate() directly (docs/PLANNER.md) must drop them as
    # well.  sys.modules lookup, not an import: no cycle with collectives.
    import sys

    mod = sys.modules.get(__package__ + ".collectives")
    if mod is not None:
        mod._legacy_jit_cache.clear()


def stats() -> dict:
    """Cumulative table stats: ``hits`` / ``misses`` / ``entries`` /
    ``invalidations`` (process-level; survive invalidate())."""
    return dict(_stats, entries=len(_table))


def reset_stats() -> None:
    _stats["hits"] = 0
    _stats["misses"] = 0
    _stats["invalidations"] = 0


def describe() -> List[dict]:
    """One JSON-ready row per live plan (``plan_tool.py dump-live``)."""
    with _lock:
        return [p.describe() for p in _table.values()]


def rank_major_sharding(m: Mesh) -> NamedSharding:
    """Cached rank-major NamedSharding per mesh (part of every eager
    plan; also consulted by the staged/async placement paths)."""
    s = _shardings.get(m)
    if s is None:
        s = _shardings[m] = NamedSharding(m, P(m.axis_names))
    return s


# ---------------------------------------------------------------------------
# Shared lookup/build plumbing
# ---------------------------------------------------------------------------


def _lookup(key: tuple) -> Optional[CollectivePlan]:
    plan = _table.get(key)
    if plan is not None:
        _stats["hits"] += 1
        plan.hits += 1
        if plan._obs_hit is not None:
            plan._obs_hit()
    return plan


def _get_or_build(key: tuple, builder: Callable[[], CollectivePlan]
                  ) -> CollectivePlan:
    """Lock-free hit, else build-and-insert under the planner lock.

    Builds are deliberately serialized (one at a time, lock held across
    the builder): a build can run a tuning backend="auto" measurement,
    and a concurrent build racing past tuning's ``measuring`` flag
    would freeze a statically-resolved backend into an auto plan and
    replay it forever.  The cost — a cold dispatch on another thread
    waits for an in-flight build — is a cold-start-only stall; the
    steady state never takes this lock.
    """
    plan = _lookup(key)
    if plan is not None:
        return plan
    with _lock:
        plan = _lookup(key)  # double-check: lost the build race
        if plan is not None:
            return plan
        t0 = time.monotonic()
        plan = builder()
        plan.build_seconds = time.monotonic() - t0
        _table[key] = plan
    _stats["misses"] += 1
    if plan.obs:
        from . import obs

        obs.record_plan("miss", plan.op, kind=plan.kind,
                        build_s=plan.build_seconds)
    return plan


def _epoch() -> tuple:
    """The staleness component of every plan key: the config epoch
    (init/set_config/stop bumps) plus the selector registry generation
    (a runtime re-register strands plans that resolved the old impl —
    the planner analog of the legacy cache keying on the impl object)."""
    return (runtime.config_epoch(), selector.generation())


def _cfg():
    return runtime.config() if runtime.is_initialized() else None


def _avals(leaves) -> Optional[tuple]:
    """Hashable (shape, dtype) signature of a leaf list; None when some
    leaf is not array-like (python scalars) — unplannable, the caller
    falls back to the legacy path."""
    out = []
    for leaf in leaves:
        shape = getattr(leaf, "shape", None)
        dtype = getattr(leaf, "dtype", None)
        if shape is None or dtype is None:
            return None
        try:
            out.append((tuple(int(d) for d in shape), np.dtype(dtype).name))
        except (TypeError, ValueError):
            return None  # polymorphic/abstract dims
    return tuple(out)


def topology_of(mesh=None, sizes=None) -> str:
    """The ``n_dcn x n_ici``-style topology fingerprint of a dispatch
    ("2x4" two-level; "8" flat), stored on every :class:`CollectivePlan`
    (and shown by ``plan_tool.py dump-live``) so a flat-vs-hierarchical
    choice reads as a per-topology decision, not an opaque cache row.
    ONE home: :func:`torchmpi_tpu.tuning.fingerprint.topology`, the same
    extents the tuning-plan keys carry via ``mesh_key`` — the planner's
    fingerprint and the plan DB's can never drift apart."""
    from .tuning import fingerprint

    return fingerprint.topology(mesh=mesh, sizes=sizes)


def _topo_sizes(mesh, axes: Tuple[str, ...]) -> Optional[Tuple[int, ...]]:
    """Trace-bound axis extents reordered to MESH order for the
    topology label: ``("ici", "dcn")`` and ``("dcn", "ici")`` calls
    over one device span must read as ONE topology (the same
    normalization :func:`fingerprint.mesh_key` applies to the plan-DB
    keys).  Axes not named by the mesh (a different user mesh) keep
    their caller order — the trace-context sizes are still correct."""
    sizes = _axis_sizes(axes)
    if mesh is None or sizes is None:
        return sizes
    try:
        if all(a in mesh.shape for a in axes):
            order = {a: i for i, a in enumerate(mesh.shape)}
            return tuple(s for _, s in sorted(
                zip(axes, sizes), key=lambda p: order[p[0]]))
    except Exception:  # noqa: BLE001 — a label must never fail a plan
        pass
    return sizes


def _axis_sizes(axes: Tuple[str, ...]) -> Optional[Tuple[int, ...]]:
    """The bound sizes of ``axes`` in the current trace context, or
    None outside any binding.  Part of every in-axis key: the same axis
    NAMES can be bound to different sizes by different user meshes, and
    a fused layout planned for one must never replay for the other."""
    try:
        return tuple(int(lax.axis_size(a)) for a in axes)
    except Exception:  # noqa: BLE001 — outside an axis binding
        return None


def _in_axis_recorder(cfg, op: str, nbytes: int, axes) -> Optional[Callable]:
    """Pre-resolved in-axis obs hook: None when obs is off (the replay
    then pays one ``is None`` check), else a bound recorder."""
    if cfg is None or cfg.obs == "off":
        return None
    import functools

    from . import obs

    return functools.partial(obs.record_in_axis, op, nbytes, axes)


# ---------------------------------------------------------------------------
# Eager rank-major plans (collectives._eager_collective)
# ---------------------------------------------------------------------------


def _wd_wrap(replay: Callable, site: str, op: str,
             nbytes: int) -> Callable:
    """Bind the watchdog in-flight window around a BLOCKING replay (the
    staged-host exchange): resolved once at plan build — the off path
    never reaches here — so the armed replay pays one begin/end pair
    and the deferred-raise boundary check, and the off replay pays
    nothing at all (docs/WATCHDOG.md)."""
    from . import watchdog

    def wrapped(x):
        watchdog.raise_pending()
        tok = watchdog.begin(site, op=op, peer="gang", nbytes=nbytes)
        try:
            return replay(x)
        finally:
            watchdog.end(tok)

    return wrapped


def _wd_boundary(replay: Callable) -> Callable:
    """Bind only the deferred-raise boundary into a NON-blocking replay
    (the direct eager dispatch, which XLA enqueues asynchronously):
    a stall a background thread is wedged in surfaces at the main
    thread's next eager dispatch — the guard-style raise_pending
    delivery point."""
    from . import watchdog

    def wrapped(x):
        watchdog.raise_pending()
        return replay(x)

    return wrapped


def plan_for(op: str, x, m: Mesh, n: int, backend: Optional[str],
             params: dict) -> CollectivePlan:
    """Plan (or replay-hit) one eager rank-major collective dispatch.

    ``x`` is the rank-major array (leading axis already validated),
    ``params`` the op's static keyword arguments.  The returned plan's
    ``replay(x)`` accepts any same-shape/dtype array.
    """
    key = ("eager", op, m, x.shape, x.dtype.name, backend,
           tuple(sorted(params.items())), _epoch())
    return _get_or_build(
        key, lambda: _build_eager(key, op, x, m, n, backend, params))


def _build_eager(key: tuple, op: str, x, m: Mesh, n: int,
                 backend_arg: Optional[str], params: dict) -> CollectivePlan:
    from . import collectives as C

    cfg = _cfg()
    obs_on = cfg is not None and cfg.obs != "off"
    nbytes = int(np.prod(x.shape[1:])) * x.dtype.itemsize
    sharding = rank_major_sharding(m)
    pd = dict(params)

    if C._staged_requested(cfg, backend_arg):
        # Host-staged mode (the reference's staged data path): the
        # faults AND guard enablement are resolved HERE — the replay
        # carries no Config.faults/Config.guard compare (injection/
        # retry/verify decisions inside an armed layer remain
        # per-attempt, as they must).
        faults_on = cfg is not None and cfg.faults != "off"
        wire_on = cfg is not None and cfg.guard in ("wire", "full")
        wd_on = cfg is not None and cfg.watchdog != "off"
        rec = None
        done = None
        if obs_on:
            from . import obs

            rec = obs.eager_recorder(op, nbytes, "host", m, x.dtype)
            done = obs.eager_done_recorder(op, nbytes, "host", m)
        if faults_on or wire_on:
            from . import faults

            def _replay(x, _faults=faults):
                if rec is not None:
                    rec()
                out = _faults.staged_exchange(op, x, n, pd, C._host_staged,
                                              wire_guard=wire_on)
                out = C._place_rank_major(np.ascontiguousarray(out), m,
                                          sharding)
                if done is not None:
                    done()
                return out
        else:

            def _replay(x):
                if rec is not None:
                    rec()
                out = C._host_staged(op, np.asarray(x), n, **pd)
                out = C._place_rank_major(np.ascontiguousarray(out), m,
                                          sharding)
                if done is not None:
                    done()
                return out

        if wd_on:
            # Resolved HERE, at plan build (the one string compare):
            # the off replay above carries zero watchdog branches.
            _replay = _wd_wrap(_replay, "host_staged", op, nbytes)
        return CollectivePlan(key, "eager-staged", op, backend="host",
                              nbytes=nbytes, staged=True, obs=obs_on,
                              faults=faults_on, guard=wire_on,
                              watchdog=wd_on,
                              topology=topology_of(m),
                              replay=_replay)

    # Direct mode.  Resolve backend="auto" against the persistent tuning
    # plan ONCE at build: the first uncached (op, size bucket, mesh,
    # platform) key measures candidates and persists the winner; the
    # plan then replays the measured decision with zero per-call lookups
    # (torchmpi_tpu/tuning/ — the per-call fingerprint/DB consults the
    # pre-planner path paid on EVERY dispatch).
    eff = backend_arg
    if eff is None and cfg is not None:
        eff, _ = C._config_backend(op, cfg)
    resolved = backend_arg
    if eff == "auto":
        from . import tuning

        measured = tuning.resolve_eager(
            op, nbytes, x.dtype, m,
            lambda b: C._eager_collective(op, x, mesh=m, backend=b, **pd))
        if measured is not None:
            # A measured decision carries per-call-backend authority
            # (bypasses the size cutover; topology fallback still
            # applies in the selector).
            resolved = measured
    axes = m.axis_names
    aval = jax.ShapeDtypeStruct(x.shape[1:], x.dtype)
    impl = C._pick(op, aval, resolved, axes, mesh=m, cfg=cfg)

    def body(xs):
        return impl(xs[0], axes, **pd)[None]

    lead = P(axes)
    # check_vma=False: the rank-major eager mode states its shardings
    # fully explicitly, and custom (pallas) backends cannot express vma
    # through pallas_call uniformly.
    shmapped = shard_map(body, mesh=m, in_specs=(lead,), out_specs=lead,
                         check_vma=False)
    # Opt-in static analysis, once per plan (Config.analysis;
    # docs/ANALYSIS.md).  An error-severity finding in "error" mode
    # raises BEFORE the plan enters the table, so the next call
    # re-checks — the retry contract the hook tests assert.
    verdict = "off"
    mode = getattr(cfg, "analysis", "off") if cfg is not None else "off"
    if mode in ("warn", "error"):
        from . import analysis

        findings = analysis.check_once(
            f"eager {op}", shmapped,
            jax.ShapeDtypeStruct(x.shape, x.dtype), mode=mode)
        verdict = "clean" if not findings else f"findings:{len(findings)}"
    fn = jax.jit(shmapped)
    backend_name = selector.name_of(op, impl)
    rec = None
    done = None
    if obs_on:
        from . import obs

        rec = obs.eager_recorder(op, nbytes, backend_name, m, x.dtype)
        done = obs.eager_done_recorder(op, nbytes, backend_name, m)

    def _replay(x):
        if rec is not None:
            rec()
        out = fn(C._place_rank_major(x, m, sharding))
        if done is not None:
            # The dispatch-returned edge (XLA enqueue is async; the
            # blocking completion surface is AsyncHandle.wait /
            # block_until_ready, which record their own events).
            done()
        return out

    wd_on = cfg is not None and cfg.watchdog != "off"
    if wd_on:
        # The direct dispatch never blocks — bind only the
        # deferred-raise boundary (one string compare at build; zero
        # branches in the off replay).
        _replay = _wd_boundary(_replay)
    return CollectivePlan(key, "eager", op, backend=backend_name,
                          nbytes=nbytes, obs=obs_on, watchdog=wd_on,
                          analysis=verdict,
                          topology=topology_of(m),
                          extra={"executable": fn}, replay=_replay)


# ---------------------------------------------------------------------------
# In-axis plans (the nine *_in_axis verbs; async_in_axis rides them)
# ---------------------------------------------------------------------------


def plan_in_axis(op: str, tree, axes: Tuple[str, ...],
                 backend: Optional[str],
                 params: dict) -> Optional[CollectivePlan]:
    """Plan (or replay-hit) one in-axis pytree collective, or None for
    an unplannable tree (non-array leaves) / a disabled planner —
    the verb then runs its legacy per-call derivation.

    Called at trace time; the plan replays across retraces, re-jits,
    and repeated step builds of the same tree structure."""
    if not _enabled:
        return None
    leaves, treedef = jax.tree.flatten(tree)
    if not leaves:
        return None
    avals = _avals(leaves)
    if avals is None:
        return None
    mesh = runtime.current_mesh() if runtime.is_initialized() else None
    key = ("in_axis", op, treedef, avals, axes, _axis_sizes(axes), backend,
           tuple(sorted(params.items())), mesh, _epoch())
    return _get_or_build(
        key, lambda: _build_in_axis(key, op, tree, leaves, treedef, avals,
                                    axes, backend, params, mesh))


def _bucket_impls(op: str, spec: fusion.FusedSpec, backend, axes, mesh,
                  cfg) -> List[Callable]:
    """The selector/tuning backend choice per fused bucket, resolved
    from each bucket's true nbytes (iteration order == fuse_tree's)."""
    from . import collectives as C

    return [
        C._pick(op, jax.ShapeDtypeStruct((hi - lo,), g.dtype), backend,
                axes, mesh=mesh, cfg=cfg)
        for g in spec.groups for (lo, hi) in g.bounds
    ]


def _resolved_backend(op: str, backend: Optional[str],
                      impls: List[Callable]) -> str:
    """The backend name a plan row reports: the explicit argument when
    one was given, else the name the selector actually resolved for the
    (first) bucket — so ``dump-live`` shows a plan-driven
    "hierarchical" pick instead of an empty config default (build-time
    only; mixed per-bucket picks report the first + "+")."""
    if backend:
        return backend
    if not impls:
        return ""
    names = {selector.name_of(op, f) for f in impls}
    first = selector.name_of(op, impls[0])
    return first if len(names) == 1 else first + "+"


def _build_in_axis(key: tuple, op: str, tree, leaves, treedef, avals,
                   axes: Tuple[str, ...], backend: Optional[str],
                   params: dict, mesh) -> CollectivePlan:
    from . import collectives as C

    cfg = _cfg()
    eff = runtime.effective_config()
    obs_on = eff.obs != "off"
    nbytes = sum(int(np.prod(s)) * np.dtype(d).itemsize for s, d in avals)
    rec = _in_axis_recorder(eff, op, nbytes, axes)
    pd = dict(params)
    max_bytes = eff.fuse_max_bytes

    # Fused elementwise (allreduce/reduce/broadcast): the maybe_fuse
    # decision, taken once.
    if (op in fusion.ELEMENTWISE_OPS and max_bytes > 0 and len(leaves) >= 2):
        spec = fusion.FusedSpec(tree, max_bytes=max_bytes)
        if spec.n_launches < spec.n_leaves:
            impls = _bucket_impls(op, spec, backend, axes, mesh, cfg)

            def _replay(tree):
                if rec is not None:
                    rec()
                return fusion.fuse_tree(op, tree, axes, backend=backend,
                                        spec=spec, impls=impls, **pd)

            return CollectivePlan(key, "in_axis-fused", op,
                                  backend=_resolved_backend(
                                      op, backend, impls),
                                  nbytes=nbytes,
                                  spec=spec, impls=impls, obs=obs_on,
                                  topology=topology_of(
                                      mesh, _topo_sizes(mesh, axes)),
                                  replay=_replay)

    # Fused reduce_scatter: tile-interleaved layout, leaf-granularity
    # buckets (the maybe_fuse_reduce_scatter decision, taken once).
    if op == "reduce_scatter" and max_bytes > 0 and len(leaves) >= 2:
        sizes = _axis_sizes(axes)
        n = int(np.prod(sizes)) if sizes else 0
        if (n > 0 and all(len(s) >= 1 and s[0] % n == 0
                          for s, _ in avals)):
            spec = fusion.FusedSpec(tree, max_bytes=max_bytes)
            n_launches = sum(len(g.leaf_buckets) for g in spec.groups)
            if n_launches < spec.n_leaves:
                impls = [
                    C._pick("reduce_scatter",
                            jax.ShapeDtypeStruct(
                                (sum(g.sizes[pos] for pos in bucket),),
                                g.dtype),
                            backend, axes, mesh=mesh, cfg=cfg)
                    for g in spec.groups for bucket in g.leaf_buckets
                ]

                def _replay(tree):
                    if rec is not None:
                        rec()
                    return fusion.fused_reduce_scatter(
                        tree, axes, spec=spec, impls=impls, n=n, **pd)

                return CollectivePlan(key, "in_axis-fused", op,
                                      backend=_resolved_backend(
                                          op, backend, impls),
                                      nbytes=nbytes, spec=spec,
                                      impls=impls, obs=obs_on,
                                      topology=topology_of(
                                          mesh, _topo_sizes(mesh, axes)),
                                      replay=_replay)

    # Per-leaf: one pre-picked implementation per leaf (the tree.map
    # path, minus the per-call config/selector/nbytes work).
    impls = [
        C._pick(op, jax.ShapeDtypeStruct(s, d), backend, axes, mesh=mesh,
                cfg=cfg)
        for s, d in avals
    ]

    def _replay(tree):
        if rec is not None:
            rec()
        ls = jax.tree.leaves(tree)
        return jax.tree.unflatten(
            treedef, [f(v, axes, **pd) for f, v in zip(impls, ls)])

    return CollectivePlan(key, "in_axis", op,
                          backend=_resolved_backend(op, backend, impls),
                          nbytes=nbytes, impls=impls, obs=obs_on,
                          topology=topology_of(mesh, _topo_sizes(mesh, axes)),
                          replay=_replay)


# ---------------------------------------------------------------------------
# Gradient-sync plans (gradsync._bucketed_allreduce / the overlap
# schedule's bucket assignment + per-bucket backend choice)
# ---------------------------------------------------------------------------


def plan_gradsync(grads, axes: Tuple[str, ...], *, op: str, n_buckets: int,
                  backend: Optional[str],
                  barrier: bool) -> Optional[CollectivePlan]:
    """Plan the bucketed gradient allreduce: FusedSpec with the
    count-driven (``gradsync_buckets``) bucketing plus per-bucket
    backend choices, replayed across step builds."""
    if not _enabled:
        return None
    leaves, treedef = jax.tree.flatten(grads)
    if not leaves:
        return None
    avals = _avals(leaves)
    if avals is None:
        return None
    mesh = runtime.current_mesh() if runtime.is_initialized() else None
    key = ("gradsync", treedef, avals, axes, _axis_sizes(axes), op,
           int(n_buckets), backend, bool(barrier), mesh, _epoch())

    def build():
        cfg = _cfg()
        eff = runtime.effective_config()
        spec = fusion.FusedSpec(grads, n_buckets=n_buckets)
        impls = _bucket_impls("allreduce", spec, backend, axes, mesh, cfg)
        nbytes = sum(int(np.prod(s)) * np.dtype(d).itemsize
                     for s, d in avals)

        def _replay(tree):
            return fusion.fuse_tree("allreduce", tree, axes,
                                    backend=backend, barrier=barrier,
                                    spec=spec, impls=impls, op=op)

        return CollectivePlan(key, "gradsync", "allreduce",
                              backend=backend or "", nbytes=nbytes,
                              spec=spec, impls=impls,
                              topology=topology_of(mesh,
                                                   _topo_sizes(mesh, axes)),
                              obs=eff.obs != "off", replay=_replay)

    return _get_or_build(key, build)


def plan_overlap(template_leaves, axes: Tuple[str, ...], *, op: str,
                 backend: Optional[str], compress: Optional[str],
                 max_bytes: int,
                 dcn_codec: Optional[str] = None) -> Optional[CollectivePlan]:
    """Decision-only plan for the backprop-overlap schedule: the
    reverse-order bucket assignment (``extra["firing"]``) and each
    bucket's pre-picked allreduce implementation (``impls``, indexed in
    firing order).  ``gradsync.make_overlapped_grad_fn`` consumes both
    when building its custom_vjp chain.  With ``dcn_codec`` (the
    error-feedback path) the buckets dispatch the FIXED two-level
    schedule — no selector picks are made and the plan row reports the
    codec, not a backend that never runs."""
    if not _enabled:
        return None
    avals = _avals(template_leaves)
    if avals is None:
        return None
    mesh = runtime.current_mesh() if runtime.is_initialized() else None
    key = ("overlap", avals, axes, op, backend, compress, int(max_bytes),
           dcn_codec, mesh, _epoch())

    def build():
        from . import collectives as C
        from .parallel import gradsync

        cfg = _cfg()
        eff = runtime.effective_config()
        firing = gradsync.assign_overlap_buckets(template_leaves, max_bytes)
        if dcn_codec is not None:
            impls = [None] * len(firing)
            label = f"dcn-{dcn_codec}"
        else:
            impls = []
            for bucket in firing:
                total = sum(int(np.prod(avals[i][0])) for i in bucket)
                wire_dt = (np.dtype("bfloat16") if compress == "bf16"
                           else np.dtype(avals[bucket[0]][1]))
                impls.append(C._pick(
                    "allreduce", jax.ShapeDtypeStruct((total,), wire_dt),
                    backend, axes, mesh=mesh, cfg=cfg))
            label = backend or ""
        nbytes = sum(int(np.prod(s)) * np.dtype(d).itemsize
                     for s, d in avals)
        return CollectivePlan(key, "overlap", "allreduce",
                              backend=label, nbytes=nbytes,
                              impls=impls, obs=eff.obs != "off",
                              topology=topology_of(mesh,
                                                   _topo_sizes(mesh, axes)),
                              extra={"firing": firing,
                                     "max_bytes": int(max_bytes)})

    return _get_or_build(key, build)


# ---------------------------------------------------------------------------
# Shared flatten/shard metadata (the ZeRO leg + gradsync FlatSpec users)
# ---------------------------------------------------------------------------


def flat_spec_for(tree, n_shards: int) -> fusion.FusedSpec:
    """Cached :class:`~torchmpi_tpu.fusion.FusedSpec` for ``(tree
    structure, n_shards)`` — the static flatten/pad/shard metadata the
    ZeRO update legs and ``zero.flat_spec`` used to rebuild on every
    trace.  Config-independent (no epoch in the key): the layout is a
    pure function of the avals and the shard count."""
    if not _enabled:
        return fusion.FusedSpec(tree, int(n_shards))
    leaves, treedef = jax.tree.flatten(tree)
    avals = _avals(leaves)
    if avals is None:
        return fusion.FusedSpec(tree, int(n_shards))
    key = ("flatspec", treedef, avals, int(n_shards))

    def build():
        spec = fusion.FusedSpec(tree, int(n_shards))
        nbytes = sum(int(np.prod(s)) * np.dtype(d).itemsize
                     for s, d in avals)
        eff = runtime.effective_config()
        return CollectivePlan(key, "flatspec", "flatten",
                              nbytes=nbytes, spec=spec,
                              obs=eff.obs != "off",
                              extra={"n_shards": int(n_shards)})

    return _get_or_build(key, build).spec


# ---------------------------------------------------------------------------
# Mesh-parallel serving replicas (torchmpi_tpu/serving/tp_engine.py)
# ---------------------------------------------------------------------------


def plan_serving_replica(replica: str, mesh, axes: Tuple[str, ...],
                         *, op: str = "tp_decode"
                         ) -> Optional[CollectivePlan]:
    """Decision-only plan row for one mesh-parallel serving replica:
    keyed per replica MESH via the topology fingerprint, so two
    replicas carved from different device slices — or the same replica
    after an elastic resize — read as distinct per-topology decisions
    in ``plan_tool.py dump-live`` instead of an opaque engine
    attribute.  The row records the sharded-decode dispatch choice
    (``shard_map`` over ``axes``); the engine's compiled executables
    key on the same (mesh, axis) tuple, so plan row and executable can
    never describe different topologies."""
    if not _enabled:
        return None
    key = ("serving", replica, mesh, tuple(axes), op, _epoch())

    def build():
        eff = runtime.effective_config()
        try:
            sizes = tuple(int(mesh.shape[a]) for a in axes)
        except Exception:  # noqa: BLE001 — a label must never fail a plan
            sizes = None
        return CollectivePlan(
            key, "serving", op, backend="shard_map",
            obs=eff.obs != "off",
            topology=topology_of(mesh, sizes),
            extra={"replica": replica, "axes": tuple(axes),
                   "devices": int(np.prod(mesh.devices.shape))})

    return _get_or_build(key, build)

"""Radix prefix-sharing KV cache over the paged :class:`.slots.SlotPool`.

Serving traffic is dominated by shared prefixes — system prompts,
few-shot headers — and the PR 9/17 tier prefills each copy from scratch.
This module is the paper's resource-cache philosophy (cache every
expensive artifact keyed by what actually distinguishes it) applied to
KV state: the first request to carry a prefix prefills it once, the
cache keeps the resulting k/v as **block-aligned fragments** in a radix
tree keyed by token content, and every later request assembles the
matched fragments into its slot row and runs ``slot_extend`` over only
the unshared suffix.

Correctness rests on two facts the rest of the stack already depends
on (docs/SERVING.md):

- **Causality + absolute-position rope**: a prefix's k/v depend only on
  the prefix tokens, so a fragment sliced from one request's prefill is
  bitwise the fragment any other request sharing that prefix would have
  computed.
- **Per-row depth masking**: everything in a slot row beyond the
  assembled depth is invisible to attention, so an assembled row decodes
  bit-identically to a freshly prefilled one — the same argument that
  makes slot reuse and bucketed-prefill padding safe.

Sharing is accounted through the pool's refcounted block ledger: each
tree node owns one ledger block (refcount 1 = cached but idle), every
live slot built from the node pins it for the session's lifetime, and
eviction is LRU strictly over idle **leaves** — never a block a live
slot holds (use-after-free), never an interior node (orphaned children
would claim a prefix whose head is gone).

The tree stores fragments as opaque pytrees (it never imports jax) —
the engine slices and writes them with the ``slot_cache_slice`` /
``slot_cache_write`` primitives, which is what lets one tree implement
both the dense flax-cache and the TP list-of-(k, v) layouts.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

from .slots import SlotPool


def _frag_nbytes(frag) -> int:
    """Total bytes of a fragment pytree (duck-typed ``.nbytes`` so the
    pure-bookkeeping tests can use numpy or even plain objects)."""
    total = 0
    stack = [frag]
    while stack:
        x = stack.pop()
        if isinstance(x, dict):
            stack.extend(x.values())
        elif isinstance(x, (list, tuple)):
            stack.extend(x)
        else:
            total += int(getattr(x, "nbytes", 0) or 0)
    return total


class _Node:
    """One radix-tree edge: ``key`` is this node's block of tokens,
    ``frag`` the k/v fragment those tokens produced, ``bid`` its ledger
    block id."""

    __slots__ = ("key", "frag", "bid", "parent", "children",
                 "last_used", "nbytes")

    def __init__(self, key: Tuple[int, ...], frag, bid: int,
                 parent: Optional["_Node"]):
        self.key = key
        self.frag = frag
        self.bid = bid
        self.parent = parent
        self.children: Dict[Tuple[int, ...], "_Node"] = {}
        self.last_used = 0
        self.nbytes = _frag_nbytes(frag)


class PrefixCache:
    """Block-aligned radix tree of shared prompt-prefix KV fragments.

    ``block_tokens`` is the sharing granularity: prefixes match in whole
    blocks only (token-aligned at block boundaries, longest match wins),
    which keeps fragments fixed-shape — one compiled slice/write per
    layout instead of one per prefix length.  Capacity is the pool's
    ``prefix_blocks`` ledger; the deterministic integer LRU clock makes
    eviction order replayable from a seed, same discipline as the slot
    free-list.
    """

    def __init__(self, pool: SlotPool, *, block_tokens: int = 8):
        if block_tokens < 1:
            raise ValueError(
                f"block_tokens must be >= 1, got {block_tokens}")
        if block_tokens > pool.slot_tokens:
            raise ValueError(
                f"block_tokens ({block_tokens}) cannot exceed "
                f"slot_tokens ({pool.slot_tokens})")
        if pool.prefix_blocks < 1:
            raise ValueError(
                "pool has no prefix block ledger (prefix_blocks == 0)")
        self.pool = pool
        self.block_tokens = int(block_tokens)
        self._root_children: Dict[Tuple[int, ...], _Node] = {}
        self._nodes: List[_Node] = []
        self._clock = 0
        self.stats = {"hits": 0, "misses": 0, "inserted": 0,
                      "evicted": 0, "tokens_saved": 0, "bytes_saved": 0}

    # ----- lookup --------------------------------------------------

    def _touch(self, node: _Node) -> None:
        self._clock += 1
        node.last_used = self._clock

    def match(self, tokens: Sequence[int]) -> List[_Node]:
        """Longest block-aligned cached prefix of ``tokens`` — a chain
        of nodes root-down.  Capped one block short of covering the
        whole prompt so at least one suffix token always remains to
        extend with (the forward needs a query token to sample the
        first output from, exactly as full prefill does).

        Counts one hit (with ``tokens_saved``/``bytes_saved``) or one
        miss per call; does NOT pin — callers that build a row from the
        chain must :meth:`pin` it before any tick in which eviction
        could run.
        """
        B = self.block_tokens
        toks = [int(t) for t in tokens]
        max_blocks = max(0, (len(toks) - 1) // B)
        chain: List[_Node] = []
        children = self._root_children
        for i in range(max_blocks):
            key = tuple(toks[i * B:(i + 1) * B])
            node = children.get(key)
            if node is None:
                break
            chain.append(node)
            children = node.children
        for node in chain:
            self._touch(node)
        if chain:
            self.stats["hits"] += 1
            self.stats["tokens_saved"] += len(chain) * B
            self.stats["bytes_saved"] += sum(n.nbytes for n in chain)
        else:
            self.stats["misses"] += 1
        return chain

    # ----- pinning -------------------------------------------------

    def pin(self, chain: Sequence[_Node]) -> None:
        """Take a live-slot reference on every block in ``chain`` (the
        admission side of copy-on-extend: the session shares the
        fragments read-only; its own writes land in its slot row)."""
        for node in chain:
            self.pool.block_ref(node.bid)

    def release(self, chain: Sequence[_Node]) -> None:
        """Drop the live-slot references (session retirement — EOS,
        budget exhaustion, or a drain)."""
        for node in chain:
            self.pool.block_deref(node.bid)

    # ----- insertion / eviction ------------------------------------

    def _evict_one(self, protect: set) -> bool:
        """Evict the least-recently-used idle leaf.  Idle = ledger
        refcount 1 (the tree's own reference — no live slot);
        leaf = no children (evicting an interior node would leave
        descendants claiming a prefix whose head is gone).  ``protect``
        holds ids of nodes in the chain currently being extended —
        they are this insertion's own parents and must survive it."""
        best = None
        for node in self._nodes:
            if id(node) in protect or node.children:
                continue
            if self.pool.block_refcount(node.bid) != 1:
                continue
            if best is None or node.last_used < best.last_used:
                best = node
        if best is None:
            return False
        if best.parent is None:
            del self._root_children[best.key]
        else:
            del best.parent.children[best.key]
        self._nodes.remove(best)
        self.pool.block_deref(best.bid)  # 1 -> 0: ledger slot freed
        self.stats["evicted"] += 1
        return True

    def insert(self, tokens: Sequence[int], true_len: int,
               make_frag: Callable[[int], Any]
               ) -> Tuple[List[_Node], int, int]:
        """Cache every full block of ``tokens[:true_len]``, reusing
        nodes that already exist and calling ``make_frag(i)`` (the
        engine's fragment slicer — block i covers token positions
        ``[i*B, (i+1)*B)``) only for blocks the tree doesn't hold yet.

        Returns ``(chain, n_new, n_evicted)`` — the full node chain
        covering the prompt's blocks (existing + new; the caller pins
        it), how many were newly inserted, and how many idle leaves
        were evicted to make room.  Fills best-effort: when the ledger
        is exhausted and nothing is evictable the tail blocks simply
        stay uncached.
        """
        B = self.block_tokens
        toks = [int(t) for t in tokens[:true_len]]
        n_blocks = len(toks) // B
        chain: List[_Node] = []
        protect: set = set()
        children = self._root_children
        parent: Optional[_Node] = None
        n_new = n_evicted = 0
        for i in range(n_blocks):
            key = tuple(toks[i * B:(i + 1) * B])
            node = children.get(key)
            if node is None:
                bid = self.pool.block_alloc()
                while bid is None:
                    if not self._evict_one(protect):
                        # Full of held/interior blocks: stop caching
                        # the tail; what's in the chain so far is
                        # still valid and pinnable.
                        return chain, n_new, n_evicted
                    n_evicted += 1
                    bid = self.pool.block_alloc()
                node = _Node(key, make_frag(i), bid, parent)
                children[key] = node
                self._nodes.append(node)
                n_new += 1
                self.stats["inserted"] += 1
            self._touch(node)
            chain.append(node)
            protect.add(id(node))
            parent = node
            children = node.children
        return chain, n_new, n_evicted

    @property
    def n_nodes(self) -> int:
        return len(self._nodes)

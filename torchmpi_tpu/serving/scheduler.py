"""Admission queue + iteration-level scheduler (the continuous-batching
serving loop).

One :class:`Server` drives N :class:`~.engine.ReplicaEngine` replicas
through a :class:`~.router.Router` over a shared FIFO admission queue:

- every tick, newly-arrived requests are admitted into free slot blocks
  (prefill + first token — the TTFT event) and ONE ``[S, 1]`` decode
  step advances each replica's in-flight slots; finished sequences
  retire immediately and their blocks free for the next admission —
  iteration-level (in-flight/continuous) batching, vs. the static
  baseline that forms a full batch and runs everyone to the longest
  decode (``benchmarks/serving_bench.py`` measures the gap);
- the clock is virtual: each tick advances by the measured wall time of
  its work (or a fixed ``tick_seconds`` for deterministic tests/chaos
  runs), and arrivals from the trace are admitted when the clock
  passes their ``arrival_s`` — so Poisson traces replay identically
  while TTFT/inter-token latencies still reflect real compute cost;
- a replica step that raises a fault-layer error takes the resilience
  path instead of crashing the server: transient faults count against
  the health ledger (the replica's sessions stall a tick), and a hard
  failure — or a ledger verdict of ``raise`` — DRAINS the replica: its
  in-flight sessions re-enter the queue front and re-prefill from
  their emitted prefix on a healthy replica (token-exact: greedy is
  deterministic, and sampled decode keys token i on
  ``fold_in(PRNGKey(seed), i)`` — replica- and slot-independent).
  ``tm_serving_rerouted_total`` counts the moved sessions.

SLO observability rides the obs registry when telemetry is active
(``tm_serving_*`` — docs/OBSERVABILITY.md): TTFT and inter-token
latency histograms (microseconds) per replica, queue-depth and
slot-occupancy gauges per tick, request/token/completion counters.
``scripts/obs_tool.py slo`` turns the dumps into p50/p95/p99 tables.
"""

from __future__ import annotations

import dataclasses
import sys
import time
from collections import deque
from typing import List, Optional, Sequence

import numpy as np

from .. import runtime
from .engine import ReplicaEngine, RequestRejected, Session
from .fleet import AdmissionController, AdmissionRejected, \
    FleetController
from .router import Router


@dataclasses.dataclass
class Request:
    """One serving request.  ``max_new`` bounds the generated tokens;
    ``eos_id`` retires the sequence early.  The server fills in the
    result fields (``tokens`` — the emitted ids, eos included when hit
    — and the SLO timestamps, seconds on the virtual clock)."""

    rid: str
    prompt: np.ndarray
    max_new: int
    eos_id: Optional[int] = None
    arrival_s: float = 0.0
    # -- decode diversity (None -> the Config default) --
    # Sampling is bitwise-reproducible given (seed, prompt): token i
    # draws from fold_in(PRNGKey(seed), i) regardless of slot, pool
    # neighbors, replica, or re-routes.  temperature <= 0 is greedy;
    # top_k 0 / top_p 1.0 disable that filter.
    temperature: Optional[float] = None
    top_k: Optional[int] = None
    top_p: Optional[float] = None
    seed: int = 0
    # -- results (server-owned) --
    tokens: List[int] = dataclasses.field(default_factory=list)
    ttft_s: Optional[float] = None
    finish_s: Optional[float] = None
    replica: Optional[str] = None
    reroutes: int = 0
    # Set instead of tokens when the request is unservable (e.g. it can
    # never fit a slot block): the server rejects IT and keeps serving
    # everyone else — one bad request must not abort the trace.
    error: Optional[str] = None
    # True when the ADMISSION GATE shed this request (SLO backpressure
    # or a serving.admit chaos drop) — ``error`` carries the typed
    # AdmissionRejected text.  Distinct from an unservable rejection:
    # a shed request is perfectly servable, the fleet just can't meet
    # its TTFT budget right now.
    shed: bool = False
    # Clock of the most recent emitted token — carries the inter-token
    # gap across a drain/re-admission so the re-route stall really
    # lands in the ITL histogram.
    last_token_s: Optional[float] = None

    def latency_s(self) -> Optional[float]:
        if self.finish_s is None:
            return None
        return self.finish_s - self.arrival_s


def _obs():
    """The obs module when telemetry is active (sys.modules lookup —
    serving must not import the telemetry it reports to)."""
    mod = sys.modules.get("torchmpi_tpu.obs")
    try:
        if mod is not None and mod.active():
            return mod
    except Exception:  # noqa: BLE001 — telemetry never fails a tick
        pass
    return None


def _is_fault(e: BaseException) -> bool:
    """Is ``e`` a fault-layer error?  Checked via sys.modules: if the
    fault layer was never armed, the classes do not exist and no
    exception can be one (the restart.py discipline)."""
    mod = sys.modules.get("torchmpi_tpu.faults.inject")
    return mod is not None and isinstance(e, mod.FaultError)


class Server:
    """Continuous-batching server over ``replicas`` engine replicas of
    one ``(model, params)`` checkpoint.

    Replica count / slots / slot block size default from the active
    Config (``serving_replicas`` / ``serving_slots`` /
    ``serving_slot_tokens``).  ``devices`` optionally pins replica i to
    ``devices[i]`` (data-parallel spread on a multi-chip host).
    """

    # Class-level defaults so a hand-assembled Server (tests build one
    # via ``Server.__new__`` around a pre-wired Router) runs the trace
    # loop with the gate and the autoscaler disarmed.
    _admission = None
    _fleet = None

    def __init__(self, model, params, *, replicas: Optional[int] = None,
                 slots: Optional[int] = None,
                 slot_tokens: Optional[int] = None,
                 devices: Optional[Sequence] = None,
                 ledger=None, sample: Optional[float] = None,
                 prefill_bucket: Optional[int] = None,
                 spec_k: Optional[int] = None, draft=None,
                 engines: Optional[Sequence] = None,
                 prefix_cache: Optional[int] = None,
                 prefix_block: int = 8,
                 slo_ttft_us: Optional[float] = None,
                 autoscale: Optional[int] = None,
                 engine_factory=None,
                 scale_high_water: int = 4, scale_low_water: int = 0,
                 scale_sustain: int = 3):
        cfg = runtime.effective_config()
        if engines is None:
            n = int(replicas if replicas is not None
                    else cfg.serving_replicas)
            if n < 1:
                raise ValueError(f"need >= 1 replica, got {n}")
            if devices is not None and len(devices) < n:
                raise ValueError(
                    f"{n} replicas but only {len(devices)} devices")
            engines = [
                ReplicaEngine(model, params, name=f"replica{i}",
                              slots=slots, slot_tokens=slot_tokens,
                              device=devices[i] if devices is not None
                              else None, sample=sample,
                              prefill_bucket=prefill_bucket,
                              spec_k=spec_k, draft=draft,
                              prefix_cache=prefix_cache,
                              prefix_block=prefix_block)
                for i in range(n)]
            if engine_factory is None:
                # Default scale-up factory: a fresh dense replica with
                # the same knobs (no device pin — a scaled replica
                # lands wherever jax defaults it).
                def engine_factory(name, _m=model, _p=params):
                    return ReplicaEngine(
                        _m, _p, name=name, slots=slots,
                        slot_tokens=slot_tokens, sample=sample,
                        prefill_bucket=prefill_bucket, spec_k=spec_k,
                        draft=draft, prefix_cache=prefix_cache,
                        prefix_block=prefix_block)
        else:
            engines = list(engines)
        self.router = Router(engines, ledger=ledger)
        # SLO admission gate: live p95 TTFT vs the target, typed
        # AdmissionRejected shedding (fleet.py).  0 disarms.
        slo = float(slo_ttft_us if slo_ttft_us is not None
                    else cfg.serving_slo_ttft_us)
        self._admission = (AdmissionController(slo) if slo > 0
                           else None)
        # Queue-depth autoscaler: value = max replicas (0 disarms).
        amax = int(autoscale if autoscale is not None
                   else cfg.serving_autoscale)
        if amax > 0:
            if engine_factory is None:
                raise ValueError(
                    "autoscale needs an engine_factory when the server "
                    "is built from pre-made engines (it must be able "
                    "to construct a replica on scale-up)")
            self._fleet = FleetController(
                self.router, engine_factory=engine_factory,
                max_replicas=amax, min_replicas=len(engines),
                high_water=scale_high_water, low_water=scale_low_water,
                sustain=scale_sustain, drain=self._drain)
        else:
            self._fleet = None
        #: Filled by :meth:`run_trace`: ``ticks`` (work ticks run),
        #: ``busy_s`` (summed tick durations — the compute time
        #: throughput divides by), ``clock_s`` (final virtual clock,
        #: idle gaps included), ``tokens`` (total emitted).
        self.last_stats: dict = {}

    @classmethod
    def sharded(cls, params, *, tp: int, num_heads: int,
                slot_tokens: int, axis: str = "model",
                replicas: Optional[int] = None,
                devices: Optional[Sequence] = None, **kw) -> "Server":
        """A server whose every replica is a TP mesh slice: carve
        ``replicas`` disjoint ``tp``-device meshes from ``devices``
        (default ``jax.devices()``) and serve one
        :class:`~.tp_engine.TPReplicaEngine` per slice.  ``params`` is
        a full ``tp_generate.init_tp_lm`` tree (placed per mesh).
        Defaults to as many replicas as the device pool can hold."""
        import jax
        from jax.sharding import Mesh

        from .tp_engine import TPReplicaEngine

        if tp < 1:
            raise ValueError(f"tp must be >= 1, got {tp}")
        devices = list(devices if devices is not None else jax.devices())
        n = int(replicas) if replicas is not None else len(devices) // tp
        if n < 1 or n * tp > len(devices):
            raise ValueError(
                f"{n} replicas x {tp} devices need {n * tp} devices, "
                f"have {len(devices)}")
        engines = [
            TPReplicaEngine(
                params,
                mesh=Mesh(np.asarray(devices[i * tp:(i + 1) * tp]),
                          (axis,)),
                axis=axis, num_heads=num_heads, name=f"tp{i}",
                slot_tokens=slot_tokens, **kw)
            for i in range(n)]
        return cls(None, None, engines=engines)

    def _total_units(self) -> float:
        """Summed work units across ALL replicas (dead included —
        their spent work stays spent): prefills + pooled forwards at
        1.0, draft forwards at the proposer's weight.  The
        ``unit_seconds`` clock advances by the per-tick delta."""
        return sum(e.units for e in self.router.replicas)

    # -- the serving loop --------------------------------------------------

    def run_trace(self, requests: Sequence[Request], *,
                  tick_seconds: Optional[float] = None,
                  unit_seconds: Optional[float] = None,
                  max_ticks: int = 1_000_000) -> List[Request]:
        """Serve a whole arrival trace to completion; returns the
        requests in completion order (every one finished — the server
        refuses to lose work: with all replicas dead it raises).

        The virtual clock, per tick:

        - default (both None): each tick's measured wall time —
          latencies reflect real compute cost;
        - ``tick_seconds``: a fixed step per tick (deterministic tests
          / chaos runs);
        - ``unit_seconds``: the tick's WORK UNITS (prefills admitted +
          replica steps run, i.e. invocations of the two compiled
          executables) times this — deterministic like
          ``tick_seconds`` but load-faithful, since a tick that
          admitted three requests costs three prefills of clock.  The
          noise-immune schedule ``benchmarks/serving_bench.py``
          compares continuous vs static on.
        """
        if tick_seconds is not None and unit_seconds is not None:
            raise ValueError(
                "tick_seconds and unit_seconds are exclusive clock "
                "modes")
        arrivals = deque(sorted(requests, key=lambda r: r.arrival_s))
        pending: deque = deque()
        completed: List[Request] = []
        clock = busy = 0.0
        n_ticks = n_tokens = 0
        units_prev = self._total_units()
        for _tick in range(max_ticks):
            if not (arrivals or pending
                    or any(e.active for e in self.router.live())):
                self.last_stats = {"ticks": n_ticks, "busy_s": busy,
                                   "clock_s": clock,
                                   "tokens": n_tokens}
                return completed
            t0 = time.monotonic()
            while arrivals and arrivals[0].arrival_s <= clock:
                req = arrivals.popleft()
                shed = self._gate(req, len(pending))
                if shed is not None:
                    # Typed backpressure, not a timeout: the request
                    # completes immediately as shed with the evidence
                    # in .error, and the fleet's admitted latency
                    # budget stays intact.
                    req.error = shed
                    req.shed = True
                    req.finish_s = clock
                    completed.append(req)
                    continue
                pending.append(req)
            newly_admitted, stepped, finished, steps_run, rejected = \
                self._tick(pending)
            for req in rejected:
                req.finish_s = clock
                completed.append(req)
            worked = bool(newly_admitted or stepped or finished
                          or rejected)
            if not worked and not pending and arrivals and \
                    not any(e.active for e in self.router.live()):
                # Idle gap — nothing queued OR in flight: jump straight
                # to the next arrival instead of spinning the virtual
                # clock through empty ticks.  (In-flight sessions
                # stalled by a transient replica fault must NOT jump:
                # their tick still costs clock and samples gauges.)
                clock = max(clock, arrivals[0].arrival_s)
                continue
            if not worked and pending and not self.router.live():
                raise RuntimeError(
                    "all replicas dead with requests still queued")
            if unit_seconds is not None:
                # The delta of the engines' own unit ledgers, not a
                # recount here: speculative ticks bill 1 verify +
                # K x draft-weight, prefills 1 each — whatever the
                # engines actually ran is what the clock charges.
                units_now = self._total_units()
                n_units = units_now - units_prev
                units_prev = units_now
                elapsed = max(1.0, n_units) * unit_seconds
            elif tick_seconds is not None:
                elapsed = tick_seconds
            else:
                elapsed = max(time.monotonic() - t0, 1e-9)
            clock += elapsed
            busy += elapsed
            n_ticks += 1
            n_tokens += len(newly_admitted) + \
                sum(s.last_emit for s in stepped)
            self._record_tick(pending, newly_admitted, stepped,
                              finished, completed, clock, elapsed)
            if self._fleet is not None:
                event = self._fleet.tick(len(pending), pending)
                if event is not None:
                    mod = _obs()
                    if mod is not None:
                        mod.record_serving(event)
        raise RuntimeError(f"trace did not drain in {max_ticks} ticks")

    # -- one tick ----------------------------------------------------------

    def _tick(self, pending: deque):
        admitted: List[Session] = []
        finished: List[Session] = []
        stepped: List[Session] = []
        rejected: List[Request] = []
        steps_run = 0
        # Admission at the token boundary: fill free slot blocks from
        # the queue front, spread by router health/load.
        while pending:
            eng = self.router.pick()
            if eng is None:
                break
            req = pending.popleft()
            try:
                res = eng.admit(req)
            except RequestRejected as e:
                # Unservable request (can never fit a slot block):
                # reject IT and keep serving — one bad request must not
                # abort everyone else's trace.  Only this typed
                # rejection is absorbed; any other admission exception
                # is a real bug and stays loud.
                req.error = str(e)
                rejected.append(req)
                mod = _obs()
                if mod is not None:
                    mod.record_serving("rejected", replica=eng.name)
                continue
            if res is None:  # raced a full pool; retry next tick
                pending.appendleft(req)
                break
            sess, done = res
            req.replica = eng.name
            admitted.append(sess)
            if done:
                finished.append(sess)
        # One decode step per replica with in-flight slots.
        for eng in list(self.router.live()):
            if not eng.active:
                continue
            try:
                self._fire(eng.name)
                advanced, fin = eng.step()
                steps_run += 1
            except BaseException as e:  # noqa: BLE001 — resilience path
                if not self._handle_failure(eng, e, pending):
                    raise
                continue
            self.router.record(eng, True)
            stepped.extend(advanced)
            finished.extend(fin)
        return admitted, stepped, finished, steps_run, rejected

    @staticmethod
    def _fire(name: str) -> None:
        """The ``serving.replica`` chaos site: one arrival per replica
        step when the fault layer is armed (one string compare when
        off — the import discipline of every other site)."""
        if runtime.effective_config().faults == "off":
            return
        from .. import faults

        faults.fire("serving.replica", peer=name)

    def _gate(self, req: Request, depth: int) -> Optional[str]:
        """The admission gate, run once per arrival BEFORE it queues:
        the ``serving.admit`` chaos site (any fault verdict at the door
        is a shed — a dropped admission RPC and an SLO rejection look
        identical to the client), then the SLO admission controller.
        Returns the shed reason, or None to admit into the queue."""
        if runtime.effective_config().faults != "off":
            from .. import faults

            try:
                faults.fire("serving.admit", peer=req.rid)
            except BaseException as e:  # noqa: BLE001 — shed, not crash
                if not _is_fault(e):
                    raise
                mod = _obs()
                if mod is not None:
                    mod.record_serving("shed")
                return (f"request {req.rid!r} shed (fault at "
                        f"serving.admit): {e}")
        if self._admission is not None:
            try:
                self._admission.check(req.rid, depth)
            except AdmissionRejected as e:
                mod = _obs()
                if mod is not None:
                    mod.record_serving("shed")
                return str(e)
            mod = _obs()
            if mod is not None:
                mod.record_serving("admitted")
        return None

    def _handle_failure(self, eng: ReplicaEngine, e: BaseException,
                        pending: deque) -> bool:
        """Route a failed replica step; returns False to re-raise (not
        a fault-layer error — a model bug must stay loud)."""
        if not _is_fault(e):
            return False
        if getattr(e, "transient", False):
            verdict = self.router.record(eng, False)
        else:
            # Hard failure: the replica is gone now.
            self.router.mark_dead(eng)
            verdict = "raise"
        if verdict == "raise":
            self._drain(eng, pending)
        return True

    def _drain(self, eng: ReplicaEngine, pending: deque) -> None:
        """Dead replica: move its in-flight sessions to the queue FRONT
        (they already waited once) for re-prefill elsewhere."""
        sessions = eng.drain()
        eng.dead = True
        mod = _obs()
        if mod is not None and sessions:
            mod.record_serving("rerouted", len(sessions),
                               replica=eng.name)
        for sess in reversed(sessions):
            req = sess.request
            req.tokens.extend(sess.emitted)
            req.reroutes += 1
            pending.appendleft(req)

    # -- telemetry + result bookkeeping ------------------------------------

    def _record_tick(self, pending, admitted, stepped, finished,
                     completed, clock: float, elapsed: float) -> None:
        mod = _obs()
        for sess in admitted:
            req = sess.request
            if req.ttft_s is None:
                req.ttft_s = clock - req.arrival_s
                if self._admission is not None:
                    # Feed the SLO gate's rolling window regardless of
                    # telemetry — admission control must work with obs
                    # off.
                    self._admission.observe(req.ttft_s)
                if mod is not None:
                    mod.record_serving("requests", replica=req.replica)
                    mod.record_serving_latency("ttft", req.ttft_s,
                                               replica=req.replica)
            elif mod is not None:
                # Re-admission after a re-route: the WHOLE stall since
                # the session's last token (drain + queue wait +
                # re-prefill) is one long inter-token latency, not a
                # second TTFT — that is the SLO impact of the kill.
                since = (req.last_token_s if req.last_token_s is not None
                         else clock - elapsed)
                mod.record_serving_latency("itl", clock - since,
                                           replica=req.replica)
            req.last_token_s = clock
        for sess in finished:
            req = sess.request
            req.tokens.extend(sess.emitted)
            sess.emitted = []
            req.finish_s = clock
            completed.append(req)
            if mod is not None:
                mod.record_serving("completed", replica=req.replica)
        if mod is None:
            return
        for sess in stepped:
            req = sess.request
            # Gap since the request's LAST token, not this tick's
            # elapsed: equal for an unstalled session (its previous
            # token landed exactly one tick ago), but a session stalled
            # N ticks by transient replica faults — or re-admitted
            # after a drain this same tick (then the admission already
            # carried the stall and last_token_s is this clock) —
            # reports its true inter-token latency.  A speculative tick
            # that landed m tokens records m observations of gap/m:
            # the histogram keeps counting per TOKEN, and the spec win
            # shows up as the smaller per-token gap it is.
            since = (req.last_token_s if req.last_token_s is not None
                     else clock - elapsed)
            m = max(1, sess.last_emit)
            for _ in range(m):
                mod.record_serving_latency("itl", (clock - since) / m,
                                           replica=req.replica)
            req.last_token_s = clock
        n_tok = len(admitted) + sum(s.last_emit for s in stepped)
        if n_tok:
            by_rep: dict = {}
            for sess in admitted:
                by_rep[sess.request.replica] = \
                    by_rep.get(sess.request.replica, 0) + 1
            for sess in stepped:
                by_rep[sess.request.replica] = \
                    by_rep.get(sess.request.replica, 0) + sess.last_emit
            for rep, n in by_rep.items():
                mod.record_serving("tokens", n, replica=rep)
        mod.record_serving_depth(len(pending))
        for eng in self.router.live():
            mod.record_serving_occupancy(eng.pool.occupancy_pct(),
                                         replica=eng.name)
        # Tick boundary: the serving-side attribution window edge
        # (obs_tool attribute; docs/OBSERVABILITY.md).
        mod.record_step("serving_tick")

"""Paged KV-cache slot pool: fixed-size slot blocks, allocated per
request, freed on EOS/retirement.

A slot is one row of the replica's pool cache — a fixed block of
``slot_tokens`` KV positions.  Admission allocates a free slot, prefill
overwrites the row, retirement returns it to the free list, and the
per-row causal mask in the decode step makes reuse safe without zeroing
(stale entries beyond a row's filled prefix are ``-inf``'d out of every
attention, so a reused slot decodes bit-identically to a fresh cache —
asserted in tests/test_serving.py).

Memory therefore bounds at ``n_slots x slot_tokens`` cache positions per
replica — the slot pool's whole point: a long straggler pins ONE block,
not the whole batch's ``batch x max_len`` cache.

Dependency-free (no jax): the pool is bookkeeping; the cache arrays live
in the engine.
"""

from __future__ import annotations

from typing import Dict, List, Optional


class SlotPool:
    """Free-list of ``n_slots`` fixed-size KV blocks.

    LIFO reuse (the most recently freed slot is handed out first) keeps
    the hot block resident and the allocation order deterministic — the
    replica-kill chaos runs replay identically from a seed.

    ``prefix_blocks`` > 0 additionally arms the **refcounted block
    ledger** the radix prefix cache (:mod:`.prefix_cache`) accounts its
    shared cache fragments against: ``block_alloc`` hands out at most
    ``prefix_blocks`` live block ids (the cache's capacity), each born
    with refcount 1 (the tree's own reference); ``block_ref`` /
    ``block_deref`` move the count as live slots pin and release a
    shared block, and a deref to exactly zero frees the id.  Going
    below zero — or touching an id the ledger never issued — raises:
    a miscounted shared block is either a leak (capacity silently gone
    forever) or a use-after-free (an evicted fragment a live slot still
    believes in), and both must be loud.
    """

    def __init__(self, n_slots: int, slot_tokens: int,
                 prefix_blocks: int = 0):
        if n_slots < 1:
            raise ValueError(f"n_slots must be >= 1, got {n_slots}")
        if slot_tokens < 1:
            raise ValueError(
                f"slot_tokens must be >= 1, got {slot_tokens}")
        if prefix_blocks < 0:
            raise ValueError(
                f"prefix_blocks must be >= 0, got {prefix_blocks}")
        self.n_slots = int(n_slots)
        self.slot_tokens = int(slot_tokens)
        self._free: List[int] = list(range(n_slots - 1, -1, -1))
        self._in_use: set = set()
        self.prefix_blocks = int(prefix_blocks)
        #: block id -> refcount (1 = only the prefix tree holds it).
        self._block_refs: Dict[int, int] = {}
        #: Monotonic id source: ids are never reissued, so a stale id
        #: held across an eviction FAILS the ledger lookup instead of
        #: silently aliasing a new block (the ABA hazard).
        self._next_block = 0

    def fits(self, total_tokens: int) -> bool:
        """Can a request of ``prompt + max_new`` tokens ever live in one
        slot block?  (Admission-time check — an unservable request must
        be rejected at the door, not wedge a slot forever.)"""
        return 0 < total_tokens <= self.slot_tokens

    def alloc(self) -> Optional[int]:
        """Allocate a slot; None when the pool is exhausted (the request
        stays in the admission queue for the next tick)."""
        if not self._free:
            return None
        slot = self._free.pop()
        self._in_use.add(slot)
        return slot

    def free(self, slot: int) -> None:
        if slot not in self._in_use:
            raise ValueError(
                f"slot {slot} is not allocated (double free, or never "
                f"alloc'd from this pool)")
        self._in_use.remove(slot)
        self._free.append(slot)

    @property
    def in_use(self) -> int:
        return len(self._in_use)

    @property
    def free_count(self) -> int:
        return len(self._free)

    def occupancy_pct(self) -> float:
        """Percent of slot blocks in use — the ``tm_serving_slot_
        occupancy_pct`` gauge sample."""
        return 100.0 * len(self._in_use) / self.n_slots

    # ----- prefix-cache block ledger -------------------------------
    #
    # Refcount protocol (see prefix_cache.py for the tree that drives
    # it): a block is born at refcount 1 — the radix tree's own
    # reference.  Every live slot assembled from the block pins it
    # (+1 on admission, -1 at retirement), so refcount == 1 means
    # "cached but idle" — exactly the eviction-eligible state — and
    # refcount >= 2 means a live row was built from this fragment and
    # eviction would corrupt an in-flight decode.

    def block_alloc(self) -> Optional[int]:
        """Issue a new prefix block id at refcount 1, or None when the
        ledger is at ``prefix_blocks`` capacity (the cache must evict
        an idle block first — or give up and prefill in full)."""
        if len(self._block_refs) >= self.prefix_blocks:
            return None
        bid = self._next_block
        self._next_block += 1
        self._block_refs[bid] = 1
        return bid

    def block_ref(self, bid: int) -> int:
        """Pin ``bid`` (+1); returns the new refcount."""
        if bid not in self._block_refs:
            raise ValueError(f"block {bid} is not live in this ledger")
        self._block_refs[bid] += 1
        return self._block_refs[bid]

    def block_deref(self, bid: int) -> int:
        """Unpin ``bid`` (-1); at zero the id is freed and its capacity
        returns to the pool.  Returns the new refcount (0 = freed)."""
        if bid not in self._block_refs:
            raise ValueError(f"block {bid} is not live in this ledger")
        self._block_refs[bid] -= 1
        n = self._block_refs[bid]
        if n <= 0:
            # == 0: clean release.  < 0 can't happen — the ledger
            # entry is deleted the moment it reaches zero, so a second
            # deref lands in the "not live" raise above.
            del self._block_refs[bid]
        return n

    def block_refcount(self, bid: int) -> int:
        """Current refcount of ``bid`` (0 if not live)."""
        return self._block_refs.get(bid, 0)

    @property
    def blocks_in_use(self) -> int:
        """Live prefix block count (capacity used, any refcount)."""
        return len(self._block_refs)

"""Paged KV-cache slot pool: fixed-size slot blocks, allocated per
request, freed on EOS/retirement.

A slot is one row of the replica's pool cache — a fixed block of
``slot_tokens`` KV positions.  Admission allocates a free slot, prefill
overwrites the row, retirement returns it to the free list, and the
per-row causal mask in the decode step makes reuse safe without zeroing
(stale entries beyond a row's filled prefix are ``-inf``'d out of every
attention, so a reused slot decodes bit-identically to a fresh cache —
asserted in tests/test_serving.py).

Memory therefore bounds at ``n_slots x slot_tokens`` cache positions per
replica — the slot pool's whole point: a long straggler pins ONE block,
not the whole batch's ``batch x max_len`` cache.

Dependency-free (no jax): the pool is bookkeeping; the cache arrays live
in the engine.
"""

from __future__ import annotations

from typing import List, Optional


class SlotPool:
    """Free-list of ``n_slots`` fixed-size KV blocks.

    LIFO reuse (the most recently freed slot is handed out first) keeps
    the hot block resident and the allocation order deterministic — the
    replica-kill chaos runs replay identically from a seed.
    """

    def __init__(self, n_slots: int, slot_tokens: int):
        if n_slots < 1:
            raise ValueError(f"n_slots must be >= 1, got {n_slots}")
        if slot_tokens < 1:
            raise ValueError(
                f"slot_tokens must be >= 1, got {slot_tokens}")
        self.n_slots = int(n_slots)
        self.slot_tokens = int(slot_tokens)
        self._free: List[int] = list(range(n_slots - 1, -1, -1))
        self._in_use: set = set()

    def fits(self, total_tokens: int) -> bool:
        """Can a request of ``prompt + max_new`` tokens ever live in one
        slot block?  (Admission-time check — an unservable request must
        be rejected at the door, not wedge a slot forever.)"""
        return 0 < total_tokens <= self.slot_tokens

    def alloc(self) -> Optional[int]:
        """Allocate a slot; None when the pool is exhausted (the request
        stays in the admission queue for the next tick)."""
        if not self._free:
            return None
        slot = self._free.pop()
        self._in_use.add(slot)
        return slot

    def free(self, slot: int) -> None:
        if slot not in self._in_use:
            raise ValueError(
                f"slot {slot} is not allocated (double free, or never "
                f"alloc'd from this pool)")
        self._in_use.remove(slot)
        self._free.append(slot)

    @property
    def in_use(self) -> int:
        return len(self._in_use)

    @property
    def free_count(self) -> int:
        return len(self._free)

    def occupancy_pct(self) -> float:
        """Percent of slot blocks in use — the ``tm_serving_slot_
        occupancy_pct`` gauge sample."""
        return 100.0 * len(self._in_use) / self.n_slots

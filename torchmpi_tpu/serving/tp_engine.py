"""A Router replica that is a tensor-parallel MESH SLICE.

:class:`TPReplicaEngine` runs the same continuous-batching slot-pool
protocol as the dense :class:`~.engine.ReplicaEngine` — same
:class:`~.slots.SlotPool`, same :class:`~.engine.Session` lifecycle,
same sampling/bucketing/speculative machinery, driven by the same
scheduler — but its backend forwards are the shard_map primitives of
:mod:`~torchmpi_tpu.models.tp_generate` (``tp_slot_prefill`` /
``tp_slot_decode``): weights column/row-sharded 1/n over the model
axis, the pool KV cache head-sharded the same way, one psum per
sublayer per token plus the tiled LM-head all_gather.  A replica stops
being one device and becomes a mesh: the host spreads its chips over
``Server.sharded(...)`` replicas of ``tp`` devices each, continuous
batching included — the PR 9 dense-only limit, lifted.

The planner records one decision-only ``serving`` plan per replica at
construction, keyed by the replica's mesh via the topology fingerprint
(:func:`~torchmpi_tpu.planner.plan_serving_replica`), so a multi-mesh
serving fleet shows up in ``plan_tool.py dump-live`` as per-topology
rows.

Sampling keys, bucket padding, the accept loop, drain/re-route — all
inherited unchanged, and all bitwise-compatible: a session served by a
dense replica and one served by a TP replica emit identical streams
for the same checkpoint math, and a drained TP session re-prefills
token-exactly on ANY healthy replica.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from .. import runtime
from ..models.tp_generate import tp_slot_decode, tp_slot_prefill
from .engine import ReplicaEngine


class TPReplicaEngine(ReplicaEngine):
    """Slot-pooled decode engine whose replica is a TP mesh slice.

    ``params`` is a full tree from
    :func:`~torchmpi_tpu.models.tp_generate.init_tp_lm` (placed on
    ``mesh`` here via ``shard_tp_lm``).  ``slot_tokens`` must resolve
    to a positive block size (argument or ``serving_slot_tokens`` —
    the TP stack is rope-only, there is no ``max_len`` to default to).
    """

    def __init__(self, params, *, mesh, axis: str = "model",
                 num_heads: int, name: str = "tp0",
                 slots: Optional[int] = None,
                 slot_tokens: Optional[int] = None,
                 sample: Optional[float] = None,
                 prefill_bucket: Optional[int] = None,
                 spec_k: Optional[int] = None, draft=None,
                 prefix_cache: Optional[int] = None,
                 prefix_block: int = 8):
        from ..models.tp_generate import shard_tp_lm

        cfg = runtime.effective_config()
        slots = int(slots if slots is not None else cfg.serving_slots)
        st = int(slot_tokens if slot_tokens is not None
                 else (cfg.serving_slot_tokens or 0))
        if st <= 0:
            raise ValueError(
                "TPReplicaEngine needs an explicit slot block size "
                "(slot_tokens= or serving_slot_tokens > 0): the TP "
                "stack has no max_len to default to")
        self.mesh = mesh
        self.axis = axis
        self.num_heads = int(num_heads)
        self.depth = len(params["blocks"])
        self.vocab = int(params["embed"].shape[0])
        self.param_count = sum(int(np.prod(p.shape))
                               for p in jax.tree.leaves(params))
        self.params, self._specs = shard_tp_lm(params, mesh, axis)
        self.dmodel = None  # shard_map path — no flax decode clone
        self._device = None
        self._init_serving(cfg, name, slots, st, sample=sample,
                           prefill_bucket=prefill_bucket, spec_k=spec_k,
                           draft=draft, prefix_cache=prefix_cache,
                           prefix_block=prefix_block)
        # Zero pool cache: per block a head-sharded (k, v) pair
        # [S, slot_tokens, H, dh] — slots replicated, heads 1/n.
        from jax.sharding import NamedSharding, PartitionSpec as P

        hd = params["blocks"][0]["wq"].shape[-1] // self.num_heads
        self._head_dim = int(hd)
        self._cache_dtype = params["embed"].dtype
        sh = NamedSharding(mesh, P(None, None, axis, None))
        zero = jnp.zeros((slots, st, self.num_heads, hd),
                         params["embed"].dtype)
        self._cache = [(jax.device_put(zero, sh),
                        jax.device_put(zero, sh))
                       for _ in range(self.depth)]
        # One per-topology plan row per replica (dump-live evidence).
        from .. import planner

        planner.plan_serving_replica(name, mesh, (axis,))

    # -- backend hooks ------------------------------------------------------

    def _backend_prefill(self, prompt, true_len, sampling):
        return tp_slot_prefill(self.params, jnp.asarray(prompt),
                               mesh=self.mesh, axis=self.axis,
                               num_heads=self.num_heads,
                               t_max=self.pool.slot_tokens,
                               true_len=true_len, sampling=sampling)

    def _backend_step(self, toks, pos, sampling):
        self._cache, nxt = tp_slot_decode(
            self.params, self._cache,
            np.asarray(toks, np.int32)[:, None], pos,
            mesh=self.mesh, axis=self.axis, num_heads=self.num_heads,
            sampling=sampling)
        return np.asarray(nxt)[:, 0]

    def _backend_verify(self, toks, pos, sampling):
        self._cache, out = tp_slot_decode(
            self.params, self._cache, toks, pos, mesh=self.mesh,
            axis=self.axis, num_heads=self.num_heads, sampling=sampling)
        return np.asarray(out)

    def _row_template(self):
        from jax.sharding import NamedSharding, PartitionSpec as P

        sh = NamedSharding(self.mesh, P(None, None, self.axis, None))
        zero = jnp.zeros((1, self.pool.slot_tokens, self.num_heads,
                          self._head_dim), self._cache_dtype)
        return [(jax.device_put(zero, sh), jax.device_put(zero, sh))
                for _ in range(self.depth)]

    def _backend_extend(self, row_cache, suffix, depth, true_len,
                        sampling):
        # The extend forward IS tp_slot_decode on a 1-row cache:
        # [1, Ts] suffix tokens at per-row depth take the cache-masked
        # branch the speculative verify already uses, which is
        # shape-generic in both the row and token dims.  tp_slot_decode
        # keys position j on (seed, idx + j), so shift the idx operand
        # by -(true_len - 1): the TRUE last suffix position then
        # samples with exactly the request's global token index, and
        # the (discarded) earlier positions' keys don't matter.
        seeds, idxs, temps, tks, tps = sampling
        shifted = (seeds, idxs - jnp.int32(true_len - 1), temps, tks,
                   tps)
        row_cache, out = tp_slot_decode(
            self.params, row_cache,
            np.asarray(suffix, np.int32),
            np.asarray([depth], np.int32), mesh=self.mesh,
            axis=self.axis, num_heads=self.num_heads, sampling=shifted)
        return row_cache, np.asarray(out)[:, true_len - 1]

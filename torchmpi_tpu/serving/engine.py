"""One replica's continuous-batching decode engine.

A :class:`ReplicaEngine` owns one slot-pooled KV cache (leading dim =
slot count) plus the per-slot session bookkeeping, and exposes the two
iteration-level operations the scheduler composes:

- :meth:`admit` — allocate a slot, prefill the request's prompt onto a
  fresh cache, write it into the pool row, emit the FIRST token (the
  TTFT event).  Admission happens at token boundaries: no batch
  formation, no waiting for peers.
- :meth:`step` — ONE ``[S, 1]`` decode tick advancing every in-flight
  slot at its own cache depth (``models.generate.slot_decode_step``);
  sequences that emit EOS or reach their token budget retire
  immediately and their slot frees for the next admission.

Greedy decoding only (see ``models/generate.py``: re-routing a session
after a replica death re-prefills from its emitted prefix, which is
only token-exact when decoding is deterministic).

The engine is time-free and telemetry-free on purpose: the scheduler
owns the clock, the SLO histograms, and the fault hooks, so the engine
stays a pure slot/cache mechanism that tests can drive tick by tick.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from .. import runtime
from ..models.generate import slot_decode_step, slot_prefill, slot_write
from .slots import SlotPool


class RequestRejected(ValueError):
    """Raised by :meth:`ReplicaEngine.admit` for a request that can
    NEVER be served (its ``prompt + max_new`` exceeds the slot block).
    A dedicated type so the scheduler can reject exactly this case and
    keep serving — any other exception out of admission is a real bug
    and stays loud."""


@dataclasses.dataclass
class Session:
    """One in-flight request on one slot."""

    request: Any            # scheduler.Request
    slot: int
    last_tok: int           # pending token (input of the next step)
    pos_next: int           # absolute cache index the next step writes
    emitted: List[int] = dataclasses.field(default_factory=list)


class ReplicaEngine:
    """Slot-pooled decode engine for one model replica.

    ``slots``/``slot_tokens`` default from the active
    :class:`~torchmpi_tpu.config.Config` (``serving_slots`` /
    ``serving_slot_tokens``; 0 slot tokens = the model's ``max_len``).
    With ``device`` set, params and the pool cache are committed to that
    device, so replicas of one host spread over its chips exactly like
    data-parallel shards.
    """

    def __init__(self, model, params, *, name: str = "replica0",
                 slots: Optional[int] = None,
                 slot_tokens: Optional[int] = None,
                 device=None):
        cfg = runtime.effective_config()
        slots = int(slots if slots is not None else cfg.serving_slots)
        st = int(slot_tokens if slot_tokens is not None
                 else (cfg.serving_slot_tokens or 0))
        if st == 0:
            st = int(model.max_len)
        if getattr(model, "pos_emb", "learned") == "learned" \
                and st != model.max_len:
            raise ValueError(
                f"serving_slot_tokens={st} != model.max_len="
                f"{model.max_len}: a learned position table is sized by "
                f"max_len, so slot blocks can only be shrunk for "
                f"pos_emb='rope' models")
        if getattr(model, "moe_axis", None) is not None or \
                getattr(model, "seq_axis", None) is not None:
            raise ValueError(
                "ReplicaEngine serves dense single-device models; "
                "mesh-parallel decode stays on generate_parallel/"
                "tp_generate (static batch)")
        self.name = name
        self.pool = SlotPool(slots, st)
        self.dmodel = model.clone(decode=True, max_len=st)
        self.params = (jax.device_put(params, device)
                       if device is not None else params)
        self._device = device
        self.dead = False
        self._sessions: Dict[int, Session] = {}
        #: Executable-invocation counters (one prefill = one admit, one
        #: step = one [S, 1] tick) — the work-unit accounting
        #: benchmarks/serving_bench.py builds its noise-immune
        #: continuous-vs-static comparison on.
        self.stats = {"prefills": 0, "steps": 0}
        # Zero pool cache from the decode model's cache spec — no
        # forward pass runs at construction.
        shapes = jax.eval_shape(
            lambda: self.dmodel.init(
                jax.random.PRNGKey(0), jnp.zeros((slots, 1), jnp.int32),
                pos_offset=jnp.zeros((slots,), jnp.int32)))["cache"]
        cache = jax.tree.map(lambda s: jnp.zeros(s.shape, s.dtype),
                             shapes)
        self._cache = (jax.device_put(cache, device)
                       if device is not None else cache)

    # -- introspection -----------------------------------------------------

    @property
    def active(self) -> int:
        return len(self._sessions)

    def sessions(self) -> List[Session]:
        return list(self._sessions.values())

    def has_capacity(self) -> bool:
        return not self.dead and self.pool.free_count > 0

    # -- iteration-level operations ----------------------------------------

    def admit(self, request) -> Optional[Tuple[Session, bool]]:
        """Prefill ``request`` into a free slot; returns ``(session,
        finished)`` — ``finished`` when the first token already ends the
        request (EOS, or max_new == 1; its slot is freed again before
        returning).  None when the pool is full (caller retries next
        tick).  Raises on a request that can NEVER fit a slot block."""
        if self.dead:
            raise RuntimeError(f"{self.name} is dead")
        base = np.asarray(request.prompt, np.int32).reshape(-1)
        prev = np.asarray(getattr(request, "tokens", []) or [], np.int32)
        # A re-routed session re-prefills from its emitted prefix:
        # greedy decode is deterministic, so the continuation equals
        # what the dead replica would have produced.
        prompt = np.concatenate([base, prev]).reshape(1, -1)
        total = base.size + int(request.max_new)
        if not self.pool.fits(total):
            raise RequestRejected(
                f"request {request.rid!r}: prompt+max_new = {total} "
                f"exceeds the {self.pool.slot_tokens}-token slot block")
        slot = self.pool.alloc()
        if slot is None:
            return None
        try:
            self.stats["prefills"] += 1
            one_cache, first = slot_prefill(self.dmodel, self.params,
                                            jnp.asarray(prompt))
            self._cache = slot_write(self._cache, one_cache, slot)
            tok = int(np.asarray(first)[0])
        except BaseException:
            # A failed prefill must not leak the block: after `slots`
            # leaks the pool would be silently full forever.
            self.pool.free(slot)
            raise
        sess = Session(request=request, slot=slot, last_tok=tok,
                       pos_next=prompt.shape[1], emitted=[tok])
        if self._finished(sess):
            self.pool.free(slot)
            return sess, True
        self._sessions[slot] = sess
        return sess, False

    def step(self) -> Tuple[List[Session], List[Session]]:
        """One decode tick over every in-flight slot; returns
        ``(advanced, finished)``.  Finished sessions are already retired
        (slot freed) — their blocks are reusable in the same tick."""
        if self.dead:
            raise RuntimeError(f"{self.name} is dead")
        if not self._sessions:
            return [], []
        self.stats["steps"] += 1
        S = self.pool.n_slots
        toks = np.zeros((S,), np.int32)
        pos = np.zeros((S,), np.int32)
        for slot, sess in self._sessions.items():
            toks[slot] = sess.last_tok
            pos[slot] = sess.pos_next
        self._cache, nxt = slot_decode_step(
            self.dmodel, self.params, self._cache, toks, pos)
        nxt = np.asarray(nxt)
        advanced, finished = [], []
        for slot in list(self._sessions):
            sess = self._sessions[slot]
            sess.last_tok = int(nxt[slot])
            sess.pos_next += 1
            sess.emitted.append(sess.last_tok)
            advanced.append(sess)
            if self._finished(sess):
                del self._sessions[slot]
                self.pool.free(slot)
                finished.append(sess)
        return advanced, finished

    def drain(self) -> List[Session]:
        """Mark this replica dead and hand its in-flight sessions back
        for re-routing (their cache state is presumed lost with the
        replica — the scheduler re-prefills each from its emitted
        prefix on a healthy replica)."""
        self.dead = True
        out = list(self._sessions.values())
        for sess in out:
            self.pool.free(sess.slot)
        self._sessions.clear()
        return out

    # -- internals ---------------------------------------------------------

    @staticmethod
    def _finished(sess: Session) -> bool:
        req = sess.request
        if req.eos_id is not None and sess.last_tok == int(req.eos_id):
            return True
        done_before = len(req.tokens) if hasattr(req, "tokens") else 0
        return done_before + len(sess.emitted) >= int(req.max_new)

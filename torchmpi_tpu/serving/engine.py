"""One replica's continuous-batching decode engine.

A :class:`ReplicaEngine` owns one slot-pooled KV cache (leading dim =
slot count) plus the per-slot session bookkeeping, and exposes the two
iteration-level operations the scheduler composes:

- :meth:`admit` — allocate a slot, prefill the request's prompt onto a
  fresh cache, write it into the pool row, emit the FIRST token (the
  TTFT event).  Admission happens at token boundaries: no batch
  formation, no waiting for peers.  With ``prefill_bucket`` set the
  prompt is right-padded to a pow-2 length bucket, so the prefill
  executable count is O(buckets) instead of O(distinct lengths) — the
  emitted token is bitwise the unpadded one (the logits are sliced at
  the true last position; causality keeps it independent of padding).
  New executables are counted (``stats["prefill_compiles"]`` +
  ``tm_serving_prefill_compiles_total``) on the bucketed AND unbucketed
  paths, so the recompile cost is visible either way.
- :meth:`step` — ONE ``[S, 1]`` decode tick advancing every in-flight
  slot at its own cache depth (``models.generate.slot_decode_step``);
  sequences that emit EOS or reach their token budget retire
  immediately and their slot frees for the next admission.  With
  ``spec_k`` > 0 the tick becomes draft-then-verify: a
  :mod:`.spec` proposer drafts K tokens per slot, ONE ``[S, K+1]``
  target forward (``slot_verify_step``) scores them all, and the
  accept loop emits tokens exactly while drafts match — the stream is
  **bitwise-identical** to the non-speculative tick at the same seed,
  it just lands up to K+1 tokens per forward.

Sampling is per-request (temperature / top-k / top-p / seed, resolved
against the Config defaults at admission) and bitwise-reproducible
given (seed, prompt): token ``i`` of a request draws from
``fold_in(PRNGKey(seed), i)`` regardless of slot, pool neighbors, or
re-routes — which is also what keeps a drained session token-exact
when it re-prefills elsewhere (greedy OR sampled).

Work accounting: ``stats`` counts executable invocations and
``units`` accumulates work units (prefill = 1, pooled forward = 1,
draft forwards at the proposer's ``unit_weight``) — the noise-immune
clock ``benchmarks/serving_bench.py`` compares schedules on.

The engine is time-free and telemetry-free on purpose (the one
exception: the prefill-compile counter above, which is a property of
the engine's own jit keying): the scheduler owns the clock, the SLO
histograms, and the fault hooks, so the engine stays a pure slot/cache
mechanism that tests can drive tick by tick.
"""

from __future__ import annotations

import dataclasses
import sys
from typing import Any, Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from .. import runtime
from ..models.generate import slot_cache_slice, slot_cache_write, \
    slot_decode_step, slot_extend, slot_prefill, slot_verify_step, \
    slot_write
from .prefix_cache import PrefixCache
from .slots import SlotPool


class RequestRejected(ValueError):
    """Raised by :meth:`ReplicaEngine.admit` for a request that can
    NEVER be served (its ``prompt + max_new`` exceeds the slot block,
    or its sampling knobs are invalid).  A dedicated type so the
    scheduler can reject exactly this case and keep serving — any
    other exception out of admission is a real bug and stays loud."""


def _obs():
    mod = sys.modules.get("torchmpi_tpu.obs")
    try:
        if mod is not None and mod.active():
            return mod
    except Exception:  # noqa: BLE001 — telemetry never fails a tick
        pass
    return None


@dataclasses.dataclass
class Session:
    """One in-flight request on one slot."""

    request: Any            # scheduler.Request
    slot: int
    last_tok: int           # pending token (input of the next step)
    pos_next: int           # absolute cache index the next step writes
    emitted: List[int] = dataclasses.field(default_factory=list)
    #: Resolved (temperature, top_k, top_p, seed); greedy rows carry
    #: the filter no-op sentinels (0.0, 0, 2.0).
    sampling: Tuple[float, int, float, int] = (0.0, 0, 2.0, 0)
    #: Tokens emitted by the LAST tick that advanced this session (1
    #: for admit/plain step, up to K+1 for a speculative tick) — the
    #: scheduler's token/ITL accounting reads it.
    last_emit: int = 1
    #: Prefix-cache nodes this session pinned at admission (empty when
    #: the cache is off or missed) — released at retirement so idle
    #: blocks become evictable again.
    prefix_chain: List[Any] = dataclasses.field(default_factory=list)


class ReplicaEngine:
    """Slot-pooled decode engine for one model replica.

    ``slots``/``slot_tokens``/``sample``/``prefill_bucket``/``spec_k``
    default from the active :class:`~torchmpi_tpu.config.Config`
    (``serving_slots`` / ``serving_slot_tokens`` / ``serving_sample`` /
    ``serving_prefill_buckets`` / ``serving_spec_k``).  ``draft`` is a
    :mod:`.spec` proposer template (bound per engine); ``spec_k`` > 0
    with no draft binds an :class:`~.spec.NgramDraft`.  With ``device``
    set, params and the pool cache are committed to that device, so
    replicas of one host spread over its chips exactly like
    data-parallel shards.
    """

    def __init__(self, model, params, *, name: str = "replica0",
                 slots: Optional[int] = None,
                 slot_tokens: Optional[int] = None,
                 device=None, sample: Optional[float] = None,
                 prefill_bucket: Optional[int] = None,
                 spec_k: Optional[int] = None, draft=None,
                 prefix_cache: Optional[int] = None,
                 prefix_block: int = 8):
        cfg = runtime.effective_config()
        slots = int(slots if slots is not None else cfg.serving_slots)
        st = int(slot_tokens if slot_tokens is not None
                 else (cfg.serving_slot_tokens or 0))
        if st == 0:
            st = int(model.max_len)
        if getattr(model, "pos_emb", "learned") == "learned" \
                and st != model.max_len:
            raise ValueError(
                f"serving_slot_tokens={st} != model.max_len="
                f"{model.max_len}: a learned position table is sized by "
                f"max_len, so slot blocks can only be shrunk for "
                f"pos_emb='rope' models")
        if getattr(model, "moe_axis", None) is not None or \
                getattr(model, "seq_axis", None) is not None:
            raise ValueError(
                "ReplicaEngine serves dense single-device models; use "
                "serving.TPReplicaEngine (or Server.sharded) for a "
                "mesh-parallel replica")
        self.dmodel = model.clone(decode=True, max_len=st)
        self.params = (jax.device_put(params, device)
                       if device is not None else params)
        self._device = device
        self.vocab = int(model.vocab)
        self.param_count = sum(int(np.prod(p.shape))
                               for p in jax.tree.leaves(params))
        self._init_serving(cfg, name, slots, st, sample=sample,
                           prefill_bucket=prefill_bucket, spec_k=spec_k,
                           draft=draft, prefix_cache=prefix_cache,
                           prefix_block=prefix_block)
        # Zero pool cache from the decode model's cache spec — no
        # forward pass runs at construction.
        shapes = jax.eval_shape(
            lambda: self.dmodel.init(
                jax.random.PRNGKey(0), jnp.zeros((slots, 1), jnp.int32),
                pos_offset=jnp.zeros((slots,), jnp.int32)))["cache"]
        cache = jax.tree.map(lambda s: jnp.zeros(s.shape, s.dtype),
                             shapes)
        self._cache = (jax.device_put(cache, device)
                       if device is not None else cache)

    def _init_serving(self, cfg, name, slots, st, *, sample,
                      prefill_bucket, spec_k, draft,
                      prefix_cache=None, prefix_block=8):
        """Backend-independent serving state (shared with the
        mesh-parallel subclass, which does NOT run the dense
        ``__init__``)."""
        self.name = name
        cap = int(prefix_cache if prefix_cache is not None
                  else cfg.serving_prefix_cache)
        self.pool = SlotPool(slots, st, prefix_blocks=cap)
        if cap > 0:
            self._prefix = PrefixCache(
                self.pool, block_tokens=min(int(prefix_block), st))
        else:
            self._prefix = None
        #: Lazily built 1-row zero cache (the assembly canvas for
        #: prefix-cache hits) — jax arrays are immutable, so one
        #: template serves every admission.
        self._row_zero = None
        self.dead = False
        self._sessions: Dict[int, Session] = {}
        self._sample_default = float(
            sample if sample is not None else cfg.serving_sample)
        self._bucket = int(prefill_bucket if prefill_bucket is not None
                           else cfg.serving_prefill_buckets)
        self._spec_k = int(spec_k if spec_k is not None
                           else cfg.serving_spec_k)
        if self._spec_k > 0:
            if draft is None:
                from .spec import NgramDraft

                draft = NgramDraft()
            self._draft = draft.bind(self)
        else:
            self._draft = None
        #: Padded prompt lengths this engine has prefilled — each new
        #: one is one jit specialization, i.e. one XLA compile.
        self._prefill_lens: set = set()
        #: Executable-invocation counters — the work-unit accounting
        #: benchmarks/serving_bench.py builds its noise-immune
        #: continuous-vs-static comparison on.  ``spec_drafted`` /
        #: ``spec_accepted`` give the live acceptance rate.
        self.stats = {"prefills": 0, "steps": 0, "prefill_compiles": 0,
                      "spec_steps": 0, "spec_drafted": 0,
                      "spec_accepted": 0, "prefill_tokens": 0,
                      "prefix_hits": 0, "prefix_misses": 0}
        #: Work units spent (prefill/pooled forward = 1 each, draft
        #: forwards at the proposer's weight) — the scheduler's
        #: ``unit_seconds`` virtual clock advances by the delta.
        self.units = 0.0

    # -- introspection -----------------------------------------------------

    @property
    def active(self) -> int:
        return len(self._sessions)

    def sessions(self) -> List[Session]:
        return list(self._sessions.values())

    def has_capacity(self) -> bool:
        return not self.dead and self.pool.free_count > 0

    # -- sampling / bucketing resolution -----------------------------------

    def _resolve_sampling(self, request) -> Tuple[float, int, float, int]:
        """Per-request knobs against the Config default, validated.
        Greedy requests are FORCED to the filter no-op sentinels
        (temp 0.0, top_k 0, top_p 2.0) so the greedy stream is bitwise
        the unfiltered argmax regardless of stray k/p values."""
        t = getattr(request, "temperature", None)
        t = self._sample_default if t is None else float(t)
        seed = int(getattr(request, "seed", 0) or 0)
        if t <= 0.0:
            return (0.0, 0, 2.0, seed)
        k = getattr(request, "top_k", None)
        k = 0 if k is None else int(k)
        p = getattr(request, "top_p", None)
        p = 2.0 if p is None else float(p)
        if k < 0:
            raise RequestRejected(
                f"request {request.rid!r}: top_k must be >= 0 "
                f"(0 = off), got {k}")
        if p != 2.0 and not 0.0 < p <= 1.0:
            raise RequestRejected(
                f"request {request.rid!r}: top_p must be in (0, 1], "
                f"got {p}")
        return (t, k, p, seed)

    def _pad_prompt(self, prompt: np.ndarray,
                    cap: Optional[int] = None) -> Tuple[np.ndarray, int]:
        """Right-pad to the pow-2 bucket (>= ``prefill_bucket``, capped
        at ``cap`` — default the slot block; a prefix-hit suffix caps
        at the room REMAINING above the assembled depth so the padded
        write provably stays inside the row).  Returns ``(padded,
        true_len)``."""
        true_len = prompt.shape[1]
        if self._bucket <= 0:
            return prompt, true_len
        bucket = max(self._bucket, 1 << max(0, true_len - 1).bit_length())
        bucket = min(bucket,
                     self.pool.slot_tokens if cap is None else cap)
        if bucket <= true_len:
            return prompt, true_len
        padded = np.zeros((1, bucket), prompt.dtype)
        padded[:, :true_len] = prompt
        return padded, true_len

    def _count_prefill_compile(self, key) -> None:
        """A prompt length this engine has not prefilled before is one
        new jit specialization — one XLA compile.  Counted on the
        bucketed and unbucketed paths alike, so the per-distinct-length
        recompile cost is visible BEFORE bucketing is turned on.
        ``key`` is the padded length for full prefill, or ``("ext",
        padded_suffix_len)`` for the prefix-hit extend forward (its own
        executable family)."""
        if key in self._prefill_lens:
            return
        self._prefill_lens.add(key)
        self.stats["prefill_compiles"] += 1
        mod = _obs()
        if mod is not None:
            mod.record_serving("prefill_compiles", replica=self.name)

    def _sampling_arrays(self, sessions: Dict[int, Session]):
        """[S] operand arrays for the pooled forwards.  ``idxs`` is
        each session's global emitted-token index (pre-reroute tokens
        included) — the fold_in schedule that makes sampling a pure
        function of (seed, token index)."""
        S = self.pool.n_slots
        seeds = np.zeros((S,), np.uint32)
        idxs = np.zeros((S,), np.int32)
        temps = np.zeros((S,), np.float32)
        tks = np.zeros((S,), np.int32)
        tps = np.full((S,), 2.0, np.float32)
        for slot, sess in sessions.items():
            t, k, p, seed = sess.sampling
            seeds[slot] = np.uint32(seed)
            idxs[slot] = len(getattr(sess.request, "tokens", []) or []) \
                + len(sess.emitted)
            temps[slot] = t
            tks[slot] = k
            tps[slot] = p
        return (jnp.asarray(seeds), jnp.asarray(idxs),
                jnp.asarray(temps), jnp.asarray(tks), jnp.asarray(tps))

    # -- backend hooks (overridden by the mesh-parallel subclass) ----------

    def _backend_prefill(self, prompt: np.ndarray, true_len: int,
                         sampling):
        # Module-global lookup on purpose: tests monkeypatch
        # ``engine.slot_prefill`` to inject prefill failures.
        return slot_prefill(self.dmodel, self.params,
                            jnp.asarray(prompt), true_len=true_len,
                            sampling=sampling)

    def _backend_step(self, toks: np.ndarray, pos: np.ndarray, sampling):
        self._cache, nxt = slot_decode_step(
            self.dmodel, self.params, self._cache, toks, pos,
            sampling=sampling)
        return np.asarray(nxt)

    def _backend_verify(self, toks: np.ndarray, pos: np.ndarray,
                        sampling):
        self._cache, out = slot_verify_step(
            self.dmodel, self.params, self._cache, toks, pos,
            sampling=sampling)
        return np.asarray(out)

    def _row_template(self):
        """Fresh single-row zero cache — the canvas prefix-cache
        fragments are assembled onto before the extend forward."""
        shapes = jax.eval_shape(
            lambda: self.dmodel.init(
                jax.random.PRNGKey(0), jnp.zeros((1, 1), jnp.int32),
                pos_offset=jnp.zeros((1,), jnp.int32)))["cache"]
        row = jax.tree.map(lambda s: jnp.zeros(s.shape, s.dtype),
                           shapes)
        return (jax.device_put(row, self._device)
                if self._device is not None else row)

    def _backend_extend(self, row_cache, suffix: np.ndarray, depth: int,
                        true_len: int, sampling):
        return slot_extend(self.dmodel, self.params, row_cache,
                           jnp.asarray(suffix),
                           pos_offset=np.asarray([depth], np.int32),
                           true_len=true_len, sampling=sampling)

    # -- iteration-level operations ----------------------------------------

    def admit(self, request) -> Optional[Tuple[Session, bool]]:
        """Prefill ``request`` into a free slot; returns ``(session,
        finished)`` — ``finished`` when the first token already ends the
        request (EOS, or max_new == 1; its slot is freed again before
        returning).  None when the pool is full (caller retries next
        tick).  Raises on a request that can NEVER fit a slot block."""
        if self.dead:
            raise RuntimeError(f"{self.name} is dead")
        sampling = self._resolve_sampling(request)
        base = np.asarray(request.prompt, np.int32).reshape(-1)
        prev = np.asarray(getattr(request, "tokens", []) or [], np.int32)
        # A re-routed session re-prefills from its emitted prefix: the
        # continuation equals what the dead replica would have produced
        # — greedy decode is deterministic, and sampled decode keys
        # each token on (seed, token index), both independent of which
        # replica/slot serves it.
        prompt = np.concatenate([base, prev]).reshape(1, -1)
        total = base.size + int(request.max_new)
        if not self.pool.fits(total):
            raise RequestRejected(
                f"request {request.rid!r}: prompt+max_new = {total} "
                f"exceeds the {self.pool.slot_tokens}-token slot block")
        slot = self.pool.alloc()
        if slot is None:
            return None
        try:
            self.stats["prefills"] += 1
            self.units += 1.0
            samp = tuple(jnp.asarray(np.asarray([v], d)) for v, d in
                         zip((sampling[3], prev.size, sampling[0],
                              sampling[1], sampling[2]),
                             (np.uint32, np.int32, np.float32, np.int32,
                              np.float32)))
            chain = (self._prefix.match(prompt[0])
                     if self._prefix is not None else [])
            if chain:
                # Cache hit: assemble the matched fragments onto a
                # fresh row and run the forward over ONLY the unshared
                # suffix.  The sampling operand (idx = the request's
                # global token index) is untouched by the hit, so the
                # fold_in schedule — and therefore every emitted token
                # — is bitwise the miss path's.
                B = self._prefix.block_tokens
                depth = B * len(chain)
                if self._row_zero is None:
                    self._row_zero = self._row_template()
                row = self._row_zero
                for i, node in enumerate(chain):
                    row = slot_cache_write(row, node.frag, i * B)
                padded, true_len = self._pad_prompt(
                    prompt[:, depth:],
                    cap=self.pool.slot_tokens - depth)
                self._count_prefill_compile(("ext", padded.shape[1]))
                one_cache, first = self._backend_extend(
                    row, padded, depth, true_len, samp)
                self.stats["prefix_hits"] += 1
            else:
                depth = 0
                padded, true_len = self._pad_prompt(prompt)
                self._count_prefill_compile(padded.shape[1])
                one_cache, first = self._backend_prefill(
                    padded, true_len, samp)
                if self._prefix is not None:
                    self.stats["prefix_misses"] += 1
            self.stats["prefill_tokens"] += int(padded.shape[1])
            self._cache = slot_write(self._cache, one_cache, slot)
            tok = int(np.asarray(first)[0])
            full_chain: List[Any] = []
            n_new = n_evicted = 0
            if self._prefix is not None:
                # Cache every full block of the TRUE prompt from the
                # row we just computed (one_cache covers the assembled
                # depth + the suffix, so slicing works for matched and
                # new blocks alike; insert only materializes the new
                # ones), then pin the whole chain for this session's
                # lifetime — eviction can never touch a block a live
                # slot was built from.
                B = self._prefix.block_tokens
                full_chain, n_new, n_evicted = self._prefix.insert(
                    prompt[0], prompt.shape[1],
                    lambda i: slot_cache_slice(one_cache, i * B, B))
                self._prefix.pin(full_chain)
                mod = _obs()
                if mod is not None:
                    if chain:
                        mod.record_serving("prefix_hits",
                                           replica=self.name)
                        mod.record_serving("prefix_tokens_saved", depth,
                                           replica=self.name)
                        mod.record_serving(
                            "prefix_bytes_saved",
                            sum(n.nbytes for n in chain),
                            replica=self.name)
                    else:
                        mod.record_serving("prefix_misses",
                                           replica=self.name)
                    if n_new:
                        mod.record_serving("prefix_inserted", n_new,
                                           replica=self.name)
                    if n_evicted:
                        mod.record_serving("prefix_evicted", n_evicted,
                                           replica=self.name)
        except BaseException:
            # A failed prefill must not leak the block: after `slots`
            # leaks the pool would be silently full forever.  (Prefix
            # pins are taken LAST, after every fallible op, so there is
            # never a pinned chain to unwind here.)
            self.pool.free(slot)
            raise
        sess = Session(request=request, slot=slot, last_tok=tok,
                       pos_next=prompt.shape[1], emitted=[tok],
                       sampling=sampling, last_emit=1,
                       prefix_chain=full_chain)
        if self._finished(sess):
            self.pool.free(slot)
            self._retire_prefix(sess)
            return sess, True
        self._sessions[slot] = sess
        if self._draft is not None:
            self.units += self._draft.admit(slot, sess)
        return sess, False

    def step(self) -> Tuple[List[Session], List[Session]]:
        """One decode tick over every in-flight slot; returns
        ``(advanced, finished)``.  Finished sessions are already retired
        (slot freed) — their blocks are reusable in the same tick.
        Speculative when a draft is bound (up to K+1 tokens per session
        per tick, bitwise the plain stream)."""
        if self.dead:
            raise RuntimeError(f"{self.name} is dead")
        if not self._sessions:
            return [], []
        if self._draft is not None:
            return self._spec_step()
        self.stats["steps"] += 1
        self.units += 1.0
        S = self.pool.n_slots
        toks = np.zeros((S,), np.int32)
        pos = np.zeros((S,), np.int32)
        for slot, sess in self._sessions.items():
            toks[slot] = sess.last_tok
            pos[slot] = sess.pos_next
        nxt = self._backend_step(toks, pos,
                                 self._sampling_arrays(self._sessions))
        advanced, finished = [], []
        for slot in list(self._sessions):
            sess = self._sessions[slot]
            sess.last_tok = int(nxt[slot])
            sess.pos_next += 1
            sess.emitted.append(sess.last_tok)
            sess.last_emit = 1
            advanced.append(sess)
            if self._finished(sess):
                del self._sessions[slot]
                self.pool.free(slot)
                self._retire_prefix(sess)
                finished.append(sess)
        return advanced, finished

    def _spec_step(self) -> Tuple[List[Session], List[Session]]:
        """Draft K, verify in ONE [S, K+1] forward, accept while the
        drafts match what the target samples.  Every kept sample
        conditions only on accepted tokens, so the emitted stream is
        bitwise the non-speculative one at the same (seed, prompt) —
        drafting moves SPEED, never content."""
        sessions = dict(self._sessions)
        # The [S, K+1] verify writes K+1 cache positions per row at its
        # own offset; a row near the end of its slot block has less
        # room than that, and an out-of-range dynamic_update_slice
        # CLAMPS the start index — silent corruption.  Clamp K to the
        # tick's tightest room instead (>= 0: an in-flight session
        # always has 1 free position for its next token).
        room = min(self.pool.slot_tokens - s.pos_next
                   for s in sessions.values())
        K = min(self._spec_k, max(0, room - 1))
        # Sampling arrays BEFORE drafting: idxs must index the first
        # token this tick emits.
        samp = self._sampling_arrays(sessions)
        drafts, draft_units = self._draft.propose(sessions, K)
        S = self.pool.n_slots
        toks = np.zeros((S, K + 1), np.int32)
        pos = np.zeros((S,), np.int32)
        for slot, sess in sessions.items():
            d = list(drafts.get(slot, []))[:K]
            toks[slot, 0] = sess.last_tok
            if d:
                toks[slot, 1:1 + len(d)] = d
            pos[slot] = sess.pos_next
        self.stats["steps"] += 1
        self.stats["spec_steps"] += 1
        self.units += 1.0 + float(draft_units)
        out = self._backend_verify(toks, pos, samp)
        advanced, finished = [], []
        tick_drafted = tick_accepted = 0
        for slot, sess in sessions.items():
            d = list(drafts.get(slot, []))[:K]
            row = out[slot]
            m = 0
            for j in range(len(d) + 1):
                t = int(row[j])
                sess.last_tok = t
                sess.emitted.append(t)
                m += 1
                if self._finished(sess):
                    break
                if j < len(d) and t != d[j]:
                    # Mismatch: t IS the corrected token (sampled from
                    # the accepted prefix); the remaining samples
                    # conditioned on the wrong draft and are dropped.
                    break
            sess.pos_next += m
            sess.last_emit = m
            tick_drafted += len(d)
            tick_accepted += sum(1 for j in range(min(m, len(d)))
                                 if int(row[j]) == d[j])
            advanced.append(sess)
            if self._finished(sess):
                del self._sessions[slot]
                self.pool.free(slot)
                self._retire_prefix(sess)
                self._draft.free(slot)
                finished.append(sess)
            else:
                self._draft.observe(slot, sess)
        self.stats["spec_drafted"] += tick_drafted
        self.stats["spec_accepted"] += tick_accepted
        mod = _obs()
        if mod is not None:
            if tick_drafted:
                mod.record_serving("spec_drafted", tick_drafted,
                                   replica=self.name)
            if tick_accepted:
                mod.record_serving("spec_accepted", tick_accepted,
                                   replica=self.name)
        return advanced, finished

    def drain(self) -> List[Session]:
        """Mark this replica dead and hand its in-flight sessions back
        for re-routing (their cache state is presumed lost with the
        replica — the scheduler re-prefills each from its emitted
        prefix on a healthy replica).  Draft state is discarded with
        the replica: nothing speculative survives the move."""
        self.dead = True
        out = list(self._sessions.values())
        for sess in out:
            self.pool.free(sess.slot)
            self._retire_prefix(sess)
        self._sessions.clear()
        if self._draft is not None:
            self._draft.drain()
        return out

    # -- internals ---------------------------------------------------------

    def _retire_prefix(self, sess: Session) -> None:
        """Release the session's prefix-block pins (refcounts fall back
        toward 1 = idle/evictable; exactly zero leaks by construction —
        the ledger raises on a double release)."""
        if sess.prefix_chain:
            self._prefix.release(sess.prefix_chain)
            sess.prefix_chain = []

    @staticmethod
    def _finished(sess: Session) -> bool:
        req = sess.request
        if req.eos_id is not None and sess.last_tok == int(req.eos_id):
            return True
        done_before = len(req.tokens) if hasattr(req, "tokens") else 0
        return done_before + len(sess.emitted) >= int(req.max_new)

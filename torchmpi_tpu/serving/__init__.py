"""Continuous-batching serving layer (docs/SERVING.md).

The request-level server over the decode stack: an admission queue +
iteration-level scheduler (:mod:`.scheduler`) injects newly-arrived
requests into the running decode batch at token boundaries and retires
finished sequences immediately; a paged KV slot pool (:mod:`.slots`)
bounds cache memory at ``slots x block`` instead of ``batch x
max_len``; a health-routed multi-replica router (:mod:`.router`)
spreads sessions over data-parallel replicas and drains + re-routes a
dead replica's in-flight sessions instead of crashing the server; and
per-request SLO telemetry (TTFT / inter-token latency histograms,
queue-depth and slot-occupancy gauges) rides the obs registry as
``tm_serving_*`` when telemetry is on.

Off by default and **never imported unless used** — the analysis/obs/
faults discipline: nothing in the library imports this package; a
session that never serves pays zero import cost
(``tests/test_serving.py`` subprocess-asserts it).  Import explicitly:

    from torchmpi_tpu import serving

    server = serving.Server(model, params, replicas=2, slots=8)
    results = server.run_trace([
        serving.Request("r0", prompt, max_new=32, arrival_s=0.0),
        ...
    ])

``benchmarks/serving_bench.py`` measures the continuous-vs-static win
on a synthetic Poisson trace; the emitted tokens stay bit-identical per
request to the offline ``models.generate.generate`` path (greedy-only,
which is also what makes re-routing token-exact).
"""

from __future__ import annotations

from .engine import ReplicaEngine, RequestRejected, Session  # noqa: F401
from .router import Router  # noqa: F401
from .scheduler import Request, Server  # noqa: F401
from .slots import SlotPool  # noqa: F401

__all__ = ["ReplicaEngine", "Request", "RequestRejected", "Router",
           "Server", "Session", "SlotPool"]

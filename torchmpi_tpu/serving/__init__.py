"""Continuous-batching serving layer (docs/SERVING.md).

The request-level server over the decode stack: an admission queue +
iteration-level scheduler (:mod:`.scheduler`) injects newly-arrived
requests into the running decode batch at token boundaries and retires
finished sequences immediately; a paged KV slot pool (:mod:`.slots`)
bounds cache memory at ``slots x block`` instead of ``batch x
max_len``; a health-routed multi-replica router (:mod:`.router`)
spreads sessions over replicas — single-device dense engines
(:mod:`.engine`) or whole TP mesh slices (:mod:`.tp_engine` /
``Server.sharded``) — and drains + re-routes a dead replica's
in-flight sessions instead of crashing the server; and per-request SLO
telemetry (TTFT / inter-token latency histograms, queue-depth and
slot-occupancy gauges) rides the obs registry as ``tm_serving_*`` when
telemetry is on.

Decode is per-request greedy OR sampled (temperature / top-k / top-p /
seed on each :class:`Request`), bitwise-reproducible given (seed,
prompt) — which is also what keeps re-routing token-exact.  Prefill
optionally pads to pow-2 length buckets (compiles O(buckets), streams
unchanged), and speculative decoding (:mod:`.spec`: ngram prompt-lookup
or a small draft LM) lands up to K+1 tokens per target forward while
staying bitwise-identical to the non-speculative stream.

Off by default and **never imported unless used** — the analysis/obs/
faults discipline: nothing in the library imports this package; a
session that never serves pays zero import cost
(``tests/test_serving.py`` subprocess-asserts it).  Import explicitly:

    from torchmpi_tpu import serving

    server = serving.Server(model, params, replicas=2, slots=8)
    results = server.run_trace([
        serving.Request("r0", prompt, max_new=32, arrival_s=0.0,
                        temperature=0.8, top_k=40, seed=7),
        ...
    ])

``benchmarks/serving_bench.py`` measures the continuous-vs-static,
TP-sharded, sampled, bucketed-prefill and speculative wins on a
synthetic Poisson trace; greedy tokens stay bit-identical per request
to the offline ``models.generate.generate`` path.
"""

from __future__ import annotations

from .engine import ReplicaEngine, RequestRejected, Session  # noqa: F401
from .fleet import AdmissionController, AdmissionRejected, \
    FleetController  # noqa: F401
from .prefix_cache import PrefixCache  # noqa: F401
from .router import Router  # noqa: F401
from .scheduler import Request, Server  # noqa: F401
from .slots import SlotPool  # noqa: F401
from .spec import ModelDraft, NgramDraft  # noqa: F401
from .tp_engine import TPReplicaEngine  # noqa: F401

__all__ = ["AdmissionController", "AdmissionRejected", "FleetController",
           "ModelDraft", "NgramDraft", "PrefixCache", "ReplicaEngine",
           "Request", "RequestRejected", "Router", "Server", "Session",
           "SlotPool", "TPReplicaEngine"]

"""SLO-driven admission control + replica autoscaling for the serving
fleet.

Closes the loop the SLO histograms (docs/OBSERVABILITY.md) already
enable: instead of letting a 10x arrival surge queue unboundedly —
every queued request's TTFT grows without limit until the whole fleet
misses SLO ("collapse") — the :class:`AdmissionController` watches live
p95 TTFT against ``Config.serving_slo_ttft_us`` and **sheds** arrivals
with a typed :class:`AdmissionRejected` the moment the fleet is out of
budget.  Shedding is backpressure, not a timeout: the client learns in
O(1) that it must retry elsewhere/later, and the requests already
admitted keep their latency bounded.

The :class:`FleetController` turns sustained queue depth into replica
count: scale-up builds a fresh engine through a caller-supplied factory
and registers it with the router; scale-down picks the least-loaded
live replica and retires it through the PR 10 drain machinery — the
same drain→reroute path a replica kill takes, minus the kill — so
in-flight sessions resume token-exactly elsewhere (re-prefill keys are
slot/replica-independent).  ``sustain`` consecutive over/under-water
ticks are required before acting: admission-rate steps are spiky, and a
controller that flaps on one tick's depth thrashes compile caches.

Both classes are dependency-free bookkeeping (no jax, no obs imports):
the scheduler owns the clock, the engines, and the telemetry; this
module owns only the decisions.
"""

from __future__ import annotations

from collections import deque
from typing import Callable, List, Optional


class AdmissionRejected(RuntimeError):
    """Typed backpressure: the fleet's live p95 TTFT is over the SLO
    target, so this arrival is shed at the door instead of queued into
    a latency it can't meet.  Carries the evidence a client (or the
    surge bench) needs to reason about the rejection."""

    def __init__(self, rid: str, *, p95_ttft_us: float, target_us: float,
                 queue_depth: int, reason: str = "slo"):
        self.rid = rid
        self.p95_ttft_us = float(p95_ttft_us)
        self.target_us = float(target_us)
        self.queue_depth = int(queue_depth)
        self.reason = reason
        super().__init__(
            f"request {rid} shed ({reason}): p95 TTFT "
            f"{self.p95_ttft_us:.0f}us > target {self.target_us:.0f}us "
            f"at queue depth {queue_depth}")


class AdmissionController:
    """Rolling-window p95 TTFT vs the SLO target.

    ``observe`` feeds every first-admission TTFT (in the scheduler's
    active clock — wall, virtual, or work-unit seconds, µs-scaled for
    comparison); ``check`` raises :class:`AdmissionRejected` while the
    window's p95 exceeds ``slo_ttft_us``.  Below ``min_samples`` the
    controller stays open — shedding on one unlucky sample would reject
    traffic the fleet could trivially serve.  ``slo_ttft_us <= 0``
    disarms it entirely (the PR 17 behavior).
    """

    def __init__(self, slo_ttft_us: float, *, window: int = 64,
                 min_samples: int = 8):
        if window < 1:
            raise ValueError(f"window must be >= 1, got {window}")
        if min_samples < 1:
            raise ValueError(
                f"min_samples must be >= 1, got {min_samples}")
        self.slo_ttft_us = float(slo_ttft_us)
        self.min_samples = int(min_samples)
        self._ttfts: deque = deque(maxlen=int(window))
        self.shed = 0
        self.admitted = 0

    @property
    def armed(self) -> bool:
        return self.slo_ttft_us > 0

    def observe(self, ttft_s: float) -> None:
        self._ttfts.append(float(ttft_s) * 1e6)

    def p95_ttft_us(self) -> float:
        if not self._ttfts:
            return 0.0
        xs = sorted(self._ttfts)
        return xs[min(len(xs) - 1, int(0.95 * len(xs)))]

    def check(self, rid: str, queue_depth: int) -> None:
        """Admit (return) or shed (raise) one arrival."""
        if not self.armed or len(self._ttfts) < self.min_samples:
            self.admitted += 1
            return
        p95 = self.p95_ttft_us()
        if p95 > self.slo_ttft_us:
            self.shed += 1
            raise AdmissionRejected(
                rid, p95_ttft_us=p95, target_us=self.slo_ttft_us,
                queue_depth=queue_depth)
        self.admitted += 1


class FleetController:
    """Sustained queue depth -> replica count.

    ``tick(depth, pending)`` once per scheduler tick; returns
    ``"scale_up"`` / ``"scale_down"`` when it acted, else None.
    Scale-up calls ``engine_factory(name)`` (a fresh engine, unique
    name) and ``router.add``; scale-down routes the victim through
    ``drain(engine, pending)`` — the scheduler's kill-path drain, which
    re-queues in-flight sessions with their emitted tokens — then
    ``router.retire`` so the health ledger can never auto-readmit it.
    """

    def __init__(self, router, *, engine_factory: Callable,
                 max_replicas: int, min_replicas: int = 1,
                 high_water: int = 4, low_water: int = 0,
                 sustain: int = 3,
                 drain: Optional[Callable] = None):
        if max_replicas < 1:
            raise ValueError(
                f"max_replicas must be >= 1, got {max_replicas}")
        if min_replicas < 1 or min_replicas > max_replicas:
            raise ValueError(
                f"min_replicas must be in [1, {max_replicas}], got "
                f"{min_replicas}")
        if high_water <= low_water:
            raise ValueError(
                f"high_water ({high_water}) must exceed low_water "
                f"({low_water})")
        if sustain < 1:
            raise ValueError(f"sustain must be >= 1, got {sustain}")
        self.router = router
        self.engine_factory = engine_factory
        self.max_replicas = int(max_replicas)
        self.min_replicas = int(min_replicas)
        self.high_water = int(high_water)
        self.low_water = int(low_water)
        self.sustain = int(sustain)
        self.drain = drain
        self._hi_streak = 0
        self._lo_streak = 0
        self._spawned = 0
        self.events: List[str] = []

    def tick(self, depth: int, pending) -> Optional[str]:
        live = self.router.live()
        if depth > self.high_water:
            self._hi_streak += 1
            self._lo_streak = 0
        elif depth <= self.low_water:
            self._lo_streak += 1
            self._hi_streak = 0
        else:
            self._hi_streak = self._lo_streak = 0

        if self._hi_streak >= self.sustain and len(live) < self.max_replicas:
            self._hi_streak = 0
            self._spawned += 1
            name = f"scale{self._spawned}"
            self.router.add(self.engine_factory(name))
            self.events.append("scale_up")
            return "scale_up"

        if self._lo_streak >= self.sustain and len(live) > self.min_replicas:
            self._lo_streak = 0
            # Least-loaded live replica loses: fewest in-flight
            # sessions to reroute (ties broken by name for replay
            # determinism, same ordering Router.pick uses).
            victim = min(live, key=lambda r: (r.active, r.name))
            if self.drain is not None:
                self.drain(victim, pending)
            self.router.retire(victim)
            self.events.append("scale_down")
            return "scale_down"
        return None

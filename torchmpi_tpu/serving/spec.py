"""Draft proposers for speculative decoding over the slot pool.

Speculative decoding splits one engine tick into *draft K tokens
cheaply* then *verify all K+1 in ONE target-model forward*
(``models.generate.slot_verify_step``): the target samples at every fed
position, and the host accept loop keeps samples exactly while the
drafts match — so the emitted stream is **bitwise-identical** to
non-speculative decoding at the same (seed, prompt), only cheaper per
token when drafts land.  The engine owns the verify and the accept
loop; this module owns the *proposers*:

- :class:`NgramDraft` — prompt-lookup drafting: propose the K tokens
  that followed the longest recent n-gram suffix match in the request's
  own history.  Zero model cost (unit weight 0) — the strongest
  TTFT/ITL lever on repetitive output, and the bench default.
- :class:`ModelDraft` — a small dense LM drafting greedily over its OWN
  slot-pooled cache, catching up on tokens the target accepted behind
  its back.  Costs ``unit_weight`` work units per draft forward
  (defaulting to the draft/target parameter ratio), so the work-unit
  clock prices the draft honestly.

Both are TEMPLATES: the engine calls :meth:`bind` once to get a
per-engine state object (so one draft config can be handed to a
multi-replica ``Server``), with the slot-keyed lifecycle the engine
drives — ``admit`` / ``propose`` / ``observe`` / ``free`` / ``drain``.
``drain`` discards all per-slot state: a replica killed mid-speculation
re-routes its sessions and the draft restarts cold on the new replica,
with nothing speculative surviving the move (chaos-asserted in
tests/test_serving.py).

Proposal quality only moves SPEED (acceptance rate), never output:
a wrong draft costs one rejected position; an empty proposal degrades
the tick to a plain (verified) decode step.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Tuple

import numpy as np


def _full_seq(sess) -> List[int]:
    """The session's full token history as the engine fed it: base
    prompt + pre-reroute tokens + this replica's emitted (the last
    entry is the pending token — sampled, not yet in the cache)."""
    req = sess.request
    base = list(np.asarray(req.prompt, np.int32).reshape(-1))
    return base + list(getattr(req, "tokens", []) or []) + sess.emitted


class NgramDraft:
    """Prompt-lookup drafting (template — :meth:`bind` per engine)."""

    def __init__(self, n: int = 3):
        if n < 1:
            raise ValueError(f"ngram order must be >= 1, got {n}")
        self.n = int(n)

    def bind(self, engine) -> "_NgramState":
        return _NgramState(self.n)


class _NgramState:
    """Per-engine ngram proposer.  Stateless between ticks (history
    lives on the sessions), so the slot lifecycle hooks are no-ops —
    which is itself the drain story: there is nothing to discard."""

    unit_weight = 0.0

    def __init__(self, n: int):
        self.n = n

    def admit(self, slot: int, sess) -> float:
        return 0.0

    def propose(self, sessions: Dict[int, object], k: int
                ) -> Tuple[Dict[int, List[int]], float]:
        drafts: Dict[int, List[int]] = {}
        for slot, sess in sessions.items():
            hist = _full_seq(sess)
            d: List[int] = []
            # Longest suffix (order n down to 1) with an EARLIER
            # occurrence; propose the tokens that followed it.  The
            # rightmost match tracks the most recent local pattern.
            for g in range(min(self.n, len(hist) - 1), 0, -1):
                suffix = hist[-g:]
                for i in range(len(hist) - g - 1, -1, -1):
                    if hist[i:i + g] == suffix:
                        d = hist[i + g:i + g + k]
                        break
                if d:
                    break
            drafts[slot] = d
        return drafts, 0.0

    def observe(self, slot: int, sess) -> None:
        pass

    def free(self, slot: int) -> None:
        pass

    def drain(self) -> None:
        pass

    def active_slots(self) -> List[int]:
        return []


class ModelDraft:
    """Small-LM drafting (template — :meth:`bind` per engine).

    ``model``/``params`` are a dense ``TransformerLM`` checkpoint over
    the SAME vocabulary as the target.  ``unit_weight`` prices one
    draft forward on the work-unit clock; None derives the
    parameter-count ratio draft/target at bind time (a 10x-smaller
    draft then costs ~0.1 units per forward)."""

    def __init__(self, model, params, *, unit_weight: Optional[float] = None):
        self.model = model
        self.params = params
        self.unit_weight = unit_weight

    def bind(self, engine) -> "_ModelDraftState":
        return _ModelDraftState(self, engine)


class _ModelDraftState:
    """Per-engine draft-LM state: its own slot-pooled cache, aligned
    slot-for-slot with the target's pool, plus a per-slot ``d_filled``
    pointer — how many positions of the slot's TRUE sequence the draft
    cache has consumed.  Each tick feeds exactly K pooled greedy decode
    steps: first the catch-up queue (true tokens the target emitted
    since last tick), then the draft's own greedy continuations — those
    continuations are the proposals."""

    def __init__(self, draft: ModelDraft, engine):
        import jax
        import jax.numpy as jnp

        st = engine.pool.slot_tokens
        model = draft.model
        if int(model.vocab) != int(engine.vocab):
            raise ValueError(
                f"draft vocab {model.vocab} != target vocab "
                f"{engine.vocab}: speculative tokens must share one id "
                f"space")
        if getattr(model, "pos_emb", "learned") == "learned" \
                and st != model.max_len:
            raise ValueError(
                f"draft max_len {model.max_len} != slot block {st}: a "
                f"learned-position draft cannot shrink its block (use "
                f"pos_emb='rope')")
        self.params = draft.params
        self.dmodel = model.clone(decode=True, max_len=st)
        w = draft.unit_weight
        if w is None:
            n_draft = sum(int(np.prod(p.shape))
                          for p in jax.tree.leaves(draft.params))
            w = n_draft / max(1, engine.param_count)
        self.unit_weight = float(w)
        S = engine.pool.n_slots
        shapes = jax.eval_shape(
            lambda: self.dmodel.init(
                jax.random.PRNGKey(0), jnp.zeros((S, 1), jnp.int32),
                pos_offset=jnp.zeros((S,), jnp.int32)))["cache"]
        self._cache = jax.tree.map(
            lambda s: jnp.zeros(s.shape, s.dtype), shapes)
        self._n_slots = S
        #: slot -> positions of the slot's true sequence already in the
        #: draft cache (kv written for seq[0 .. d_filled-1]).
        self._filled: Dict[int, int] = {}
        #: slot -> tokens fed beyond the catch-up point last tick, to
        #: advance ``_filled`` by the verified-correct prefix.
        self._fed: Dict[int, List[int]] = {}

    # -- slot lifecycle ----------------------------------------------------

    def admit(self, slot: int, sess) -> float:
        """Prefill the fed prompt (base + pre-reroute tokens) on the
        draft and write it into the draft pool row.  Returns the work
        units spent (one draft prefill)."""
        from ..models.generate import slot_prefill, slot_write

        req = sess.request
        base = np.asarray(req.prompt, np.int32).reshape(-1)
        prev = np.asarray(getattr(req, "tokens", []) or [], np.int32)
        prompt = np.concatenate([base, prev]).reshape(1, -1)
        one, _ = slot_prefill(self.dmodel, self.params, prompt)
        self._cache = slot_write(self._cache, one, slot)
        self._filled[slot] = prompt.shape[1]
        self._fed[slot] = []
        return self.unit_weight

    def propose(self, sessions: Dict[int, object], k: int
                ) -> Tuple[Dict[int, List[int]], float]:
        from ..models.generate import slot_decode_step

        S = self._n_slots
        queues: Dict[int, List[int]] = {}
        for slot, sess in sessions.items():
            if slot not in self._filled:  # admitted before spec was on
                self.admit(slot, sess)
            full = _full_seq(sess)
            queues[slot] = full[self._filled[slot]:]
            self._fed[slot] = []
        drafts: Dict[int, List[int]] = {slot: [] for slot in sessions}
        for step in range(k):
            toks = np.zeros((S,), np.int32)
            pos = np.zeros((S,), np.int32)
            for slot in sessions:
                q = queues[slot]
                toks[slot] = q[step] if step < len(q) else \
                    drafts[slot][step - len(q)]
                pos[slot] = self._filled[slot] + step
            self._cache, nxt = slot_decode_step(
                self.dmodel, self.params, self._cache, toks, pos)
            nxt = np.asarray(nxt)
            for slot in sessions:
                q = queues[slot]
                if step >= len(q):
                    self._fed[slot].append(int(toks[slot]))
                # The output becomes a PROPOSAL once the known queue is
                # consumed (the last known feed's output is draft #1).
                if step >= len(q) - 1:
                    drafts[slot].append(int(nxt[slot]))
        out = {}
        for slot, sess in sessions.items():
            q = queues[slot]
            # Catch-up longer than K: nothing proposable this tick (the
            # next ticks keep catching up); the engine degrades to a
            # verified plain step.
            out[slot] = drafts[slot][:max(0, k - max(0, len(q) - 1))]
            # Known-queue feeds are true sequence by construction.
            self._filled[slot] += min(len(q), k)
        return out, float(k) * self.unit_weight

    def observe(self, slot: int, sess) -> None:
        """After verify: advance ``d_filled`` over the speculative
        feeds that turned out to be the true sequence; everything after
        the first wrong feed stays unconsumed (its cache rows are
        re-fed — overwritten — on later ticks)."""
        full = _full_seq(sess)
        df = self._filled.get(slot)
        if df is None:
            return
        for tok in self._fed.get(slot, []):
            if df < len(full) and tok == full[df]:
                df += 1
            else:
                break
        self._filled[slot] = df
        self._fed[slot] = []

    def free(self, slot: int) -> None:
        self._filled.pop(slot, None)
        self._fed.pop(slot, None)

    def drain(self) -> None:
        """Replica death: discard ALL speculative state (cache rows are
        garbage once the target's sessions re-route — the per-row depth
        mask makes stale rows invisible after the next admit)."""
        self._filled.clear()
        self._fed.clear()

    def active_slots(self) -> List[int]:
        return sorted(self._filled)

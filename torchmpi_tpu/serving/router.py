"""Health-routed multi-replica dispatch.

The router spreads sessions across replicas and folds every step
outcome into a per-replica health ledger — the same
``HealthLedger`` / ``decide()`` (ok | degrade | raise) machinery the
fault layer runs on its cross-host surfaces (docs/FAULTS.md).  When the
fault layer is armed, the router uses ITS ledger, so replica
transitions emit the standard ``tm_fault_health_total`` counters and
chaos plans drive the same thresholds; otherwise a private ledger with
the same semantics.

Routing policy (:meth:`Router.pick`): least-loaded among the replicas
whose verdict is ``ok``; ``degrade`` replicas only admit when no
healthy replica has a free slot (shed optional load onto suspects,
never prefer them); ``raise`` (dead) replicas admit nothing and —
handled by the scheduler — drain their in-flight sessions for
re-routing instead of crashing the server.

Recovery feeds back the same way (docs/ELASTIC.md's rejoin, replica
edition): a drained replica whose ledger returns to ``healthy`` — a
probe or a shared-ledger success for the same peer recorded through
:meth:`Router.record` — is re-admitted into the dispatch rotation
(:meth:`Router.readmit`); its slot pool was drained, so it comes back
empty and simply starts taking new admissions.
"""

from __future__ import annotations

import sys
from typing import List, Optional

from .engine import ReplicaEngine


def _shared_ledger():
    """The fault layer's ledger when armed (sys.modules lookup keeps the
    decision symmetric with the rest of the library: an armed fault
    layer is necessarily already imported)."""
    mod = sys.modules.get("torchmpi_tpu.faults")
    if mod is not None and mod.active():
        return mod.ledger()
    return None


class Router:
    """Health-aware replica selection over a fixed replica set."""

    def __init__(self, replicas: List[ReplicaEngine], *,
                 ledger=None, suspect_after: int = 2,
                 dead_after: int = 3):
        if not replicas:
            raise ValueError("router needs at least one replica")
        names = [r.name for r in replicas]
        if len(set(names)) != len(names):
            raise ValueError(f"replica names must be unique: {names}")
        self.replicas = list(replicas)
        self._ledger = ledger or _shared_ledger()
        if self._ledger is None:
            from ..faults.health import HealthLedger

            self._ledger = HealthLedger(suspect_after=suspect_after,
                                        dead_after=dead_after)

    # -- health ------------------------------------------------------------

    def record(self, replica: ReplicaEngine, ok: bool) -> str:
        """Fold one step outcome; returns the decide() verdict.  A
        success that brings a DRAINED replica's ledger back to
        ``healthy`` (one success fully resets — the HealthLedger
        contract) re-admits it into the rotation."""
        self._ledger.record(replica.name, ok)
        if ok and replica.dead and \
                self._ledger.state(replica.name) == "healthy":
            self.readmit(replica)
        return self.decide(replica)

    def readmit(self, replica: ReplicaEngine) -> None:
        """Return a healed (previously drained) replica to the
        dispatch rotation: clears its dead flag so ``pick()`` can
        select it again.  Its sessions were re-routed at the drain, so
        it rejoins empty; callers that cannot trust the old process
        should rebuild the engine instead.  A RETIRED replica (scaled
        down on purpose — :meth:`retire`) never comes back this way:
        readmission is for healed failures, not cancelled decisions."""
        if not replica.dead or getattr(replica, "retired", False):
            return
        replica.dead = False
        mod = sys.modules.get("torchmpi_tpu.obs")
        try:
            if mod is not None and mod.active():
                mod.record_serving("readmitted", replica=replica.name)
        except Exception:  # noqa: BLE001 — telemetry never fails this
            pass

    def decide(self, replica: ReplicaEngine) -> str:
        if replica.dead:
            return "raise"
        return self._ledger.decide(replica.name)

    def mark_dead(self, replica: ReplicaEngine) -> None:
        """Hard failure (the peer is gone — ``InjectedFailure``
        semantics): push the ledger straight past its thresholds so the
        verdict flips to ``raise`` without burning ``dead_after`` ticks
        of a replica that already told us it is dead."""
        for _ in range(max(1, getattr(self._ledger, "dead_after", 1))):
            self._ledger.record(replica.name, ok=False)

    # -- fleet membership --------------------------------------------------

    def add(self, replica: ReplicaEngine) -> None:
        """Register a freshly built replica (autoscale scale-up) into
        the dispatch rotation.  Name uniqueness is the same invariant
        the constructor enforces — per-replica telemetry and ledger
        rows key on it."""
        if any(r.name == replica.name for r in self.replicas):
            raise ValueError(
                f"replica name {replica.name!r} already registered")
        self.replicas.append(replica)

    def retire(self, replica: ReplicaEngine) -> None:
        """Take a replica out of the fleet FOR GOOD (autoscale
        scale-down): dead so ``pick``/``live`` skip it, ``retired`` so
        a later healthy ledger state can never auto-readmit a replica
        the controller deliberately removed.  The caller drains it
        first — retirement loses capacity, never work."""
        replica.dead = True
        replica.retired = True

    # -- selection ---------------------------------------------------------

    def live(self) -> List[ReplicaEngine]:
        return [r for r in self.replicas if not r.dead]

    def pick(self) -> Optional[ReplicaEngine]:
        """Replica for the next admission, or None when nothing can
        take it this tick."""
        ok = [r for r in self.live()
              if self.decide(r) == "ok" and r.has_capacity()]
        if ok:
            return min(ok, key=lambda r: (r.active, r.name))
        degraded = [r for r in self.live()
                    if self.decide(r) == "degrade" and r.has_capacity()]
        if degraded:
            return min(degraded, key=lambda r: (r.active, r.name))
        return None

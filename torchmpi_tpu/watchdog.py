"""Collective watchdog: live hang detection, lease-based liveness, and
typed hang-breaking (docs/WATCHDOG.md).

Every recovery layer so far handles failures that *announce themselves*
— a typed transient, a dead heartbeat, a digest mismatch, a corrupt
checkpoint.  The failure mode none of them can see is the silent one a
collective substrate invites: a dispatch that never completes.  Today
that is post-mortem territory (``obs_tool blame`` over dumped flight
rings names the tail-hang host *after* someone kills the job) and the
``faults`` deadline budgets cover only the host-staged sites.  This
module is the NCCL-watchdog equivalent for the stack: detect a stuck
collective live, attribute it, and convert it into the typed errors the
restart/elastic machinery already heals.  Three layers:

- **progress monitor** — a per-process daemon thread over an in-flight
  table.  Every blocking dispatch surface (the host-staged eager
  exchange, ``runtime.barrier``, ``AsyncHandle.wait``, the PS wait leg)
  brackets its wait in :func:`begin`/:func:`end`; any entry older than
  ``Config.watchdog_deadline_s`` is flagged **stalled** —
  ``tm_watchdog_{armed,stalled,broken,escalated,cleared}_total``
  counters plus a ``watchdog`` flight-ring event carrying op/seq/
  elapsed, right next to the collective events it indicts.
- **lease-based liveness** — the monitor renews a heartbeat *lease*
  file (``wd_lease_<rank>.json``) on the membership-board filesystem —
  the transport still standing when the device fabric's gang is exactly
  what wedged — carrying the live in-flight/stall snapshot.  A rank
  whose lease is FRESH but whose collective is stalled means *peer*
  trouble; an EXPIRED lease is death evidence the elastic layer already
  handles (``ElasticGang.poll`` reads :func:`dead_ranks`).
  ``obs_tool blame --live <dir>`` renders the leases while the job
  runs, instead of requiring post-mortem dumps.
- **hang-breaking** (``mode="break"``) — a stalled entry gets a *break
  request*: cooperative waiters (the polling ``AsyncHandle.wait``, the
  injected ``stall`` hold) observe it via :func:`check_break` and raise
  a typed :class:`CollectiveHangError` in place; non-cooperative stalls
  get the error queued for the next eager boundary
  (:func:`raise_pending` — the guard-style deferred raise: an in-thread
  raise inside XLA would wedge the effects token).  The error is
  timeout-flavored, so the faults policy, ``restart.run_with_restarts``
  (the ``on_peer_timeout`` path) and ``elastic.run_elastic`` (a
  member-implicating hang shrinks the gang) all recover from it.  The
  ladder is staged on the deadline: **stalled** at 1x (the live-blame
  window), **broken** at 1.5x, **escalated** at 2.5x — a stall inside
  a compiled region that cannot be unwound exits cleanly
  (``os._exit``, :data:`ESCALATE_EXIT_CODE`) and the elastic
  membership layer turns the death into an N-1 shrink + checkpoint
  restore: "wedged forever" becomes "recovered at the last step
  boundary".

Off by default and **never imported when off** — the ``analysis``/
``obs``/``faults``/``guard`` import discipline: ``Config.watchdog`` is
read as ONE string compare at plan build / site entry, the planned
dispatch path gains zero branches when off, and ``import torchmpi_tpu``
never imports this module (``tests/test_watchdog.py`` asserts all of
it, subprocess-included).  Dependency-free on purpose (no jax, no
numpy): the monitor thread must run while the runtime is exactly what
wedged.  Telemetry rides the sys.modules-gated shim
(``utils/telemetry.py``) — the watchdog never imports obs.
"""

from __future__ import annotations

import json
import os
import threading
import time
import warnings
from typing import Dict, List, Optional

from .utils import telemetry

MODES = ("off", "warn", "break")

# Exit status of the escalation path (a stall the break could not
# unwind): distinctive on purpose, so a scheduler/log reader can tell a
# watchdog escalation from an OOM kill or a crash.
ESCALATE_EXIT_CODE = 113

# Test seam: monkeypatch to observe escalation without dying.
_exit_fn = os._exit


class CollectiveHangError(RuntimeError):
    """A collective the watchdog had to break: it made no progress
    within ``watchdog_deadline_s``.  Timeout-flavored for the fault
    policy (``is_timeout``) but NOT transient — retrying the very wait
    that wedged would re-wedge; the correct response is the recovery
    path (``restart.run_with_restarts`` routes it through
    ``on_peer_timeout``; ``elastic.run_elastic`` shrinks when ``peer``
    implicates a gang member).  Carries the site/op/seq/elapsed
    attribution and the obs flight-ring tail when telemetry is active.
    """

    transient = False
    is_timeout = True

    def __init__(self, site: str, *, op: str = "", peer: str = "",
                 seq: int = -1, elapsed_s: float = 0.0,
                 deadline_s: float = 0.0,
                 flight_tail: Optional[List[dict]] = None):
        self.site = site
        self.op = op
        self.peer = peer
        self.seq = int(seq)
        self.elapsed_s = float(elapsed_s)
        self.deadline_s = float(deadline_s)
        self.flight_tail = flight_tail or []
        tail = ""
        if self.flight_tail:
            last = self.flight_tail[-1]
            tail = (f"; last flight event #{last.get('seq')} "
                    f"{last.get('ev')}:{last.get('op')}")
        op_s = f" op={op}" if op else ""
        peer_s = f" peer={peer}" if peer else ""
        super().__init__(
            f"watchdog broke a stalled collective at {site}{op_s}"
            f"{peer_s} (wd-seq {self.seq}): no completion within "
            f"{deadline_s:.3g}s deadline (elapsed {elapsed_s:.3g}s)"
            f"{tail}")


class _InFlight:
    """One armed dispatch window (begin .. end)."""

    __slots__ = ("token", "site", "op", "peer", "nbytes", "seq", "t0",
                 "thread", "stalled", "break_requested",
                 "suppress_clear", "escalated")

    def __init__(self, token: int, site: str, op: str, peer: str,
                 nbytes: int, seq: int, t0: float):
        self.token = token
        self.site = site
        self.op = op
        self.peer = peer
        self.nbytes = int(nbytes)
        self.seq = int(seq)
        self.t0 = float(t0)
        self.thread = threading.get_ident()
        self.stalled = False
        self.break_requested = False
        # Set when a sibling window on the SAME thread delivered its
        # break: this window is about to unwind through that exception,
        # so its end() must not read as "the stall resolved on its own"
        # (the deadline-tuning `cleared` signal would lie).
        self.suppress_clear = False
        # One escalation per window: os._exit never returns in
        # production, but the test seam does — re-escalating the same
        # entry every tick would spam the exit hook.
        self.escalated = False


# ---------------------------------------------------------------------------
# Module state
# ---------------------------------------------------------------------------

_lock = threading.Lock()
_mode = "off"
_deadline_s = 30.0
_poll_s = 0.05
_lease_dir: Optional[str] = None
_rank = 0
_inflight: Dict[int, _InFlight] = {}
_pending: Dict[int, CollectiveHangError] = {}
_next_token = 0
_seq = 0  # monotonic watchdog op sequence (the flight-event seq field)
_stats = {"begun": 0, "stalled": 0, "broken": 0, "escalated": 0}
_thread: Optional[threading.Thread] = None
_stop = threading.Event()
# Monitor generation: bumped by deactivate().  A monitor thread that
# outlived its join deadline (e.g. blocked in a lease fsync on a hung
# filesystem) exits on its next wakeup instead of racing a re-activated
# successor — two concurrent monitors would double-count, double-queue
# breaks, and could both reach the escalation exit.
_gen = 0
_last_lease = 0.0
# Coarse driver state published in the lease ("running" | "parked"):
# the elastic park loop sets "parked" (+ the epoch it waits on) so
# `obs_tool blame --live` can tell a quorum-parked minority — fresh
# lease, deliberately idle — from a corpse or a stalled rank
# (docs/ELASTIC.md "Partitions and split-brain").
_state = "running"
_state_detail = ""


def mode() -> str:
    return _mode


def active() -> bool:
    return _mode != "off"


def deadline_s() -> float:
    return _deadline_s


def lease_dir() -> Optional[str]:
    """Where this process's liveness leases land (None = disabled)."""
    return _lease_dir


def set_lease_dir(directory: str) -> None:
    """Point the armed watchdog's leases at ``directory`` — the seam
    ``elastic.ElasticGang`` uses to adopt its membership board as the
    lease home when ``watchdog_dir`` was left unset (the board
    directory is only known at driver construction, not at
    ``runtime.init``).  Forces an immediate renewal so readers see the
    lease as soon as the gang exists."""
    global _lease_dir
    with _lock:
        _lease_dir = directory
    os.makedirs(directory, exist_ok=True)
    _write_lease(force=True)


def set_state(state: str, detail: str = "") -> None:
    """Publish a coarse driver state into the lease payload
    (``"running"`` default; the elastic driver sets ``"parked"`` with
    the epoch it is waiting on while a quorum-lost minority waits out
    a partition; the hot-state tier sets ``"migrating"`` with the
    ``source -> spare`` ranks while a live drain is in flight —
    docs/HOTSTATE.md).  Forces an immediate lease renewal so live
    triage (``obs_tool blame --live``) sees the transition at once; a
    no-op when the watchdog is off."""
    global _state, _state_detail
    if _mode == "off":
        return
    with _lock:
        _state = str(state)
        _state_detail = str(detail)
    _write_lease(force=True)


def state() -> str:
    return _state


def stats() -> Dict[str, int]:
    with _lock:
        return dict(_stats)


def pending_count() -> int:
    return len(_pending)


def inflight_count() -> int:
    return len(_inflight)


# ---------------------------------------------------------------------------
# Activation (runtime.init / set_config call this when Config.watchdog
# is on; same idempotent re-activation contract as obs/faults)
# ---------------------------------------------------------------------------


def activate(wd_mode: str, *, deadline_s: float, poll_s: float = 0.05,
             lease_dir: Optional[str] = None,
             rank: int = 0) -> None:
    """Arm the watchdog (idempotent; re-activation updates settings).

    ``lease_dir`` is where the liveness leases land (the membership
    board directory by convention — ``Config.watchdog_dir``, falling
    back to ``Config.elastic_dir``); ``None`` disables leases, the
    in-process monitor still runs."""
    global _mode, _deadline_s, _poll_s, _lease_dir, _rank, _thread, \
        _state, _state_detail
    if wd_mode not in ("warn", "break"):
        raise ValueError(
            f"watchdog mode must be warn|break, got {wd_mode!r}")
    if float(deadline_s) <= 0 or float(poll_s) <= 0:
        raise ValueError(
            f"watchdog deadline_s/poll_s must be > 0, got "
            f"{deadline_s}/{poll_s}")
    with _lock:
        _mode = wd_mode
        _deadline_s = float(deadline_s)
        _poll_s = float(poll_s)
        _rank = int(rank)
        _state, _state_detail = "running", ""
        # Unconditional on purpose: re-activation with lease_dir=None
        # must DISABLE leases (not silently keep writing liveness into
        # a previous activation's — possibly another run's — board).
        _lease_dir = lease_dir or None
        if _lease_dir:
            os.makedirs(_lease_dir, exist_ok=True)
        if wd_mode != "break":
            # Softening to warn (which "never intervenes") must disarm
            # any break already requested under the previous break-mode
            # activation — a queued CollectiveHangError delivered into
            # a warn-mode step would be exactly the intervention warn
            # promises not to make.
            _pending.clear()
            for e in _inflight.values():
                e.break_requested = False
        start = _thread is None or not _thread.is_alive()
        if start:
            _stop.clear()
            _thread = threading.Thread(target=_loop, args=(_gen,),
                                       daemon=True, name="tm-watchdog")
    if start:
        _thread.start()
    _write_lease(force=True)


def deactivate() -> None:
    """Disarm: the monitor thread exits at its next tick; in-flight
    windows are released (their ``end()`` calls become no-ops) and
    pending breaks are dropped — a disarmed watchdog must never raise
    into a later step.  The rank's lease is RETRACTED (removed) from
    the board: a lease that merely stopped renewing would expire, and
    peers reading expiry as death evidence (``dead_ranks`` /
    ``ElasticGang.poll``) would shrink a live, healthy rank out of the
    gang just for turning its watchdog off."""
    global _mode, _thread, _lease_dir, _gen, _state, _state_detail
    with _lock:
        _mode = "off"
        _state, _state_detail = "running", ""
        _gen += 1  # any straggling monitor thread exits at its next tick
        th, _thread = _thread, None
        _inflight.clear()
        _pending.clear()
        ld, _lease_dir = _lease_dir, None
        rank = _rank
    _stop.set()
    if th is not None and th.is_alive():
        th.join(timeout=1.0)
    if ld is not None:
        try:
            os.remove(lease_path(ld, rank))
        except OSError:
            pass  # never leased / already gone — same outcome


def reset() -> None:
    """Disarm AND forget stats (tests)."""
    deactivate()
    with _lock:
        for k in _stats:
            _stats[k] = 0


# ---------------------------------------------------------------------------
# The in-flight window (the site instrumentation surface)
# ---------------------------------------------------------------------------


def begin(site: str, op: str = "", peer: str = "",
          nbytes: int = 0) -> int:
    """Open one armed dispatch window; returns a token for
    :func:`end`/:func:`check_break`.  Call sites gate on
    ``Config.watchdog != "off"`` before importing this module, so the
    off path never reaches here; a disarmed watchdog returns -1 (every
    later call on the token is a no-op)."""
    global _next_token, _seq
    if _mode == "off":
        return -1
    with _lock:
        token = _next_token
        _next_token += 1
        seq = _seq
        _seq += 1
        _inflight[token] = _InFlight(token, site, op, peer, nbytes, seq,
                                     time.monotonic())
        _stats["begun"] += 1
    telemetry.emit("record_watchdog", "armed", site, op=op)
    return token


def end(token: int) -> None:
    """Close a window.  A window that was flagged stalled emits a
    ``cleared`` event (the stall resolved on its own — a genuinely-slow
    collective, the deadline-tuning signal docs/WATCHDOG.md describes);
    any queued deferred break for the token is dropped with it."""
    if token < 0:
        return
    with _lock:
        e = _inflight.pop(token, None)
        undelivered = _pending.pop(token, None)
    # "cleared" = the stall resolved on its own: flagged but never
    # broken (warn mode), or broken but the queued error was never
    # delivered (the wait completed before any break point saw it).  A
    # delivered break (pending consumed by check_break/raise_pending)
    # is NOT a clear — it ended by raising — and neither is a window a
    # same-thread sibling's break is unwinding through
    # (``suppress_clear``): the deadline-tuning signal must never fire
    # for a stall the watchdog itself resolved.
    if e is not None and e.stalled and not e.suppress_clear \
            and (undelivered is not None or not e.break_requested):
        telemetry.emit("record_watchdog", "cleared", e.site, op=e.op,
                       seq=e.seq,
                       elapsed_s=time.monotonic() - e.t0)


def should_break(token: int) -> bool:
    """Non-raising poll for cooperative waiters."""
    if token < 0:
        return False
    e = _inflight.get(token)
    return e is not None and e.break_requested


def is_inflight(token: int) -> bool:
    """Is this window still registered?  False for a stale token from
    before a deactivate/re-activate cycle — long-lived cooperative
    holds (the injected ``stall``) use this to re-register with the
    new watchdog instead of polling a window it no longer watches."""
    return token >= 0 and token in _inflight


def check_break(token: int) -> None:
    """Cooperative break point: raises the window's typed
    :class:`CollectiveHangError` iff the monitor requested a break
    (mode="break" only — a softened/disarmed watchdog never
    intervenes).  The in-place raise consumes the deferred copy, so a
    broken wait never double-raises at a later boundary; sibling
    windows on the SAME thread have their queued breaks consumed too —
    the exception is about to unwind through them, and a second copy
    delivered at a later boundary (or a spurious ``cleared`` at their
    ``end()``) would misreport one stall as several."""
    if token < 0 or _mode != "break":
        return
    with _lock:
        err = _pending.pop(token, None)
        e = _inflight.get(token)
        if err is None and e is not None and e.break_requested:
            err = _make_error(e)
        if err is not None and e is not None:
            for sib in _inflight.values():
                if sib.thread == e.thread and sib.token != token:
                    sib.suppress_clear = True
                    _pending.pop(sib.token, None)
    if err is not None:
        raise err


def raise_pending() -> None:
    """The deferred-raise boundary (the guard.raise_pending pattern):
    raise the oldest queued break whose window is STILL in flight — a
    stall a background thread is wedged in (the async staged worker, a
    PS helper) surfaces on the main thread at its next eager dispatch,
    where the step loop's recovery machinery can catch it.  No-op when
    nothing is pending (one len check on the armed path; call sites
    gate the off path)."""
    if not _pending or _mode != "break":
        return
    with _lock:
        err = None
        for tok in sorted(_pending):
            if tok in _inflight:
                err = _pending.pop(tok)
                break
    if err is not None:
        raise err


def _make_error(e: _InFlight) -> CollectiveHangError:
    return CollectiveHangError(
        e.site, op=e.op, peer=e.peer, seq=e.seq,
        elapsed_s=time.monotonic() - e.t0, deadline_s=_deadline_s,
        flight_tail=telemetry.flight_tail())


# ---------------------------------------------------------------------------
# The monitor thread
# ---------------------------------------------------------------------------


def _loop(gen: int) -> None:
    while not _stop.wait(_poll_s):
        if _mode == "off" or gen != _gen:
            return  # disarmed, or a successor monitor took over
        try:
            _scan()
        except Exception:  # noqa: BLE001 — the monitor must outlive
            pass           # anything; a crashed watchdog is no watchdog


def _scan() -> None:
    # The escalation ladder (docs/WATCHDOG.md): STALLED at 1x the
    # deadline (flag + lease + warn — the live-blame window), BROKEN at
    # 1.5x (break mode: the typed error is armed for cooperative
    # waiters and queued for the next eager boundary), ESCALATED at
    # 2.5x (the break went untaken for a whole further deadline — the
    # wait is non-cooperative, a compiled region or a native call that
    # cannot be unwound in-process).
    now = time.monotonic()
    flagged: List[_InFlight] = []
    broke: List[_InFlight] = []
    escalate: Optional[_InFlight] = None
    with _lock:
        for e in list(_inflight.values()):
            elapsed = now - e.t0
            if not e.stalled and elapsed >= _deadline_s:
                e.stalled = True
                _stats["stalled"] += 1
                flagged.append(e)
            elif (e.stalled and _mode == "break"
                    and not e.break_requested
                    and elapsed >= 1.5 * _deadline_s):
                e.break_requested = True
                _pending[e.token] = _make_error(e)
                _stats["broken"] += 1
                broke.append(e)
            elif (e.stalled and e.break_requested and _mode == "break"
                    and not e.escalated
                    and elapsed >= 2.5 * _deadline_s
                    and escalate is None):
                e.escalated = True
                _stats["escalated"] += 1
                escalate = e
    changed = bool(flagged or broke or escalate)
    for e in flagged:
        telemetry.emit("record_watchdog", "stalled", e.site, op=e.op,
                       seq=e.seq, elapsed_s=now - e.t0, peer=e.peer)
        if _mode == "warn":
            warnings.warn(
                f"torchmpi_tpu.watchdog: collective stalled at "
                f"{e.site} (op={e.op or '?'}, wd-seq {e.seq}) for "
                f"{now - e.t0:.3g}s (deadline {_deadline_s:.3g}s) — "
                f"mode='warn' will not break it",
                RuntimeWarning, stacklevel=2)
    for e in broke:
        telemetry.emit("record_watchdog", "broken", e.site, op=e.op,
                       seq=e.seq, elapsed_s=now - e.t0, peer=e.peer)
    if escalate is not None:
        _escalate(escalate, now)
        return  # unreachable in production (_exit); reachable in tests
    _write_lease(force=changed)


def _escalate(e: _InFlight, now: float) -> None:
    """The documented last resort: dump the evidence, tombstone the
    lease, and exit cleanly so the elastic membership layer can turn
    this death into an N-1 shrink + checkpoint restore."""
    telemetry.emit("record_watchdog", "escalated", e.site, op=e.op,
                   seq=e.seq, elapsed_s=now - e.t0, peer=e.peer)
    _write_lease(force=True, escalated=True)
    # os._exit skips atexit — flush the telemetry dump explicitly so
    # the post-mortem evidence this exit creates actually lands.
    import sys

    obs = sys.modules.get("torchmpi_tpu.obs")
    try:
        if obs is not None and obs.active():
            obs.dump(best_effort=True)
    except Exception:  # noqa: BLE001 — dying is the job; dump is bonus
        pass
    if _mode != "break":
        # Disarmed while this escalation was dumping evidence (a
        # deactivate racing the monitor): the operator withdrew the
        # consent the exit rides on — stand down.
        return
    _exit_fn(ESCALATE_EXIT_CODE)


# ---------------------------------------------------------------------------
# Leases (layer 2): heartbeat + live in-flight snapshot on the board
# filesystem.  Plain atomic JSON on purpose — readable by obs_tool
# (standalone, no jax) and by a peer whose runtime is what wedged.
# ---------------------------------------------------------------------------


def _renew_interval() -> float:
    # Liveness granularity tracks the detection deadline, not the poll
    # tick: a 30s deadline must not hammer a network filesystem with
    # 50ms fsync-ed writes.  State changes force an immediate renewal.
    return max(_poll_s, _deadline_s / 4.0, 0.05)


def lease_path(directory: str, rank: int) -> str:
    return os.path.join(directory, f"wd_lease_{int(rank)}.json")


def _write_lease(force: bool = False, escalated: bool = False) -> None:
    global _last_lease
    d = _lease_dir
    if d is None or _mode == "off":
        return
    now = time.monotonic()
    if not force and now - _last_lease < _renew_interval():
        return
    _last_lease = now
    with _lock:
        snap = [{"site": e.site, "op": e.op, "peer": e.peer,
                 "seq": e.seq, "elapsed_s": round(now - e.t0, 4),
                 "stalled": e.stalled,
                 "break_requested": e.break_requested}
                for e in _inflight.values()]
        stats = dict(_stats)
    ttl = max(4.0 * _renew_interval(), 1.0)
    payload = {"rank": _rank, "pid": os.getpid(), "mode": _mode,
               "deadline_s": _deadline_s, "ttl_s": ttl,
               "ts": time.time(), "inflight": snap,
               "state": _state, "state_detail": _state_detail,
               "stalled_total": stats["stalled"],
               "broken_total": stats["broken"],
               "escalated": bool(escalated or stats["escalated"])}
    path = lease_path(d, _rank)
    try:
        tmp = path + ".tmp"
        with open(tmp, "w") as f:
            json.dump(payload, f, sort_keys=True)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, path)
    except OSError:
        pass  # a lost lease renewal is a liveness gap, not a crash


def read_leases(directory: str) -> Dict[int, dict]:
    """Every parseable ``wd_lease_*.json`` under ``directory``, keyed
    by rank (torn/unreadable files ignored — an unreadable lease is the
    same as an unrenewed one)."""
    out: Dict[int, dict] = {}
    try:
        names = sorted(n for n in os.listdir(directory)
                       if n.startswith("wd_lease_")
                       and n.endswith(".json"))
    except OSError:
        return out
    for name in names:
        try:
            with open(os.path.join(directory, name)) as f:
                d = json.load(f)
            out[int(d["rank"])] = d
        except (OSError, ValueError, KeyError):
            continue
    return out


def lease_expired(lease: dict, now: Optional[float] = None) -> bool:
    """Has this lease's renewal promise lapsed?  ``now`` is wall time
    (``time.time()``); leases carry their own ``ttl_s`` so readers
    need no knowledge of the writer's cadence."""
    if now is None:
        now = time.time()
    return now > float(lease.get("ts", 0)) + float(lease.get("ttl_s", 0))


def dead_ranks(directory: str, now: Optional[float] = None,
               newer_than: Optional[float] = None) -> List[int]:
    """Ranks whose lease is EXPIRED or tombstoned ``escalated`` — the
    death evidence ``elastic.ElasticGang.poll`` folds into its
    membership verdict.  A rank that never leased is not evidence
    (absence proves nothing), and with ``newer_than`` (a wall-clock
    floor — the elastic driver passes its own construction time)
    neither is a lease last renewed BEFORE it: a SIGKILLed previous
    run's leftover leases on a persistent board must not read as this
    run's deaths while a slow-starting peer is still in jax init — it
    becomes evidence only once it has leased fresh in this life."""
    out = []
    for rank, lease in read_leases(directory).items():
        if newer_than is not None and \
                float(lease.get("ts", 0)) < newer_than:
            continue  # a previous life's remains, not this run's state
        if lease.get("escalated") or lease_expired(lease, now):
            out.append(rank)
    return sorted(out)

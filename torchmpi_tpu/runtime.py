"""Process/runtime management and the communicator (mesh) stack.

TPU-native rebuild of the reference's C1 runtime (``lib/torch_mpi.cpp``,
reconstructed — reference mount empty, SURVEY.md §0/§3) and C2 resource manager
(``lib/resources.cpp``): ``mpi.start/stop/rank/size/barrier`` plus the
communicator tree (world / intra-node / inter-node / user splits).

Mapping to TPU (SURVEY.md §6.8):

- ``MPI_Init`` under mpirun        -> ``jax.distributed.initialize`` from slice
                                      metadata (or single-process).
- intra-node communicator (shm/IPC/NCCL) -> the ``ici`` mesh axis (intra-slice
                                      interconnect; XLA collectives ride it).
- inter-node communicator (MPI)    -> the ``dcn`` mesh axis (inter-slice).
- ``push_communicator(key)`` splits -> named sub-``Mesh`` stack, cached by key.

Nothing above this module touches raw device lists — the same invariant the
reference kept for raw ``MPI_Comm`` (SURVEY.md §2 L1).
"""

from __future__ import annotations

import dataclasses
import os
import sys
import threading
from typing import Dict, List, Optional, Sequence, Tuple

import jax
import numpy as np
from jax.sharding import Mesh

from .config import Config

# Canonical axis names for the two-level communicator tree.
DCN_AXIS = "dcn"  # outer: inter-slice / inter-node (reference: interComm)
ICI_AXIS = "ici"  # inner: intra-slice interconnect (reference: intraComm)
WORLD_AXES = (DCN_AXIS, ICI_AXIS)


class _State:
    """Module-level singleton, the analog of the reference's global C state."""

    def __init__(self) -> None:
        self.lock = threading.Lock()
        self.initialized = False
        self.config: Config = Config()
        # Monotonic configuration-change counter: bumped by init(),
        # set_config(), and stop().  Every CollectivePlan key embeds it
        # (torchmpi_tpu/planner.py), so a live config switch makes every
        # previously-built plan unreachable without any cache walking —
        # the single staleness mechanism for all planner-backed caches.
        self.config_epoch = 0
        self.devices: List[jax.Device] = []
        # Stack of (name, Mesh); bottom is always ("world", world_mesh).
        self.mesh_stack: List[Tuple[str, Mesh]] = []
        # Cache of user split meshes keyed by name (reference: communicator
        # cache keyed by the push string).
        self.mesh_cache: Dict[str, Mesh] = {}
        self.distributed_initialized = False


_state = _State()


def _build_world_mesh(cfg: Config, devices: Sequence[jax.Device]) -> Mesh:
    """Build the world mesh.

    Two modes:

    - ``cfg.mesh_shape`` (first-class N-D, VERDICT r3 #6): ONE mesh whose
      named axes are exactly the dict's keys, major -> minor in dict
      order (the last axis is the most interconnect-local).  One size may
      be -1 (inferred).  No communicator pushes needed for N-D
      parallelism.
    - classic 2-level ``(dcn, ici)``: auto shape puts ``dcn`` = number of
      processes when it divides the device count (each process' local
      devices share fast interconnect — the analog of the reference
      splitting MPI_COMM_WORLD by hostname), else 1; ``ici`` = rest.
      ``cfg.ici_size``/``cfg.dcn_size`` override (used by tests to
      emulate a multi-slice topology on a flat 8-device CPU mesh).
    """
    n = len(devices)
    if cfg.mesh_shape is not None:
        if cfg.ici_size is not None or cfg.dcn_size is not None:
            raise ValueError(
                "mesh_shape is mutually exclusive with ici_size/dcn_size "
                "(mesh_shape names its own axes)")
        if not cfg.mesh_shape:
            raise ValueError("mesh_shape must name at least one axis")
        axes = tuple(cfg.mesh_shape.keys())
        sizes = list(cfg.mesh_shape.values())
        wild = [i for i, s in enumerate(sizes) if s == -1]
        if len(wild) > 1:
            raise ValueError(f"mesh_shape {cfg.mesh_shape}: at most one "
                             "axis size may be -1")
        if wild:
            rest = int(np.prod([s for s in sizes if s != -1]))
            if rest == 0 or n % rest != 0:
                raise ValueError(
                    f"mesh_shape {cfg.mesh_shape} cannot be inferred over "
                    f"{n} devices")
            sizes[wild[0]] = n // rest
        if int(np.prod(sizes)) != n:
            raise ValueError(
                f"mesh_shape {dict(zip(axes, sizes))} does not cover "
                f"{n} devices")
        return Mesh(np.asarray(devices).reshape(sizes), axes)
    dcn = cfg.dcn_size
    ici = cfg.ici_size
    if dcn is None and ici is None:
        nproc = jax.process_count()
        dcn = nproc if nproc > 1 and n % nproc == 0 else 1
        ici = n // dcn
    elif dcn is None:
        assert ici is not None
        if n % ici != 0:
            raise ValueError(f"ici_size={ici} does not divide device count {n}")
        dcn = n // ici
    elif ici is None:
        if n % dcn != 0:
            raise ValueError(f"dcn_size={dcn} does not divide device count {n}")
        ici = n // dcn
    if dcn * ici != n:
        raise ValueError(
            f"mesh shape dcn={dcn} x ici={ici} != device count {n}"
        )
    dev_array = np.asarray(devices).reshape(dcn, ici)
    return Mesh(dev_array, WORLD_AXES)


def _normalize_analysis(value) -> Optional[str]:
    """Canonical analysis mode for a config/env value: "off"|"warn"|
    "error", with boolean-ish spellings accepted ("1"/"true"/"yes"/"on"
    mean "warn", "0"/"false"/"no"/"" mean "off").  None = unrecognized
    (the caller raises)."""
    v = str(value).strip().lower()
    if v in ("off", "0", "false", "no", "none", ""):
        return "off"
    if v in ("warn", "1", "true", "yes", "on"):
        return "warn"
    if v == "error":
        return "error"
    return None


def _normalize_obs(value) -> Optional[str]:
    """Canonical obs mode for a config/env value: "off"|"metrics"|
    "trace", with boolean-ish spellings accepted ("1"/"true"/"yes"/"on"
    mean "metrics", "0"/"false"/"no"/"" mean "off").  None =
    unrecognized (the caller raises)."""
    v = str(value).strip().lower()
    if v in ("off", "0", "false", "no", "none", ""):
        return "off"
    if v in ("metrics", "1", "true", "yes", "on"):
        return "metrics"
    if v == "trace":
        return "trace"
    return None


def _normalize_overlap(value) -> Optional[str]:
    """Canonical gradsync_overlap mode for a config/env value:
    "off"|"auto", with boolean-ish spellings accepted ("1"/"true"/
    "yes"/"on" mean "auto", "0"/"false"/"no"/"" mean "off").  None =
    unrecognized (the caller raises)."""
    v = str(value).strip().lower()
    if v in ("off", "0", "false", "no", "none", ""):
        return "off"
    if v in ("auto", "on", "1", "true", "yes"):
        return "auto"
    return None


def _normalize_dcn_compress(value) -> Optional[str]:
    """Canonical dcn_compress codec for a config/env value:
    "off"|"bf16"|"int8"|"fp8" (case-insensitive; boolean-ish off
    spellings accepted).  None = unrecognized (the caller raises)."""
    v = str(value).strip().lower()
    if v in ("off", "0", "false", "no", "none", ""):
        return "off"
    if v in ("bf16", "int8", "fp8"):
        return v
    return None


def _normalize_elastic(value) -> Optional[str]:
    """Canonical elastic mode for a config/env value: "off"|"on", with
    boolean-ish spellings accepted.  None = unrecognized (the caller
    raises)."""
    v = str(value).strip().lower()
    if v in ("off", "0", "false", "no", "none", ""):
        return "off"
    if v in ("on", "1", "true", "yes"):
        return "on"
    return None


def _normalize_hotstate(value) -> Optional[str]:
    """Canonical hotstate mode for a config/env value: "off"|"on", with
    boolean-ish spellings accepted.  None = unrecognized (the caller
    raises)."""
    v = str(value).strip().lower()
    if v in ("off", "0", "false", "no", "none", ""):
        return "off"
    if v in ("on", "1", "true", "yes"):
        return "on"
    return None


def _normalize_elastic_quorum(value) -> Optional[str]:
    """Canonical elastic_quorum mode: "off"|"majority", boolean-ish
    spellings accepted ("1"/"true"/"yes"/"on" mean "majority" — the
    protect-me reading a boolean opt-in wants).  None = unrecognized
    (the caller raises)."""
    v = str(value).strip().lower()
    if v in ("off", "0", "false", "no", "none", ""):
        return "off"
    if v in ("majority", "on", "1", "true", "yes"):
        return "majority"
    return None


def _normalize_guard(value) -> Optional[str]:
    """Canonical guard mode for a config/env value:
    "off"|"wire"|"numeric"|"full", with boolean-ish spellings accepted
    ("1"/"true"/"yes"/"on" mean "full" — the everything-armed reading a
    boolean opt-in wants, "0"/"false"/"no"/"" mean "off").  None =
    unrecognized (the caller raises)."""
    v = str(value).strip().lower()
    if v in ("off", "0", "false", "no", "none", ""):
        return "off"
    if v in ("full", "on", "1", "true", "yes"):
        return "full"
    if v in ("wire", "numeric"):
        return v
    return None


def _normalize_watchdog(value) -> Optional[str]:
    """Canonical watchdog mode for a config/env value:
    "off"|"warn"|"break", with boolean-ish spellings accepted
    ("1"/"true"/"yes"/"on" mean "break" — the everything-armed reading
    a boolean opt-in wants, "0"/"false"/"no"/"" mean "off").  None =
    unrecognized (the caller raises)."""
    v = str(value).strip().lower()
    if v in ("off", "0", "false", "no", "none", ""):
        return "off"
    if v in ("break", "on", "1", "true", "yes"):
        return "break"
    if v == "warn":
        return v
    return None


def _normalize_ckpt_redundancy(value) -> Optional[str]:
    """Canonical ckpt_redundancy mode for a config/env value:
    "off"|"verify"|"buddy", with boolean-ish spellings accepted
    ("1"/"true"/"yes"/"on" mean "buddy" — the everything-armed reading
    a boolean opt-in wants, "0"/"false"/"no"/"" mean "off").  None =
    unrecognized (the caller raises)."""
    v = str(value).strip().lower()
    if v in ("off", "0", "false", "no", "none", ""):
        return "off"
    if v in ("buddy", "on", "1", "true", "yes"):
        return "buddy"
    if v == "verify":
        return v
    return None


def _normalize_guard_policy(value) -> Optional[str]:
    """Canonical guard_numeric_policy: "skip_step"|"raise".  None =
    unrecognized (the caller raises)."""
    v = str(value).strip().lower()
    if v in ("skip_step", "skip"):
        return "skip_step"
    if v == "raise":
        return "raise"
    return None


def _normalize_faults(value) -> str:
    """Canonical faults mode for a config/env value: "off", "policy",
    or a fault-plan path (kept verbatim).  Boolean-ish spellings map to
    the two modes ("0"/"false"/"no" -> off, "1"/"true"/"yes"/"on" ->
    policy); anything else is treated as a path — a typo'd path fails
    loudly when the plan loads, which is the posture a chaos knob
    wants."""
    v = str(value).strip()
    low = v.lower()
    if low in ("off", "0", "false", "no", "none", ""):
        return "off"
    if low in ("policy", "on", "1", "true", "yes"):
        return "policy"
    return v


def _env_default_pickup(cfg: Config, field: str, env: str, cast) -> None:
    """Obs-ring-style any-config env pickup for a numeric knob: a field
    left at its dataclass default defers to the environment, an explicit
    non-default value wins."""
    import dataclasses as _dc

    raw = os.environ.get(env)
    if not raw:
        return
    default = next(f.default for f in _dc.fields(Config)
                   if f.name == field)
    if getattr(cfg, field) == default:
        setattr(cfg, field, cast(raw))


def _faults_activate(cfg: Config) -> None:
    """Import and arm the fault layer (only ever called with
    ``cfg.faults != "off"`` — the off path never imports the module).
    Raises on an unreadable/corrupt plan path: a chaos run that
    silently injects nothing is worse than one that fails to start."""
    from . import faults

    faults.activate(cfg.faults, retries=cfg.fault_retries,
                    backoff_s=cfg.fault_backoff_s,
                    deadline_s=cfg.fault_deadline_s)


def _faults_deactivate_stale() -> None:
    """Disarm a previous session's fault layer without importing it
    (sys.modules only — turning faults off never imports the module)."""
    import sys

    mod = sys.modules.get(__package__ + ".faults")
    if mod is not None and mod.active():
        mod.deactivate()


def _watchdog_activate(cfg: Config) -> None:
    """Import and arm the collective watchdog (only ever called with
    ``cfg.watchdog != "off"`` — the off path never imports the
    module).  The lease directory resolves to ``watchdog_dir``, then
    the membership board (``elastic_dir``), then — on a re-activation
    (a mid-run ``set_config`` deadline tune) — whatever directory the
    already-armed watchdog leases into, so a lease home the elastic
    driver ADOPTED at gang construction (``watchdog.set_lease_dir``)
    survives reconfiguration instead of silently orphaning the rank's
    lease on the board (peers read its expiry as death evidence).
    None disables leases; the in-process monitor still runs."""
    from . import watchdog

    lease_dir = cfg.watchdog_dir or cfg.elastic_dir
    if lease_dir is None and watchdog.active():
        lease_dir = watchdog.lease_dir()
    watchdog.activate(cfg.watchdog, deadline_s=cfg.watchdog_deadline_s,
                      poll_s=cfg.watchdog_poll_s,
                      lease_dir=lease_dir,
                      rank=jax.process_index())


def _watchdog_deactivate_stale() -> None:
    """Disarm a previous session's watchdog without importing it
    (sys.modules only — turning the watchdog off never imports it)."""
    import sys

    mod = sys.modules.get(__package__ + ".watchdog")
    if mod is not None and mod.active():
        mod.deactivate()


def _obs_activate(cfg: Config) -> None:
    """Import and arm the telemetry layer (only ever called with
    ``cfg.obs != "off"`` — the off path never imports the module).

    The same any-config env pickup as the mode itself: obs_dir and
    obs_ring_size left at their defaults defer to TORCHMPI_TPU_OBS_DIR
    / _OBS_RING, so `TORCHMPI_TPU_OBS=metrics python some_script.py`
    honors all three envs even when the script builds its Config
    explicitly; an explicit non-default field still wins."""
    import dataclasses as _dc

    from . import obs

    out_dir = (cfg.obs_dir or os.environ.get("TORCHMPI_TPU_OBS_DIR")
               or obs.DEFAULT_OUT_DIR)
    ring = cfg.obs_ring_size
    env_ring = os.environ.get("TORCHMPI_TPU_OBS_RING")
    default_ring = next(f.default for f in _dc.fields(Config)
                        if f.name == "obs_ring_size")
    if env_ring and ring == default_ring:
        ring = int(env_ring)
    obs.activate(cfg.obs, out_dir=out_dir, ring_size=ring,
                 host=jax.process_index())


def init(config: Optional[Config] = None, **overrides) -> Mesh:
    """Start the runtime (reference: ``mpi.start(withCuda)`` -> torchmpi_start).

    Idempotent.  Returns the world mesh.  Unlike the reference there is no
    mpirun: on a multi-host TPU slice, ``jax.distributed.initialize`` picks up
    topology from the TPU metadata environment; single-process (tests, one
    chip) needs no bring-up at all.
    """
    with _state.lock:
        if _state.initialized:
            return _state.mesh_stack[0][1]
        # Copy so later set_config() calls never mutate the caller's object
        # (incl. a private copy of the mutable per-op table).
        cfg = Config.from_env() if config is None else dataclasses.replace(config)
        if cfg.backend_per_op is not None:
            cfg.backend_per_op = _validate_backend_per_op(cfg.backend_per_op)
        for k, v in overrides.items():
            if not hasattr(cfg, k):
                raise ValueError(f"unknown config field {k!r}")
            if k == "backend_per_op" and v is not None:
                v = _validate_backend_per_op(v)
            setattr(cfg, k, v)

        # Launcher env pickup applies to ANY config (scripts typically pass
        # an explicit Config; they must still join the launched job rather
        # than silently running N disconnected single-process copies).
        import os

        # Same any-config rule for the analyzer opt-in: an operator (or
        # scripts/lint_collectives.py) exporting TORCHMPI_TPU_ANALYSIS
        # must reach scripts that build their Config explicitly.  An
        # explicit non-default field still wins.  Normalization happens
        # in one place for BOTH sources (explicit Config value and env)
        # so "WARN", "1", and "warn" behave identically everywhere.
        if _normalize_analysis(cfg.analysis) == "off":
            cfg.analysis = os.environ.get("TORCHMPI_TPU_ANALYSIS", "off")
        cfg.analysis = _normalize_analysis(cfg.analysis)
        if cfg.analysis is None:
            raise ValueError(
                "config.analysis (or TORCHMPI_TPU_ANALYSIS) must be "
                "off|warn|error")

        # Same any-config env pickup + one-home normalization for the
        # telemetry opt-in (TORCHMPI_TPU_OBS): an explicit non-default
        # field wins; "1"/"true" mean "metrics".
        if _normalize_obs(cfg.obs) == "off":
            cfg.obs = os.environ.get("TORCHMPI_TPU_OBS", "off")
        cfg.obs = _normalize_obs(cfg.obs)
        if cfg.obs is None:
            raise ValueError(
                "config.obs (or TORCHMPI_TPU_OBS) must be "
                "off|metrics|trace")

        # Same any-config rule for the fault layer (TORCHMPI_TPU_FAULTS
        # + the numeric policy/timeout knobs): an explicit non-default
        # field wins, env fills the defaults — so `TORCHMPI_TPU_FAULTS=
        # plan.json python train.py` reaches scripts that build their
        # Config explicitly (the chaos-smoke CI job relies on this).
        if _normalize_faults(cfg.faults) == "off":
            cfg.faults = os.environ.get("TORCHMPI_TPU_FAULTS", "off")
        cfg.faults = _normalize_faults(cfg.faults)
        _env_default_pickup(cfg, "fault_retries",
                            "TORCHMPI_TPU_FAULT_RETRIES", int)
        _env_default_pickup(cfg, "fault_backoff_s",
                            "TORCHMPI_TPU_FAULT_BACKOFF", float)
        _env_default_pickup(cfg, "fault_deadline_s",
                            "TORCHMPI_TPU_FAULT_DEADLINE", float)
        _env_default_pickup(cfg, "ps_timeout_s",
                            "TORCHMPI_TPU_PS_TIMEOUT", float)
        # Payload-integrity + numeric-anomaly guard (docs/GUARD.md):
        # same any-config env pickup + one-home normalization as
        # analysis/obs/faults.  "off" (the default) never imports
        # torchmpi_tpu.guard (or faults.integrity): the mode is read as
        # one string compare at plan build / trace time.
        if _normalize_guard(cfg.guard) == "off":
            cfg.guard = os.environ.get("TORCHMPI_TPU_GUARD", "off")
        cfg.guard = _normalize_guard(cfg.guard)
        if cfg.guard is None:
            raise ValueError(
                "config.guard (or TORCHMPI_TPU_GUARD) must be "
                "off|wire|numeric|full")
        cfg.guard_numeric_policy = _normalize_guard_policy(
            cfg.guard_numeric_policy)
        if cfg.guard_numeric_policy is None:
            raise ValueError(
                "config.guard_numeric_policy (or TORCHMPI_TPU_GUARD_POLICY)"
                " must be skip_step|raise")
        _env_default_pickup(cfg, "guard_norm_bound",
                            "TORCHMPI_TPU_GUARD_NORM_BOUND", float)
        _env_default_pickup(cfg, "guard_spike_window",
                            "TORCHMPI_TPU_GUARD_WINDOW", int)
        _env_default_pickup(cfg, "guard_spike_threshold",
                            "TORCHMPI_TPU_GUARD_THRESHOLD", float)
        if cfg.guard_norm_bound < 0:
            raise ValueError(
                f"config.guard_norm_bound must be >= 0 (0 = finite-only),"
                f" got {cfg.guard_norm_bound}")
        if cfg.guard_spike_window < 2 or cfg.guard_spike_threshold <= 0:
            raise ValueError(
                f"config.guard_spike_window must be >= 2 and "
                f"guard_spike_threshold > 0, got "
                f"{cfg.guard_spike_window}/{cfg.guard_spike_threshold}")
        # Collective watchdog (docs/WATCHDOG.md): same any-config env
        # pickup + one-home normalization as analysis/obs/faults/guard.
        # "off" (default) never imports torchmpi_tpu.watchdog — the
        # mode is read as one string compare at plan build / site
        # entry, and the planned dispatch path gains zero branches.
        if _normalize_watchdog(cfg.watchdog) == "off":
            cfg.watchdog = os.environ.get("TORCHMPI_TPU_WATCHDOG", "off")
        cfg.watchdog = _normalize_watchdog(cfg.watchdog)
        if cfg.watchdog is None:
            raise ValueError(
                "config.watchdog (or TORCHMPI_TPU_WATCHDOG) must be "
                "off|warn|break")
        _env_default_pickup(cfg, "watchdog_deadline_s",
                            "TORCHMPI_TPU_WATCHDOG_DEADLINE", float)
        _env_default_pickup(cfg, "watchdog_poll_s",
                            "TORCHMPI_TPU_WATCHDOG_POLL", float)
        if cfg.watchdog_dir is None:
            cfg.watchdog_dir = (
                os.environ.get("TORCHMPI_TPU_WATCHDOG_DIR") or None)
        if cfg.watchdog_deadline_s <= 0 or cfg.watchdog_poll_s <= 0:
            raise ValueError(
                f"config.watchdog_deadline_s and watchdog_poll_s must "
                f"be > 0, got {cfg.watchdog_deadline_s}/"
                f"{cfg.watchdog_poll_s}")
        # Durable checkpoints (docs/CHECKPOINT.md): same any-config env
        # pickup + one-home normalization.  "off" (default) never
        # imports utils/durable.py — save/restore read the mode as one
        # string compare at entry.
        if _normalize_ckpt_redundancy(cfg.ckpt_redundancy) == "off":
            cfg.ckpt_redundancy = os.environ.get(
                "TORCHMPI_TPU_CKPT_REDUNDANCY", "off")
        cfg.ckpt_redundancy = _normalize_ckpt_redundancy(
            cfg.ckpt_redundancy)
        if cfg.ckpt_redundancy is None:
            raise ValueError(
                "config.ckpt_redundancy (or TORCHMPI_TPU_CKPT_REDUNDANCY)"
                " must be off|verify|buddy")
        _env_default_pickup(cfg, "ckpt_buddies",
                            "TORCHMPI_TPU_CKPT_BUDDIES", int)
        _env_default_pickup(cfg, "ckpt_keep",
                            "TORCHMPI_TPU_CKPT_KEEP", int)
        if cfg.ckpt_buddies < 1 or cfg.ckpt_keep < 0:
            raise ValueError(
                f"config.ckpt_buddies must be >= 1 and ckpt_keep >= 0 "
                f"(0 = keep everything), got "
                f"{cfg.ckpt_buddies}/{cfg.ckpt_keep}")
        # Hot-state replication tier (docs/HOTSTATE.md): same
        # any-config env pickup + one-home normalization.  "on" arms
        # NOTHING here — torchmpi_tpu.hotstate is a driver layer the
        # user enables explicitly, and the knob is its consent gate;
        # "off" (default) never imports the module and the dispatch
        # path has no branch on it at all.
        if _normalize_hotstate(cfg.hotstate) == "off":
            cfg.hotstate = os.environ.get("TORCHMPI_TPU_HOTSTATE", "off")
        cfg.hotstate = _normalize_hotstate(cfg.hotstate)
        if cfg.hotstate is None:
            raise ValueError(
                "config.hotstate (or TORCHMPI_TPU_HOTSTATE) must be "
                "off|on")
        _env_default_pickup(cfg, "hotstate_interval",
                            "TORCHMPI_TPU_HOTSTATE_INTERVAL", int)
        _env_default_pickup(cfg, "hotstate_budget_mb",
                            "TORCHMPI_TPU_HOTSTATE_BUDGET_MB", int)
        if cfg.hotstate_interval < 1 or cfg.hotstate_budget_mb < 1:
            raise ValueError(
                f"config.hotstate_interval and hotstate_budget_mb must "
                f"be >= 1, got {cfg.hotstate_interval}/"
                f"{cfg.hotstate_budget_mb}")
        # Elastic gang membership (docs/ELASTIC.md): same any-config env
        # pickup + one-home normalization.  "on" arms NOTHING here —
        # torchmpi_tpu.elastic is a driver layer the user calls
        # explicitly, and the knob is its consent gate; "off" (default)
        # never imports the module and the dispatch path has no branch
        # on it at all.
        if _normalize_elastic(cfg.elastic) == "off":
            cfg.elastic = os.environ.get("TORCHMPI_TPU_ELASTIC", "off")
        cfg.elastic = _normalize_elastic(cfg.elastic)
        if cfg.elastic is None:
            raise ValueError(
                "config.elastic (or TORCHMPI_TPU_ELASTIC) must be off|on")
        if cfg.elastic_dir is None:
            cfg.elastic_dir = (
                os.environ.get("TORCHMPI_TPU_ELASTIC_DIR") or None)
        _env_default_pickup(cfg, "elastic_poll_s",
                            "TORCHMPI_TPU_ELASTIC_POLL", float)
        _env_default_pickup(cfg, "elastic_deadline_s",
                            "TORCHMPI_TPU_ELASTIC_DEADLINE", float)
        if cfg.elastic_poll_s <= 0 or cfg.elastic_deadline_s <= 0:
            raise ValueError(
                f"config.elastic_poll_s and elastic_deadline_s must be "
                f"> 0, got {cfg.elastic_poll_s}/{cfg.elastic_deadline_s}")
        if _normalize_elastic_quorum(cfg.elastic_quorum) == "off":
            cfg.elastic_quorum = os.environ.get(
                "TORCHMPI_TPU_ELASTIC_QUORUM", "off")
        cfg.elastic_quorum = _normalize_elastic_quorum(cfg.elastic_quorum)
        if cfg.elastic_quorum is None:
            raise ValueError(
                "config.elastic_quorum (or TORCHMPI_TPU_ELASTIC_QUORUM) "
                "must be off|majority")
        # Serving-layer sizing (docs/SERVING.md): same any-config env
        # pickup; the knobs are plain ints, the package itself is only
        # ever imported by explicit use.
        _env_default_pickup(cfg, "serving_slots",
                            "TORCHMPI_TPU_SERVING_SLOTS", int)
        _env_default_pickup(cfg, "serving_slot_tokens",
                            "TORCHMPI_TPU_SERVING_SLOT_TOKENS", int)
        _env_default_pickup(cfg, "serving_replicas",
                            "TORCHMPI_TPU_SERVING_REPLICAS", int)
        _env_default_pickup(cfg, "serving_sample",
                            "TORCHMPI_TPU_SERVING_SAMPLE", float)
        _env_default_pickup(cfg, "serving_spec_k",
                            "TORCHMPI_TPU_SERVING_SPEC_K", int)
        _env_default_pickup(cfg, "serving_prefill_buckets",
                            "TORCHMPI_TPU_SERVING_PREFILL_BUCKETS", int)
        _env_default_pickup(cfg, "serving_prefix_cache",
                            "TORCHMPI_TPU_SERVING_PREFIX_CACHE", int)
        _env_default_pickup(cfg, "serving_slo_ttft_us",
                            "TORCHMPI_TPU_SERVING_SLO_TTFT_US", float)
        _env_default_pickup(cfg, "serving_autoscale",
                            "TORCHMPI_TPU_SERVING_AUTOSCALE", int)
        if cfg.serving_prefix_cache < 0 or cfg.serving_autoscale < 0 \
                or cfg.serving_slo_ttft_us < 0:
            raise ValueError(
                f"config.serving_prefix_cache / serving_autoscale / "
                f"serving_slo_ttft_us must be >= 0 (0 = off), got "
                f"{cfg.serving_prefix_cache}/{cfg.serving_autoscale}/"
                f"{cfg.serving_slo_ttft_us}")
        if cfg.serving_spec_k < 0 or cfg.serving_prefill_buckets < 0:
            raise ValueError(
                f"config.serving_spec_k and serving_prefill_buckets "
                f"must be >= 0 (0 = off), got {cfg.serving_spec_k}/"
                f"{cfg.serving_prefill_buckets}")
        if cfg.serving_slots < 1 or cfg.serving_replicas < 1 \
                or cfg.serving_slot_tokens < 0:
            raise ValueError(
                f"config.serving_slots/serving_replicas must be >= 1 and "
                f"serving_slot_tokens >= 0 (0 = model max_len), got "
                f"{cfg.serving_slots}/{cfg.serving_replicas}/"
                f"{cfg.serving_slot_tokens}")
        if (os.environ.get("TORCHMPI_TPU_PS_TIMEOUT") is None
                and os.environ.get("TORCHMPI_TPU_PS_TIMEOUT_MS")):
            # Legacy millisecond spelling (pre-Config knob): honored
            # when the new env is unset, as config.py promises.
            _env_default_pickup(cfg, "ps_timeout_s",
                                "TORCHMPI_TPU_PS_TIMEOUT_MS",
                                lambda v: float(v) / 1000.0)
        if cfg.ps_timeout_s < 0:
            raise ValueError(
                f"config.ps_timeout_s must be >= 0 (0 disables), got "
                f"{cfg.ps_timeout_s}")

        # Backprop-overlapped gradient sync (docs/OVERLAP.md): same
        # any-config env pickup + normalization as analysis/obs/faults.
        if _normalize_overlap(cfg.gradsync_overlap) == "off":
            cfg.gradsync_overlap = os.environ.get(
                "TORCHMPI_TPU_GRADSYNC_OVERLAP", "off")
        cfg.gradsync_overlap = _normalize_overlap(cfg.gradsync_overlap)
        if cfg.gradsync_overlap is None:
            raise ValueError(
                "config.gradsync_overlap (or TORCHMPI_TPU_GRADSYNC_OVERLAP)"
                " must be off|auto")
        _env_default_pickup(cfg, "gradsync_overlap_bytes",
                            "TORCHMPI_TPU_GRADSYNC_OVERLAP_BYTES", int)
        if cfg.gradsync_overlap_bytes < 0:
            raise ValueError(
                f"config.gradsync_overlap_bytes must be >= 0 (0 = derive "
                f"from the tuning plan), got {cfg.gradsync_overlap_bytes}")

        # Two-level DCN staging knobs (docs/HIERARCHICAL.md): same
        # any-config env pickup + one-home normalization as the layers
        # above.  The codec itself is resolved at trace/plan-build time
        # — "off" never imports torchmpi_tpu.compress.
        if _normalize_dcn_compress(cfg.dcn_compress) == "off":
            cfg.dcn_compress = os.environ.get("TORCHMPI_TPU_DCN_COMPRESS",
                                              "off")
        cfg.dcn_compress = _normalize_dcn_compress(cfg.dcn_compress)
        if cfg.dcn_compress is None:
            raise ValueError(
                "config.dcn_compress (or TORCHMPI_TPU_DCN_COMPRESS) must "
                "be off|bf16|int8|fp8")
        _env_default_pickup(cfg, "dcn_compress_min_bytes",
                            "TORCHMPI_TPU_DCN_COMPRESS_MIN_BYTES", int)
        _env_default_pickup(cfg, "dcn_chunk_bytes",
                            "TORCHMPI_TPU_DCN_CHUNK_BYTES", int)
        if cfg.dcn_compress_min_bytes < 0 or cfg.dcn_chunk_bytes < 0:
            raise ValueError(
                "config.dcn_compress_min_bytes and dcn_chunk_bytes must "
                "be >= 0 (0 = no floor / no chunking)")

        if cfg.coordinator_address is None:
            coord = os.environ.get("TORCHMPI_TPU_COORDINATOR")
            if coord:
                cfg.coordinator_address = coord
                cfg.num_processes = int(
                    os.environ.get("TORCHMPI_TPU_NUM_PROCESSES", "1"))
                cfg.process_id = int(
                    os.environ.get("TORCHMPI_TPU_PROCESS_ID", "0"))

        # Multi-process bring-up (reference: MPI_Init_thread under mpirun).
        if cfg.coordinator_address is not None and not _state.distributed_initialized:
            if os.environ.get("TORCHMPI_TPU_LOCAL_CPU"):
                # Launched by `python -m torchmpi_tpu.launch`: emulated
                # multi-host on CPU devices with gloo cross-process
                # collectives (the mpirun-on-localhost test rig).
                jax.config.update("jax_platforms", "cpu")
                jax.config.update("jax_cpu_collectives_implementation",
                                  "gloo")
            jax.distributed.initialize(
                coordinator_address=cfg.coordinator_address,
                num_processes=cfg.num_processes,
                process_id=cfg.process_id,
            )
            _state.distributed_initialized = True

        # Re-assert the relay compile-budget gate (armed at package
        # import; a client may have uninstalled it or imported around
        # the package __init__).  See utils/compilegate.py.
        from .utils import compilegate

        compilegate.install()

        # Arm (or disarm a stale) fault layer BEFORE the runtime marks
        # itself initialized: a corrupt/missing fault plan must fail
        # init outright — never leave a half-armed runtime behind a
        # chaos knob that silently injects nothing.  Off (the default)
        # never imports torchmpi_tpu.faults.
        if cfg.faults != "off":
            _faults_activate(cfg)
        else:
            _faults_deactivate_stale()

        _state.config = cfg
        _state.devices = list(jax.devices())
        world = _build_world_mesh(cfg, _state.devices)
        _state.mesh_stack = [("world", world)]
        _state.mesh_cache = {"world": world}
        _state.initialized = True
        _state.config_epoch += 1
    # Outside the lock: tuning.configure reads runtime state via the
    # public accessors.  Loads the persistent collective plan DB and
    # registers the selector's plan provider when the config opts into
    # measured selection (backend="auto", a per-op "auto", or an
    # explicit plan path — e.g. one emitted by benchmarks/autotune.py).
    if _tuning_opted_in(cfg):
        from . import tuning

        tuning.configure(cfg.tuning_plan_path, rounds=cfg.tuning_rounds,
                         auto_active=_tuning_auto_active(cfg))
    if cfg.analysis != "off":
        # Arm the findings capture (and the TORCHMPI_TPU_ANALYSIS_OUT
        # atexit report) so even a process that dies before its first
        # checked compile leaves an (empty) report behind.
        from . import analysis

        analysis.arm_runtime_capture()
    if cfg.obs != "off":
        # Arm telemetry (registry + flight recorder + SIGTERM/atexit
        # dump).  Off (the default) never imports torchmpi_tpu.obs.
        _obs_activate(cfg)
    else:
        # A previous session's telemetry must not survive a re-init
        # that opted out (stale mode, SIGTERM handler, atexit dump) —
        # but only via sys.modules: turning obs off never imports it.
        import sys

        mod = sys.modules.get(__package__ + ".obs")
        if mod is not None and mod.active():
            mod.deactivate()
    # Collective watchdog: armed AFTER obs so the monitor's first
    # events land in an armed registry.  Off (the default) never
    # imports torchmpi_tpu.watchdog.
    if cfg.watchdog != "off":
        _watchdog_activate(cfg)
    else:
        _watchdog_deactivate_stale()
    return world


def stop() -> None:
    """Tear down (reference: ``mpi.stop`` -> torchmpi_stop -> MPI_Finalize)."""
    with _state.lock:
        _state.initialized = False
        _state.mesh_stack = []
        _state.mesh_cache = {}
        _state.config_epoch += 1
    from . import collectives, tuning

    collectives.clear_cache()
    tuning.reset()
    # A quorum-armed elastic gang published an epoch fence for the
    # checkpoint seam (faults/fencing.py) — retract it with the
    # runtime so a later non-elastic session's saves are not checked
    # against a dead board.  sys.modules on purpose: the module is
    # only ever imported when quorum was armed.
    fencing = sys.modules.get("torchmpi_tpu.faults.fencing")
    if fencing is not None:
        fencing.disarm()


def is_initialized() -> bool:
    return _state.initialized


def _require_init() -> None:
    if not _state.initialized:
        raise RuntimeError(
            "torchmpi_tpu runtime not initialized; call torchmpi_tpu.init() first "
            "(the reference raised the same way when mpi.start() was skipped)"
        )


def config() -> Config:
    return _state.config


def config_epoch() -> int:
    """Monotonic counter of configuration changes (init / set_config /
    stop each bump it).  ``torchmpi_tpu.planner`` embeds the current
    value in every plan key, so a live knob switch invalidates every
    cached :class:`~torchmpi_tpu.planner.CollectivePlan` by making it
    unreachable — mutate the active config only through
    :func:`set_config` (direct writes to the :func:`config` object
    bypass the epoch and can replay stale plans)."""
    return _state.config_epoch


def effective_config() -> Config:
    """The active Config when the runtime is initialized, else defaults.

    For trace-time knob reads (``chunk_bytes``, ``pallas_bidirectional``)
    from code that may run outside ``init()`` — direct kernel use, tests —
    so every consumer resolves knobs identically."""
    return _state.config if _state.initialized else Config()


def resolve_blocks(block_a, block_b, field_a: str, field_b: str):
    """Resolve ``None`` kernel-tiling arguments from the active Config —
    the knobs ``benchmarks/autotune.py`` measures per platform.  The one
    resolution point for every Pallas kernel entry (flash forward, the
    custom-VJP training wrappers, ring attention, fused-xent), so the
    autotuned values reach training code, not just forward-only calls."""
    if block_a is None or block_b is None:
        cfg = effective_config()
        if block_a is None:
            block_a = getattr(cfg, field_a)
        if block_b is None:
            block_b = getattr(cfg, field_b)
    return block_a, block_b


def _validate_backend_per_op(table: Dict[str, str]) -> Dict[str, str]:
    """Per-op override tables fail loudly on typos (a silently-ignored key
    would let a user benchmark the wrong implementation)."""
    from . import selector

    avail = selector.available()
    for op, backend in table.items():
        if op not in avail:
            raise ValueError(
                f"backend_per_op: unknown collective {op!r} "
                f"(known: {sorted(avail)})")
        if backend not in ("xla", "auto") and backend not in avail[op]:
            raise ValueError(
                f"backend_per_op[{op!r}]: backend {backend!r} has no "
                f"implementation for this op (available: "
                f"{sorted(avail[op])})")
    return dict(table)  # private copy: never alias the caller's dict


def _tuning_auto_active(cfg: Config) -> bool:
    """Does some backend actually resolve to "auto" (plan-driven)?"""
    if cfg.backend == "auto":
        return True
    return bool(cfg.backend_per_op
                and "auto" in cfg.backend_per_op.values())


def _tuning_opted_in(cfg: Config) -> bool:
    """Did this config ask the tuning subsystem to load a plan?  A plan
    path WITHOUT any "auto" backend still loads (and the decision log
    notes it is inactive) so the misconfiguration is visible."""
    return _tuning_auto_active(cfg) or cfg.tuning_plan_path is not None


def set_config(**kw) -> None:
    """Runtime-switch knobs (reference: the torchmpi_set_* FFI setters).

    Bumps the config epoch and clears the collective plan table
    (``torchmpi_tpu/planner.py``): every planned decision — compiled
    executables, fusion bucketing, selector/tuning backend choices,
    obs/faults enablement — was resolved under the old config and must
    not be replayed (the reference's setters likewise took effect
    immediately).  In-axis collectives inside a USER's jit are cached by
    jax itself and keep their traced-time settings until the user
    retraces.
    """
    _require_init()
    for k, v in kw.items():
        if not hasattr(_state.config, k):
            raise ValueError(f"unknown config field {k!r}")
        if k == "backend_per_op" and v is not None:
            v = _validate_backend_per_op(v)
        if k == "analysis":
            v = _normalize_analysis(v)
            if v is None:
                raise ValueError(
                    "config.analysis must be off|warn|error")
        if k == "obs":
            v = _normalize_obs(v)
            if v is None:
                raise ValueError("config.obs must be off|metrics|trace")
        if k == "faults":
            v = _normalize_faults(v)
        if k == "guard":
            v = _normalize_guard(v)
            if v is None:
                raise ValueError(
                    "config.guard must be off|wire|numeric|full")
        if k == "guard_numeric_policy":
            v = _normalize_guard_policy(v)
            if v is None:
                raise ValueError(
                    "config.guard_numeric_policy must be skip_step|raise")
        if k == "guard_norm_bound":
            v = float(v)
            if v < 0:
                raise ValueError(
                    "config.guard_norm_bound must be >= 0 "
                    "(0 = finite-only)")
        if k == "guard_spike_window":
            v = int(v)
            if v < 2:
                raise ValueError("config.guard_spike_window must be >= 2")
        if k == "guard_spike_threshold":
            v = float(v)
            if v <= 0:
                raise ValueError(
                    "config.guard_spike_threshold must be > 0")
        if k == "watchdog":
            v = _normalize_watchdog(v)
            if v is None:
                raise ValueError(
                    "config.watchdog must be off|warn|break")
        if k in ("watchdog_deadline_s", "watchdog_poll_s"):
            v = float(v)
            if v <= 0:
                raise ValueError(f"config.{k} must be > 0")
        if k == "ckpt_redundancy":
            v = _normalize_ckpt_redundancy(v)
            if v is None:
                raise ValueError(
                    "config.ckpt_redundancy must be off|verify|buddy")
        if k == "ckpt_buddies":
            v = int(v)
            if v < 1:
                raise ValueError("config.ckpt_buddies must be >= 1")
        if k == "ckpt_keep":
            v = int(v)
            if v < 0:
                raise ValueError(
                    "config.ckpt_keep must be >= 0 (0 = keep everything)")
        if k == "elastic":
            v = _normalize_elastic(v)
            if v is None:
                raise ValueError("config.elastic must be off|on")
        if k == "hotstate":
            v = _normalize_hotstate(v)
            if v is None:
                raise ValueError("config.hotstate must be off|on")
        if k in ("hotstate_interval", "hotstate_budget_mb"):
            v = int(v)
            if v < 1:
                raise ValueError(f"config.{k} must be >= 1")
        if k in ("elastic_poll_s", "elastic_deadline_s"):
            v = float(v)
            if v <= 0:
                raise ValueError(f"config.{k} must be > 0")
        if k == "elastic_quorum":
            v = _normalize_elastic_quorum(v)
            if v is None:
                raise ValueError(
                    "config.elastic_quorum must be off|majority")
        if k == "elastic_dir":
            # Same one-home normalization as init: "" means unset.
            v = v or None
        if k == "gradsync_overlap":
            v = _normalize_overlap(v)
            if v is None:
                raise ValueError("config.gradsync_overlap must be off|auto")
        if k == "gradsync_overlap_bytes":
            v = int(v)
            if v < 0:
                raise ValueError(
                    "config.gradsync_overlap_bytes must be >= 0")
        if k == "dcn_compress":
            v = _normalize_dcn_compress(v)
            if v is None:
                raise ValueError(
                    "config.dcn_compress must be off|bf16|int8|fp8")
        if k in ("dcn_compress_min_bytes", "dcn_chunk_bytes"):
            v = int(v)
            if v < 0:
                raise ValueError(f"config.{k} must be >= 0")
        if k == "ps_timeout_s":
            v = float(v)
            if v < 0:
                raise ValueError(
                    "config.ps_timeout_s must be >= 0 (0 disables)")
        if k in ("serving_slots", "serving_replicas"):
            v = int(v)
            if v < 1:
                raise ValueError(f"config.{k} must be >= 1")
        if k == "serving_slot_tokens":
            v = int(v)
            if v < 0:
                raise ValueError(
                    "config.serving_slot_tokens must be >= 0 "
                    "(0 = model max_len)")
        if k == "serving_sample":
            # <= 0 means greedy (config.py), so only the type is pinned.
            v = float(v)
        if k in ("serving_spec_k", "serving_prefill_buckets"):
            v = int(v)
            if v < 0:
                raise ValueError(f"config.{k} must be >= 0 (0 = off)")
        if k in ("serving_prefix_cache", "serving_autoscale"):
            v = int(v)
            if v < 0:
                raise ValueError(f"config.{k} must be >= 0 (0 = off)")
        if k == "serving_slo_ttft_us":
            v = float(v)
            if v < 0:
                raise ValueError(
                    "config.serving_slo_ttft_us must be >= 0 "
                    "(0 = admit everything)")
        if k == "fault_retries":
            v = int(v)
        if k in ("fault_backoff_s", "fault_deadline_s"):
            v = float(v)
        setattr(_state.config, k, v)
    # Every plan key embeds the epoch (torchmpi_tpu/planner.py), so the
    # bump alone already strands every stale CollectivePlan; the
    # clear_cache() below additionally releases their memory.
    _state.config_epoch += 1
    if ("faults" in kw or "fault_retries" in kw or "fault_backoff_s" in kw
            or "fault_deadline_s" in kw):
        if _state.config.faults != "off":
            _faults_activate(_state.config)
        else:
            _faults_deactivate_stale()
    if "obs" in kw or "obs_dir" in kw or "obs_ring_size" in kw:
        if _state.config.obs != "off":
            _obs_activate(_state.config)
        else:
            import sys

            # Turning obs OFF must not import the module it disables.
            mod = sys.modules.get(__package__ + ".obs")
            if mod is not None:
                mod.deactivate()
    if "analysis" in kw and _state.config.analysis != "off":
        # Same arming as init: capture + the ANALYSIS_OUT atexit report.
        from . import analysis

        analysis.arm_runtime_capture()
    if ("watchdog" in kw or "watchdog_deadline_s" in kw
            or "watchdog_poll_s" in kw or "watchdog_dir" in kw):
        if _state.config.watchdog != "off":
            _watchdog_activate(_state.config)
        else:
            # Turning the watchdog OFF must not import the module.
            _watchdog_deactivate_stale()
    from . import collectives, tuning

    collectives.clear_cache()
    # (Re)configure tuning whenever the config opts into auto/planned
    # selection: a changed tuning_plan_path or tuning_rounds takes
    # effect immediately (the reference's setters likewise did), and
    # switching INTO auto at runtime activates the plan DB.  An
    # unchanged path keeps the in-memory entries (they may be
    # unpersistable on a read-only tree) and merges in whatever
    # appeared on disk meanwhile; a changed path reloads outright.
    if _tuning_opted_in(_state.config):
        tuning.configure(_state.config.tuning_plan_path,
                         rounds=_state.config.tuning_rounds,
                         auto_active=_tuning_auto_active(_state.config))


# --- rank/size family -------------------------------------------------------
# TorchMPI's rank was a per-*process* concept (one process per GPU).  Under
# JAX SPMD one process drives many devices, so both granularities are exposed:
# process-level (data loading, logging, PS clients) and device-level (inside
# shard_map, via jax.lax.axis_index).


def rank() -> int:
    """Process rank (reference: ``mpi.rank()``)."""
    return jax.process_index()


def size() -> int:
    """Process count (reference: ``mpi.size()``)."""
    return jax.process_count()


def local_rank() -> int:
    """Rank of this process among processes on the same host.

    Defined (round 1 returned a plausible guess): the launcher that
    co-locates processes exports ``TORCHMPI_TPU_LOCAL_RANK`` (our
    ``launch.py`` does; schedulers can too); absent that, JAX's standard
    deployment is one process per host, so the local rank is 0.  The
    reference used localRank % numDevices for GPU binding; JAX binds
    devices per process itself, so this is informational."""
    v = os.environ.get("TORCHMPI_TPU_LOCAL_RANK")
    return int(v) if v is not None else 0


def device_count() -> int:
    """Total device (chip) count across all processes."""
    _require_init()
    return len(_state.devices)


def local_device_count() -> int:
    return jax.local_device_count()


def barrier(name: str = "torchmpi_tpu_barrier") -> None:
    """Global barrier (reference: ``mpi.barrier()`` -> MPI_Barrier).

    Implemented as a tiny fully-replicated psum across every device — the
    devices *are* the processes' gang, so completion implies every process
    reached the barrier.
    """
    _require_init()
    if _state.config.obs != "off":
        from . import obs

        # Recorded BEFORE the wait: a host stuck in this barrier shows
        # it as the last flight event (obs_tool.py blame anchor).
        obs.record_barrier(name)
    wd = None
    wd_tok = -1
    if _state.config.watchdog != "off":
        # Live hang detection over the gang sync (docs/WATCHDOG.md):
        # a barrier the gang never completes is flagged stalled within
        # watchdog_deadline_s — and any deferred break from a stalled
        # background wait is delivered HERE, at the eager boundary,
        # before this process commits to another gang-wide wait.
        from . import watchdog

        wd = watchdog
        wd.raise_pending()
        wd_tok = wd.begin("runtime.barrier", op=name, peer="gang")

    def _sync():
        if jax.process_count() > 1:
            from jax.experimental import multihost_utils

            multihost_utils.sync_global_devices(name)
        else:
            jax.block_until_ready(jax.device_put(np.zeros(())))

    try:
        if _state.config.faults != "off":
            from . import faults

            # Injection fires per attempt and the gang sync runs under
            # the site deadline: a wedged peer becomes PeerTimeoutError
            # instead of an unbounded wait (docs/FAULTS.md).
            faults.guarded_barrier(name, _sync)
        else:
            _sync()
    finally:
        if wd is not None:
            wd.end(wd_tok)
    if _state.config.obs != "off":
        from . import obs

        # The completion edge: lets obs_tool blame tell "launched and
        # stuck inside the barrier" from "completed it, never launched
        # the next collective" (docs/OBSERVABILITY.md).
        obs.record_barrier_done(name)


# --- communicator (mesh) stack ---------------------------------------------


def world_mesh() -> Mesh:
    _require_init()
    return _state.mesh_stack[0][1]


def current_mesh() -> Mesh:
    """Innermost pushed communicator (reference: the active communicator the
    collectives resolved against)."""
    _require_init()
    return _state.mesh_stack[-1][1]


def current_mesh_name() -> str:
    _require_init()
    return _state.mesh_stack[-1][0]


def resize_world(devices: Sequence[jax.Device], *,
                 shape: Optional[Dict[str, int]] = None) -> Mesh:
    """Re-form the world mesh over a device subset — the gang-resize
    primitive ``torchmpi_tpu.elastic`` shrinks/grows through
    (docs/ELASTIC.md; the reference analog is tearing down and
    re-creating the communicator tree, PAPER.md: communicators are
    disposable).

    ``shape`` is an ordered axis-name -> size dict over exactly
    ``devices`` (the :func:`push_communicator` convention); ``None``
    builds a 1-D ``(ici,)`` mesh.  Replaces the whole communicator
    stack (pushed communicators are views of the OLD gang — they do
    not survive a membership change) and bumps the config epoch, so
    every cached :class:`~torchmpi_tpu.planner.CollectivePlan` built
    against the old mesh is stranded; ``planner.invalidate()`` then
    releases the stale plans' memory.  The active Config is untouched.
    """
    _require_init()
    devs = list(devices)
    if not devs:
        raise ValueError("resize_world needs at least one device")
    with _state.lock:
        if shape is None:
            mesh = Mesh(np.asarray(devs), (ICI_AXIS,))
        else:
            axes = tuple(shape.keys())
            sizes = tuple(shape.values())
            if int(np.prod(sizes)) != len(devs):
                raise ValueError(
                    f"shape {shape} does not cover {len(devs)} devices")
            mesh = Mesh(np.asarray(devs).reshape(sizes), axes)
        _state.devices = devs
        _state.mesh_stack = [("world", mesh)]
        _state.mesh_cache = {"world": mesh}
        _state.config_epoch += 1
    from . import collectives

    # Routes to planner.invalidate(): drops every plan + cached
    # sharding + legacy executable pinned to the old gang's meshes.
    collectives.clear_cache()
    return mesh


def push_communicator(
    key: str,
    *,
    devices: Optional[Sequence[jax.Device]] = None,
    shape: Optional[Dict[str, int]] = None,
) -> Mesh:
    """Push a named communicator scope (reference: user-defined communicator
    splits keyed by a string, SURVEY.md §1 cap.6).

    - ``devices``: explicit subset (1-D mesh named ``ici``) or, with ``shape``,
      reshaped into the given named axes.
    - ``shape``: dict axis-name -> size over the *current* mesh's devices
      (or over ``devices`` when given).
    - Neither: re-push of a cached mesh under ``key`` (must exist).

    Meshes are cached by key, like the reference cached communicators per
    split string.
    """
    _require_init()
    with _state.lock:
        if devices is None and shape is None:
            if key not in _state.mesh_cache:
                raise KeyError(f"no cached communicator {key!r}")
            mesh = _state.mesh_cache[key]
        else:
            devs = list(devices) if devices is not None else list(
                _state.mesh_stack[-1][1].devices.flat
            )
            if shape is None:
                mesh = Mesh(np.asarray(devs), (ICI_AXIS,))
            else:
                axes = tuple(shape.keys())
                sizes = tuple(shape.values())
                if int(np.prod(sizes)) != len(devs):
                    raise ValueError(
                        f"shape {shape} does not cover {len(devs)} devices"
                    )
                mesh = Mesh(np.asarray(devs).reshape(sizes), axes)
            _state.mesh_cache[key] = mesh
        _state.mesh_stack.append((key, mesh))
        return mesh


def pop_communicator() -> None:
    _require_init()
    with _state.lock:
        if len(_state.mesh_stack) <= 1:
            raise RuntimeError("cannot pop the world communicator")
        _state.mesh_stack.pop()


class communicator:
    """Context manager: ``with runtime.communicator("half", shape={...}):``"""

    def __init__(self, key: str, **kw) -> None:
        self._key = key
        self._kw = kw

    def __enter__(self) -> Mesh:
        return push_communicator(self._key, **self._kw)

    def __exit__(self, *exc) -> None:
        pop_communicator()
        return None

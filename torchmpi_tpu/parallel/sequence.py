"""Sequence/context parallelism: ring attention and Ulysses all-to-all.

The reference predates transformers and has NO sequence parallelism
(SURVEY.md §3.3/§6.7 record the gap explicitly); its mesh abstraction was
required not to preclude one.  This module is the forward-looking extension
the TPU rebuild adds on top of the same communicator tree: long-context
attention where the sequence dimension is sharded over a mesh axis.

Two standard strategies, both built on this library's collectives:

- :func:`ulysses_attention` — all-to-all: swap the sequence shard for a
  head shard before attention and back after, so every device computes full
  attention for a subset of heads.  Two ``all_to_all`` ops per call; needs
  ``num_heads % axis_size == 0``.

- :func:`ring_attention` — blockwise: queries stay put while key/value
  blocks rotate around the ring via ``ppermute``, combined with a running
  (online-softmax / flash-style) accumulator, so the full sequence never
  materializes on any device.  Communication overlaps with the per-block
  matmuls under XLA's scheduler; memory is O(seq/n) per device.

Both are written for use inside ``shard_map`` over a mesh axis (typically a
dedicated ``seq`` axis or the ``ici`` axis), matching the in-axis collective
API style of the rest of the library.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax


def _combine(m_run, l_run, o_run, o_b, m_b, l_b):
    """One step of the cross-block online-softmax rescale: fold block
    partials (o_b numerator, m_b max, l_b denom) into the running state.
    Handles -inf (dense blocks) and finite NEG_INF with l_b == 0 (flash
    blocks) alike: a fully-masked block's weight times its zero l/o
    contributes nothing, and exp never sees a positive overflow because
    m_run <= m_new."""
    m_new = jnp.maximum(m_run, m_b)
    safe_new = jnp.where(jnp.isfinite(m_new), m_new, 0.0)
    c_run = jnp.where(jnp.isfinite(m_run), jnp.exp(m_run - safe_new), 0.0)
    c_b = jnp.where(jnp.isfinite(m_b), jnp.exp(m_b - safe_new), 0.0)
    l_run = l_run * c_run + l_b * c_b
    o_run = (o_run * c_run.transpose(0, 2, 1)[..., None]
             + o_b * c_b.transpose(0, 2, 1)[..., None])
    return m_new, l_run, o_run


def _attn_block(q, k, v, scale, mask):
    """One q-block x kv-block partial attention with explicit max/denom.

    q: [B, Tq, H, D]; k/v: [B, Tk, H, D]; mask: [Tq, Tk] bool or None.
    Returns (numerator [B, Tq, H, D], block max [B, H, Tq],
    block denom [B, H, Tq]).
    """
    s = jnp.einsum("bqhd,bkhd->bhqk", q, k) * scale
    if mask is not None:
        s = jnp.where(mask[None, None, :, :], s, -jnp.inf)
    m = jnp.max(s, axis=-1)  # [B, H, Tq]
    # exp(-inf - -inf) guard: fully-masked rows produce m=-inf; make the
    # exponent finite so p=0 rather than nan.
    safe_m = jnp.where(jnp.isfinite(m), m, 0.0)
    p = jnp.exp(s - safe_m[..., None])
    p = jnp.where(jnp.isfinite(s), p, 0.0)
    l = jnp.sum(p, axis=-1)  # [B, H, Tq]
    o = jnp.einsum("bhqk,bkhd->bqhd", p, v)
    return o, m, l



def causal_window_mask(q_pos, k_pos, window=None):
    """[Tq, Tk] bool: causal over global positions, optionally restricted
    to the sliding band ``q - k < window``.  The ONE definition of the
    band every dense path (ring, ulysses, reference oracle) shares."""
    keep = q_pos[:, None] >= k_pos[None, :]
    if window is not None:
        keep &= q_pos[:, None] - k_pos[None, :] < window
    return keep


def ring_attention(q, k, v, axis_name: str, *, causal: bool = False,
                   scale: Optional[float] = None, block_impl: str = "dense",
                   block_q: Optional[int] = None,
                   block_k: Optional[int] = None,
                   window: Optional[int] = None):
    """Blockwise ring attention over a sequence-sharded axis.

    Shapes (per device): q, k, v — ``[batch, seq_local, heads, head_dim]``,
    the global sequence being ``axis_size * seq_local`` in mesh-rank order.
    Returns the local block of the attention output, same shape as ``q``.

    Communication: ``axis_size - 1`` ppermute rotations of (k, v) — each
    device sends/receives ``2 * seq_local * heads * head_dim`` elements per
    step, the ring-bandwidth-optimal schedule.  Numerics: one online-softmax
    accumulation across blocks (flash-attention style), exact up to float
    associativity.

    ``block_impl`` selects the per-step local computation: ``"dense"``
    (XLA einsum — materializes the [T_local, T_local] score block) or
    ``"flash"`` (the Pallas kernel of ops/flash.py with residual outputs —
    VMEM-blocked, so per-device memory stays O(block) even for long local
    shards; the kv owner's global offset rides into the kernel as a traced
    SMEM scalar).
    """
    if window is not None:
        from ..ops.flash import _check_window

        _check_window(window, causal)
    if block_impl == "flash":
        if scale is None:
            scale = 1.0 / (q.shape[-1] ** 0.5)
        from .. import runtime

        block_q, block_k = runtime.resolve_blocks(
            block_q, block_k, "flash_block_q", "flash_block_k")
        axis_key = (axis_name if isinstance(axis_name, str)
                    else tuple(axis_name))
        return _ring_flash_vjp(axis_key, causal, float(scale), block_q,
                               block_k, window)(q, k, v)
    if block_impl != "dense":
        raise ValueError(f"unknown block_impl {block_impl!r}")
    n = lax.axis_size(axis_name)
    my = lax.axis_index(axis_name)
    B, Tq, H, D = q.shape
    if scale is None:
        scale = 1.0 / (D ** 0.5)

    q_pos = my * Tq + jnp.arange(Tq)  # global query positions

    m_run = jnp.full((B, H, Tq), -jnp.inf, q.dtype)
    l_run = jnp.zeros((B, H, Tq), q.dtype)
    o_run = jnp.zeros_like(q)

    perm = [(i, (i + 1) % n) for i in range(n)]

    def mask_for(kv_owner):
        if not causal:
            return None
        k_pos = kv_owner * k.shape[1] + jnp.arange(k.shape[1])
        return causal_window_mask(q_pos, k_pos, window)

    for step in range(n):  # n is static: unrolled
        kv_owner = lax.rem(my - step + n, n)
        o_b, m_b, l_b = _attn_block(q, k, v, scale, mask_for(kv_owner))
        m_run, l_run, o_run = _combine(m_run, l_run, o_run, o_b, m_b, l_b)
        if step != n - 1:
            k = lax.ppermute(k, axis_name, perm)
            v = lax.ppermute(v, axis_name, perm)

    denom = jnp.where(l_run > 0, l_run, 1.0).transpose(0, 2, 1)[..., None]
    return o_run / denom


def _ring_flash_forward(q, k, v, axis_name, causal, scale, block_q,
                        block_k, window=None):
    """Ring forward with Pallas flash blocks; returns (o, lse) with f32
    softmax statistics (lse feeds the backward's blockwise recompute)."""
    from ..ops.flash import flash_attention, lse_from_residuals

    n = lax.axis_size(axis_name)
    my = lax.axis_index(axis_name)
    B, Tq, H, D = q.shape
    Tk = k.shape[1]

    m_run = jnp.full((B, H, Tq), -jnp.inf, jnp.float32)
    l_run = jnp.zeros((B, H, Tq), jnp.float32)
    o_run = jnp.zeros(q.shape, jnp.float32)
    perm = [(i, (i + 1) % n) for i in range(n)]

    for step in range(n):  # n is static: unrolled
        kv_owner = lax.rem(my - step + n, n)
        o_b, m_b, l_b = flash_attention(
            q, k, v, causal=causal, scale=scale, q_offset=my * Tq,
            kv_offset=kv_owner * Tk, block_q=block_q, block_k=block_k,
            window=window, return_residuals=True)
        m_run, l_run, o_run = _combine(m_run, l_run, o_run, o_b, m_b, l_b)
        if step != n - 1:
            k = lax.ppermute(k, axis_name, perm)
            v = lax.ppermute(v, axis_name, perm)

    denom = jnp.where(l_run > 0, l_run, 1.0).transpose(0, 2, 1)[..., None]
    o = (o_run / denom).astype(q.dtype)
    return o, lse_from_residuals(jnp.where(jnp.isfinite(m_run), m_run, 0.0),
                                 l_run)


@functools.lru_cache(maxsize=None)
def _ring_flash_vjp(axis_name, causal: bool, scale: float, block_q: int,
                    block_k: int, window: Optional[int] = None):
    """Ring attention as one differentiable unit: Pallas kernels in both
    directions, with the backward running its own ring — (k, v) and the
    (dk, dv) accumulators rotate together for a full cycle (n ppermutes, so
    each shard's gradient visits every q owner and arrives back home).
    Autodiff cannot derive this (no VJP rule for Pallas kernels, and the
    communication schedule reverses), hence the custom VJP."""

    @jax.custom_vjp
    def f(q, k, v):
        return _ring_flash_forward(q, k, v, axis_name, causal, scale,
                                   block_q, block_k, window)[0]

    def fwd(q, k, v):
        o, lse = _ring_flash_forward(q, k, v, axis_name, causal, scale,
                                     block_q, block_k, window)
        return o, (q, k, v, o, lse)

    def bwd(res, do):
        from ..ops.flash import flash_attention_bwd

        q, k, v, o, lse = res
        n = lax.axis_size(axis_name)
        my = lax.axis_index(axis_name)
        Tq, Tk = q.shape[1], k.shape[1]
        dvec = jnp.einsum("bqhd,bqhd->bhq", do.astype(jnp.float32),
                          o.astype(jnp.float32))
        dq = jnp.zeros(q.shape, jnp.float32)
        dk_cur = jnp.zeros(k.shape, jnp.float32)
        dv_cur = jnp.zeros(v.shape, jnp.float32)
        k_cur, v_cur = k, v
        perm = [(i, (i + 1) % n) for i in range(n)]
        for step in range(n):
            kv_owner = lax.rem(my - step + n, n)
            dq_c, dk_c, dv_c = flash_attention_bwd(
                q, k_cur, v_cur, do, lse, dvec, causal=causal, scale=scale,
                q_offset=my * Tq, kv_offset=kv_owner * Tk, block_q=block_q,
                block_k=block_k, window=window)
            dq = dq + dq_c
            dk_cur = dk_cur + dk_c
            dv_cur = dv_cur + dv_c
            # The ACCUMULATORS rotate on every step (n total) so each
            # shard's gradient visits all q owners and lands back on its
            # owner; k/v themselves are dead after the last use.
            dk_cur = lax.ppermute(dk_cur, axis_name, perm)
            dv_cur = lax.ppermute(dv_cur, axis_name, perm)
            if step != n - 1:
                k_cur = lax.ppermute(k_cur, axis_name, perm)
                v_cur = lax.ppermute(v_cur, axis_name, perm)
        return (dq.astype(q.dtype), dk_cur.astype(k.dtype),
                dv_cur.astype(v.dtype))

    f.defvjp(fwd, bwd)
    return f


def ulysses_attention(q, k, v, axis_name: str, *, causal: bool = False,
                      scale: Optional[float] = None,
                      block_impl: str = "dense",
                      window: Optional[int] = None):
    """All-to-all (DeepSpeed-Ulysses style) sequence-parallel attention.

    Shapes (per device): ``[batch, seq_local, heads, head_dim]`` with
    ``heads % axis_size == 0``.  Two ``all_to_all`` ops swap the sequence
    shard for a head shard and back; in between every device runs ordinary
    full-sequence attention on its head subset (XLA's tuned path, MXU
    friendly).

    ``block_impl="flash"`` computes the local full-sequence attention with
    the Pallas flash kernel (ops/flash.py) instead of the dense path: the
    [T, T] score matrix never materializes, so the head-sharded middle
    section scales to sequence lengths the dense path cannot hold —
    differentiable end to end (the kernel's custom VJP).
    """
    n = lax.axis_size(axis_name)
    B, Tl, H, D = q.shape
    if window is not None:
        from ..ops.flash import _check_window

        _check_window(window, causal)
    if H % n != 0:
        raise ValueError(f"heads {H} not divisible by axis size {n}")
    if scale is None:
        scale = 1.0 / (D ** 0.5)

    def seq_to_heads(x):
        # [B, Tl, H, D] -> [B, T, H/n, D]
        return lax.all_to_all(x, axis_name, split_axis=2, concat_axis=1,
                              tiled=True)

    def heads_to_seq(x):
        return lax.all_to_all(x, axis_name, split_axis=1, concat_axis=2,
                              tiled=True)

    qg, kg, vg = seq_to_heads(q), seq_to_heads(k), seq_to_heads(v)
    if block_impl == "flash":
        from ..ops.flash import flash_attention_grad

        # Static zero offsets: with a window this takes the banded
        # O(T*window) kernel grids on each device's head subset.
        return heads_to_seq(
            flash_attention_grad(qg, kg, vg, causal=causal, scale=scale,
                                 window=window))
    if block_impl != "dense":
        raise ValueError(f"unknown block_impl {block_impl!r}")
    T = qg.shape[1]
    mask = None
    if causal:
        pos = jnp.arange(T)
        mask = causal_window_mask(pos, pos, window)
    o, m, l = _attn_block(qg, kg, vg, scale, mask)
    denom = jnp.where(l > 0, l, 1.0).transpose(0, 2, 1)[..., None]
    return heads_to_seq(o / denom)


def reference_attention(q, k, v, *, causal: bool = False,
                        scale: Optional[float] = None,
                        window: Optional[int] = None):
    """Single-device full attention (the oracle for the parallel variants).

    ``window`` (causal only) restricts each query to itself plus the
    ``window - 1`` keys before it — the dense oracle for
    ``ops.flash``'s sliding-window mode.  ``k``/``v`` may carry fewer
    heads than ``q`` (GQA): each kv head serves ``H // H_kv``
    consecutive q heads, matching the kernel's layout."""
    B, T, H, D = q.shape
    if scale is None:
        scale = 1.0 / (D ** 0.5)
    if window is not None:
        from ..ops.flash import _check_window

        _check_window(window, causal)  # same errors as the kernel path
    if k.shape[2] != H:
        from ..ops.flash import _gqa_group

        g = _gqa_group(H, k.shape[2])
        k = jnp.repeat(k, g, axis=2)
        v = jnp.repeat(v, g, axis=2)
    s = jnp.einsum("bqhd,bkhd->bhqk", q, k) * scale
    if causal:
        pos = jnp.arange(T)
        s = jnp.where(causal_window_mask(pos, pos, window)[None, None], s,
                      -jnp.inf)
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("bhqk,bkhd->bqhd", p, v)

"""Pipeline (stage) parallelism building block.

Not in the reference (SURVEY.md §3.3: PP explicitly out of its scope; the
mesh design just must not preclude a stage axis).  This module provides the
minimal, correct GPipe-style schedule on a mesh axis, mostly as proof that
the communicator tree composes with a pipeline axis — not a production
pipeline trainer.

SPMD formulation: every device runs the same ``M + S - 1`` tick loop.  At
each tick a device receives its predecessor's activation (linear ppermute,
no wraparound), stage 0 instead injects the next microbatch, every device
applies its local stage, and the last stage's outputs are collected.  The
loop is unrolled under jit, so XLA overlaps the ppermute with the next
tick's compute where profitable, and autodiff differentiates the schedule
for free (ppermute's transpose is the reverse ppermute — activations flow
backward through the pipe in reverse stage order, which IS pipeline
backward).

Bubble fraction is the usual GPipe ``(S-1)/(M+S-1)``; pick ``M >> S``.
"""

from __future__ import annotations

from typing import Callable

import jax.numpy as jnp
from jax import lax

from .. import collectives


def gpipe_apply(stage_fn: Callable, stage_params, microbatches,
                axis_name: str, *, broadcast_out: bool = True):
    """Run a linear pipeline over ``axis_name``.

    - ``stage_fn(stage_params, x) -> y``: one stage, same activation shape
      in and out (use projection stages inside ``stage_fn`` if widths vary;
      uniform shape keeps the rotating buffer static for XLA).
    - ``stage_params``: this device's stage (shard a [S, ...] tree over the
      axis outside).
    - ``microbatches``: ``[M, mb, ...]`` — the full input, replicated (only
      stage 0 reads it; replication keeps injection shard-free).

    Returns ``[M, mb, ...]`` outputs — valid on the last stage, broadcast to
    every device when ``broadcast_out`` (one collective), else zeros off the
    last stage.
    """
    S = lax.axis_size(axis_name)
    my = lax.axis_index(axis_name)
    M = microbatches.shape[0]
    act_shape = microbatches.shape[1:]

    perm = [(i, i + 1) for i in range(S - 1)]  # linear, no wraparound
    recv = jnp.zeros(act_shape, microbatches.dtype)
    zero_in = jnp.zeros(act_shape, microbatches.dtype)
    outs = []
    for t in range(M + S - 1):  # static unroll
        inject = microbatches[t] if t < M else zero_in
        x = jnp.where(my == 0, inject, recv)
        h = stage_fn(stage_params, x)
        if t >= S - 1:
            # h on the last stage is microbatch (t - S + 1)'s final output.
            outs.append(jnp.where(my == S - 1, h, jnp.zeros_like(h)))
        if t != M + S - 2:
            recv = lax.ppermute(h, axis_name, perm)
    result = jnp.stack(outs)  # [M, mb, ...]
    if broadcast_out:
        result = collectives.broadcast_in_axis(result, axis_name,
                                               root=S - 1)
    return result

"""Pipeline (stage) parallelism building blocks.

Not in the reference (SURVEY.md §3.3: PP explicitly out of its scope; the
mesh design just must not preclude a stage axis).  This module provides two
correct schedules on a mesh axis — plain GPipe and the interleaved
(virtual-stage) variant that divides the bubble by the number of virtual
chunks — mostly as proof that the communicator tree composes with a
pipeline axis, not a production pipeline trainer.

SPMD formulation (:func:`gpipe_apply`): every device runs the same
``M + S - 1`` tick loop.  At each tick a device receives its predecessor's
activation (linear ppermute, no wraparound), stage 0 instead injects the
next microbatch, every device applies its local stage, and the last
stage's outputs are collected.  The loop is unrolled under jit, so XLA
overlaps the ppermute with the next tick's compute where profitable, and
autodiff differentiates the schedule for free (ppermute's transpose is the
reverse ppermute — activations flow backward through the pipe in reverse
stage order, which IS pipeline backward).  Bubble fraction is the usual
GPipe ``(S-1)/(M+S-1)``; pick ``M >> S``.

:func:`interleaved_apply` runs the virtual-stage variant of the same idea
(``V*M + S - 1`` ticks, WRAPAROUND ring ppermute carrying chunk handoffs),
dividing the bubble by the number of chunks per device — see its docstring
for the schedule decode.
"""

from __future__ import annotations

from typing import Callable

import jax
import jax.numpy as jnp
from jax import lax

from .. import collectives


def gpipe_apply(stage_fn: Callable, stage_params, microbatches,
                axis_name: str, *, broadcast_out: bool = True,
                remat: bool = False, unroll: int = 1):
    """Run a linear pipeline over ``axis_name``.

    - ``stage_fn(stage_params, x) -> y``: one stage, same activation shape
      in and out (use projection stages inside ``stage_fn`` if widths vary;
      uniform shape keeps the rotating buffer static for XLA).
    - ``stage_params``: this device's stage (shard a [S, ...] tree over the
      axis outside).
    - ``microbatches``: ``[M, mb, ...]`` — the full input, replicated (only
      stage 0 reads it; replication keeps injection shard-free).

    Returns ``[M, mb, ...]`` outputs — valid on the last stage, broadcast to
    every device when ``broadcast_out`` (one collective), else zeros off the
    last stage.

    ``remat=True`` rematerializes each stage application in backward
    (``jax.checkpoint``): training stores one activation per tick edge
    instead of every stage-internal intermediate — the standard lever when
    the ``M`` in-flight microbatches bound pipeline memory.  Numerics are
    unchanged (the backward recomputes exactly the forward).

    The ``M + S - 1`` tick loop is a ``lax.scan`` (VERDICT r3 weak #6):
    the stage body and its ppermute appear ONCE in the HLO however large
    ``M`` grows — production microbatch counts would otherwise inline
    hundreds of stage copies and blow up compile time.  Autodiff still
    differentiates the schedule for free (scan's transpose runs the
    ticks in reverse; ppermute's transpose is the reverse ppermute —
    which IS pipeline backward).  ``unroll`` forwards to ``lax.scan``
    for XLA-level tick unrolling if profitable.
    """
    S = lax.axis_size(axis_name)
    my = lax.axis_index(axis_name)
    M = microbatches.shape[0]
    act_shape = microbatches.shape[1:]
    if remat:
        stage_fn = jax.checkpoint(stage_fn)

    perm = [(i, i + 1) for i in range(S - 1)]  # linear, no wraparound
    zero_act = jnp.zeros(act_shape, microbatches.dtype)

    def compute(recv, t):
        inject = jnp.where(
            t < M,
            lax.dynamic_index_in_dim(microbatches,
                                     jnp.minimum(t, M - 1), 0,
                                     keepdims=False),
            zero_act)
        x = jnp.where(my == 0, inject, recv)
        h = stage_fn(stage_params, x)
        # h on the last stage at tick t >= S-1 is microbatch
        # (t - S + 1)'s final output.
        out_t = jnp.where((my == S - 1) & (t >= S - 1), h,
                          jnp.zeros_like(h))
        return h, out_t

    def tick(recv, t):
        h, out_t = compute(recv, t)
        return lax.ppermute(h, axis_name, perm), out_t

    # Final tick peeled out of the scan: its ppermute feeds nothing, and
    # inside the scan body it could not be elided (each iteration's
    # ppermute feeds the carry) — one dead collective per forward and
    # its transpose per backward (code review r4).
    T = M + S - 1
    recv_last, ticks_out = lax.scan(tick, zero_act, jnp.arange(T - 1),
                                    unroll=unroll)
    _, out_last = compute(recv_last, jnp.asarray(T - 1))
    result = jnp.concatenate([ticks_out[S - 1:], out_last[None]])
    if broadcast_out:
        result = collectives.broadcast_in_axis(result, axis_name,
                                               root=S - 1)
    return result


def interleave_stages(stage_tree, n_devices: int):
    """Reorder an ``[L, ...]`` per-stage pytree into the ``[S, V, ...]``
    round-robin layout :func:`interleaved_apply` expects: element
    ``[d, v]`` is logical stage ``v*S + d``, so device ``d`` owns stages
    ``{d, S+d, 2S+d, ...}``.  Shard dim 0 over the pipeline axis (a plain
    contiguous split of ``[L, ...]`` would hand each device CONSECUTIVE
    stages, which defeats interleaving)."""
    def re(leaf):
        L = leaf.shape[0]
        if L % n_devices:
            raise ValueError(
                f"stage count {L} not divisible by pipeline size "
                f"{n_devices}")
        V = L // n_devices
        return leaf.reshape(V, n_devices, *leaf.shape[1:]).swapaxes(0, 1)
    return jax.tree.map(re, stage_tree)


def interleaved_apply(stage_fn: Callable, stage_params, microbatches,
                      axis_name: str, *, broadcast_out: bool = True,
                      remat: bool = False):
    """Interleaved (virtual-stage) pipeline over ``axis_name`` — the
    Megatron-style schedule: each device holds ``V`` non-adjacent stage
    chunks (logical stage ``v*S + d`` on device ``d``), so the pipeline
    flush costs ``S-1`` VIRTUAL-stage times instead of ``S-1``
    composite-stage times.  Bubble fraction ``(S-1)/(V*M + S-1)`` vs
    GPipe's ``(S-1)/(M + S-1)`` at equal total work per tick.

    SPMD formulation: microbatches run in groups of ``S``; at tick ``t``
    device ``d`` decodes its unique work item from ``u = t - d`` as
    ``(group, chunk, slot) = (u // VS, (u % VS) // S, u % S)`` and applies
    exactly one virtual stage; activations ride a WRAPAROUND ring ppermute
    (the ``S-1 -> 0`` hop is the chunk ``v -> v+1`` handoff).  The loop is
    ``V*M + S - 1`` ticks as ONE ``lax.scan`` (the virtual-stage body
    appears once in the HLO however large ``V*M`` grows — VERDICT r3
    weak #6; injection/collection tick decodes become traced index
    arithmetic and a scatter into the carried output buffer), and
    autodiff runs the schedule backward for free, exactly as in
    :func:`gpipe_apply`.

    - ``stage_params``: this device's ``[V, ...]`` chunk tree in the
      round-robin layout (build with :func:`interleave_stages`, shard dim 0
      over the axis, index ``[0]`` away the shard dim inside shard_map —
      then dim 0 is ``V``).
    - ``microbatches``: ``[M, mb, ...]`` replicated; ``M`` must be a
      multiple of ``S`` (the group structure of the schedule).
    - ``V == 1`` reduces tick-for-tick to :func:`gpipe_apply`.
    - ``remat=True`` rematerializes each virtual-stage application in
      backward, exactly as in :func:`gpipe_apply`.
    """
    S = lax.axis_size(axis_name)
    my = lax.axis_index(axis_name)
    if remat:
        stage_fn = jax.checkpoint(stage_fn)
    leaves = jax.tree.leaves(stage_params)
    if not leaves:
        raise ValueError("stage_params is empty")
    V = leaves[0].shape[0]
    M = microbatches.shape[0]
    if M % S:
        raise ValueError(
            f"interleaved schedule needs M % S == 0, got M={M}, S={S}")
    act_shape = microbatches.shape[1:]
    VS = V * S
    T = V * M + S - 1

    perm = [(i, (i + 1) % S) for i in range(S)]  # ring WITH wraparound
    zero_act = jnp.zeros(act_shape, microbatches.dtype)
    outs0 = jnp.zeros((M,) + act_shape, microbatches.dtype)

    def compute(recv, outs, t):
        # This device's virtual chunk for the tick (traced via my).  For
        # the not-yet-filled head (u < 0) the floor-mod already lands in
        # [0, VS) — those ticks compute garbage that is overwritten before
        # first valid use and never collected.
        u = t - my
        v = lax.rem(lax.rem(u, VS) + VS, VS) // S
        params_v = jax.tree.map(
            lambda l: lax.dynamic_index_in_dim(l, v, 0, keepdims=False),
            stage_params)
        # Injection happens at device 0's chunk-0 ticks.
        g, r = t // VS, lax.rem(t, VS)
        m_in = g * S + r
        valid_in = (r < S) & (m_in < M)
        inject = lax.dynamic_index_in_dim(
            microbatches, jnp.clip(m_in, 0, M - 1), 0, keepdims=False)
        x = jnp.where((my == 0) & valid_in, inject, recv)
        h = stage_fn(params_v, x)
        # Collection happens at the last device's chunk-(V-1) ticks:
        # a masked scatter into the carried [M, ...] output buffer.
        u_last = t - (S - 1)
        gl, rl = u_last // VS, lax.rem(u_last, VS)
        m_out = gl * S + (rl - (V - 1) * S)
        valid_out = (u_last >= 0) & (rl >= (V - 1) * S) & (m_out < M)
        m_out_c = jnp.clip(m_out, 0, M - 1)
        cur = lax.dynamic_index_in_dim(outs, m_out_c, 0, keepdims=False)
        new = jnp.where(valid_out,
                        jnp.where(my == S - 1, h, jnp.zeros_like(h)),
                        cur)
        return h, lax.dynamic_update_index_in_dim(outs, new, m_out_c, 0)

    def tick(carry, t):
        recv, outs = carry
        h, outs = compute(recv, outs, t)
        return (lax.ppermute(h, axis_name, perm), outs), None

    # Final tick peeled: its ppermute is dead (see gpipe_apply).
    (recv_last, outs_last), _ = lax.scan(tick, (zero_act, outs0),
                                         jnp.arange(T - 1))
    _, result = compute(recv_last, outs_last, jnp.asarray(T - 1))
    if broadcast_out:
        result = collectives.broadcast_in_axis(result, axis_name,
                                               root=S - 1)
    return result

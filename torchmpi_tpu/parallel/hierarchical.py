"""Hierarchical (two-level) collectives: ICI intra-slice + DCN inter-slice.

Rebuild of the reference's custom hierarchical algorithms (SURVEY.md §3 C4,
§4.2, reconstructed — reference mount empty): intra-node reduce (shm/CUDA-IPC)
-> inter-node allreduce (MPI) -> intra-node broadcast, chunk-pipelined.  The
TPU mapping (SURVEY.md §6.8): intra-node -> the ``ici`` mesh axis, inter-node
-> the ``dcn`` mesh axis.

The bandwidth-optimal staging on TPU is:

    reduce_scatter over ICI  ->  allreduce over DCN (on 1/ici_n of the data)
    ->  all_gather over ICI

which sends only ``1/ici_n`` of the tensor over the slow DCN links per chip —
the same reason the reference reduced intra-node first.  The allreduce is
additionally **chunk-pipelined** (``config.dcn_chunk_bytes``): when the
ICI-scattered shard exceeds the chunk bound, the tensor splits into chunks
whose DCN legs are ordered through an optimization-barrier chain while the
ICI legs stay independent — the DCN transfer of chunk *i* overlaps the ICI
reduce/gather work of chunk *i+1*, the reference's hand-rolled chunk
pipelining made explicit instead of left to XLA's scheduler.  Results are
bit-identical chunked or not (the reduction is elementwise).

The DCN leg can also run on a **quantized wire** (``config.dcn_compress``:
bf16/int8/fp8 — ``torchmpi_tpu/compress.py``, docs/HIERARCHICAL.md): only
the small post-reduce_scatter shard crossing the slow links is narrowed,
never the ICI legs.  Off (the default) never imports the codec module and
dispatches bit-identically to the uncompressed schedule.

These functions register with the selector as backend ``"hierarchical"`` and
expect exactly two mesh axes ``(outer/dcn, inner/ici)``.
"""

from __future__ import annotations

from typing import Optional, Tuple

import jax.numpy as jnp
from jax import lax

from .. import selector

_REDUCERS = {"sum": lax.psum, "mean": lax.pmean, "max": lax.pmax,
             "min": lax.pmin}

# Chunk-count ceiling for the pipelined schedule: the chunks are
# trace-time unrolled (each is an independent psum_scatter/psum/
# all_gather triple), so the pipeline depth is capped — past a handful
# of in-flight chunks the overlap is already saturated and more chunks
# only grow the HLO.
_MAX_CHUNKS = 16


def _serialize_collectives() -> bool:
    """XLA:CPU's thunk executor runs a device's independent thunks
    concurrently, and every CPU collective blocks its thread at a
    rendezvous — so two collectives left unordered in the program can
    be entered in opposite orders by different devices and deadlock
    the simulated mesh.  On CPU the chunk pipeline is therefore fully
    serialized (its overlap win is hardware-only anyway); TPU keeps
    only the DCN-leg chain and lets ICI work overlap."""
    import jax

    return jax.default_backend() == "cpu"


def _check_axes(axis_names) -> Tuple[str, str]:
    axes = (axis_names,) if isinstance(axis_names, str) else tuple(axis_names)
    if len(axes) != 2:
        raise ValueError(
            f"hierarchical collectives need (outer, inner) axes, got {axes}"
        )
    return axes[0], axes[1]


def _global_rank(outer: str, inner: str):
    return lax.axis_index(outer) * lax.axis_size(inner) + lax.axis_index(inner)


def _dcn_codec(x, op: str, axes: Tuple[str, str]) -> Optional[str]:
    """Resolve the DCN wire codec for this allreduce at trace time
    (docs/HIERARCHICAL.md): ``config.dcn_compress`` when the payload is
    floating point, the op reduces through the staged sum path, and the
    DCN leg — the post-reduce_scatter shard, ``1/ici_n`` of the tensor
    — clears ``dcn_compress_min_bytes``.  "off" is one string
    compare and NEVER imports the codec module — the analysis/obs/
    faults discipline.  Incompatible or sub-floor requests emit the C2
    trace record so the static analyzer can report what silently ran
    uncompressed."""
    from .. import fusion, runtime

    cfg = runtime.effective_config()
    if cfg.dcn_compress == "off":
        return None
    nbytes = selector.nbytes_of(x)
    if op not in ("sum", "mean") or not jnp.issubdtype(
            getattr(x, "dtype", jnp.float32), jnp.inexact):
        # Quantizing a max/min (or integer) reduction would change its
        # semantics, not just its precision — run uncompressed and
        # leave the C2 evidence for the analyzer.  Record the bytes of
        # the leg that actually crosses DCN so the field is comparable
        # across records: max/min sends the FULL tensor over dcn
        # (no reduce_scatter staging); an integer sum still stages, so
        # its DCN leg is the 1/ici_n shard.
        if fusion._trace_listener is not None:
            from .. import compress

            leg_nbytes = int(nbytes)
            if op in ("sum", "mean"):
                leg_nbytes = -(-leg_nbytes
                               // max(1, int(lax.axis_size(axes[1]))))
            compress.note_skipped(
                op, cfg.dcn_compress, leg_nbytes, axes,
                min_bytes=cfg.dcn_compress_min_bytes, incompatible=True)
        return None
    # The floor applies to what would actually be quantized: the DCN
    # shard (1/ici_n of the tensor), not the whole payload.
    shard_nbytes = -(-int(nbytes) // max(1, int(lax.axis_size(axes[1]))))
    if shard_nbytes < cfg.dcn_compress_min_bytes:
        if fusion._trace_listener is not None:
            from .. import compress

            compress.note_skipped(
                op, cfg.dcn_compress, shard_nbytes, axes,
                min_bytes=cfg.dcn_compress_min_bytes)
        return None
    from .. import compress

    return compress.resolve_dcn(cfg)


def hier_allreduce(x, axis_names, *, op: str = "sum"):
    """reduce_scatter(ici) -> allreduce(dcn) -> all_gather(ici),
    chunk-pipelined (``config.dcn_chunk_bytes``) with an optionally
    quantized DCN leg (``config.dcn_compress``)."""
    outer, inner = _check_axes(axis_names)
    if op in ("max", "min"):
        _dcn_codec(x, op, (outer, inner))  # C2 evidence only
        f = _REDUCERS[op]
        return f(f(x, inner), outer)
    if op not in ("sum", "mean"):
        raise KeyError(f"hierarchical allreduce does not support op {op!r}")
    from .. import runtime

    codec = _dcn_codec(x, op, (outer, inner))
    n_inner = lax.axis_size(inner)
    shape = x.shape
    flat = x.reshape(-1)
    # Chunk count: split so each chunk's ICI-scattered shard is at most
    # ~dcn_chunk_bytes, bounded by _MAX_CHUNKS (trace-time unroll).
    chunk_bytes = runtime.effective_config().dcn_chunk_bytes
    shard_bytes = (flat.shape[0] * flat.dtype.itemsize) // max(1, n_inner)
    k = 1
    if chunk_bytes > 0 and shard_bytes > chunk_bytes:
        k = min(_MAX_CHUNKS, -(-shard_bytes // chunk_bytes))
    if codec is not None and k > 1:
        # The floor is paid PER LEG (each chunk's DCN crossing carries
        # its own scale bookkeeping), so chunking may not split a
        # passing shard into sub-floor legs — clamp the chunk count so
        # every leg still clears dcn_compress_min_bytes.
        min_bytes = runtime.effective_config().dcn_compress_min_bytes
        if min_bytes > 0:
            k = max(1, min(k, shard_bytes // min_bytes))
    pad = (-flat.shape[0]) % (n_inner * k)
    if pad:
        flat = jnp.concatenate([flat, jnp.zeros((pad,), flat.dtype)])
    chunks = flat.reshape(k, -1)
    outs = []
    prev = None
    serialize = k > 1 and _serialize_collectives()
    for i in range(k):
        # Stage 1 (ICI): each neighbor ends with its 1/n_inner shard of
        # this chunk's ICI sum.  Independent across chunks — chunk
        # i+1's scatter can run while chunk i's DCN leg is in flight
        # (on CPU sim the chunks are chained instead; see
        # _serialize_collectives).
        cin = chunks[i]
        if serialize and outs:
            cin, _ = lax.optimization_barrier((cin, outs[-1]))
        shard = lax.psum_scatter(cin, inner, scatter_dimension=0,
                                 tiled=True)
        if prev is not None:
            # Pipeline order: chunk i's DCN transfer issues after chunk
            # i-1's (the barrier also keeps the per-chunk collectives
            # distinct through XLA's combiner, which would otherwise
            # re-merge them into the unchunked schedule).
            shard, _ = lax.optimization_barrier((shard, prev))
        # Stage 2 (DCN): allreduce the small shard across slices.
        if codec is not None:
            from .. import compress

            compress.note_leg(
                "allreduce", codec,
                shard.size * shard.dtype.itemsize,
                compress.wire_nbytes_of(shard.size, codec), (outer, inner))
            shard, _ = compress.dcn_allreduce(shard, outer, codec)
        else:
            if runtime.effective_config().obs != "off":
                from .. import obs

                obs.record_dcn("allreduce", "none",
                               shard.size * shard.dtype.itemsize,
                               shard.size * shard.dtype.itemsize)
            shard = lax.psum(shard, outer)
        prev = shard
        # Stage 3 (ICI): regather this chunk.
        outs.append(lax.all_gather(shard, inner, axis=0, tiled=True))
    full = outs[0] if k == 1 else jnp.concatenate(outs)
    if pad:
        full = full[: full.shape[0] - pad]
    out = full.reshape(shape)
    if op == "mean":
        out = out / (lax.axis_size(outer) * n_inner)
    return out


def hier_broadcast(x, axis_names, *, root: int = 0):
    """Broadcast over the (dcn, ici) tree: delegates to the stock broadcast
    over the combined axes, which streams large tensors down a pipelined
    ppermute chain (~1x wire) and keeps small ones on single-collective
    masked-psum (~2x wire but one launch)."""
    from .. import collectives

    outer, inner = _check_axes(axis_names)
    return collectives._xla_broadcast(x, (outer, inner), root=root)


def hier_reduce(x, axis_names, *, root: int = 0, op: str = "sum"):
    outer, inner = _check_axes(axis_names)
    f = _REDUCERS[op]
    total = f(f(x, inner), outer)
    r = _global_rank(outer, inner)
    return jnp.where(r == root, total, x)


def hier_allgather(x, axis_names):
    """all_gather(ici) then all_gather(dcn); global rank order is
    dcn-major * ici, matching the world mesh layout."""
    outer, inner = _check_axes(axis_names)
    inner_g = lax.all_gather(x, inner, axis=0, tiled=False)
    both = lax.all_gather(inner_g, outer, axis=0, tiled=False)
    return both.reshape((-1,) + x.shape)


def hier_gather(x, axis_names, *, root: int = 0):
    """Gather staged over the tree with O(size) wire on each level: a
    convergecast chain over ici brings each slice's tensors to its local
    leader (the device sharing root's ici coordinate), then a chain over
    dcn brings the per-slice stacks to root's slice — each tensor crosses
    DCN at most once, versus the old allgather-both-axes+mask form that
    moved n_global x the payload over BOTH levels.  Small tensors keep
    the two-allgather form: two launches beat 2(n-1) latency-bound hops.
    Output matches the stock gather: [group, ...] at root, zeros
    elsewhere (the stage-2 chain only carries nonzero data on root's
    ici-coordinate lane, so masking is implicit)."""
    from .. import collectives, runtime

    outer, inner = _check_axes(axis_names)
    n_i = lax.axis_size(inner)
    n_o = lax.axis_size(outer)
    ro, ri = root // n_i, root % n_i
    if selector.nbytes_of(x) >= runtime.effective_config().chunk_bytes:
        g_local = collectives._chain_gather(x, (inner,), root=ri, n=n_i)
        g_both = collectives._chain_gather(g_local, (outer,), root=ro,
                                           n=n_o)
        return g_both.reshape((-1,) + x.shape)
    g = hier_allgather(x, axis_names)
    r = _global_rank(outer, inner)
    return jnp.where(r == root, g, jnp.zeros_like(g))


def hier_scatter(x, axis_names, *, root: int = 0):
    """Scatter staged over the tree with O(size) wire per level: a dcn
    chain delivers each slice its contiguous block of chunks (one DCN
    crossing per block — the flat combined-axis chain would drag far
    slices' chunks across every intermediate slice boundary), then an
    ici chain scatters within each slice.  Small tensors keep the stock
    broadcast+slice via the same ``chunk_bytes`` cutover as the flat
    path."""
    from .. import collectives, runtime

    outer, inner = _check_axes(axis_names)
    n_i = lax.axis_size(inner)
    n_o = lax.axis_size(outer)
    n = n_i * n_o
    if x.shape[0] % n != 0:
        raise ValueError(
            f"scatter needs leading dim divisible by group size: "
            f"{x.shape[0]} % {n}")
    if selector.nbytes_of(x) < runtime.effective_config().chunk_bytes:
        return collectives._xla_scatter(x, (outer, inner), root=root)
    ro, ri = root // n_i, root % n_i
    # Stage 1 over dcn: view the rank-major chunks as n_o slice blocks
    # and chain-scatter them from root's slice.  Lanes with ici coord
    # != ri run the same collective on their own (non-root) x, but that
    # data never propagates: stage 2's chain only injects from the ri
    # lane, whose stage-1 result came from (ro, ri) — the true root.
    block = collectives._chain_scatter(x, (outer,), root=ro, n=n_o)
    # Stage 2 over ici: chain-scatter each slice's block from the ri lane.
    return collectives._chain_scatter(block, (inner,), root=ri, n=n_i)


selector.register("allreduce", "hierarchical", hier_allreduce)
selector.register("broadcast", "hierarchical", hier_broadcast)
selector.register("reduce", "hierarchical", hier_reduce)
selector.register("allgather", "hierarchical", hier_allgather)
selector.register("gather", "hierarchical", hier_gather)
selector.register("scatter", "hierarchical", hier_scatter)

"""Hierarchical (two-level) collectives: ICI intra-slice + DCN inter-slice.

Rebuild of the reference's custom hierarchical algorithms (SURVEY.md §3 C4,
§4.2, reconstructed — reference mount empty): intra-node reduce (shm/CUDA-IPC)
-> inter-node allreduce (MPI) -> intra-node broadcast, chunk-pipelined.  The
TPU mapping (SURVEY.md §6.8): intra-node -> the ``ici`` mesh axis, inter-node
-> the ``dcn`` mesh axis.

The bandwidth-optimal staging on TPU is:

    reduce_scatter over ICI  ->  allreduce over DCN (on 1/ici_n of the data)
    ->  all_gather over ICI

which sends only ``1/ici_n`` of the tensor over the slow DCN links per chip —
the same reason the reference reduced intra-node first.  XLA overlaps the
per-shard DCN transfer with ICI work where it can, playing the role of the
reference's hand-rolled chunk pipelining.

These functions register with the selector as backend ``"hierarchical"`` and
expect exactly two mesh axes ``(outer/dcn, inner/ici)``.
"""

from __future__ import annotations

from typing import Tuple

import jax.numpy as jnp
from jax import lax

from .. import selector

_REDUCERS = {"sum": lax.psum, "mean": lax.pmean, "max": lax.pmax,
             "min": lax.pmin}


def _check_axes(axis_names) -> Tuple[str, str]:
    axes = (axis_names,) if isinstance(axis_names, str) else tuple(axis_names)
    if len(axes) != 2:
        raise ValueError(
            f"hierarchical collectives need (outer, inner) axes, got {axes}"
        )
    return axes[0], axes[1]


def _global_rank(outer: str, inner: str):
    return lax.axis_index(outer) * lax.axis_size(inner) + lax.axis_index(inner)


def hier_allreduce(x, axis_names, *, op: str = "sum"):
    """reduce_scatter(ici) -> allreduce(dcn) -> all_gather(ici)."""
    outer, inner = _check_axes(axis_names)
    if op in ("max", "min"):
        f = _REDUCERS[op]
        return f(f(x, inner), outer)
    if op not in ("sum", "mean"):
        raise KeyError(f"hierarchical allreduce does not support op {op!r}")
    n_inner = lax.axis_size(inner)
    shape = x.shape
    flat = x.reshape(-1)
    pad = (-flat.shape[0]) % n_inner
    if pad:
        flat = jnp.concatenate([flat, jnp.zeros((pad,), flat.dtype)])
    # Stage 1: each ICI neighbor ends with its 1/n_inner shard of the ICI sum.
    shard = lax.psum_scatter(flat, inner, scatter_dimension=0, tiled=True)
    # Stage 2: allreduce the small shard across slices over DCN.
    shard = lax.psum(shard, outer)
    # Stage 3: regather the full tensor over ICI.
    full = lax.all_gather(shard, inner, axis=0, tiled=True)
    if pad:
        full = full[: full.shape[0] - pad]
    out = full.reshape(shape)
    if op == "mean":
        out = out / (lax.axis_size(outer) * n_inner)
    return out


def hier_broadcast(x, axis_names, *, root: int = 0):
    """Broadcast over the (dcn, ici) tree: delegates to the stock broadcast
    over the combined axes, which streams large tensors down a pipelined
    ppermute chain (~1x wire) and keeps small ones on single-collective
    masked-psum (~2x wire but one launch)."""
    from .. import collectives

    outer, inner = _check_axes(axis_names)
    return collectives._xla_broadcast(x, (outer, inner), root=root)


def hier_reduce(x, axis_names, *, root: int = 0, op: str = "sum"):
    outer, inner = _check_axes(axis_names)
    f = _REDUCERS[op]
    total = f(f(x, inner), outer)
    r = _global_rank(outer, inner)
    return jnp.where(r == root, total, x)


def hier_allgather(x, axis_names):
    """all_gather(ici) then all_gather(dcn); global rank order is
    dcn-major * ici, matching the world mesh layout."""
    outer, inner = _check_axes(axis_names)
    inner_g = lax.all_gather(x, inner, axis=0, tiled=False)
    both = lax.all_gather(inner_g, outer, axis=0, tiled=False)
    return both.reshape((-1,) + x.shape)


def hier_gather(x, axis_names, *, root: int = 0):
    """Gather staged over the tree with O(size) wire on each level: a
    convergecast chain over ici brings each slice's tensors to its local
    leader (the device sharing root's ici coordinate), then a chain over
    dcn brings the per-slice stacks to root's slice — each tensor crosses
    DCN at most once, versus the old allgather-both-axes+mask form that
    moved n_global x the payload over BOTH levels.  Small tensors keep
    the two-allgather form: two launches beat 2(n-1) latency-bound hops.
    Output matches the stock gather: [group, ...] at root, zeros
    elsewhere (the stage-2 chain only carries nonzero data on root's
    ici-coordinate lane, so masking is implicit)."""
    from .. import collectives, runtime

    outer, inner = _check_axes(axis_names)
    n_i = lax.axis_size(inner)
    n_o = lax.axis_size(outer)
    ro, ri = root // n_i, root % n_i
    if selector.nbytes_of(x) >= runtime.effective_config().chunk_bytes:
        g_local = collectives._chain_gather(x, (inner,), root=ri, n=n_i)
        g_both = collectives._chain_gather(g_local, (outer,), root=ro,
                                           n=n_o)
        return g_both.reshape((-1,) + x.shape)
    g = hier_allgather(x, axis_names)
    r = _global_rank(outer, inner)
    return jnp.where(r == root, g, jnp.zeros_like(g))


def hier_scatter(x, axis_names, *, root: int = 0):
    """Scatter staged over the tree with O(size) wire per level: a dcn
    chain delivers each slice its contiguous block of chunks (one DCN
    crossing per block — the flat combined-axis chain would drag far
    slices' chunks across every intermediate slice boundary), then an
    ici chain scatters within each slice.  Small tensors keep the stock
    broadcast+slice via the same ``chunk_bytes`` cutover as the flat
    path."""
    from .. import collectives, runtime

    outer, inner = _check_axes(axis_names)
    n_i = lax.axis_size(inner)
    n_o = lax.axis_size(outer)
    n = n_i * n_o
    if x.shape[0] % n != 0:
        raise ValueError(
            f"scatter needs leading dim divisible by group size: "
            f"{x.shape[0]} % {n}")
    if selector.nbytes_of(x) < runtime.effective_config().chunk_bytes:
        return collectives._xla_scatter(x, (outer, inner), root=root)
    ro, ri = root // n_i, root % n_i
    # Stage 1 over dcn: view the rank-major chunks as n_o slice blocks
    # and chain-scatter them from root's slice.  Lanes with ici coord
    # != ri run the same collective on their own (non-root) x, but that
    # data never propagates: stage 2's chain only injects from the ri
    # lane, whose stage-1 result came from (ro, ri) — the true root.
    block = collectives._chain_scatter(x, (outer,), root=ro, n=n_o)
    # Stage 2 over ici: chain-scatter each slice's block from the ri lane.
    return collectives._chain_scatter(block, (inner,), root=ri, n=n_i)


selector.register("allreduce", "hierarchical", hier_allreduce)
selector.register("broadcast", "hierarchical", hier_broadcast)
selector.register("reduce", "hierarchical", hier_reduce)
selector.register("allgather", "hierarchical", hier_allgather)
selector.register("gather", "hierarchical", hier_gather)
selector.register("scatter", "hierarchical", hier_scatter)

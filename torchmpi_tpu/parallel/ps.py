"""Asynchronous parameter server: Python client/orchestration over the C++
host transport (csrc/ps.cpp).

Rebuild of the reference's C8 parameter-server shards + C11 Lua client
(``lib/parameterserver.cpp``, ``torchmpi/parameterserver.lua`` [MED],
SURVEY.md §3/§4.5 — reconstructed, reference mount empty):

- a flat parameter vector is sharded across server instances (the reference
  sharded across ranks; here each host runs servers as native threads and
  clients reach them over TCP/DCN);
- clients ``send(tree, rule)`` / ``receive()`` asynchronously and wait on
  opaque handles (the prefetch pattern in §4.5);
- server-side update rules: ``copy``/``add``/``zero``/``axpy`` plus the
  EASGD ``elastic`` rule (server returns the elastic delta so client and
  center move symmetrically).

This lives deliberately outside SPMD: async PS traffic cannot ride
gang-scheduled XLA collectives (SURVEY.md §8.2.5); device arrays are staged
host-side (numpy) exactly as the reference staged GPU tensors through pinned
buffers.

Dtype contract: the wire/shard format is float32; f32/bf16/f16 leaves round
trip bit-exactly, anything lossy raises (see utils/tree.py).
"""

from __future__ import annotations

import ctypes
import os
import threading
from typing import Any, List, Optional, Sequence, Tuple

import numpy as np

from .. import runtime
from ..utils import native
from ..utils import tree as tree_util

PyTree = Any

RULES = {"copy": 0, "add": 1, "zero": 2, "axpy": 3, "elastic": 4}


def _timeout_ms() -> int:
    """Socket timeout armed on every client connection: a wedged shard
    server surfaces as a failed future within this bound instead of
    hanging wait() (ADVICE round 1).  0 disables.  Config-driven
    (``Config.ps_timeout_s`` / ``TORCHMPI_TPU_PS_TIMEOUT``, normalized
    in ``runtime.init``); standalone use (no init) falls back to the
    env, including the legacy millisecond spelling."""
    if runtime.is_initialized():
        return int(runtime.config().ps_timeout_s * 1000)
    v = os.environ.get("TORCHMPI_TPU_PS_TIMEOUT")
    if v is not None:
        return int(float(v) * 1000)
    v = os.environ.get("TORCHMPI_TPU_PS_TIMEOUT_MS")
    if v is not None:
        return int(v)
    return 30000


def _faults_armed() -> bool:
    """One string compare per call — ``torchmpi_tpu.faults`` is never
    imported unless the config armed it (docs/FAULTS.md)."""
    return runtime.effective_config().faults != "off"


def _wire_guard() -> bool:
    """One string compare per call — the wire-integrity guard
    (docs/GUARD.md); ``faults.integrity`` is never imported unless the
    config armed it."""
    return runtime.effective_config().guard in ("wire", "full")

_LIB_LOCK = threading.Lock()
_LIB: Optional[ctypes.CDLL] = None

# Last-resort keep-alive for buffers whose native op never completed within
# the destructor's bounded wait (should be unreachable with socket timeouts
# armed): leaking beats a native write into freed numpy memory.
_ORPHANED_BUFFERS: List[Any] = []


def _bind(lib: ctypes.CDLL) -> None:
    lib.tm_ps_server_create.restype = ctypes.c_int64
    lib.tm_ps_server_create.argtypes = [ctypes.c_uint64, ctypes.c_int]
    lib.tm_ps_server_port.restype = ctypes.c_int
    lib.tm_ps_server_port.argtypes = [ctypes.c_int64]
    lib.tm_ps_server_ops.restype = ctypes.c_uint64
    lib.tm_ps_server_ops.argtypes = [ctypes.c_int64]
    lib.tm_ps_server_stats.restype = ctypes.c_int
    lib.tm_ps_server_stats.argtypes = [
        ctypes.c_int64, ctypes.POINTER(ctypes.c_uint64), ctypes.c_int]
    lib.tm_ps_server_destroy.restype = None
    lib.tm_ps_server_destroy.argtypes = [ctypes.c_int64]
    lib.tm_ps_client_connect.restype = ctypes.c_int64
    lib.tm_ps_client_connect.argtypes = [ctypes.c_char_p, ctypes.c_int,
                                             ctypes.c_int]
    lib.tm_ps_client_destroy.restype = None
    lib.tm_ps_client_destroy.argtypes = [ctypes.c_int64]
    lib.tm_ps_send.restype = ctypes.c_int64
    lib.tm_ps_send.argtypes = [
            ctypes.c_int64, ctypes.c_uint32, ctypes.c_float, ctypes.c_uint64,
            ctypes.POINTER(ctypes.c_float), ctypes.POINTER(ctypes.c_float),
            ctypes.c_uint64]
    lib.tm_ps_receive.restype = ctypes.c_int64
    lib.tm_ps_receive.argtypes = [
            ctypes.c_int64, ctypes.c_uint64, ctypes.POINTER(ctypes.c_float),
            ctypes.c_uint64]
    lib.tm_ps_wait.restype = ctypes.c_int
    lib.tm_ps_wait.argtypes = [ctypes.c_int64]
    lib.tm_ps_wait_for.restype = ctypes.c_int
    lib.tm_ps_wait_for.argtypes = [ctypes.c_int64, ctypes.c_int]
    lib.tm_ps_test.restype = ctypes.c_int
    lib.tm_ps_test.argtypes = [ctypes.c_int64]
    lib.tm_ps_forget.restype = None
    lib.tm_ps_forget.argtypes = [ctypes.c_int64]
    lib.tm_ps_ping.restype = ctypes.c_int64
    lib.tm_ps_ping.argtypes = [ctypes.c_int64]


def _load_lib() -> ctypes.CDLL:
    """Load (building if necessary) the host-transport shared library via
    the shared native loader (hash-keyed staleness; ADVICE round 1)."""
    global _LIB
    with _LIB_LOCK:
        if _LIB is None:
            _LIB = native.load_native("libtorchmpi_ps.so", "ps.cpp", _bind)
        return _LIB


def _fptr(a: np.ndarray):
    return a.ctypes.data_as(ctypes.POINTER(ctypes.c_float))


class PSHandle:
    """Opaque async handle (reference: parameterserver.syncHandle target).

    Holds references to the numpy buffers the native side writes into, so
    they stay alive until ``wait()``.
    """

    def __init__(self, lib, future_ids: List[int],
                 buffers: List[np.ndarray], result_fn=None):
        self._lib = lib
        self._pending = list(future_ids)  # not yet waited/freed
        self._n_futures = len(self._pending)
        self._buffers = buffers  # keep-alive
        self._result_fn = result_fn
        self._done = False
        self._failed = False
        self._result = None
        # Shard index (enqueue order) of the first failed/timed-out
        # future — how the fault layer's health ledger attributes a
        # failed exchange to the right peer.  None = no failure seen.
        self.failed_index: Optional[int] = None

    def wait(self, timeout_ms: int = 0):
        """Block until every shard future resolves.  ``timeout_ms > 0``
        bounds each PER-SHARD native wait; on expiry raises
        ``TimeoutError`` with the future left live (the handle can be
        waited again, or abandoned to the bounded destructor drain) —
        the hook the resilient-dispatch layer uses to retransmit
        instead of hanging."""
        if self._failed:
            raise RuntimeError("parameter-server op already failed")
        was_done = self._done
        wd = None
        wd_tok = -1
        if not self._done and \
                runtime.effective_config().watchdog != "off":
            # Live hang detection over the native shard waits
            # (docs/WATCHDOG.md): a wedged shard server past its socket
            # timeout still shows up as a stalled in-flight window — and
            # under an unbounded timeout_ms=0 wait, the watchdog is the
            # ONLY thing bounding it.  One string compare when off.
            from .. import watchdog

            wd = watchdog
            wd.raise_pending()
            wd_tok = wd.begin("ps.response", op="ps_wait",
                              nbytes=self._n_futures)
        try:
            self._wait_pending(timeout_ms)
        finally:
            if wd is not None:
                wd.end(wd_tok)
        if self._done and not was_done:
            from ..utils import telemetry

            # The completion edge for PS waits (flight ring via the
            # sys.modules-gated shim, ONCE per handle): lets blame see
            # "the PS exchange completed; the hang is elsewhere".
            telemetry.emit("record_ps_wait", self._n_futures)
        return self._result

    def _wait_pending(self, timeout_ms: int = 0):
        if not self._done:
            while self._pending:
                fid = self._pending[0]
                if timeout_ms and timeout_ms > 0:
                    status = self._lib.tm_ps_wait_for(fid, int(timeout_ms))
                    if status == -3:  # still in flight; future stays live
                        self.failed_index = (self._n_futures
                                             - len(self._pending))
                        raise TimeoutError(
                            f"parameter-server op still in flight after "
                            f"{timeout_ms}ms (shard {self.failed_index})")
                else:
                    status = self._lib.tm_ps_wait(fid)  # frees the future
                self._pending.pop(0)
                if status != 1:
                    self.failed_index = (self._n_futures
                                         - len(self._pending) - 1)
                    self._failed = True
                    self._drain_pending()
                    raise RuntimeError(f"parameter-server op failed "
                                       f"(status {status}, shard "
                                       f"{self.failed_index})")
            self._done = True
            self._result = (self._result_fn() if self._result_fn is not None
                            else None)
        return self._result

    def _drain_pending(self):
        """Retire remaining futures after a failure.  Futures whose native
        ops write into our numpy buffers (other shards of a receive may
        still be in flight — shard failures are per-connection) must be
        drained with a bounded wait; if one is STILL in flight after the
        budget, its buffers are parked in _ORPHANED_BUFFERS rather than
        freed under a writing native thread."""
        t_ms = _timeout_ms()
        budget_ms = 2 * t_ms if t_ms > 0 else 0
        for rest in self._pending:
            if self._result_fn is None:
                self._lib.tm_ps_forget(rest)
            elif budget_ms > 0:
                if self._lib.tm_ps_wait_for(rest, budget_ms) == -3:
                    _ORPHANED_BUFFERS.append(self._buffers)
                    self._lib.tm_ps_forget(rest)
            else:
                self._lib.tm_ps_wait(rest)
        self._pending = []

    @property
    def done(self) -> bool:
        if self._done or self._failed:
            return True
        return all(self._lib.tm_ps_test(fid) == 1 for fid in self._pending)

    def __del__(self):
        # Fire-and-forget handles (async pushes never waited on) must not
        # leak future registry entries in the native layer.  Handles whose
        # ops write back into Python-owned buffers (receive / elastic —
        # marked by result_fn) must instead be drained: forgetting them
        # would free numpy memory the native thread still writes.  The
        # drain is BOUNDED (2x the socket timeout) so GC/interpreter
        # shutdown can never hang on a wedged server; a timed-out op's
        # buffers are parked in _ORPHANED_BUFFERS instead of freed.
        try:
            if getattr(self, "_pending", None):
                self._drain_pending()
        except Exception:
            pass


class ShardedParameterServer:
    """Server-side: owns `num_shards` shard servers as native threads.

    The reference co-located one shard per rank; on TPU hosts run
    ``init_servers`` once per host (one process), and every worker connects
    with :class:`PSClient`.
    """

    def __init__(self, total_floats: int, num_shards: int = 1,
                 base_port: int = 0):
        self._lib = _load_lib()
        self.total = int(total_floats)
        self.num_shards = num_shards
        bounds = np.linspace(0, self.total, num_shards + 1).astype(np.int64)
        self.shard_bounds: List[Tuple[int, int]] = [
            (int(bounds[i]), int(bounds[i + 1])) for i in range(num_shards)]
        self.server_ids: List[int] = []
        self.ports: List[int] = []
        for i, (lo, hi) in enumerate(self.shard_bounds):
            port = 0 if base_port == 0 else base_port + i
            sid = self._lib.tm_ps_server_create(hi - lo, port)
            if sid < 0:
                raise RuntimeError("failed to start PS shard server")
            self.server_ids.append(sid)
            self.ports.append(self._lib.tm_ps_server_port(sid))
        # Previous stats() snapshot as recorded into the telemetry
        # registry (torchmpi_tpu.obs) — deltas, not cumulative re-adds.
        self._last_stats = None

    def ops_served(self) -> int:
        return sum(self._lib.tm_ps_server_ops(s) for s in self.server_ids)

    def _read_counters(self) -> np.ndarray:
        """One pass over every shard's 8 native counters (each shard's
        pass is mutex-consistent in the native layer; an older .so that
        only knows 7 leaves ``elastic_bytes_out`` at 0)."""
        tot = np.zeros(8, dtype=np.uint64)
        buf = (ctypes.c_uint64 * 8)()
        for sid in self.server_ids:
            if self._lib.tm_ps_server_stats(sid, buf, 8) >= 7:
                tot += np.ctypeslib.as_array(buf)
        return tot

    def stats(self) -> dict:
        """Cycle-cost decomposition of the server loop (VERDICT r4 #8),
        summed over shards: where a served op's time went, in seconds —
        ``recv_s`` (payload read syscalls), ``lock_wait_s`` (shard-mutex
        contention), ``apply_s`` (rule loop / memcpy under the mutex),
        ``send_s`` (response writes) — plus ``ops``, ``bytes_in``,
        ``bytes_out``, and ``elastic_bytes_out`` (the RULE_ELASTIC
        response payloads inside ``bytes_out``, tracked separately so
        throughput models don't count them as apply work —
        benchmarks/ps_bench.py).  The idle wait between requests is in
        no bucket.  Backs ps_bench's loopback breakdown and the scaling
        model in docs/ROUND3_NOTES.md.

        Consistency (ADVICE round 5): the native counters update in
        groups under the shard mutex and the snapshot reads under the
        same mutex, so a per-shard pass can no longer tear mid-op.
        Every op a completed ``wait()`` observed is fully counted in
        ``ops``/``bytes_in``/``recv_s``/``lock_wait_s``/``apply_s``
        (they land before the response unblocks the client — tests
        assert ``==`` at quiescence); ``send_s``/``bytes_out``/
        ``elastic_bytes_out`` land after the response write and may lag
        by the ops still in flight.

        With ``Config.obs`` on, each snapshot's deltas against the
        previous one are folded into the telemetry registry as
        ``tm_ps_*_total`` counters (docs/OBSERVABILITY.md)."""
        tot = self._read_counters()
        out = {
            "ops": int(tot[0]),
            "bytes_in": int(tot[1]),
            "bytes_out": int(tot[2]),
            "recv_s": float(tot[3]) / 1e9,
            "lock_wait_s": float(tot[4]) / 1e9,
            "apply_s": float(tot[5]) / 1e9,
            "send_s": float(tot[6]) / 1e9,
            "elastic_bytes_out": int(tot[7]),
        }
        if runtime.effective_config().obs != "off":
            from .. import obs

            obs.record_ps_stats(out, self._last_stats)
            self._last_stats = dict(out)
        return out

    def shutdown(self) -> None:
        for sid in self.server_ids:
            self._lib.tm_ps_server_destroy(sid)
        self.server_ids = []

    def __del__(self):  # best effort
        try:
            self.shutdown()
        except Exception:
            pass


class _ResilientPSHandle:
    """PSHandle facade returned when ``Config.faults`` is armed: the
    exchange is already enqueued (async overlap preserved); ``wait()``
    runs under the retry policy, retransmitting the WHOLE exchange on a
    transient failure and recording per-shard peer health — see
    ``faults.ps_wait``.  ``done`` reflects the currently-enqueued
    attempt."""

    def __init__(self, inner: PSHandle, make_handle, peers: List[str]):
        self._inner = inner
        self._make = make_handle
        self._peers = peers
        self._result = None
        self._waited = False

    def wait(self):
        if not self._waited:
            from .. import faults

            self._result = faults.ps_wait(self._peers, self._make,
                                          self._inner)
            self._waited = True
        return self._result

    @property
    def done(self) -> bool:
        return self._waited or self._inner.done


class PSClient:
    """Client-side: async send/receive against the shard servers.

    With ``Config.faults`` armed, ``send``/``receive`` return handles
    whose ``wait()`` retries the exchange under the fault policy (sites
    ``ps.request``/``ps.response``) and feeds the per-peer health
    ledger; with the default ``faults="off"`` nothing here changes and
    ``torchmpi_tpu.faults`` is never imported."""

    def __init__(self, template: PyTree,
                 ports: Sequence[int],
                 shard_bounds: Sequence[Tuple[int, int]],
                 host: str = "127.0.0.1"):
        self._lib = _load_lib()
        flat, self.spec = tree_util.flatten_f32(template)
        self.total = self.spec.total
        self.shard_bounds = list(shard_bounds)
        self.client_ids: List[int] = []
        self.peers: List[str] = [f"{host}:{int(p)}" for p in ports]
        for port in ports:
            cid = self._lib.tm_ps_client_connect(host.encode(), int(port),
                                                 _timeout_ms())
            if cid < 0:
                raise RuntimeError(f"failed to connect to PS at "
                                   f"{host}:{port}")
            self.client_ids.append(cid)

    def _per_shard(self, flat: np.ndarray):
        if not self.client_ids:
            raise RuntimeError("PS client is shut down")
        for cid, (lo, hi) in zip(self.client_ids, self.shard_bounds):
            yield cid, lo, hi, flat[lo:hi]

    def send(self, tree: PyTree, rule: str = "add",
             alpha: float = 1.0) -> PSHandle:
        """Async push (reference: ``ps.send(handle, grads, rule)``).

        For ``rule="elastic"`` the handle's ``wait()`` returns the elastic
        delta pytree (subtract it from the local params — EASGD).

        With the wire guard armed (``Config.guard`` in ``wire``/``full``
        — docs/GUARD.md) each attempt's staged flat payload is blake2b-
        digested at staging and verified at the native-transport
        handoff; a mismatch is a transient the fault policy retries by
        re-staging from ``tree``."""
        wire = _wire_guard()
        if _faults_armed() or wire:
            from .. import faults

            stage = lambda: self._stage(tree)  # noqa: E731
            enq = lambda flat: self._send_flat(flat, rule, alpha)  # noqa: E731
            make = lambda: faults.ps_exchange_once(  # noqa: E731
                self.peers, stage, enq, wire_guard=wire)
            return _ResilientPSHandle(
                faults.ps_enqueue(self.peers, enq, stage=stage,
                                  wire_guard=wire), make, self.peers)
        return self._send_once(tree, rule, alpha)

    def _stage(self, tree: PyTree) -> np.ndarray:
        """Stage a pytree to the flat f32 wire format (one attempt's
        host payload; retries re-stage from the tree — the buffers the
        faults/corruption cannot touch)."""
        flat, _ = tree_util.flatten_f32(tree)
        if flat.shape[0] != self.total:
            raise ValueError(f"tree has {flat.shape[0]} floats, PS holds "
                             f"{self.total}")
        return flat

    def _send_once(self, tree: PyTree, rule: str,
                   alpha: float) -> PSHandle:
        return self._send_flat(self._stage(tree), rule, alpha)

    def _send_flat(self, flat: np.ndarray, rule: str,
                   alpha: float) -> PSHandle:
        rid = RULES[rule]
        fids, bufs = [], []
        inout_full = (np.zeros_like(flat) if rule == "elastic" else None)
        for cid, lo, hi, seg in self._per_shard(flat):
            seg = np.ascontiguousarray(seg, np.float32)
            inout = (inout_full[lo:hi] if inout_full is not None
                     else np.zeros((0,), np.float32))
            if inout_full is not None and not inout.flags.c_contiguous:
                inout = np.ascontiguousarray(inout)
            fid = self._lib.tm_ps_send(cid, rid, float(alpha), 0, _fptr(seg),
                                       _fptr(inout), hi - lo)
            if fid < 0:
                raise RuntimeError("ps send failed to enqueue")
            fids.append(fid)
            bufs.extend([seg, inout])
        result_fn = None
        if rule == "elastic":
            result_fn = lambda: tree_util.unflatten_f32(self.spec, inout_full)
        return PSHandle(self._lib, fids, bufs, result_fn)

    def receive(self) -> PSHandle:
        """Async pull of the full parameter vector (prefetch pattern);
        ``wait()`` returns the pytree."""
        if _faults_armed():
            from .. import faults

            make = lambda: faults.ps_exchange_once(  # noqa: E731
                self.peers, None, self._receive_once)
            return _ResilientPSHandle(
                faults.ps_enqueue(self.peers, self._receive_once),
                make, self.peers)
        return self._receive_once()

    def _receive_once(self) -> PSHandle:
        out = np.zeros((self.total,), np.float32)
        fids, bufs = [], []
        for cid, lo, hi, _ in self._per_shard(out):
            seg = out[lo:hi]
            if not seg.flags.c_contiguous:
                seg = np.ascontiguousarray(seg)
            fid = self._lib.tm_ps_receive(cid, 0, _fptr(seg), hi - lo)
            if fid < 0:
                raise RuntimeError("ps receive failed to enqueue")
            fids.append(fid)
            bufs.append(seg)
        return PSHandle(self._lib, fids, bufs,
                        lambda: tree_util.unflatten_f32(self.spec, out))

    def ping(self) -> List[bool]:
        """Liveness of each shard server (failure detection, SURVEY §6.3):
        OP_PING round-trips on every connection; False = shard unreachable."""
        if not self.client_ids:
            raise RuntimeError("PS client is shut down")
        handles = [PSHandle(self._lib, [self._lib.tm_ps_ping(cid)], [])
                   for cid in self.client_ids]
        alive = []
        for h in handles:
            try:
                h.wait()
                alive.append(True)
            except RuntimeError:
                alive.append(False)
        if _faults_armed():
            from .. import faults

            # Liveness probes feed the same per-peer ledger the
            # resilient exchanges use (degrade-or-raise input).
            for peer, ok in zip(self.peers, alive):
                faults.ledger().record(peer, ok)
        return alive

    def shutdown(self) -> None:
        for cid in self.client_ids:
            self._lib.tm_ps_client_destroy(cid)
        self.client_ids = []

    def __del__(self):
        try:
            self.shutdown()
        except Exception:
            pass


class ParameterServer:
    """Single-process convenience: servers + one client, the shape the
    reference exposed via ``parameterserver.init(flatParams)``."""

    def __init__(self, template: PyTree, num_shards: int = 2,
                 host: str = "127.0.0.1", base_port: int = 0,
                 init: str = "copy"):
        flat, spec = tree_util.flatten_f32(template)
        self.servers = ShardedParameterServer(spec.total, num_shards,
                                              base_port)
        self.client = PSClient(template, self.servers.ports,
                               self.servers.shard_bounds, host)
        if init == "copy":
            self.client.send(template, rule="copy").wait()

    def send(self, tree: PyTree, rule: str = "add",
             alpha: float = 1.0) -> PSHandle:
        return self.client.send(tree, rule, alpha)

    def receive(self) -> PSHandle:
        return self.client.receive()

    def ops_served(self) -> int:
        return self.servers.ops_served()

    def stats(self) -> dict:
        """Server-loop cycle-cost decomposition — see
        :meth:`ShardedParameterServer.stats`."""
        return self.servers.stats()

    def healthy(self) -> bool:
        """All shard servers reachable (see PSClient.ping)."""
        return all(self.client.ping())

    def shutdown(self) -> None:
        self.client.shutdown()
        self.servers.shutdown()


def sync_handle(h: PSHandle):
    """Reference: ``parameterserver.syncHandle(h)``."""
    return h.wait()
